"""Benchmark: analyzer throughput (msgs/s) on the local accelerator.

Protocol: pre-materialize synthetic record batches on the host (ingest is
benchmarked separately — the native shim's generator runs at memory
bandwidth), then stream them through the jitted TPU backend with donated
state, and report end-to-end messages/second over the timed window.

Output contract: the LAST JSON line on stdout is the result —
  {"metric": "msgs_per_sec", "value": N, "unit": "msgs/s", "vs_baseline": R, ...}
vs_baseline is the ratio to the reference's only published number,
590,221 msgs/s (BASELINE.md, demo_output.png).  A non-degraded run prints
an earlier salvage-checkpoint line (same headline fields, no breakdown)
that the supervisor reuses if the optional breakdown section wedges the
accelerator tunnel; consumers must take the last line (tools/bench_all.py
does).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

BASELINE_MSGS_PER_SEC = 590_221.0


#: BASELINE.json benchmark configs (scaled-down record counts; the shape of
#: each workload — partitions, features, key cardinality — is preserved).
CONFIGS = {
    1: dict(partitions=1, features="counters", keys=10_000,
            help="1-partition default metrics scan"),
    2: dict(partitions=16, features="counters,quantiles", keys=200_000,
            help="16-partition size histograms + ts range"),
    3: dict(partitions=16, features="counters,alive,hll", keys=1_000_000,
            help="log-compacted alive/distinct keys"),
    4: dict(partitions=16, features="counters,quantiles", keys=200_000,
            vmin=10, vmax=65_000, help="mixed-size payload percentiles"),
    5: dict(partitions=64, features="counters,alive,hll,quantiles",
            keys=500_000, help="8-topic fan-in shape (64 total rows)"),
}


def supervised_main() -> int:
    """Run the bench in a killable child with a hard deadline; on a hang
    (this round's observed axon-tunnel failure mode: init or the first
    real device op blocks forever with idle relay sockets), kill it and
    rerun on the host CPU platform with the degraded flag set.

    Guarantees the driver ALWAYS gets its one JSON line.  The child is
    this same script with KTA_BENCH_CHILD=1; KTA_BENCH_DEADLINE (seconds,
    default 900) bounds the accelerator attempt.
    """
    import subprocess

    deadline = float(os.environ.get("KTA_BENCH_DEADLINE") or 900)
    env = dict(os.environ)
    env["KTA_BENCH_CHILD"] = "1"
    # The probe subprocess is skipped in the child: this wrapper IS the
    # watchdog, and back-to-back client inits have been observed to hang
    # the tunnel (see BENCH_NOTES.md round 2).
    env.setdefault("KTA_ACCEL_OK", "1")

    # Cheap liveness probe before committing to the accelerator attempt:
    # when the tunnel relay process is dead (observed 2026-07-29 after a
    # SIGKILLed hung client), EVERY client init blocks forever in a
    # connect-retry loop — skip straight to the CPU attempt instead of
    # burning the whole deadline discovering that.
    attempts = [(1, {}), (2, {"KTA_JAX_PLATFORMS": "cpu",
                              "KTA_DEGRADED": "1"})]
    try:
        probe_s = float(os.environ.get("KTA_PROBE_TIMEOUT") or 150)
    except ValueError:
        probe_s = 150.0  # malformed override: keep the default
    if (
        probe_s > 0
        and not os.environ.get("KTA_JAX_PLATFORMS")
        # An orchestrator that already probed (tools/bench_all.py) passes
        # its verdict via KTA_ACCEL_OK; re-probing per child would stack
        # client inits against the relay — the documented wedge mechanism.
        and not os.environ.get("KTA_ACCEL_OK")
    ):
        # The one shared probe (real device op; see jax_support): None =
        # wedged tunnel, "cpu" = working CPU-only machine — different
        # diagnoses, same consequence (skip the accelerator attempt; the
        # CPU run is flagged either way, since neither case yields chip
        # numbers).
        from kafka_topic_analyzer_tpu.jax_support import probe_device_platform

        platform = probe_device_platform(probe_s)
        if platform is None:
            print(
                f"bench: accelerator init probe failed within {probe_s:.0f}s "
                "(tunnel relay down?) — skipping to host CPU, degraded",
                file=sys.stderr, flush=True,
            )
            attempts = attempts[1:]
        elif platform == "cpu":
            print(
                "bench: no accelerator present — running on host CPU, "
                "flagged degraded",
                file=sys.stderr, flush=True,
            )
            attempts = attempts[1:]
    def salvage(stdout: "str | None") -> bool:
        """A killed accelerator child may have printed its headline JSON
        line before hanging in the optional breakdown section — losing a
        successful chip measurement to a CPU rerun would be strictly worse
        than reporting it.  Re-print the last JSON line, flagged."""
        for line in reversed((stdout or "").strip().splitlines()):
            if line.startswith("{"):
                try:
                    doc = json.loads(line)
                except ValueError:
                    continue
                doc["breakdown_truncated"] = True
                print(json.dumps(doc), flush=True)
                return True
        return False

    for attempt, extra in attempts:
        env.update(extra)
        try:
            # Child stdout is captured (and forwarded) so a kill mid-run
            # can salvage an already-printed result line; stderr is NOT
            # captured, so progress/diagnostics stream through live.
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__)] + sys.argv[1:],
                env=env, timeout=deadline if attempt == 1 else None,
                stdout=None if attempt == 2 else subprocess.PIPE, text=True,
            )
            rc = proc.returncode
            stdout = proc.stdout
        except subprocess.TimeoutExpired as te:
            rc = None
            stdout = te.stdout
            if isinstance(stdout, bytes):
                stdout = stdout.decode(errors="replace")
        if rc is None:
            if salvage(stdout):
                return 0
            print(
                f"bench: accelerator attempt exceeded {deadline:.0f}s "
                "(tunnel hang) — rerunning on host CPU, degraded",
                file=sys.stderr, flush=True,
            )
        if rc is not None and rc >= 0:
            # Normal exit (success or a deterministic failure like a
            # usage error): report it faithfully — degrading would just
            # rerun the same failure and misattribute it to the chip.
            if stdout:
                sys.stdout.write(stdout)
                sys.stdout.flush()
            return rc
        if attempt == 2:
            return 1  # fallback child killed by a signal: genuine failure
        if rc is not None:
            if salvage(stdout):
                return 0
            print(
                f"bench: accelerator attempt died on signal {-rc} — "
                "rerunning on host CPU, degraded",
                file=sys.stderr, flush=True,
            )
    return 1


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", type=int, choices=sorted(CONFIGS),
                    help="BASELINE.json workload preset (overrides "
                         "--partitions/--features)")
    ap.add_argument("--partitions", type=int, default=16)
    ap.add_argument("--batch-size", type=int, default=None,
                    help="records per device step (default: 2^20; 2^16 on "
                         "the axon tunnel platform, where a 2^20 warmup has "
                         "been observed to wedge the relay — BENCH_NOTES.md)")
    ap.add_argument("--batches", type=int, default=8,
                    help="distinct pre-materialized batches")
    ap.add_argument("--steps", type=int, default=64,
                    help="timed device steps (cycling the batches)")
    ap.add_argument("--features", default="counters,hll,quantiles",
                    help="comma set: counters,alive,hll,quantiles")
    ap.add_argument("--alive-bits", type=int, default=26)
    ap.add_argument("--keys", type=int, default=200_000)
    ap.add_argument("--vmin", type=int, default=100)
    ap.add_argument("--vmax", type=int, default=420)
    ap.add_argument("--pallas", action="store_true",
                    help="use the Pallas MXU counter kernel")
    ap.add_argument("--wire-format", choices=["v4", "v5"], default="v5",
                    help="Packed wire format referee: v5 combiner rows "
                         "(host pre-reduced fold tables, default) vs v4 "
                         "per-record columns — byte-identical results, "
                         "different device fold cost (BENCH round 11)")
    ap.add_argument("--alive-compaction", choices=["auto", "off"],
                    default="auto",
                    help="host-side LWW alive-pair compaction referee "
                         "(BENCH round 13): 'auto' ships one bounded "
                         "per-dispatch pair table applied after the scan, "
                         "'off' keeps the per-row pair sections and the "
                         "in-scan pair scatter — byte-identical results")
    ap.add_argument("--superbatch", default="1", metavar="K|auto",
                    help="stack K packed batches per jitted scan dispatch "
                         "(state donated once per superbatch; 'auto' "
                         "targets 2^20 records/dispatch)")
    ap.add_argument("--dispatch-depth", type=int, default=2,
                    help="superbatches allowed in flight while the device "
                         "folds (default 2)")
    ap.add_argument("--accuracy", action="store_true",
                    help="also run the CPU-exact oracle over the same records "
                         "and report sketch errors (BASELINE metric: msgs/s "
                         "profiled + sketch error vs exact)")
    ap.add_argument("--accuracy-seeds", type=int, default=6,
                    help="extra independent dataset seeds for the sketch-"
                         "error distribution (mean/max reported alongside "
                         "the main run's draw, so a single ±2σ draw can't "
                         "masquerade as the sketch's accuracy — r3 weak #2)")
    ap.add_argument("--accuracy-seed-batches", type=int, default=None,
                    help="batches per accuracy seed (default: same as "
                         "--batches, so the seed distribution is measured at "
                         "the SAME cardinality as the main draw — HLL error "
                         "depends on cardinality, r4 weak #5)")
    args = ap.parse_args()
    # Validate argument combinations immediately — a bad value must fail
    # here, not after the multi-minute timed run has already burned its
    # budget (the old post-run check lost the whole measurement).
    if (
        args.accuracy_seed_batches is not None
        and args.accuracy_seed_batches < 1
    ):
        ap.error("--accuracy-seed-batches must be >= 1")
    if args.config:
        preset = CONFIGS[args.config]
        args.partitions = preset["partitions"]
        args.features = preset["features"]
        args.keys = preset.get("keys", args.keys)
        args.vmin = preset.get("vmin", args.vmin)
        args.vmax = preset.get("vmax", args.vmax)
        print(f"bench: config {args.config} — {preset['help']}", file=sys.stderr)

    # Accelerator watchdog: a wedged TPU tunnel blocks the first device op
    # forever (even backend init); fall back to host CPU (clearly flagged)
    # instead of hanging the driver.
    from kafka_topic_analyzer_tpu.jax_support import ensure_responsive_accelerator

    degraded = (
        not ensure_responsive_accelerator()
        or os.environ.get("KTA_DEGRADED") == "1"
    )

    import jax

    from kafka_topic_analyzer_tpu.jax_support import detect_cpu_fallback

    platform = jax.devices()[0].platform
    # A fast-FAILING accelerator plugin leaves jax on host CPU without
    # tripping the watchdog (e.g. under an orchestrator's KTA_ACCEL_OK=1
    # verdict that predates the failure): flag it rather than report an
    # unflagged CPU number.
    if detect_cpu_fallback():
        degraded = True

    if args.batch_size is None:
        args.batch_size = 1 << 16 if platform == "axon" else 1 << 20

    from kafka_topic_analyzer_tpu.backends.tpu import TpuBackend
    from kafka_topic_analyzer_tpu.config import AnalyzerConfig
    from kafka_topic_analyzer_tpu.io.synthetic import SyntheticSource, SyntheticSpec
    from kafka_topic_analyzer_tpu.packing import packed_nbytes

    feats = set(args.features.split(","))
    config = AnalyzerConfig(
        num_partitions=args.partitions,
        batch_size=args.batch_size,
        count_alive_keys="alive" in feats,
        alive_bitmap_bits=args.alive_bits,
        enable_hll="hll" in feats,
        enable_quantiles="quantiles" in feats,
        use_pallas_counters=args.pallas,
        wire_format={"v4": 4, "v5": 5}[args.wire_format],
        alive_compaction=args.alive_compaction,
    )
    spec = SyntheticSpec(
        num_partitions=args.partitions,
        messages_per_partition=(args.batch_size * args.batches) // args.partitions,
        keys_per_partition=args.keys,
        key_null_permille=50,
        tombstone_permille=100,
        value_len_min=args.vmin,
        value_len_max=args.vmax,
        seed=0xBEEF,
    )

    print(f"bench: device={jax.devices()[0]}", file=sys.stderr)
    t_gen = time.perf_counter()
    try:
        from kafka_topic_analyzer_tpu.io.native import NativeSyntheticSource

        src = NativeSyntheticSource(spec)
    except Exception:
        src = SyntheticSource(spec)
    host_batches = list(src.batches(args.batch_size))
    host_batches = [b.pad_to(args.batch_size) for b in host_batches]
    gen_s = time.perf_counter() - t_gen
    total_host = sum(b.num_valid for b in host_batches)
    print(
        f"bench: generated {total_host} records in {gen_s:.1f}s "
        f"({total_host / gen_s:,.0f}/s host, {type(src).__name__}); "
        f"{packed_nbytes(config, args.batch_size) / args.batch_size:.1f} B/record on the wire",
        file=sys.stderr,
    )

    from kafka_topic_analyzer_tpu.config import DispatchConfig

    dispatch = DispatchConfig.parse(args.superbatch, args.dispatch_depth)
    backend = TpuBackend(config, init_now_s=0, dispatch=dispatch)
    super_k = backend.superbatch_k
    # Warmup: compile + first-touch — one full superbatch so the timed
    # loop never pays the scan-step compile.  The warmup batches are part
    # of the fold (and of the accuracy oracle's identical feed below).
    warmup = [host_batches[i % len(host_batches)] for i in range(super_k)]
    if super_k > 1:
        backend.update_superbatch(warmup)
    else:
        backend.update(warmup[0])
    backend.block_until_ready()

    t0 = time.perf_counter()
    if super_k > 1:
        for i in range(0, args.steps, super_k):
            backend.update_superbatch([
                host_batches[j % len(host_batches)]
                for j in range(i, min(i + super_k, args.steps))
            ])
    else:
        for i in range(args.steps):
            backend.update(host_batches[i % len(host_batches)])
    backend.block_until_ready()
    dt = time.perf_counter() - t0

    n = args.steps * args.batch_size
    msgs_per_sec = n / dt
    metrics = backend.finalize()
    assert int(metrics.overall_count) == n + super_k * args.batch_size  # incl. warmup

    print(
        f"bench: {n} records in {dt:.3f}s on {jax.devices()[0].platform}",
        file=sys.stderr,
    )
    result = {
        "metric": "msgs_per_sec",
        "value": round(msgs_per_sec, 1),
        "unit": "msgs/s",
        "vs_baseline": round(msgs_per_sec / BASELINE_MSGS_PER_SEC, 2),
        "batch_size": args.batch_size,
        "platform": platform,
    }
    if degraded:
        from kafka_topic_analyzer_tpu.jax_support import mark_degraded

        mark_degraded(result)

    # Measured breakdown (VERDICT r1 items 1/5): where does the streamed
    # number bind?  (a) host->device bandwidth — on this rig an SSH-tunneled
    # relay, on a production host PCIe; (b) the device-resident step rate —
    # what the same chip sustains once transfer is off the critical path.
    # Accelerator platforms only: on host CPU (degraded fallback OR an
    # explicit KTA_JAX_PLATFORMS=cpu run) there is no device for these
    # numbers to describe — a host-to-host memcpy reported as
    # `transfer_gbps` would poison cross-report comparisons.  The headline
    # line prints first, so even if a breakdown op wedges the tunnel and
    # this child is killed, the supervisor salvages the measurement.
    if platform != "cpu":
        # Salvage checkpoint: the supervisor reuses this line if a
        # breakdown op hangs and the child must be killed.
        print(json.dumps(result), flush=True)
        try:
            from kafka_topic_analyzer_tpu.packing import pack_batch
            from kafka_topic_analyzer_tpu.tools.hwmeasure import (
                headline_transfer_gbps,
                timed_step_loop,
            )

            result["transfer_gbps"] = headline_transfer_gbps()
            dev_bufs = [
                jax.device_put(pack_batch(b, config))
                for b in host_batches[: min(2, len(host_batches))]
            ]
            jax.block_until_ready(dev_bufs)
            resident = timed_step_loop(
                config, dev_bufs, steps=min(32, args.steps),
                device_resident=True,
            )
            result["device_resident_msgs_per_sec"] = resident["msgs_per_sec"]
        except Exception as e:  # breakdown is informative, never fatal
            result["breakdown_error"] = repr(e)

    if args.accuracy and (config.enable_hll or config.enable_quantiles):
        # Sketch error vs the CPU-exact oracle — fed EXACTLY the sequence the
        # device consumed (warmup batch + steps cycling the batch list), so
        # the comparison measures sketch error, not dataset mismatch.
        from kafka_topic_analyzer_tpu.backends.cpu import CpuExactBackend

        t_acc = time.perf_counter()
        oracle = CpuExactBackend(config, init_now_s=0)
        for b in warmup:  # mirror the device warmup (K batches)
            oracle.update(b)
        for i in range(args.steps):
            oracle.update(host_batches[i % len(host_batches)])
        exact = oracle.finalize()
        sketch = metrics
        if config.enable_hll and exact.distinct_keys_exact:
            result["hll_rel_error"] = round(
                abs(sketch.distinct_keys_hll - exact.distinct_keys_exact)
                / exact.distinct_keys_exact,
                5,
            )
        if config.enable_quantiles and exact.quantiles is not None:
            errs = [
                abs(s - e) / e
                for s, e in zip(sketch.quantiles.values, exact.quantiles.values)
                if e
            ]
            result["quantile_rel_error_max"] = round(max(errs), 5) if errs else 0.0

        # Error DISTRIBUTION over independent seeds: one draw cannot tell a
        # within-budget sketch from a lucky one (r3's config-3 record was a
        # ~2σ draw read as the truth).  Each seed gets its own dataset at
        # the SAME batch count as the main run — HLL error is a function of
        # cardinality, so a smaller per-seed dataset would measure a
        # different distribution than the headline draw's (r4 weak #5).
        # Shapes are identical so the jitted step is compile-cache warm.
        seed_errs_hll: "list[float]" = []
        seed_errs_q: "list[float]" = []
        acc_batches = (args.accuracy_seed_batches
                       if args.accuracy_seed_batches is not None
                       else args.batches)
        if args.accuracy_seeds > 0:
            result["accuracy_seed_batches"] = acc_batches
            result["accuracy_seed_records"] = acc_batches * args.batch_size
        for s in range(max(0, args.accuracy_seeds)):
            import dataclasses as _dc

            sspec = _dc.replace(
                spec,
                seed=0xACC0 + s,
                messages_per_partition=(args.batch_size * acc_batches)
                // args.partitions,
            )
            try:
                ssrc = NativeSyntheticSource(sspec)
            except Exception:
                ssrc = SyntheticSource(sspec)
            sbatches = [
                b.pad_to(args.batch_size)
                for b in ssrc.batches(args.batch_size)
            ]
            sk_backend = TpuBackend(config, init_now_s=0)
            sk_oracle = CpuExactBackend(config, init_now_s=0)
            for b in sbatches:
                sk_backend.update(b)
                sk_oracle.update(b)
            sk = sk_backend.finalize()
            ex = sk_oracle.finalize()
            if config.enable_hll and ex.distinct_keys_exact:
                seed_errs_hll.append(
                    abs(sk.distinct_keys_hll - ex.distinct_keys_exact)
                    / ex.distinct_keys_exact
                )
            if config.enable_quantiles and ex.quantiles is not None:
                qe = [
                    abs(a - e) / e
                    for a, e in zip(sk.quantiles.values, ex.quantiles.values)
                    if e
                ]
                if qe:
                    seed_errs_q.append(max(qe))
        if seed_errs_hll:
            result["hll_rel_error_seeds"] = [round(e, 5) for e in seed_errs_hll]
            result["hll_rel_error_mean"] = round(
                sum(seed_errs_hll) / len(seed_errs_hll), 5
            )
            result["hll_rel_error_max"] = round(max(seed_errs_hll), 5)
        if seed_errs_q:
            result["quantile_rel_error_seeds_max"] = round(max(seed_errs_q), 5)
        print(
            f"bench: accuracy referee took {time.perf_counter() - t_acc:.1f}s "
            f"({len(seed_errs_hll) or len(seed_errs_q)} extra seeds)",
            file=sys.stderr,
        )

    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    if os.environ.get("KTA_BENCH_CHILD") == "1":
        sys.exit(main())
    sys.exit(supervised_main())
