"""Structure-of-arrays record batches — the host↔device data contract.

The reference hands each message to its reducers as a borrowed rdkafka message
(``src/kafka.rs:107-109``) and every reducer re-extracts partition / key /
payload / timestamp per message (``src/metric.rs:207-252``).  On TPU that
per-message shape is hostile: XLA wants static shapes and the reducers never
actually need payload *bytes* — only lengths, null-ness, timestamps, and key
hashes (SURVEY.md §3.4, §7).  So the host ingest layer pre-extracts exactly
those into fixed-width vectors; one `RecordBatch` is the unit that crosses the
host→device boundary.

Ordering contract: within a partition, records appear in offset order, and all
records of a given partition are routed to the same data shard (keys live in a
single partition, so shard-local last-writer-wins alive tracking composes into
an exact global OR-merge — see models/compaction.py).
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass
class RecordBatch:
    """One batch of pre-extracted record metadata (host-side numpy).

    All arrays share length ``B``.  Padded records have ``valid=False`` and
    must be ignored by every reducer.
    """

    #: Partition id of each record (int32).
    partition: np.ndarray
    #: Key length in bytes; 0 when the key is null (int32).
    key_len: np.ndarray
    #: Value length in bytes; 0 when the value is null / tombstone (int32).
    value_len: np.ndarray
    #: True where the record has no key (bool).
    key_null: np.ndarray
    #: True where the record has no value — a tombstone (bool).
    value_null: np.ndarray
    #: Message timestamp in *seconds* (int64).  The reference parses
    #: timestamps at second granularity: ``to_millis().unwrap_or(0) / 1000``
    #: (src/metric.rs:209-211); missing timestamps become 0 (epoch).
    ts_s: np.ndarray
    #: Bug-compatible fnv32 hash of the key bytes (uint32); 0 for null keys.
    #: Indexes the alive-key bitmap exactly like src/metric.rs:256-260.
    key_hash32: np.ndarray
    #: Standard 64-bit key hash (uint64); feeds HLL / exact distinct counting.
    key_hash64: np.ndarray
    #: False for padding records appended to reach the static batch size.
    valid: np.ndarray
    #: OPTIONAL host-only per-record Kafka offsets (int64), never transferred
    #: to the device.  Sources whose offset space has gaps (log compaction)
    #: attach them so snapshots can record exact resume positions; gapless
    #: sources leave None and progress is tracked by counting.
    offsets: "np.ndarray | None" = None

    FIELDS = (
        ("partition", np.int32),
        ("key_len", np.int32),
        ("value_len", np.int32),
        ("key_null", np.bool_),
        ("value_null", np.bool_),
        ("ts_s", np.int64),
        ("key_hash32", np.uint32),
        ("key_hash64", np.uint64),
        ("valid", np.bool_),
    )

    def __post_init__(self) -> None:
        n = len(self.partition)
        for name, dtype in self.FIELDS:
            arr = np.asarray(getattr(self, name))
            if arr.dtype != dtype:
                arr = arr.astype(dtype)
            if arr.shape != (n,):
                raise ValueError(f"{name}: expected shape ({n},), got {arr.shape}")
            setattr(self, name, arr)
        if self.offsets is not None:
            self.offsets = np.asarray(self.offsets, dtype=np.int64)
            if self.offsets.shape != (n,):
                raise ValueError("offsets: wrong shape")

    def __len__(self) -> int:
        return len(self.partition)

    @property
    def num_valid(self) -> int:
        return int(np.count_nonzero(self.valid))

    @classmethod
    def empty(cls, n: int = 0) -> "RecordBatch":
        return cls(**{name: np.zeros(n, dtype=dt) for name, dt in cls.FIELDS})

    def pad_to(self, size: int) -> "RecordBatch":
        """Pad with invalid records up to ``size`` (no-op if already there)."""
        n = len(self)
        if n == size:
            return self
        if n > size:
            raise ValueError(f"batch of {n} records cannot pad to {size}")
        out = {}
        for name, dt in self.FIELDS:
            arr = np.zeros(size, dtype=dt)
            arr[:n] = getattr(self, name)
            out[name] = arr
        padded = RecordBatch(**out)
        if self.offsets is not None:
            offs = np.full(size, -1, dtype=np.int64)
            offs[:n] = self.offsets
            padded.offsets = offs
        return padded

    @classmethod
    def concat(cls, batches: "list[RecordBatch]") -> "RecordBatch":
        if not batches:
            return cls.empty()
        if len(batches) == 1:
            return batches[0]  # treated immutably everywhere: safe to share
        out = cls(
            **{
                name: np.concatenate([getattr(b, name) for b in batches])
                for name, _ in cls.FIELDS
            }
        )
        if all(b.offsets is not None for b in batches):
            out.offsets = np.concatenate([b.offsets for b in batches])
        return out

    def take(self, idx: np.ndarray) -> "RecordBatch":
        out = RecordBatch(
            **{name: getattr(self, name)[idx] for name, _ in self.FIELDS}
        )
        if self.offsets is not None:
            out.offsets = self.offsets[idx]
        return out

    def slice(self, lo: int, hi: int) -> "RecordBatch":
        """Zero-copy view of rows [lo, hi) — the hot path's re-batching uses
        this instead of ``take(arange(lo, hi))`` (which fancy-index-copies
        every column).  Views alias this batch's buffers; downstream
        consumers copy at pack/pad time and never mutate in place."""
        out = RecordBatch(
            **{name: getattr(self, name)[lo:hi] for name, _ in self.FIELDS}
        )
        if self.offsets is not None:
            out.offsets = self.offsets[lo:hi]
        return out

    @classmethod
    def resplit(
        cls, pend: "list[RecordBatch]", batch_size: int, force: bool
    ) -> "tuple[list[RecordBatch], list[RecordBatch], int]":
        """Re-batch accumulated chunks to ``batch_size``: concat ONCE, cut
        zero-copy slice views, keep one remainder.  Returns
        (full_batches, remainder_list, remainder_count).  Shared by the
        wire client's flush and bench_ingest so the benchmark times the
        exact hot-path algorithm.

        The yielded batches are views that pin the concat buffer until the
        downstream pack/pad copies them (bounded: one in-flight batch).
        The *remainder* would pin it across flushes — potentially for the
        rest of the scan — so it alone is copied out (< batch_size rows,
        amortized cost ~0; ADVICE r3)."""
        full = cls.concat(pend)
        out = []
        lo = 0
        while len(full) - lo >= batch_size or (force and lo < len(full)):
            hi = min(lo + batch_size, len(full))
            out.append(full.slice(lo, hi))
            lo = hi
        rest = full.slice(lo, len(full)).copy()
        return out, ([rest] if len(rest) else []), len(rest)

    def copy(self) -> "RecordBatch":
        """Deep-copy the columns (detach a view from its parent buffer)."""
        out = RecordBatch(
            **{name: getattr(self, name).copy() for name, _ in self.FIELDS}
        )
        if self.offsets is not None:
            out.offsets = self.offsets.copy()
        return out

    def as_dict(self) -> "dict[str, np.ndarray]":
        return {name: getattr(self, name) for name, _ in self.FIELDS}

    @property
    def nbytes(self) -> int:
        return sum(getattr(self, name).nbytes for name, _ in self.FIELDS)
