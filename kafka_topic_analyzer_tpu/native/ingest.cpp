// Native ingest shim — the TPU build's counterpart of the reference's only
// native component, librdkafka (Cargo.toml:19; SURVEY.md §2.2).  The
// reference leans on librdkafka's C threads for all wire-level work and then
// processes messages one at a time in Rust; here the native layer's job is
// the *batch extraction* hot path (SURVEY.md §7 hard parts (a)/(b)): produce
// fixed-width record-metadata columns (lengths, null flags, timestamps, key
// hashes) at memory bandwidth so only numeric tensors ever cross into JAX.
//
// Exposed via a plain C ABI for ctypes (no pybind11 in this image):
//   - kta_synth_batch:   deterministic synthetic workload generation,
//                        bit-identical to io/synthetic.py::synth_fields
//   - kta_hash_batch:    fnv32(reference variant, src/fnv32.rs:92-101) +
//                        standard fnv64 over packed variable-length keys
//   - kta_version:       ABI version stamp
//
// Build: `make -C native` → libkta_ingest.so (g++ -O3, pthreads).

#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kFnv32Offset = 0x811c9dc5u;
// The reference multiplies by the offset basis, NOT the FNV prime —
// reproduced on purpose (src/fnv32.rs:92-101).
constexpr uint32_t kFnv32Mult = 0x811c9dc5u;
constexpr uint64_t kFnv64Offset = 0xcbf29ce484222325ull;
constexpr uint64_t kFnv64Prime = 0x100000001b3ull;

inline uint64_t splitmix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ull;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ull;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebull;
  return x ^ (x >> 31);
}

inline uint32_t fnv1a32_ref(const uint8_t* p, int64_t n) {
  uint32_t h = kFnv32Offset;
  for (int64_t i = 0; i < n; ++i) h = (h ^ p[i]) * kFnv32Mult;
  return h;
}

inline uint64_t fnv1a64(const uint8_t* p, int64_t n) {
  uint64_t h = kFnv64Offset;
  for (int64_t i = 0; i < n; ++i) h = (h ^ p[i]) * kFnv64Prime;
  return h;
}

// Parallel-for over [0, n) in contiguous chunks.
template <typename F>
void parallel_for(int64_t n, int threads, F&& body) {
  if (threads <= 1 || n < (1 << 14)) {
    body(0, n);
    return;
  }
  std::vector<std::thread> pool;
  int64_t chunk = (n + threads - 1) / threads;
  for (int t = 0; t < threads; ++t) {
    int64_t lo = t * chunk;
    int64_t hi = lo + chunk < n ? lo + chunk : n;
    if (lo >= hi) break;
    pool.emplace_back([&body, lo, hi] { body(lo, hi); });
  }
  for (auto& th : pool) th.join();
}

}  // namespace

extern "C" {

// Mirrors io/synthetic.py::SyntheticSpec (wire contract — keep in sync).
struct KtaSynthSpec {
  uint64_t seed;
  int32_t num_partitions;
  int64_t messages_per_partition;
  uint64_t keys_per_partition;
  int32_t key_null_permille;
  int32_t tombstone_permille;
  int32_t value_len_min;
  int32_t value_len_max;
  int32_t key_digits;
  int64_t ts_start_ms;
  int64_t ts_step_ms;
};

int32_t kta_version() { return 11; }

// CRC32-C (Castagnoli) over a byte buffer — Kafka's record-batch checksum.
// Table-driven; the Python fallback (kafka_codec._crc32c) is a per-byte
// interpreter loop that costs ~100 ms/MB, which made check.crcs=true
// impractical.
uint32_t kta_crc32c(const uint8_t* data, int64_t n) {
  // Thread-safe magic static: ctypes releases the GIL, so concurrent first
  // calls are real; a hand-rolled flag would race on the table writes.
  static const std::vector<uint32_t> table = [] {
    std::vector<uint32_t> t(256);
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t crc = i;
      for (int k = 0; k < 8; ++k)
        crc = (crc & 1) ? (crc >> 1) ^ 0x82f63b78u : crc >> 1;
      t[i] = crc;
    }
    return t;
  }();
  uint32_t crc = 0xffffffffu;
  for (int64_t i = 0; i < n; ++i)
    crc = table[(crc ^ data[i]) & 0xff] ^ (crc >> 8);
  return crc ^ 0xffffffffu;
}

// Last-writer-wins dedupe of alive-bitmap updates for one batch
// (the host half of the packed transfer's pre-reduction; see
// kafka_topic_analyzer_tpu/packing.py).  For each slot = h32 & (2^bits - 1)
// of an active record, only the LAST record's aliveness survives —
// equivalent to replaying insert/remove in record order.  Open-addressing
// hash table over the batch (capacity = next pow2 >= 2n), single pass.
// Outputs at most n (slot, alive) pairs; returns the pair count, or -1 on
// bad arguments.
int64_t kta_dedupe_slots(const uint32_t* h32, const uint8_t* active,
                         const uint8_t* alive, int64_t n, int32_t bits,
                         uint32_t* slot_out, uint8_t* alive_out) {
  if (!h32 || !active || !alive || !slot_out || !alive_out || n < 0 ||
      bits < 1 || bits > 32)
    return -1;
  const uint32_t mask =
      bits == 32 ? 0xffffffffu : ((1u << bits) - 1u);
  size_t cap = 16;
  while (cap < static_cast<size_t>(n) * 2) cap <<= 1;
  const size_t cap_mask = cap - 1;
  // table: index into out arrays + 1; 0 = empty.
  std::vector<int64_t> table(cap, 0);
  int64_t count = 0;
  for (int64_t i = 0; i < n; ++i) {
    if (!active[i]) continue;
    const uint32_t slot = h32[i] & mask;
    size_t pos = (splitmix64(slot) & cap_mask);
    for (;;) {
      int64_t entry = table[pos];
      if (entry == 0) {
        table[pos] = count + 1;
        slot_out[count] = slot;
        alive_out[count] = alive[i];
        ++count;
        break;
      }
      if (slot_out[entry - 1] == slot) {
        alive_out[entry - 1] = alive[i];  // later record wins
        break;
      }
      pos = (pos + 1) & cap_mask;
    }
  }
  return count;
}

// Generate records for global indices [lo, hi) over the partition list
// `parts` (round-robin: g -> parts[g % nparts] at offset g / nparts),
// exactly like SyntheticSource.batches.  All output arrays have hi-lo
// elements.  Returns 0 on success.
int32_t kta_synth_batch(const KtaSynthSpec* spec,
                        const int32_t* parts, int32_t nparts,
                        int64_t lo, int64_t hi, int32_t threads,
                        int32_t* partition_out, int32_t* key_len_out,
                        int32_t* value_len_out, uint8_t* key_null_out,
                        uint8_t* value_null_out, int64_t* ts_s_out,
                        uint32_t* h32_out, uint64_t* h64_out,
                        uint8_t* valid_out) {
  if (!spec || !parts || nparts <= 0 || hi < lo) return -1;
  const int64_t n = hi - lo;
  const KtaSynthSpec s = *spec;
  const int key_len_total = 1 + s.key_digits;

  // Stream bases depend only on the partition — mix once per slot of the
  // round-robin, not once per record.
  std::vector<uint64_t> bases(nparts);
  for (int32_t j = 0; j < nparts; ++j)
    bases[j] = splitmix64(s.seed ^ (static_cast<uint64_t>(parts[j]) << 40));

  parallel_for(n, threads, [&](int64_t a, int64_t b) {
    uint8_t keybuf[64];
    keybuf[0] = 'k';
    for (int64_t i = a; i < b; ++i) {
      const int64_t g = lo + i;
      const int32_t p = parts[g % nparts];
      const int64_t o = g / nparts;
      // Record o is the o-th output of a SplitMix64 stream with a mixed
      // per-partition base (see io/synthetic.py — wire contract).
      const uint64_t x = splitmix64(bases[g % nparts] +
                                    static_cast<uint64_t>(o) * 0x9e3779b97f4a7c15ull);

      const bool key_null =
          static_cast<int64_t>(x % 1000ull) < s.key_null_permille;
      const bool value_null =
          static_cast<int64_t>((x >> 10) % 1000ull) < s.tombstone_permille;
      const uint64_t local = (x >> 20) % s.keys_per_partition;
      const uint64_t key_id =
          static_cast<uint64_t>(p) +
          static_cast<uint64_t>(s.num_partitions) * local;
      const uint64_t vspread =
          static_cast<uint64_t>(s.value_len_max - s.value_len_min + 1);
      const int32_t vlen =
          value_null ? 0
                     : s.value_len_min +
                           static_cast<int32_t>((x >> 40) % vspread);

      partition_out[i] = p;
      value_len_out[i] = vlen;
      key_null_out[i] = key_null ? 1 : 0;
      value_null_out[i] = value_null ? 1 : 0;
      // floor division like numpy (`//`): values are non-negative here.
      ts_s_out[i] = (s.ts_start_ms + o * s.ts_step_ms) / 1000;
      valid_out[i] = 1;

      if (key_null) {
        key_len_out[i] = 0;
        h32_out[i] = 0;
        h64_out[i] = 0;
      } else {
        key_len_out[i] = key_len_total;
        uint64_t rem = key_id;
        for (int d = s.key_digits - 1; d >= 0; --d) {
          keybuf[1 + d] = static_cast<uint8_t>('0' + (rem % 10));
          rem /= 10;
        }
        h32_out[i] = fnv1a32_ref(keybuf, key_len_total);
        h64_out[i] = fnv1a64(keybuf, key_len_total);
      }
    }
  });
  return 0;
}

// Hash n variable-length byte slices packed in `data` at `offsets`
// (offsets[n] marks the end).  Used by the Kafka wire source to hash real
// key bytes off the fetch path.
int32_t kta_hash_batch(const uint8_t* data, const int64_t* offsets, int64_t n,
                       int32_t threads, uint32_t* h32_out, uint64_t* h64_out) {
  if (!data || !offsets || n < 0) return -1;
  parallel_for(n, threads, [&](int64_t a, int64_t b) {
    for (int64_t i = a; i < b; ++i) {
      const int64_t off = offsets[i];
      const int64_t len = offsets[i + 1] - off;
      h32_out[i] = fnv1a32_ref(data + off, len);
      h64_out[i] = fnv1a64(data + off, len);
    }
  });
  return 0;
}

// Kafka RecordBatch v2 record decoding: parse one decompressed batch
// payload into fixed-width SoA columns, hashing key bytes inline — the hot
// half of the wire client (the Python per-record generator measures ~225k
// records/s; this decodes at tens of millions).  The caller (io/native.py /
// kafka_codec.iter_batch_frames) has already handled framing, CRC and
// decompression.  Returns the number of records decoded, or -1 on malformed
// input (caller falls back to the Python decoder for a precise error).
int64_t kta_decode_records(const uint8_t* payload, int64_t payload_len,
                           int32_t num_records, int64_t base_offset,
                           int64_t first_ts_ms,
                           int64_t* offsets_out, int64_t* ts_ms_out,
                           int32_t* key_len_out, int32_t* value_len_out,
                           uint8_t* key_null_out, uint8_t* value_null_out,
                           uint32_t* h32_out, uint64_t* h64_out);

}  // extern "C"

namespace {
// Zigzag varint over [pos, len); false on truncation/overflow.
inline bool read_zigzag(const uint8_t* p, int64_t len, int64_t& pos,
                        int64_t& out) {
  uint64_t z = 0;
  int shift = 0;
  while (pos < len) {
    const uint8_t b = p[pos++];
    z |= static_cast<uint64_t>(b & 0x7f) << shift;
    if (!(b & 0x80)) {
      out = static_cast<int64_t>(z >> 1) ^ -static_cast<int64_t>(z & 1);
      return true;
    }
    shift += 7;
    if (shift > 63) return false;
  }
  return false;
}
}  // namespace

extern "C" int64_t kta_decode_records(
    const uint8_t* payload, int64_t payload_len, int32_t num_records,
    int64_t base_offset, int64_t first_ts_ms,
    int64_t* offsets_out, int64_t* ts_ms_out,
    int32_t* key_len_out, int32_t* value_len_out,
    uint8_t* key_null_out, uint8_t* value_null_out,
    uint32_t* h32_out, uint64_t* h64_out) {
  if (!payload || payload_len < 0 || num_records < 0) return -1;
  int64_t pos = 0;
  for (int32_t i = 0; i < num_records; ++i) {
    int64_t length;
    if (!read_zigzag(payload, payload_len, pos, length)) return -1;
    // Overflow-safe: a hostile 10-byte varint can encode ~2^63 and
    // `pos + length` would overflow int64 (UB) and bypass the bound.
    if (length < 0 || length > payload_len - pos) return -1;
    const int64_t rec_end = pos + length;
    if (pos >= rec_end) return -1;
    ++pos;  // record attributes
    int64_t ts_delta, off_delta, klen, vlen;
    if (!read_zigzag(payload, rec_end, pos, ts_delta)) return -1;
    if (!read_zigzag(payload, rec_end, pos, off_delta)) return -1;
    if (!read_zigzag(payload, rec_end, pos, klen)) return -1;
    if (klen < 0) {
      key_null_out[i] = 1;
      key_len_out[i] = 0;
      h32_out[i] = 0;
      h64_out[i] = 0;
    } else {
      if (klen > rec_end - pos || klen > 0x7fffffff) return -1;
      key_null_out[i] = 0;
      key_len_out[i] = static_cast<int32_t>(klen);
      h32_out[i] = fnv1a32_ref(payload + pos, klen);
      h64_out[i] = fnv1a64(payload + pos, klen);
      pos += klen;
    }
    if (!read_zigzag(payload, rec_end, pos, vlen)) return -1;
    if (vlen < 0) {
      value_null_out[i] = 1;
      value_len_out[i] = 0;
    } else {
      if (vlen > rec_end - pos || vlen > 0x7fffffff) return -1;
      value_null_out[i] = 0;
      value_len_out[i] = static_cast<int32_t>(vlen);
      pos += vlen;  // value bytes never needed (SURVEY.md §3.4)
    }
    int64_t nheaders;
    if (!read_zigzag(payload, rec_end, pos, nheaders)) return -1;
    if (nheaders < 0) return -1;
    for (int64_t h = 0; h < nheaders; ++h) {
      int64_t hk, hv;
      if (!read_zigzag(payload, rec_end, pos, hk)) return -1;
      if (hk < 0 || hk > rec_end - pos) return -1;
      pos += hk;
      if (!read_zigzag(payload, rec_end, pos, hv)) return -1;
      if (hv > 0) {
        if (hv > rec_end - pos) return -1;
        pos += hv;
      }
    }
    offsets_out[i] = base_offset + off_delta;
    ts_ms_out[i] = first_ts_ms + ts_delta;
    pos = rec_end;  // tolerate unknown trailing record fields
  }
  return num_records;
}

namespace {

inline int64_t be64(const uint8_t* p) {
  uint64_t v = 0;
  for (int i = 0; i < 8; ++i) v = (v << 8) | p[i];
  return static_cast<int64_t>(v);
}
inline int32_t be32(const uint8_t* p) {
  uint32_t v = 0;
  for (int i = 0; i < 4; ++i) v = (v << 8) | p[i];
  return static_cast<int32_t>(v);
}
inline int16_t be16(const uint8_t* p) {
  return static_cast<int16_t>((uint16_t(p[0]) << 8) | p[1]);
}

// One RecordBatch v2 frame header at `pos` of the NATIVE-decodable kind:
// complete, magic 2, uncompressed, sane record count, CRC ok (when asked).
// Returns true and fills the fields; false means the caller must stop the
// native walk here (a Python path decodes the frame — compressed, legacy
// MessageSet, truncated tail — or raises the precise protocol error).
struct FrameHeader {
  int64_t base_offset;
  int64_t first_ts;
  int64_t end;           // byte offset one past the frame
  int64_t payload_pos;   // first record byte
  int64_t covered_end;   // base_offset + max(last_offset_delta, 0) + 1
  int32_t num_records;
  bool control;          // txn commit/abort markers: skip, offsets count
};

inline bool native_frame_at(const uint8_t* buf, int64_t len, int64_t pos,
                            int32_t verify_crc, FrameHeader* fh) {
  if (pos + 61 > len) return false;          // header incomplete
  const int64_t batch_length = be32(buf + pos + 8);
  if (batch_length <= 0) return false;
  const int64_t end = pos + 12 + batch_length;
  if (end > len) return false;               // partial trailing frame
  if (buf[pos + 16] != 2) return false;      // legacy MessageSet v0/v1
  const int16_t attributes = be16(buf + pos + 21);
  if ((attributes & 0x07) != 0) return false;  // compressed
  fh->control = (attributes & 0x20) != 0;
  const int32_t num_records = be32(buf + pos + 57);
  const int64_t payload_pos = pos + 61;
  // Untrusted count: a valid record needs >= 7 payload bytes.
  if (num_records < 0 || num_records > (end - payload_pos) / 7) return false;
  if (verify_crc) {
    const uint32_t crc = static_cast<uint32_t>(be32(buf + pos + 17));
    if (kta_crc32c(buf + pos + 21, end - (pos + 21)) != crc) return false;
  }
  const int32_t last_offset_delta = be32(buf + pos + 23);
  fh->base_offset = be64(buf + pos);
  fh->first_ts = be64(buf + pos + 27);
  fh->end = end;
  fh->payload_pos = payload_pos;
  fh->num_records = num_records;
  fh->covered_end =
      fh->base_offset + (last_offset_delta > 0 ? last_offset_delta : 0) + 1;
  return true;
}

}  // namespace

// Count the records in the native-decodable PREFIX of a record set (a
// Fetch response's per-partition records field): consecutive complete,
// uncompressed, magic-2 frames.  The count sizes the caller's output
// arrays for kta_decode_record_set; the walk is a header jump per frame
// (no record parsing), so it costs ~nothing next to the decode.
extern "C" int64_t kta_scan_record_set(const uint8_t* buf, int64_t len,
                                       int32_t verify_crc,
                                       int64_t* consumed_out,
                                       int64_t* covered_out) {
  if (!buf || len < 0) return -1;
  int64_t pos = 0, total = 0, covered = -1;
  FrameHeader fh;
  while (native_frame_at(buf, len, pos, verify_crc, &fh)) {
    if (!fh.control) total += fh.num_records;  // markers aren't messages
    if (fh.covered_end > covered) covered = fh.covered_end;
    pos = fh.end;
  }
  if (consumed_out) *consumed_out = pos;
  if (covered_out) *covered_out = covered;
  return total;
}

// Decode the native-decodable prefix of a record set in ONE call: every
// frame's records into contiguous SoA columns (the per-frame
// kta_decode_records core, pointer-shifted per frame).  Replaces the
// per-frame Python loop of header parse + ctypes call + numpy slicing —
// the wire client's remaining hot-path overhead after round 1 made the
// record decode itself native (io/kafka_wire.py::batches).
// Returns records decoded (== kta_scan_record_set's count), or -1 on a
// malformed frame (callers re-walk with the Python decoder for the
// precise error).  consumed_out: bytes of prefix handled; covered_end_out:
// max over frames of (base_offset + last_offset_delta + 1), the
// compaction-aware scan position advance.
extern "C" int64_t kta_decode_record_set(
    const uint8_t* buf, int64_t len, int32_t verify_crc, int64_t capacity,
    int64_t* offsets_out, int64_t* ts_ms_out,
    int32_t* key_len_out, int32_t* value_len_out,
    uint8_t* key_null_out, uint8_t* value_null_out,
    uint32_t* h32_out, uint64_t* h64_out,
    int64_t* consumed_out, int64_t* covered_end_out) {
  if (!buf || len < 0 || capacity < 0) return -1;
  int64_t pos = 0, n = 0, covered = -1;
  FrameHeader fh;
  while (native_frame_at(buf, len, pos, verify_crc, &fh)) {
    if (fh.control) {  // txn markers: no records, offsets still covered
      if (fh.covered_end > covered) covered = fh.covered_end;
      pos = fh.end;
      continue;
    }
    if (n + fh.num_records > capacity) return -1;
    const int64_t got = kta_decode_records(
        buf + fh.payload_pos, fh.end - fh.payload_pos, fh.num_records,
        fh.base_offset, fh.first_ts,
        offsets_out + n, ts_ms_out + n, key_len_out + n, value_len_out + n,
        key_null_out + n, value_null_out + n, h32_out + n, h64_out + n);
    if (got != fh.num_records) return -1;
    n += got;
    if (fh.covered_end > covered) covered = fh.covered_end;
    pos = fh.end;
  }
  if (consumed_out) *consumed_out = pos;
  if (covered_end_out) *covered_end_out = covered;
  return n;
}

// Fused batch packing: RecordBatch SoA columns -> wire-format-v4 buffer
// (kafka_topic_analyzer_tpu/packing.py), including the host pre-reductions
// (per-partition ts min/max table, last-writer-wins bitmap dedupe via
// kta_dedupe_slots' table, and the HLL reduction — global register table
// in mode 2, per-record (bucket, rho) pairs in mode 1).  One C++ pass
// replaces several numpy conversions on the per-batch hot path.  Layout
// contract lives in packing.py; keep in sync (HEADER 16B; sections
// p i16[B] | klen u16[B] | vlen u32[B] | flags u8[B] | ts_minmax i64[2P] |
// sz_minmax i64[2P] | [slot u32[B] | alive u8[B]] |
// [hll: regs u8[rows << p] (mode 2) OR idx u16[B] | rho u8[B] (mode 1)]).
// Returns total bytes written, or -1 on error (including key_len > u16 /
// partition out of i16/num_partitions range — mirrors pack_batch's
// validation).
extern "C" int64_t kta_pack_batch(
    const int32_t* partition, const int32_t* key_len, const int32_t* value_len,
    const uint8_t* key_null, const uint8_t* value_null, const int64_t* ts_s,
    const uint32_t* h32, const uint64_t* h64,
    int64_t n_valid, int64_t batch_size, int32_t num_partitions,
    int32_t with_alive, int32_t alive_bits, int32_t with_hll, int32_t hll_p,
    int32_t hll_rows,
    int32_t value_len_cap,
    uint8_t* out, int64_t out_cap) {
  if (n_valid < 0 || n_valid > batch_size) return -1;
  if (num_partitions <= 0) return -1;
  const int64_t b = batch_size;
  const int64_t P = num_partitions;
  // Wire format v4: the per-record i64 ts column is replaced by TWO [2P]
  // per-partition min/max tables — timestamps and (tombstone-excluded)
  // message sizes (packing.py::_sections rationale).
  int64_t need = 16 + b * (2 + 2 + 4 + 1) + 2 * (2 * P * 8);
  if (with_alive) need += b * 5;
  // with_hll: 0 = off, 1 = per-record pairs, 2 = host-reduced register
  // table of hll_rows << hll_p bytes (wire v3; rows = 1 global or P
  // per-partition — python's packing.hll_table_rows decides).
  if (with_hll == 1) need += b * 3;
  if (with_hll == 2) {
    // Per-row tables index by partition id: rows must cover every id the
    // (validated) partition column can carry, or tbl[row << p | idx]
    // writes past the section.
    if (hll_rows < 1 || (hll_rows > 1 && hll_rows < num_partitions))
      return -1;
    need += int64_t(hll_rows) << hll_p;
  }
  if (need > out_cap) return -1;

  std::memset(out, 0, need);
  int64_t pos = 16;
  // Section base pointers stay uint8_t*; elements are stored via memcpy —
  // sections are only naturally aligned when batch_size is a multiple of 8,
  // and typed stores through misaligned pointers are UB.
  uint8_t* p16 = out + pos;
  pos += b * 2;
  uint8_t* kl16 = out + pos;
  pos += b * 2;
  uint8_t* vl32 = out + pos;
  pos += b * 4;
  uint8_t* fl8 = out + pos;
  pos += b;
  uint8_t* tsmm64 = out + pos;
  pos += 2 * P * 8;
  uint8_t* szmm64 = out + pos;
  pos += 2 * P * 8;

  auto store = [](uint8_t* base, int64_t idx, auto v) {
    std::memcpy(base + idx * static_cast<int64_t>(sizeof(v)), &v, sizeof(v));
  };

  const int32_t vcap =
      value_len_cap > 0 ? value_len_cap : 0x7fffffff;
  std::atomic<bool> bad{false};
  parallel_for(n_valid, 8, [&](int64_t a, int64_t e) {
    for (int64_t i = a; i < e; ++i) {
      if (partition[i] < 0 || partition[i] > 0x7fff ||
          partition[i] >= num_partitions ||
          key_len[i] < 0 || key_len[i] > 0xffff ||
          value_len[i] < 0 || value_len[i] > vcap) {
        bad.store(true);
        return;
      }
      store(p16, i, static_cast<int16_t>(partition[i]));
      store(kl16, i, static_cast<uint16_t>(key_len[i]));
      store(vl32, i, static_cast<uint32_t>(value_len[i]));
      fl8[i] = (key_null[i] ? 1 : 0) | (value_null[i] ? 2 : 0);
    }
  });
  if (bad.load()) return -1;

  {
    // Per-partition ts min/max AND (tombstone-excluded) message-size
    // min/max over the valid prefix: identity-filled, single sequential
    // pass (~1-2 ns/record; not worth the thread fan-out).  Size
    // identities are I64_MAX / 0, matching the reference's `largest`
    // starting at 0 (src/metric.rs:34, :249-251).
    std::vector<int64_t> mm(2 * P), sz(2 * P);
    for (int64_t r = 0; r < P; ++r) {
      mm[r] = INT64_MAX;
      mm[P + r] = INT64_MIN;
      sz[r] = INT64_MAX;
      sz[P + r] = 0;
    }
    for (int64_t i = 0; i < n_valid; ++i) {
      const int64_t r = partition[i];
      const int64_t t = ts_s[i];
      if (t < mm[r]) mm[r] = t;
      if (t > mm[P + r]) mm[P + r] = t;
      if (!value_null[i]) {
        const int64_t size =
            (key_null[i] ? 0 : static_cast<int64_t>(key_len[i])) +
            static_cast<int64_t>(value_len[i]);
        if (size < sz[r]) sz[r] = size;
        if (size > sz[P + r]) sz[P + r] = size;
      }
    }
    std::memcpy(tsmm64, mm.data(), 2 * P * 8);
    std::memcpy(szmm64, sz.data(), 2 * P * 8);
  }

  int64_t n_pairs = 0;
  if (with_alive) {
    uint8_t* slot32 = out + pos;
    pos += b * 4;
    uint8_t* alive8 = out + pos;
    pos += b;
    if (n_valid > 0) {
      // active = valid & key non-null; alive = value non-null.  Dedupe into
      // aligned temporaries, then memcpy into the (possibly unaligned)
      // section.  (Empty batches skip this entirely — sharded scans pack
      // empty shard batches every step.)
      std::vector<uint8_t> active(n_valid), alive(n_valid);
      for (int64_t i = 0; i < n_valid; ++i) {
        active[i] = key_null[i] ? 0 : 1;
        alive[i] = value_null[i] ? 0 : 1;
      }
      std::vector<uint32_t> slots(n_valid);
      std::vector<uint8_t> flags(n_valid);
      n_pairs = kta_dedupe_slots(h32, active.data(), alive.data(), n_valid,
                                 alive_bits, slots.data(), flags.data());
      if (n_pairs < 0) return -1;
      std::memcpy(slot32, slots.data(), n_pairs * 4);
      std::memcpy(alive8, flags.data(), n_pairs);
    }
  }
  if (with_hll == 1) {
    uint8_t* idx16 = out + pos;
    pos += b * 2;
    uint8_t* rho8 = out + pos;
    pos += b;
    const int p = hll_p;
    parallel_for(n_valid, 8, [&](int64_t a, int64_t e) {
      for (int64_t i = a; i < e; ++i) {
        if (key_null[i]) {
          store(idx16, i, static_cast<uint16_t>(0));
          rho8[i] = 0;
          continue;
        }
        const uint64_t h = splitmix64(h64[i]);
        store(idx16, i, static_cast<uint16_t>(h >> (64 - p)));
        const uint64_t rest = h << p;
        rho8[i] = rest == 0
                      ? static_cast<uint8_t>(64 - p + 1)
                      : static_cast<uint8_t>(__builtin_clzll(rest) + 1);
      }
    });
  } else if (with_hll == 2) {
    // Register table: scatter-max on the host's cache-resident
    // u8[rows << p] (64 KB at p=16 global), sequential single pass — the
    // device then merges it elementwise.  Row 0 for the global sketch;
    // the record's partition row when per-partition registers fit the
    // table budget.  (The memset above already zeroed it.)
    uint8_t* tbl = out + pos;
    const int p = hll_p;
    const bool per_row = hll_rows > 1;
    pos += int64_t(hll_rows) << p;
    for (int64_t i = 0; i < n_valid; ++i) {
      if (key_null[i]) continue;
      const uint64_t h = splitmix64(h64[i]);
      const int64_t row = per_row ? partition[i] : 0;
      const int64_t idx = (row << p) | static_cast<int64_t>(h >> (64 - p));
      const uint64_t rest = h << p;
      const uint8_t rho =
          rest == 0 ? static_cast<uint8_t>(64 - p + 1)
                    : static_cast<uint8_t>(__builtin_clzll(rest) + 1);
      if (rho > tbl[idx]) tbl[idx] = rho;
    }
  }

  // Header: n_valid i32 | n_pairs i32 | reserved.
  const int32_t hv = static_cast<int32_t>(n_valid);
  const int32_t hp = static_cast<int32_t>(n_pairs);
  std::memcpy(out, &hv, 4);
  std::memcpy(out + 4, &hp, 4);
  return need;
}

// ---------------------------------------------------------------------------
// Decompressors for Kafka record batches (kafka_codec.py): snappy raw blocks
// (plus the xerial chunked framing Kafka's Java client emits) and LZ4 frames.
// Python has neither in its stdlib; the shim supplies them so the wire client
// covers the common broker compression codecs without extra dependencies.

namespace {

// Raw snappy block decode (format: preamble varint = uncompressed length,
// then literal/copy tagged elements).  Returns bytes written or -1.
int64_t snappy_raw(const uint8_t* in, int64_t in_len, uint8_t* out,
                   int64_t out_cap) {
  int64_t ip = 0;
  // uncompressed length: LITTLE-endian base-128 varint (not zigzag)
  uint64_t ulen = 0;
  int shift = 0;
  while (ip < in_len) {
    uint8_t b = in[ip++];
    ulen |= static_cast<uint64_t>(b & 0x7f) << shift;
    if (!(b & 0x80)) break;
    shift += 7;
    if (shift > 35) return -1;
  }
  if (static_cast<int64_t>(ulen) > out_cap) return -1;
  int64_t op = 0;
  while (ip < in_len) {
    const uint8_t tag = in[ip++];
    const int type = tag & 3;
    if (type == 0) {  // literal
      int64_t len = (tag >> 2) + 1;
      if (len > 60) {
        const int extra = static_cast<int>(len) - 60;
        if (ip + extra > in_len) return -1;
        len = 0;
        for (int i = 0; i < extra; ++i)
          len |= static_cast<int64_t>(in[ip + i]) << (8 * i);
        len += 1;
        ip += extra;
      }
      if (ip + len > in_len || op + len > out_cap) return -1;
      std::memcpy(out + op, in + ip, len);
      ip += len;
      op += len;
    } else {  // copy
      int64_t len = 0, offset = 0;
      if (type == 1) {
        if (ip >= in_len) return -1;
        len = ((tag >> 2) & 7) + 4;
        offset = (static_cast<int64_t>(tag >> 5) << 8) | in[ip++];
      } else if (type == 2) {
        if (ip + 2 > in_len) return -1;
        len = (tag >> 2) + 1;
        offset = in[ip] | (static_cast<int64_t>(in[ip + 1]) << 8);
        ip += 2;
      } else {
        if (ip + 4 > in_len) return -1;
        len = (tag >> 2) + 1;
        offset = 0;
        for (int i = 0; i < 4; ++i)
          offset |= static_cast<int64_t>(in[ip + i]) << (8 * i);
        ip += 4;
      }
      if (offset <= 0 || offset > op || op + len > out_cap) return -1;
      // byte-by-byte: copies may overlap their own output (RLE)
      for (int64_t i = 0; i < len; ++i, ++op) out[op] = out[op - offset];
    }
  }
  return op == static_cast<int64_t>(ulen) ? op : -1;
}

inline uint32_t read_be32(const uint8_t* p) {
  return (static_cast<uint32_t>(p[0]) << 24) | (static_cast<uint32_t>(p[1]) << 16) |
         (static_cast<uint32_t>(p[2]) << 8) | p[3];
}

inline uint32_t read_le32(const uint8_t* p) {
  return p[0] | (static_cast<uint32_t>(p[1]) << 8) |
         (static_cast<uint32_t>(p[2]) << 16) | (static_cast<uint32_t>(p[3]) << 24);
}

// LZ4 block decode (literals + matches); returns bytes written or -1.
int64_t lz4_block(const uint8_t* in, int64_t in_len, uint8_t* out,
                  int64_t out_cap) {
  int64_t ip = 0, op = 0;
  while (ip < in_len) {
    const uint8_t token = in[ip++];
    int64_t lit = token >> 4;
    if (lit == 15) {
      for (;;) {
        if (ip >= in_len) return -1;  // truncated length extension
        const uint8_t b = in[ip++];
        lit += b;
        if (b != 255) break;
      }
    }
    if (ip + lit > in_len || op + lit > out_cap) return -1;
    std::memcpy(out + op, in + ip, lit);
    ip += lit;
    op += lit;
    if (ip >= in_len) break;  // last sequence has no match
    if (ip + 2 > in_len) return -1;
    const int64_t offset = in[ip] | (static_cast<int64_t>(in[ip + 1]) << 8);
    ip += 2;
    if (offset == 0 || offset > op) return -1;
    int64_t mlen = (token & 0x0f);
    if (mlen == 15) {
      for (;;) {
        if (ip >= in_len) return -1;  // truncated length extension
        const uint8_t b = in[ip++];
        mlen += b;
        if (b != 255) break;
      }
    }
    mlen += 4;
    if (op + mlen > out_cap) return -1;
    for (int64_t i = 0; i < mlen; ++i, ++op) out[op] = out[op - offset];
  }
  return op;
}

}  // namespace

extern "C" {

// Snappy: accepts Kafka's xerial framing (magic \x82SNAPPY\x00, then
// [be32 block length][raw snappy block]...) or a bare raw block.
// Returns bytes written to out, or -1 on malformed input / short out_cap.
int64_t kta_snappy_decompress(const uint8_t* in, int64_t in_len, uint8_t* out,
                              int64_t out_cap) {
  if (!in || !out || in_len < 0) return -1;
  static const uint8_t kXerial[8] = {0x82, 'S', 'N', 'A', 'P', 'P', 'Y', 0};
  if (in_len >= 16 && std::memcmp(in, kXerial, 8) == 0) {
    int64_t ip = 16;  // magic + version + compat (be32 each)
    int64_t op = 0;
    while (ip + 4 <= in_len) {
      const int64_t blen = read_be32(in + ip);
      ip += 4;
      if (blen < 0 || ip + blen > in_len) return -1;
      const int64_t n = snappy_raw(in + ip, blen, out + op, out_cap - op);
      if (n < 0) return -1;
      ip += blen;
      op += n;
    }
    return ip == in_len ? op : -1;
  }
  return snappy_raw(in, in_len, out, out_cap);
}

// LZ4: accepts an LZ4 frame (magic 0x184D2204; content checksum and block
// checksums tolerated/skipped, dictionaries unsupported) or a bare block.
int64_t kta_lz4_decompress(const uint8_t* in, int64_t in_len, uint8_t* out,
                           int64_t out_cap) {
  if (!in || !out || in_len < 0) return -1;
  if (in_len >= 7 && read_le32(in) == 0x184D2204u) {
    int64_t ip = 4;
    const uint8_t flg = in[ip];
    ip += 2;  // FLG + BD
    const bool content_size = flg & 0x08;
    const bool block_checksum = flg & 0x10;
    const bool content_checksum = flg & 0x04;
    if (flg & 0x01) return -1;  // dictionaries unsupported
    if (content_size) ip += 8;
    ip += 1;  // header checksum
    int64_t op = 0;
    while (ip + 4 <= in_len) {
      const uint32_t bsize = read_le32(in + ip);
      ip += 4;
      if (bsize == 0) {  // EndMark
        if (content_checksum) ip += 4;
        return op;
      }
      const bool uncompressed = bsize & 0x80000000u;
      const int64_t blen = bsize & 0x7fffffffu;
      if (ip + blen > in_len) return -1;
      if (uncompressed) {
        if (op + blen > out_cap) return -1;
        std::memcpy(out + op, in + ip, blen);
        op += blen;
      } else {
        const int64_t n = lz4_block(in + ip, blen, out + op, out_cap - op);
        if (n < 0) return -1;
        op += n;
      }
      ip += blen;
      if (block_checksum) ip += 4;
    }
    return -1;  // missing EndMark
  }
  return lz4_block(in, in_len, out, out_cap);
}

}  // extern "C"
