// Native ingest shim — the TPU build's counterpart of the reference's only
// native component, librdkafka (Cargo.toml:19; SURVEY.md §2.2).  The
// reference leans on librdkafka's C threads for all wire-level work and then
// processes messages one at a time in Rust; here the native layer's job is
// the *batch extraction* hot path (SURVEY.md §7 hard parts (a)/(b)): produce
// fixed-width record-metadata columns (lengths, null flags, timestamps, key
// hashes) at memory bandwidth so only numeric tensors ever cross into JAX.
//
// Exposed via a plain C ABI for ctypes (no pybind11 in this image):
//   - kta_synth_batch:   deterministic synthetic workload generation,
//                        bit-identical to io/synthetic.py::synth_fields
//   - kta_hash_batch:    fnv32(reference variant, src/fnv32.rs:92-101) +
//                        standard fnv64 over packed variable-length keys
//   - kta_version:       ABI version stamp
//
// Build: `make -C native` → libkta_ingest.so (g++ -O3, pthreads).

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kFnv32Offset = 0x811c9dc5u;
// The reference multiplies by the offset basis, NOT the FNV prime —
// reproduced on purpose (src/fnv32.rs:92-101).
constexpr uint32_t kFnv32Mult = 0x811c9dc5u;
constexpr uint64_t kFnv64Offset = 0xcbf29ce484222325ull;
constexpr uint64_t kFnv64Prime = 0x100000001b3ull;

inline uint64_t splitmix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ull;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ull;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebull;
  return x ^ (x >> 31);
}

inline uint32_t fnv1a32_ref(const uint8_t* p, int64_t n) {
  uint32_t h = kFnv32Offset;
  for (int64_t i = 0; i < n; ++i) h = (h ^ p[i]) * kFnv32Mult;
  return h;
}

inline uint64_t fnv1a64(const uint8_t* p, int64_t n) {
  uint64_t h = kFnv64Offset;
  for (int64_t i = 0; i < n; ++i) h = (h ^ p[i]) * kFnv64Prime;
  return h;
}

// Both key hashes in ONE pass: the two dependent xor-multiply chains are
// independent of each other, so interleaving them overlaps their
// latencies — per-key hash time approaches max(h32, h64) instead of the
// sum.  Used by every record decoder (per-frame, whole-set, fused).
inline void fnv1a_both(const uint8_t* p, int64_t n, uint32_t* h32,
                       uint64_t* h64) {
  uint32_t a = kFnv32Offset;
  uint64_t b = kFnv64Offset;
  for (int64_t i = 0; i < n; ++i) {
    const uint8_t c = p[i];
    a = (a ^ c) * kFnv32Mult;
    b = (b ^ c) * kFnv64Prime;
  }
  *h32 = a;
  *h64 = b;
}

// Parallel-for over [0, n) in contiguous chunks.
template <typename F>
void parallel_for(int64_t n, int threads, F&& body) {
  if (threads <= 1 || n < (1 << 14)) {
    body(0, n);
    return;
  }
  std::vector<std::thread> pool;
  int64_t chunk = (n + threads - 1) / threads;
  for (int t = 0; t < threads; ++t) {
    int64_t lo = t * chunk;
    int64_t hi = lo + chunk < n ? lo + chunk : n;
    if (lo >= hi) break;
    pool.emplace_back([&body, lo, hi] { body(lo, hi); });
  }
  for (auto& th : pool) th.join();
}

}  // namespace

extern "C" {

// Mirrors io/synthetic.py::SyntheticSpec (wire contract — keep in sync).
struct KtaSynthSpec {
  uint64_t seed;
  int32_t num_partitions;
  int64_t messages_per_partition;
  uint64_t keys_per_partition;
  int32_t key_null_permille;
  int32_t tombstone_permille;
  int32_t value_len_min;
  int32_t value_len_max;
  int32_t key_digits;
  int64_t ts_start_ms;
  int64_t ts_step_ms;
};

int32_t kta_version() { return 14; }

// CRC32-C (Castagnoli) over a byte buffer — Kafka's record-batch checksum.
// Table-driven; the Python fallback (kafka_codec._crc32c) is a per-byte
// interpreter loop that costs ~100 ms/MB, which made check.crcs=true
// impractical.
uint32_t kta_crc32c(const uint8_t* data, int64_t n) {
  // Thread-safe magic static: ctypes releases the GIL, so concurrent first
  // calls are real; a hand-rolled flag would race on the table writes.
  static const std::vector<uint32_t> table = [] {
    std::vector<uint32_t> t(256);
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t crc = i;
      for (int k = 0; k < 8; ++k)
        crc = (crc & 1) ? (crc >> 1) ^ 0x82f63b78u : crc >> 1;
      t[i] = crc;
    }
    return t;
  }();
  uint32_t crc = 0xffffffffu;
  for (int64_t i = 0; i < n; ++i)
    crc = table[(crc ^ data[i]) & 0xff] ^ (crc >> 8);
  return crc ^ 0xffffffffu;
}

// Last-writer-wins dedupe of alive-bitmap updates for one batch
// (the host half of the packed transfer's pre-reduction; see
// kafka_topic_analyzer_tpu/packing.py).  For each slot = h32 & (2^bits - 1)
// of an active record, only the LAST record's aliveness survives —
// equivalent to replaying insert/remove in record order.  Open-addressing
// hash table over the batch (capacity = next pow2 >= 2n), single pass.
// Outputs at most n (slot, alive) pairs; returns the pair count, or -1 on
// bad arguments.
int64_t kta_dedupe_slots(const uint32_t* h32, const uint8_t* active,
                         const uint8_t* alive, int64_t n, int32_t bits,
                         uint32_t* slot_out, uint8_t* alive_out) {
  if (!h32 || !active || !alive || !slot_out || !alive_out || n < 0 ||
      bits < 1 || bits > 32)
    return -1;
  const uint32_t mask =
      bits == 32 ? 0xffffffffu : ((1u << bits) - 1u);
  size_t cap = 16;
  while (cap < static_cast<size_t>(n) * 2) cap <<= 1;
  const size_t cap_mask = cap - 1;
  // table: index into out arrays + 1; 0 = empty.
  std::vector<int64_t> table(cap, 0);
  int64_t count = 0;
  for (int64_t i = 0; i < n; ++i) {
    if (!active[i]) continue;
    const uint32_t slot = h32[i] & mask;
    size_t pos = (splitmix64(slot) & cap_mask);
    for (;;) {
      int64_t entry = table[pos];
      if (entry == 0) {
        table[pos] = count + 1;
        slot_out[count] = slot;
        alive_out[count] = alive[i];
        ++count;
        break;
      }
      if (slot_out[entry - 1] == slot) {
        alive_out[entry - 1] = alive[i];  // later record wins
        break;
      }
      pos = (pos + 1) & cap_mask;
    }
  }
  return count;
}

// Generate records for global indices [lo, hi) over the partition list
// `parts` (round-robin: g -> parts[g % nparts] at offset g / nparts),
// exactly like SyntheticSource.batches.  All output arrays have hi-lo
// elements.  Returns 0 on success.
int32_t kta_synth_batch(const KtaSynthSpec* spec,
                        const int32_t* parts, int32_t nparts,
                        int64_t lo, int64_t hi, int32_t threads,
                        int32_t* partition_out, int32_t* key_len_out,
                        int32_t* value_len_out, uint8_t* key_null_out,
                        uint8_t* value_null_out, int64_t* ts_s_out,
                        uint32_t* h32_out, uint64_t* h64_out,
                        uint8_t* valid_out) {
  if (!spec || !parts || nparts <= 0 || hi < lo) return -1;
  const int64_t n = hi - lo;
  const KtaSynthSpec s = *spec;
  const int key_len_total = 1 + s.key_digits;

  // Stream bases depend only on the partition — mix once per slot of the
  // round-robin, not once per record.
  std::vector<uint64_t> bases(nparts);
  for (int32_t j = 0; j < nparts; ++j)
    bases[j] = splitmix64(s.seed ^ (static_cast<uint64_t>(parts[j]) << 40));

  parallel_for(n, threads, [&](int64_t a, int64_t b) {
    uint8_t keybuf[64];
    keybuf[0] = 'k';
    for (int64_t i = a; i < b; ++i) {
      const int64_t g = lo + i;
      const int32_t p = parts[g % nparts];
      const int64_t o = g / nparts;
      // Record o is the o-th output of a SplitMix64 stream with a mixed
      // per-partition base (see io/synthetic.py — wire contract).
      const uint64_t x = splitmix64(bases[g % nparts] +
                                    static_cast<uint64_t>(o) * 0x9e3779b97f4a7c15ull);

      const bool key_null =
          static_cast<int64_t>(x % 1000ull) < s.key_null_permille;
      const bool value_null =
          static_cast<int64_t>((x >> 10) % 1000ull) < s.tombstone_permille;
      const uint64_t local = (x >> 20) % s.keys_per_partition;
      const uint64_t key_id =
          static_cast<uint64_t>(p) +
          static_cast<uint64_t>(s.num_partitions) * local;
      const uint64_t vspread =
          static_cast<uint64_t>(s.value_len_max - s.value_len_min + 1);
      const int32_t vlen =
          value_null ? 0
                     : s.value_len_min +
                           static_cast<int32_t>((x >> 40) % vspread);

      partition_out[i] = p;
      value_len_out[i] = vlen;
      key_null_out[i] = key_null ? 1 : 0;
      value_null_out[i] = value_null ? 1 : 0;
      // floor division like numpy (`//`): values are non-negative here.
      ts_s_out[i] = (s.ts_start_ms + o * s.ts_step_ms) / 1000;
      valid_out[i] = 1;

      if (key_null) {
        key_len_out[i] = 0;
        h32_out[i] = 0;
        h64_out[i] = 0;
      } else {
        key_len_out[i] = key_len_total;
        uint64_t rem = key_id;
        for (int d = s.key_digits - 1; d >= 0; --d) {
          keybuf[1 + d] = static_cast<uint8_t>('0' + (rem % 10));
          rem /= 10;
        }
        h32_out[i] = fnv1a32_ref(keybuf, key_len_total);
        h64_out[i] = fnv1a64(keybuf, key_len_total);
      }
    }
  });
  return 0;
}

// Hash n variable-length byte slices packed in `data` at `offsets`
// (offsets[n] marks the end).  Used by the Kafka wire source to hash real
// key bytes off the fetch path.
int32_t kta_hash_batch(const uint8_t* data, const int64_t* offsets, int64_t n,
                       int32_t threads, uint32_t* h32_out, uint64_t* h64_out) {
  if (!data || !offsets || n < 0) return -1;
  parallel_for(n, threads, [&](int64_t a, int64_t b) {
    for (int64_t i = a; i < b; ++i) {
      const int64_t off = offsets[i];
      const int64_t len = offsets[i + 1] - off;
      h32_out[i] = fnv1a32_ref(data + off, len);
      h64_out[i] = fnv1a64(data + off, len);
    }
  });
  return 0;
}

// Kafka RecordBatch v2 record decoding: parse one decompressed batch
// payload into fixed-width SoA columns, hashing key bytes inline — the hot
// half of the wire client (the Python per-record generator measures ~225k
// records/s; this decodes at tens of millions).  The caller (io/native.py /
// kafka_codec.iter_batch_frames) has already handled framing, CRC and
// decompression.  Returns the number of records decoded, or -1 on malformed
// input (caller falls back to the Python decoder for a precise error).
int64_t kta_decode_records(const uint8_t* payload, int64_t payload_len,
                           int32_t num_records, int64_t base_offset,
                           int64_t first_ts_ms,
                           int64_t* offsets_out, int64_t* ts_ms_out,
                           int32_t* key_len_out, int32_t* value_len_out,
                           uint8_t* key_null_out, uint8_t* value_null_out,
                           uint32_t* h32_out, uint64_t* h64_out);

}  // extern "C"

namespace {
// Zigzag varint over [pos, len); false on truncation/overflow.
inline bool read_zigzag(const uint8_t* p, int64_t len, int64_t& pos,
                        int64_t& out) {
  uint64_t z = 0;
  int shift = 0;
  while (pos < len) {
    const uint8_t b = p[pos++];
    z |= static_cast<uint64_t>(b & 0x7f) << shift;
    if (!(b & 0x80)) {
      out = static_cast<int64_t>(z >> 1) ^ -static_cast<int64_t>(z & 1);
      return true;
    }
    shift += 7;
    if (shift > 63) return false;
  }
  return false;
}
}  // namespace

extern "C" int64_t kta_decode_records(
    const uint8_t* payload, int64_t payload_len, int32_t num_records,
    int64_t base_offset, int64_t first_ts_ms,
    int64_t* offsets_out, int64_t* ts_ms_out,
    int32_t* key_len_out, int32_t* value_len_out,
    uint8_t* key_null_out, uint8_t* value_null_out,
    uint32_t* h32_out, uint64_t* h64_out) {
  if (!payload || payload_len < 0 || num_records < 0) return -1;
  int64_t pos = 0;
  for (int32_t i = 0; i < num_records; ++i) {
    int64_t length;
    if (!read_zigzag(payload, payload_len, pos, length)) return -1;
    // Overflow-safe: a hostile 10-byte varint can encode ~2^63 and
    // `pos + length` would overflow int64 (UB) and bypass the bound.
    if (length < 0 || length > payload_len - pos) return -1;
    const int64_t rec_end = pos + length;
    if (pos >= rec_end) return -1;
    ++pos;  // record attributes
    int64_t ts_delta, off_delta, klen, vlen;
    if (!read_zigzag(payload, rec_end, pos, ts_delta)) return -1;
    if (!read_zigzag(payload, rec_end, pos, off_delta)) return -1;
    if (!read_zigzag(payload, rec_end, pos, klen)) return -1;
    if (klen < 0) {
      key_null_out[i] = 1;
      key_len_out[i] = 0;
      h32_out[i] = 0;
      h64_out[i] = 0;
    } else {
      if (klen > rec_end - pos || klen > 0x7fffffff) return -1;
      key_null_out[i] = 0;
      key_len_out[i] = static_cast<int32_t>(klen);
      fnv1a_both(payload + pos, klen, h32_out + i, h64_out + i);
      pos += klen;
    }
    if (!read_zigzag(payload, rec_end, pos, vlen)) return -1;
    if (vlen < 0) {
      value_null_out[i] = 1;
      value_len_out[i] = 0;
    } else {
      if (vlen > rec_end - pos || vlen > 0x7fffffff) return -1;
      value_null_out[i] = 0;
      value_len_out[i] = static_cast<int32_t>(vlen);
      pos += vlen;  // value bytes never needed (SURVEY.md §3.4)
    }
    int64_t nheaders;
    if (!read_zigzag(payload, rec_end, pos, nheaders)) return -1;
    if (nheaders < 0) return -1;
    for (int64_t h = 0; h < nheaders; ++h) {
      int64_t hk, hv;
      if (!read_zigzag(payload, rec_end, pos, hk)) return -1;
      if (hk < 0 || hk > rec_end - pos) return -1;
      pos += hk;
      if (!read_zigzag(payload, rec_end, pos, hv)) return -1;
      if (hv > 0) {
        if (hv > rec_end - pos) return -1;
        pos += hv;
      }
    }
    offsets_out[i] = base_offset + off_delta;
    ts_ms_out[i] = first_ts_ms + ts_delta;
    pos = rec_end;  // tolerate unknown trailing record fields
  }
  return num_records;
}

namespace {

inline int64_t be64(const uint8_t* p) {
  uint64_t v = 0;
  for (int i = 0; i < 8; ++i) v = (v << 8) | p[i];
  return static_cast<int64_t>(v);
}
inline int32_t be32(const uint8_t* p) {
  uint32_t v = 0;
  for (int i = 0; i < 4; ++i) v = (v << 8) | p[i];
  return static_cast<int32_t>(v);
}
inline int16_t be16(const uint8_t* p) {
  return static_cast<int16_t>((uint16_t(p[0]) << 8) | p[1]);
}

// One RecordBatch v2 frame header at `pos` of the NATIVE-decodable kind:
// complete, magic 2, uncompressed, sane record count, CRC ok (when asked).
// Returns true and fills the fields; false means the caller must stop the
// native walk here (a Python path decodes the frame — compressed, legacy
// MessageSet, truncated tail — or raises the precise protocol error).
struct FrameHeader {
  int64_t base_offset;
  int64_t first_ts;
  int64_t end;           // byte offset one past the frame
  int64_t payload_pos;   // first record byte
  int64_t covered_end;   // base_offset + max(last_offset_delta, 0) + 1
  int32_t num_records;
  bool control;          // txn commit/abort markers: skip, offsets count
};

inline bool native_frame_at(const uint8_t* buf, int64_t len, int64_t pos,
                            int32_t verify_crc, FrameHeader* fh) {
  if (pos + 61 > len) return false;          // header incomplete
  const int64_t batch_length = be32(buf + pos + 8);
  if (batch_length <= 0) return false;
  const int64_t end = pos + 12 + batch_length;
  if (end > len) return false;               // partial trailing frame
  if (buf[pos + 16] != 2) return false;      // legacy MessageSet v0/v1
  const int16_t attributes = be16(buf + pos + 21);
  if ((attributes & 0x07) != 0) return false;  // compressed
  fh->control = (attributes & 0x20) != 0;
  const int32_t num_records = be32(buf + pos + 57);
  const int64_t payload_pos = pos + 61;
  // Untrusted count: a valid record needs >= 7 payload bytes.
  if (num_records < 0 || num_records > (end - payload_pos) / 7) return false;
  if (verify_crc) {
    const uint32_t crc = static_cast<uint32_t>(be32(buf + pos + 17));
    if (kta_crc32c(buf + pos + 21, end - (pos + 21)) != crc) return false;
  }
  const int32_t last_offset_delta = be32(buf + pos + 23);
  fh->base_offset = be64(buf + pos);
  fh->first_ts = be64(buf + pos + 27);
  fh->end = end;
  fh->payload_pos = payload_pos;
  fh->num_records = num_records;
  fh->covered_end =
      fh->base_offset + (last_offset_delta > 0 ? last_offset_delta : 0) + 1;
  return true;
}

}  // namespace

// Count the records in the native-decodable PREFIX of a record set (a
// Fetch response's per-partition records field): consecutive complete,
// uncompressed, magic-2 frames.  The count sizes the caller's output
// arrays for kta_decode_record_set; the walk is a header jump per frame
// (no record parsing), so it costs ~nothing next to the decode.
extern "C" int64_t kta_scan_record_set(const uint8_t* buf, int64_t len,
                                       int32_t verify_crc,
                                       int64_t* consumed_out,
                                       int64_t* covered_out) {
  if (!buf || len < 0) return -1;
  int64_t pos = 0, total = 0, covered = -1;
  FrameHeader fh;
  while (native_frame_at(buf, len, pos, verify_crc, &fh)) {
    if (!fh.control) total += fh.num_records;  // markers aren't messages
    if (fh.covered_end > covered) covered = fh.covered_end;
    pos = fh.end;
  }
  if (consumed_out) *consumed_out = pos;
  if (covered_out) *covered_out = covered;
  return total;
}

// Decode the native-decodable prefix of a record set in ONE call: every
// frame's records into contiguous SoA columns (the per-frame
// kta_decode_records core, pointer-shifted per frame).  Replaces the
// per-frame Python loop of header parse + ctypes call + numpy slicing —
// the wire client's remaining hot-path overhead after round 1 made the
// record decode itself native (io/kafka_wire.py::batches).
// Returns records decoded (== kta_scan_record_set's count), or -1 on a
// malformed frame (callers re-walk with the Python decoder for the
// precise error).  consumed_out: bytes of prefix handled; covered_end_out:
// max over frames of (base_offset + last_offset_delta + 1), the
// compaction-aware scan position advance.
extern "C" int64_t kta_decode_record_set(
    const uint8_t* buf, int64_t len, int32_t verify_crc, int64_t capacity,
    int64_t* offsets_out, int64_t* ts_ms_out,
    int32_t* key_len_out, int32_t* value_len_out,
    uint8_t* key_null_out, uint8_t* value_null_out,
    uint32_t* h32_out, uint64_t* h64_out,
    int64_t* consumed_out, int64_t* covered_end_out) {
  if (!buf || len < 0 || capacity < 0) return -1;
  int64_t pos = 0, n = 0, covered = -1;
  FrameHeader fh;
  while (native_frame_at(buf, len, pos, verify_crc, &fh)) {
    if (fh.control) {  // txn markers: no records, offsets still covered
      if (fh.covered_end > covered) covered = fh.covered_end;
      pos = fh.end;
      continue;
    }
    if (n + fh.num_records > capacity) return -1;
    const int64_t got = kta_decode_records(
        buf + fh.payload_pos, fh.end - fh.payload_pos, fh.num_records,
        fh.base_offset, fh.first_ts,
        offsets_out + n, ts_ms_out + n, key_len_out + n, value_len_out + n,
        key_null_out + n, value_null_out + n, h32_out + n, h64_out + n);
    if (got != fh.num_records) return -1;
    n += got;
    if (fh.covered_end > covered) covered = fh.covered_end;
    pos = fh.end;
  }
  if (consumed_out) *consumed_out = pos;
  if (covered_end_out) *covered_end_out = covered;
  return n;
}

namespace {
// Wire-v5 full-batch packer (combiner rows) — defined after the fused
// row-layout machinery it shares with the incremental packers.
int64_t pack_batch_v5(
    const int32_t* partition, const int32_t* key_len, const int32_t* value_len,
    const uint8_t* key_null, const uint8_t* value_null, const int64_t* ts_s,
    const uint32_t* h32, const uint64_t* h64,
    int64_t n_valid, int64_t batch_size, int32_t num_partitions,
    int32_t with_alive, int32_t alive_bits, int32_t with_hll, int32_t hll_p,
    int32_t hll_rows, int32_t value_len_cap, int32_t q_rows,
    int32_t q_nbuckets, const int64_t* q_edges, uint8_t* out,
    int64_t out_cap);
}  // namespace

// Fused batch packing: RecordBatch SoA columns -> wire-format-v4 buffer
// (kafka_topic_analyzer_tpu/packing.py), including the host pre-reductions
// (per-partition ts min/max table, last-writer-wins bitmap dedupe via
// kta_dedupe_slots' table, and the HLL reduction — global register table
// in mode 2, per-record (bucket, rho) pairs in mode 1).  One C++ pass
// replaces several numpy conversions on the per-batch hot path.  Layout
// contract lives in packing.py; keep in sync (HEADER 16B; sections
// p i16[B] | klen u16[B] | vlen u32[B] | flags u8[B] | ts_minmax i64[2P] |
// sz_minmax i64[2P] | [slot u32[B] | alive u8[B]] |
// [hll: regs u8[rows << p] (mode 2) OR idx u16[B] | rho u8[B] (mode 1)]).
// wire_v5 selects the combiner layout instead (packing.py wire v5): the
// four per-record columns are replaced by a per-partition counter-delta
// table i64[P*7] (+ an optional DDSketch bucket table i64[q_rows*(nb+2)]
// keyed by the shared integer edge table q_edges), with_hll gains mode 3
// (flat u32 idx = partition << p | bucket, v5's per-partition pair form).
// Returns total bytes written, or -1 on error (including key_len > u16 /
// partition out of i16/num_partitions range — mirrors pack_batch's
// validation).
extern "C" int64_t kta_pack_batch(
    const int32_t* partition, const int32_t* key_len, const int32_t* value_len,
    const uint8_t* key_null, const uint8_t* value_null, const int64_t* ts_s,
    const uint32_t* h32, const uint64_t* h64,
    int64_t n_valid, int64_t batch_size, int32_t num_partitions,
    int32_t with_alive, int32_t alive_bits, int32_t with_hll, int32_t hll_p,
    int32_t hll_rows,
    int32_t value_len_cap,
    int32_t wire_v5, int32_t q_rows, int32_t q_nbuckets,
    const int64_t* q_edges,
    uint8_t* out, int64_t out_cap) {
  if (n_valid < 0 || n_valid > batch_size) return -1;
  if (num_partitions <= 0) return -1;
  // with_alive == 2 (pairs-to-scratch compaction) is a fused-row mode:
  // this whole-batch packer has no scratch to emit into — the Python
  // caller packs with alive OFF and dedupes the columns separately
  // (packing.batch_alive_pairs).
  if (with_alive != 0 && with_alive != 1) return -1;
  if (wire_v5)
    return pack_batch_v5(
        partition, key_len, value_len, key_null, value_null, ts_s, h32, h64,
        n_valid, batch_size, num_partitions, with_alive, alive_bits, with_hll,
        hll_p, hll_rows, value_len_cap, q_rows, q_nbuckets, q_edges, out,
        out_cap);
  const int64_t b = batch_size;
  const int64_t P = num_partitions;
  // Wire format v4: the per-record i64 ts column is replaced by TWO [2P]
  // per-partition min/max tables — timestamps and (tombstone-excluded)
  // message sizes (packing.py::_sections rationale).
  int64_t need = 16 + b * (2 + 2 + 4 + 1) + 2 * (2 * P * 8);
  if (with_alive) need += b * 5;
  // with_hll: 0 = off, 1 = per-record pairs, 2 = host-reduced register
  // table of hll_rows << hll_p bytes (wire v3; rows = 1 global or P
  // per-partition — python's packing.hll_table_rows decides).
  if (with_hll == 1) need += b * 3;
  if (with_hll == 2) {
    // Per-row tables index by partition id: rows must cover every id the
    // (validated) partition column can carry, or tbl[row << p | idx]
    // writes past the section.
    if (hll_rows < 1 || (hll_rows > 1 && hll_rows < num_partitions))
      return -1;
    need += int64_t(hll_rows) << hll_p;
  }
  if (need > out_cap) return -1;

  std::memset(out, 0, need);
  int64_t pos = 16;
  // Section base pointers stay uint8_t*; elements are stored via memcpy —
  // sections are only naturally aligned when batch_size is a multiple of 8,
  // and typed stores through misaligned pointers are UB.
  uint8_t* p16 = out + pos;
  pos += b * 2;
  uint8_t* kl16 = out + pos;
  pos += b * 2;
  uint8_t* vl32 = out + pos;
  pos += b * 4;
  uint8_t* fl8 = out + pos;
  pos += b;
  uint8_t* tsmm64 = out + pos;
  pos += 2 * P * 8;
  uint8_t* szmm64 = out + pos;
  pos += 2 * P * 8;

  auto store = [](uint8_t* base, int64_t idx, auto v) {
    std::memcpy(base + idx * static_cast<int64_t>(sizeof(v)), &v, sizeof(v));
  };

  const int32_t vcap =
      value_len_cap > 0 ? value_len_cap : 0x7fffffff;
  std::atomic<bool> bad{false};
  parallel_for(n_valid, 8, [&](int64_t a, int64_t e) {
    for (int64_t i = a; i < e; ++i) {
      if (partition[i] < 0 || partition[i] > 0x7fff ||
          partition[i] >= num_partitions ||
          key_len[i] < 0 || key_len[i] > 0xffff ||
          value_len[i] < 0 || value_len[i] > vcap) {
        bad.store(true);
        return;
      }
      store(p16, i, static_cast<int16_t>(partition[i]));
      store(kl16, i, static_cast<uint16_t>(key_len[i]));
      store(vl32, i, static_cast<uint32_t>(value_len[i]));
      fl8[i] = (key_null[i] ? 1 : 0) | (value_null[i] ? 2 : 0);
    }
  });
  if (bad.load()) return -1;

  {
    // Per-partition ts min/max AND (tombstone-excluded) message-size
    // min/max over the valid prefix: identity-filled, single sequential
    // pass (~1-2 ns/record; not worth the thread fan-out).  Size
    // identities are I64_MAX / 0, matching the reference's `largest`
    // starting at 0 (src/metric.rs:34, :249-251).
    std::vector<int64_t> mm(2 * P), sz(2 * P);
    for (int64_t r = 0; r < P; ++r) {
      mm[r] = INT64_MAX;
      mm[P + r] = INT64_MIN;
      sz[r] = INT64_MAX;
      sz[P + r] = 0;
    }
    for (int64_t i = 0; i < n_valid; ++i) {
      const int64_t r = partition[i];
      const int64_t t = ts_s[i];
      if (t < mm[r]) mm[r] = t;
      if (t > mm[P + r]) mm[P + r] = t;
      if (!value_null[i]) {
        const int64_t size =
            (key_null[i] ? 0 : static_cast<int64_t>(key_len[i])) +
            static_cast<int64_t>(value_len[i]);
        if (size < sz[r]) sz[r] = size;
        if (size > sz[P + r]) sz[P + r] = size;
      }
    }
    std::memcpy(tsmm64, mm.data(), 2 * P * 8);
    std::memcpy(szmm64, sz.data(), 2 * P * 8);
  }

  int64_t n_pairs = 0;
  if (with_alive) {
    uint8_t* slot32 = out + pos;
    pos += b * 4;
    uint8_t* alive8 = out + pos;
    pos += b;
    if (n_valid > 0) {
      // active = valid & key non-null; alive = value non-null.  Dedupe into
      // aligned temporaries, then memcpy into the (possibly unaligned)
      // section.  (Empty batches skip this entirely — sharded scans pack
      // empty shard batches every step.)
      std::vector<uint8_t> active(n_valid), alive(n_valid);
      for (int64_t i = 0; i < n_valid; ++i) {
        active[i] = key_null[i] ? 0 : 1;
        alive[i] = value_null[i] ? 0 : 1;
      }
      std::vector<uint32_t> slots(n_valid);
      std::vector<uint8_t> flags(n_valid);
      n_pairs = kta_dedupe_slots(h32, active.data(), alive.data(), n_valid,
                                 alive_bits, slots.data(), flags.data());
      if (n_pairs < 0) return -1;
      std::memcpy(slot32, slots.data(), n_pairs * 4);
      std::memcpy(alive8, flags.data(), n_pairs);
    }
  }
  if (with_hll == 1) {
    uint8_t* idx16 = out + pos;
    pos += b * 2;
    uint8_t* rho8 = out + pos;
    pos += b;
    const int p = hll_p;
    parallel_for(n_valid, 8, [&](int64_t a, int64_t e) {
      for (int64_t i = a; i < e; ++i) {
        if (key_null[i]) {
          store(idx16, i, static_cast<uint16_t>(0));
          rho8[i] = 0;
          continue;
        }
        const uint64_t h = splitmix64(h64[i]);
        store(idx16, i, static_cast<uint16_t>(h >> (64 - p)));
        const uint64_t rest = h << p;
        rho8[i] = rest == 0
                      ? static_cast<uint8_t>(64 - p + 1)
                      : static_cast<uint8_t>(__builtin_clzll(rest) + 1);
      }
    });
  } else if (with_hll == 2) {
    // Register table: scatter-max on the host's cache-resident
    // u8[rows << p] (64 KB at p=16 global), sequential single pass — the
    // device then merges it elementwise.  Row 0 for the global sketch;
    // the record's partition row when per-partition registers fit the
    // table budget.  (The memset above already zeroed it.)
    uint8_t* tbl = out + pos;
    const int p = hll_p;
    const bool per_row = hll_rows > 1;
    pos += int64_t(hll_rows) << p;
    for (int64_t i = 0; i < n_valid; ++i) {
      if (key_null[i]) continue;
      const uint64_t h = splitmix64(h64[i]);
      const int64_t row = per_row ? partition[i] : 0;
      const int64_t idx = (row << p) | static_cast<int64_t>(h >> (64 - p));
      const uint64_t rest = h << p;
      const uint8_t rho =
          rest == 0 ? static_cast<uint8_t>(64 - p + 1)
                    : static_cast<uint8_t>(__builtin_clzll(rest) + 1);
      if (rho > tbl[idx]) tbl[idx] = rho;
    }
  }

  // Header: n_valid i32 | n_pairs i32 | reserved.
  const int32_t hv = static_cast<int32_t>(n_valid);
  const int32_t hp = static_cast<int32_t>(n_pairs);
  std::memcpy(out, &hv, 4);
  std::memcpy(out + 4, &hp, 4);
  return need;
}

// ---------------------------------------------------------------------------
// Fused decode→pack: one pass from raw fetch bytes to wire-v4 packed rows.
//
// The chained hot path is kta_decode_record_set (wire bytes → eight SoA
// columns) followed by kta_pack_batch (columns → wire-v4 buffer): every
// record's metadata is written to memory once and read back once purely to
// move between the two calls.  The fused entry points below append records
// STRAIGHT into a caller-supplied wire-v4 row as they are decoded — the SoA
// intermediate never exists.  Because a row outlives a single call (record
// sets are smaller or larger than one batch), append state persists in a
// caller-owned int64 scratch:
//
//   scratch[0] = n        records appended to the row so far (the cursor;
//                         becomes header n_valid)
//   scratch[1] = n_pairs  alive-dedupe pairs emitted (header n_pairs)
//   scratch[2] = cap      dedupe table capacity (0 when alive is off)
//   scratch[3..3+cap)     open-addressing LWW table (pair index + 1; 0 empty)
//
// The dedupe table persisting across appends is what makes incremental
// packing exact: output pair ORDER is first-occurrence record order —
// independent of table capacity — so a row built by many appends is
// byte-identical to kta_pack_batch over the same records (asserted by
// tests/test_fused.py).
//
// Error contract (mirrors the taxonomy io/native.py documents):
//   >= 0  records appended
//   -1    bad arguments / layout mismatch
//   -2    pack-range violation (a decoded value the wire-v4 layout cannot
//         carry: key_len > u16, value_len > cap, partition out of range) —
//         detail[0] = field code (0 klen / 1 vlen / 2 partition),
//         detail[1] = offending value.  The Python wrapper re-raises the
//         same ValueError the numpy packer would.
// A *malformed frame* is NOT an error here: the walk stops at the frame
// boundary exactly like kta_scan_record_set, and the caller's per-frame
// Python chain classifies it precisely (CorruptFrameError taxonomy).
// Frames are validated in a store-free pre-pass before any append, so a
// frame either appends completely or not at all — corruption can never
// leave half a frame committed to a row.

namespace {

struct PackRowLayout {
  int64_t b;
  int64_t P;
  int32_t with_alive;
  int32_t alive_bits;
  int32_t with_hll;  // 0 off, 1 u16 pairs, 2 register table, 3 u32 flat
                     // pairs (v5: partition << p | bucket)
  int32_t hll_p;
  int32_t hll_rows;
  int32_t vcap;
  int32_t wire_v5;   // combiner layout: counts table replaces the columns
  int32_t q_rows;    // DDSketch rows (0 = no quant section; v5 only)
  int32_t q_nbuckets;            // log buckets (section adds +2)
  const int64_t* q_edges;        // shared integer bucket edge table
  int64_t need;
  // Section base pointers (uint8_t*: sections are only naturally aligned
  // when batch_size is a multiple of 8 — all element access via memcpy).
  uint8_t *p16, *kl16, *vl32, *fl8, *tsmm, *szmm;
  uint8_t *cnt64;          // v5: i64[P * 7] counter deltas
  uint8_t *slot32, *alive8;
  uint8_t *hll_a, *hll_b;  // idx/rho (modes 1/3) or regs/- (mode 2)
  uint8_t *q64;            // v5: i64[q_rows * (q_nbuckets + 2)]
};

inline bool pack_row_layout(uint8_t* out, int64_t out_cap, int64_t b,
                            int32_t P, int32_t with_alive, int32_t alive_bits,
                            int32_t with_hll, int32_t hll_p, int32_t hll_rows,
                            int32_t value_len_cap, int32_t wire_v5,
                            int32_t q_rows, int32_t q_nbuckets,
                            const int64_t* q_edges, PackRowLayout* r) {
  if (!out || b < 0 || P <= 0 || P > 0x7fff) return false;
  if (with_alive < 0 || with_alive > 2) return false;
  if (with_alive && (alive_bits < 1 || alive_bits > 32)) return false;
  if (with_alive == 2 && !wire_v5) return false;  // compaction is v5-only
  if (with_hll == 3 && !wire_v5) return false;  // flat pairs are v5-only
  if (q_rows > 0 && (!wire_v5 || !q_edges || q_nbuckets < 1)) return false;
  if (q_rows > 1 && q_rows < P) return false;  // rows index by partition
  int64_t need = 16 + 2 * (2 * int64_t(P) * 8);
  if (wire_v5)
    need += int64_t(P) * 7 * 8;
  else
    need += b * (2 + 2 + 4 + 1);
  // with_alive == 2 (compaction): the dedupe table still runs, but the
  // pairs divert to a caller-scratch region (attach_scratch_pairs) and
  // the row carries NO pair sections — the dispatch-level merged pair
  // table ships them instead (packing.pack_pair_table).
  if (with_alive == 1) need += b * 5;
  if (with_hll == 1) need += b * 3;
  if (with_hll == 3) need += b * 5;
  if (with_hll == 2) {
    if (hll_rows < 1 || (hll_rows > 1 && hll_rows < P)) return false;
    need += int64_t(hll_rows) << hll_p;
  }
  if (q_rows > 0) need += int64_t(q_rows) * (int64_t(q_nbuckets) + 2) * 8;
  if (need > out_cap) return false;
  r->b = b;
  r->P = P;
  r->with_alive = with_alive;
  r->alive_bits = alive_bits;
  r->with_hll = with_hll;
  r->hll_p = hll_p;
  r->hll_rows = hll_rows;
  r->vcap = value_len_cap > 0 ? value_len_cap : 0x7fffffff;
  r->wire_v5 = wire_v5;
  r->q_rows = q_rows;
  r->q_nbuckets = q_nbuckets;
  r->q_edges = q_edges;
  r->need = need;
  int64_t pos = 16;
  r->p16 = r->kl16 = r->vl32 = r->fl8 = r->cnt64 = nullptr;
  if (wire_v5) {
    r->cnt64 = out + pos;
    pos += int64_t(P) * 7 * 8;
  } else {
    r->p16 = out + pos;
    pos += b * 2;
    r->kl16 = out + pos;
    pos += b * 2;
    r->vl32 = out + pos;
    pos += b * 4;
    r->fl8 = out + pos;
    pos += b;
  }
  r->tsmm = out + pos;
  pos += 2 * P * 8;
  r->szmm = out + pos;
  pos += 2 * P * 8;
  r->slot32 = r->alive8 = nullptr;
  if (with_alive == 1) {
    r->slot32 = out + pos;
    pos += b * 4;
    r->alive8 = out + pos;
    pos += b;
  }
  r->hll_a = r->hll_b = nullptr;
  if (with_hll == 1) {
    r->hll_a = out + pos;  // idx u16[B]
    pos += b * 2;
    r->hll_b = out + pos;  // rho u8[B]
    pos += b;
  } else if (with_hll == 3) {
    r->hll_a = out + pos;  // idx u32[B] (row << p | bucket)
    pos += b * 4;
    r->hll_b = out + pos;  // rho u8[B]
    pos += b;
  } else if (with_hll == 2) {
    r->hll_a = out + pos;  // regs u8[rows << p]
    pos += int64_t(hll_rows) << hll_p;
  }
  r->q64 = nullptr;
  if (q_rows > 0) {
    r->q64 = out + pos;
    pos += int64_t(q_rows) * (int64_t(q_nbuckets) + 2) * 8;
  }
  return true;
}

inline int64_t pack_scratch_cap(int64_t b, int32_t with_alive,
                                int32_t alive_bits) {
  if (!with_alive) return 0;
  // The table can only ever hold min(b, 2^bits) distinct slots; sizing
  // by that instead of b keeps it cache-resident for practical bitmap
  // sizes (capacity changes probe POSITIONS, never the first-occurrence
  // output order, so rows stay byte-identical to kta_dedupe_slots).
  int64_t distinct = b;
  if (alive_bits < 62 && (int64_t(1) << alive_bits) < distinct)
    distinct = int64_t(1) << alive_bits;
  int64_t cap = 16;
  while (cap < distinct * 2) cap <<= 1;
  return cap;
}

template <typename T>
inline void store_at(uint8_t* base, int64_t idx, T v) {
  std::memcpy(base + idx * int64_t(sizeof(T)), &v, sizeof(T));
}
template <typename T>
inline T load_at(const uint8_t* base, int64_t idx) {
  T v;
  std::memcpy(&v, base + idx * int64_t(sizeof(T)), sizeof(T));
  return v;
}

// Batched append core.  The per-record interleaved form (decode one
// record, probe the dedupe table, RMW the extreme tables, repeat) stalls
// on a dependent random cache miss per record; the passes below keep the
// chained packer's memory-level parallelism — decode writes the
// per-record sections in one tight loop while stashing the reduction
// inputs compactly, then dedupe/HLL/extremes each run as a dedicated
// tight pass per frame.

// Compact per-frame stash of the reduction inputs, carved out of the
// caller scratch after the dedupe table: hashes + aliveness for ACTIVE
// (non-null key) records, and — wire v5 with quantiles — the message
// sizes of SIZED (non-tombstone) records for the DDSketch bucket pass.
struct FrameStash {
  uint64_t* h64;
  uint32_t* h32;
  int64_t* size;
  uint8_t* alive;
  int64_t n;    // active records stashed (h64/h32/alive)
  int64_t nsz;  // sized records stashed (size)
};

inline FrameStash stash_of(int64_t* scr, int64_t b, int64_t cap_alloc) {
  // cap_alloc is the ALLOCATED table capacity (pack_scratch_cap), not
  // scr[2]: the active capacity starts small and grows, but the stash
  // lives past the full allocation.  Region order keeps every 8-byte
  // field 8-aligned for any b (base is int64-aligned; 8b and 16b are
  // multiples of 8).
  FrameStash s;
  uint8_t* base = reinterpret_cast<uint8_t*>(scr + 3 + cap_alloc);
  s.h64 = reinterpret_cast<uint64_t*>(base);
  s.size = reinterpret_cast<int64_t*>(base + 8 * b);
  s.h32 = reinterpret_cast<uint32_t*>(base + 16 * b);
  s.alive = base + 20 * b;
  s.n = 0;
  s.nsz = 0;
  return s;
}

inline int64_t pack_stash_len64(int64_t b, int32_t with_alive,
                                int32_t with_hll, int32_t q_rows) {
  if (!with_alive && with_hll != 2 && q_rows <= 0) return 0;
  return (21 * b + 7) / 8;
}

// Compacted-pair emission region (with_alive == 2): slots u32[b] + flags
// u8[b] carved out of the caller scratch PAST the full (unconditional)
// stash, so Python locates it as kta_pack_scratch_len(b, 1, bits) int64
// elements in — independent of which stash sections the config uses.
inline int64_t pairs_off64(int64_t b, int32_t alive_bits) {
  return 3 + pack_scratch_cap(b, 1, alive_bits) +
         pack_stash_len64(b, 1, 2, 1);
}

inline int64_t pairs_len64(int64_t b) { return (5 * b + 7) / 8; }

inline void attach_scratch_pairs(PackRowLayout* r, int64_t* scratch) {
  if (r->with_alive != 2) return;
  uint8_t* pb =
      reinterpret_cast<uint8_t*>(scratch + pairs_off64(r->b, r->alive_bits));
  r->slot32 = pb;
  r->alive8 = pb + 4 * r->b;
}

// Grow the active dedupe table (doubling, bounded by the allocated max)
// once the load factor reaches 1/2, re-inserting the existing pairs from
// the row's slot section.  Capacity and rehashing change probe POSITIONS
// only — pair output order stays first-occurrence record order — so rows
// remain byte-identical to kta_dedupe_slots while a low-cardinality
// batch keeps its table cache-resident instead of paying the worst-case
// 2·batch_size table from the first record.
inline void dedupe_maybe_grow(const PackRowLayout& r, int64_t* scr,
                              int64_t cap_max) {
  int64_t cap = scr[2];
  if (cap >= cap_max || scr[1] * 2 < cap) return;
  while (cap < cap_max && scr[1] * 2 >= cap) cap <<= 1;
  int64_t* table = scr + 3;
  std::memset(table, 0, size_t(cap) * 8);
  const int64_t cap_mask = cap - 1;
  for (int64_t j = 0; j < scr[1]; ++j) {
    const uint32_t slot = load_at<uint32_t>(r.slot32, j);
    int64_t pos = int64_t(splitmix64(slot) & uint64_t(cap_mask));
    while (table[pos] != 0) pos = (pos + 1) & cap_mask;
    table[pos] = j + 1;
  }
  scr[2] = cap;
}

// Dedicated LWW dedupe pass: insert the stash's (slot, alive) pairs into
// the row's persistent open-addressing table — same algorithm (and same
// first-occurrence output order) as kta_dedupe_slots, but incremental
// across appends because the table lives in the caller scratch.
inline void dedupe_pass(const PackRowLayout& r, int64_t* scr,
                        const uint32_t* h32, const uint8_t* alive,
                        int64_t n) {
  const uint32_t mask =
      r.alive_bits == 32 ? 0xffffffffu : ((1u << r.alive_bits) - 1u);
  const int64_t cap_max = pack_scratch_cap(r.b, 1, r.alive_bits);
  int64_t* table = scr + 3;
  int64_t np = scr[1];
  for (int64_t j = 0; j < n; ++j) {
    scr[1] = np;
    dedupe_maybe_grow(r, scr, cap_max);
    const int64_t cap_mask = scr[2] - 1;
    const uint32_t slot = h32[j] & mask;
    int64_t pos = int64_t(splitmix64(slot) & uint64_t(cap_mask));
    for (;;) {
      const int64_t entry = table[pos];
      if (entry == 0) {
        table[pos] = np + 1;
        store_at<uint32_t>(r.slot32, np, slot);
        r.alive8[np] = alive[j];
        ++np;
        break;
      }
      if (load_at<uint32_t>(r.slot32, entry - 1) == slot) {
        r.alive8[entry - 1] = alive[j];  // later record wins
        break;
      }
      pos = (pos + 1) & cap_mask;
    }
  }
  scr[1] = np;
}

// Dedicated HLL register-table pass (mode 2) over the stash's h64 values.
inline void hll_table_pass(const PackRowLayout& r, int32_t dense_p,
                           const uint64_t* h64, int64_t n) {
  const int64_t row = r.hll_rows > 1 ? dense_p : 0;
  uint8_t* tbl = r.hll_a;
  for (int64_t j = 0; j < n; ++j) {
    const uint64_t h = splitmix64(h64[j]);
    const int64_t idx = (row << r.hll_p) | int64_t(h >> (64 - r.hll_p));
    const uint64_t rest = h << r.hll_p;
    const uint8_t rho =
        rest == 0 ? static_cast<uint8_t>(64 - r.hll_p + 1)
                  : static_cast<uint8_t>(__builtin_clzll(rest) + 1);
    if (rho > tbl[idx]) tbl[idx] = rho;
  }
}

// Wire v5: fold one single-partition frame's counter registers into the
// row's i64[P, 7] delta table — ONE 7-entry RMW per frame/append, the
// combiner's whole per-frame cost for the channels that used to ship as
// four per-record columns.  Channel order = results.COUNTER_CHANNELS.
inline void commit_counts(const PackRowLayout& r, int32_t dense_p,
                          int64_t total, int64_t tomb, int64_t knull,
                          int64_t ksum, int64_t vsum) {
  const int64_t base = int64_t(dense_p) * 7;
  const int64_t vals[7] = {total,         tomb,  total - tomb, knull,
                           total - knull, ksum,  vsum};
  for (int c = 0; c < 7; ++c)
    store_at<int64_t>(r.cnt64, base + c,
                      load_at<int64_t>(r.cnt64, base + c) + vals[c]);
}

// Wire v5 DDSketch pass: bucket the stashed message sizes through the
// shared integer edge table (ops/ddsketch.py::ddsketch_edges — binary
// search == numpy searchsorted side='left') into the row's per-row
// bucket-count table.  Runs after the frame parses, like every reduction.
inline void quant_pass(const PackRowLayout& r, int32_t dense_p,
                       const int64_t* sizes, int64_t n) {
  const int64_t nb = int64_t(r.q_nbuckets) + 2;
  const int64_t base = (r.q_rows > 1 ? int64_t(dense_p) : 0) * nb;
  for (int64_t j = 0; j < n; ++j) {
    const int64_t s = sizes[j];
    int64_t idx = 0;
    if (s != 0)
      idx = (std::lower_bound(r.q_edges, r.q_edges + r.q_nbuckets, s) -
             r.q_edges) + 1;
    store_at<int64_t>(r.q64, base + idx,
                      load_at<int64_t>(r.q64, base + idx) + 1);
  }
}

// One table RMW per frame/append instead of four per record.
inline void commit_extremes(const PackRowLayout& r, int32_t dense_p,
                            int64_t ts_min, int64_t ts_max, int64_t sz_min,
                            int64_t sz_max, bool any_ts, bool any_sz) {
  if (any_ts) {
    if (ts_min < load_at<int64_t>(r.tsmm, dense_p))
      store_at<int64_t>(r.tsmm, dense_p, ts_min);
    if (ts_max > load_at<int64_t>(r.tsmm, r.P + dense_p))
      store_at<int64_t>(r.tsmm, r.P + dense_p, ts_max);
  }
  if (any_sz) {
    if (sz_min < load_at<int64_t>(r.szmm, dense_p))
      store_at<int64_t>(r.szmm, dense_p, sz_min);
    if (sz_max > load_at<int64_t>(r.szmm, r.P + dense_p))
      store_at<int64_t>(r.szmm, r.P + dense_p, sz_max);
  }
}

// Rewind a failed frame's partial appends: reset the cursor and re-zero
// the per-record section spans it touched, so the row stays byte-
// identical to one that never saw the frame (the reductions were not
// committed — they only run after a frame parses completely).
inline void rewind_appends(const PackRowLayout& r, int64_t* scr,
                           int64_t cursor0) {
  const int64_t n = scr[0];
  if (n <= cursor0) return;
  const int64_t c = n - cursor0;
  if (!r.wire_v5) {
    // v5 has no per-record column sections — its counter/quantile
    // reductions only commit after the frame parses, so the cursor reset
    // below is the whole rewind for them.
    std::memset(r.p16 + 2 * cursor0, 0, size_t(2 * c));
    std::memset(r.kl16 + 2 * cursor0, 0, size_t(2 * c));
    std::memset(r.vl32 + 4 * cursor0, 0, size_t(4 * c));
    std::memset(r.fl8 + cursor0, 0, size_t(c));
  }
  if (r.with_hll == 1) {
    std::memset(r.hll_a + 2 * cursor0, 0, size_t(2 * c));
    std::memset(r.hll_b + cursor0, 0, size_t(c));
  } else if (r.with_hll == 3) {
    std::memset(r.hll_a + 4 * cursor0, 0, size_t(4 * c));
    std::memset(r.hll_b + cursor0, 0, size_t(c));
  }
  scr[0] = cursor0;
}

// Store-free validation of one v2 frame's records: every record must parse
// inside its bounds, and every record IN the acceptance window must fit
// the wire-v4 ranges, so the append pass can never fail mid-frame.
// (Out-of-window records are never packed — the chained path filters them
// before pack_batch ever sees them, so a range violation there must not
// abort the fused scan either.)  Returns 0 ok, 1 malformed (caller stops
// the walk at this frame for the Python chain to classify), 2 pack-range
// violation (detail filled; the whole call errors like the numpy packer
// would).
inline int validate_frame_records(const uint8_t* payload, int64_t plen,
                                  int32_t nrec, int32_t vcap,
                                  int64_t base_offset, int64_t min_off,
                                  int64_t max_off, int64_t* detail) {
  int64_t pos = 0;
  for (int32_t i = 0; i < nrec; ++i) {
    int64_t length;
    if (!read_zigzag(payload, plen, pos, length)) return 1;
    if (length < 0 || length > plen - pos) return 1;
    const int64_t rec_end = pos + length;
    if (pos >= rec_end) return 1;
    ++pos;  // attributes
    int64_t ts_delta, off_delta, klen, vlen;
    if (!read_zigzag(payload, rec_end, pos, ts_delta)) return 1;
    if (!read_zigzag(payload, rec_end, pos, off_delta)) return 1;
    if (!read_zigzag(payload, rec_end, pos, klen)) return 1;
    if (klen >= 0) {
      if (klen > rec_end - pos || klen > 0x7fffffff) return 1;
      pos += klen;
    }
    if (!read_zigzag(payload, rec_end, pos, vlen)) return 1;
    if (vlen >= 0) {
      if (vlen > rec_end - pos || vlen > 0x7fffffff) return 1;
      pos += vlen;
    }
    const int64_t off = base_offset + off_delta;
    if (off >= min_off && off < max_off) {
      if (klen > 0xffff) {
        detail[0] = 0;
        detail[1] = klen;
        return 2;
      }
      if (vlen > vcap) {
        detail[0] = 1;
        detail[1] = vlen;
        return 2;
      }
    }
    int64_t nheaders;
    if (!read_zigzag(payload, rec_end, pos, nheaders)) return 1;
    if (nheaders < 0) return 1;
    for (int64_t h = 0; h < nheaders; ++h) {
      int64_t hk, hv;
      if (!read_zigzag(payload, rec_end, pos, hk)) return 1;
      if (hk < 0 || hk > rec_end - pos) return 1;
      pos += hk;
      if (!read_zigzag(payload, rec_end, pos, hv)) return 1;
      if (hv > 0) {
        if (hv > rec_end - pos) return 1;
        pos += hv;
      }
    }
    pos = rec_end;
  }
  return 0;
}

// Wire-v5 full-batch packer (the chained path's combiner form): one
// sequential pass folds the SoA columns into the per-partition tables —
// counter deltas, ts/size extremes, DDSketch buckets — with the same
// validation kta_pack_batch's v4 branch applies.  Multi-partition batches
// are fine here (unlike the fused single-partition appends): every table
// indexes by the record's own partition.
int64_t pack_batch_v5(
    const int32_t* partition, const int32_t* key_len, const int32_t* value_len,
    const uint8_t* key_null, const uint8_t* value_null, const int64_t* ts_s,
    const uint32_t* h32, const uint64_t* h64,
    int64_t n_valid, int64_t batch_size, int32_t num_partitions,
    int32_t with_alive, int32_t alive_bits, int32_t with_hll, int32_t hll_p,
    int32_t hll_rows, int32_t value_len_cap, int32_t q_rows,
    int32_t q_nbuckets, const int64_t* q_edges, uint8_t* out,
    int64_t out_cap) {
  PackRowLayout r;
  if (!pack_row_layout(out, out_cap, batch_size, num_partitions, with_alive,
                       alive_bits, with_hll, hll_p, hll_rows, value_len_cap,
                       1, q_rows, q_nbuckets, q_edges, &r))
    return -1;
  const int64_t P = num_partitions;
  std::memset(out, 0, r.need);

  std::vector<int64_t> cnt(size_t(P) * 7, 0);
  std::vector<int64_t> mm(2 * P), sz(2 * P);
  for (int64_t p = 0; p < P; ++p) {
    mm[p] = INT64_MAX;
    mm[P + p] = INT64_MIN;
    sz[p] = INT64_MAX;
    sz[P + p] = 0;
  }
  const int64_t nb = int64_t(q_nbuckets) + 2;
  std::vector<int64_t> qt(
      q_rows > 0 ? size_t(q_rows) * size_t(nb) : size_t(0), 0);
  for (int64_t i = 0; i < n_valid; ++i) {
    const int32_t p = partition[i];
    if (p < 0 || p > 0x7fff || p >= num_partitions ||
        key_len[i] < 0 || key_len[i] > 0xffff ||
        value_len[i] < 0 || value_len[i] > r.vcap)
      return -1;
    const bool kn = !key_null[i];
    const bool vn = !value_null[i];
    int64_t* row = cnt.data() + int64_t(p) * 7;
    row[0] += 1;
    row[1] += vn ? 0 : 1;
    row[2] += vn ? 1 : 0;
    row[3] += kn ? 0 : 1;
    row[4] += kn ? 1 : 0;
    if (kn) row[5] += key_len[i];
    if (vn) row[6] += value_len[i];
    const int64_t t = ts_s[i];
    if (t < mm[p]) mm[p] = t;
    if (t > mm[P + p]) mm[P + p] = t;
    if (vn) {
      const int64_t size =
          (kn ? int64_t(key_len[i]) : 0) + int64_t(value_len[i]);
      if (size < sz[p]) sz[p] = size;
      if (size > sz[P + p]) sz[P + p] = size;
      if (q_rows > 0) {
        int64_t idx = 0;
        if (size != 0)
          idx = (std::lower_bound(q_edges, q_edges + q_nbuckets, size) -
                 q_edges) + 1;
        qt[size_t((q_rows > 1 ? int64_t(p) : 0) * nb + idx)] += 1;
      }
    }
  }
  std::memcpy(r.cnt64, cnt.data(), size_t(P) * 7 * 8);
  std::memcpy(r.tsmm, mm.data(), size_t(2 * P) * 8);
  std::memcpy(r.szmm, sz.data(), size_t(2 * P) * 8);
  if (q_rows > 0) std::memcpy(r.q64, qt.data(), qt.size() * 8);

  int64_t n_pairs = 0;
  if (with_alive && n_valid > 0) {
    // Same pre-reduction as the v4 branch: LWW dedupe into aligned
    // temporaries, then memcpy into the (possibly unaligned) section.
    std::vector<uint8_t> active(n_valid), alive(n_valid);
    for (int64_t i = 0; i < n_valid; ++i) {
      active[i] = key_null[i] ? 0 : 1;
      alive[i] = value_null[i] ? 0 : 1;
    }
    std::vector<uint32_t> slots(n_valid);
    std::vector<uint8_t> flags(n_valid);
    n_pairs = kta_dedupe_slots(h32, active.data(), alive.data(), n_valid,
                               alive_bits, slots.data(), flags.data());
    if (n_pairs < 0) return -1;
    std::memcpy(r.slot32, slots.data(), size_t(n_pairs) * 4);
    std::memcpy(r.alive8, flags.data(), size_t(n_pairs));
  }
  if (with_hll == 1 || with_hll == 3) {
    for (int64_t i = 0; i < n_valid; ++i) {
      if (key_null[i]) {
        if (with_hll == 1)
          store_at<uint16_t>(r.hll_a, i, 0);
        else
          store_at<uint32_t>(r.hll_a, i, 0);
        r.hll_b[i] = 0;
        continue;
      }
      const uint64_t h = splitmix64(h64[i]);
      const uint32_t bucket = static_cast<uint32_t>(h >> (64 - hll_p));
      if (with_hll == 1)
        store_at<uint16_t>(r.hll_a, i, static_cast<uint16_t>(bucket));
      else
        store_at<uint32_t>(
            r.hll_a, i,
            (static_cast<uint32_t>(partition[i]) << hll_p) | bucket);
      const uint64_t rest = h << hll_p;
      r.hll_b[i] = rest == 0
                       ? static_cast<uint8_t>(64 - hll_p + 1)
                       : static_cast<uint8_t>(__builtin_clzll(rest) + 1);
    }
  } else if (with_hll == 2) {
    uint8_t* tbl = r.hll_a;
    const bool per_row = hll_rows > 1;
    for (int64_t i = 0; i < n_valid; ++i) {
      if (key_null[i]) continue;
      const uint64_t h = splitmix64(h64[i]);
      const int64_t row = per_row ? partition[i] : 0;
      const int64_t idx = (row << hll_p) | int64_t(h >> (64 - hll_p));
      const uint64_t rest = h << hll_p;
      const uint8_t rho =
          rest == 0 ? static_cast<uint8_t>(64 - hll_p + 1)
                    : static_cast<uint8_t>(__builtin_clzll(rest) + 1);
      if (rho > tbl[idx]) tbl[idx] = rho;
    }
  }

  const int32_t hv = static_cast<int32_t>(n_valid);
  const int32_t hp = static_cast<int32_t>(n_pairs);
  std::memcpy(out, &hv, 4);
  std::memcpy(out + 4, &hp, 4);
  return r.need;
}

}  // namespace

extern "C" {

// Scratch length (int64 elements) a pack row needs: counters + the
// persistent dedupe table + the per-frame reduction stash.
int64_t kta_pack_scratch_len(int64_t batch_size, int32_t with_alive,
                             int32_t alive_bits) {
  if (batch_size < 0) return -1;
  // The stash region is sized unconditionally (it also serves HLL table
  // mode with alive off, and wire v5's size stash) — a few MB at worst,
  // allocated once per sink.  with_alive == 2 (pair compaction) appends
  // the pair emission region; its offset is exactly the with_alive == 1
  // return value, which is how the Python side locates it.
  int64_t n = 3 + pack_scratch_cap(batch_size, with_alive, alive_bits) +
              pack_stash_len64(batch_size, 1, 2, 1);
  if (with_alive == 2) n += pairs_len64(batch_size);
  return n;
}

// Initialize one wire row (v4 or v5) for incremental appends: zero the
// buffer, identity-fill the extreme tables, reset the scratch.  An
// initialized, never-appended row is byte-identical to a packed EMPTY
// batch (the superbatch identity pad), so partial-row padding is just
// init — under v5 the zeroed counter/quantile tables ARE the fold
// identity.  Returns the row's total bytes (== packing.packed_nbytes)
// or -1.
int64_t kta_pack_row_init(uint8_t* out, int64_t out_cap, int64_t* scratch,
                          int64_t scratch_len, int64_t batch_size,
                          int32_t num_partitions, int32_t with_alive,
                          int32_t alive_bits, int32_t with_hll,
                          int32_t hll_p, int32_t hll_rows,
                          int32_t value_len_cap, int32_t wire_v5,
                          int32_t q_rows, int32_t q_nbuckets,
                          const int64_t* q_edges) {
  PackRowLayout r;
  if (!scratch ||
      !pack_row_layout(out, out_cap, batch_size, num_partitions, with_alive,
                       alive_bits, with_hll, hll_p, hll_rows, value_len_cap,
                       wire_v5, q_rows, q_nbuckets, q_edges, &r))
    return -1;
  const int64_t cap = pack_scratch_cap(batch_size, with_alive, alive_bits);
  if (scratch_len < 3 + cap + pack_stash_len64(batch_size, with_alive,
                                               with_hll, q_rows))
    return -1;
  if (with_alive == 2 &&
      scratch_len < pairs_off64(batch_size, alive_bits) +
                        pairs_len64(batch_size))
    return -1;
  attach_scratch_pairs(&r, scratch);
  std::memset(out, 0, r.need);
  for (int64_t p = 0; p < r.P; ++p) {
    store_at<int64_t>(r.tsmm, p, INT64_MAX);
    store_at<int64_t>(r.tsmm, r.P + p, INT64_MIN);
    store_at<int64_t>(r.szmm, p, INT64_MAX);
    store_at<int64_t>(r.szmm, r.P + p, 0);
  }
  scratch[0] = 0;
  scratch[1] = 0;
  // Active table capacity starts small and grows with distinct slots
  // (dedupe_maybe_grow) — low-cardinality rows keep it cache-resident.
  scratch[2] = cap < 4096 ? cap : 4096;
  std::memset(scratch + 3, 0, size_t(scratch[2]) * 8);
  return r.need;
}

// Compacted alive-pair MASK build (packing.alive_table_mode == 2): apply
// the raw (slot, flag) pair stream — concatenated per-dispatch batches,
// STREAM ORDER, duplicates allowed — last-writer-wins straight into
// set/clear word masks: a set pair turns its bit on in set and off in
// clear, a tombstone pair the reverse, so the masks ARE the compacted
// LWW monoid value and the device merge is one elementwise
// (words & ~clear) | set pass (no scatter).  Both masks are zeroed here.
// Returns the number of DISTINCT touched slots (the emitted-pairs
// telemetry), or -1 on bad arguments.
int64_t kta_pairs_to_masks(const uint32_t* slots, const uint8_t* flags,
                           int64_t n, int32_t bits, uint32_t* set_out,
                           uint32_t* clear_out) {
  if (!set_out || !clear_out || n < 0 || bits < 1 || bits > 32) return -1;
  if (n > 0 && (!slots || !flags)) return -1;
  const int64_t W = int64_t(1) << (bits > 5 ? bits - 5 : 0);
  std::memset(set_out, 0, size_t(W) * 4);
  std::memset(clear_out, 0, size_t(W) * 4);
  int64_t touched = 0;
  for (int64_t i = 0; i < n; ++i) {
    const uint32_t s = slots[i];
    const int64_t w = s >> 5;
    const uint32_t bit = 1u << (s & 31);
    if (w >= W) return -1;  // slot past the declared bitmap width
    if (!((set_out[w] | clear_out[w]) & bit)) ++touched;
    if (flags[i]) {
      set_out[w] |= bit;
      clear_out[w] &= ~bit;
    } else {
      clear_out[w] |= bit;
      set_out[w] &= ~bit;
    }
  }
  return touched;
}

// Fused decode→pack over a record set's native-decodable prefix, starting
// at byte `start_pos` (0, or a previous call's resume position).  Records
// with min_off <= offset < max_off append to the row; the walk stops at
// the first non-native frame (compressed / legacy / truncated / malformed
// — Python chain takes over from `consumed`) or when the row fills
// mid-frame (st[5] = 1; resume with start_pos = st[0], skip = st[4] after
// rotating rows).  Frame atomicity: a frame that might span the row
// boundary is pre-validated store-free; any other frame that turns out
// malformed mid-parse has its partial appends rewound (reductions only
// commit after a frame parses completely) — either way a frame appends
// all of its in-range records or none.  st is int64[8]:
//   in:  st[4] = records of the frame at start_pos already processed
//   out: st[0] consumed (bytes of fully-processed frames)
//        st[1] covered_end (max base+last_offset_delta+1; -1 none)
//        st[2] last appended offset (-1 none this call)
//        st[3] last appended ts_s
//        st[4] resume skip count   st[5] row-full flag
//        st[6]/st[7] pack-range error detail (rc == -2)
// Returns records appended this call, -1 bad args, -2 pack-range.
int64_t kta_decode_pack_record_set(
    const uint8_t* buf, int64_t len, int32_t verify_crc, int64_t start_pos,
    int64_t min_off, int64_t max_off, int32_t dense_partition,
    int64_t batch_size, int32_t num_partitions, int32_t with_alive,
    int32_t alive_bits, int32_t with_hll, int32_t hll_p, int32_t hll_rows,
    int32_t value_len_cap, int32_t wire_v5, int32_t q_rows,
    int32_t q_nbuckets, const int64_t* q_edges, uint8_t* out,
    int64_t out_cap, int64_t* scratch, int64_t* st) {
  PackRowLayout r;
  if (!buf || len < 0 || !st || !scratch || start_pos < 0 ||
      start_pos > len || dense_partition < 0 ||
      dense_partition >= num_partitions ||
      !pack_row_layout(out, out_cap, batch_size, num_partitions, with_alive,
                       alive_bits, with_hll, hll_p, hll_rows, value_len_cap,
                       wire_v5, q_rows, q_nbuckets, q_edges, &r))
    return -1;
  attach_scratch_pairs(&r, scratch);
  const bool need_stash = with_alive || with_hll == 2;
  FrameStash stash = stash_of(
      scratch, r.b, pack_scratch_cap(r.b, with_alive, alive_bits));
  int64_t skip = st[4];
  int64_t pos = start_pos, covered = -1, appended = 0;
  int64_t last_off = -1, last_ts = 0;
  st[5] = 0;
  FrameHeader fh;
  while (native_frame_at(buf, len, pos, verify_crc, &fh)) {
    if (fh.control) {
      if (fh.covered_end > covered) covered = fh.covered_end;
      pos = fh.end;
      skip = 0;
      continue;
    }
    const uint8_t* payload = buf + fh.payload_pos;
    const int64_t plen = fh.end - fh.payload_pos;
    const int64_t space = r.b - scratch[0];
    if (fh.num_records - skip > space) {
      // This frame may outlive the current row: pre-validate it store-
      // free so a malformation found AFTER the row rotates can never
      // leave a committed partial frame behind.  Boundary-only cost —
      // at most one frame per row takes this double walk.
      const int v = validate_frame_records(payload, plen, fh.num_records,
                                           r.vcap, fh.base_offset, min_off,
                                           max_off, st + 6);
      if (v == 2) return -2;
      if (v != 0) break;
    }
    // Decode pass: tight per-record parse + section stores at the
    // cursor, reduction inputs stashed compactly; dedupe/HLL/extreme
    // commits run as dedicated passes after the frame parses.
    const int64_t cursor0 = scratch[0];
    stash.n = 0;
    stash.nsz = 0;
    int64_t ts_min = INT64_MAX, ts_max = INT64_MIN;
    int64_t sz_min = INT64_MAX, sz_max = 0;
    // Wire v5: per-frame counter registers (single-partition frames fold
    // to ONE 7-entry table commit — commit_counts).
    int64_t f_tomb = 0, f_knull = 0, f_ksum = 0, f_vsum = 0;
    int64_t f_last_off = -1, f_last_ts = 0, f_appended = 0;
    int64_t rpos = 0;
    int32_t i = 0;
    bool full = false, malformed = false;
    for (; i < fh.num_records; ++i) {
      int64_t length = 0, ts_delta = 0, off_delta = 0, klen = 0, vlen = 0;
      if (!read_zigzag(payload, plen, rpos, length) || length < 0 ||
          length > plen - rpos) {
        malformed = true;
        break;
      }
      const int64_t rec_end = rpos + length;
      if (rpos >= rec_end) {
        malformed = true;
        break;
      }
      ++rpos;  // attributes
      if (!read_zigzag(payload, rec_end, rpos, ts_delta) ||
          !read_zigzag(payload, rec_end, rpos, off_delta) ||
          !read_zigzag(payload, rec_end, rpos, klen)) {
        malformed = true;
        break;
      }
      const uint8_t* kp = payload + rpos;
      if (klen >= 0) {
        if (klen > rec_end - rpos || klen > 0x7fffffff) {
          malformed = true;
          break;
        }
        rpos += klen;
      }
      if (!read_zigzag(payload, rec_end, rpos, vlen)) {
        malformed = true;
        break;
      }
      if (vlen >= 0) {
        if (vlen > rec_end - rpos || vlen > 0x7fffffff) {
          malformed = true;
          break;
        }
        rpos += vlen;
      }
      int64_t nheaders = 0;
      if (!read_zigzag(payload, rec_end, rpos, nheaders) || nheaders < 0) {
        malformed = true;
        break;
      }
      for (int64_t h = 0; h < nheaders; ++h) {
        int64_t hk = 0, hv = 0;
        if (!read_zigzag(payload, rec_end, rpos, hk) || hk < 0 ||
            hk > rec_end - rpos) {
          malformed = true;
          break;
        }
        rpos += hk;
        if (!read_zigzag(payload, rec_end, rpos, hv)) {
          malformed = true;
          break;
        }
        if (hv > 0) {
          if (hv > rec_end - rpos) {
            malformed = true;
            break;
          }
          rpos += hv;
        }
      }
      if (malformed) break;
      rpos = rec_end;  // tolerate unknown trailing record fields
      if (i < skip) continue;  // already appended into a previous row
      const int64_t off = fh.base_offset + off_delta;
      if (off < min_off || off >= max_off) continue;
      // Pack-range checks only for records the scan ACCEPTS — the
      // chained path filters out-of-window records before pack_batch
      // ever sees them, so an oversized record past the watermark must
      // not abort the fused scan either.
      if (klen > 0xffff) {
        rewind_appends(r, scratch, cursor0);
        st[6] = 0;
        st[7] = klen;
        return -2;
      }
      if (vlen > r.vcap) {
        rewind_appends(r, scratch, cursor0);
        st[6] = 1;
        st[7] = vlen;
        return -2;
      }
      if (scratch[0] >= r.b) {
        full = true;
        break;
      }
      const bool key_null = klen < 0;
      const bool value_null = vlen < 0;
      const int64_t n = scratch[0];
      if (r.wire_v5) {
        // Combiner rows: no per-record columns — accumulate the frame's
        // counter registers instead (committed once per frame below).
        if (value_null) ++f_tomb;
        if (key_null) ++f_knull;
        if (!key_null) f_ksum += klen;
        if (!value_null) f_vsum += vlen;
      } else {
        store_at<int16_t>(r.p16, n, static_cast<int16_t>(dense_partition));
        store_at<uint16_t>(r.kl16, n,
                           static_cast<uint16_t>(key_null ? 0 : klen));
        store_at<uint32_t>(r.vl32, n,
                           static_cast<uint32_t>(value_null ? 0 : vlen));
        r.fl8[n] = (key_null ? 1 : 0) | (value_null ? 2 : 0);
      }
      const int64_t ts_ms = fh.first_ts + ts_delta;
      const int64_t ts_s = ts_ms < 0 ? 0 : ts_ms / 1000;
      if (ts_s < ts_min) ts_min = ts_s;
      if (ts_s > ts_max) ts_max = ts_s;
      if (!value_null) {
        const int64_t size = (key_null ? 0 : klen) + vlen;
        if (size < sz_min) sz_min = size;
        if (size > sz_max) sz_max = size;
        if (r.q64) stash.size[stash.nsz++] = size;
      }
      uint32_t h32 = 0;
      uint64_t h64 = 0;
      if (!key_null) {
        fnv1a_both(kp, klen, &h32, &h64);
        if (need_stash) {
          stash.h32[stash.n] = h32;
          stash.h64[stash.n] = h64;
          stash.alive[stash.n] = value_null ? 0 : 1;
          ++stash.n;
        }
      }
      if (r.with_hll == 1 || r.with_hll == 3) {
        if (key_null) {
          if (r.with_hll == 1)
            store_at<uint16_t>(r.hll_a, n, 0);
          else
            store_at<uint32_t>(r.hll_a, n, 0);
          r.hll_b[n] = 0;
        } else {
          const uint64_t h = splitmix64(h64);
          const uint32_t bucket =
              static_cast<uint32_t>(h >> (64 - r.hll_p));
          if (r.with_hll == 1)
            store_at<uint16_t>(r.hll_a, n, static_cast<uint16_t>(bucket));
          else
            // v5 flat pairs: the register row rides inside the index.
            store_at<uint32_t>(
                r.hll_a, n,
                (static_cast<uint32_t>(dense_partition) << r.hll_p) | bucket);
          const uint64_t rest = h << r.hll_p;
          r.hll_b[n] =
              rest == 0 ? static_cast<uint8_t>(64 - r.hll_p + 1)
                        : static_cast<uint8_t>(__builtin_clzll(rest) + 1);
        }
      }
      scratch[0] = n + 1;
      ++f_appended;
      f_last_off = off;
      f_last_ts = ts_s;
    }
    if (malformed) {
      // A spanning frame was pre-validated, so this is a non-spanning
      // frame's first touch: rewind its partial appends and hand the
      // frame to the Python chain for the precise classification.
      rewind_appends(r, scratch, cursor0);
      break;
    }
    // Commit the frame's (possibly partial, on row-full) reductions —
    // these records stay in this row either way.
    if (f_appended) {
      commit_extremes(r, dense_partition, ts_min, ts_max, sz_min, sz_max,
                      true, sz_min != INT64_MAX || sz_max != 0);
      if (with_alive) dedupe_pass(r, scratch, stash.h32, stash.alive,
                                  stash.n);
      if (r.with_hll == 2) hll_table_pass(r, dense_partition, stash.h64,
                                          stash.n);
      if (r.wire_v5) {
        commit_counts(r, dense_partition, f_appended, f_tomb, f_knull,
                      f_ksum, f_vsum);
        if (r.q64) quant_pass(r, dense_partition, stash.size, stash.nsz);
      }
      appended += f_appended;
      last_off = f_last_off;
      last_ts = f_last_ts;
    }
    if (full) {
      st[4] = i;  // resume: skip the records already processed
      st[5] = 1;
      break;
    }
    if (fh.covered_end > covered) covered = fh.covered_end;
    pos = fh.end;
    skip = 0;
  }
  st[0] = pos;
  st[1] = covered;
  st[2] = last_off;
  st[3] = last_ts;
  if (!st[5]) st[4] = 0;
  // Live header: the row is a valid packed batch after every call.
  // Under pair compaction the row has no pair sections — its header says
  // n_pairs 0 (the pairs ride the dispatch-level merged table instead).
  const int32_t hv = static_cast<int32_t>(scratch[0]);
  const int32_t hp =
      with_alive == 2 ? 0 : static_cast<int32_t>(scratch[1]);
  std::memcpy(out, &hv, 4);
  std::memcpy(out + 4, &hp, 4);
  return appended;
}

// Column-append fallback half of the fused path: records [start, n) — n
// is the exclusive END INDEX into the columns, not a count —
// of already-decoded SoA columns (a salvaged frame, a segment chunk's
// memmap views) append into the row through the SAME batched passes, so
// fused rows mixing decoded and fallback records stay byte-identical to
// the chained pack.  All records belong to ONE (dense) partition.
// ts semantics: ts_mode = 0 takes ts[] as seconds verbatim; 1 floor-
// divides milliseconds by 1000 (the segment reader's rule); 2 clamps
// negatives to 0 then divides (the wire decoder's rule).
// Returns records appended (stops at row capacity), -1 bad args, -2
// pack-range violation (detail[0] field / detail[1] value).
int64_t kta_pack_append_columns(
    uint8_t* out, int64_t out_cap, int64_t* scratch, int32_t dense_partition,
    const int32_t* key_len, const int32_t* value_len, const uint8_t* key_null,
    const uint8_t* value_null, const int64_t* ts, int32_t ts_mode,
    const uint32_t* h32, const uint64_t* h64, int64_t start, int64_t n,
    int64_t batch_size, int32_t num_partitions, int32_t with_alive,
    int32_t alive_bits, int32_t with_hll, int32_t hll_p, int32_t hll_rows,
    int32_t value_len_cap, int32_t wire_v5, int32_t q_rows,
    int32_t q_nbuckets, const int64_t* q_edges, int64_t* detail) {
  PackRowLayout r;
  if (!key_len || !value_len || !key_null || !value_null || !ts || !h32 ||
      !h64 || !scratch || !detail || start < 0 || n < 0 || start > n ||
      dense_partition < 0 || dense_partition >= num_partitions ||
      dense_partition > 0x7fff || ts_mode < 0 || ts_mode > 2 ||
      !pack_row_layout(out, out_cap, batch_size, num_partitions, with_alive,
                       alive_bits, with_hll, hll_p, hll_rows, value_len_cap,
                       wire_v5, q_rows, q_nbuckets, q_edges, &r))
    return -1;
  attach_scratch_pairs(&r, scratch);
  int64_t take = n - start;
  const int64_t space = r.b - scratch[0];
  if (space < 0) return -1;
  if (take > space) take = space;
  const int64_t lo = start, hi = start + take;
  // Validate before any append — same atomicity rule as the decode path,
  // and the same UNCONDITIONAL column checks as kta_pack_batch (range
  // violations reject even on null-key/tombstone records).
  for (int64_t i = lo; i < hi; ++i) {
    if (key_len[i] < 0 || key_len[i] > 0xffff) {
      detail[0] = 0;
      detail[1] = key_len[i];
      return -2;
    }
    if (value_len[i] < 0 || value_len[i] > r.vcap) {
      detail[0] = 1;
      detail[1] = value_len[i];
      return -2;
    }
  }
  const int64_t c0 = scratch[0];
  if (r.wire_v5) {
    // Combiner rows: fold the columns straight into the frame registers
    // (one commit_counts below) — no per-record column sections exist.
    int64_t f_tomb = 0, f_knull = 0, f_ksum = 0, f_vsum = 0;
    for (int64_t i = lo; i < hi; ++i) {
      if (value_null[i]) ++f_tomb;
      if (key_null[i]) ++f_knull;
      if (!key_null[i]) f_ksum += key_len[i];
      if (!value_null[i]) f_vsum += value_len[i];
    }
    if (take)
      commit_counts(r, dense_partition, take, f_tomb, f_knull, f_ksum,
                    f_vsum);
  } else {
    // Columnar section stores (klen/vlen stored VERBATIM, like
    // kta_pack_batch — sources write 0 for null keys/tombstones but the
    // layout carries whatever the column said).
    for (int64_t i = lo; i < hi; ++i)
      store_at<int16_t>(r.p16, c0 + (i - lo),
                        static_cast<int16_t>(dense_partition));
    for (int64_t i = lo; i < hi; ++i)
      store_at<uint16_t>(r.kl16, c0 + (i - lo),
                         static_cast<uint16_t>(key_len[i]));
    for (int64_t i = lo; i < hi; ++i)
      store_at<uint32_t>(r.vl32, c0 + (i - lo),
                         static_cast<uint32_t>(value_len[i]));
    for (int64_t i = lo; i < hi; ++i)
      r.fl8[c0 + (i - lo)] =
          (key_null[i] ? 1 : 0) | (value_null[i] ? 2 : 0);
  }
  // Extremes: scalar reduction, ONE table RMW.  The wire-v5 quantile
  // pass stashes the same tombstone-excluded sizes this loop derives.
  FrameStash qstash = stash_of(
      scratch, r.b, pack_scratch_cap(r.b, with_alive, alive_bits));
  int64_t ts_min = INT64_MAX, ts_max = INT64_MIN;
  int64_t sz_min = INT64_MAX, sz_max = 0;
  for (int64_t i = lo; i < hi; ++i) {
    int64_t ts_s = ts[i];
    if (ts_mode == 1)
      ts_s = ts_s >= 0 ? ts_s / 1000 : -((-ts_s + 999) / 1000);
    else if (ts_mode == 2)
      ts_s = ts_s < 0 ? 0 : ts_s / 1000;
    if (ts_s < ts_min) ts_min = ts_s;
    if (ts_s > ts_max) ts_max = ts_s;
    if (!value_null[i]) {
      const int64_t size =
          (key_null[i] ? 0 : int64_t(key_len[i])) + int64_t(value_len[i]);
      if (size < sz_min) sz_min = size;
      if (size > sz_max) sz_max = size;
      if (r.q64) qstash.size[qstash.nsz++] = size;
    }
  }
  if (take)
    commit_extremes(r, dense_partition, ts_min, ts_max, sz_min, sz_max,
                    true, sz_min != INT64_MAX || sz_max != 0);
  if (take && r.q64)
    quant_pass(r, dense_partition, qstash.size, qstash.nsz);
  // Dedupe + HLL as dedicated passes straight off the input columns.
  if (with_alive) {
    FrameStash stash = stash_of(
        scratch, r.b, pack_scratch_cap(r.b, with_alive, alive_bits));
    for (int64_t i = lo; i < hi; ++i) {
      if (key_null[i]) continue;
      stash.h32[stash.n] = h32[i];
      stash.alive[stash.n] = value_null[i] ? 0 : 1;
      ++stash.n;
    }
    dedupe_pass(r, scratch, stash.h32, stash.alive, stash.n);
  }
  if (r.with_hll == 1 || r.with_hll == 3) {
    for (int64_t i = lo; i < hi; ++i) {
      const int64_t pos = c0 + (i - lo);
      if (key_null[i]) {
        if (r.with_hll == 1)
          store_at<uint16_t>(r.hll_a, pos, 0);
        else
          store_at<uint32_t>(r.hll_a, pos, 0);
        r.hll_b[pos] = 0;
      } else {
        const uint64_t h = splitmix64(h64[i]);
        const uint32_t bucket = static_cast<uint32_t>(h >> (64 - r.hll_p));
        if (r.with_hll == 1)
          store_at<uint16_t>(r.hll_a, pos, static_cast<uint16_t>(bucket));
        else
          store_at<uint32_t>(
              r.hll_a, pos,
              (static_cast<uint32_t>(dense_partition) << r.hll_p) | bucket);
        const uint64_t rest = h << r.hll_p;
        r.hll_b[pos] =
            rest == 0 ? static_cast<uint8_t>(64 - r.hll_p + 1)
                      : static_cast<uint8_t>(__builtin_clzll(rest) + 1);
      }
    }
  } else if (r.with_hll == 2) {
    FrameStash stash = stash_of(
        scratch, r.b, pack_scratch_cap(r.b, with_alive, alive_bits));
    for (int64_t i = lo; i < hi; ++i) {
      if (key_null[i]) continue;
      stash.h64[stash.n] = h64[i];
      ++stash.n;
    }
    hll_table_pass(r, dense_partition, stash.h64, stash.n);
  }
  scratch[0] = c0 + take;
  const int32_t hv = static_cast<int32_t>(scratch[0]);
  const int32_t hp =
      with_alive == 2 ? 0 : static_cast<int32_t>(scratch[1]);
  std::memcpy(out, &hv, 4);
  std::memcpy(out + 4, &hp, 4);
  return take;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Decompressors for Kafka record batches (kafka_codec.py): snappy raw blocks
// (plus the xerial chunked framing Kafka's Java client emits) and LZ4 frames.
// Python has neither in its stdlib; the shim supplies them so the wire client
// covers the common broker compression codecs without extra dependencies.

namespace {

// Raw snappy block decode (format: preamble varint = uncompressed length,
// then literal/copy tagged elements).  Returns bytes written or -1.
int64_t snappy_raw(const uint8_t* in, int64_t in_len, uint8_t* out,
                   int64_t out_cap) {
  int64_t ip = 0;
  // uncompressed length: LITTLE-endian base-128 varint (not zigzag)
  uint64_t ulen = 0;
  int shift = 0;
  while (ip < in_len) {
    uint8_t b = in[ip++];
    ulen |= static_cast<uint64_t>(b & 0x7f) << shift;
    if (!(b & 0x80)) break;
    shift += 7;
    if (shift > 35) return -1;
  }
  if (static_cast<int64_t>(ulen) > out_cap) return -1;
  int64_t op = 0;
  while (ip < in_len) {
    const uint8_t tag = in[ip++];
    const int type = tag & 3;
    if (type == 0) {  // literal
      int64_t len = (tag >> 2) + 1;
      if (len > 60) {
        const int extra = static_cast<int>(len) - 60;
        if (ip + extra > in_len) return -1;
        len = 0;
        for (int i = 0; i < extra; ++i)
          len |= static_cast<int64_t>(in[ip + i]) << (8 * i);
        len += 1;
        ip += extra;
      }
      if (ip + len > in_len || op + len > out_cap) return -1;
      std::memcpy(out + op, in + ip, len);
      ip += len;
      op += len;
    } else {  // copy
      int64_t len = 0, offset = 0;
      if (type == 1) {
        if (ip >= in_len) return -1;
        len = ((tag >> 2) & 7) + 4;
        offset = (static_cast<int64_t>(tag >> 5) << 8) | in[ip++];
      } else if (type == 2) {
        if (ip + 2 > in_len) return -1;
        len = (tag >> 2) + 1;
        offset = in[ip] | (static_cast<int64_t>(in[ip + 1]) << 8);
        ip += 2;
      } else {
        if (ip + 4 > in_len) return -1;
        len = (tag >> 2) + 1;
        offset = 0;
        for (int i = 0; i < 4; ++i)
          offset |= static_cast<int64_t>(in[ip + i]) << (8 * i);
        ip += 4;
      }
      if (offset <= 0 || offset > op || op + len > out_cap) return -1;
      // byte-by-byte: copies may overlap their own output (RLE)
      for (int64_t i = 0; i < len; ++i, ++op) out[op] = out[op - offset];
    }
  }
  return op == static_cast<int64_t>(ulen) ? op : -1;
}

inline uint32_t read_be32(const uint8_t* p) {
  return (static_cast<uint32_t>(p[0]) << 24) | (static_cast<uint32_t>(p[1]) << 16) |
         (static_cast<uint32_t>(p[2]) << 8) | p[3];
}

inline uint32_t read_le32(const uint8_t* p) {
  return p[0] | (static_cast<uint32_t>(p[1]) << 8) |
         (static_cast<uint32_t>(p[2]) << 16) | (static_cast<uint32_t>(p[3]) << 24);
}

// LZ4 block decode (literals + matches); returns bytes written or -1.
int64_t lz4_block(const uint8_t* in, int64_t in_len, uint8_t* out,
                  int64_t out_cap) {
  int64_t ip = 0, op = 0;
  while (ip < in_len) {
    const uint8_t token = in[ip++];
    int64_t lit = token >> 4;
    if (lit == 15) {
      for (;;) {
        if (ip >= in_len) return -1;  // truncated length extension
        const uint8_t b = in[ip++];
        lit += b;
        if (b != 255) break;
      }
    }
    if (ip + lit > in_len || op + lit > out_cap) return -1;
    std::memcpy(out + op, in + ip, lit);
    ip += lit;
    op += lit;
    if (ip >= in_len) break;  // last sequence has no match
    if (ip + 2 > in_len) return -1;
    const int64_t offset = in[ip] | (static_cast<int64_t>(in[ip + 1]) << 8);
    ip += 2;
    if (offset == 0 || offset > op) return -1;
    int64_t mlen = (token & 0x0f);
    if (mlen == 15) {
      for (;;) {
        if (ip >= in_len) return -1;  // truncated length extension
        const uint8_t b = in[ip++];
        mlen += b;
        if (b != 255) break;
      }
    }
    mlen += 4;
    if (op + mlen > out_cap) return -1;
    for (int64_t i = 0; i < mlen; ++i, ++op) out[op] = out[op - offset];
  }
  return op;
}

}  // namespace

extern "C" {

// Snappy: accepts Kafka's xerial framing (magic \x82SNAPPY\x00, then
// [be32 block length][raw snappy block]...) or a bare raw block.
// Returns bytes written to out, or -1 on malformed input / short out_cap.
int64_t kta_snappy_decompress(const uint8_t* in, int64_t in_len, uint8_t* out,
                              int64_t out_cap) {
  if (!in || !out || in_len < 0) return -1;
  static const uint8_t kXerial[8] = {0x82, 'S', 'N', 'A', 'P', 'P', 'Y', 0};
  if (in_len >= 16 && std::memcmp(in, kXerial, 8) == 0) {
    int64_t ip = 16;  // magic + version + compat (be32 each)
    int64_t op = 0;
    while (ip + 4 <= in_len) {
      const int64_t blen = read_be32(in + ip);
      ip += 4;
      if (blen < 0 || ip + blen > in_len) return -1;
      const int64_t n = snappy_raw(in + ip, blen, out + op, out_cap - op);
      if (n < 0) return -1;
      ip += blen;
      op += n;
    }
    return ip == in_len ? op : -1;
  }
  return snappy_raw(in, in_len, out, out_cap);
}

// LZ4: accepts an LZ4 frame (magic 0x184D2204; content checksum and block
// checksums tolerated/skipped, dictionaries unsupported) or a bare block.
int64_t kta_lz4_decompress(const uint8_t* in, int64_t in_len, uint8_t* out,
                           int64_t out_cap) {
  if (!in || !out || in_len < 0) return -1;
  if (in_len >= 7 && read_le32(in) == 0x184D2204u) {
    int64_t ip = 4;
    const uint8_t flg = in[ip];
    ip += 2;  // FLG + BD
    const bool content_size = flg & 0x08;
    const bool block_checksum = flg & 0x10;
    const bool content_checksum = flg & 0x04;
    if (flg & 0x01) return -1;  // dictionaries unsupported
    if (content_size) ip += 8;
    ip += 1;  // header checksum
    int64_t op = 0;
    while (ip + 4 <= in_len) {
      const uint32_t bsize = read_le32(in + ip);
      ip += 4;
      if (bsize == 0) {  // EndMark
        if (content_checksum) ip += 4;
        return op;
      }
      const bool uncompressed = bsize & 0x80000000u;
      const int64_t blen = bsize & 0x7fffffffu;
      if (ip + blen > in_len) return -1;
      if (uncompressed) {
        if (op + blen > out_cap) return -1;
        std::memcpy(out + op, in + ip, blen);
        op += blen;
      } else {
        const int64_t n = lz4_block(in + ip, blen, out + op, out_cap - op);
        if (n < 0) return -1;
        op += n;
      }
      ip += blen;
      if (block_checksum) ip += 4;
    }
    return -1;  // missing EndMark
  }
  return lz4_block(in, in_len, out, out_cap);
}

}  // extern "C"
