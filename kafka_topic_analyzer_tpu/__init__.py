"""TPU-native Kafka topic analyzer.

A brand-new framework with the capabilities of xenji/kafka-topic-analyzer
(reference layout: ``src/{main,kafka,metric,fnv32}.rs``), redesigned TPU-first:

- the reference's per-message metric accumulators (``src/metric.rs:12-26``)
  become batched, associatively-mergeable reducer states updated by ``jax.jit``
  kernels (`kafka_topic_analyzer_tpu.models`, `.ops`),
- the reference's single ``poll``-loop ingestion (``src/kafka.rs:74-137``)
  becomes a batching record pipeline with pluggable sources
  (`kafka_topic_analyzer_tpu.io`) including a native C++ shim,
- scale-out is data-parallel over a `jax.sharding.Mesh` with XLA collectives
  (``psum``/``pmax``) merging per-device sketch states over ICI
  (`kafka_topic_analyzer_tpu.parallel`),
- the CLI surface and terminal report stay identical to the reference
  (``src/main.rs:32-67`` and ``src/main.rs:123-179``), plus a
  ``--backend {cpu,tpu}`` selector (`kafka_topic_analyzer_tpu.cli`).
"""

__version__ = "0.1.0"

from kafka_topic_analyzer_tpu.config import AnalyzerConfig  # noqa: F401
from kafka_topic_analyzer_tpu.records import RecordBatch  # noqa: F401
