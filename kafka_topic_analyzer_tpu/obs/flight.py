"""Pipeline flight recorder: synchronized per-stage occupancy time series.

Every bench round since the superbatch layer landed has diagnosed the
pipeline by hand — BENCH_NOTES rounds 7/9/10/11 each reconstruct a
per-stage ledger from scattered counters to argue whether a scan was
ingest-bound, fold-bound, or tunnel-gated.  This module records the same
signals the ledger was built from, continuously and in one clock domain,
so the attribution can be computed instead of argued (obs/doctor.py).

Design constraints (DESIGN.md §17):

- **Never perturb the pipeline.**  The sampler is a read-only consumer of
  the instruments the hot paths already write (§9): one tick reads ~a
  dozen counter/gauge values — each a lock acquire + a float read — at a
  default 4 Hz.  It takes no pipeline locks, allocates a handful of
  floats per tick, and touches no queue, socket, or device handle.  The
  instruments it reads are booked whether or not a recorder is running
  (notably ``kta_dispatch_throttle_seconds_total``), so switching the
  recorder on changes *observation*, not *behavior* — scans stay
  byte-identical (tests/test_flight.py holds the report equal either
  way, and the drain-throughput referee holds within 2%).
- **Bounded memory for unbounded scans.**  Samples land in a decimating
  ring: when the buffer reaches ``max_samples`` it is thinned 2:1 and
  the sampling interval doubles, so an arbitrarily long scan keeps a
  full-scan-coverage series at progressively coarser resolution instead
  of growing without bound (or silently dropping its head or tail).
- **Clock-injectable** like Spinner/Backoff: tests drive ``sample_once``
  with a fake clock and never sleep.

Tracks are CUMULATIVE registry values (counters, histogram sums) or
INSTANTANEOUS gauges, sampled at one timestamp per tick — deltas between
ticks are the per-window occupancy obs/doctor.py windows verdicts over.
The live series is exported three ways: ``/flight`` on the Prometheus
endpoint (JSON), Chrome counter tracks on the active ``--trace-json``
tracer (``ph: "C"`` events alongside the stage spans), and the windowed
verdict lines of the ``--stats`` BOTTLENECK digest.

Cross-controller: series stay process-local (timestamps from different
hosts don't interleave meaningfully), but every cumulative track reads a
COUNTER, and counters sum across the ``gather_telemetry`` merge — so the
fleet-wide doctor verdict aggregates through the registry algebra, not
through series shipping.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from kafka_topic_analyzer_tpu.obs import metrics as obs_metrics
from kafka_topic_analyzer_tpu.obs import trace as obs_trace
from kafka_topic_analyzer_tpu.utils.profiling import STAGE_ORDER as _STAGES


def _family_total(family) -> float:
    """Sum of a labeled family's child values (0.0 when no children)."""
    return sum(s["value"] for s in family.samples() if s.get("labels"))


def _hist_sum(hist) -> float:
    return hist.samples()[0]["sum"]


def _default_tracks() -> "List[Tuple[str, str, Callable[[], float]]]":
    """(name, kind, reader) triples.  kind: 'cum' = cumulative (window
    occupancy = delta / window), 'inst' = instantaneous gauge."""
    m = obs_metrics
    tracks: "List[Tuple[str, str, Callable[[], float]]]" = [
        # Drive-loop occupancy (ScanProfile books these live per stage
        # window; the ingest stage IS the consumer's wait-for-batch time).
        *[
            (f"stage_{name}_s", "cum",
             (lambda c=m.STAGE_SECONDS.labels(stage=name): c.value))
            for name in _STAGES
        ],
        # Dispatch backpressure: the launch-site throttle wait, in-flight
        # depth, and the pending superbatch fill.
        ("throttle_s", "cum", lambda: m.DISPATCH_THROTTLE_SECONDS.value),
        ("dispatch_inflight", "inst", lambda: m.DISPATCH_INFLIGHT.value),
        ("superbatch_fill", "inst", lambda: m.SUPERBATCH_FILL.value),
        ("stager_slots", "cum", lambda: m.STAGER_SLOTS.value),
        # Ingest-side occupancy: per-worker stall/active totals and the
        # fan-in queue depth (sum over pools).
        ("worker_stall_s", "cum",
         lambda: _family_total(m.INGEST_WORKER_STALL_SECONDS)),
        ("worker_active_s", "cum",
         lambda: _family_total(m.INGEST_WORKER_ACTIVE_SECONDS)),
        ("ingest_queue_depth", "inst",
         lambda: _family_total(m.INGEST_QUEUE_DEPTH)),
        # Source-side rates: fetch/decode seconds and round/byte counts
        # (io/kafka_wire.py books these per fetch round).
        ("fetch_s", "cum", lambda: m.FETCH_SECONDS.value),
        ("decode_s", "cum", lambda: m.DECODE_SECONDS.value),
        ("fetch_rounds", "cum", lambda: m.FETCH_REQUESTS.value),
        ("fetch_bytes", "cum", lambda: m.FETCH_BYTES.value),
        # Device step/retire latency totals (histogram sums are cumulative
        # seconds — delta/window = device-side busy fraction as seen from
        # the dispatching thread).
        ("step_s", "cum", lambda: _hist_sum(m.BACKEND_STEP_SECONDS)),
        ("retire_s", "cum", lambda: _hist_sum(m.DISPATCH_SECONDS)),
        # Scan progress, so windows carry a records-rate alongside.
        ("records", "cum", lambda: m.SCAN_RECORDS.value),
        # Follow-mode service signals (serve/follow.py): the moving-head
        # lag and the poll/pass cadence, so a service run's flight series
        # shows "how far behind the head" next to the stage occupancies
        # for the life of the service.  Zero-valued lanes for batch scans.
        ("follow_lag", "inst", lambda: m.FOLLOW_LAG.value),
        ("follow_polls", "cum", lambda: m.FOLLOW_POLLS.value),
        ("follow_passes", "cum", lambda: m.FOLLOW_PASSES.value),
        # Service-health lanes (obs/doctor.diagnose_trends + the alert
        # engine's longer baselines): fault/corruption/cache counters
        # whose RATES are what the trend doctor windows verdicts over —
        # retry storms, corruption storms, segstore fallback and
        # cache-poison spikes, and the warm-cache verify residual.
        ("degraded_partitions", "inst",
         lambda: m.DEGRADED_PARTITIONS.value),
        ("refresh_failures", "cum",
         lambda: m.WATERMARK_REFRESH_FAILURES.value),
        ("backoff_sleeps", "cum", lambda: m.BACKOFF_SLEEPS.value),
        ("corrupt_frames", "cum",
         lambda: _family_total(m.CORRUPT_FRAMES)),
        ("segstore_fallbacks", "cum",
         lambda: _family_total(m.SEGSTORE_FALLBACK)),
        ("cache_verify_s", "cum",
         lambda: m.SEGSTORE_CACHE_VERIFY_SECONDS.value),
        ("cache_hit_bytes", "cum",
         lambda: m.SEGSTORE_CACHE_HIT_BYTES.value),
        # Fetch-scheduler occupancy (io/fetchsched.py): queue depth vs
        # in-flight workers vs cumulative queue wait.  The trio is what
        # lets diagnose_trends attribute a fetch-bound stretch to
        # scheduler starvation (queue persistently deeper than the pool
        # — raise --fetch-concurrency) vs wire saturation (pool busy,
        # queue shallow — the link is the limit).
        ("fetch_sched_queue", "inst",
         lambda: m.FETCH_SCHED_QUEUE_DEPTH.value),
        ("fetch_sched_inflight", "inst",
         lambda: m.FETCH_SCHED_INFLIGHT.value),
        ("fetch_sched_wait_s", "cum",
         lambda: m.FETCH_SCHED_WAIT_SECONDS.value),
    ]
    return tracks


class FlightRecorder:
    """Low-overhead occupancy sampler over the default metrics registry.

    ``start()`` runs the sampler on a daemon thread at ``interval_s``;
    tests call ``sample_once()`` directly with an injected clock and
    never start the thread.  ``series()`` returns the JSON-able ring
    contents at any time (the ``/flight`` endpoint serves it live).
    """

    def __init__(
        self,
        interval_s: float = 0.25,
        max_samples: int = 2048,
        clock: Callable[[], float] = time.monotonic,
    ):
        if interval_s <= 0:
            raise ValueError("flight sample interval must be > 0")
        if max_samples < 16:
            raise ValueError("flight ring needs >= 16 samples")
        self.interval_s = float(interval_s)
        self.max_samples = int(max_samples)
        self._clock = clock
        self._t0 = clock()
        self._tracks = _default_tracks()
        self._lock = threading.Lock()
        self._t: List[float] = []
        self._bufs: "List[List[float]]" = [[] for _ in self._tracks]
        #: Samples ever taken — the ring mutates ONLY inside a sample
        #: (appends and the 2:1 decimation both), so this count is the
        #: strong cache validator ``/flight`` conditional GETs revalidate
        #: against, and the key of the one-entry serialized cache below.
        self._samples_total = 0
        self._series_cache: "Optional[Tuple[int, bytes]]" = None
        self._stop = threading.Event()
        self._thread: "Optional[threading.Thread]" = None
        #: Optional disk-backed history sink (obs/history.HistoryStore):
        #: every tick the recorder takes also lands one history row, so
        #: the durable series and the live ring can never disagree about
        #: what a tick saw.  The store has its own lock and directory —
        #: the recorder's read-only-consumer discipline is untouched.
        self._history = None

    def attach_history(self, store) -> "FlightRecorder":
        """Persist every sample into ``store`` (obs/history.HistoryStore),
        registering the track kinds so downsampling follows the same
        cum/inst policy the doctor's window math assumes."""
        store.register_kinds({name: kind for name, kind, _ in self._tracks})
        self._history = store
        return self

    # -- sampling ------------------------------------------------------------

    def sample_once(self) -> None:
        """Take one synchronized sample of every track.  Reads are
        per-instrument lock acquires only — no pipeline state is touched."""
        now = self._clock() - self._t0
        row = [reader() for _, _, reader in self._tracks]
        with self._lock:
            self._t.append(now)
            self._samples_total += 1
            for buf, v in zip(self._bufs, row):
                buf.append(v)
            if len(self._t) > self.max_samples:
                # Decimate 2:1 and double the interval: bounded memory,
                # full-scan coverage, progressively coarser resolution.
                self._t = self._t[::2]
                self._bufs = [buf[::2] for buf in self._bufs]
                self.interval_s *= 2.0
        obs_metrics.FLIGHT_SAMPLES.inc()
        history = self._history
        if history is not None:
            try:
                history.append(
                    {
                        name: row[i]
                        for i, (name, _, _) in enumerate(self._tracks)
                    }
                )
            except Exception:
                # Telemetry is best-effort by contract (obs/events.py):
                # a full disk or vanished directory must neither kill
                # the sampler thread nor fail a finished scan at
                # teardown's closing sample.  Detach the sink — one log
                # line, not one per tick.
                import logging

                logging.getLogger(__name__).exception(
                    "history sink failed; detaching it"
                )
                self._history = None
        tracer = obs_trace.active()
        if tracer is not None:
            # Counter tracks render as stacked area lanes under the stage
            # spans in chrome://tracing / Perfetto.  Instantaneous gauges
            # are the useful live lanes; cumulative tracks would render as
            # ever-growing ramps, so they stay in the /flight series.
            tracer.add_counter(
                "flight",
                {
                    name: row[i]
                    for i, (name, kind, _) in enumerate(self._tracks)
                    if kind == "inst"
                },
            )

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.sample_once()

    def start(self) -> "FlightRecorder":
        if self._thread is not None:
            raise RuntimeError("flight recorder already started")
        self._thread = threading.Thread(
            target=self._run, name="kta-flight-recorder", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        """Stop the sampler thread (idempotent) and take one closing
        sample so even sub-interval scans record their final state."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        self.sample_once()

    # -- export --------------------------------------------------------------

    def series(self) -> dict:
        """JSON-able snapshot of the ring: one timestamp list plus one
        value list per track, with each track's kind ('cum'/'inst')."""
        with self._lock:
            t = list(self._t)
            bufs = [list(b) for b in self._bufs]
        return {
            "interval_s": self.interval_s,
            "t": t,
            "kinds": {name: kind for name, kind, _ in self._tracks},
            "tracks": {
                name: bufs[i]
                for i, (name, _, _) in enumerate(self._tracks)
            },
        }

    def series_etag(self) -> str:
        """Strong validator for ``/flight``: the ring changes only when
        a sample lands, so the sample count pins its contents.  O(1) —
        the handler checks If-None-Match before any body exists."""
        with self._lock:
            return f'"f{self._samples_total}"'

    def series_bytes(self) -> "Tuple[bytes, str]":
        """(body, etag) for ``/flight`` — serialized on the RECORDER
        side (rule 9: handlers serialize nothing) with a one-entry cache
        keyed by the validator, so N dashboard polls between ticks pay
        one encode, not N."""
        with self._lock:
            cached = self._series_cache
            if cached is not None and cached[0] == self._samples_total:
                return cached[1], f'"f{cached[0]}"'
            stamp = self._samples_total
        body = json.dumps(self.series()).encode()
        with self._lock:
            # A tick may have landed during the encode; cache under the
            # stamp the body was built from so the ETag stays truthful
            # (the next poll simply re-encodes).
            self._series_cache = (stamp, body)
        return body, f'"f{stamp}"'


_active: "Optional[FlightRecorder]" = None


def set_active(recorder: "Optional[FlightRecorder]") -> None:
    global _active
    _active = recorder


def active() -> "Optional[FlightRecorder]":
    return _active
