"""Metrics registry: counters, gauges, and fixed-bucket histograms.

Dependency-free (stdlib only) and cheap enough for the scan hot loop:
every instrument is a plain Python object guarded by its own lock, and an
increment is one lock acquire + one integer add — no allocation per
observation, no string formatting until scrape time.  Instruments update
once per *batch* or per *fetch round*, never per record, so the telemetry
tax on a multi-million-record scan is a few thousand lock round-trips.

Three representations, one source of truth:

- live instruments (this module) — what the hot paths mutate;
- ``MetricsRegistry.snapshot()`` — a JSON-able dict, the wire format for
  cross-process aggregation (``merge_snapshots``) and the ``--json``
  report's ``telemetry`` block;
- ``render_prometheus(snapshot)`` — Prometheus text exposition v0.0.4,
  served by ``obs.exporters.PrometheusExporter``.

Thread-safety (audited for N-ingest-worker scans, where the wire counters
and per-worker instruments are hit from several threads concurrently —
tests/test_obs.py has the hammer):

- every mutation of an instrument's numeric state (``inc``/``set``/
  ``observe``/``_reset_values``) holds that instrument's own lock, so
  concurrent writers never lose updates;
- child creation (``labels``) and registry get-or-create hold the family/
  registry lock; the child *lookup* is deliberately lock-free (a CPython
  dict read is atomic under the GIL, children are only ever added) so the
  per-observation cost on labeled hot paths is one dict get, not a shared
  lock acquire per worker per batch;
- ``reset()`` is NOT atomic with respect to concurrent traffic (children
  can be re-created mid-reset); it is a test-isolation helper, called only
  between scans.

Merge semantics (multi-controller aggregation, parallel/sharded.py):
counters and histograms are additive; gauges take the elementwise max by
default (per-partition gauges carry disjoint label sets across processes,
so the max is a union in practice), but a gauge whose per-process values
are themselves disjoint counts — e.g. each process's locally-degraded
partitions — declares ``merge="sum"`` and the policy rides in the
snapshot.  The histogram merge law — merging N shard snapshots equals
observing the union of their samples — is property-tested in
tests/test_obs.py.
"""

from __future__ import annotations

import bisect
import contextlib
import math
import re
import threading
from time import perf_counter as _perf_counter
from typing import Dict, Iterable, List, Optional, Tuple

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

#: Default latency buckets (seconds) — device steps land in the 1-100 ms
#: range on current hardware, finalize in the 10 ms - 10 s range.
LATENCY_BUCKETS_S = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0,
)

#: Default batch-size buckets (records per engine step).
BATCH_SIZE_BUCKETS = (
    256, 1024, 4096, 16384, 65536, 262144, 1048576, 4194304,
)


def _check_labels(labelnames: Tuple[str, ...]) -> None:
    for ln in labelnames:
        if not _LABEL_RE.match(ln):
            raise ValueError(f"bad label name {ln!r}")


class _Instrument:
    """Shared base: name/help/label plumbing.  An instrument constructed
    with ``labelnames`` is a *family*; ``labels(...)`` returns (creating on
    first use) the child carrying those label values."""

    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: Tuple[str, ...] = ()):
        if not _NAME_RE.match(name):
            raise ValueError(f"bad metric name {name!r}")
        _check_labels(tuple(labelnames))
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: "Dict[Tuple[str, ...], _Instrument]" = {}

    def labels(self, *values: object, **kv: object) -> "_Instrument":
        if kv:
            if values:
                raise ValueError("pass label values positionally OR by name")
            values = tuple(str(kv[ln]) for ln in self.labelnames)
        else:
            values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, got {values}"
            )
        # Lock-free fast path: children are only ever ADDED (reset() is
        # confined to between-scan test isolation), and a CPython dict get
        # is atomic — so the steady-state labeled hot path (per-worker
        # counters, per-partition gauges) costs one dict lookup instead of
        # serializing every ingest worker on the family lock.
        child = self._children.get(values)
        if child is not None:
            return child
        with self._lock:
            child = self._children.get(values)
            if child is None:
                child = self._make_child()
                self._children[values] = child
            return child

    def _make_child(self) -> "_Instrument":
        raise NotImplementedError

    # -- snapshot ------------------------------------------------------------

    def _sample_values(self) -> dict:
        raise NotImplementedError

    def _reset_values(self) -> None:
        raise NotImplementedError

    def samples(self) -> List[dict]:
        """One dict per label set ({} for the unlabeled instrument)."""
        if self.labelnames:
            with self._lock:
                items = sorted(self._children.items())
            return [
                dict(labels=dict(zip(self.labelnames, vals)),
                     **child._sample_values())
                for vals, child in items
            ]
        return [dict(labels={}, **self._sample_values())]

    def reset(self) -> None:
        with self._lock:
            self._children.clear()
        self._reset_values()


class Counter(_Instrument):
    """Monotonically increasing value."""

    kind = "counter"

    def __init__(self, name: str, help: str, labelnames: Tuple[str, ...] = ()):
        super().__init__(name, help, labelnames)
        self._value = 0.0

    def _make_child(self) -> "Counter":
        return Counter(self.name, self.help)

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _sample_values(self) -> dict:
        return {"value": self.value}

    def _reset_values(self) -> None:
        with self._lock:
            self._value = 0.0


class Gauge(_Instrument):
    """Point-in-time value (lag, ETA, degraded count).

    ``merge`` picks the cross-process aggregation: ``"max"`` (default —
    right for same-quantity gauges like lag, where the fleet's worst value
    is the honest one) or ``"sum"`` (for gauges whose per-process values
    are disjoint local counts, like each process's degraded partitions)."""

    kind = "gauge"

    def __init__(
        self,
        name: str,
        help: str,
        labelnames: Tuple[str, ...] = (),
        merge: str = "max",
    ):
        super().__init__(name, help, labelnames)
        if merge not in ("max", "sum"):
            raise ValueError(f"bad gauge merge policy {merge!r}")
        self.merge = merge
        self._value = 0.0

    def _make_child(self) -> "Gauge":
        return Gauge(self.name, self.help, merge=self.merge)

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _sample_values(self) -> dict:
        return {"value": self.value}

    def _reset_values(self) -> None:
        with self._lock:
            self._value = 0.0


class Histogram(_Instrument):
    """Fixed-bucket histogram: per-bucket counts (non-cumulative in
    memory, cumulative at exposition) plus sum and count.  ``observe`` is
    one bisect + three adds under the lock — no allocation."""

    kind = "histogram"

    def __init__(
        self,
        name: str,
        help: str,
        buckets: Iterable[float] = LATENCY_BUCKETS_S,
        labelnames: Tuple[str, ...] = (),
    ):
        super().__init__(name, help, labelnames)
        bs = tuple(float(b) for b in buckets)
        if not bs or list(bs) != sorted(bs) or len(set(bs)) != len(bs):
            raise ValueError("histogram buckets must be sorted and unique")
        if math.isinf(bs[-1]):
            bs = bs[:-1]  # +Inf is implicit (the overflow slot)
        self.buckets = bs
        self._counts = [0] * (len(bs) + 1)  # last slot = overflow (+Inf)
        self._sum = 0.0
        self._count = 0

    def _make_child(self) -> "Histogram":
        return Histogram(self.name, self.help, self.buckets)

    def observe(self, value: float) -> None:
        i = bisect.bisect_left(self.buckets, value)
        with self._lock:
            self._counts[i] += 1
            self._sum += value
            self._count += 1

    @contextlib.contextmanager
    def time(self):
        """Observe the wall seconds of the ``with`` body (backend
        step/finalize latency instrumentation)."""
        t0 = _perf_counter()
        try:
            yield
        finally:
            self.observe(_perf_counter() - t0)

    def _sample_values(self) -> dict:
        with self._lock:
            return {
                "buckets": list(self.buckets),
                "counts": list(self._counts),
                "sum": self._sum,
                "count": self._count,
            }

    def _reset_values(self) -> None:
        with self._lock:
            self._counts = [0] * (len(self.buckets) + 1)
            self._sum = 0.0
            self._count = 0


class MetricsRegistry:
    """Get-or-create instrument store.  The module-level default registry
    (``default_registry()``) is what the library's hot paths write to;
    tests build private registries or ``reset()`` the default one."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._instruments: "Dict[str, _Instrument]" = {}

    def _get_or_create(self, cls, name: str, help: str, **kw) -> _Instrument:
        with self._lock:
            inst = self._instruments.get(name)
            if inst is not None:
                if not isinstance(inst, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as {inst.kind}"
                    )
                return inst
            inst = cls(name, help, **kw)
            self._instruments[name] = inst
            return inst

    def counter(
        self, name: str, help: str, labelnames: Tuple[str, ...] = ()
    ) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames=labelnames)

    def gauge(
        self,
        name: str,
        help: str,
        labelnames: Tuple[str, ...] = (),
        merge: str = "max",
    ) -> Gauge:
        return self._get_or_create(
            Gauge, name, help, labelnames=labelnames, merge=merge
        )

    def histogram(
        self,
        name: str,
        help: str,
        buckets: Iterable[float] = LATENCY_BUCKETS_S,
        labelnames: Tuple[str, ...] = (),
    ) -> Histogram:
        return self._get_or_create(
            Histogram, name, help, buckets=buckets, labelnames=labelnames
        )

    def instruments(self) -> List[_Instrument]:
        with self._lock:
            return [self._instruments[n] for n in sorted(self._instruments)]

    def snapshot(self) -> dict:
        """JSON-able view of every instrument — the registry wire format.
        Gauges carry their merge policy so ``merge_snapshots`` applies it
        without access to the live instruments."""
        out = {}
        for inst in self.instruments():
            doc = {
                "type": inst.kind,
                "help": inst.help,
                "samples": inst.samples(),
            }
            if inst.kind == "gauge":
                doc["merge"] = inst.merge
            out[inst.name] = doc
        return out

    def reset(self) -> None:
        """Zero every instrument (keeps registrations) — test isolation."""
        for inst in self.instruments():
            inst.reset()


_default = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    return _default


# -- snapshot algebra ---------------------------------------------------------


def _label_key(sample: dict) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted(sample.get("labels", {}).items()))


def _copy_sample(sample: dict) -> dict:
    """One-level-deep sample copy (labels dict, bucket/count lists) so
    merges never mutate a caller's snapshot."""
    return {
        k: (dict(v) if isinstance(v, dict) else
            list(v) if isinstance(v, list) else v)
        for k, v in sample.items()
    }


def merge_snapshots(snapshots: List[dict]) -> dict:
    """Aggregate registry snapshots from N processes into one cluster-wide
    view: counters and histogram bucket counts add, gauges follow their
    declared merge policy (max by default, sum for disjoint local counts;
    disjoint-label gauges union either way).  Mismatched histogram bucket
    layouts raise — they indicate skewed code versions across the fleet."""
    out: dict = {}
    for snap in snapshots:
        for name, metric in snap.items():
            tgt = out.get(name)
            if tgt is None:
                out[name] = {
                    "type": metric["type"],
                    "help": metric.get("help", ""),
                    "samples": [_copy_sample(s) for s in metric["samples"]],
                }
                if "merge" in metric:
                    out[name]["merge"] = metric["merge"]
                continue
            if tgt["type"] != metric["type"]:
                raise ValueError(
                    f"metric {name!r} has conflicting types across "
                    f"processes: {tgt['type']} vs {metric['type']}"
                )
            by_labels = {_label_key(s): s for s in tgt["samples"]}
            for s in metric["samples"]:
                cur = by_labels.get(_label_key(s))
                if cur is None:
                    tgt["samples"].append(_copy_sample(s))
                    by_labels[_label_key(s)] = tgt["samples"][-1]
                elif tgt["type"] == "counter":
                    cur["value"] += s["value"]
                elif tgt["type"] == "gauge":
                    if tgt.get("merge", "max") == "sum":
                        cur["value"] += s["value"]
                    else:
                        cur["value"] = max(cur["value"], s["value"])
                elif tgt["type"] == "histogram":
                    if list(cur["buckets"]) != list(s["buckets"]):
                        raise ValueError(
                            f"histogram {name!r} bucket layouts differ "
                            "across processes"
                        )
                    cur["counts"] = [
                        a + b for a, b in zip(cur["counts"], s["counts"])
                    ]
                    cur["sum"] += s["sum"]
                    cur["count"] += s["count"]
    for metric in out.values():
        metric["samples"].sort(key=_label_key)
    return out


# -- Prometheus text exposition ----------------------------------------------


def _fmt_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    f = float(v)
    return repr(int(f)) if f.is_integer() and abs(f) < 2**53 else repr(f)


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _labels_text(labels: dict, extra: "Optional[Tuple[str, str]]" = None) -> str:
    items = sorted(labels.items())
    if extra is not None:
        items.append(extra)
    if not items:
        return ""
    inner = ",".join(f'{k}="{_escape_label(str(v))}"' for k, v in items)
    return "{" + inner + "}"


def render_prometheus(snapshot: dict) -> str:
    """Prometheus text exposition v0.0.4 of a registry snapshot."""
    lines: List[str] = []
    for name in sorted(snapshot):
        metric = snapshot[name]
        help_text = str(metric.get("help", "")).replace("\n", " ")
        lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {metric['type']}")
        for s in metric["samples"]:
            labels = s.get("labels", {})
            if metric["type"] == "histogram":
                cum = 0
                for le, c in zip(
                    list(s["buckets"]) + [math.inf],
                    s["counts"],
                ):
                    cum += c
                    lt = _labels_text(labels, ("le", _fmt_value(le)))
                    lines.append(f"{name}_bucket{lt} {cum}")
                lines.append(
                    f"{name}_sum{_labels_text(labels)} {_fmt_value(s['sum'])}"
                )
                lines.append(
                    f"{name}_count{_labels_text(labels)} {s['count']}"
                )
            else:
                lines.append(
                    f"{name}{_labels_text(labels)} {_fmt_value(s['value'])}"
                )
    return "\n".join(lines) + "\n"
