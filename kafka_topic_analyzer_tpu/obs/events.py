"""Structured event log: scan lifecycle + transport-fault events as JSONL.

The module-level bus is a no-op until a sink attaches (``emit`` returns
after one attribute load when the sink list is empty), so library modules
emit unconditionally — the hot paths pay nothing unless ``--events-jsonl``
(or a test sink) is active.

Event catalog (field names stable — they are an output format):

- ``scan_start``            topic, partitions, batch_size
- ``heartbeat``             seq, records_per_sec, lag_total   (rate-limited)
- ``snapshot_saved``        records_seen
- ``transport_failure``     leader, partitions, error
- ``connection_evicted``    host, port
- ``metadata_reload``       ok
- ``fetch_error``           partition, code
- ``retry_budget_exhausted`` partition, reason
- ``partition_degraded``    partition, reason
- ``corrupt_suspect``       partition, anchor, kind   (re-fetch pending)
- ``corrupt_frame``         partition, anchor, skip_to, kind, action,
                            quarantined
- ``scan_end``              topic, records, duration_secs, degraded,
                            corrupt_frames

Follow-mode additions (serve/follow.py; a service run emits ONE
scan_start/scan_end pair for its whole lifetime — per-pass lifecycle
events are suppressed so a long-lived run cannot flood the log):

- ``follow_poll``           poll, new_records, lag_total   (only on polls
                            that found new records; idle polls are silent)
- ``watermark_refresh_failed``  attempts, error   (budget exhausted; the
                            previous watermark snapshot stays in force)
- ``partition_healed``      partition   (a degraded partition caught back
                            up to the head in a later follow pass)
- ``follow_stop``           reason, polls, passes   (stop requested:
                            signal name, 'idle', or a caller's reason)
"""

from __future__ import annotations

import json
import threading
import time
from typing import Callable, List, Optional


class JsonlEventLog:
    """Append-only JSONL sink: one ``{"ts": ..., "type": ..., ...}`` object
    per line, flushed per event (events are rare — scan lifecycle and
    faults, not records — so durability beats buffering)."""

    def __init__(self, path: str, clock: Callable[[], float] = time.time):
        self._fh = open(path, "a", encoding="utf-8")
        self._lock = threading.Lock()
        self._clock = clock

    def __call__(self, etype: str, fields: dict) -> None:
        doc = {"ts": round(self._clock(), 3), "type": etype}
        doc.update(fields)
        line = json.dumps(doc, default=str, sort_keys=True)
        with self._lock:
            self._fh.write(line + "\n")
            self._fh.flush()

    def close(self) -> None:
        with self._lock:
            self._fh.close()


_sinks: "List[Callable[[str, dict], None]]" = []


def add_sink(sink: Callable[[str, dict], None]) -> None:
    _sinks.append(sink)


def remove_sink(sink: Callable[[str, dict], None]) -> None:
    try:
        _sinks.remove(sink)
    except ValueError:
        pass


def emit(etype: str, **fields) -> None:
    """Publish an event to every attached sink.  A sink that raises is
    detached (a full disk must not take down the scan) — telemetry is
    best-effort by contract."""
    if not _sinks:
        return
    for sink in list(_sinks):
        try:
            sink(etype, fields)
        except Exception:
            import logging

            logging.getLogger(__name__).exception(
                "event sink failed; detaching it"
            )
            remove_sink(sink)


class Heartbeat:
    """Rate limiter for periodic status events: ``ready()`` is True at most
    once per ``interval_s`` (clock-injectable for tests)."""

    def __init__(
        self,
        interval_s: float = 10.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.interval_s = interval_s
        self._clock = clock
        self._last: Optional[float] = None

    def ready(self) -> bool:
        now = self._clock()
        if self._last is not None and now - self._last < self.interval_s:
            return False
        self._last = now
        return True

    def force(self) -> None:
        """Make the next ``ready()`` fire regardless of the interval
        (closing heartbeat at scan end)."""
        self._last = None
