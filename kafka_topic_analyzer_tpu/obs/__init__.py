"""Telemetry subsystem: metrics registry, exporters, events, trace spans.

Dependency-free observability for the scan pipeline:

- ``obs.registry``  — counters / gauges / fixed-bucket histograms, with
  snapshot + merge algebra for multi-controller aggregation;
- ``obs.metrics``   — the instrument catalog every layer writes to;
- ``obs.exporters`` — Prometheus text exposition over HTTP
  (``--metrics-port``);
- ``obs.events``    — structured JSONL event log (``--events-jsonl``) and
  the rate-limited heartbeat;
- ``obs.trace``     — host-side span tracer exporting Chrome trace-event
  JSON (``--trace-json``), complementary to the ``--profile-dir`` XLA
  trace.

``telemetry_session`` is the CLI's one-stop wiring: it attaches exactly
the sinks the flags ask for, yields the tracer for ``run_scan``, and
tears everything down (flushing the trace file) on exit.
"""

from __future__ import annotations

import contextlib
import logging
from typing import Iterator, Optional

from kafka_topic_analyzer_tpu.obs.registry import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    default_registry,
    merge_snapshots,
    render_prometheus,
)
from kafka_topic_analyzer_tpu.obs.trace import SpanTracer  # noqa: F401


@contextlib.contextmanager
def telemetry_session(
    metrics_port: "Optional[int]" = None,
    events_jsonl: "Optional[str]" = None,
    trace_json: "Optional[str]" = None,
    flight_record: bool = False,
    history_dir: "Optional[str]" = None,
    history_bytes: int = 0,
    sse: bool = False,
) -> "Iterator[Optional[SpanTracer]]":
    """Wire up the flag-selected telemetry outputs around a scan.

    Yields the span tracer (None unless ``trace_json`` is set) for
    ``run_scan``'s profile to mirror stages into.  On exit the trace file
    is written, the event log closed, and the scrape endpoint shut down —
    the endpoint therefore serves while the scan runs.

    ``flight_record`` starts the occupancy sampler (obs/flight.py) as the
    process-wide active recorder for the session: the ``/flight``
    endpoint, the Chrome counter tracks, and the ``--stats`` windowed
    verdict lines all read it.  The recorder keeps sampling until
    teardown (so readers inside the session — the report code — see a
    LIVE series and take their own closing ``sample_once()`` if they
    need the final state; cli._diagnose does); teardown then stops the
    thread and clears ``active()``.

    ``history_dir``/``history_bytes`` open the disk-backed telemetry
    history (obs/history.py) next to the checkpoints and feed it from
    the recorder's tick path — the recorder is started implicitly when
    history is on, since history IS the recorder's durable sink.  The
    session also constructs the alert engine (obs/health.py, built-in
    rules) as the process-wide active one whenever any serving surface
    exists to read it (``metrics_port`` set, or history on) — the
    follow/fleet services evaluate it at their poll boundaries, the
    engine drive loop at heartbeat cadence, and ``/healthz`` serves its
    latest verdict.  Services may install their own engine instead
    (tests do); last ``set_active`` wins.

    ``sse`` starts the Server-Sent-Events publisher (serve/push.py) as
    the session's active one: every report publish is pushed to
    ``/events`` subscribers from the publisher's own fan-out thread.
    Requires ``metrics_port`` (the route needs a server to live on).

    Output paths are opened (and truncated, for the trace) at setup so a
    bad ``--trace-json``/``--events-jsonl`` path fails before the scan,
    not after it; and each teardown step is isolated, so a failing trace
    write still closes the event log and the endpoint.
    """
    import sys

    from kafka_topic_analyzer_tpu.obs import events as _events
    from kafka_topic_analyzer_tpu.obs import flight as _flight
    from kafka_topic_analyzer_tpu.obs import health as _health
    from kafka_topic_analyzer_tpu.obs import history as _history
    from kafka_topic_analyzer_tpu.obs import trace as _trace

    exporter = None
    sink = None
    tracer = None
    recorder = None
    store = None
    engine = None
    pusher = None
    try:
        if metrics_port is not None:
            from kafka_topic_analyzer_tpu.obs.exporters import (
                PrometheusExporter,
            )

            exporter = PrometheusExporter(metrics_port)
            if metrics_port == 0:
                # The ephemeral port is useless unless announced; stderr,
                # like the spinner, so --json stdout stays clean.
                sys.stderr.write(
                    "serving metrics on "
                    f"http://{exporter.host}:{exporter.port}/metrics\n"
                )
        if events_jsonl:
            sink = _events.JsonlEventLog(events_jsonl)
            _events.add_sink(sink)
        if trace_json:
            with open(trace_json, "w", encoding="utf-8"):
                pass  # fail fast on an unwritable path; write() re-opens
            tracer = SpanTracer()
            _trace.set_active(tracer)
        if flight_record or history_dir:
            # After the tracer: the recorder mirrors its instantaneous
            # tracks onto the active tracer as Chrome counter events.
            # History implies the recorder — it is the durable sink of
            # the same tick path.
            recorder = _flight.FlightRecorder()
            if history_dir:
                store = _history.HistoryStore(
                    history_dir,
                    max_bytes=max(4096, int(history_bytes)),
                )
                recorder.attach_history(store)
                _history.set_active(store)
            _flight.set_active(recorder)
            recorder.start()
        if metrics_port is not None or history_dir:
            # The alert engine costs nothing until something evaluates
            # it; it exists whenever a surface (the HTTP endpoints, the
            # --stats health digest, the JSONL event bus) can read it.
            engine = _health.HealthEngine()
            _health.set_active(engine)
        if sse and metrics_port is not None:
            from kafka_topic_analyzer_tpu.serve import push as _push

            pusher = _push.SsePublisher().start()
            _push.set_active(pusher)
        yield tracer
    finally:
        if pusher is not None:
            from kafka_topic_analyzer_tpu.serve import push as _push

            try:
                pusher.stop()  # closes every stream; booked "shutdown"
            finally:
                _push.set_active(None)
        if engine is not None:
            # The session is the CLI's outermost scope: whatever engine
            # is active at teardown (ours, or a service's replacement)
            # has no reader once the endpoint below closes.
            _health.set_active(None)
        if recorder is not None:
            try:
                recorder.stop()  # final sample; series stays readable
            finally:
                _flight.set_active(None)
        if store is not None:
            try:
                store.close()
            finally:
                _history.set_active(None)
        if tracer is not None:
            _trace.set_active(None)
        try:
            if tracer is not None:
                try:
                    tracer.write(trace_json)
                except OSError:
                    # Best-effort by contract: a trace-write failure (disk
                    # filled mid-scan) must not mask the scan's own
                    # exception or fail a finished scan.
                    logging.getLogger(__name__).exception(
                        "failed to write %s", trace_json
                    )
        finally:
            try:
                if sink is not None:
                    _events.remove_sink(sink)
                    sink.close()
            finally:
                if exporter is not None:
                    exporter.close()
