"""Declarative alert-rule engine: automated health verdicts (DESIGN.md §22).

PR 10's doctor answers "what is the bottleneck"; nothing in the system
answers "is this service HEALTHY" without a human reading ``--stats``.
This module is that layer: a small rule engine evaluated at poll
boundaries (follow/fleet) and heartbeat boundaries (batch scans) over
registry snapshots and short observed windows, with the alerting
semantics a pager expects —

- **threshold + for-duration**: a rule's condition must hold
  continuously for ``for_s`` before the alert fires (a one-poll blip
  never pages);
- **resolve hysteresis**: a firing alert must observe its condition
  clear continuously for ``resolve_s`` before it resolves (a flapping
  condition re-arms the firing state without emitting a second
  ``alert_firing`` event — flap suppression);
- **no silent state changes**: EVERY transition of the per-rule state
  machine (ok → pending → firing → resolving → ok) books
  ``kta_alerts_transitions_total{rule=,state=}`` — the alert trace is
  reconstructible from the counter alone (tools/lint.sh rule 12), and
  the set of currently-active alerts is ``kta_alerts_firing{rule=}``.

Transitions also emit typed events on the JSONL bus (``alert_pending``,
``alert_firing``, ``alert_resolving``, ``alert_resolved``,
``alert_cleared`` for a pending blip that never fired), and every
evaluation publishes a pre-serialized health document — the ``health``
block of ``/report.json`` and ``--stats``, and the body ``/healthz``
serves (200 while healthy, 503 with the firing-rule JSON otherwise —
fit for a k8s liveness probe).  The HTTP handler reads ONLY the
``healthz``/``doc`` snapshot accessors (rule 9): serialization happens
here, on the evaluating side, never per probe.

The engine is clock-injectable like Spinner/Backoff; tests drive
``evaluate`` with a fake clock and scripted snapshots and never sleep.
State is per (rule, scope): fleet mode evaluates per-topic rules once
per topic (scope = the topic name), so ``/report.json?topic=`` carries
exactly that topic's alerts while the bare rollup carries all of them.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import threading
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Tuple

from kafka_topic_analyzer_tpu.config import HealthConfig
from kafka_topic_analyzer_tpu.obs import events as obs_events
from kafka_topic_analyzer_tpu.obs import metrics as obs_metrics

log = logging.getLogger(__name__)

#: Rule states (the transitions counter's ``state`` label values).
OK = "ok"
PENDING = "pending"
FIRING = "firing"
RESOLVING = "resolving"

#: An alert counts as ACTIVE (unhealthy) while firing or resolving —
#: resolve hysteresis means "not yet proven healed".
ACTIVE_STATES = (FIRING, RESOLVING)


@dataclasses.dataclass(frozen=True)
class AlertRule:
    """One declarative rule.  ``predicate(ctx)`` returns an evidence
    dict while the condition holds and None while it is clear — the
    evidence rides the events, the health document, and ``--stats``
    (same discipline as the doctor: never a bare label)."""

    name: str
    #: What firing MEANS, for humans ("follow lag diverging...").
    summary: str
    predicate: "Callable[[EvalContext], Optional[dict]]"
    #: Condition must hold this long before the alert fires.
    for_s: float = 0.0
    #: Condition must stay clear this long before the alert resolves.
    resolve_s: float = 0.0
    #: Fleet mode: evaluate once per topic in ``extras['topics']``
    #: (scope = topic name) instead of once globally.
    per_topic: bool = False


class EvalContext:
    """What a predicate sees: the registry snapshot, the engine's
    observed scalar series (short in-memory windows, clock-stamped),
    the optional disk history, caller extras, and the scope topic."""

    def __init__(
        self,
        engine: "HealthEngine",
        snapshot: "Optional[dict]",
        now: float,
        extras: "Optional[dict]" = None,
        topic: "Optional[str]" = None,
    ):
        self.engine = engine
        self.snapshot = snapshot or {}
        self.now = now
        self.extras = extras or {}
        self.topic = topic
        self.cfg = engine.cfg

    def total(self, metric: str) -> float:
        """Sum of a snapshot metric's sample values (0.0 when absent)."""
        m = self.snapshot.get(metric)
        if not m:
            return 0.0
        return float(sum(s.get("value", 0.0) for s in m["samples"]))

    def value(self, series: str) -> "Optional[float]":
        """Latest observed value of an engine series."""
        obs = self.engine._series.get(series)
        return obs[-1][1] if obs else None

    def at(self, series: str, age_s: float) -> "Optional[Tuple[float, float]]":
        """The newest observation at least ``age_s`` old: (t, value), or
        None when the series has not been observed that long — rules
        refuse to fire on a window they have not actually watched."""
        obs = self.engine._series.get(series)
        if not obs:
            return None
        cutoff = self.now - age_s
        best = None
        for t, v in obs:
            if t <= cutoff:
                best = (t, v)
            else:
                break
        return best

    def delta(self, series: str, age_s: float, strict: bool = False) -> "Optional[float]":
        """Increase of a cumulative series over the trailing window.
        When the series does not yet span the window, the non-strict
        form differences against the OLDEST observation — a shorter
        span yields a conservative subset of the window's delta, which
        is the right call for threshold rules (a fault counter moving
        at all should not wait a full window to be noticed).  ``strict``
        returns None instead (rules comparing rates across specific
        spans need the real window)."""
        now_v = self.value(series)
        if now_v is None:
            return None
        then = self.at(series, age_s)
        if then is None:
            if strict:
                return None
            obs = self.engine._series.get(series)
            if not obs or len(obs) < 2:
                return None
            then = obs[0]
        return now_v - then[1]


@dataclasses.dataclass
class _RuleState:
    state: str = OK
    #: Clock time the CURRENT state was entered.
    since: float = 0.0
    #: Clock time the alert last fired (entered FIRING from ok/pending).
    fired_at: float = 0.0
    evidence: "Optional[dict]" = None


class HealthEngine:
    """Own the rule states and the published health document.

    ``evaluate(snapshot, extras)`` runs one pass (services call it at
    their poll boundaries); ``maybe_evaluate()`` is the rate-limited
    form the engine drive loop calls at heartbeat cadence (it snapshots
    the default registry itself).  Both publish the serialized document
    under the engine lock — the ``/healthz`` handler reads one
    reference.
    """

    #: (series name, reader over a snapshot) — the scalar series the
    #: engine observes each evaluation for windowed rule predicates.
    SERIES: "List[Tuple[str, str]]" = [
        ("lag", "kta_follow_lag_records"),
        ("records", "kta_scan_records_total"),
        ("refresh_failures", "kta_watermark_refresh_failures_total"),
        ("corrupt_frames", "kta_corrupt_frames_total"),
        ("degraded", "kta_scan_degraded_partitions"),
        ("backoff_sleeps", "kta_backoff_sleeps_total"),
        ("segstore_fallbacks", "kta_segstore_fallback_total"),
        ("lease_losses", "kta_lease_losses_total"),
        ("failovers", "kta_fleet_failovers_total"),
        ("lost_records", "kta_log_lost_records_total"),
        ("lost_ranges", "kta_log_lost_ranges_total"),
        ("watermark_regressions", "kta_log_watermark_regressions_total"),
    ]

    def __init__(
        self,
        rules: "Optional[List[AlertRule]]" = None,
        cfg: "Optional[HealthConfig]" = None,
        clock: Callable[[], float] = time.monotonic,
        wall_clock: Callable[[], float] = time.time,
        history=None,
    ):
        self.cfg = cfg if cfg is not None else HealthConfig()
        self.rules = (
            list(rules) if rules is not None else built_in_rules(self.cfg)
        )
        names = [r.name for r in self.rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate alert rule names in {names}")
        self._clock = clock
        self._wall_clock = wall_clock
        self.history = history
        self._lock = threading.Lock()
        #: The last non-None extras (the fleet's per-topic lag map and
        #: failed set).  Extras-free evaluations — the engine-drive-loop
        #: heartbeat hook fires DURING topic passes — reuse it, so an
        #: extras-derived condition (fleet-topic-failure) cannot flap
        #: ok↔firing between poll boundaries just because an evaluator
        #: had no context; staleness is bounded by one fleet poll.
        self._last_extras: "Optional[dict]" = None
        self._states: "Dict[Tuple[str, Optional[str]], _RuleState]" = {}
        self._series: "Dict[str, Deque[Tuple[float, float]]]" = {}
        self._doc: "Optional[dict]" = None
        self._doc_bytes: "Optional[bytes]" = None
        self._healthy = True
        self._last_eval: "Optional[float]" = None
        self.evaluations = 0

    # -- evaluation -----------------------------------------------------------

    def maybe_evaluate(self, extras: "Optional[dict]" = None) -> None:
        """Evaluate at most once per ``cfg.eval_interval_s`` — the
        engine-drive-loop hook (engine.run_scan's heartbeat path), so a
        plain batch scan gets live ``/healthz`` too."""
        now = self._clock()
        with self._lock:
            if (
                self._last_eval is not None
                and now - self._last_eval < self.cfg.eval_interval_s
            ):
                return
        self.evaluate(extras=extras)

    def evaluate(
        self,
        snapshot: "Optional[dict]" = None,
        extras: "Optional[dict]" = None,
    ) -> dict:
        """One evaluation pass: observe the series, run every rule's
        state machine, publish the health document.  ``snapshot``
        defaults to a fresh default-registry snapshot (the poll-boundary
        callers pass nothing)."""
        if snapshot is None:
            from kafka_topic_analyzer_tpu.obs.registry import (
                default_registry,
            )

            snapshot = default_registry().snapshot()
        now = self._clock()
        with self._lock:
            if extras is not None:
                self._last_extras = extras
            extras = self._last_extras
            self._last_eval = now
            self.evaluations += 1
            self._observe(snapshot, now, extras)
            rows: "List[dict]" = []
            for rule in self.rules:
                scopes: "List[Optional[str]]"
                if rule.per_topic:
                    # Fleet mode: one state per topic.  Scopes that
                    # already hold state keep being evaluated even when
                    # this evaluation carries no topic context (the
                    # heartbeat path) — a published document must never
                    # DROP a firing per-topic row just because the
                    # evaluator had no extras.  Without any topic map
                    # (solo follow, batch scans) the rule evaluates
                    # once, unscoped, over the global series.
                    topics = set((extras or {}).get("topics", {}))
                    topics |= {
                        s
                        for (rname, s) in self._states
                        if rname == rule.name and s is not None
                    }
                    scopes = sorted(topics) if topics else [None]
                else:
                    scopes = [None]
                for scope in scopes:
                    ctx = EvalContext(
                        self, snapshot, now, extras, topic=scope
                    )
                    rows.append(self._eval_rule(rule, scope, ctx, now))
            doc = self._build_doc(rows, now)
            self._doc = doc
            self._doc_bytes = json.dumps(doc).encode()
            self._healthy = doc["healthy"]
        obs_metrics.HEALTH_EVALUATIONS.inc()
        return doc

    def _observe(
        self, snapshot: dict, now: float, extras: "Optional[dict]"
    ) -> None:
        """Record the windowed scalar series this evaluation sees.
        Retention is the longest rule window plus slack."""
        keep = self.cfg.retention_s

        def push(name: str, v: float) -> None:
            obs = self._series.setdefault(name, deque())
            obs.append((now, float(v)))
            while obs and now - obs[0][0] > keep:
                obs.popleft()

        ctx = EvalContext(self, snapshot, now)
        for name, metric in self.SERIES:
            push(name, ctx.total(metric))
        # Truncation is one REASON of the lost-records counter — the
        # truncation rule needs it split out (a retention race is routine
        # under short retention; a truncation never is).
        lost_metric = snapshot.get("kta_log_lost_records_total") or {}
        push(
            "truncated_records",
            float(
                sum(
                    s.get("value", 0.0)
                    for s in lost_metric.get("samples", [])
                    if s.get("labels", {}).get("reason") == "truncation"
                )
            ),
        )
        for topic, lag in ((extras or {}).get("topics") or {}).items():
            push(f"topic:{topic}:lag", float(lag))
        for topic, records in (
            (extras or {}).get("topic_loss") or {}
        ).items():
            push(f"topic:{topic}:lost", float(records))

    def _eval_rule(
        self,
        rule: AlertRule,
        scope: "Optional[str]",
        ctx: EvalContext,
        now: float,
    ) -> dict:
        key = (rule.name, scope)
        st = self._states.setdefault(key, _RuleState(since=now))
        try:
            evidence = rule.predicate(ctx)
        except Exception:
            # A broken rule must never take the service down — health is
            # telemetry, and telemetry is best-effort by contract.
            log.exception("alert rule %r predicate failed", rule.name)
            evidence = None
        cond = evidence is not None
        if st.state == OK and cond:
            if rule.for_s > 0:
                self._transition(rule, scope, st, PENDING, now, evidence)
            else:
                self._transition(rule, scope, st, FIRING, now, evidence)
        elif st.state == PENDING:
            if not cond:
                self._transition(rule, scope, st, OK, now, None)
            elif now - st.since >= rule.for_s:
                self._transition(rule, scope, st, FIRING, now, evidence)
            else:
                st.evidence = evidence
        elif st.state == FIRING:
            if cond:
                st.evidence = evidence
            elif rule.resolve_s > 0:
                self._transition(rule, scope, st, RESOLVING, now, None)
            else:
                self._transition(rule, scope, st, OK, now, None)
        elif st.state == RESOLVING:
            if cond:
                # Flap suppression: the re-armed firing state books its
                # transition but emits no second alert_firing event and
                # re-increments no gauge — the alert never resolved.
                self._transition(rule, scope, st, FIRING, now, evidence)
            elif now - st.since >= rule.resolve_s:
                self._transition(rule, scope, st, OK, now, None)
        return {
            "rule": rule.name,
            "topic": scope,
            "state": st.state,
            "since_s": round(max(0.0, now - st.since), 3),
            "firing_s": (
                round(max(0.0, now - st.fired_at), 3)
                if st.state in ACTIVE_STATES
                else None
            ),
            "summary": rule.summary,
            "evidence": st.evidence,
        }

    def _transition(
        self,
        rule: AlertRule,
        scope: "Optional[str]",
        st: _RuleState,
        new: str,
        now: float,
        evidence: "Optional[dict]",
    ) -> None:
        """The ONE place rule state changes (tools/lint.sh rule 12):
        every transition books kta_alerts_transitions_total{rule,state};
        entering/leaving the active set moves kta_alerts_firing{rule}
        and emits the typed event."""
        prev = st.state
        obs_metrics.ALERTS_TRANSITIONS.labels(rule=rule.name, state=new).inc()
        fields = dict(rule=rule.name, state=new)
        if scope is not None:
            fields["topic"] = scope
        if evidence:
            fields["evidence"] = evidence
        if new == FIRING and prev in (OK, PENDING):
            obs_metrics.ALERTS_FIRING.labels(rule=rule.name).inc(1.0)
            st.fired_at = now
            obs_events.emit("alert_firing", **fields)
        elif new == OK and prev in ACTIVE_STATES:
            obs_metrics.ALERTS_FIRING.labels(rule=rule.name).inc(-1.0)
            obs_events.emit("alert_resolved", **fields)
        elif new == PENDING:
            obs_events.emit("alert_pending", **fields)
        elif new == RESOLVING:
            obs_events.emit("alert_resolving", **fields)
        elif new == OK and prev == PENDING:
            obs_events.emit("alert_cleared", **fields)
        st.state = new
        st.since = now
        st.evidence = evidence if evidence else (
            st.evidence if new in ACTIVE_STATES else None
        )

    def _build_doc(self, rows: "List[dict]", now: float) -> dict:
        active = [r for r in rows if r["state"] in ACTIVE_STATES]
        return {
            "healthy": not active,
            "evaluations": self.evaluations,
            "evaluated_at": round(self._wall_clock(), 3),
            "firing": active,
            "rules": rows,
        }

    # -- read side (the rule-9 snapshot accessors) ---------------------------

    def doc(self) -> "Optional[dict]":
        """Latest health document (None before the first evaluation)."""
        with self._lock:
            return self._doc

    def healthz(self) -> "Optional[Tuple[int, bytes]]":
        """(status_code, body) for the ``/healthz`` probe: 200 while no
        alert is active, 503 with the firing-rule JSON otherwise; None
        before the first evaluation (the handler serves 503 for that —
        an unevaluated service must not claim liveness)."""
        with self._lock:
            if self._doc_bytes is None:
                return None
            return (200 if self._healthy else 503), self._doc_bytes

    def healthz_entry(self) -> "Optional[Tuple[int, bytes, str]]":
        """(status_code, body, etag) for conditional ``/healthz`` GETs
        (DESIGN §26).  The evaluation count is the strong validator:
        ``_doc_bytes`` is re-serialized exactly once per evaluation
        (``evaluated_at``/``since_s`` move every pass, so each count
        really is a distinct body), and both are assigned in the same
        critical section of ``evaluate``."""
        with self._lock:
            if self._doc_bytes is None:
                return None
            return (
                (200 if self._healthy else 503),
                self._doc_bytes,
                f'"e{self.evaluations}"',
            )

    def alerts_block(self, topic: "Optional[str]" = None) -> "Optional[dict]":
        """The ``health`` block a report document embeds.  With
        ``topic``: only that topic's scoped alerts plus the global ones
        (what ``/report.json?topic=`` should show); without: the whole
        document."""
        with self._lock:
            if self._doc is None:
                return None
            if topic is None:
                return self._doc
            rows = [
                r
                for r in self._doc["rules"]
                if r["topic"] in (None, topic)
            ]
            active = [r for r in rows if r["state"] in ACTIVE_STATES]
            return {
                "healthy": not active,
                "evaluations": self._doc["evaluations"],
                "evaluated_at": self._doc["evaluated_at"],
                "firing": active,
                "rules": rows,
            }


# -- built-in rules -----------------------------------------------------------


def _lag_series(ctx: EvalContext) -> str:
    return f"topic:{ctx.topic}:lag" if ctx.topic is not None else "lag"


def _lag_growth(ctx: EvalContext) -> "Optional[dict]":
    """Lag divergence: the cursor is behind AND the gap has grown over
    the rule window — at this rate the scan never catches up (ETA ∞)."""
    cfg = ctx.cfg
    series = _lag_series(ctx)
    lag = ctx.engine._series.get(series)
    lag_now = lag[-1][1] if lag else None
    if lag_now is None or lag_now <= 0:
        return None
    then = ctx.at(series, cfg.lag_window_s)
    if then is None:
        return None  # not watched long enough to call divergence
    t_then, lag_then = then
    growth = lag_now - lag_then
    if growth < cfg.lag_min_growth:
        return None
    dt = max(1e-9, ctx.now - t_then)
    return {
        "lag": int(lag_now),
        "lag_then": int(lag_then),
        "window_s": round(dt, 1),
        "growth_per_s": round(growth / dt, 2),
        "eta": "inf",
    }


def _degraded(ctx: EvalContext) -> "Optional[dict]":
    n = ctx.total("kta_scan_degraded_partitions")
    if n <= 0:
        return None
    return {"degraded_partitions": int(n)}


def _corruption_storm(ctx: EvalContext) -> "Optional[dict]":
    d = ctx.delta("corrupt_frames", ctx.cfg.storm_window_s)
    if d is None or d < ctx.cfg.corrupt_frames_threshold:
        return None
    return {
        "corrupt_frames": int(d),
        "window_s": ctx.cfg.storm_window_s,
    }


def _watermark_outage(ctx: EvalContext) -> "Optional[dict]":
    d = ctx.delta("refresh_failures", ctx.cfg.outage_window_s)
    if d is None or d <= 0:
        return None
    return {
        "refresh_failures": int(d),
        "window_s": ctx.cfg.outage_window_s,
    }


def _throughput_regression(ctx: EvalContext) -> "Optional[dict]":
    """Recent fold rate collapsed against the trailing baseline while
    there is still work (lag > 0) — an idle service at the head is
    healthy, a backed-up one folding at a fraction of its own baseline
    is not."""
    cfg = ctx.cfg
    lag_now = ctx.value(_lag_series(ctx))
    if not lag_now or lag_now <= 0:
        return None
    now_v = ctx.value("records")
    then = ctx.at("records", cfg.throughput_window_s)
    base_then = ctx.at("records", cfg.throughput_baseline_s)
    if now_v is None or then is None or base_then is None:
        return None
    base_span = then[0] - base_then[0]
    recent_span = ctx.now - then[0]
    if base_span <= 0 or recent_span <= 0:
        return None
    baseline_rate = (then[1] - base_then[1]) / base_span
    # Both rates divide by their ACTUAL observed spans: `then` can be
    # older than the nominal window at sparse evaluation cadence, and
    # dividing that wider delta by the nominal width would overestimate
    # the recent rate — silently raising the firing threshold.
    recent_rate = (now_v - then[1]) / recent_span
    if baseline_rate < cfg.min_baseline_rate:
        return None
    if recent_rate >= cfg.throughput_drop_fraction * baseline_rate:
        return None
    return {
        "recent_per_s": round(recent_rate, 1),
        "baseline_per_s": round(baseline_rate, 1),
        "drop_fraction": round(
            recent_rate / baseline_rate if baseline_rate > 0 else 0.0, 3
        ),
        "lag": int(lag_now),
    }


def _fleet_topic_failure(ctx: EvalContext) -> "Optional[dict]":
    failed = sorted((ctx.extras or {}).get("failed_topics") or [])
    if not failed:
        return None
    return {"failed_topics": failed, "count": len(failed)}


def _lease_lost(ctx: EvalContext) -> "Optional[dict]":
    """This instance lost topic leases it held (fenced by a successor,
    or expired with renewals failing) in the trailing window — scanned
    work is being handed over, which is news even when the handover is
    working as designed (ISSUE 16)."""
    d = ctx.delta("lease_losses", ctx.cfg.storm_window_s)
    if d is None or d <= 0:
        return None
    return {"lease_losses": int(d), "window_s": ctx.cfg.storm_window_s}


def _failover(ctx: EvalContext) -> "Optional[dict]":
    """Topics changed owner in the trailing window: this instance took
    over leases whose previous holder was a different instance — some
    peer crashed, hung, or released (DESIGN §23)."""
    d = ctx.delta("failovers", ctx.cfg.storm_window_s)
    if d is None or d <= 0:
        return None
    return {"failovers": int(d), "window_s": ctx.cfg.storm_window_s}


def _loss_series(ctx: EvalContext) -> str:
    return (
        f"topic:{ctx.topic}:lost" if ctx.topic is not None else "lost_records"
    )


def _lost_range(ctx: EvalContext) -> "Optional[dict]":
    """The log mutated records out from under the scanner in the trailing
    window (retention races past the cursor, resume below log-start) —
    the counts are honest but incomplete, which an operator must hear
    about before trusting a dashboard built on them (ISSUE 18)."""
    d = ctx.delta(_loss_series(ctx), ctx.cfg.storm_window_s)
    if d is None or d <= 0:
        return None
    evidence = {
        "lost_records": int(d),
        "window_s": ctx.cfg.storm_window_s,
    }
    ranges = ctx.delta("lost_ranges", ctx.cfg.storm_window_s)
    if ranges:
        evidence["lost_ranges"] = int(ranges)
    return evidence


def _truncation(ctx: EvalContext) -> "Optional[dict]":
    """The log was TRUNCATED under the scanner (unclean leader election
    replacing already-counted records) in the trailing window — unlike a
    retention race, this marks folds non-authoritative and is never
    routine.  Watermark regressions ride along as evidence only: a held
    stale-replica answer heals by itself and must not page."""
    d = ctx.delta("truncated_records", ctx.cfg.storm_window_s)
    if d is None or d <= 0:
        return None
    evidence = {
        "truncated_records": int(d),
        "window_s": ctx.cfg.storm_window_s,
    }
    w = ctx.delta("watermark_regressions", ctx.cfg.storm_window_s)
    if w:
        evidence["watermark_regressions"] = int(w)
    return evidence


def built_in_rules(cfg: "Optional[HealthConfig]" = None) -> "List[AlertRule]":
    """The shipped rule set (ISSUE 15): lag growth, degraded-partition
    transitions, corruption storms, watermark-refresh outages,
    throughput regression, fleet-topic failure.  Thresholds/windows come
    from `config.HealthConfig`; services and tests may extend or replace
    the list freely — the engine is declarative."""
    cfg = cfg if cfg is not None else HealthConfig()
    return [
        AlertRule(
            "lag-growth",
            "follow lag diverging: the cursor falls further behind the "
            "head every poll — at this rate the scan never catches up",
            _lag_growth,
            for_s=cfg.for_s,
            resolve_s=cfg.resolve_s,
            per_topic=True,
        ),
        AlertRule(
            "degraded-partitions",
            "partitions dropped from the scan after exhausting their "
            "transport retry budget — metrics undercount their tails",
            _degraded,
            for_s=0.0,  # a degraded transition is immediately actionable
            resolve_s=cfg.resolve_s,
        ),
        AlertRule(
            "corruption-storm",
            "corrupt frames classified in the trailing window — the "
            "topic (or a broker volume) is shedding poisoned data",
            _corruption_storm,
            for_s=0.0,
            resolve_s=cfg.resolve_s,
        ),
        AlertRule(
            "watermark-refresh-outage",
            "watermark re-polls exhausting the transport retry budget — "
            "the service is flying blind on stale head offsets",
            _watermark_outage,
            for_s=cfg.for_s,
            resolve_s=cfg.resolve_s,
        ),
        AlertRule(
            "throughput-regression",
            "fold throughput collapsed against the service's own "
            "trailing baseline while lag remains",
            _throughput_regression,
            for_s=cfg.for_s,
            resolve_s=cfg.resolve_s,
        ),
        AlertRule(
            "fleet-topic-failure",
            "one or more fleet topics hard-failed (isolation caught the "
            "error; their numbers are partial until rerun)",
            _fleet_topic_failure,
            for_s=0.0,
            resolve_s=0.0,
        ),
        AlertRule(
            "lease_lost",
            "this instance was fenced off topics it owned (lease lost "
            "to a successor or expired unrenewed) — its in-flight work "
            "on those topics was discarded at the epoch fence",
            _lease_lost,
            for_s=0.0,  # a fencing is immediately actionable
            resolve_s=cfg.resolve_s,
        ),
        AlertRule(
            "failover",
            "topics changed owner: this instance took over leases from "
            "a crashed, hung, or departed peer (DESIGN §23)",
            _failover,
            for_s=0.0,
            resolve_s=cfg.resolve_s,
        ),
        AlertRule(
            "lost-range",
            "the log mutated records out from under the scanner "
            "(retention race / resume below log-start) — counts are "
            "honest for the surviving records but name a lost range",
            _lost_range,
            for_s=0.0,  # every lost record is immediately actionable
            resolve_s=cfg.resolve_s,
            per_topic=True,
        ),
        AlertRule(
            "truncation",
            "the log was truncated under the scanner (unclean election "
            "or watermark regression) — affected folds are "
            "non-authoritative until rescanned",
            _truncation,
            for_s=0.0,
            resolve_s=cfg.resolve_s,
        ),
    ]


_active: "Optional[HealthEngine]" = None


def set_active(engine: "Optional[HealthEngine]") -> None:
    global _active
    _active = engine


def active() -> "Optional[HealthEngine]":
    return _active
