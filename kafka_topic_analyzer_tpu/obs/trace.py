"""Host-side span tracer → Chrome trace-event JSON.

``--profile-dir`` already captures the XLA timeline via the JAX profiler;
this records the *host* side (fetch, decode, fold dispatch, snapshot,
finalize) in the same Chrome ``traceEvents`` format, so both timelines
load into the same viewer (chrome://tracing, Perfetto) for side-by-side
inspection.

Spans are complete events (``ph: "X"``) appended under a lock — prefetch
workers and fetch-pool threads record concurrently and the per-thread
``tid`` keeps their tracks separate.  ``ScanProfile`` mirrors its stage
windows into the active tracer with the *same* measured duration, so the
trace's per-stage totals agree with ``--stats`` by construction
(tests/test_telemetry.py holds them within 5%).
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Callable, Iterator, List, Optional


class SpanTracer:
    def __init__(self, clock: Callable[[], float] = time.perf_counter):
        self._clock = clock
        self._t0 = clock()
        self._lock = threading.Lock()
        self._events: List[dict] = []

    def add_complete(
        self,
        name: str,
        start_s: float,
        dur_s: float,
        cat: str = "span",
        args: "Optional[dict]" = None,
    ) -> None:
        """Record one complete span; ``start_s`` is in this tracer's clock
        domain (the same clock used by the caller's measurement, so the
        recorded duration is exactly the measured one)."""
        ev = {
            "name": name,
            "cat": cat,
            "ph": "X",
            "ts": (start_s - self._t0) * 1e6,
            "dur": dur_s * 1e6,
            "pid": os.getpid(),
            "tid": threading.get_ident(),
        }
        if args:
            ev["args"] = args
        with self._lock:
            self._events.append(ev)

    def add_counter(
        self, name: str, values: "dict", t_s: "Optional[float]" = None
    ) -> None:
        """Record one counter-track sample (``ph: "C"``): the flight
        recorder's occupancy gauges render as stacked numeric lanes under
        the stage spans in chrome://tracing / Perfetto.  ``t_s`` is in
        this tracer's clock domain, like ``add_complete``; None stamps
        the sample "now" (callers on a different clock — the flight
        recorder's injectable monotonic — must not translate domains).
        ``values`` maps series name -> number (one lane per key)."""
        ev = {
            "name": name,
            "cat": "flight",
            "ph": "C",
            "ts": ((self._clock() if t_s is None else t_s) - self._t0) * 1e6,
            "pid": os.getpid(),
            "tid": 0,  # counter tracks live on one lane, not per-thread
            "args": {k: float(v) for k, v in values.items()},
        }
        with self._lock:
            self._events.append(ev)

    @contextlib.contextmanager
    def span(self, name: str, cat: str = "span", **args) -> Iterator[None]:
        t0 = self._clock()
        try:
            yield
        finally:
            self.add_complete(
                name, t0, self._clock() - t0, cat, args or None
            )

    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def chrome_trace(self) -> dict:
        return {"traceEvents": self.events(), "displayTimeUnit": "ms"}

    def write(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(self.chrome_trace(), fh)


_active: "Optional[SpanTracer]" = None


def set_active(tracer: "Optional[SpanTracer]") -> None:
    global _active
    _active = tracer


def active() -> "Optional[SpanTracer]":
    return _active


@contextlib.contextmanager
def maybe_span(name: str, cat: str = "span") -> Iterator[None]:
    """Span on the active tracer, or a fast no-op when tracing is off —
    what library modules (io/kafka_wire.py) wrap their fetch/decode work
    in without threading a tracer through every call."""
    tr = _active
    if tr is None:
        yield
        return
    with tr.span(name, cat):
        yield
