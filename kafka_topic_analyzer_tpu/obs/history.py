"""Disk-backed multi-resolution telemetry history (DESIGN.md §22).

PR 10's flight recorder answers "what is the pipeline doing *right
now*" from an in-memory ring that dies with the process.  A service
meant to follow a topic for days needs the same series to survive a
SIGTERM→restart and to stay queryable over hours without unbounded
memory — this module is that layer: an RRD-style, crash-safe,
append-only time-series store fed from the recorder's tick path
(obs/flight.FlightRecorder.attach_history) and served at ``/history``
on ``--metrics-port``.

Shape of the store (one directory, living NEXT TO the checkpoints —
``checkpoint.history_dir`` — so the series resumes with the state):

- **Tiers of halving resolution.**  Tier 0 receives every appended
  sample.  Every 2 samples of tier k downsample into 1 sample of tier
  k+1 (cumulative tracks keep the LAST value — deltas are preserved
  exactly; instantaneous gauges average), so tier k holds 2^k-coarser
  rows covering 2^k the time span in the same bytes.  A window query
  answers from the finest tier that still retains each sub-range —
  recent history at full resolution, old history coarser, never absent.
- **Append-only segment files, atomic rotation.**  Rows append as JSONL
  lines (write+flush per row — a killed process loses at most the line
  in flight) to ``tier<k>/open.jsonl``; at the segment byte bound the
  open file is ``os.replace``d to its ``seg-<t0>-<t1>.jsonl`` name in
  one atomic rename and a fresh open file starts.  Load tolerates a
  truncated final line (SIGKILL mid-write) by skipping it.
- **Bounded by ``--history-bytes``.**  The byte budget splits evenly
  across tiers; when a tier exceeds its share its OLDEST closed segment
  is deleted — which is exactly the RRD contract: fine-grained history
  ages out first, the coarse tiers keep the long view.
- **Restart continuity without gap misattribution.**  Every row carries
  the store's *epoch* (bumped once per open).  Counters restart from
  zero with the process, so a consumer computing rates must difference
  only within an epoch; the wall-clock gap between the last pre-restart
  row and the first post-restart row stays IN the timeline (quiet-gap
  windows are counted in any rate denominator, never collapsed) — see
  ``track_rate``.  ``window()`` serves the pre-restart rows the moment
  the store reopens.

Timestamps are wall-clock (``time.time``), not monotonic: rows from
different process lifetimes must order on one axis.  The clock is
injectable like Spinner/Backoff so tests never sleep.

Like obs/flight.py, the module-level ``active()``/``set_active()`` pair
registers the session's store for the ``/history`` HTTP handler, which
may only call the ``window`` snapshot accessor (tools/lint.sh rule 9).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import zlib
from typing import Callable, Dict, List, Optional, Tuple

from kafka_topic_analyzer_tpu.obs import metrics as obs_metrics

log = logging.getLogger(__name__)

META_NAME = "meta.json"
OPEN_NAME = "open.jsonl"

#: Row = (wall_ts, epoch, {track: value}).
Row = Tuple[float, int, Dict[str, float]]


class HistoryStore:
    """One directory of tiered telemetry history.

    ``append`` is called from the flight recorder's sampler thread (4 Hz
    by default): one JSON line + flush per tier touched.  ``window`` is
    the read side — the ``/history`` handler, the trend doctor, and
    tests all consume the same dict shape.
    """

    def __init__(
        self,
        directory: str,
        max_bytes: int = 8 << 20,
        tiers: int = 4,
        clock: Callable[[], float] = time.time,
    ):
        if max_bytes < 4096:
            raise ValueError("--history-bytes must be >= 4096")
        if not (1 <= tiers <= 10):
            raise ValueError("history tiers must be in [1, 10]")
        self.directory = directory
        self.max_bytes = int(max_bytes)
        self.tiers = int(tiers)
        self._clock = clock
        self._lock = threading.Lock()
        self._kinds: Dict[str, str] = {}
        #: Per-tier byte budget; segments rotate at a quarter of it so a
        #: tier always retains >= ~3/4 budget of closed history.
        self._tier_budget = max(1024, self.max_bytes // self.tiers)
        self._seg_bytes = max(512, self._tier_budget // 4)
        #: In-memory mirror of everything retained on disk (bounded by
        #: max_bytes of JSONL, so the decoded rows stay small).
        self._rows: "List[List[Row]]" = [[] for _ in range(self.tiers)]
        #: Closed segments, oldest first: {path, bytes, nrows}.
        self._segments: "List[List[dict]]" = [[] for _ in range(self.tiers)]
        #: Open-file handle / byte count / first row ts per tier.
        self._open_fh: "List[Optional[object]]" = [None] * self.tiers
        self._open_bytes = [0] * self.tiers
        self._open_first: "List[Optional[float]]" = [None] * self.tiers
        self._open_last: "List[Optional[float]]" = [None] * self.tiers
        self._open_rows = [0] * self.tiers
        #: Downsample cascade: the unpaired row of tier k awaiting its
        #: partner (reset on restart — exactness is per-run).
        self._pending: "List[Optional[Row]]" = [None] * self.tiers
        self.epoch = 1
        self._closed = False
        #: Rows appended this process lifetime — with the epoch, the
        #: store's strong cache validator: retention eviction and
        #: downsample cascades only ever happen inside an append, so
        #: (epoch, append_seq) pins the full retained row state.
        self._append_seq = 0
        #: Serialized-query cache keyed by ETag (which embeds the
        #: validator state + query): repeated identical dashboard
        #: queries between appends reuse one encode (DESIGN §26).
        self._query_cache: "Dict[str, bytes]" = {}
        self._load()

    # -- layout ---------------------------------------------------------------

    def _tier_dir(self, k: int) -> str:
        return os.path.join(self.directory, f"tier{k}")

    def _load(self) -> None:
        """Open (or reopen) the directory: bump the epoch, rotate any
        crash-leftover open segment, and mirror the retained rows."""
        os.makedirs(self.directory, exist_ok=True)
        meta_path = os.path.join(self.directory, META_NAME)
        meta: dict = {}
        try:
            with open(meta_path, "r", encoding="utf-8") as f:
                meta = json.load(f)
        except (OSError, ValueError):
            meta = {}
        self.epoch = int(meta.get("epoch", 0)) + 1
        self._kinds = dict(meta.get("kinds", {}))
        self._write_meta()
        for k in range(self.tiers):
            d = self._tier_dir(k)
            os.makedirs(d, exist_ok=True)
            # A leftover open.jsonl is the pre-restart tail: seal it as a
            # closed segment so the pre-restart window stays served.
            leftover = os.path.join(d, OPEN_NAME)
            if os.path.exists(leftover):
                rows, nbytes = self._read_rows(leftover)
                if rows:
                    final = os.path.join(
                        d,
                        f"seg-{int(rows[0][0] * 1000)}"
                        f"-{int(rows[-1][0] * 1000)}.jsonl",
                    )
                    os.replace(leftover, final)
                else:
                    os.unlink(leftover)
            segs = sorted(
                f for f in os.listdir(d)
                if f.startswith("seg-") and f.endswith(".jsonl")
            )
            for name in segs:
                path = os.path.join(d, name)
                rows, nbytes = self._read_rows(path)
                if not rows:
                    try:
                        os.unlink(path)
                    except OSError:
                        pass
                    continue
                self._rows[k].extend(rows)
                self._segments[k].append(
                    {"path": path, "bytes": nbytes, "nrows": len(rows)}
                )
            # The mirror stays in SEGMENT order (filename sort ≈ write
            # order), never globally time-sorted: _enforce_budget drops
            # the oldest segment's rows as a positional prefix, and that
            # invariant must hold even when a wall-clock step between
            # runs makes write order disagree with timestamp order.
            # window() sorts its filtered rows at query time instead.
            self._enforce_budget(k)
            self._open_segment(k)
        self._book_bytes()

    @staticmethod
    def _read_rows(path: str) -> "Tuple[List[Row], int]":
        """Rows of one segment file, tolerating a truncated tail line
        (the crash-in-flight write) and skipping undecodable lines."""
        rows: "List[Row]" = []
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            return [], 0
        for line in data.splitlines():
            if not line.strip():
                continue
            try:
                t, epoch, values = json.loads(line)
                rows.append((float(t), int(epoch), dict(values)))
            except (ValueError, TypeError):
                continue  # truncated/corrupt line: skip, keep the rest
        return rows, len(data)

    def _write_meta(self) -> None:
        meta_path = os.path.join(self.directory, META_NAME)
        tmp = meta_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"epoch": self.epoch, "kinds": self._kinds}, f)
        os.replace(tmp, meta_path)

    def _open_segment(self, k: int) -> None:
        self._open_fh[k] = open(
            os.path.join(self._tier_dir(k), OPEN_NAME), "ab"
        )
        self._open_bytes[k] = 0
        self._open_first[k] = None
        self._open_last[k] = None
        self._open_rows[k] = 0

    # -- write side -----------------------------------------------------------

    def register_kinds(self, kinds: Dict[str, str]) -> None:
        """Track kind map ('cum'/'inst') — the downsample policy.  The
        flight recorder registers its tracks at attach time; kinds
        persist in meta.json so a reopened store downsamples new rows
        identically."""
        with self._lock:
            if self._closed:
                return
            self._kinds.update(kinds)
            self._write_meta()

    def append(
        self, values: Dict[str, float], t: "Optional[float]" = None
    ) -> None:
        """Record one sample row (stamped with the store clock unless a
        test injects ``t``).  Lands in tier 0 and cascades coarser."""
        with self._lock:
            if self._closed:
                return
            ts = float(self._clock() if t is None else t)
            self._append_tier(0, (ts, self.epoch, dict(values)))
            self._append_seq += 1
        obs_metrics.HISTORY_SAMPLES.inc()
        self._book_bytes()

    def _append_tier(self, k: int, row: Row) -> None:
        self._rows[k].append(row)
        line = (
            json.dumps(
                [round(row[0], 3), row[1], row[2]],
                separators=(",", ":"),
            ).encode()
            + b"\n"
        )
        fh = self._open_fh[k]
        fh.write(line)
        fh.flush()
        self._open_bytes[k] += len(line)
        self._open_rows[k] += 1
        if self._open_first[k] is None:
            self._open_first[k] = row[0]
        self._open_last[k] = row[0]
        if self._open_bytes[k] >= self._seg_bytes:
            self._rotate(k)
        if k + 1 < self.tiers:
            pend = self._pending[k]
            if pend is None:
                self._pending[k] = row
            else:
                self._pending[k] = None
                self._append_tier(k + 1, self._merge(pend, row))

    def _merge(self, a: Row, b: Row) -> Row:
        """Downsample one pair: cumulative tracks keep the LAST value
        (the delta over the merged span is exact), instantaneous gauges
        average.  Pairs spanning an epoch boundary keep the later row's
        values outright — averaging across a counter reset would invent
        data."""
        values: Dict[str, float] = {}
        for name, vb in b[2].items():
            kind = self._kinds.get(name, "cum")
            va = a[2].get(name)
            if kind == "inst" and va is not None and a[1] == b[1]:
                values[name] = (va + vb) / 2.0
            else:
                values[name] = vb
        return (b[0], b[1], values)

    def _rotate(self, k: int) -> None:
        """Seal the open segment under its span name (one atomic rename)
        and start a fresh one; then enforce the tier's byte budget."""
        fh = self._open_fh[k]
        fh.close()
        path = os.path.join(self._tier_dir(k), OPEN_NAME)
        final = os.path.join(
            self._tier_dir(k),
            f"seg-{int(self._open_first[k] * 1000)}"
            f"-{int(self._open_last[k] * 1000)}.jsonl",
        )
        os.replace(path, final)
        self._segments[k].append(
            {
                "path": final,
                "bytes": self._open_bytes[k],
                "nrows": self._open_rows[k],
            }
        )
        obs_metrics.HISTORY_ROTATIONS.inc()
        self._open_segment(k)
        self._enforce_budget(k)

    def _enforce_budget(self, k: int) -> None:
        while (
            sum(s["bytes"] for s in self._segments[k]) > self._tier_budget
            and len(self._segments[k]) > 1
        ):
            seg = self._segments[k].pop(0)
            try:
                os.unlink(seg["path"])
            except OSError:
                log.warning("history: could not delete %r", seg["path"])
            del self._rows[k][: seg["nrows"]]

    def _book_bytes(self) -> None:
        with self._lock:
            total = sum(
                sum(s["bytes"] for s in self._segments[k])
                + self._open_bytes[k]
                for k in range(self.tiers)
            )
        obs_metrics.HISTORY_BYTES.set(total)

    def close(self) -> None:
        """Flush and close the open files (idempotent).  Open segments
        stay on disk and are sealed by the next open — a SIGKILL without
        close() loses nothing but the line in flight."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            for fh in self._open_fh:
                if fh is not None:
                    try:
                        fh.close()
                    except OSError:
                        pass

    # -- read side ------------------------------------------------------------

    def tier_rows(self, k: int) -> "List[Row]":
        """One tier's retained rows (tests/introspection)."""
        with self._lock:
            return list(self._rows[k])

    def _compose(
        self, lo: float, hi: float, min_tier: int
    ) -> "Tuple[List[Row], List[int]]":
        """In-window rows at the finest retained resolution per
        sub-range, starting no finer than ``min_tier`` (callers hold the
        lock)."""
        out: "List[Row]" = []
        covered_from: "Optional[float]" = None
        tiers_used: "List[int]" = []
        for k in range(min_tier, self.tiers):
            # Sorted per query: the mirror keeps write order (the
            # eviction invariant), which a wall-clock step across a
            # restart can decouple from timestamp order.
            rows = sorted(
                (r for r in self._rows[k] if lo <= r[0] <= hi),
                key=lambda r: r[0],
            )
            if not rows:
                continue
            if covered_from is None:
                out = rows
                covered_from = rows[0][0]
                tiers_used.append(k)
            else:
                older = [r for r in rows if r[0] < covered_from]
                if older:
                    out = older + out
                    covered_from = older[0][0]
                    tiers_used.append(k)
        out.sort(key=lambda r: (r[0], r[1]))
        return out, tiers_used

    def _window_locked(
        self,
        t0: "Optional[float]",
        t1: "Optional[float]",
        tracks: "Optional[List[str]]",
        max_points: "Optional[int]",
    ) -> dict:
        lo = float("-inf") if t0 is None else float(t0)
        hi = float("inf") if t1 is None else float(t1)
        out, tiers_used = self._compose(lo, hi, 0)
        decimated = False
        if max_points is not None and 0 < max_points < len(out):
            # Price the query from the existing RRD tiers: drop the
            # finest tiers until the composed window fits — the answer
            # a coarser tier gives is the same downsample policy the
            # store already applies over time, just applied over the
            # whole window.
            for start in range(1, self.tiers):
                coarser, used = self._compose(lo, hi, start)
                if not coarser:
                    break  # coarser tiers hold nothing here yet
                out, tiers_used = coarser, used
                if len(out) <= max_points:
                    break
            if len(out) > max_points:
                # Even the coarsest retained tier exceeds the price:
                # stride-decimate keeping each stride's LAST row (the
                # cum-exact choice, same as the tier cascade).
                stride = -(-len(out) // max_points)
                out = out[stride - 1::stride]
                decimated = True
        names = (
            list(tracks)
            if tracks
            else sorted({n for r in out for n in r[2]})
        )
        doc = {
            "t": [round(r[0], 3) for r in out],
            "epoch": [r[1] for r in out],
            "tracks": {
                name: [r[2].get(name) for r in out] for name in names
            },
            "kinds": {
                n: self._kinds.get(n, "cum") for n in names
            },
            "tiers_used": tiers_used,
            "epoch_now": self.epoch,
            "now": round(self._clock(), 3),
        }
        if max_points is not None:
            doc["max_points"] = int(max_points)
            doc["points"] = len(out)
            doc["decimated"] = decimated
        return doc

    def window(
        self,
        t0: "Optional[float]" = None,
        t1: "Optional[float]" = None,
        tracks: "Optional[List[str]]" = None,
        max_points: "Optional[int]" = None,
    ) -> dict:
        """Windowed query: rows with ``t0 <= t <= t1`` at the finest
        retained resolution per sub-range — tier 0 answers for whatever
        span it still holds, each coarser tier extends the answer
        further back.  ``max_points`` prices the query: the coarsest
        retained tier that satisfies the bound answers instead, so a
        month-wide dashboard query returns kilobytes, not the raw ring.
        The JSON-able result is what ``/history`` serves: one timestamp
        list, one epoch list (restart boundaries are data), and one
        value list per track (None where a row predates the track)."""
        with self._lock:
            return self._window_locked(t0, t1, tracks, max_points)

    @staticmethod
    def _query_key(
        t0: "Optional[float]",
        t1: "Optional[float]",
        tracks: "Optional[List[str]]",
        max_points: "Optional[int]",
    ) -> int:
        key = repr(
            (t0, t1, tuple(tracks) if tracks else None, max_points)
        )
        return zlib.crc32(key.encode())

    def window_etag(
        self,
        t0: "Optional[float]" = None,
        t1: "Optional[float]" = None,
        tracks: "Optional[List[str]]" = None,
        max_points: "Optional[int]" = None,
    ) -> str:
        """Strong validator for one ``/history`` query: (epoch,
        append-seq, query) — any append (which is also the only place
        retention eviction or a downsample cascade can run) moves it.
        O(1); the handler checks If-None-Match against this BEFORE any
        body is built."""
        qh = self._query_key(t0, t1, tracks, max_points)
        with self._lock:
            return f'"h{self.epoch}.{self._append_seq}.{qh:08x}"'

    def window_bytes(
        self,
        t0: "Optional[float]" = None,
        t1: "Optional[float]" = None,
        tracks: "Optional[List[str]]" = None,
        max_points: "Optional[int]" = None,
    ) -> "Tuple[bytes, str]":
        """(body, etag) for ``/history`` — serialized on the STORE side
        (rule 9: handlers serialize nothing), under the store's own
        lock, and cached per validator so identical queries between
        appends reuse one encode.  The body is frozen at first encode
        for its ETag: a 200 and a later 304 for the same validator
        always describe the same bytes."""
        qh = self._query_key(t0, t1, tracks, max_points)
        with self._lock:
            etag = f'"h{self.epoch}.{self._append_seq}.{qh:08x}"'
            body = self._query_cache.get(etag)
            if body is None:
                body = json.dumps(
                    self._window_locked(t0, t1, tracks, max_points)
                ).encode()
                self._query_cache[etag] = body
                while len(self._query_cache) > 32:
                    self._query_cache.pop(next(iter(self._query_cache)))
            return body, etag


# -- window algebra (shared by the trend doctor and the alert rules) ----------


def track_points(
    window: dict, name: str
) -> "List[Tuple[float, int, float]]":
    """(t, epoch, value) points of one track, rows without it skipped."""
    t = window.get("t") or []
    epochs = window.get("epoch") or [1] * len(t)
    series = (window.get("tracks") or {}).get(name) or []
    return [
        (t[i], epochs[i], float(series[i]))
        for i in range(min(len(t), len(series)))
        if series[i] is not None
    ]


def track_delta(window: dict, name: str) -> float:
    """Total increase of a CUMULATIVE track over the window, summing
    within-epoch differences only — a restart's counter reset never
    reads as a negative delta, and the dead time between epochs simply
    contributes nothing (the wall clock still advances, see
    ``track_rate``)."""
    pts = track_points(window, name)
    total = 0.0
    for i in range(1, len(pts)):
        if pts[i][1] == pts[i - 1][1]:
            total += max(0.0, pts[i][2] - pts[i - 1][2])
        else:
            # First row of a new epoch: the counter restarted at 0, so
            # its current value IS the progress since the restart.
            total += max(0.0, pts[i][2])
    return total

def track_rate(window: dict, name: str) -> float:
    """Per-second rate of a cumulative track over the FULL wall span of
    the window — quiet/restart gaps count in the denominator (a scan
    that sat dead for an hour did not sustain its pre-crash rate)."""
    pts = track_points(window, name)
    if len(pts) < 2:
        return 0.0
    span = pts[-1][0] - pts[0][0]
    return track_delta(window, name) / span if span > 0 else 0.0


_active: "Optional[HistoryStore]" = None


def set_active(store: "Optional[HistoryStore]") -> None:
    global _active
    _active = store


def active() -> "Optional[HistoryStore]":
    return _active
