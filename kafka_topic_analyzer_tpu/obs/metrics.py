"""Metric name catalog: every instrument the scan pipeline writes.

One module so the full surface is auditable in one place (README
"Observability" documents it verbatim).  All instruments live on the
default registry; they update per batch / per fetch round — never per
record — so instrumentation stays invisible next to decode costs
(tools/bench_ingest.py holds telemetry-on within 2% of off).

Naming follows Prometheus conventions: ``_total`` counters, ``_seconds``
for durations, base units only.
"""

from __future__ import annotations

from kafka_topic_analyzer_tpu.obs.registry import (
    BATCH_SIZE_BUCKETS,
    LATENCY_BUCKETS_S,
    default_registry,
)

_REG = default_registry()

# -- engine (run_scan) --------------------------------------------------------

SCAN_RECORDS = _REG.counter(
    "kta_scan_records_total", "Valid records folded by the scan engine")
SCAN_BATCHES = _REG.counter(
    "kta_scan_batches_total", "Engine steps dispatched (batches)")
SCAN_BYTES = _REG.counter(
    "kta_scan_bytes_total", "Decoded record-batch bytes through the engine")
BATCH_RECORDS = _REG.histogram(
    "kta_batch_records", "Valid records per engine step",
    buckets=BATCH_SIZE_BUCKETS)
STAGE_SECONDS = _REG.counter(
    "kta_stage_seconds_total",
    "Cumulative wall seconds per scan stage, booked LIVE at every stage "
    "window exit (utils/profiling.ScanProfile) so the flight recorder can "
    "sample per-stage occupancy mid-scan",
    labelnames=("stage",))
STAGE_RECORDS = _REG.counter(
    "kta_stage_records_total",
    "Records attributed per scan stage (ScanProfile, booked live)",
    labelnames=("stage",))
STAGE_BYTES = _REG.counter(
    "kta_stage_bytes_total",
    "Decoded bytes attributed per scan stage (ScanProfile, booked live) — "
    "what makes the snapshot-sourced --stats stage digest carry the same "
    "MB/s the old in-process profile summary did",
    labelnames=("stage",))
PARTITION_LAG = _REG.gauge(
    "kta_partition_lag",
    "Records between the scan position and the end watermark",
    labelnames=("partition",),
    # Each process feeds (and therefore lags on) a disjoint partition set,
    # so the cross-process merge is a label union; max is the no-op policy
    # for the union case and the honest one if labels ever collide.
    merge="max")
PARTITION_ETA_SECONDS = _REG.gauge(
    "kta_partition_eta_seconds",
    "Projected seconds to drain the partition at the current scan rate",
    labelnames=("partition",),
    # Disjoint per-process label sets (see kta_partition_lag).
    merge="max")
SNAPSHOTS_SAVED = _REG.counter(
    "kta_snapshots_saved_total", "Resumable scan snapshots written")
DEGRADED_PARTITIONS = _REG.gauge(
    "kta_scan_degraded_partitions",
    "Partitions dropped from the scan after exhausting their retry budget",
    # Each process counts ITS locally-degraded partitions (the feeds are
    # disjoint), so the cluster-wide figure is the sum, not the max.
    merge="sum")

# -- parallel ingest (parallel/ingest.py) -------------------------------------

INGEST_QUEUE_DEPTH = _REG.gauge(
    "kta_ingest_queue_depth",
    "Staged batches waiting in a parallel-ingest fan-in's queues "
    "(all of the pool's workers; 0 when the merge loop keeps up). "
    "'pool' is the fan-in's first worker id — sharded-mesh controllers "
    "run one pool per data row, so pools are disjoint and the fleet "
    "depth is the sum",
    labelnames=("pool",),
    # Disjoint pools per controller (and per data row): the cluster-wide
    # queue depth is their sum, not the worst one.
    merge="sum")
INGEST_RESOLVED_WORKERS = _REG.gauge(
    "kta_ingest_resolved_workers",
    "Parallel-ingest worker threads the scan resolved for THIS "
    "controller (after auto/partition-count clamping; 1 = sequential). "
    "Controllers feed disjoint partition sets, so the cross-controller "
    "merge (gather_telemetry) sums to the fleet-wide thread count",
    merge="sum")
INGEST_WORKER_RECORDS = _REG.counter(
    "kta_ingest_worker_records_total",
    "Valid records produced per parallel-ingest worker",
    labelnames=("worker",))
INGEST_WORKER_STALL_SECONDS = _REG.counter(
    "kta_ingest_worker_stall_seconds_total",
    "Seconds each parallel-ingest worker spent blocked on its full "
    "fan-in queue (backpressure from the merge loop/device)",
    labelnames=("worker",))
INGEST_WORKER_ACTIVE_SECONDS = _REG.counter(
    "kta_ingest_worker_active_seconds_total",
    "Thread-lifetime seconds per parallel-ingest worker (stream open to "
    "stream exhausted/cancelled).  The denominator for a worker's busy "
    "fraction: busy = (active - stall) / active — a worker whose "
    "partitions drained early must not read as 'stalled' for the rest "
    "of the scan (obs/doctor.py)",
    labelnames=("worker",))

# -- cold segment path (io/segfile.py + io/segstore.py) -----------------------

SEGMENT_FILES_OPENED = _REG.counter(
    "kta_segment_files_opened_total",
    "Segment chunks (.ktaseg) opened by the cold-path catalog")
SEGMENT_BYTES_MAPPED = _REG.counter(
    "kta_segment_bytes_mapped_total",
    "Bytes of segment chunks memory-mapped by the cold-path catalog")
SEGMENT_RECORDS = _REG.counter(
    "kta_segment_records_total",
    "Records read from memory-mapped segment chunks")
SEGMENT_BATCHES = _REG.counter(
    "kta_segment_batches_total",
    "Batches cut from memory-mapped segment chunks")

# -- remote segment tier (io/objstore.py + io/segstore.py) --------------------

SEGSTORE_GETS = _REG.counter(
    "kta_segstore_gets_total",
    "Object-store GET requests the remote segment tier completed, by kind "
    "(list = chunk enumeration, header = catalog header/end-offset range "
    "probes, body = whole-chunk fetches, refetch = the one disambiguating "
    "re-fetch after a classification failure)",
    labelnames=("kind",))
SEGSTORE_BYTES = _REG.counter(
    "kta_segstore_bytes_fetched_total",
    "Bytes fetched from object stores by the remote segment tier "
    "(response bodies of completed GETs)")
SEGSTORE_RETRIES = _REG.counter(
    "kta_segstore_retries_total",
    "Transient object-store request failures retried through the backoff "
    "schedule (resets/timeouts/5xx/truncated or MD5-mismatched bodies)")
SEGSTORE_READAHEAD = _REG.gauge(
    "kta_segstore_readahead_occupancy",
    "Remote chunks currently prefetched (or fetching) ahead of the "
    "consuming ingest streams through the process-wide fetch scheduler "
    "(0..streams x (--segment-readahead + 1))",
    # Each process's streams prefetch disjoint chunks; fleet-wide
    # occupancy is their sum, not the worst stream's.
    merge="sum")
SEGSTORE_CACHE_HITS = _REG.counter(
    "kta_segstore_cache_hits_total",
    "Chunk fetches served from the local segment cache after sha256 "
    "verification (--segment-cache)")
SEGSTORE_CACHE_MISSES = _REG.counter(
    "kta_segstore_cache_misses_total",
    "Chunk fetches the local segment cache could not serve (absent, "
    "unreadable, or poisoned entries)")
SEGSTORE_CACHE_VERIFY_SECONDS = _REG.counter(
    "kta_segstore_cache_verify_seconds_total",
    "Seconds spent sha256-re-hashing cached chunk bytes on cache HITS "
    "(--segment-cache serves nothing unverified).  The warm-re-audit "
    "residual BENCH round 14 measured as 'sha-verify on every hit costs "
    "2.1x' — booked so the claim is attributable from telemetry alone "
    "and the trend doctor can flag verify-bound re-audits")
SEGSTORE_CACHE_HIT_BYTES = _REG.counter(
    "kta_segstore_cache_hit_bytes_total",
    "Chunk bytes served from the local segment cache after sha256 "
    "verification — with the verify-seconds counter, the measured "
    "verify cost per cached byte (the warm-cache residual's ledger)")
SEGSTORE_CACHE_EVICTIONS = _REG.counter(
    "kta_segstore_cache_evictions_total",
    "Cache entries evicted: least-recently-used past --segment-cache-bytes, "
    "plus poisoned entries dropped on detection")
SEGSTORE_FALLBACK = _REG.counter(
    "kta_segstore_fallback_total",
    "Chunk acquisitions that fell back to a direct store fetch, by reason "
    "(cache-poisoned = a cached entry failed sha256 verification, "
    "cache-stale = a verified entry no longer matches the catalog's "
    "header — the archive was re-dumped at the same name and size, "
    "cache-io-error = the cache directory was unreadable/unwritable, "
    "range-ignored = the endpoint answered a ranged GET with the full "
    "object and the requested window was sliced client-side, "
    "etag-not-md5 = a persistent ETag/MD5 mismatch was accepted after a "
    "byte-identical re-fetch — SSE-KMS/SSE-C-shaped ETag) — "
    "a cache bypass is never silent",
    labelnames=("reason",))
SEGSTORE_CACHE_VERIFY_LATCHED = _REG.counter(
    "kta_segstore_cache_verify_latched_total",
    "Cache hits served under the process-lifetime verify latch: the "
    "entry's sha256 was checked once this process and latched as "
    "trusted, so this hit skipped re-hashing (the verify-seconds "
    "counter stands still while this one advances).  Eviction, "
    "re-population, and poison detection all drop the latch, so the "
    "first touch of any on-disk bytes is ALWAYS verified — the PR-14 "
    "never-serve-poison guarantee is unchanged")

# -- process-wide fetch scheduler (io/fetchsched.py) --------------------------

FETCH_SCHED_QUEUE_DEPTH = _REG.gauge(
    "kta_fetch_sched_queue_depth",
    "Fetch requests queued in the process-wide scheduler, not yet "
    "picked up by a worker (demand + speculative).  Persistently "
    "deeper than kta_fetch_sched_inflight = the pool is the "
    "bottleneck — raise --fetch-concurrency",
    # One scheduler per process; fleet-wide backlog is the sum of the
    # per-process queues.
    merge="sum")
FETCH_SCHED_INFLIGHT = _REG.gauge(
    "kta_fetch_sched_inflight",
    "Fetch requests currently executing on scheduler workers "
    "(0..--fetch-concurrency).  Pegged at the pool size with a shallow "
    "queue = the wire, not the scheduler, is the limit",
    # One scheduler per process; fleet-wide in-flight is the sum.
    merge="sum")
FETCH_SCHED_REORDERS = _REG.counter(
    "kta_fetch_sched_reorders_total",
    "Deadline-aware departures from submission order, by reason "
    "(demand-over-speculative = a chunk a consumer is blocked on was "
    "served before earlier-queued speculative read-ahead, "
    "deadline-promotion = a consumer reached a chunk whose speculative "
    "request was still queued and promoted it to demand class)",
    labelnames=("reason",))
FETCH_SCHED_WAIT_SECONDS = _REG.counter(
    "kta_fetch_sched_wait_seconds_total",
    "Cumulative seconds fetch requests spent queued before a scheduler "
    "worker picked them up.  The starvation ledger: high wait with a "
    "deep queue means the pool is undersized, high wait with the pool "
    "pegged and a shallow queue means the wire is saturated "
    "(obs/doctor.py attributes fetch-bound verdicts from exactly this)")
FETCH_SCHED_CANCELLED = _REG.counter(
    "kta_fetch_sched_cancelled_total",
    "Queued fetch requests cancelled before a worker started them: "
    "released chunks (degraded-partition skips), closed streams, and "
    "scheduler shutdown — bytes nobody would have read, not fetched")

# -- fused ingest (packing.FusedPackSink + io/kafka_wire + io/segfile) --------

FUSED_BATCHES = _REG.counter(
    "kta_fused_batches_total",
    "Wire-v4 rows completed by the fused native decode→pack path")
FUSED_RECORDS = _REG.counter(
    "kta_fused_records_total",
    "Records packed by the fused path without a decoded-column "
    "intermediate")
FUSED_FALLBACK = _REG.counter(
    "kta_fused_fallback_total",
    "Records that bypassed the fused decode and entered rows through the "
    "python chain (reason: compressed/legacy frames, per-frame salvage, "
    "python-decoded rows) or skipped fused packing entirely (native shim "
    "disabled/failed, source or backend without fused support)",
    labelnames=("reason",))

# -- packed wire format (packing.py v4/v5; backends book the transfers) -------

WIRE_BYTES = _REG.counter(
    "kta_wire_bytes_total",
    "Packed host→device wire bytes dispatched (buffers as transferred, "
    "superbatch identity padding included)")
WIRE_BYTES_PER_RECORD = _REG.gauge(
    "kta_wire_packed_bytes_per_record",
    "Packed wire bytes per scanned record for the finished scan "
    "(kta_wire_bytes_total delta / records) — the observable v4→v5 "
    "wire saving",
    merge="max")
WIRE_V4_FALLBACK = _REG.counter(
    "kta_wire_v4_fallback_total",
    "Scans that ran the v4 per-record wire format instead of the v5 "
    "combiner rows (reason: env-kill-switch = KTA_WIRE_V4, explicit = "
    "caller pinned v4) — a bypassed combiner is never silent",
    labelnames=("reason",))
ALIVE_PAIRS_RAW = _REG.counter(
    "kta_alive_pairs_raw_total",
    "Per-batch LWW alive-pairs entering the dispatch-level compaction "
    "merge (the compacted path's input side; DESIGN §19)")
ALIVE_PAIRS_EMITTED = _REG.counter(
    "kta_alive_pairs_emitted_total",
    "Merged alive-pairs actually shipped in compacted per-dispatch pair "
    "tables — emitted/raw is the measured compaction ratio the --stats "
    "wire digest reports")
ALIVE_COMPACTION_OFF = _REG.counter(
    "kta_alive_compaction_off_total",
    "Alive-key scans that ran WITHOUT pair compaction (reason: "
    "env-kill-switch = KTA_DISABLE_COMPACTION, explicit = "
    "--alive-compaction off, wire-v4 = the v4 layout keeps per-row "
    "pairs) — a bypassed compaction is never silent",
    labelnames=("reason",))

# -- io/kafka_wire ------------------------------------------------------------

FETCH_REQUESTS = _REG.counter(
    "kta_fetch_requests_total", "Fetch responses read from brokers")
FETCH_BYTES = _REG.counter(
    "kta_fetch_bytes_total", "Record-set bytes carried by fetch responses")
FETCH_SECONDS = _REG.counter(
    "kta_fetch_seconds_total",
    "Seconds spent blocked reading fetch responses off broker sockets "
    "(the wire scan's source-wait side — booked per fetch round, on the "
    "fetching thread, mirroring the 'fetch' trace span)")
DECODE_SECONDS = _REG.counter(
    "kta_decode_seconds_total",
    "Seconds spent in record-set decode: the native whole-response "
    "pre-decode pass and the fused decode→pack appends (booked per fetch "
    "round; python per-frame fallback decoding is not timed — it shares "
    "the round with masking/state bookkeeping)")
FETCH_ERRORS = _REG.counter(
    "kta_fetch_errors_total",
    "Per-partition Kafka protocol errors in fetch responses")
TRANSPORT_FAILURES = _REG.counter(
    "kta_transport_failures_total",
    "Leader fetch rounds lost to resets/timeouts/truncated streams")
CONNECTION_EVICTIONS = _REG.counter(
    "kta_connection_evictions_total",
    "Broker connections closed as dead or desynced")
METADATA_RELOADS = _REG.counter(
    "kta_metadata_reloads_total",
    "Cluster metadata refreshes attempted during recovery")

# -- corruption (io/kafka_wire + io/kafka_codec) ------------------------------

CORRUPT_FRAMES = _REG.counter(
    "kta_corrupt_frames_total",
    "Frames classified deterministically corrupt and handled by policy",
    labelnames=("kind",))
CORRUPT_RECORDS = _REG.counter(
    "kta_corrupt_records_total",
    "Header-claimed records inside corrupt frames (0 when unreadable)")
CORRUPT_BYTES = _REG.counter(
    "kta_corrupt_bytes_total", "Raw bytes of corrupt frames skipped")
CORRUPT_QUARANTINED = _REG.counter(
    "kta_corrupt_quarantined_total",
    "Corrupt frames spooled to the quarantine directory")
CORRUPT_REFETCHES = _REG.counter(
    "kta_corrupt_refetches_total",
    "Suspect spans re-fetched once to rule out an in-flight bit flip")

# -- log mutation (io/kafka_wire + checkpoint resume) -------------------------

LOG_LOST_RECORDS = _REG.counter(
    "kta_log_lost_records_total",
    "Records the mutating log made unreachable before the scan read them "
    "(reason: retention = expired below the cursor, truncation = removed "
    "by an unclean leader election, resume-below-log-start = expired "
    "while the scan was checkpointed)",
    labelnames=("reason",))
LOG_LOST_RANGES = _REG.counter(
    "kta_log_lost_ranges_total",
    "Contiguous lost offset ranges booked on kta_log_lost_records_total, "
    "plus re-anchor-regressed: OFFSET_OUT_OF_RANGE recoveries whose "
    "earliest-offset lookup failed or regressed (no records booked — the "
    "cursor holds and the round counts as non-progressing)",
    labelnames=("reason",))
LOG_EPOCH_FENCES = _REG.counter(
    "kta_log_epoch_fences_total",
    "FENCED_LEADER_EPOCH / UNKNOWN_LEADER_EPOCH fetch errors (the broker "
    "rejected our tracked leader epoch; metadata is refreshed and the "
    "divergence check runs before the cursor moves)")
LOG_DIVERGENCE_CHECKS = _REG.counter(
    "kta_log_divergence_checks_total",
    "OffsetForLeaderEpoch divergence probes issued on epoch regression "
    "or resume-epoch mismatch (each either clears the cursor or books a "
    "truncation loss)")
LOG_WATERMARK_REGRESSIONS = _REG.counter(
    "kta_log_watermark_regressions_total",
    "Follow-mode end-watermark regressions (stale replica / unclean "
    "election): the service holds the previous head instead of scanning "
    "backwards")

# -- io/retry -----------------------------------------------------------------

BACKOFF_SLEEPS = _REG.counter(
    "kta_backoff_sleeps_total", "Retry/backoff sleeps taken")
BACKOFF_SLEEP_SECONDS = _REG.counter(
    "kta_backoff_sleep_seconds_total", "Seconds spent in retry backoff")
RETRY_BUDGET_EXHAUSTIONS = _REG.counter(
    "kta_retry_budget_exhaustions_total",
    "Partitions whose consecutive-transport-failure budget ran out")

# -- superbatch dispatch (backends/base.py DispatchQueue) ---------------------

DISPATCH_INFLIGHT = _REG.gauge(
    "kta_dispatch_inflight",
    "Superbatch dispatches launched but not yet retired (bounded by "
    "--dispatch-depth; 0 when the device keeps up)",
    # Each process runs its own dispatch queue over its own device rows;
    # the fleet's in-flight figure is their sum, not the worst one.
    merge="sum")
DISPATCH_THROTTLE_SECONDS = _REG.counter(
    "kta_dispatch_throttle_seconds_total",
    "Seconds the drive loop spent blocked in DispatchQueue.throttle "
    "waiting for an in-flight superbatch to retire — the backpressure "
    "wait at the launch site, and the one signal that directly separates "
    "dispatch-bound from ingest-bound scans (booked unconditionally, "
    "flight recorder on or off)")
SUPERBATCH_FILL = _REG.gauge(
    "kta_superbatch_fill",
    "Packed batches accumulated toward the next superbatch dispatch "
    "(0..K; the staging fill level of the current stager ring slot)",
    # Same-quantity gauge across processes (every controller fills its
    # rows in lockstep rounds): report the fleet's fullest pending stack.
    merge="max")
STAGER_SLOTS = _REG.counter(
    "kta_stager_slots_total",
    "Superbatch stager ring slots handed out for assembly "
    "(packing.SuperbatchStager.next_slot) — with kta_dispatch_inflight, "
    "the ring-occupancy signal: slots in use = in-flight dispatches + "
    "the slot being assembled")
SUPERBATCH_SIZE = _REG.histogram(
    "kta_superbatch_size",
    "Packed batches folded per device dispatch (K, or the partial tail)",
    buckets=(1, 2, 4, 8, 16, 32, 64))
DISPATCH_SECONDS = _REG.histogram(
    "kta_dispatch_seconds",
    "Per-dispatch latency: superbatch launch to fold completion "
    "(includes device queue time at depth > 1)",
    buckets=LATENCY_BUCKETS_S)

# -- backends -----------------------------------------------------------------

BACKEND_STEP_SECONDS = _REG.histogram(
    "kta_backend_step_seconds",
    "Backend update dispatch latency (async backends: dispatch only)",
    buckets=LATENCY_BUCKETS_S)
BACKEND_FINALIZE_SECONDS = _REG.histogram(
    "kta_backend_finalize_seconds",
    "Backend finalize (device sync + collective merge) latency",
    buckets=LATENCY_BUCKETS_S)

# -- follow-mode service (serve/follow.py + io/kafka_wire.py) -----------------

FOLLOW_POLLS = _REG.counter(
    "kta_follow_polls_total",
    "Watermark re-polls the follow service took at the head")
FOLLOW_PASSES = _REG.counter(
    "kta_follow_passes_total",
    "Fold passes the follow service ran: the initial catch-up pass, one "
    "per poll that found new records, and the final shutdown commit")
FOLLOW_LAG = _REG.gauge(
    "kta_follow_lag_records",
    "Records between the follow cursor and the latest polled end "
    "watermarks, summed over this process's partitions — recomputed "
    "against the MOVING head every poll, unlike the per-partition "
    "kta_partition_lag gauges a batch scan freezes at its start snapshot",
    # Controllers feed disjoint partition sets; fleet lag is their sum.
    merge="sum")
WATERMARK_REFRESH_FAILURES = _REG.counter(
    "kta_watermark_refresh_failures_total",
    "Watermark re-polls that exhausted the transport retry budget and "
    "kept the previous snapshot (the service retries next poll)")
REPORT_SNAPSHOTS = _REG.counter(
    "kta_report_snapshots_total",
    "Point-in-time report documents published for /report.json (one per "
    "follow poll boundary; the HTTP handler only ever reads the latest)")

# -- the serving plane (obs/exporters.py + serve/push.py, DESIGN §26) ---------

SERVE_REQUESTS = _REG.counter(
    "kta_serve_requests_total",
    "HTTP requests served, by route and status code — 304s, JSON error "
    "bodies, and SSE stream opens each book exactly one row, so the "
    "read path's full traffic mix is reconstructible from the counter",
    labelnames=("route", "status"))
SERVE_NOT_MODIFIED = _REG.counter(
    "kta_serve_not_modified_total",
    "Conditional requests answered 304 Not Modified (If-None-Match "
    "matched the published ETag): zero body bytes on the wire — the "
    "read path's cache-hit count")
SERVE_BYTES = _REG.counter(
    "kta_serve_bytes_total",
    "Response body bytes actually sent, by content encoding (gzip = the "
    "publish-time-compressed variant; identity = raw JSON/text, which "
    "is also where a gzip-requesting client lands when the snapshot "
    "stored no gzip variant — the encoding fallback is visible here, "
    "never silent; sse = streamed event frames)",
    labelnames=("encoding",))
SERVE_SSE_SUBSCRIBERS = _REG.gauge(
    "kta_serve_sse_subscribers",
    "Currently connected /events subscribers (serve/push.py)",
    # Each process serves its own subscriber set; a federated scrape
    # wants the fleet-wide audience.
    merge="sum")
SERVE_SSE_DROPPED = _REG.counter(
    "kta_serve_sse_dropped_total",
    "SSE subscriber streams closed by the publisher, by reason: "
    "slow-client (bounded per-subscriber queue overflowed — eviction "
    "over blocking, the backpressure contract) or shutdown (publisher "
    "stopped with the session) — every eviction books exactly one "
    "reason, never silent",
    labelnames=("reason",))

# -- fleet mode (fleet/discovery.py + fleet/scheduler.py + fleet/service.py) --

FLEET_TOPICS_DISCOVERED = _REG.counter(
    "kta_fleet_topics_discovered_total",
    "Topics returned by all-topics cluster metadata requests (every "
    "discovery pass counts the full listing, pre-filter — re-discovery "
    "polls make this grow by roughly the cluster's topic count per poll)")
FLEET_ADMISSIONS = _REG.counter(
    "kta_fleet_admissions_total",
    "Admission decisions the fleet scheduler took, by reason: "
    "admitted-seed (initial greedy-LPT wave placement), admitted-poll "
    "(a lagging topic granted a pass), deferred-budget (ready but the "
    "concurrency/worker budget was spent), skipped-empty (no lag), "
    "released (scan finished, budget returned) — every decision books "
    "exactly one reason, so the admission trace is reconstructible from "
    "the counter alone (tools/lint.sh rule 10).  'instance' is the "
    "analyzer instance id ('solo' outside a multi-instance fleet) so a "
    "federated scrape attributes decisions to the instance that took them",
    labelnames=("reason", "instance"))
FLEET_TOPICS_ACTIVE = _REG.gauge(
    "kta_fleet_topics_active",
    "Per-topic scans currently admitted and holding budget in this "
    "instance's fleet service",
    labelnames=("instance",),
    # One fleet service per instance; instances own disjoint topic sets
    # (lease-arbitrated), so the cluster-wide figure is the sum.
    merge="sum")
FLEET_TOPIC_LAG = _REG.gauge(
    "kta_fleet_topic_lag_records",
    "Records between a fleet topic's cursor and its latest polled end "
    "watermarks (the per-topic twin of kta_follow_lag_records; admission "
    "weight input)",
    labelnames=("topic", "instance"),
    # Every instance POLLS every topic, but only the lease holder
    # reports its lag (non-holders pin 0 — fleet/service._poll_topic),
    # so the fleet-wide sum counts each topic's lag exactly once.
    merge="sum")
FLEET_REBALANCES = _REG.counter(
    "kta_fleet_rebalances_total",
    "Budget rebalances the fleet scheduler applied between polls "
    "(doctor-verdict driven: ingest-bound scans shed dispatch share and "
    "gain workers freed from dispatch-bound scans)",
    labelnames=("instance",))
FLEET_FAILOVERS = _REG.counter(
    "kta_fleet_failovers_total",
    "Topic ownership takeovers: this instance acquired a topic lease "
    "whose previous holder was a DIFFERENT instance (expired or "
    "released) — the crash-failover trace (fleet/lease.py; DESIGN §23)",
    labelnames=("instance",))

# -- topic ownership leases (fleet/lease.py) ----------------------------------

LEASE_ACQUISITIONS = _REG.counter(
    "kta_lease_acquisitions_total",
    "Lease acquisition attempts by outcome: acquired (fresh or "
    "re-entrant grant), takeover (expired/released lease of ANOTHER "
    "instance claimed — also books kta_fleet_failovers_total), "
    "held-elsewhere (an unexpired lease blocks this instance), "
    "lost-race (a competing writer landed between read and "
    "conditional write), released (a held lease handed back; epoch "
    "retained in the store), store-error (the lease store was "
    "unreachable after retries) — every acquire/release decision "
    "books exactly one outcome (tools/lint.sh rule 13); never silent",
    labelnames=("outcome", "instance"))
LEASE_RENEWALS = _REG.counter(
    "kta_lease_renewals_total",
    "Lease renewal attempts by outcome: renewed (expiry extended "
    "through the store), deferred (transient store outage — the lease "
    "is still locally unexpired, so the holder keeps scanning and "
    "retries next boundary rather than self-fencing early)",
    labelnames=("outcome", "instance"))
LEASE_LOSSES = _REG.counter(
    "kta_lease_losses_total",
    "Leases this instance held and LOST without releasing: fenced (the "
    "store shows a newer epoch/different owner, or a stale-epoch "
    "checkpoint write was refused — checkpoint.py books the refusal "
    "here too) or expired (the local TTL ran out before any renewal "
    "succeeded).  The zombie-fencing trace; fires the lease_lost alert",
    labelnames=("instance",))
LEASE_HELD = _REG.gauge(
    "kta_lease_held",
    "1 while this instance holds the topic's ownership lease, 0 once "
    "released or lost (fleet/lease.py)",
    labelnames=("topic", "instance"),
    # (topic, instance) label sets are disjoint across processes by
    # construction — at most one holder per topic; sum unions them and
    # totals the cluster's currently-owned topics.
    merge="sum")

# -- flight recorder (obs/flight.py) ------------------------------------------

FLIGHT_SAMPLES = _REG.counter(
    "kta_flight_samples_total",
    "Occupancy samples the flight recorder took (--flight-record) — the "
    "recorder's own cost stays auditable in the data it records")

# -- telemetry history (obs/history.py) ---------------------------------------

HISTORY_SAMPLES = _REG.counter(
    "kta_history_samples_total",
    "Sample rows appended to the disk-backed telemetry history "
    "(--history-bytes; tier-0 appends — downsampled tier rows are "
    "derived, not re-counted)")
HISTORY_ROTATIONS = _REG.counter(
    "kta_history_segment_rotations_total",
    "History segment files sealed by atomic rotation (all tiers) — with "
    "kta_history_bytes, the store's write/retention cadence")
HISTORY_BYTES = _REG.gauge(
    "kta_history_bytes",
    "Bytes the telemetry history currently holds on disk (all tiers, "
    "open segments included; bounded by --history-bytes)",
    # One store per process; a fleet of processes holds disjoint stores.
    merge="sum")

# -- health / alerting (obs/health.py) ----------------------------------------

HEALTH_EVALUATIONS = _REG.counter(
    "kta_health_evaluations_total",
    "Alert-engine evaluation passes (poll boundaries + the rate-limited "
    "heartbeat hook) — /healthz serves 503 until this first moves")
ALERTS_FIRING = _REG.gauge(
    "kta_alerts_firing",
    "Alerts currently ACTIVE (firing or in resolve hysteresis) per "
    "rule; under fleet per-topic rules this counts the topics the rule "
    "is firing for",
    labelnames=("rule",),
    # Each process's engine fires over its own scan; fleet-wide active
    # alerts are the sum, not the worst process's.
    merge="sum")
ALERTS_TRANSITIONS = _REG.counter(
    "kta_alerts_transitions_total",
    "Alert state-machine transitions by rule and entered state "
    "(ok/pending/firing/resolving) — every state change books exactly "
    "one row, so the alert trace is reconstructible from the counter "
    "alone (tools/lint.sh rule 12); no silent state changes",
    labelnames=("rule", "state"))
