"""The service HTTP read path (Prometheus scrape endpoint + dashboards).

A threaded stdlib HTTP/1.1 server exposing ``/metrics`` (text exposition
v0.0.4) while the scan runs — scrapes render a fresh registry snapshot
per request, so a dashboard pointed at ``--metrics-port`` watches
throughput, retries, and per-partition lag live.  Port 0 binds an
ephemeral port (``.port`` reports the bound one — tests use this).

Every snapshot route follows the read-path contract (DESIGN.md §26):

- **Conditional.**  ``/report.json``, ``/healthz``, ``/history``, and
  ``/flight`` carry a strong ``ETag`` minted by the publishing side (the
  snapshot seq, evaluation count, history epoch+append-seq, flight
  sample count); ``If-None-Match`` answers 304 with ZERO body bytes, so
  a dashboard polling at 1 Hz pays one full body per publish, not per
  request.
- **Pre-encoded.**  ``/report.json`` serves the gzip variant stored at
  publish time (serve/state.py's atomic ``(raw, gzipped, etag)`` triple)
  when ``Accept-Encoding`` allows — the handler never compresses,
  serializes, or locks anything of its own (tools/lint.sh rule 9,
  extended): per-request cost is O(headers).
- **Push.**  ``/events`` streams one Server-Sent-Events frame per report
  publish (serve/push.py): bounded per-subscriber queues, slow-client
  eviction booked on ``kta_serve_sse_dropped_total``, catch-up frame on
  (re)connect — dashboards stop polling entirely.
- **Booked.**  Every response books ``kta_serve_requests_total{route,
  status}`` and its body bytes by encoding; 304s book
  ``kta_serve_not_modified_total``.  No silent traffic.

``/healthz`` (obs/health.py) is the k8s-shaped liveness probe: 200
while no alert rule is active, 503 with the firing-rule JSON otherwise
(503 before the first evaluation; 404 without an engine).  ``/history``
(obs/history.py) serves windowed queries over the disk-backed telemetry
history while ``--history-bytes`` is active (404 otherwise) —
``?max_points=`` prices the query from the RRD tiers on the store side.
All error responses are JSON bodies with exact ``Content-Length`` so
HTTP/1.1 keep-alive framing survives every status code.
"""

from __future__ import annotations

import logging
import queue as _queue
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from kafka_topic_analyzer_tpu.config import DEFAULT_SERVE
from kafka_topic_analyzer_tpu.obs import metrics as obs_metrics
from kafka_topic_analyzer_tpu.obs.registry import (
    MetricsRegistry,
    default_registry,
    render_prometheus,
)

log = logging.getLogger(__name__)

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

#: Cache policy for the snapshot routes: caches may store the body but
#: must revalidate (the whole point of the strong ETags — a 1 Hz poller
#: pays 304s between publishes).
CACHE_CONTROL = "no-cache"

#: Seconds between ``: keepalive`` comment frames on an idle ``/events``
#: stream (config.ServeConfig) — keeps intermediaries from timing the
#: connection out and gives the handler a boundary to notice a closed
#: stream.
SSE_KEEPALIVE_S = DEFAULT_SERVE.sse_keepalive_s


class _MetricsHandler(BaseHTTPRequestHandler):
    #: HTTP/1.1: persistent connections by default — a 1 Hz dashboard
    #: poller reuses one socket instead of a TCP+handshake per request.
    #: Every response below therefore carries an exact Content-Length
    #: (or is a body-less 304 / Connection: close SSE stream).
    protocol_version = "HTTP/1.1"

    # -- response plumbing (headers only — rule 9: no json/gzip/locks) -------

    def _book(self, route: str, code: int) -> None:
        obs_metrics.SERVE_REQUESTS.labels(
            route=route, status=str(code)
        ).inc()

    def _send_body(
        self,
        route: str,
        body: bytes,
        content_type: str,
        code: int = 200,
        etag: "Optional[str]" = None,
        cache: "Optional[str]" = None,
        encoding: "Optional[str]" = None,
        vary: bool = False,
    ) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        if etag is not None:
            self.send_header("ETag", etag)
        if cache is not None:
            self.send_header("Cache-Control", cache)
        if encoding is not None:
            self.send_header("Content-Encoding", encoding)
        if vary:
            self.send_header("Vary", "Accept-Encoding")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)
        self._book(route, code)
        obs_metrics.SERVE_BYTES.labels(
            encoding=encoding or "identity"
        ).inc(len(body))

    def _error(self, route: str, code: int, message: str) -> None:
        """JSON error body with exact framing headers — keep-alive must
        survive 404/503/400 (the old HTML send_error dates from the
        metrics-only server)."""
        body = ('{"error": "' + message + '"}').encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)
        self._book(route, code)
        obs_metrics.SERVE_BYTES.labels(encoding="identity").inc(len(body))

    # -- conditional GET ------------------------------------------------------

    @staticmethod
    def _etag_match(if_none_match: str, *etags: "Optional[str]") -> bool:
        """RFC 9110 §13.1.2 weak comparison over a comma list; ``*``
        matches any current representation."""
        if if_none_match.strip() == "*":
            return True
        cand = set()
        for part in if_none_match.split(","):
            part = part.strip()
            cand.add(part)
            if part.startswith("W/"):
                cand.add(part[2:])
        return any(e is not None and e in cand for e in etags)

    def _not_modified(
        self,
        route: str,
        etag: str,
        *alternates: "Optional[str]",
        cache: "Optional[str]" = CACHE_CONTROL,
        vary: bool = False,
    ) -> bool:
        """Answer 304 (zero body bytes) if the client's If-None-Match
        covers any current representation of this resource.  All
        encodings of one seq carry the same content, so matching either
        variant's validator is exact, not optimistic."""
        inm = self.headers.get("If-None-Match")
        if inm is None or not self._etag_match(inm, etag, *alternates):
            return False
        self.send_response(304)
        self.send_header("ETag", etag)
        if cache is not None:
            self.send_header("Cache-Control", cache)
        if vary:
            self.send_header("Vary", "Accept-Encoding")
        self.send_header("Content-Length", "0")
        self.end_headers()
        self._book(route, 304)
        obs_metrics.SERVE_NOT_MODIFIED.inc()
        return True

    def _accepts_gzip(self) -> bool:
        ae = self.headers.get("Accept-Encoding", "")
        for part in ae.split(","):
            token, _, params = part.strip().partition(";")
            if token.strip().lower() not in ("gzip", "x-gzip", "*"):
                continue
            q = 1.0
            for p in params.split(";"):
                p = p.strip().lower()
                if p.startswith("q="):
                    try:
                        q = float(p[2:])
                    except ValueError:
                        q = 0.0
            if q > 0:
                return True
        return False

    # -- routes ---------------------------------------------------------------

    def do_GET(self) -> None:  # noqa: N802 (stdlib handler contract)
        path, _, query = self.path.partition("?")
        if path == "/healthz":
            # Liveness probe (obs/health.py): 200 while no alert is
            # active, 503 with the pre-serialized firing-rule JSON
            # otherwise, 503 before the first evaluation (an unevaluated
            # service must not claim liveness), 404 when no alert engine
            # runs at all.  The handler reads ONE snapshot accessor —
            # serialization + validator minting happened on the
            # evaluating side (rule 9).
            from kafka_topic_analyzer_tpu.obs import health as _health

            eng = _health.active()
            if eng is None:
                self._error(
                    path, 404,
                    "no alert engine (run a scan with --metrics-port, "
                    "--follow, or --fleet)",
                )
                return
            hz = eng.healthz_entry()
            if hz is None:
                # Health-doc-shaped so pollers parsing the body see an
                # empty firing set, not a foreign error schema.
                self._send_body(
                    path,
                    b'{"error": "health not yet evaluated", "firing": []}',
                    "application/json",
                    code=503,
                )
                return
            code, body, etag = hz
            if self._not_modified(path, etag):
                return
            self._send_body(
                path, body, "application/json", code=code, etag=etag,
                cache=CACHE_CONTROL,
            )
            return
        if path == "/history":
            # Windowed telemetry-history query (obs/history.py):
            # ``?t0=&t1=`` bound the window (epoch seconds), ``tracks=``
            # selects a comma list, ``max_points=`` prices the answer
            # from the RRD tiers.  The etag/bytes accessors read the
            # store's in-memory mirror under the store's own lock —
            # never a drive-loop lock, and the handler serializes
            # nothing (rule 9).
            from urllib.parse import parse_qs

            from kafka_topic_analyzer_tpu.obs import history as _history

            store = _history.active()
            if store is None:
                self._error(
                    path, 404,
                    "no telemetry history (run with --history-bytes)",
                )
                return
            qs = parse_qs(query)
            try:
                t0 = float(qs["t0"][0]) if "t0" in qs else None
                t1 = float(qs["t1"][0]) if "t1" in qs else None
            except ValueError:
                self._error(path, 400, "t0/t1 must be epoch seconds")
                return
            try:
                max_points = (
                    int(qs["max_points"][0]) if "max_points" in qs else None
                )
                if max_points is not None and max_points < 1:
                    raise ValueError
            except ValueError:
                self._error(
                    path, 400, "max_points must be a positive integer"
                )
                return
            tracks = None
            if "tracks" in qs:
                tracks = [
                    t for t in qs["tracks"][0].split(",") if t
                ]
            etag = store.window_etag(t0, t1, tracks, max_points)
            if self._not_modified(path, etag):
                return
            body, etag = store.window_bytes(t0, t1, tracks, max_points)
            self._send_body(
                path, body, "application/json", etag=etag,
                cache=CACHE_CONTROL,
            )
            return
        if path == "/report.json":
            # Follow/fleet point-in-time report (serve/state.py).  The
            # handler only ever reads the latest PRE-SERIALIZED,
            # PRE-ENCODED triple through the designated snapshot
            # accessor — body, gzip variant, and validator all belong to
            # one seq by construction, so no reader racing a publish can
            # see a torn response (tools/lint.sh rule 9; DESIGN §26).
            # ``?topic=<name>`` selects a fleet topic's document;
            # without it, the main slot (single-topic report, or the
            # fleet's cluster rollup) is served.
            from urllib.parse import parse_qs

            from kafka_topic_analyzer_tpu.serve import state as _serve_state

            svc = _serve_state.active()
            if svc is None:
                self._error(
                    path, 404,
                    "no follow/fleet service (run with --follow/--fleet)",
                )
                return
            topic = (parse_qs(query).get("topic") or [None])[0]
            entry = svc.entry(topic)
            if entry is None and topic is not None:
                self._error(
                    path, 404,
                    f"no report for topic {topic!r} (unknown topic, or "
                    "its first fleet pass has not finished)",
                )
                return
            if entry is None:
                self._error(
                    path, 503,
                    "report not yet assembled (first pass running)",
                )
                return
            gz = entry.gzipped is not None and self._accepts_gzip()
            etag = entry.etag_gzip if gz else entry.etag
            if self._not_modified(
                path, etag,
                entry.etag, entry.etag_gzip, vary=True,
            ):
                return
            self._send_body(
                path,
                entry.gzipped if gz else entry.body,
                "application/json",
                etag=etag,
                cache=CACHE_CONTROL,
                encoding="gzip" if gz else None,
                vary=True,
            )
            return
        if path == "/flight":
            from kafka_topic_analyzer_tpu.obs import flight as _flight

            rec = _flight.active()
            if rec is None:
                self._error(
                    path, 404,
                    "no flight recorder (run with --flight-record)",
                )
                return
            if self._not_modified(path, rec.series_etag()):
                return
            body, etag = rec.series_bytes()
            self._send_body(
                path, body, "application/json", etag=etag,
                cache=CACHE_CONTROL,
            )
            return
        if path == "/events":
            # SSE push channel (serve/push.py): one frame per report
            # publish.  The stream is close-delimited (no Content-Length
            # can exist), every frame was formatted on the publisher's
            # thread, and the handler's only state is its own bounded
            # queue — it blocks on frames, never on fold state.
            from kafka_topic_analyzer_tpu.serve import push as _push

            pub = _push.active()
            if pub is None:
                self._error(
                    path, 404, "no SSE publisher (run with --sse)"
                )
                return
            sub = pub.subscribe()
            try:
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-store")
                self.send_header("Connection", "close")
                self.close_connection = True
                self.end_headers()
                self._book(path, 200)
                self.wfile.write(b": stream open\n\n")
                self.wfile.flush()
                while True:
                    try:
                        frame = sub.next_frame(timeout=SSE_KEEPALIVE_S)
                    except _queue.Empty:
                        self.wfile.write(b": keepalive\n\n")
                        self.wfile.flush()
                        continue
                    if frame is None:
                        break  # evicted or publisher shutdown
                    self.wfile.write(frame)
                    self.wfile.flush()
                    obs_metrics.SERVE_BYTES.labels(encoding="sse").inc(
                        len(frame)
                    )
            except (BrokenPipeError, ConnectionResetError, OSError):
                pass  # client went away; unsubscribe below books nothing
            finally:
                pub.unsubscribe(sub)
            return
        if path not in ("/metrics", "/"):
            self._error(
                "other", 404,
                "try /metrics, /flight, /history, /healthz, "
                "/report.json, or /events",
            )
            return
        body = render_prometheus(self.server.registry.snapshot()).encode()
        self._send_body("/metrics", body, CONTENT_TYPE)

    def log_message(self, format: str, *args) -> None:
        log.debug("metrics scrape: " + format, *args)


class _Server(ThreadingHTTPServer):
    daemon_threads = True
    registry: MetricsRegistry


class PrometheusExporter:
    """Serve ``registry`` on ``http://host:port/metrics`` from a daemon
    thread until ``close()``."""

    def __init__(
        self,
        port: int,
        registry: "Optional[MetricsRegistry]" = None,
        host: str = "127.0.0.1",
    ):
        self._server = _Server((host, port), _MetricsHandler)
        self._server.registry = (
            registry if registry is not None else default_registry()
        )
        self.host = host
        self.port = int(self._server.server_address[1])
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="kta-metrics-exporter",
            daemon=True,
        )
        self._thread.start()
        log.info("serving Prometheus metrics on http://%s:%d/metrics",
                 host, self.port)

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5)
