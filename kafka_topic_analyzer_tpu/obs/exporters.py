"""Prometheus scrape endpoint for the metrics registry.

A threaded stdlib HTTP server exposing ``/metrics`` (text exposition
v0.0.4) while the scan runs — scrapes render a fresh registry snapshot
per request, so a dashboard pointed at ``--metrics-port`` watches
throughput, retries, and per-partition lag live.  Port 0 binds an
ephemeral port (``.port`` reports the bound one — tests use this).
"""

from __future__ import annotations

import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from kafka_topic_analyzer_tpu.obs.registry import (
    MetricsRegistry,
    default_registry,
    render_prometheus,
)

log = logging.getLogger(__name__)

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class _MetricsHandler(BaseHTTPRequestHandler):
    def do_GET(self) -> None:  # noqa: N802 (stdlib handler contract)
        if self.path.split("?", 1)[0] not in ("/metrics", "/"):
            self.send_error(404, "try /metrics")
            return
        body = render_prometheus(self.server.registry.snapshot()).encode()
        self.send_response(200)
        self.send_header("Content-Type", CONTENT_TYPE)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, format: str, *args) -> None:
        log.debug("metrics scrape: " + format, *args)


class _Server(ThreadingHTTPServer):
    daemon_threads = True
    registry: MetricsRegistry


class PrometheusExporter:
    """Serve ``registry`` on ``http://host:port/metrics`` from a daemon
    thread until ``close()``."""

    def __init__(
        self,
        port: int,
        registry: "Optional[MetricsRegistry]" = None,
        host: str = "127.0.0.1",
    ):
        self._server = _Server((host, port), _MetricsHandler)
        self._server.registry = (
            registry if registry is not None else default_registry()
        )
        self.host = host
        self.port = int(self._server.server_address[1])
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="kta-metrics-exporter",
            daemon=True,
        )
        self._thread.start()
        log.info("serving Prometheus metrics on http://%s:%d/metrics",
                 host, self.port)

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5)
