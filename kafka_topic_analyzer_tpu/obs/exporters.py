"""Prometheus scrape endpoint for the metrics registry.

A threaded stdlib HTTP server exposing ``/metrics`` (text exposition
v0.0.4) while the scan runs — scrapes render a fresh registry snapshot
per request, so a dashboard pointed at ``--metrics-port`` watches
throughput, retries, and per-partition lag live.  Port 0 binds an
ephemeral port (``.port`` reports the bound one — tests use this).

``/flight`` serves the flight recorder's ring-buffered occupancy time
series as JSON while ``--flight-record`` is active (404 otherwise):
Prometheus scrapes sample the *instant*; the flight series carries the
whole scan's per-stage history at the recorder's resolution, which is
what the doctor's windowed verdicts and any post-hoc notebook need.

``/report.json`` serves the follow service's point-in-time report (same
schema as ``--json``) while ``--follow`` runs (404 otherwise): the drive
loop publishes a pre-serialized document at every poll boundary
(serve/state.py), and the handler reads only that latest snapshot — the
rule 9 lock-discipline boundary that keeps a slow scrape from ever
stalling ingest.

``/healthz`` (obs/health.py) is the k8s-shaped liveness probe: 200
while no alert rule is active, 503 with the firing-rule JSON otherwise
(503 before the first evaluation; 404 without an engine).  ``/history``
(obs/history.py) serves windowed queries over the disk-backed telemetry
history while ``--history-bytes`` is active (404 otherwise).  Both
follow the same rule-9 discipline: pre-published snapshots only.
"""

from __future__ import annotations

import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from kafka_topic_analyzer_tpu.obs.registry import (
    MetricsRegistry,
    default_registry,
    render_prometheus,
)

log = logging.getLogger(__name__)

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class _MetricsHandler(BaseHTTPRequestHandler):
    def _respond(
        self, body: bytes, content_type: str, code: int = 200
    ) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:  # noqa: N802 (stdlib handler contract)
        path, _, query = self.path.partition("?")
        if path == "/healthz":
            # Liveness probe (obs/health.py): 200 while no alert is
            # active, 503 with the pre-serialized firing-rule JSON
            # otherwise, 503 before the first evaluation (an unevaluated
            # service must not claim liveness), 404 when no alert engine
            # runs at all.  The handler reads ONE snapshot accessor —
            # serialization happened on the evaluating side (rule 9).
            from kafka_topic_analyzer_tpu.obs import health as _health

            eng = _health.active()
            if eng is None:
                self.send_error(
                    404,
                    "no alert engine (run a scan with --metrics-port, "
                    "--follow, or --fleet)",
                )
                return
            hz = eng.healthz()
            if hz is None:
                self.send_error(
                    503, "health not yet evaluated (first evaluation "
                    "pending)"
                )
                return
            code, body = hz
            self._respond(body, "application/json", code=code)
            return
        if path == "/history":
            # Windowed telemetry-history query (obs/history.py):
            # ``?t0=&t1=`` bound the window (epoch seconds), ``tracks=``
            # selects a comma list.  The ``window`` accessor reads the
            # store's in-memory mirror under the store's own lock —
            # never a drive-loop lock (rule 9).
            import json
            from urllib.parse import parse_qs

            from kafka_topic_analyzer_tpu.obs import history as _history

            store = _history.active()
            if store is None:
                self.send_error(
                    404, "no telemetry history (run with --history-bytes)"
                )
                return
            qs = parse_qs(query)
            try:
                t0 = float(qs["t0"][0]) if "t0" in qs else None
                t1 = float(qs["t1"][0]) if "t1" in qs else None
            except ValueError:
                self.send_error(400, "t0/t1 must be epoch seconds")
                return
            tracks = None
            if "tracks" in qs:
                tracks = [
                    t for t in qs["tracks"][0].split(",") if t
                ]
            body = json.dumps(store.window(t0, t1, tracks)).encode()
            self._respond(body, "application/json")
            return
        if path == "/report.json":
            # Follow/fleet point-in-time report (serve/state.py).  The
            # handler only ever reads the latest PRE-SERIALIZED document
            # through the designated snapshot accessor — it must never
            # call into the drive loop or take fold-state locks, so a
            # slow scrape cannot stall ingest (tools/lint.sh rule 9).
            # ``?topic=<name>`` selects a fleet topic's document; without
            # it, the main slot (single-topic report, or the fleet's
            # cluster rollup) is served.
            from urllib.parse import parse_qs

            from kafka_topic_analyzer_tpu.serve import state as _serve_state

            svc = _serve_state.active()
            if svc is None:
                self.send_error(
                    404, "no follow/fleet service (run with --follow/--fleet)"
                )
                return
            topic = (parse_qs(query).get("topic") or [None])[0]
            body = svc.report_bytes(topic)
            if body is None and topic is not None:
                self.send_error(
                    404,
                    f"no report for topic {topic!r} (unknown topic, or "
                    "its first fleet pass has not finished)",
                )
                return
            if body is None:
                self.send_error(
                    503, "report not yet assembled (first pass running)"
                )
                return
            self._respond(body, "application/json")
            return
        if path == "/flight":
            import json

            from kafka_topic_analyzer_tpu.obs import flight as _flight

            rec = _flight.active()
            if rec is None:
                self.send_error(
                    404, "no flight recorder (run with --flight-record)"
                )
                return
            self._respond(
                json.dumps(rec.series()).encode(), "application/json"
            )
            return
        if path not in ("/metrics", "/"):
            self.send_error(
                404,
                "try /metrics, /flight, /history, /healthz, or "
                "/report.json",
            )
            return
        body = render_prometheus(self.server.registry.snapshot()).encode()
        self._respond(body, CONTENT_TYPE)

    def log_message(self, format: str, *args) -> None:
        log.debug("metrics scrape: " + format, *args)


class _Server(ThreadingHTTPServer):
    daemon_threads = True
    registry: MetricsRegistry


class PrometheusExporter:
    """Serve ``registry`` on ``http://host:port/metrics`` from a daemon
    thread until ``close()``."""

    def __init__(
        self,
        port: int,
        registry: "Optional[MetricsRegistry]" = None,
        host: str = "127.0.0.1",
    ):
        self._server = _Server((host, port), _MetricsHandler)
        self._server.registry = (
            registry if registry is not None else default_registry()
        )
        self.host = host
        self.port = int(self._server.server_address[1])
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="kta-metrics-exporter",
            daemon=True,
        )
        self._thread.start()
        log.info("serving Prometheus metrics on http://%s:%d/metrics",
                 host, self.port)

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5)
