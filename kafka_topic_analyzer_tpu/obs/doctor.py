"""Scan doctor: automated bottleneck attribution from telemetry.

Converts the manual BENCH_NOTES ledger procedure (rounds 7/9/10/11:
reconstruct per-stage seconds from counters, argue which stage gated the
scan) into computed, tested verdicts.  Inputs are the SAME merged
registry snapshot ``--json`` embeds and ``gather_telemetry`` aggregates
across controllers — so the fleet-wide verdict on a mesh scan falls out
of the counter merge algebra, with no extra collective.

The model is the engine drive loop (engine.run_scan): every wall second
of the scan is spent in exactly one stage window — ``ingest`` (blocked
waiting for the fan-in/prefetch to yield the next staged batch),
``dispatch`` (staging + launching the device fold, INCLUDING the
DispatchQueue throttle wait), ``snapshot``, or ``finalize``.  Per-stage
occupancy is each stage's share of the total accounted drive seconds
(self-normalizing, so merged multi-controller counters need no wall-clock
denominator).  Queue-theory evidence then separates the two interesting
verdicts:

- **ingest-bound** — the drive loop waits on ingest; the ingest workers
  are busy, not stalled (their queues are EMPTY: the consumer outruns
  them), and the dispatch throttle never engages.  The producers are the
  bottleneck.
- **dispatch-bound** — the drive loop sits in dispatch, and decisively in
  the throttle wait (``kta_dispatch_throttle_seconds_total``); the
  ingest workers stall on FULL queues.  The device (or the dispatch
  tunnel) is the bottleneck, and ingest parallelism cannot help.
- **balanced** — neither stage dominates (the pipeline overlap is doing
  its job), or too little was booked to call it.

Attribution rules (DESIGN.md §17): a stage verdict needs its occupancy
to clear ``DOMINANT`` (0.5) or to lead the runner-up by ``LEAD`` (2x).
Windowed verdicts apply the same rule to per-window deltas of a flight
recorder series, so a scan that changes regime mid-run (cold catalog
warmup, a broker fault, a device stall) shows the timeline instead of
one smeared average.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

#: Occupancy share that makes a stage the verdict on its own.
DOMINANT = 0.5
#: Or: lead over the runner-up stage that makes it the verdict.
LEAD = 2.0
#: Below this much booked drive time, refuse to attribute (an empty or
#: sub-millisecond scan has no signal worth a verdict).
MIN_ACCOUNTED_S = 1e-4


def _samples(snapshot: "Optional[dict]", name: str) -> "List[dict]":
    metric = (snapshot or {}).get(name)
    return metric["samples"] if metric else []


def _total(snapshot: "Optional[dict]", name: str) -> float:
    return float(sum(s.get("value", 0.0) for s in _samples(snapshot, name)))


def _by_label(snapshot: "Optional[dict]", name: str, label: str) -> "Dict[str, float]":
    return {
        s["labels"][label]: float(s["value"])
        for s in _samples(snapshot, name)
        if label in s.get("labels", {})
    }


@dataclasses.dataclass
class Diagnosis:
    """One scan's attribution: the ranked verdict plus the occupancy and
    evidence numbers it was computed from (never a bare label — the
    digest must be checkable against the same snapshot it came from)."""

    #: "ingest-bound" / "dispatch-bound" / "snapshot-bound" /
    #: "finalize-bound" / "balanced" / "no-signal".
    verdict: str
    #: One-line human rationale ("ingest-bound: workers 94% busy,
    #: dispatch queue empty 88% of samples").
    summary: str
    #: The evidence clause alone, without the leading verdict label —
    #: what renderers compose their own "BOTTLENECK: <verdict> — ..."
    #: line from (never re-parsed out of ``summary``).
    rationale: str
    #: stage -> fraction of accounted drive seconds, canonical order.
    stages: "Dict[str, float]"
    #: stage -> booked drive seconds (fleet totals under multi-controller).
    stage_seconds: "Dict[str, float]"
    #: Named evidence fractions (throttle_wait, worker_busy, ...).
    evidence: "Dict[str, float]"
    #: verdict -> share of flight-recorder windows ({} without a series).
    window_share: "Dict[str, float]"
    #: Per-window verdicts [{"t0", "t1", "verdict"}, ...] ([] without).
    windows: "List[dict]"
    #: Controllers the merged snapshot aggregates (1 = single process).
    controllers: int = 1

    def as_dict(self) -> dict:
        return {
            "verdict": self.verdict,
            "summary": self.summary,
            "rationale": self.rationale,
            "stages": {k: round(v, 4) for k, v in self.stages.items()},
            "stage_seconds": {
                k: round(v, 6) for k, v in self.stage_seconds.items()
            },
            "evidence": {k: round(v, 4) for k, v in self.evidence.items()},
            "window_share": {
                k: round(v, 4) for k, v in self.window_share.items()
            },
            "windows": self.windows,
            "controllers": self.controllers,
        }


def _rank(stages: "Dict[str, float]") -> str:
    """Apply the dominance rule to a stage-occupancy map."""
    if not stages:
        return "no-signal"
    ordered = sorted(stages.items(), key=lambda kv: -kv[1])
    top_name, top = ordered[0]
    runner = ordered[1][1] if len(ordered) > 1 else 0.0
    if top >= DOMINANT or (runner > 0 and top / runner >= LEAD) or (
        runner == 0 and top > 0
    ):
        return f"{top_name}-bound"
    return "balanced"


def _window_verdicts(flight: "Optional[dict]") -> "List[dict]":
    """Per-window verdicts from a flight recorder series: the dominance
    rule over per-tick deltas of the live stage counters.

    Stage counters book at stage-window EXIT, so a single stage window
    longer than the sampling interval (a cold jit compile inside the
    first dispatch, a multi-second collective) reads as ``idle`` until
    it closes and then attributes its whole duration to the closing
    window.  The headline verdict is immune (it uses totals); read a
    large ``idle`` share next to a decisive headline as "few, long
    windows", not "nothing happening"."""
    if not flight:
        return []
    t = flight.get("t") or []
    tracks = flight.get("tracks") or {}
    stage_tracks = {
        name.split("stage_", 1)[1].rsplit("_s", 1)[0]: tracks[name]
        for name in tracks
        if name.startswith("stage_") and name.endswith("_s")
    }
    if len(t) < 2 or not stage_tracks:
        return []
    out: "List[dict]" = []
    for i in range(1, len(t)):
        deltas = {
            stage: max(0.0, series[i] - series[i - 1])
            for stage, series in stage_tracks.items()
            if len(series) == len(t)
        }
        accounted = sum(deltas.values())
        if accounted < MIN_ACCOUNTED_S:
            verdict = "idle"
        else:
            verdict = _rank(
                {s: d / accounted for s, d in deltas.items()}
            )
        out.append(
            {"t0": round(t[i - 1], 3), "t1": round(t[i], 3),
             "verdict": verdict}
        )
    return out


def diagnose(
    snapshot: "Optional[dict]",
    controllers: int = 1,
    dispatch_depth: int = 1,
    flight: "Optional[dict]" = None,
) -> Diagnosis:
    """Attribute the scan's bottleneck from a (merged) registry snapshot.

    ``snapshot`` is ``ScanResult.telemetry`` — already the cluster-wide
    merge under multi-controller, so every total below is a fleet total
    and the occupancy fractions are fleet averages.  ``flight`` is an
    optional ``FlightRecorder.series()`` dict; it adds the windowed
    timeline and the queue-empty/-full sample evidence, but the headline
    verdict never requires it (the counters are always booked)."""
    stage_seconds = {
        s: v
        for s, v in _by_label(
            snapshot, "kta_stage_seconds_total", "stage"
        ).items()
        # The flight recorder creates zero-valued stage children eagerly;
        # a stage that never ran carries no signal and no occupancy row.
        if v > 0
    }
    accounted = sum(stage_seconds.values())
    stages = (
        {s: v / accounted for s, v in stage_seconds.items()}
        if accounted > 0
        else {}
    )

    evidence: "Dict[str, float]" = {}
    throttle_s = _total(snapshot, "kta_dispatch_throttle_seconds_total")
    if accounted > 0:
        evidence["throttle_wait"] = throttle_s / accounted
        # fetch/decode run CONCURRENTLY on N ingest worker threads, so
        # these fractions are thread-seconds per accounted drive second
        # and legitimately exceed 1.0 on parallel scans — e.g. fetch 2.1
        # with 4 workers means the fleet of streams spent ~2 socket-wait
        # seconds per drive-loop second, i.e. ~0.5 per worker.
        evidence["fetch"] = (
            _total(snapshot, "kta_fetch_seconds_total") / accounted
        )
        evidence["decode"] = (
            _total(snapshot, "kta_decode_seconds_total") / accounted
        )
    stall = _by_label(
        snapshot, "kta_ingest_worker_stall_seconds_total", "worker"
    )
    active = _by_label(
        snapshot, "kta_ingest_worker_active_seconds_total", "worker"
    )
    active_total = sum(active.values())
    if active_total > 0:
        stall_total = sum(stall.get(w, 0.0) for w in active)
        evidence["worker_stall"] = min(1.0, stall_total / active_total)
        evidence["worker_busy"] = 1.0 - evidence["worker_stall"]

    # Sample-level evidence from the flight series: how often the fan-in
    # queues sat empty (consumer outran producers) and how often the
    # dispatch queue sat full (device outrun by everything else).
    if flight:
        tracks = flight.get("tracks") or {}
        qd = tracks.get("ingest_queue_depth") or []
        if qd:
            evidence["queue_empty"] = sum(
                1 for v in qd if v <= 0
            ) / len(qd)
        infl = tracks.get("dispatch_inflight") or []
        if infl and dispatch_depth >= 1:
            evidence["inflight_full"] = sum(
                1 for v in infl if v >= dispatch_depth
            ) / len(infl)

    if accounted < MIN_ACCOUNTED_S:
        verdict = "no-signal"
        rationale = "too little booked drive time to attribute"
    else:
        verdict = _rank(stages)
        rationale = _summarize(verdict, stages, evidence)

    windows = _window_verdicts(flight)
    window_share: "Dict[str, float]" = {}
    if windows:
        for w in windows:
            window_share[w["verdict"]] = (
                window_share.get(w["verdict"], 0.0) + 1
            )
        n = len(windows)
        window_share = {k: v / n for k, v in window_share.items()}

    return Diagnosis(
        verdict=verdict,
        summary=f"{verdict}: {rationale}",
        rationale=rationale,
        stages=dict(
            sorted(stages.items(), key=lambda kv: -kv[1])
        ),
        stage_seconds=stage_seconds,
        evidence=evidence,
        window_share=window_share,
        windows=windows,
        controllers=max(1, int(controllers)),
    )


def _summarize(
    verdict: str,
    stages: "Dict[str, float]",
    evidence: "Dict[str, float]",
) -> str:
    """The one-line rationale (evidence clause only — callers prepend
    the verdict label themselves)."""
    pct = lambda v: f"{v * 100.0:.0f}%"  # noqa: E731
    parts: "List[str]" = []
    if verdict == "ingest-bound":
        parts.append(
            f"drive loop waited on ingest {pct(stages.get('ingest', 0))} "
            "of accounted time"
        )
        if "worker_busy" in evidence:
            parts.append(f"workers {pct(evidence['worker_busy'])} busy")
        if "queue_empty" in evidence:
            parts.append(
                f"dispatch queue empty {pct(evidence['queue_empty'])} "
                "of samples"
            )
    elif verdict == "dispatch-bound":
        parts.append(
            f"device dispatch occupied {pct(stages.get('dispatch', 0))} "
            "of accounted time"
        )
        if evidence.get("throttle_wait", 0) > 0:
            parts.append(
                f"backpressure throttle {pct(evidence['throttle_wait'])}"
            )
        if "worker_stall" in evidence and evidence["worker_stall"] > 0.05:
            parts.append(
                f"workers stalled {pct(evidence['worker_stall'])} on "
                "full queues"
            )
        if "inflight_full" in evidence:
            parts.append(
                f"dispatch queue full {pct(evidence['inflight_full'])} "
                "of samples"
            )
    elif verdict == "balanced":
        top = sorted(stages.items(), key=lambda kv: -kv[1])[:2]
        parts.append(
            "no stage dominates ("
            + ", ".join(f"{s} {pct(v)}" for s, v in top)
            + ") — the pipeline overlap is working"
        )
    else:
        top = sorted(stages.items(), key=lambda kv: -kv[1])[:1]
        parts.extend(f"{s} {pct(v)} of accounted time" for s, v in top)
    return "; ".join(parts)


# -- trend doctor (DESIGN.md §22) ---------------------------------------------
#
# `diagnose` answers "what is the bottleneck RIGHT NOW" from the live
# ring; these verdicts answer "is this service getting WORSE" from a
# disk-backed history window (obs/history.HistoryStore.window format) —
# throughput droop vs the run's own trailing baseline, lag divergence
# (ETA ∞), retry/corruption storms, segstore fallback and cache-poison
# spikes, and the warm-cache verify residual.  Same evidence discipline
# as the live doctor: every finding carries the numbers it was computed
# from, never a bare label.  Epoch-aware: counter deltas difference only
# within a process lifetime (obs/history.track_delta), while rates keep
# the FULL wall denominator — a restart's dead time counts as quiet
# time, it is never collapsed out of the window.

#: Recent fraction of the window the droop/storm comparisons treat as
#: "now" (the leading 1-RECENT_FRAC is the trailing baseline).
RECENT_FRAC = 0.25
#: A recent rate below this multiple of the baseline is a droop.
DROOP_RATIO = 0.5
#: A recent fault rate above this multiple of the baseline is a storm
#: (with at least MIN_STORM_EVENTS recent events — a 0→2 blip on an
#: otherwise-silent counter is noise, not a storm).
STORM_RATIO = 3.0
MIN_STORM_EVENTS = 3
#: Verify-bound: sha-verify seconds per wall second above this share.
VERIFY_BOUND_SHARE = 0.25
#: Fetch-bound: scheduler queue-wait seconds per wall second above this
#: share flags the remote tier; queue depth vs in-flight then attributes
#: it (starved pool vs saturated wire).
FETCH_WAIT_SHARE = 0.25


def _split_window(window: dict) -> "Optional[tuple]":
    t = window.get("t") or []
    if len(t) < 4:
        return None
    t0, t1 = t[0], t[-1]
    if t1 <= t0:
        return None
    split = t1 - (t1 - t0) * RECENT_FRAC
    return t0, split, t1


def _sub(window: dict, lo: float, hi: float) -> dict:
    """Restrict a window dict to [lo, hi] (same shape)."""
    t = window.get("t") or []
    idx = [i for i, ts in enumerate(t) if lo <= ts <= hi]
    return {
        "t": [t[i] for i in idx],
        "epoch": [(window.get("epoch") or [1] * len(t))[i] for i in idx],
        "tracks": {
            name: [series[i] for i in idx]
            for name, series in (window.get("tracks") or {}).items()
        },
    }


def _rate_pair(window: dict, name: str) -> "Optional[tuple]":
    """(baseline_rate, recent_rate, recent_delta) across the split, or
    None when the window is too short to compare."""
    from kafka_topic_analyzer_tpu.obs.history import (
        track_delta,
        track_rate,
    )

    parts = _split_window(window)
    if parts is None:
        return None
    t0, split, t1 = parts
    base = _sub(window, t0, split)
    recent = _sub(window, split, t1)
    if len(base.get("t") or []) < 2 or len(recent.get("t") or []) < 2:
        return None
    return (
        track_rate(base, name),
        track_rate(recent, name),
        track_delta(recent, name),
    )


def _storm(window: dict, track: str, kind: str, what: str) -> "Optional[dict]":
    pair = _rate_pair(window, track)
    if pair is None:
        return None
    base_rate, recent_rate, recent_events = pair
    if recent_events < MIN_STORM_EVENTS:
        return None
    if base_rate > 0 and recent_rate < STORM_RATIO * base_rate:
        return None
    return {
        "kind": kind,
        "summary": (
            f"{kind}: {what} at {recent_rate:.2f}/s in the recent window "
            f"vs {base_rate:.2f}/s baseline"
        ),
        "evidence": {
            "recent_per_s": round(recent_rate, 3),
            "baseline_per_s": round(base_rate, 3),
            "recent_events": int(recent_events),
        },
    }


def diagnose_trends(window: dict) -> "List[dict]":
    """Trend verdicts over one history window.  Returns [] for a healthy
    (or too-short) window; each finding is ``{"kind", "summary",
    "evidence"}`` with the evidence numbers the verdict was computed
    from.  Callers: the ``--stats`` TRENDS digest (cli._print_stats)
    and anything reading ``/history`` offline."""
    from kafka_topic_analyzer_tpu.obs.history import track_points

    findings: "List[dict]" = []
    parts = _split_window(window)
    if parts is None:
        return findings
    t0, split, t1 = parts
    wall = t1 - t0

    # Throughput droop vs the run's own trailing baseline.
    pair = _rate_pair(window, "records")
    if pair is not None:
        base_rate, recent_rate, _ = pair
        if base_rate > 1.0 and recent_rate < DROOP_RATIO * base_rate:
            findings.append({
                "kind": "throughput-droop",
                "summary": (
                    f"throughput-droop: recent fold rate "
                    f"{recent_rate:,.0f}/s is "
                    f"{recent_rate / base_rate:.0%} of the trailing "
                    f"baseline {base_rate:,.0f}/s"
                ),
                "evidence": {
                    "recent_per_s": round(recent_rate, 1),
                    "baseline_per_s": round(base_rate, 1),
                    "ratio": round(recent_rate / base_rate, 3),
                },
            })

    # Lag divergence: the gap to the head grew over the window — at this
    # rate the scan never catches up (ETA ∞).
    lag_pts = track_points(window, "follow_lag")
    if len(lag_pts) >= 2:
        first, last = lag_pts[0], lag_pts[-1]
        growth = last[2] - first[2]
        if last[2] > 0 and growth > 0:
            findings.append({
                "kind": "lag-divergence",
                "summary": (
                    f"lag-divergence: lag grew {growth:,.0f} records over "
                    f"{wall:.0f}s ({growth / wall:,.1f}/s) — at this rate "
                    "the scan never catches up (ETA ∞)"
                ),
                "evidence": {
                    "lag": int(last[2]),
                    "lag_then": int(first[2]),
                    "growth_per_s": round(growth / wall, 2),
                    "eta": "inf",
                },
            })

    storm = _storm(window, "backoff_sleeps", "retry-storm",
                   "transport retries backing off")
    if storm:
        findings.append(storm)
    storm = _storm(window, "corrupt_frames", "corruption-storm",
                   "frames classifying corrupt")
    if storm:
        findings.append(storm)
    storm = _storm(window, "segstore_fallbacks", "segstore-fallback-spike",
                   "segment-store fallbacks (cache poison/stale/IO) booking")
    if storm:
        findings.append(storm)

    # Warm-cache verify residual: sha-verify on cache hits eating a
    # material share of the window (the round-14 2.1x re-audit ledger
    # claim, attributable from telemetry alone).
    from kafka_topic_analyzer_tpu.obs.history import track_delta

    verify_s = track_delta(window, "cache_verify_s")
    hit_bytes = track_delta(window, "cache_hit_bytes")
    if wall > 0 and hit_bytes > 0 and verify_s / wall >= VERIFY_BOUND_SHARE:
        findings.append({
            "kind": "verify-bound",
            "summary": (
                f"verify-bound: sha256 verification of cache hits consumed "
                f"{verify_s / wall:.0%} of the window "
                f"({hit_bytes / max(verify_s, 1e-9) / 1e6:,.0f} MB/s "
                "verified) — the warm re-audit is paying the "
                "verify-on-every-hit cost (BENCH round 14 residual)"
            ),
            "evidence": {
                "verify_seconds": round(verify_s, 3),
                "verify_share": round(verify_s / wall, 4),
                "hit_bytes": int(hit_bytes),
            },
        })

    # Fetch-bound attribution (io/fetchsched.py): requests spending a
    # material share of the window queued in the scheduler.  The queue
    # depth vs in-flight comparison says WHICH resource ran out —
    # scheduler starvation (queue persistently deeper than the worker
    # pool: --fetch-concurrency is too small for this stream count) vs
    # wire saturation (pool busy but the queue stays shallow: the link,
    # not the admission layer, is the limit).
    wait_s = track_delta(window, "fetch_sched_wait_s")
    if wall > 0 and wait_s / wall >= FETCH_WAIT_SHARE:
        queue_pts = track_points(window, "fetch_sched_queue")
        inflight_pts = track_points(window, "fetch_sched_inflight")
        mean_queue = (
            sum(p[2] for p in queue_pts) / len(queue_pts)
            if queue_pts else 0.0
        )
        mean_inflight = (
            sum(p[2] for p in inflight_pts) / len(inflight_pts)
            if inflight_pts else 0.0
        )
        starved = mean_queue > max(mean_inflight, 1.0)
        attribution = (
            "scheduler-starvation" if starved else "wire-saturation"
        )
        advice = (
            "raise --fetch-concurrency"
            if starved
            else "the wire is the limit — more workers will not help"
        )
        findings.append({
            "kind": "fetch-bound",
            "summary": (
                f"fetch-bound ({attribution}): requests spent "
                f"{wait_s / wall:.0%} of the window queued in the fetch "
                f"scheduler (mean queue {mean_queue:.1f} vs "
                f"{mean_inflight:.1f} in flight) — {advice}"
            ),
            "evidence": {
                "wait_seconds": round(wait_s, 3),
                "wait_share": round(wait_s / wall, 4),
                "mean_queue_depth": round(mean_queue, 2),
                "mean_inflight": round(mean_inflight, 2),
                "attribution": attribution,
            },
        })
    return findings


def diagnose_scan(result) -> Diagnosis:
    """`diagnose` over a finished (or in-flight follow) `ScanResult`,
    with the flight recorder folded in when one is active — the shared
    entry point for the CLI's --stats/--json paths and the follow
    service's /report.json publisher (serve/follow.py), so every surface
    attributes from the same evidence."""
    from kafka_topic_analyzer_tpu.obs import flight as _flight

    rec = _flight.active()
    if rec is not None:
        # Close the timeline before reading it: the session-owned recorder
        # is still sampling (teardown stops it later), and a scan shorter
        # than the sampling interval would otherwise diagnose from an
        # empty series.
        rec.sample_once()
    return diagnose(
        result.telemetry,
        controllers=max(1, len(result.ingest_workers_per_controller)),
        dispatch_depth=result.dispatch_depth,
        flight=rec.series() if rec is not None else None,
    )
