"""CLI — the reference's flag surface plus TPU-build extensions.

Reference-compatible flags (src/main.rs:32-67): ``-t/--topic`` (required),
``-b/--bootstrap-server`` (comma separated), ``--librdkafka`` (comma-separated
``k=v`` passthrough into the consumer config), ``-c/--count-alive-keys``.
Extensions: ``--backend {cpu,tpu}`` (default cpu per BASELINE.json),
``--source``, sketch/batch/mesh knobs.  Exit code -2 on an empty topic
(src/main.rs:98-101).
"""

from __future__ import annotations

import argparse
import contextlib
import sys
from typing import Dict, Optional

from kafka_topic_analyzer_tpu.config import AnalyzerConfig


#: Exit code when the scan finished but one or more partitions were dropped
#: after exhausting their transport retry budget: the report renders (with
#: the degraded block) yet the numbers undercount, so automation must see a
#: failure.  Distinct from 1 (hard error) and -2 (empty topic).
EXIT_DEGRADED = 3

#: Exit code when the scan COMPLETED but skipped (or quarantined) one or
#: more deterministically corrupt frames (--on-corruption=skip/quarantine):
#: the metrics exclude exactly those frames' records, which automation must
#: distinguish from both a clean run (0) and a degraded one (3 — an
#: unbounded undercount; degradation therefore takes precedence when both
#: occur).
EXIT_CORRUPT = 4

#: Exit code when a scan ABORTED because the log mutated out from under it
#: (retention race, truncation after an unclean election, resume below
#: log-start) under ``--on-data-loss=fail``: the loss is fully booked and a
#: fold-consistent checkpoint is written before the abort, so a --resume
#: continues past the named gap.  Under the default ``report`` policy the
#: scan finishes with exit 0 and the DATA-LOSS block names the loss;
#: ``ignore`` finishes with exit 0 and no block (metrics/JSON still carry
#: it) — loss is always accounted, the policy only picks the reaction.
EXIT_DATA_LOSS = 5


def _scan_issue_exit(result, doc=None, render=False,
                     data_loss_policy: str = "report") -> int:
    """Shared tail of every report path: surface corrupt, degraded, and
    lost partitions — into ``doc`` as str-keyed maps (``--json``; the one
    block builder report.attach_issue_blocks) and/or as the post-table
    warning blocks (``render``) — and pick the exit code."""
    rc = 0
    corrupt = getattr(result, "corrupt_partitions", None) or {}
    if doc is not None:
        from kafka_topic_analyzer_tpu.report import attach_issue_blocks

        attach_issue_blocks(doc, result)
    lost = getattr(result, "lost_partitions", None) or {}
    if lost and render and data_loss_policy != "ignore":
        from kafka_topic_analyzer_tpu.report import render_data_loss_block

        sys.stdout.write(render_data_loss_block(lost))
    if corrupt:
        if render:
            from kafka_topic_analyzer_tpu.report import render_corrupt_block

            sys.stdout.write(render_corrupt_block(corrupt))
        rc = EXIT_CORRUPT
    if result.degraded_partitions:
        if render:
            from kafka_topic_analyzer_tpu.report import render_degraded_block

            sys.stdout.write(
                render_degraded_block(result.degraded_partitions)
            )
        rc = EXIT_DEGRADED
    return rc


class UserInputError(ValueError):
    """A bad flag/spec value (setup phase) — reported as one clean line.
    Internal ValueErrors deliberately do NOT inherit this, so they keep
    their tracebacks."""


@contextlib.contextmanager
def user_input_phase():
    """Re-brand setup-phase ValueErrors as user input errors."""
    try:
        yield
    except UserInputError:
        raise
    except ValueError as e:
        raise UserInputError(e) from e


def parse_kv_pairs(text: Optional[str]) -> Dict[str, str]:
    """Parse ``"a=b,c=d"`` exactly like src/main.rs:84-92."""
    if not text:
        return {}
    out: Dict[str, str] = {}
    for pair in text.split(","):
        k, _, v = pair.partition("=")
        out[k] = v
    return out


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="kafka-topic-analyzer",
        description="An analyzer for getting metrics about the contents of an "
        "Apache Kafka topic (TPU-native rebuild)",
    )
    # --- reference-compatible surface (src/main.rs:32-67) -------------------
    from kafka_topic_analyzer_tpu import __version__

    # (The reference's -V banner self-reports a stale 0.4.1 — a quirk
    # SURVEY.md §0 says not to replicate.)
    p.add_argument("-V", "--version", action="version",
                   version=f"kafka-topic-analyzer-tpu {__version__}")
    p.add_argument("-t", "--topic", required=True, metavar="TOPIC",
                   help="The topic to analyze")
    p.add_argument("-b", "--bootstrap-server", metavar="BOOTSTRAP_SERVER",
                   help="Bootstrap server(s) to work with, comma separated")
    p.add_argument("--librdkafka", metavar="LIBRDKAFKA",
                   help="Options to pass into the underlying consumer, comma "
                        "separated key=value pairs")
    p.add_argument("-c", "--count-alive-keys", action="store_true",
                   help="Counts the effective number of alive keys in a log "
                        "compacted topic. A key is 'alive' when it is present "
                        "and has a non-null value in its latest-offset version")
    # --- TPU-build extensions ----------------------------------------------
    p.add_argument("--backend", choices=["cpu", "tpu"], default="cpu",
                   help="Metric backend: numpy exact oracle (cpu) or JAX "
                        "streaming reducers (tpu). Default: cpu")
    p.add_argument("--source", choices=["kafka", "synthetic", "segfile"],
                   default="kafka",
                   help="Record source. 'kafka' reads the real topic via the "
                        "wire protocol; 'synthetic'/'segfile' are cluster-free")
    p.add_argument("--synthetic", metavar="SPEC",
                   help="Synthetic workload spec, comma separated k=v: "
                        "partitions,messages,keys,key_null,tombstones,vmin,"
                        "vmax,seed")
    p.add_argument("--segment-dir", metavar="DIR|URL",
                   help="Segment store of .ktaseg dumps (--source segfile): "
                        "a local directory (or file://DIR), or a remote "
                        "object store — http(s)://host[:port]/bucket"
                        "[/prefix] for any S3-compatible endpoint "
                        "(path-style), s3://bucket[/prefix] through "
                        "KTA_S3_ENDPOINT. Composes with --ingest-workers "
                        "(partitions shard across parallel decode+pack "
                        "workers, balanced by the catalog's record "
                        "counts), --superbatch, --segment-readahead and "
                        "--segment-cache")
    p.add_argument("--segment-readahead", default="auto", metavar="N|auto",
                   help="Remote chunks kept in flight ahead of each ingest "
                        "stream (the per-stream window over the shared "
                        "fetch scheduler), so per-GET wire latency "
                        "overlaps the running decode→pack pass instead "
                        "of serializing with it. 'auto' = 4 for remote "
                        "stores, 0 (demand-only) for local directories. "
                        "Results are byte-identical at any depth. "
                        "Default: auto")
    p.add_argument("--fetch-concurrency", default="auto", metavar="N|auto",
                   help="Worker count of the ONE process-wide fetch "
                        "scheduler every remote segment byte is admitted "
                        "through (catalog header probes, demand fetches, "
                        "read-ahead) — sized once per process, not per "
                        "stream, so connection count stays fixed while "
                        "--ingest-workers scales. Demand requests outrank "
                        "speculative read-ahead; streams share the pool "
                        "fairly. 'auto' sizes from the host and grows "
                        "with the resolved stream count. Default: auto")
    p.add_argument("--segment-cache", metavar="DIR",
                   help="Local chunk cache for remote segment stores: "
                        "fetched chunks land here (atomic rename-in, "
                        "sha256 sidecar) and repeated audits of the same "
                        "archive run at local-disk speed. Entries are "
                        "sha256-verified at first touch each process "
                        "(then latched as trusted and served as "
                        "zero-copy mmap views) — a flipped byte is "
                        "detected, booked and re-fetched, never served")
    p.add_argument("--segment-cache-bytes", type=int, default=1 << 30,
                   metavar="BYTES",
                   help="Size bound of --segment-cache: inserts evict "
                        "least-recently-used entries past it. "
                        "Default: 1 GiB")
    p.add_argument("--batch-size", type=int, default=1 << 18,
                   help="Records per device step")
    p.add_argument("--alive-bitmap-bits", type=int, default=32,
                   help="log2 of alive-key bitmap slots (32 = reference-exact)")
    p.add_argument("--distinct-keys", action="store_true",
                   help="Also estimate distinct keys with a HyperLogLog sketch")
    p.add_argument("--distinct-keys-per-partition", action="store_true",
                   help="Track one HLL register file per partition "
                        "(implies --distinct-keys)")
    p.add_argument("--quantiles", action="store_true",
                   help="Also compute message-size quantiles (DDSketch)")
    p.add_argument("--quantiles-per-partition", action="store_true",
                   help="Track one size-quantile sketch per partition "
                        "(implies --quantiles)")
    p.add_argument("--mesh", metavar="DATA[,SPACE]", default="1",
                   help="Device mesh shape: data shards[, space shards]")
    p.add_argument("--ingest-workers", default="1", metavar="N|auto",
                   help="Parallel partition-sharded ingest for one scan: "
                        "shard the partition set over N private "
                        "fetch+decode+pack worker threads feeding the "
                        "backend through deterministic fan-ins — results "
                        "stay byte-identical to the sequential scan. "
                        "'auto' sizes from the host (min(cores-1, "
                        "partitions)). Composes with --mesh: each "
                        "controller resolves the count against ITS data "
                        "shard's partitions and fans in per data row "
                        "(host x device x dispatch parallelism in one "
                        "scan). Default: 1")
    p.add_argument("--superbatch", default="1", metavar="K|auto",
                   help="Superbatch dispatch: stack K packed batches into "
                        "one uint8[K, N] host array and fold them in a "
                        "single jitted lax.scan dispatch (state donated "
                        "once per superbatch, one large host->device "
                        "transfer) — K x fewer dispatches with "
                        "byte-identical results. 'auto' targets 2^20 "
                        "records per dispatch (min 1, max 16), capped at "
                        "2^18 records per fold so a long synchronous fold "
                        "cannot starve ingest overlap (DESIGN.md §12); an "
                        "explicit K is never capped. Default: 1. "
                        "Requires --backend tpu")
    p.add_argument("--dispatch-depth", type=int, default=2, metavar="D",
                   help="Superbatches allowed in flight (staged/"
                        "transferring) while the device folds; the drive "
                        "loop blocks — backpressuring ingest — beyond it. "
                        "2 overlaps the next transfer with the current "
                        "fold. Default: 2")
    p.add_argument("--pallas", action="store_true",
                   help="Use the Pallas MXU counter kernel for the "
                        "per-partition counters (tpu backend; requires "
                        "batch-size %% 1024 == 0)")
    p.add_argument("--distributed", metavar="COORD:PORT,PID,NPROCS",
                   help="Multi-host mode: initialize jax.distributed with the "
                        "given coordinator address, process id and process "
                        "count before building the mesh (collectives then "
                        "span hosts over DCN)")
    p.add_argument("--wire-format", choices=["auto", "v4", "v5"],
                   default="auto", metavar="auto|v4|v5",
                   help="Packed host→device wire format: v5 (combiner rows "
                        "— host pre-reduced per-partition fold tables, the "
                        "default) or v4 (per-record columns). 'auto' "
                        "resolves to v5 unless KTA_WIRE_V4 is set. Results "
                        "are byte-identical either way; snapshots resume "
                        "across formats")
    p.add_argument("--alive-compaction", choices=["auto", "off"],
                   default="auto", metavar="auto|off",
                   help="Host-side LWW compaction of the alive-key pairs "
                        "into one bounded per-dispatch table (wire v5 "
                        "only; DESIGN §19). 'auto' (default) compacts "
                        "whenever -c runs under v5; 'off' keeps the "
                        "per-row pair sections. Results are byte-identical "
                        "either way; KTA_DISABLE_COMPACTION is the env "
                        "kill switch, and a bypass is booked on "
                        "kta_alive_compaction_off_total")
    p.add_argument("--native", choices=["auto", "on", "off"], default="auto",
                   help="Use the native C++ ingest shim when available")
    p.add_argument("--profile-dir", metavar="DIR",
                   help="Write a JAX profiler trace of the scan")
    p.add_argument("--snapshot-dir", metavar="DIR",
                   help="Periodically save resumable scan snapshots here")
    p.add_argument("--snapshot-every", type=float, default=60.0,
                   metavar="SECONDS", help="Snapshot interval (default 60s)")
    p.add_argument("--resume", action="store_true",
                   help="Resume from a snapshot in --snapshot-dir if present")
    p.add_argument("--from-timestamp", metavar="ISO8601|EPOCH_MS",
                   help="Scan only records at or after this time (kafka "
                        "source: broker-side ListOffsets timestamp lookup). "
                        "Accepts epoch milliseconds or ISO-8601, e.g. "
                        "2026-01-01T00:00:00")
    p.add_argument("--dump-segments", metavar="DIR",
                   help="While scanning, dump record metadata into .ktaseg "
                        "chunks so the topic can be re-analyzed from disk "
                        "(not combined with --resume)")
    p.add_argument("--json", action="store_true",
                   help="Emit the report as JSON on stdout instead of the "
                        "terminal tables")
    p.add_argument("--extremes-table", action="store_true",
                   help="Also print a per-partition first/last-timestamp and "
                        "min/max-size table (new capability)")
    p.add_argument("--stats", action="store_true",
                   help="Print per-stage throughput stats and the telemetry "
                        "counter digest to stderr")
    p.add_argument("--metrics-port", type=int, metavar="PORT",
                   help="Serve Prometheus metrics on "
                        "http://127.0.0.1:PORT/metrics while the scan runs "
                        "(0 binds an ephemeral port)")
    p.add_argument("--sse", action="store_true",
                   help="Push a Server-Sent-Events stream of report "
                        "publishes at /events on --metrics-port: each "
                        "frame carries the new snapshot's seq and a "
                        "compact delta summary, so dashboards re-fetch "
                        "/report.json only when it actually changed "
                        "(requires --metrics-port)")
    p.add_argument("--no-serve-gzip", action="store_true",
                   help="Disable publish-time gzip of /report.json "
                        "bodies (the default compresses once per "
                        "publish and serves the cached encoding to "
                        "Accept-Encoding: gzip readers)")
    p.add_argument("--events-jsonl", metavar="FILE",
                   help="Append structured scan lifecycle + transport-fault "
                        "events to FILE as JSON lines")
    p.add_argument("--trace-json", metavar="FILE",
                   help="Write a Chrome trace-event JSON of host-side scan "
                        "spans (fetch/decode/stages) to FILE; combine with "
                        "--profile-dir for the XLA timeline")
    p.add_argument("--flight-record", action="store_true",
                   help="Run the pipeline flight recorder: a low-overhead "
                        "sampler records per-stage occupancy time series "
                        "(ingest/dispatch/snapshot occupancy, worker "
                        "stalls, queue depths, throttle waits) while the "
                        "scan runs. Adds windowed verdicts to the --stats "
                        "BOTTLENECK digest, counter tracks to --trace-json, "
                        "and serves the ring-buffered series at /flight on "
                        "--metrics-port. The bottleneck verdict itself is "
                        "always computed — the recorder adds the timeline")
    p.add_argument("--history-bytes", type=int, default=0, metavar="BYTES",
                   help="Persist the flight recorder's telemetry series "
                        "to a crash-safe, multi-resolution on-disk store "
                        "bounded by BYTES (RRD-style: recent history at "
                        "full resolution, older history progressively "
                        "halved), living next to the checkpoints "
                        "(requires --snapshot-dir) so a restarted "
                        "service resumes its series. Serves windowed "
                        "queries at /history on --metrics-port, feeds "
                        "the trend doctor's TRENDS digest on --stats, "
                        "and implies --flight-record. 0 disables "
                        "(default)")
    p.add_argument("--fleet", action="store_true",
                   help="Cluster-wide topic discovery + scan: ask the "
                        "cluster for ALL topics (one all-topics Metadata "
                        "request), filter them (-t becomes a comma-"
                        "separated include-glob list, default '*'; "
                        "--fleet-exclude subtracts; internal "
                        "__consumer_offsets-style topics are excluded "
                        "unless --fleet-internal), then scan every match "
                        "— up to --fleet-concurrency topics at once, "
                        "sharing the global --ingest-workers and "
                        "--dispatch-depth budgets across the concurrent "
                        "scans.  Per-topic results are byte-identical to "
                        "solo scans; one topic's failure never kills the "
                        "fleet (it becomes a status row).  Composes with "
                        "--follow (the whole cluster tailed as one "
                        "service), --json (cluster rollup + per-topic "
                        "documents), --snapshot-dir (one subdirectory "
                        "per topic) and /report.json?topic= on "
                        "--metrics-port")
    p.add_argument("--fleet-exclude", metavar="GLOBS",
                   help="Comma-separated topic-name globs to exclude "
                        "from --fleet discovery (applied after the -t "
                        "include globs)")
    p.add_argument("--fleet-internal", action="store_true",
                   help="Include broker-internal topics "
                        "(__consumer_offsets-style; metadata-flagged or "
                        "__-prefixed) in --fleet discovery")
    p.add_argument("--fleet-concurrency", default="auto", metavar="N|auto",
                   help="Per-topic scans admitted concurrently under "
                        "--fleet ('auto' sizes from the worker budget "
                        "and topic count). The admission scheduler "
                        "defers the rest until budget returns. "
                        "Default: auto")
    p.add_argument("--instance-id", metavar="ID", default=None,
                   nargs="?", const="auto",
                   help="This analyzer's identity in a multi-instance "
                        "fleet (DESIGN §23): turns on per-topic ownership "
                        "leases so N analyzers pointed at one cluster "
                        "split the topics instead of double-scanning "
                        "them, with crash failover by lease expiry. "
                        "Stamped on lease records, kta_lease_*/kta_fleet_* "
                        "metrics, and published report documents. "
                        "Requires --fleet and a lease store "
                        "(--snapshot-dir for file leases, or a remote "
                        "--segment-dir spec with --lease-store object). "
                        "Bare --instance-id derives HOSTNAME-PID. "
                        "Omit the flag to run solo (the default)")
    p.add_argument("--lease-ttl", type=float, default=30.0,
                   metavar="SECONDS",
                   help="Topic-lease lifetime under --instance-id: the "
                        "failover bound (a crashed instance's topics are "
                        "reacquirable this long after its last renewal). "
                        "Renewals ride every poll boundary. Default: 30")
    p.add_argument("--lease-store", default="auto",
                   metavar="auto|file|object",
                   help="Where lease records live: 'file' = atomic-rename "
                        "JSON records under SNAPSHOT_DIR/_kta_leases/, "
                        "'object' = ETag-fenced conditional writes to the "
                        "--segment-store bucket, 'auto' (default) picks "
                        "'object' when the segment store is remote, else "
                        "'file'")
    p.add_argument("--follow", action="store_true",
                   help="Run as a long-lived analyzer service: after the "
                        "initial earliest→latest pass, keep re-polling "
                        "watermarks and fold new records incrementally "
                        "(superbatch/parallel-ingest/mesh composition "
                        "unchanged), serving the evolving report at "
                        "/report.json on --metrics-port. SIGINT/SIGTERM "
                        "stop at the next poll boundary: final "
                        "checkpoint, final report, clean exit. Resumes "
                        "from any --snapshot-dir snapshot, including one "
                        "a batch scan wrote")
    p.add_argument("--poll-interval", type=float, default=1.0,
                   metavar="SECONDS",
                   help="Follow-mode watermark poll cadence; consecutive "
                        "empty polls back off exponentially from here to "
                        "10s. Default: 1.0")
    p.add_argument("--checkpoint-interval", type=float, default=None,
                   metavar="SECONDS",
                   help="Follow-mode checkpoint cadence (committed only "
                        "at superbatch boundaries; requires "
                        "--snapshot-dir). Defaults to --snapshot-every")
    p.add_argument("--follow-idle-exit", type=float, default=None,
                   metavar="SECONDS",
                   help="Exit the follow service cleanly after this long "
                        "at the head with no new records (drain mode); "
                        "default: follow forever")
    p.add_argument("--window-secs", type=float, default=60.0,
                   metavar="SECONDS",
                   help="Width of one follow-mode report window (the "
                        "time-windowed per-partition rate/cardinality/"
                        "size folds served in /report.json). Default: 60")
    p.add_argument("--window-count", type=int, default=8, metavar="N",
                   help="Window states kept in the follow-mode ring "
                        "(merged associatively for the whole-ring view); "
                        "0 disables windowed folds. Default: 8")
    p.add_argument("--check-crcs", action="store_true",
                   help="Verify record-batch checksums (CRC32-C) while "
                        "decoding, like librdkafka's check.crcs. Without it, "
                        "corruption detection only catches structural "
                        "damage; payload bit rot decodes as garbage values")
    p.add_argument("--on-corruption", choices=["fail", "skip", "quarantine"],
                   default="fail", metavar="POLICY",
                   help="What to do with a deterministically corrupt record "
                        "frame (one that fails decode identically on a "
                        "re-fetch): 'fail' aborts the scan (default), 'skip' "
                        "skips exactly that frame and finishes with exit "
                        f"code {EXIT_CORRUPT}, 'quarantine' additionally "
                        "spools the raw frame + JSON sidecar to "
                        "--quarantine-dir")
    p.add_argument("--quarantine-dir", metavar="DIR",
                   help="Directory for quarantined corrupt frames "
                        "(requires --on-corruption=quarantine)")
    p.add_argument("--on-data-loss", choices=["fail", "report", "ignore"],
                   default="report", metavar="POLICY",
                   help="What to do when the log mutates out from under "
                        "the scan (retention races past the cursor, "
                        "truncation after an unclean leader election, "
                        "resume below the live log start): 'fail' aborts "
                        "with a fold-consistent checkpoint and exit code "
                        f"{EXIT_DATA_LOSS}, 'report' (default) finishes "
                        "with exit 0 and a DATA-LOSS block naming every "
                        "lost range, 'ignore' finishes with exit 0 and no "
                        "block. The loss is ALWAYS booked to metrics and "
                        "the --json data_loss map regardless of policy")
    p.add_argument("--quiet", action="store_true", help="No progress spinner")
    return p


def parse_timestamp_ms(text: str) -> int:
    """Epoch milliseconds, or ISO-8601 (naive strings are taken as UTC).
    Negative values are rejected — they collide with Kafka's ListOffsets
    sentinels (-1 latest, -2 earliest) and would silently change scan
    semantics."""
    ms: "int | None" = None
    try:
        ms = int(text)
    except ValueError:
        import datetime

        try:
            dt = datetime.datetime.fromisoformat(text)
        except ValueError as e:
            raise ValueError(
                f"bad --from-timestamp {text!r}: expected epoch ms or ISO-8601"
            ) from e
        if dt.tzinfo is None:
            dt = dt.replace(tzinfo=datetime.timezone.utc)
        ms = int(dt.timestamp() * 1000)
    if ms < 0:
        raise ValueError(
            f"bad --from-timestamp {text!r}: must not be before the epoch"
        )
    return ms


def parse_mesh(text: str) -> "tuple[int, int]":
    try:
        parts = [int(x) for x in text.split(",") if x]
    except ValueError:
        raise ValueError(
            f"bad --mesh {text!r}: expected DATA or DATA,SPACE device "
            "counts (integers, e.g. '4' or '4,2')"
        ) from None
    if len(parts) not in (1, 2):
        raise ValueError(
            f"bad --mesh {text!r}: expected 1 or 2 comma-separated device "
            f"counts, got {len(parts)}"
        )
    if any(p < 1 for p in parts):
        raise ValueError(
            f"bad --mesh {text!r}: device counts must be positive"
        )
    return (parts[0], parts[1] if len(parts) == 2 else 1)


def make_source(args, topic: "str | None" = None, seed_salt: int = 0) -> "object":
    topic = topic if topic is not None else args.topic
    if args.source != "kafka" and (
        getattr(args, "on_corruption", "fail") != "fail"
        or getattr(args, "quarantine_dir", None)
    ):
        raise ValueError(
            "--on-corruption/--quarantine-dir require --source kafka "
            "(only the wire scan can classify and re-fetch frames)"
        )
    if args.source != "segfile" and getattr(args, "segment_cache", None):
        raise ValueError(
            "--segment-cache requires --source segfile (it caches chunks "
            "fetched from a remote segment store)"
        )
    if args.source == "synthetic":
        from kafka_topic_analyzer_tpu.io.synthetic import SyntheticSource, SyntheticSpec

        spec = SyntheticSpec.from_kv(parse_kv_pairs(args.synthetic), seed_salt)
        use_native = args.native in ("auto", "on")
        if use_native:
            try:
                from kafka_topic_analyzer_tpu.io.native import NativeSyntheticSource

                return NativeSyntheticSource(spec)
            except Exception:
                if args.native == "on":
                    raise
        return SyntheticSource(spec)
    if args.source == "segfile":
        if not args.segment_dir:
            raise SystemExit(
                "--source segfile requires --segment-dir (a local "
                "directory of .ktaseg dumps, or a remote store spec like "
                "http(s)://host:port/bucket or s3://bucket/prefix)"
            )
        import dataclasses

        from kafka_topic_analyzer_tpu.config import (
            SegmentFetchConfig,
            TransportRetryConfig,
        )
        from kafka_topic_analyzer_tpu.io.segfile import SegmentFileSource

        fetch = SegmentFetchConfig.parse(
            readahead=getattr(args, "segment_readahead", "auto"),
            cache_dir=getattr(args, "segment_cache", None),
            cache_max_bytes=getattr(args, "segment_cache_bytes", 1 << 30),
            fetch_concurrency=getattr(args, "fetch_concurrency", "auto"),
        )
        # The remote tier runs the SAME retry substrate as the wire scan,
        # so the same --librdkafka knobs tune it (retry.backoff.ms,
        # reconnect.backoff.max.ms, transport.retry.budget).
        retry_overrides = parse_kv_pairs(args.librdkafka)
        if retry_overrides:
            fetch = dataclasses.replace(
                fetch,
                retry=TransportRetryConfig.from_overrides(retry_overrides),
            )
        return SegmentFileSource(args.segment_dir, topic=topic, fetch=fetch)
    # kafka
    if not args.bootstrap_server:
        raise SystemExit("--source kafka requires -b/--bootstrap-server")
    from kafka_topic_analyzer_tpu.config import (
        CorruptionConfig,
        DataLossConfig,
    )
    from kafka_topic_analyzer_tpu.io.kafka_wire import KafkaWireSource

    overrides = parse_kv_pairs(args.librdkafka)
    if getattr(args, "check_crcs", False):
        # First-class flag for the knob that upgrades corruption detection
        # from structural damage to full payload checksums; the explicit
        # flag wins over a --librdkafka check.crcs override.
        overrides["check.crcs"] = "true"
    corruption = None
    if (
        getattr(args, "on_corruption", "fail") != "fail"
        or getattr(args, "quarantine_dir", None)
    ):
        corruption = CorruptionConfig(
            policy=getattr(args, "on_corruption", "fail"),
            quarantine_dir=getattr(args, "quarantine_dir", None),
        )
    data_loss = None
    if getattr(args, "on_data_loss", "report") != "report":
        data_loss = DataLossConfig(policy=args.on_data_loss)
    return KafkaWireSource(
        bootstrap_servers=args.bootstrap_server,
        topic=topic,
        overrides=overrides,
        use_native_hashing=args.native != "off",
        # None lets an --librdkafka on.corruption/quarantine.dir (or
        # on.data.loss) override apply; explicit flags win.
        corruption=corruption,
        data_loss=data_loss,
    )


def wrap_with_dump(args, topic: str, source):
    """Attach a segment-dump tee to a source when --dump-segments is set
    (shared by the single- and multi-topic paths)."""
    if not args.dump_segments:
        return source
    if args.resume:
        raise UserInputError(
            "--dump-segments cannot be combined with --resume (the dump "
            "would miss already-scanned records); drop --resume — or "
            "delete the snapshot — so the dump scan covers the topic "
            "from its earliest offset"
        )
    from kafka_topic_analyzer_tpu.io.segfile import SegmentDumpWriter, TeeSource

    return TeeSource(source, SegmentDumpWriter(args.dump_segments, topic))



def resolve_ingest_workers(args, mesh_shape, num_partitions):
    """Parse --ingest-workers (shared by the single- and multi-topic
    paths).  For the single-device scan, returns the concrete worker
    count after 'auto'/partition-count resolution.  For a sharded mesh,
    returns the parsed IngestConfig unresolved: the engine resolves it
    PER CONTROLLER — auto = min(cores-1, that controller's shard
    partition count), explicit N clamped the same way — because under
    multi-controller neither the global partition count nor this
    process's core count describes the other hosts (DESIGN.md §14)."""
    from kafka_topic_analyzer_tpu.config import IngestConfig

    cfg = IngestConfig.parse(args.ingest_workers)
    if mesh_shape != (1, 1):
        return cfg
    return cfg.resolve(num_partitions)


def resolve_dispatch(args):
    """Parse + validate --superbatch/--dispatch-depth against the backend
    (shared by the single- and multi-topic paths).  Returns the
    DispatchConfig for the device backends, or None for the cpu oracle —
    which has no device dispatch to amortize, so an EXPLICIT K>1 request
    there is a contradiction (reject rather than silently underdeliver;
    'auto' means "size appropriately" and resolves to no superbatching)."""
    from kafka_topic_analyzer_tpu.config import DispatchConfig

    cfg = DispatchConfig.parse(args.superbatch, args.dispatch_depth)
    if args.backend != "tpu":
        if cfg.superbatch != "auto" and int(cfg.superbatch) > 1:
            raise ValueError(
                "--superbatch requires --backend tpu (the cpu oracle has "
                "no device dispatch to amortize)"
            )
        return None
    return cfg


def resolve_wire_format(args) -> int:
    """--wire-format → AnalyzerConfig.wire_format (shared by the single-
    and multi-topic paths): 'auto' = 0 (config resolves to v5 unless the
    KTA_WIRE_V4 kill switch is set), 'v4'/'v5' pin the format.  Results
    are byte-identical either way (DESIGN.md §16) and the format is
    outside the checkpoint fingerprint, so snapshots resume across it."""
    return {"auto": 0, "v4": 4, "v5": 5}[getattr(args, "wire_format", "auto")]


def _diagnose(result):
    """Scan-doctor attribution for a finished scan: computed from the
    SAME merged snapshot ``--json`` embeds (fleet-wide under
    multi-controller), plus the flight recorder's series when one ran.
    Shared with the follow service's /report.json publisher
    (obs/doctor.diagnose_scan) so every surface attributes identically."""
    from kafka_topic_analyzer_tpu.obs.doctor import diagnose_scan

    return diagnose_scan(result)


def _print_stats(args, result, diagnosis=None) -> None:
    """--stats stderr dump: per-stage digest + telemetry counters + the
    doctor's BOTTLENECK attribution (cluster-wide under multi-controller).
    Stage timings render ONCE, from the registry snapshot — the same
    source the doctor attributes from — not from the in-process profile
    (which under multi-controller only knew this process's stages)."""
    if not args.stats:
        return
    from kafka_topic_analyzer_tpu.report import (
        render_bottleneck,
        render_stage_stats,
        render_telemetry_stats,
    )

    sys.stderr.write(render_stage_stats(result.telemetry))
    sys.stderr.write(
        render_telemetry_stats(
            result.telemetry,
            ingest_workers=result.ingest_workers,
            ingest_workers_per_controller=(
                result.ingest_workers_per_controller
            ),
            superbatch_k=result.superbatch_k,
            dispatch_depth=result.dispatch_depth,
            wire=result.wire,
        )
    )
    sys.stderr.write(
        render_bottleneck(
            diagnosis if diagnosis is not None else _diagnose(result)
        )
    )
    _print_health_stats()


def _print_health_stats() -> None:
    """--stats HEALTH + TRENDS digests (shared by the solo and fleet
    stats paths): the alert engine's latest document, and the trend
    doctor's findings over the history window when --history-bytes ran."""
    from kafka_topic_analyzer_tpu.obs import health as obs_health
    from kafka_topic_analyzer_tpu.obs import history as obs_history
    from kafka_topic_analyzer_tpu.report import render_health, render_trends

    engine = obs_health.active()
    if engine is not None:
        if engine.doc() is None:
            # Sub-interval scans never hit a heartbeat boundary; the
            # digest must still report from one real evaluation.
            engine.evaluate()
        sys.stderr.write(render_health(engine.doc()))
    store = obs_history.active()
    if store is not None:
        from kafka_topic_analyzer_tpu.obs.doctor import diagnose_trends

        sys.stderr.write(render_trends(diagnose_trends(store.window())))


def _not_report_process(args) -> bool:
    """Multi-host runs produce ONE report: every process scans and takes
    part in the collective finalize, but only process 0 renders output."""
    if not args.distributed:
        return False
    import jax

    return jax.process_index() != 0


def _make_cli_backend(args, config: AnalyzerConfig, mesh_shape, dispatch=None):
    """cpu oracle, single-device tpu, or sharded mesh backend per flags."""
    if args.backend == "tpu":
        # A wedged accelerator tunnel blocks forever inside backend init;
        # probe it in a killable subprocess first and degrade to the host
        # CPU platform (with a warning) instead of hanging the tool.
        from kafka_topic_analyzer_tpu.jax_support import (
            ensure_responsive_accelerator,
        )

        ensure_responsive_accelerator()
    if args.backend == "tpu" and mesh_shape != (1, 1):
        from kafka_topic_analyzer_tpu.parallel.sharded import ShardedTpuBackend

        return ShardedTpuBackend(config, dispatch=dispatch)
    from kafka_topic_analyzer_tpu.backends.base import make_backend

    return make_backend(args.backend, config, dispatch=dispatch)


def parse_from_timestamp_flag(args) -> "int | None":
    """Validate --from-timestamp's flag combination and parse it to ms
    (shared by the single- and multi-topic paths)."""
    if not args.from_timestamp:
        return None
    if args.source != "kafka":
        raise ValueError(
            "--from-timestamp requires --source kafka (broker-side "
            "timestamp index lookup)"
        )
    if args.resume:
        raise ValueError(
            "--from-timestamp cannot be combined with --resume (the "
            "snapshot's offsets already fix where the scan continues); "
            "drop --resume to seek to the timestamp, or drop "
            "--from-timestamp to resume the snapshot"
        )
    return parse_timestamp_ms(args.from_timestamp)


def resolve_start_offsets(source, from_ts_ms, label):
    """(start_at, exhausted): per-partition first offsets at/after the
    cutoff via the broker timestamp index; exhausted=True (with the
    message already printed) when nothing remains at or after it."""
    if from_ts_ms is None:
        return None, False
    start_at = source.offsets_for_timestamp(from_ts_ms)
    _, end = source.watermarks()
    if all(start_at.get(p, 0) >= end[p] for p in end):
        print(
            f"No records at or after {label} — nothing to analyze.",
            file=sys.stderr,
        )
        return None, True
    return start_at, False


def run_multi_topic(args, topics: "list[str]") -> int:
    """Fan-in scan of several topics through one backend: per-topic reports
    from row slices, plus a cross-topic union block whose sketch lines come
    from the associatively merged state (io/multi.py)."""
    from kafka_topic_analyzer_tpu.engine import run_scan
    from kafka_topic_analyzer_tpu.io.multi import MultiTopicSource
    from kafka_topic_analyzer_tpu.report import render_report
    from kafka_topic_analyzer_tpu.results import slice_rows
    from kafka_topic_analyzer_tpu.utils.profiling import maybe_jax_trace
    from kafka_topic_analyzer_tpu.utils.progress import Spinner
    from kafka_topic_analyzer_tpu.utils.timefmt import format_utc_seconds

    with user_input_phase():
        from_ts_ms = parse_from_timestamp_flag(args)
        # Dump tees attach per topic, before fan-in remaps partition ids.
        topic_sources = [
            (t, wrap_with_dump(args, t, make_source(args, topic=t, seed_salt=i)))
            for i, t in enumerate(topics)
        ]
        multi = MultiTopicSource(topic_sources)
    if multi.is_empty():
        print(
            "Given topic has no content, no analysis possible. Exiting.",
            file=sys.stderr,
        )
        sys.exit(-2)
    start_at, exhausted = resolve_start_offsets(
        multi, from_ts_ms, args.from_timestamp
    )
    if exhausted:
        return 0

    with user_input_phase():
        mesh_shape = parse_mesh(args.mesh)
        config = AnalyzerConfig(
            num_partitions=len(multi.partitions()),
            batch_size=args.batch_size,
            count_alive_keys=args.count_alive_keys,
            alive_bitmap_bits=args.alive_bitmap_bits,
            enable_hll=args.distinct_keys,
            distinct_keys_per_partition=args.distinct_keys_per_partition,
            enable_quantiles=args.quantiles,
            quantiles_per_partition=args.quantiles_per_partition,
            mesh_shape=mesh_shape,
            use_pallas_counters=args.pallas,
            wire_format=resolve_wire_format(args),
            alive_compaction=getattr(args, "alive_compaction", "auto"),
        )
        ingest_workers = resolve_ingest_workers(
            args, mesh_shape, len(multi.partitions())
        )
        dispatch = resolve_dispatch(args)
    backend = _make_cli_backend(args, config, mesh_shape, dispatch=dispatch)

    banner_out = sys.stderr if args.json else sys.stdout
    print(f"Subscribing to {', '.join(topics)} ({len(topics)}-topic fan-in)",
          file=banner_out)
    print("Starting message consumption...", file=banner_out)
    with maybe_jax_trace(args.profile_dir):
        result = run_scan(
            args.topic,
            multi,
            backend,
            batch_size=args.batch_size,
            spinner=Spinner(enabled=not args.quiet),
            snapshot_dir=args.snapshot_dir,
            snapshot_every_s=args.snapshot_every,
            resume=args.resume,
            start_at=start_at,
            ingest_workers=ingest_workers,
        )
    # Only the --stats digest and the --json flight block consume the
    # diagnosis; the plain report path skips the doctor pass entirely.
    diagnosis = _diagnose(result) if (args.stats or args.json) else None
    _print_stats(args, result, diagnosis)
    multi.close()  # flush per-topic segment dumps, release connections
    if _not_report_process(args):
        return _scan_issue_exit(result)  # multi-host: one report, from process 0

    union = result.metrics
    # Per-topic projections, computed once for both output formats.
    slices = []
    for topic in topics:
        rows = multi.rows_for(topic)
        ids = [multi.true_partition(r) for r in rows]
        sliced = slice_rows(union, rows, ids)
        start = {multi.true_partition(r): result.start_offsets[r] for r in rows}
        end = {multi.true_partition(r): result.end_offsets[r] for r in rows}
        slices.append((topic, sliced, start, end))

    if args.json:
        import json

        doc: dict = {
            "topics": {},
            "duration_secs": result.duration_secs,
            "ingest_workers": result.ingest_workers,
            "ingest_workers_per_controller": (
                result.ingest_workers_per_controller
            ),
            "superbatch_k": result.superbatch_k,
            "dispatch_depth": result.dispatch_depth,
        }
        for topic, sliced, start, end in slices:
            doc["topics"][topic] = sliced.to_dict(start, end)
        union_doc = {
            "count": union.overall_count,
            "size_bytes": union.overall_size,
            "earliest_ts": union.earliest_ts_s,
            "latest_ts": union.latest_ts_s,
        }
        if union.alive_keys is not None:
            union_doc["alive_keys_sum_over_topics"] = union.alive_keys
        if union.distinct_keys_hll is not None:
            union_doc["distinct_keys_hll"] = union.distinct_keys_hll
        if union.distinct_keys_exact is not None:
            union_doc["distinct_keys_exact"] = union.distinct_keys_exact
        if union.quantiles is not None:
            union_doc["size_quantiles"] = union.quantiles.as_dict()
        doc["union"] = union_doc
        doc["telemetry"] = result.telemetry
        from kafka_topic_analyzer_tpu.report import attach_scan_digests

        attach_scan_digests(doc, result, diagnosis)
        # Degraded keys are dense fan-in rows; reasons carry topic/partition.
        rc = _scan_issue_exit(result, doc=doc)
        print(json.dumps(doc))
        return rc
    # Per-topic reports from the shared projections.
    for topic, sliced, start, end in slices:
        # Extensions render only the per-row lines a slice can carry (e.g.
        # per-partition quantiles); merged union-only sketches are None here.
        sys.stdout.write(
            render_report(
                topic, sliced, start, end, result.duration_secs,
                show_alive_keys=False, show_extensions=True,
            )
        )
        if args.extremes_table:
            from kafka_topic_analyzer_tpu.report import render_extremes_table

            sys.stdout.write(render_extremes_table(sliced))

    # Union block: totals + merged sketches (not sliceable per topic).
    eq = "=" * 120
    print(eq)
    print(f"FAN-IN UNION of {len(topics)} topics: {', '.join(topics)}")
    print(f"Messages: {union.overall_count}")
    print(f"Bytes: {union.overall_size}")
    print(f"Earliest Message: {format_utc_seconds(union.earliest_ts_s)}")
    print(f"Latest Message: {format_utc_seconds(union.latest_ts_s)}")
    if args.count_alive_keys and union.alive_keys is not None:
        # Sum of per-topic alive keys (slots are salted per topic so the
        # count is mesh- and interleaving-independent; io/multi.py).
        print(f"Alive keys (sum over topics): {union.alive_keys}")
    if union.distinct_keys_hll is not None:
        print(f"Distinct keys (HLL est., union): {round(union.distinct_keys_hll)}")
    if union.distinct_keys_exact is not None:
        print(f"Distinct keys (exact, union): {union.distinct_keys_exact}")
    if union.quantiles is not None:
        qs = " ".join(
            f"p{int(p * 100)}={v:.0f}B"
            for p, v in zip(union.quantiles.probs, union.quantiles.values)
        )
        print(f"Message size quantiles (union): {qs}")
    print(eq)
    return _scan_issue_exit(
        result, render=True,
        data_loss_policy=getattr(args, "on_data_loss", "report"),
    )


def _fleet_exit(fleet_result) -> int:
    """Fleet exit precedence mirrors the solo scan's (degraded outranks
    corrupt — PR 3's contract) with one rung above both: a topic whose
    scan hard-failed (isolation caught it; its numbers are partial)."""
    if fleet_result.any_failed:
        return 1
    if fleet_result.any_degraded:
        return EXIT_DEGRADED
    if fleet_result.any_corrupt:
        return EXIT_CORRUPT
    if getattr(fleet_result, "any_data_loss", False):
        return EXIT_DATA_LOSS
    return 0


def make_lease_manager(cfg, snapshot_dir=None, store_spec=None):
    """Resolve a ``LeaseConfig`` + the run's stores into a live
    `fleet.lease.LeaseManager` (DESIGN §23): ``object`` leases ride the
    remote segment store's ETag-fenced conditional writes; ``file``
    leases ride atomic renames under the checkpoint dir; ``auto`` picks
    object exactly when the segment spec is remote."""
    import re as _re

    from kafka_topic_analyzer_tpu.config import (
        SegmentFetchConfig,
        TransportRetryConfig,
    )
    from kafka_topic_analyzer_tpu.fleet.lease import (
        FileLeaseStore,
        LeaseManager,
        ObjectLeaseStore,
    )
    from kafka_topic_analyzer_tpu.io.retry import Backoff

    remote = bool(
        store_spec and _re.match(r"^(https?|s3)://", str(store_spec))
    )
    choice = cfg.store
    if choice == "auto":
        choice = "object" if remote else "file"
    if choice == "object":
        if not remote:
            raise ValueError(
                "--lease-store object needs a remote --segment-dir spec "
                "(http://, https://, s3://) to host the lease records"
            )
        from kafka_topic_analyzer_tpu.io.objstore import RetryingHttp

        store = ObjectLeaseStore(
            RetryingHttp(str(store_spec), SegmentFetchConfig())
        )
    else:
        if not snapshot_dir:
            raise ValueError(
                "--instance-id needs a lease store: pass --snapshot-dir "
                "(file leases live in SNAPSHOT_DIR/_kta_leases/) or a "
                "remote --segment-dir spec with --lease-store object"
            )
        store = FileLeaseStore(snapshot_dir)
    return LeaseManager(
        store,
        instance=cfg.instance_id,
        ttl_s=cfg.ttl_s,
        backoff=Backoff(TransportRetryConfig()),
    )


def run_fleet(args, topics: "list[str] | None" = None) -> int:
    """Cluster-wide scan (--fleet), or an explicit multi-topic follow
    (``-t a,b --follow`` — each topic keeps its solo pass chain; the
    fleet scheduler shares the budgets).  ``topics`` pins the list and
    skips discovery."""
    from kafka_topic_analyzer_tpu.config import IngestConfig
    from kafka_topic_analyzer_tpu.fleet.discovery import (
        discover_topics,
        parse_globs,
    )
    from kafka_topic_analyzer_tpu.fleet.scheduler import (
        FleetScheduler,
        TopicSeed,
    )
    from kafka_topic_analyzer_tpu.fleet.service import FleetService

    with user_input_phase():
        if args.source != "kafka":
            raise ValueError(
                "--fleet requires --source kafka (discovery reads cluster "
                "metadata); a segment store is one topic's immutable "
                "archive with no topic list or moving head — scan it solo "
                "with --source segfile (synthetic sources scan solo too)"
            )
        if not args.bootstrap_server:
            raise SystemExit("--fleet requires -b/--bootstrap-server")
        mesh_shape = parse_mesh(args.mesh)
        if mesh_shape != (1, 1):
            raise ValueError(
                "--fleet does not support --mesh yet (fleet scans run "
                "per-topic single-device backends); drop --mesh, or scan "
                "one topic solo to use a device mesh"
            )
        if args.distributed:
            raise ValueError(
                "--fleet does not support --distributed (per-poll "
                "admission would need fleet-wide lockstep agreement); "
                "run the fleet single-controller"
            )
        if args.dump_segments:
            raise ValueError(
                "--fleet does not support --dump-segments (the dump tee "
                "is single-topic); run a solo scan with --dump-segments "
                "per topic instead"
            )
        if args.from_timestamp:
            raise ValueError(
                "--fleet does not support --from-timestamp yet (the "
                "cutoff would need a per-topic seek); scan the topic "
                "solo with --from-timestamp instead"
            )
        dispatch = resolve_dispatch(args)
        ingest_cfg = IngestConfig.parse(args.ingest_workers)
        text = str(args.fleet_concurrency).strip().lower()
        explicit_concurrency = None
        if text != "auto":
            try:
                explicit_concurrency = int(text)
            except ValueError:
                raise ValueError(
                    f"bad --fleet-concurrency {args.fleet_concurrency!r}: "
                    "expected a positive integer or 'auto'"
                ) from None
            if explicit_concurrency < 1:
                raise ValueError("--fleet-concurrency must be >= 1")

    banner_out = sys.stderr if args.json else sys.stdout
    rediscover = None
    if topics is None:
        include = parse_globs(args.topic) or ["*"]
        exclude = parse_globs(args.fleet_exclude)

        def discover() -> "list[TopicSeed]":
            return [
                TopicSeed(name=d.name, partitions=d.partitions)
                for d in discover_topics(
                    args.bootstrap_server, include, exclude,
                    args.fleet_internal,
                )
            ]

        seeds = discover()
        if args.follow:
            rediscover = discover
        print(
            f"Fleet discovery: {len(seeds)} topic(s) matched "
            f"{','.join(include)}"
            + (f" minus {','.join(exclude)}" if exclude else ""),
            file=banner_out,
        )
    else:
        # Explicit list (multi-topic --follow): real partition counts
        # come from one all-topics metadata round trip — the worker
        # budget below is resolved against them, and a placeholder of 1
        # would silently cap the whole fleet at len(topics) workers.  An
        # unreachable cluster keeps the placeholders; every scan then
        # fails in isolation and the service exits, like solo.
        parts_by_name: "dict[str, int]" = {}
        try:
            wanted = set(topics)
            for d in discover_topics(
                args.bootstrap_server, include_internal=True
            ):
                if d.name in wanted:
                    parts_by_name[d.name] = d.partitions
        except Exception as e:
            print(
                f"warning: could not size the fleet from cluster "
                f"metadata ({e}); worker budget assumes 1 partition "
                "per topic",
                file=sys.stderr,
            )
        seeds = [
            TopicSeed(name=t, partitions=parts_by_name.get(t, 1))
            for t in topics
        ]
    if not seeds:
        print(
            "No topics matched the fleet filters, no analysis possible. "
            "Exiting.",
            file=sys.stderr,
        )
        sys.exit(-2)

    total_parts = sum(max(1, s.partitions) for s in seeds)
    worker_budget = ingest_cfg.resolve(max(1, total_parts))
    max_concurrent = (
        explicit_concurrency
        if explicit_concurrency is not None
        else max(1, min(4, len(seeds), worker_budget))
    )
    # Under --fleet, --dispatch-depth is the GLOBAL in-flight budget the
    # concurrent device scans share (each admitted scan holds >= 1
    # token).  The cpu oracle has no dispatch queue, so its token budget
    # just matches the concurrency.
    dispatch_budget = (
        max(1, args.dispatch_depth)
        if args.backend == "tpu"
        else max_concurrent
    )

    def source_factory(topic: str):
        return make_source(args, topic=topic)

    def backend_factory(topic: str, num_partitions: int, grant):
        with user_input_phase():
            config = AnalyzerConfig(
                num_partitions=num_partitions,
                batch_size=args.batch_size,
                count_alive_keys=args.count_alive_keys,
                alive_bitmap_bits=args.alive_bitmap_bits,
                enable_hll=args.distinct_keys,
                distinct_keys_per_partition=args.distinct_keys_per_partition,
                enable_quantiles=args.quantiles,
                quantiles_per_partition=args.quantiles_per_partition,
                mesh_shape=(1, 1),
                use_pallas_counters=args.pallas,
                wire_format=resolve_wire_format(args),
                alive_compaction=getattr(args, "alive_compaction", "auto"),
            )
        topic_dispatch = None
        if dispatch is not None:
            from kafka_topic_analyzer_tpu.config import DispatchConfig

            topic_dispatch = DispatchConfig(
                superbatch=dispatch.superbatch,
                depth=grant.dispatch_depth,
            )
        return _make_cli_backend(args, config, (1, 1), dispatch=topic_dispatch)

    follow_cfg = None
    if args.follow:
        with user_input_phase():
            from kafka_topic_analyzer_tpu.config import FollowConfig

            follow_cfg = FollowConfig(
                poll_interval_s=args.poll_interval,
                checkpoint_every_s=(
                    args.checkpoint_interval
                    if args.checkpoint_interval is not None
                    else args.snapshot_every
                ),
                idle_exit_s=args.follow_idle_exit,
            )

    lease_mgr = None
    instance = "solo"
    if getattr(args, "instance_id", None):
        with user_input_phase():
            from kafka_topic_analyzer_tpu.config import LeaseConfig

            instance = args.instance_id
            if instance == "auto":
                # Bare --instance-id: HOSTNAME-PID is unique per process
                # on a shared cluster, which is all a lease owner needs.
                import os
                import socket

                instance = f"{socket.gethostname()}-{os.getpid()}"
            lease_cfg = LeaseConfig(
                instance_id=instance,
                ttl_s=args.lease_ttl,
                store=args.lease_store,
            )
            lease_mgr = make_lease_manager(
                lease_cfg,
                snapshot_dir=args.snapshot_dir,
                store_spec=getattr(args, "segment_dir", None),
            )

    scheduler = FleetScheduler(
        worker_budget, dispatch_budget, max_concurrent, instance=instance
    )
    from kafka_topic_analyzer_tpu.utils.progress import Spinner

    svc = FleetService(
        seeds,
        source_factory,
        backend_factory,
        args.batch_size,
        scheduler,
        follow=follow_cfg,
        snapshot_dir=args.snapshot_dir,
        resume=args.resume,
        # /report.json assembly is pure waste when no HTTP server exists
        # to serve it (same rule as the solo follow service).
        publish_reports=args.metrics_port is not None,
        serve_gzip=not args.no_serve_gzip,
        spinner=Spinner(enabled=not args.quiet),
        rediscover=rediscover,
        leases=lease_mgr,
        instance=instance,
    )
    print(
        f"Fleet scan of {len(seeds)} topic(s): "
        f"{worker_budget} worker(s), dispatch budget {dispatch_budget}, "
        f"concurrency {max_concurrent}"
        + (" (follow)" if args.follow else "")
        + (
            f" [instance {instance}, lease TTL {args.lease_ttl:g}s]"
            if lease_mgr is not None else ""
        ),
        file=banner_out,
    )
    if args.follow:
        restore = svc.install_signal_handlers()
        try:
            fleet_result = svc.run_follow()
        finally:
            restore()
    else:
        fleet_result = svc.run_batch()

    if args.stats:
        from kafka_topic_analyzer_tpu.obs.registry import default_registry
        from kafka_topic_analyzer_tpu.report import (
            render_fleet_status,
            render_telemetry_stats,
        )

        sys.stderr.write(render_fleet_status(fleet_result.rollup))
        sys.stderr.write(
            render_telemetry_stats(default_registry().snapshot())
        )
        _print_health_stats()
    if args.json:
        import json

        from kafka_topic_analyzer_tpu.report import build_json_doc

        doc = dict(fleet_result.rollup)
        doc["topics"] = {
            t: build_json_doc(
                t,
                result,
                diagnosis=_diagnose(result),
                fleet=fleet_result.statuses[t].as_dict(),
            )
            for t, result in sorted(fleet_result.results.items())
        }
        rc = _fleet_exit(fleet_result)
        print(json.dumps(doc))
        return rc
    from kafka_topic_analyzer_tpu.report import (
        render_fleet_status,
        render_report,
    )

    for t, result in sorted(fleet_result.results.items()):
        sys.stdout.write(
            render_report(
                t,
                result.metrics,
                result.start_offsets,
                result.end_offsets,
                result.duration_secs,
                show_alive_keys=args.count_alive_keys,
            )
        )
    sys.stdout.write(render_fleet_status(fleet_result.rollup))
    return _fleet_exit(fleet_result)


def main(argv: "list[str] | None" = None) -> int:
    from kafka_topic_analyzer_tpu.utils.log import init_logging

    init_logging()  # env_logger parity: RUST_LOG / KTA_LOG (src/main.rs:30)
    args = build_parser().parse_args(argv)
    from kafka_topic_analyzer_tpu.io.kafka_codec import KafkaProtocolError
    from kafka_topic_analyzer_tpu.obs import telemetry_session

    history_dir = None
    if args.history_bytes:
        if args.history_bytes < 4096:
            print("error: --history-bytes must be >= 4096", file=sys.stderr)
            return 1
        if not args.snapshot_dir:
            print(
                "error: --history-bytes requires --snapshot-dir (the "
                "telemetry history lives next to the checkpoints so a "
                "restarted service resumes both from one place)",
                file=sys.stderr,
            )
            return 1
        from kafka_topic_analyzer_tpu.checkpoint import (
            history_dir as _history_dir,
        )

        history_dir = _history_dir(args.snapshot_dir)
    try:
        with telemetry_session(
            metrics_port=args.metrics_port,
            events_jsonl=args.events_jsonl,
            trace_json=args.trace_json,
            flight_record=args.flight_record,
            history_dir=history_dir,
            history_bytes=args.history_bytes,
            sse=args.sse,
        ):
            return _run(args)
    except (OSError, KafkaProtocolError) as e:
        # Environment/user-facing failures get one clean line, not a
        # traceback (the reference panics here; we can do better).  Other
        # exception types — including internal ValueErrors — keep their
        # tracebacks so bugs stay diagnosable.
        from kafka_topic_analyzer_tpu.io.kafka_wire import DataLossError

        if isinstance(e, DataLossError):
            # --on-data-loss=fail abort: the loss is booked and a
            # fold-consistent checkpoint was written on the way out, so
            # the distinct exit code tells automation a --resume will
            # continue past the NAMED gap (not a hard failure).
            print(f"error: DATA-LOSS: {e}", file=sys.stderr)
            return EXIT_DATA_LOSS
        print(f"error: {e}", file=sys.stderr)
        return 1
    except UserInputError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1


def _run(args) -> int:
    if args.distributed:
        from kafka_topic_analyzer_tpu.parallel.mesh import initialize_distributed

        with user_input_phase():
            initialize_distributed(args.distributed)
    if args.fleet:
        return run_fleet(args)
    if getattr(args, "instance_id", None) and not (
        "," in args.topic and args.follow
    ):
        with user_input_phase():
            raise ValueError(
                "--instance-id requires the fleet scheduler (topic "
                "ownership leases arbitrate WHOLE topics between "
                "analyzer instances; a solo or batch fan-in scan has "
                "no admission to arbitrate) — add --fleet, or --follow "
                "with a multi-topic list"
            )
    # Kafka topic names cannot contain commas, so "-t a,b,c" unambiguously
    # selects multi-topic fan-in (new capability; BASELINE.json config 5).
    if "," in args.topic:
        if args.follow:
            # Lifted (fleet mode): an explicit topic list under --follow
            # runs through the fleet scheduler — each topic keeps its
            # solo pass chain (NOT the fan-in's merged state), budgets
            # are shared, and /report.json?topic= serves each document —
            # so --instance-id leases compose here too.
            return run_fleet(
                args, topics=[t for t in args.topic.split(",") if t]
            )
        return run_multi_topic(args, [t for t in args.topic.split(",") if t])
    with user_input_phase():
        # Cheap flag validation first — before any broker handshake or dump
        # directory creation.
        if args.follow and args.source == "segfile":
            raise ValueError(
                "--follow cannot tail --source segfile (a segment store "
                "is immutable — there is no moving head to poll); run "
                "the batch scan of the store, or --follow the live "
                "topic with --source kafka (add --dump-segments to keep "
                "the archive fresh)"
            )
        from_ts_ms = parse_from_timestamp_flag(args)
        source = wrap_with_dump(args, args.topic, make_source(args))
        start_at, exhausted = resolve_start_offsets(
            source, from_ts_ms, args.from_timestamp
        )
        if exhausted:
            return 0

    # Empty-topic guard: exit(-2) like src/main.rs:98-101.  A follow
    # service deliberately skips it — sitting on a still-empty topic and
    # waiting for the first record IS the job.
    if source.is_empty() and not args.follow:
        print(
            "Given topic has no content, no analysis possible. Exiting.",
            file=sys.stderr,
        )
        sys.exit(-2)

    with user_input_phase():
        mesh_shape = parse_mesh(args.mesh)
        config = AnalyzerConfig(
            num_partitions=len(source.partitions()),
            batch_size=args.batch_size,
            count_alive_keys=args.count_alive_keys,
            alive_bitmap_bits=args.alive_bitmap_bits,
            enable_hll=args.distinct_keys,
            distinct_keys_per_partition=args.distinct_keys_per_partition,
            enable_quantiles=args.quantiles,
            quantiles_per_partition=args.quantiles_per_partition,
            mesh_shape=mesh_shape,
            use_pallas_counters=args.pallas,
            wire_format=resolve_wire_format(args),
            alive_compaction=getattr(args, "alive_compaction", "auto"),
        )
        ingest_workers = resolve_ingest_workers(
            args, mesh_shape, len(source.partitions())
        )
        dispatch = resolve_dispatch(args)

    from kafka_topic_analyzer_tpu.engine import run_scan
    from kafka_topic_analyzer_tpu.report import render_report
    from kafka_topic_analyzer_tpu.utils.profiling import maybe_jax_trace
    from kafka_topic_analyzer_tpu.utils.progress import Spinner

    backend = _make_cli_backend(args, config, mesh_shape, dispatch=dispatch)

    banner_out = sys.stderr if args.json else sys.stdout
    print(f"Subscribing to {args.topic}", file=banner_out)
    print("Starting message consumption...", file=banner_out)
    follow_service = None
    with maybe_jax_trace(args.profile_dir):
        if args.follow:
            from kafka_topic_analyzer_tpu.config import FollowConfig
            from kafka_topic_analyzer_tpu.serve.follow import FollowService

            with user_input_phase():
                follow_cfg = FollowConfig(
                    poll_interval_s=args.poll_interval,
                    checkpoint_every_s=(
                        args.checkpoint_interval
                        if args.checkpoint_interval is not None
                        else args.snapshot_every
                    ),
                    idle_exit_s=args.follow_idle_exit,
                    window_secs=args.window_secs,
                    window_count=args.window_count,
                )
            with user_input_phase():
                follow_service = FollowService(
                    args.topic,
                    source,
                    backend,
                    batch_size=args.batch_size,
                    follow=follow_cfg,
                    spinner=Spinner(enabled=not args.quiet),
                    snapshot_dir=args.snapshot_dir,
                    resume=args.resume,
                    start_at=start_at,
                    ingest_workers=ingest_workers,
                    # /report.json assembly is pure waste when no HTTP
                    # server exists to serve it.
                    publish_reports=args.metrics_port is not None,
                    serve_gzip=not args.no_serve_gzip,
                )
            restore_signals = follow_service.install_signal_handlers()
            try:
                result = follow_service.run()
            finally:
                restore_signals()
        else:
            result = run_scan(
                args.topic,
                source,
                backend,
                batch_size=args.batch_size,
                spinner=Spinner(enabled=not args.quiet),
                snapshot_dir=args.snapshot_dir,
                snapshot_every_s=args.snapshot_every,
                resume=args.resume,
                start_at=start_at,
                ingest_workers=ingest_workers,
            )
    # Only the --stats digest and the --json flight block consume the
    # diagnosis; the plain report path skips the doctor pass entirely.
    diagnosis = _diagnose(result) if (args.stats or args.json) else None
    _print_stats(args, result, diagnosis)
    if hasattr(source, "close"):
        source.close()  # flush segment dumps, release broker connections
    if _not_report_process(args):
        # Multi-host: one report, from process 0 — but every process must
        # agree on the degraded exit code for orchestrators (run_scan
        # reduces the degraded flag across processes).
        return _scan_issue_exit(result)

    if args.json:
        import json

        from kafka_topic_analyzer_tpu.obs import health as obs_health
        from kafka_topic_analyzer_tpu.report import build_json_doc

        health_engine = obs_health.active()
        if health_engine is not None and health_engine.doc() is None:
            # Sub-interval scans never hit a heartbeat boundary; the
            # document must still carry one real evaluation — a missing
            # health key would be indistinguishable from "alerting
            # never ran" (same rule as the --stats digest).
            health_engine.evaluate()
        doc = build_json_doc(
            args.topic,
            result,
            diagnosis=diagnosis,
            follow=(
                follow_service.follow_block()
                if follow_service is not None
                else None
            ),
            windows=(
                follow_service.windows_report()
                if follow_service is not None
                else None
            ),
            health=(
                health_engine.alerts_block()
                if health_engine is not None
                else None
            ),
        )
        rc = _scan_issue_exit(result)
        print(json.dumps(doc))
        return rc
    sys.stdout.write(
        render_report(
            args.topic,
            result.metrics,
            result.start_offsets,
            result.end_offsets,
            result.duration_secs,
            show_alive_keys=args.count_alive_keys,
        )
    )
    if args.extremes_table:
        from kafka_topic_analyzer_tpu.report import render_extremes_table

        sys.stdout.write(render_extremes_table(result.metrics))
    return _scan_issue_exit(
        result, render=True,
        data_loss_policy=getattr(args, "on_data_loss", "report"),
    )


if __name__ == "__main__":
    sys.exit(main())
