"""DDSketch quantile state (device) — see ops/ddsketch.py for the kernels."""

from __future__ import annotations

import dataclasses

import jax

from kafka_topic_analyzer_tpu.config import AnalyzerConfig
from kafka_topic_analyzer_tpu.jax_support import jnp
from kafka_topic_analyzer_tpu.ops.ddsketch import ddsketch_num_buckets


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class DDSketchState:
    counts: jax.Array  # int64[R, nbuckets + 2]; R = P or 1

    @classmethod
    def init(cls, config: AnalyzerConfig) -> "DDSketchState":
        n = ddsketch_num_buckets(config.quantile_buckets)
        rows = config.num_partitions if config.quantiles_per_partition else 1
        return cls(counts=jnp.zeros((rows, n), dtype=jnp.int64))

    def merge(self, other: "DDSketchState") -> "DDSketchState":
        return DDSketchState(counts=self.counts + other.counts)
