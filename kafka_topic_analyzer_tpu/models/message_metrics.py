"""Exact counter state — the TPU analog of ``MessageMetrics``.

State layout (vs. the reference's seven ``HashMap<i32, u64>`` buckets plus
six globals, src/metric.rs:12-26): one dense ``int64[P, 7]`` matrix (channel
order ``results.COUNTER_CHANNELS``) plus six int64 scalars.  Everything is
exact integer arithmetic — no sketching — and every field merges
associatively (sums add; extremes min/max), which is what makes the state
shardable across devices with ``psum``/``pmin``/``pmax``.
"""

from __future__ import annotations

import dataclasses

import jax

from kafka_topic_analyzer_tpu.config import AnalyzerConfig
from kafka_topic_analyzer_tpu.jax_support import jnp
from kafka_topic_analyzer_tpu.ops.counters import I64_MAX, I64_MIN


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class MessageMetricsState:
    """Extremes are tracked per partition (the reference keeps only global
    scalars, src/metric.rs:20-23): per-partition min/max is a new capability
    in its own right, and it is what makes multi-topic fan-in reports exact
    — any row slice of the state reconstructs that topic's extremes, and the
    reference's global lines are reductions over rows (finalize)."""

    per_partition: jax.Array  # int64[P, 7]
    earliest_s: jax.Array     # int64[P], I64_MAX until first record
    latest_s: jax.Array       # int64[P], I64_MIN until first record
    smallest: jax.Array       # int64[P], I64_MAX until first sized record
    largest: jax.Array        # int64[P]
    overall_size: jax.Array   # int64 scalar
    overall_count: jax.Array  # int64 scalar

    @classmethod
    def init(cls, config: AnalyzerConfig) -> "MessageMetricsState":
        p = config.num_partitions
        # Note: every leaf must be a distinct buffer — the TPU backend donates
        # the whole state, and XLA rejects donating one buffer twice.
        return cls(
            per_partition=jnp.zeros((p, 7), dtype=jnp.int64),
            earliest_s=jnp.full((p,), I64_MAX, dtype=jnp.int64),
            latest_s=jnp.full((p,), I64_MIN, dtype=jnp.int64),
            smallest=jnp.full((p,), I64_MAX, dtype=jnp.int64),
            largest=jnp.zeros((p,), dtype=jnp.int64),
            overall_size=jnp.int64(0),
            overall_count=jnp.int64(0),
        )

    def merge(self, other: "MessageMetricsState") -> "MessageMetricsState":
        return MessageMetricsState(
            per_partition=self.per_partition + other.per_partition,
            earliest_s=jnp.minimum(self.earliest_s, other.earliest_s),
            latest_s=jnp.maximum(self.latest_s, other.latest_s),
            smallest=jnp.minimum(self.smallest, other.smallest),
            largest=jnp.maximum(self.largest, other.largest),
            overall_size=self.overall_size + other.overall_size,
            overall_count=self.overall_count + other.overall_count,
        )

