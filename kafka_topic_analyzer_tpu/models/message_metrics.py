"""Exact counter state — the TPU analog of ``MessageMetrics``.

State layout (vs. the reference's seven ``HashMap<i32, u64>`` buckets plus
six globals, src/metric.rs:12-26): one dense ``int64[P, 7]`` matrix (channel
order ``results.COUNTER_CHANNELS``) plus six int64 scalars.  Everything is
exact integer arithmetic — no sketching — and every field merges
associatively (sums add; extremes min/max), which is what makes the state
shardable across devices with ``psum``/``pmin``/``pmax``.
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np

from kafka_topic_analyzer_tpu.config import AnalyzerConfig
from kafka_topic_analyzer_tpu.jax_support import jnp
from kafka_topic_analyzer_tpu.ops.counters import I64_MAX, I64_MIN
from kafka_topic_analyzer_tpu.results import U64_MAX


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class MessageMetricsState:
    per_partition: jax.Array  # int64[P, 7]
    earliest_s: jax.Array     # int64 scalar, I64_MAX until first record
    latest_s: jax.Array       # int64 scalar, I64_MIN until first record
    smallest: jax.Array       # int64 scalar, I64_MAX until first sized record
    largest: jax.Array        # int64 scalar
    overall_size: jax.Array   # int64 scalar
    overall_count: jax.Array  # int64 scalar

    @classmethod
    def init(cls, config: AnalyzerConfig) -> "MessageMetricsState":
        # Note: every leaf must be a distinct buffer — the TPU backend donates
        # the whole state, and XLA rejects donating one buffer twice.
        return cls(
            per_partition=jnp.zeros((config.num_partitions, 7), dtype=jnp.int64),
            earliest_s=jnp.int64(I64_MAX),
            latest_s=jnp.int64(I64_MIN),
            smallest=jnp.int64(I64_MAX),
            largest=jnp.int64(0),
            overall_size=jnp.int64(0),
            overall_count=jnp.int64(0),
        )

    def merge(self, other: "MessageMetricsState") -> "MessageMetricsState":
        return MessageMetricsState(
            per_partition=self.per_partition + other.per_partition,
            earliest_s=jnp.minimum(self.earliest_s, other.earliest_s),
            latest_s=jnp.maximum(self.latest_s, other.latest_s),
            smallest=jnp.minimum(self.smallest, other.smallest),
            largest=jnp.maximum(self.largest, other.largest),
            overall_size=self.overall_size + other.overall_size,
            overall_count=self.overall_count + other.overall_count,
        )


def finalize_extremes(
    earliest_s: int, latest_s: int, smallest: int, init_now_s: int
) -> "tuple[int, int, int]":
    """Map sentinel-initialized extremes to the reference's reporting values.

    The reference initializes ``earliest_message`` to *scan start time* and
    ``latest_message`` to epoch 0 (src/metric.rs:40-41), so the reported
    earliest is ``min(now, min_ts)`` and latest is ``max(0, max_ts)``;
    ``smallest_message`` reports u64::MAX → 0 handled via `results`.
    """
    earliest = min(init_now_s, earliest_s) if earliest_s != I64_MAX else init_now_s
    latest = max(0, latest_s) if latest_s != I64_MIN else 0
    smallest_u64 = U64_MAX if smallest == int(I64_MAX) else smallest
    return earliest, latest, smallest_u64


def state_to_numpy(state: MessageMetricsState) -> "dict[str, np.ndarray]":
    return {
        f.name: np.asarray(getattr(state, f.name))
        for f in dataclasses.fields(MessageMetricsState)
    }
