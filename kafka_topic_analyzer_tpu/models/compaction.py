"""Alive-key tracking states for log-compacted topics (``-c`` flag).

Two models, selected by config:

- `AliveBitmapState` — reference-compatible: packed bits over the fnv32 slot
  space, identical collision semantics to ``LogCompactionInMemoryMetrics``'s
  ``BitSet`` (src/metric.rs:262-305) when ``alive_bitmap_bits=32``.  2^32
  slots = 512 MiB of HBM; optionally sharded over the mesh's 'space' axis.
- `HLLState` — sketch of *distinct keys ever seen* (insertions only; an HLL
  cannot observe deletions, so it reports key cardinality, not aliveness —
  the right tool for BASELINE.json config 3's 50M-key distinct count).
"""

from __future__ import annotations

import dataclasses

import jax

from kafka_topic_analyzer_tpu.config import AnalyzerConfig
from kafka_topic_analyzer_tpu.jax_support import jnp
from kafka_topic_analyzer_tpu.ops.bitmap import bitmap_num_words


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class AliveBitmapState:
    words: jax.Array  # uint32[W] packed bits (this shard's slot range)

    @classmethod
    def init(cls, config: AnalyzerConfig) -> "AliveBitmapState":
        w = bitmap_num_words(config.alive_bitmap_bits, config.space_shards)
        return cls(words=jnp.zeros((w,), dtype=jnp.uint32))

    def merge(self, other: "AliveBitmapState") -> "AliveBitmapState":
        return AliveBitmapState(words=self.words | other.words)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class HLLState:
    regs: jax.Array  # int32[R, 2^p]; R = P or 1

    @classmethod
    def init(cls, config: AnalyzerConfig) -> "HLLState":
        rows = (
            config.num_partitions if config.distinct_keys_per_partition else 1
        )
        return cls(regs=jnp.zeros((rows, config.hll_m), dtype=jnp.int32))

    def merge(self, other: "HLLState") -> "HLLState":
        return HLLState(regs=jnp.maximum(self.regs, other.regs))
