"""Combined analyzer state — the single pytree carried across device steps.

Optional sub-states are ``None`` when their feature is disabled (None leaves
are empty subtrees in jax pytrees, so one code path covers every feature
combination; each combination is its own jit specialization).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax

from kafka_topic_analyzer_tpu.config import AnalyzerConfig
from kafka_topic_analyzer_tpu.models.compaction import AliveBitmapState, HLLState
from kafka_topic_analyzer_tpu.models.message_metrics import MessageMetricsState
from kafka_topic_analyzer_tpu.models.quantiles import DDSketchState


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class AnalyzerState:
    metrics: MessageMetricsState
    alive: Optional[AliveBitmapState]
    hll: Optional[HLLState]
    quantiles: Optional[DDSketchState]

    @classmethod
    def init(cls, config: AnalyzerConfig) -> "AnalyzerState":
        return cls(
            metrics=MessageMetricsState.init(config),
            alive=AliveBitmapState.init(config) if config.count_alive_keys else None,
            hll=HLLState.init(config) if config.enable_hll else None,
            quantiles=DDSketchState.init(config) if config.enable_quantiles else None,
        )

    def merge(self, other: "AnalyzerState") -> "AnalyzerState":
        return AnalyzerState(
            metrics=self.metrics.merge(other.metrics),
            alive=self.alive.merge(other.alive) if self.alive is not None else None,
            hll=self.hll.merge(other.hll) if self.hll is not None else None,
            quantiles=(
                self.quantiles.merge(other.quantiles)
                if self.quantiles is not None
                else None
            ),
        )
