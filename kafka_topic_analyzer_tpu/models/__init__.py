"""Metric state models.

Each model owns a fixed-shape, associatively-mergeable accumulator state (a
jax pytree) plus init/finalize logic.  The pure batch-update kernels live in
`kafka_topic_analyzer_tpu.ops`; backends wire models and ops together.  This
mirrors the reference's split between metric state (``src/metric.rs:12-26``)
and its per-message update (``src/metric.rs:206-253``) — with the update
re-shaped from per-message virtual dispatch into batched reductions.
"""

from kafka_topic_analyzer_tpu.models.message_metrics import MessageMetricsState  # noqa: F401
from kafka_topic_analyzer_tpu.models.compaction import AliveBitmapState, HLLState  # noqa: F401
from kafka_topic_analyzer_tpu.models.quantiles import DDSketchState  # noqa: F401
from kafka_topic_analyzer_tpu.models.state import AnalyzerState  # noqa: F401
