"""Host-ingest capacity benchmark: where does the wire drain bind, and what
is the per-core ceiling?  (VERDICT r2 next #3.)

Three measurements, one JSON line:

1. ``drain_msgs_per_sec`` — wall-clock drain through a real loopback TCP
   broker (the honest single-stream number; on a 1-core container the
   serving process shares the core, so this UNDERSTATES a dedicated core).
2. ``drain_cpu_msgs_per_sec`` — records / client-process CPU seconds
   (``os.times``; the spawned broker is excluded): the rate one dedicated
   core sustains INCLUDING its share of kernel TCP receive work.
3. ``pipeline_msgs_per_sec`` — the socket-free client pipeline (native
   record-set scan + decode + range-accept + re-batching) over pre-built
   wire buffers: the per-core capacity when bytes arrive for free (in
   production, NIC/softirq work lands on other cores and the remote
   broker's send cost is not ours).

The per-core ceiling analysis derived from these lives in BENCH_NOTES.md.
This replaces profiling the reference's consume loop (src/kafka.rs:92-135,
whose published figure is 590,221 msgs/s end to end).
"""

from __future__ import annotations

import argparse
import json
import os
import struct
import sys
import threading
import time

import numpy as np

from kafka_topic_analyzer_tpu.io import kafka_codec as kc


class _StallSampler(threading.Thread):
    """Measures the client process's longest GIL-held stretches during a
    drain: a thread asking for a 1 ms sleep can only resume once it can
    re-acquire the GIL, so (observed - requested) bounds the serialized
    GIL-held share that would block a second drain thread (VERDICT r3 #5 /
    r4 #3 — is the 3.1M rec/s/core x N-core extrapolation killed by the
    GIL?).  On a 1-core box, OS timeslices granted to the broker child
    land in the same delay, so this is an UPPER bound on GIL stalls."""

    def __init__(self) -> None:
        super().__init__(daemon=True)
        self.delays: "list[float]" = []
        # NB: not named _stop — threading.Thread uses a _stop() method
        # internally; shadowing it with an Event breaks join().
        self._halt = threading.Event()

    def run(self) -> None:
        while not self._halt.is_set():
            t0 = time.perf_counter()
            time.sleep(0.001)
            self.delays.append(time.perf_counter() - t0 - 0.001)

    def finish(self) -> "dict[str, float]":
        self._halt.set()
        self.join(2)
        if self.is_alive():
            # The sampler never confirmed stopping (a pathological stall
            # outlived the join timeout): computing percentiles would race
            # its ongoing appends — list growth mid-sort can misindex.
            # No numbers beat wrong numbers in a published benchmark.
            return {}
        # Snapshot only now that the thread has provably exited: the list
        # is quiescent, so sort + percentile indexing see one stable view.
        delays = list(self.delays)
        if not delays:
            return {}
        d = np.sort(np.asarray(delays))
        return {
            "gil_stall_p50_ms": round(float(d[len(d) // 2]) * 1e3, 2),
            "gil_stall_p99_ms": round(float(d[int(len(d) * 0.99)]) * 1e3, 2),
            "gil_stall_max_ms": round(float(d[-1]) * 1e3, 2),
        }


def _drain_stream(port: int, topic: str, batch_size: int, barrier,
                  out: "list", idx: int) -> None:
    """One stream's drain: own wire client, own loopback broker.  All
    streams rendezvous after connection setup so the timed window measures
    concurrent drains, not staggered ones."""
    from kafka_topic_analyzer_tpu.io.kafka_wire import KafkaWireSource

    src = KafkaWireSource(f"127.0.0.1:{port}", topic)
    try:
        barrier.wait(timeout=120)
        got = 0
        t0 = time.perf_counter()
        for batch in src.batches(batch_size):
            got += len(batch)
        out[idx] = (got, time.perf_counter() - t0)
    finally:
        src.close()


def _patched_record_sets(templates: "list[bytes]", windows: int,
                         records_per_batch: int,
                         frames_per_set: int = 8) -> "list[bytes]":
    """Record sets of ``frames_per_set`` consecutive base_offset-patched
    template frames (the first 8 bytes of a v2 frame are not CRC-covered) —
    the same multi-frame-per-response shape real fetch responses have, so
    per-decode-call fixed costs are amortized like the wire client's."""
    out = []
    group = bytearray()
    for w in range(windows):
        t = bytearray(templates[w % len(templates)])
        struct.pack_into(">q", t, 0, w * records_per_batch)
        group += t
        if (w + 1) % frames_per_set == 0:
            out.append(bytes(group))
            group = bytearray()
    if group:
        out.append(bytes(group))
    return out


def measure_pipeline(record_sets: "list[bytes]", total_records: int,
                     batch_size: int, verify_crc: bool) -> "tuple[int, float]":
    """Drive scan → decode → accept → re-batch over in-memory buffers,
    mirroring the wire client's per-response hot path (kafka_wire.py
    fetch_leader phase 1 + accept_records + flush)."""
    from kafka_topic_analyzer_tpu.io.kafka_wire import _chunk_to_batch
    from kafka_topic_analyzer_tpu.io.native import (
        decode_record_set_native,
        scan_record_set_native,
    )
    from kafka_topic_analyzer_tpu.records import RecordBatch

    total = total_records
    pend: "list[RecordBatch]" = []
    pend_count = 0
    n_out = 0
    t0 = time.perf_counter()
    for rs in record_sets:
        prescan = scan_record_set_native(rs, verify_crc)
        soa, used, covered = decode_record_set_native(
            rs, verify_crc, prescan=prescan
        )
        offs = soa["offsets"]
        hi = int(np.searchsorted(offs, total, "left"))
        pend.append(_chunk_to_batch(soa, slice(0, hi), 0))
        pend_count += hi
        if pend_count >= batch_size:
            out, pend, pend_count = RecordBatch.resplit(
                pend, batch_size, force=False
            )
            n_out += sum(len(b) for b in out)
    n_out += pend_count
    return n_out, time.perf_counter() - t0


def _bench_pack_config(partitions: int, batch_size: int):
    """The representative full-featured pack config both decode→pack
    referees share (alive bitmap + HLL — the default heavy path)."""
    from kafka_topic_analyzer_tpu.config import AnalyzerConfig

    return AnalyzerConfig(
        num_partitions=partitions, batch_size=batch_size,
        count_alive_keys=True, enable_hll=True,
    )


def measure_pipeline_chained(record_sets: "list[bytes]", total_records: int,
                             batch_size: int, verify_crc: bool,
                             config) -> "tuple[int, float]":
    """The CHAINED decode→pack referee: the measure_pipeline hot path plus
    pack_batch over every re-batched buffer — byte bytes leave the decode
    as SoA columns, get re-batched, and are read back by the packer."""
    from kafka_topic_analyzer_tpu.io.kafka_wire import _chunk_to_batch
    from kafka_topic_analyzer_tpu.io.native import (
        decode_record_set_native,
        scan_record_set_native,
    )
    from kafka_topic_analyzer_tpu.packing import pack_batch
    from kafka_topic_analyzer_tpu.records import RecordBatch

    total = total_records
    pend: "list[RecordBatch]" = []
    pend_count = 0
    n_out = 0
    t0 = time.perf_counter()
    for rs in record_sets:
        prescan = scan_record_set_native(rs, verify_crc)
        soa, used, covered = decode_record_set_native(
            rs, verify_crc, prescan=prescan
        )
        offs = soa["offsets"]
        hi = int(np.searchsorted(offs, total, "left"))
        pend.append(_chunk_to_batch(soa, slice(0, hi), 0))
        pend_count += hi
        if pend_count >= batch_size:
            out, pend, pend_count = RecordBatch.resplit(
                pend, batch_size, force=False
            )
            for b in out:
                pack_batch(b, config)
                n_out += len(b)
    if pend:
        out, pend, pend_count = RecordBatch.resplit(pend, batch_size, True)
        for b in out:
            pack_batch(b, config)  # partial tail packs with n_valid < B
            n_out += len(b)
    return n_out, time.perf_counter() - t0


def measure_pipeline_fused(record_sets: "list[bytes]", total_records: int,
                           batch_size: int, verify_crc: bool,
                           config) -> "tuple[int, float]":
    """The FUSED referee: the same record sets through
    FusedPackSink.append_record_set — one native pass from set bytes to
    wire-v4 rows, no SoA columns, no re-batching copy."""
    from kafka_topic_analyzer_tpu.io.native import scan_record_set_native
    from kafka_topic_analyzer_tpu.packing import FusedPackSink

    sink = FusedPackSink(config, batch_size, dense_of=lambda p: p)
    n_out = 0
    t0 = time.perf_counter()
    for rs in record_sets:
        prescan = scan_record_set_native(rs, verify_crc)
        n, _, _, _ = sink.append_record_set(
            rs, 0, total_records, 0, verify_crc, prescan=prescan
        )
        n_out += n
        sink.take_completed()
    sink.flush()
    sink.take_completed()
    return n_out, time.perf_counter() - t0


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--records", type=int, default=20_000_000)
    ap.add_argument("--partitions", type=int, default=16)
    ap.add_argument("--records-per-batch", type=int, default=4096)
    ap.add_argument("--templates", type=int, default=16)
    ap.add_argument("--batch-size", type=int, default=1 << 20)
    ap.add_argument("--vmin", type=int, default=100)
    ap.add_argument("--vmax", type=int, default=420)
    ap.add_argument("--check-crcs", action="store_true")
    ap.add_argument("--fused", action=argparse.BooleanOptionalAction,
                    default=True,
                    help="also referee the fused decode→pack pass against "
                         "the chained decode→SoA→pack path (single-thread "
                         "always; per-thread when --pipeline-threads is "
                         "set).  --no-fused skips both packed referees")
    ap.add_argument("--repeat", type=int, default=3,
                    help="pipeline passes; the best is the headline "
                         "(capacity is a max — interference on a shared box "
                         "only subtracts), with the median and the full run "
                         "list reported alongside")
    ap.add_argument("--skip-drain", action="store_true",
                    help="only the socket-free pipeline measurement")
    ap.add_argument("--pipeline-threads", type=int, default=0,
                    help="also run the socket-free pipeline concurrently "
                         "in N threads (private buffer copies — budget "
                         "~records x 300 B of RAM PER THREAD).  The "
                         "referee for the GIL-share claim: client compute "
                         "only, no loopback-TCP kernel time.  0 = skip")
    ap.add_argument("--workers", type=int, default=0,
                    help="also measure ONE scan drained through N "
                         "partition-sharded parallel-ingest workers "
                         "(parallel/ingest.py fan-in; the in-scan analog "
                         "of --streams' N independent scans).  Reports the "
                         "aggregate wall rate, records/client-CPU-second, "
                         "per-worker rates, and the GIL-stall percentiles "
                         "(scan_gil_stall_*).  0 = skip")
    ap.add_argument("--flight-record", action="store_true",
                    help="sample the --workers fan-in drain with the "
                         "pipeline flight recorder (obs/flight.py) and "
                         "report the doctor's occupancy evidence (worker "
                         "busy fraction, queue-empty share).  This bench "
                         "has no engine drive loop, so there is no stage "
                         "verdict — the evidence quantifies the ingest "
                         "ceiling the manual ledger used to eyeball")
    ap.add_argument("--streams", type=int, default=1,
                    help="concurrent loopback drains in ONE process (each "
                         "stream gets its own broker child + wire client + "
                         "thread).  Tests whether the leader-parallel pool's "
                         "N-core scaling claim survives the GIL: the native "
                         "decode releases the GIL (ctypes.CDLL), so N "
                         "streams should aggregate close to the 1-stream "
                         "CPU rate x available cores, and the reported "
                         "gil_stall_* percentiles bound the serialized share")
    args = ap.parse_args(argv)
    if args.streams < 1:
        ap.error("--streams must be >= 1")
    if args.workers < 0:
        ap.error("--workers must be >= 0")

    from kafka_topic_analyzer_tpu.tools.bench_e2e import (
        BrokerProcess,
        build_templates,
    )

    doc: "dict[str, object]" = {"metric": "ingest", "nproc": os.cpu_count()}

    # The socket-free pipeline (and its fused/chained referee) measure the
    # NATIVE decode path; without the shim there is nothing to referee —
    # note it and keep the drain/worker sections (python-chain) running.
    from kafka_topic_analyzer_tpu.io.native import native_status

    native_ok, native_why = native_status()
    if not native_ok:
        doc["pipeline_skipped"] = f"native-{native_why}"
        print(
            f"bench_ingest: native shim unavailable ({native_why}); "
            "skipping the pipeline/referee sections", file=sys.stderr,
        )

    # --- 3: socket-free pipeline capacity --------------------------------
    templates = build_templates(
        args.records_per_batch, args.templates, args.vmin, args.vmax
    )
    windows = max(args.records // args.records_per_batch, 1)
    record_sets = _patched_record_sets(
        templates, windows, args.records_per_batch
    )
    rates = []
    for _ in range(max(args.repeat, 1) if native_ok else 0):
        n, dt = measure_pipeline(
            record_sets, windows * args.records_per_batch, args.batch_size,
            args.check_crcs,
        )
        rates.append(n / dt)
    # Best is the headline (capacity is a max: on a shared box interference
    # only subtracts), but the median and full run list ship alongside so a
    # lucky draw over a wide spread cannot read as the typical rate
    # (VERDICT r3 weak #5).
    if rates:
        doc["pipeline_msgs_per_sec"] = round(max(rates))
        doc["pipeline_msgs_per_sec_median"] = round(
            float(np.median(np.asarray(rates)))
        )
        doc["pipeline_runs"] = [round(r) for r in rates]
        print(
            f"bench_ingest: pipeline {n} records, best of {len(rates)}: "
            f"{max(rates):,.0f}/s, median {doc['pipeline_msgs_per_sec_median']:,}/s "
            "(socket-free)", file=sys.stderr,
        )

    # --- 3a: fused vs chained decode→pack referee ------------------------
    # The ISSUE-8 headline: one native pass from record-set bytes to
    # wire-v4 rows vs decode→SoA columns→re-batch→pack.  Same buffers,
    # same acceptance window, same pack config (alive bitmap + HLL).
    if args.fused and native_ok:
        pcfg = _bench_pack_config(args.partitions, args.batch_size)
        chained_rates, fused_rates = [], []
        for _ in range(max(args.repeat, 1)):
            n, dt = measure_pipeline_chained(
                record_sets, windows * args.records_per_batch,
                args.batch_size, args.check_crcs, pcfg,
            )
            chained_rates.append(n / dt)
            n2, dt2 = measure_pipeline_fused(
                record_sets, windows * args.records_per_batch,
                args.batch_size, args.check_crcs, pcfg,
            )
            assert n2 == n, (n2, n)
            fused_rates.append(n2 / dt2)
        doc["pipeline_chained_pack_msgs_per_sec"] = round(max(chained_rates))
        doc["pipeline_fused_pack_msgs_per_sec"] = round(max(fused_rates))
        doc["fused_speedup"] = round(max(fused_rates) / max(chained_rates), 3)
        doc["pipeline_chained_pack_runs"] = [round(r) for r in chained_rates]
        doc["pipeline_fused_pack_runs"] = [round(r) for r in fused_rates]
        print(
            f"bench_ingest: decode+pack chained best {max(chained_rates):,.0f}/s, "
            f"fused best {max(fused_rates):,.0f}/s "
            f"({doc['fused_speedup']}x)", file=sys.stderr,
        )

    # --- 3b: socket-free pipeline, N concurrent threads ------------------
    # Referee for the parallel-ingest design claim (BENCH_NOTES r5/r6):
    # the client's fetch→decode→pack compute parallelizes across threads
    # because the native path releases the GIL.  Measured WITHOUT sockets,
    # so loopback-TCP kernel time (which inflates the --workers scan's sys
    # CPU on a shared box) cannot blur the picture.
    if args.pipeline_threads and native_ok:
        import threading as _threading
        import time as _time

        n_thr = args.pipeline_threads
        sets = [record_sets] + [
            _patched_record_sets(templates, windows, args.records_per_batch)
            for _ in range(n_thr - 1)
        ]  # private buffers per thread: no shared-cache flattery
        total = windows * args.records_per_batch
        out: "list" = [None] * n_thr
        barrier = _threading.Barrier(n_thr + 1)

        def _thr(i: int) -> None:
            barrier.wait(timeout=120)
            try:
                out[i] = measure_pipeline(
                    sets[i], total, args.batch_size, args.check_crcs
                )
            except BaseException as e:  # surface on the main thread
                out[i] = e

        threads = [
            _threading.Thread(target=_thr, args=(i,), daemon=True)
            for i in range(n_thr)
        ]
        for t in threads:
            t.start()
        barrier.wait(timeout=120)
        c0 = os.times()
        t0 = _time.perf_counter()
        for t in threads:
            t.join()
        wall = _time.perf_counter() - t0
        c1 = os.times()
        del sets
        failed = [o for o in out if isinstance(o, BaseException) or o is None]
        if failed:
            raise RuntimeError(
                f"{len(failed)} pipeline thread(s) failed: "
                f"{failed[0]!r} — the aggregate rate would be meaningless"
            )
        got = sum(o[0] for o in out)
        cpu = (c1.user - c0.user) + (c1.system - c0.system)
        doc["pipeline_threads"] = n_thr
        doc["pipeline_mt_msgs_per_sec"] = round(got / wall)
        doc["pipeline_mt_cpu_msgs_per_sec"] = (
            round(got / cpu) if cpu else None
        )
        print(
            f"bench_ingest: pipeline x{n_thr} threads {got} records "
            f"wall={wall:.2f}s cpu={cpu:.2f}s ({got / wall:,.0f}/s)",
            file=sys.stderr,
        )

        # Fused twin of the referee: does removing the SoA share (the
        # GIL-held numpy slice/concat in _chunk_to_batch + resplit) close
        # the 4+ thread droop?  Private buffers AND private sinks per
        # thread.
        if args.fused:
            pcfg = _bench_pack_config(args.partitions, args.batch_size)
            for fn, key in (
                (measure_pipeline_chained, "chained"),
                (measure_pipeline_fused, "fused"),
            ):
                sets = [record_sets] + [
                    _patched_record_sets(
                        templates, windows, args.records_per_batch
                    )
                    for _ in range(n_thr - 1)
                ]
                out = [None] * n_thr
                barrier = _threading.Barrier(n_thr + 1)

                def _thr_packed(i: int) -> None:
                    barrier.wait(timeout=120)
                    try:
                        out[i] = fn(
                            sets[i], total, args.batch_size,
                            args.check_crcs, pcfg,
                        )
                    except BaseException as e:
                        out[i] = e

                threads = [
                    _threading.Thread(
                        target=_thr_packed, args=(i,), daemon=True
                    )
                    for i in range(n_thr)
                ]
                for t in threads:
                    t.start()
                barrier.wait(timeout=120)
                c0 = os.times()
                t0 = _time.perf_counter()
                for t in threads:
                    t.join()
                wall = _time.perf_counter() - t0
                c1 = os.times()
                del sets
                failed = [
                    o for o in out
                    if isinstance(o, BaseException) or o is None
                ]
                if failed:
                    raise RuntimeError(
                        f"{len(failed)} {key} pack thread(s) failed: "
                        f"{failed[0]!r}"
                    )
                got = sum(o[0] for o in out)
                cpu = (c1.user - c0.user) + (c1.system - c0.system)
                doc[f"pipeline_mt_{key}_pack_msgs_per_sec"] = round(got / wall)
                doc[f"pipeline_mt_{key}_pack_cpu_msgs_per_sec"] = (
                    round(got / cpu) if cpu else None
                )
                print(
                    f"bench_ingest: decode+pack {key} x{n_thr} threads "
                    f"{got} records wall={wall:.2f}s cpu={cpu:.2f}s "
                    f"({got / wall:,.0f}/s)", file=sys.stderr,
                )

    # --- 1+2: loopback TCP drain + client-CPU rate -----------------------
    del record_sets, templates  # ~6 GB at default size; the drain phase
    #                             must not run (or swap) under dead RSS
    if not args.skip_drain:
        from contextlib import ExitStack

        n_streams = args.streams
        pwindows = max(args.records // (n_streams * args.partitions *
                                        args.records_per_batch), 1)
        with ExitStack() as stack:
            ports = [
                stack.enter_context(BrokerProcess(
                    topic=f"bench-ingest-{i}", partitions=args.partitions,
                    windows=pwindows, R=args.records_per_batch,
                    n_templates=args.templates, vmin=args.vmin,
                    vmax=args.vmax, compression=kc.COMPRESSION_NONE,
                    tombstone_every=0, brokers=1,
                ))
                for i in range(n_streams)
            ]
            results: "list" = [None] * n_streams
            barrier = threading.Barrier(n_streams + 1)
            threads = [
                threading.Thread(
                    target=_drain_stream,
                    args=(ports[i], f"bench-ingest-{i}", args.batch_size,
                          barrier, results, i),
                    daemon=True,
                )
                for i in range(n_streams)
            ]
            for t in threads:
                t.start()
            barrier.wait(timeout=120)  # all clients connected; start clock
            sampler = _StallSampler()
            sampler.start()
            c0 = os.times()
            t0 = time.perf_counter()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
            c1 = os.times()
            doc.update(sampler.finish())
        if any(r is None for r in results):
            raise RuntimeError("a drain stream died; see stderr")
        got = sum(r[0] for r in results)
        cpu = (c1.user - c0.user) + (c1.system - c0.system)
        # Aggregate rate over the CONCURRENT window (all streams started
        # together; wall is until the last finishes).
        doc["drain_msgs_per_sec"] = round(got / wall)
        doc["drain_cpu_msgs_per_sec"] = round(got / cpu) if cpu else None
        doc["drain_user_cpu_s"] = round(c1.user - c0.user, 2)
        doc["drain_sys_cpu_s"] = round(c1.system - c0.system, 2)
        if n_streams > 1:
            doc["streams"] = n_streams
            doc["stream_msgs_per_sec"] = [
                round(r[0] / r[1]) for r in results
            ]
        print(
            f"bench_ingest: drain {got} records x{n_streams} streams "
            f"wall={wall:.2f}s cpu={cpu:.2f}s", file=sys.stderr,
        )

    # --- 4: single-scan parallel ingest (--workers N) --------------------
    # The in-scan analog of --streams: ONE topic, ONE scan, N
    # partition-sharded worker streams merged through the deterministic
    # fan-in (parallel/ingest.py) — exactly what `--ingest-workers N` runs
    # inside the engine, minus the backend (so this measures the ingest
    # ceiling, not device dispatch).  Broker nodes match the worker count
    # so leaders spread like a real multi-broker cluster.  Runs even under
    # --skip-drain (that flag skips the independent-streams drain; this is
    # its own measurement).
    if args.workers:
        import time as _time

        from kafka_topic_analyzer_tpu.io.kafka_wire import KafkaWireSource
        from kafka_topic_analyzer_tpu.obs.registry import default_registry
        from kafka_topic_analyzer_tpu.parallel.ingest import (
            ParallelIngest,
            shard_partitions,
        )
        from kafka_topic_analyzer_tpu.results import IngestStats

        wwindows = max(
            args.records // (args.partitions * args.records_per_batch), 1
        )
        runs = []
        for _ in range(max(args.repeat, 1)):
            if args.flight_record:
                # The doctor's worker busy/stall evidence reads the
                # process-global CUMULATIVE counters; without a reset,
                # repeat N's evidence would blend repeats 1..N-1 (worker
                # labels recur across runs).  Per-run isolation — the
                # per_worker accounting below deltas against `before`
                # either way, so it is reset-agnostic.
                default_registry().reset()
            with BrokerProcess(
                topic="bench-ingest-w", partitions=args.partitions,
                windows=wwindows, R=args.records_per_batch,
                n_templates=args.templates, vmin=args.vmin, vmax=args.vmax,
                compression=kc.COMPRESSION_NONE, tombstone_every=0,
                brokers=min(args.workers, args.partitions),
            ) as port:
                src = KafkaWireSource(f"127.0.0.1:{port}", "bench-ingest-w")
                groups = shard_partitions(src.partitions(), args.workers)
                before = IngestStats.from_telemetry(
                    default_registry().snapshot()
                )
                recorder = None
                if args.flight_record:
                    from kafka_topic_analyzer_tpu.obs import (
                        flight as obs_flight,
                    )

                    recorder = obs_flight.FlightRecorder(interval_s=0.05)
                    obs_flight.set_active(recorder)
                    recorder.start()
                sampler = _StallSampler()
                sampler.start()
                c0 = os.times()
                t0 = _time.perf_counter()
                pool = ParallelIngest(src, args.batch_size, groups, depth=2)
                wids = [str(w.wid) for w in pool.workers]
                got = 0
                try:
                    for batch, _staged in pool:
                        got += len(batch)
                    wall = _time.perf_counter() - t0
                    c1 = os.times()
                finally:
                    pool.close()
                    src.close()
                    # A failing drain must not leak a live sampler as the
                    # process-wide active recorder (same rule as
                    # bench_e2e); the stopped series stays readable.
                    if recorder is not None:
                        from kafka_topic_analyzer_tpu.obs import (
                            flight as obs_flight,
                        )

                        recorder.stop()
                        obs_flight.set_active(None)
                stalls = sampler.finish()
                flight_evidence = None
                if recorder is not None:
                    from kafka_topic_analyzer_tpu.obs import doctor

                    d = doctor.diagnose(
                        default_registry().snapshot(),
                        flight=recorder.series(),
                    )
                    flight_evidence = {
                        k: round(v, 4) for k, v in d.evidence.items()
                    }
            after = IngestStats.from_telemetry(default_registry().snapshot())
            runs.append({
                "flight": flight_evidence,
                "got": got, "wall": wall,
                "user": c1.user - c0.user, "sys": c1.system - c0.system,
                # Delta vs the pre-run snapshot, restricted to THIS pool's
                # workers: the registry is process-global and cumulative,
                # and stale worker labels from earlier runs must not ride
                # along at delta 0.
                "per_worker": {
                    w: int(after.workers.get(w, 0) - before.workers.get(w, 0))
                    for w in wids
                },
                "stalls": stalls,
            })
        # Best-of, like the pipeline measurement: capacity is a max — on a
        # shared box interference only subtracts.  The full run list ships
        # alongside so a lucky draw cannot read as the typical rate.
        best = max(runs, key=lambda r: r["got"] / r["wall"])
        got, wall = best["got"], best["wall"]
        cpu = best["user"] + best["sys"]
        doc["workers"] = min(args.workers, args.partitions)
        doc["scan_msgs_per_sec"] = round(got / wall)
        doc["scan_runs"] = [round(r["got"] / r["wall"]) for r in runs]
        doc["scan_cpu_msgs_per_sec"] = round(got / cpu) if cpu else None
        doc["scan_user_cpu_s"] = round(best["user"], 2)
        doc["scan_sys_cpu_s"] = round(best["sys"], 2)
        doc["scan_worker_records"] = best["per_worker"]
        doc["scan_worker_msgs_per_sec"] = {
            w: round(n / wall) for w, n in best["per_worker"].items()
        }
        doc.update({f"scan_{k}": v for k, v in best["stalls"].items()})
        if best.get("flight") is not None:
            doc["scan_flight_evidence"] = best["flight"]
            per = ", ".join(
                f"{k.replace('_', '-')} {v * 100:.0f}%"
                for k, v in sorted(best["flight"].items())
            )
            print(f"bench_ingest: flight evidence: {per}", file=sys.stderr)
        print(
            f"bench_ingest: single scan x{args.workers} workers drained "
            f"{got} records, best of {len(runs)}: {got / wall:,.0f}/s "
            f"(wall={wall:.2f}s cpu={cpu:.2f}s)", file=sys.stderr,
        )

    print(json.dumps(doc))
    return 0


if __name__ == "__main__":
    sys.exit(main())
