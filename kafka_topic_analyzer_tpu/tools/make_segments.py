"""Create .ktaseg segment dumps from a synthetic workload spec.

Usage:
    python -m kafka_topic_analyzer_tpu.tools.make_segments \
        --out /tmp/segs --topic demo \
        --synthetic "partitions=4,messages=100000,keys=5000"
"""

from __future__ import annotations

import argparse
import os
import sys

from kafka_topic_analyzer_tpu.cli import parse_kv_pairs
from kafka_topic_analyzer_tpu.io.segfile import write_segment_from_batches
from kafka_topic_analyzer_tpu.io.synthetic import SyntheticSource, SyntheticSpec


def spec_from_kv(text: "str | None") -> SyntheticSpec:
    return SyntheticSpec.from_kv(parse_kv_pairs(text))


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", required=True, help="output directory")
    ap.add_argument("--topic", required=True)
    ap.add_argument("--synthetic", help="same spec format as the analyzer CLI")
    ap.add_argument("--batch-size", type=int, default=1 << 20)
    ap.add_argument("--native", choices=["auto", "on", "off"], default="auto")
    args = ap.parse_args(argv)

    try:
        spec = spec_from_kv(args.synthetic)
    except ValueError as e:
        # Same clean one-line reporting as the analyzer CLI's
        # user_input_phase (the messages name the offending key).
        print(f"error: {e}", file=sys.stderr)
        return 1
    src: SyntheticSource
    if args.native in ("auto", "on"):
        try:
            from kafka_topic_analyzer_tpu.io.native import NativeSyntheticSource

            src = NativeSyntheticSource(spec)
        except Exception:
            if args.native == "on":
                raise
            src = SyntheticSource(spec)
    else:
        src = SyntheticSource(spec)

    os.makedirs(args.out, exist_ok=True)
    for p in src.partitions():
        batches = list(src.batches(args.batch_size, partitions=[p]))
        path = write_segment_from_batches(args.out, args.topic, p, batches)
        print(f"wrote {path}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
