"""Create .ktaseg segment dumps from a synthetic workload spec.

Usage:
    python -m kafka_topic_analyzer_tpu.tools.make_segments \
        --out /tmp/segs --topic demo \
        --synthetic "partitions=4,messages=100000,keys=5000"
"""

from __future__ import annotations

import argparse
import os
import sys

from kafka_topic_analyzer_tpu.cli import parse_kv_pairs
from kafka_topic_analyzer_tpu.io.segfile import write_segment_from_batches
from kafka_topic_analyzer_tpu.io.synthetic import SyntheticSource, SyntheticSpec


def spec_from_kv(text: "str | None") -> SyntheticSpec:
    return SyntheticSpec.from_kv(parse_kv_pairs(text))


class _Parser(argparse.ArgumentParser):
    """Points ``--partitions 4``-style mistakes at the ``--synthetic`` kv
    form (the workload shape is a spec string, not individual flags —
    VERDICT r3 weak #6: the bare "unrecognized arguments" error cost a
    first-time user real confusion)."""

    def error(self, message: str) -> "None":
        if "unrecognized arguments" in message:
            stray = [
                w.lstrip("-").replace("-", "_")
                for w in message.split(":", 1)[-1].split()
                if w.startswith("--")
            ]
            near = sorted(
                k for k in SyntheticSpec.KV_KEYS
                if any(s and (s in k or k in s) for s in stray)
            )
            hint = (
                "workload shape is given as one --synthetic spec, e.g. "
                '--synthetic "partitions=4,messages=100000,keys=5000"; '
                "valid keys: " + ", ".join(sorted(SyntheticSpec.KV_KEYS))
            )
            if near:
                hint = f"did you mean --synthetic \"{near[0]}=...\"? " + hint
            message = f"{message}\n{' ' * 7}{hint}"
        super().error(message)


def main(argv: "list[str] | None" = None) -> int:
    ap = _Parser(
        prog="make_segments",
        epilog="--synthetic takes the analyzer CLI's comma-separated k=v "
               "spec; valid keys: " + ", ".join(sorted(SyntheticSpec.KV_KEYS)),
    )
    ap.add_argument("--out", required=True, help="output directory")
    ap.add_argument("--topic", required=True)
    ap.add_argument("--synthetic",
                    help="workload spec, comma separated k=v (same format as "
                         "the analyzer CLI), e.g. "
                         "\"partitions=4,messages=100000,keys=5000\"")
    ap.add_argument("--batch-size", type=int, default=1 << 20)
    ap.add_argument("--chunk-records", type=int, default=0,
                    help="roll output into {topic}-{p}.cN.ktaseg chunks of "
                         "this many records (0 = one chunk per partition) — "
                         "the shape remote-tier read-ahead works against")
    ap.add_argument("--native", choices=["auto", "on", "off"], default="auto")
    args = ap.parse_args(argv)

    try:
        spec = spec_from_kv(args.synthetic)
    except ValueError as e:
        # Same clean one-line reporting as the analyzer CLI's
        # user_input_phase (the messages name the offending key).
        print(f"error: {e}", file=sys.stderr)
        return 1
    src: SyntheticSource
    if args.native in ("auto", "on"):
        try:
            from kafka_topic_analyzer_tpu.io.native import NativeSyntheticSource

            src = NativeSyntheticSource(spec)
        except Exception:
            if args.native == "on":
                raise
            src = SyntheticSource(spec)
    else:
        src = SyntheticSource(spec)

    os.makedirs(args.out, exist_ok=True)
    if args.chunk_records > 0:
        from kafka_topic_analyzer_tpu.io.segfile import SegmentDumpWriter

        writer = SegmentDumpWriter(
            args.out, args.topic, records_per_chunk=args.chunk_records
        )
        # Batch at the chunk size so rolling (batch-granular) lands chunks
        # of exactly the requested record count.
        for p in src.partitions():
            for b in src.batches(
                min(args.batch_size, args.chunk_records), partitions=[p]
            ):
                writer.append(b)
        writer.close()
        n = len(os.listdir(args.out))
        print(f"wrote {n} rolled chunk file(s) to {args.out}",
              file=sys.stderr)
        return 0
    for p in src.partitions():
        batches = list(src.batches(args.batch_size, partitions=[p]))
        path = write_segment_from_batches(args.out, args.topic, p, batches)
        print(f"wrote {path}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
