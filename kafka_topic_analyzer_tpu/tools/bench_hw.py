"""One-process real-TPU measurement campaign.

The axon tunnel to the chip degrades with repeated client inits (see
BENCH_NOTES.md round 2), so every hardware number we need is measured by
THIS single process, stage by stage, each stage printing a flushed line
before moving on — a hang names the last stage that made it out, and the
JSON at the end carries whatever completed.

Stages:
  init            jax.devices() + platform
  transfer        host->device bandwidth, single stream (1/8/32 MiB)
  transfer-conc   4 concurrent 8 MiB puts (does the tunnel scale with
                  parallel streams?)
  pack            native host pack throughput (no device)
  stream          full-feature analyzer step, host batches crossing the
                  wire each step — bench.py's protocol at --batch-pow
  resident        same step with the packed buffers pre-staged on device:
                  the device-compute rate a PCIe host would see
  counters        resident, counters-only config (the reference's exact
                  workload, src/metric.rs:12-26)
  pallas          resident, counters-only via the Pallas MXU kernel
                  (ops/pallas_counters.py) — the promote-or-demote number

  big             LAST (hang risk): the stream protocol again at
                  --big-pow (default 2^20 — the batch size whose warmup
                  wedged the tunnel on 2026-07-29; everything above has
                  already been captured if this one dies)

Usage: python -m kafka_topic_analyzer_tpu.tools.bench_hw
         [--batch-pow 16] [--steps 64] [--stop-after STAGE] [--json PATH]
"""

from __future__ import annotations

import argparse
import json
import sys
import time


RESULTS: dict = {}


def _stage(name):
    print(f"bench_hw: [{name}] start", file=sys.stderr, flush=True)
    t0 = time.perf_counter()

    def done(extra: str = ""):
        dt = time.perf_counter() - t0
        print(
            f"bench_hw: [{name}] ok in {dt:.2f}s {extra}",
            file=sys.stderr, flush=True,
        )
        return dt

    return done


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-pow", type=int, default=16,
                    help="log2 batch size (16 -> 65536: the shape already "
                         "in the compile cache from probe runs)")
    ap.add_argument("--steps", type=int, default=64)
    ap.add_argument("--partitions", type=int, default=16)
    ap.add_argument("--stop-after", default=None,
                    choices=["init", "transfer", "transfer-conc", "pack",
                             "stream", "resident", "counters", "pallas"])
    ap.add_argument("--big-pow", type=int, default=20,
                    help="log2 batch size for the final 'big' stage; "
                         "0 disables it")
    ap.add_argument("--big-steps", type=int, default=8)
    ap.add_argument("--json", default=None, help="also write results here")
    args = ap.parse_args()
    B = 1 << args.batch_pow
    S = args.steps

    def flush_json() -> None:
        # Incremental: a later-stage hang must not lose earlier numbers.
        if args.json:
            with open(args.json, "w") as f:
                json.dump(RESULTS, f, indent=1)

    def emit() -> int:
        print(json.dumps(RESULTS), flush=True)
        flush_json()
        return 0

    def stop(stage: str) -> bool:
        return args.stop_after == stage

    # -- init ---------------------------------------------------------------
    done = _stage("init")
    # Through jax_support: honors KTA_JAX_PLATFORMS and drops the axon
    # tunnel's backend factory when excluded — a plain `import jax` +
    # `jax.devices()` initializes every discovered plugin, and a wedged
    # tunnel blocks that init even under JAX_PLATFORMS=cpu.
    from kafka_topic_analyzer_tpu.jax_support import jax
    import numpy as np

    dev = jax.devices()[0]
    RESULTS["device"] = str(dev)
    RESULTS["platform"] = dev.platform
    done(str(dev))
    if stop("init"):
        return emit()

    # -- transfer bandwidth -------------------------------------------------
    done = _stage("transfer")
    from kafka_topic_analyzer_tpu.tools.hwmeasure import (
        measure_transfer_gbps,
        timed_step_loop,
    )

    from kafka_topic_analyzer_tpu.tools.hwmeasure import HEADLINE_TRANSFER_MIB

    bws = measure_transfer_gbps(dev, mib_sizes=(1, HEADLINE_TRANSFER_MIB, 32))
    # Same key, same policy as bench.py's JSON line (hwmeasure): the
    # headline-size single put; the per-size detail keeps its own key.
    RESULTS["transfer_gbps"] = bws[HEADLINE_TRANSFER_MIB]
    RESULTS["transfer_gbps_by_mib"] = bws
    flush_json()
    done(" ".join(f"{m}MiB={v:.3f}GB/s" for m, v in bws.items()))
    if stop("transfer"):
        return emit()

    done = _stage("transfer-conc")
    hosts = [np.full((8 << 20,), i, np.uint8) for i in range(4)]
    t0 = time.perf_counter()
    ds = [jax.device_put(h, dev) for h in hosts]
    jax.block_until_ready(ds)
    dt = time.perf_counter() - t0
    RESULTS["transfer_gbps_concurrent"] = round(4 * 8 / 1024 / dt, 4)
    flush_json()
    done(f"4x8MiB={RESULTS['transfer_gbps_concurrent']:.3f}GB/s")
    del ds
    if stop("transfer-conc"):
        return emit()

    # -- shared workload ----------------------------------------------------
    from kafka_topic_analyzer_tpu.config import AnalyzerConfig
    from kafka_topic_analyzer_tpu.packing import pack_batch
    from kafka_topic_analyzer_tpu.io.synthetic import SyntheticSpec

    full_cfg = AnalyzerConfig(
        num_partitions=args.partitions, batch_size=B,
        count_alive_keys=True, alive_bitmap_bits=26,
        enable_hll=True, enable_quantiles=True,
    )
    cnt_cfg = AnalyzerConfig(num_partitions=args.partitions, batch_size=B)
    pal_cfg = AnalyzerConfig(
        num_partitions=args.partitions, batch_size=B, use_pallas_counters=True
    )
    spec = SyntheticSpec(
        num_partitions=args.partitions,
        messages_per_partition=(4 * B) // args.partitions,
        keys_per_partition=200_000,
        key_null_permille=50,
        tombstone_permille=100,
        seed=0xBEEF,
    )

    done = _stage("pack")
    try:
        from kafka_topic_analyzer_tpu.io.native import NativeSyntheticSource

        src = NativeSyntheticSource(spec)
    except Exception:
        from kafka_topic_analyzer_tpu.io.synthetic import SyntheticSource

        src = SyntheticSource(spec)
    batches = [b.pad_to(B) for b in src.batches(B)]
    t0 = time.perf_counter()
    bufs = {}
    for name, cfg in (("full", full_cfg), ("cnt", cnt_cfg)):
        bufs[name] = [pack_batch(b, cfg) for b in batches]
    pack_dt = time.perf_counter() - t0
    n_packed = 2 * len(batches) * B
    RESULTS["host_pack_msgs_per_sec"] = round(n_packed / pack_dt, 1)
    RESULTS["packed_bytes_per_record"] = round(
        bufs["full"][0].nbytes / B, 1
    )
    flush_json()
    done(f"{n_packed / pack_dt / 1e6:.1f}M rec/s, "
         f"{RESULTS['packed_bytes_per_record']}B/rec full")
    if stop("pack"):
        return emit()

    def timed_loop(name, cfg, device_bufs, host_bufs=None, steps=S):
        """One timed_step_loop (tools/hwmeasure.py) recorded under `name`;
        either streams host buffers (device_put per step) or cycles
        pre-staged device buffers (resident)."""
        done = _stage(name)
        resident = device_bufs is not None
        r = timed_step_loop(
            cfg,
            device_bufs if resident else host_bufs,
            steps=steps,
            device_resident=resident,
            dev=dev,
        )
        RESULTS[name + "_msgs_per_sec"] = r["msgs_per_sec"]
        RESULTS[name + "_compile_s"] = r["compile_s"]
        flush_json()
        done(f"{r['msgs_per_sec'] / 1e6:.2f}M msgs/s "
             f"(compile+first {r['compile_s']:.1f}s)")

    # -- stream: host batches cross the tunnel every step --------------------
    timed_loop("stream", full_cfg, None, host_bufs=bufs["full"])
    if stop("stream"):
        return emit()

    # -- resident: buffers pre-staged on device ------------------------------
    done = _stage("stage-bufs")
    dev_full = [jax.device_put(b, dev) for b in bufs["full"]]
    jax.block_until_ready(dev_full)
    done(f"{len(dev_full)} bufs")
    timed_loop("resident", full_cfg, dev_full)
    del dev_full
    if stop("resident"):
        return emit()

    done = _stage("stage-cnt-bufs")
    dev_cnt = [jax.device_put(b, dev) for b in bufs["cnt"]]
    jax.block_until_ready(dev_cnt)
    done()
    timed_loop("counters", cnt_cfg, dev_cnt)
    if stop("counters"):
        return emit()

    timed_loop("pallas", pal_cfg, dev_cnt)

    if RESULTS.get("pallas_msgs_per_sec") and RESULTS.get("counters_msgs_per_sec"):
        RESULTS["pallas_vs_scatter"] = round(
            RESULTS["pallas_msgs_per_sec"] / RESULTS["counters_msgs_per_sec"], 3
        )
        flush_json()
    del dev_cnt
    if stop("pallas") or not args.big_pow:
        return emit()

    # -- big: the wedge-prone shape, LAST -------------------------------------
    BIG = 1 << args.big_pow
    big_cfg = AnalyzerConfig(
        num_partitions=args.partitions, batch_size=BIG,
        count_alive_keys=True, alive_bitmap_bits=26,
        enable_hll=True, enable_quantiles=True,
    )
    done = _stage("big-pack")
    big_spec = SyntheticSpec(
        num_partitions=args.partitions,
        messages_per_partition=(2 * BIG) // args.partitions,
        keys_per_partition=200_000,
        key_null_permille=50,
        tombstone_permille=100,
        seed=0xBEEF,
    )
    try:
        bsrc = NativeSyntheticSource(big_spec)
    except Exception:
        from kafka_topic_analyzer_tpu.io.synthetic import SyntheticSource

        bsrc = SyntheticSource(big_spec)
    big_bufs = [
        pack_batch(b.pad_to(BIG), big_cfg) for b in bsrc.batches(BIG)
    ]
    done(f"{len(big_bufs)} bufs of {big_bufs[0].nbytes >> 20}MiB")
    timed_loop("big", big_cfg, None, host_bufs=big_bufs,
               steps=args.big_steps)
    return emit()


if __name__ == "__main__":
    sys.exit(main())
