"""Operational tools (segment dump creation, snapshot inspection)."""
