"""Incremental accelerator-tunnel probe: pinpoint WHERE a device workload
stops responding (init / small transfer / small compile / large transfer /
analyzer-step compile / steady-state steps).

Each stage prints a flushed line with its latency before moving on, so a
hang names its stage (the driver's log shows the last line that made it
out).  Usage: ``python -m kafka_topic_analyzer_tpu.tools.tunnel_probe
[--stop-after STAGE]``.
"""

from __future__ import annotations

import argparse
import sys
import time


def _stage(name):
    print(f"probe: [{name}] start", file=sys.stderr, flush=True)
    t0 = time.perf_counter()

    def done(extra: str = "") -> None:
        dt = time.perf_counter() - t0
        print(
            f"probe: [{name}] ok in {dt:.2f}s {extra}",
            file=sys.stderr, flush=True,
        )

    return done


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--stop-after", default="steps",
                    choices=["init", "put1", "jit1", "put20m", "step",
                             "steps"])
    ap.add_argument("--batch-size", type=int, default=1 << 20)
    args = ap.parse_args()

    done = _stage("init")
    import kafka_topic_analyzer_tpu.jax_support  # noqa: F401  (x64 config)
    import jax

    dev = jax.devices()[0]
    done(f"device={dev}")
    if args.stop_after == "init":
        return 0

    done = _stage("put1")
    import numpy as np

    x = jax.device_put(np.arange(8, dtype=np.int32))
    jax.block_until_ready(x)
    done()
    if args.stop_after == "put1":
        return 0

    done = _stage("jit1")
    y = jax.jit(lambda a: a * 2 + 1)(x)
    jax.block_until_ready(y)
    done(f"sum={int(y.sum())}")
    if args.stop_after == "jit1":
        return 0

    done = _stage("put20m")
    big = np.random.default_rng(0).integers(
        0, 255, size=20 << 20, dtype=np.uint8
    )
    t0 = time.perf_counter()
    bigd = jax.device_put(big)
    jax.block_until_ready(bigd)
    dt = time.perf_counter() - t0
    done(f"{len(big) / dt / 1e9:.3f} GB/s")
    if args.stop_after == "put20m":
        return 0

    done = _stage("step-compile")
    from kafka_topic_analyzer_tpu.backends.tpu import TpuBackend
    from kafka_topic_analyzer_tpu.config import AnalyzerConfig
    from kafka_topic_analyzer_tpu.io.native import NativeSyntheticSource
    from kafka_topic_analyzer_tpu.io.synthetic import SyntheticSpec

    config = AnalyzerConfig(num_partitions=4, batch_size=args.batch_size)
    spec = SyntheticSpec(
        num_partitions=4,
        messages_per_partition=args.batch_size // 4,
        keys_per_partition=10_000,
        seed=0xBEEF,
    )
    src = NativeSyntheticSource(spec)
    batch = next(iter(src.batches(args.batch_size))).pad_to(args.batch_size)
    backend = TpuBackend(config, init_now_s=0)
    backend.update(batch)
    backend.block_until_ready()
    done()
    if args.stop_after == "step":
        return 0

    done = _stage("steps")
    t0 = time.perf_counter()
    n = 8
    for _ in range(n):
        backend.update(batch)
    backend.block_until_ready()
    dt = time.perf_counter() - t0
    done(f"{n * args.batch_size / dt / 1e6:.2f}M rec/s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
