"""Serve a .ktaseg directory as an S3-shaped object store (DESIGN.md §21).

The remote segment tier (io/objstore.py + ObjectSegmentStore) speaks a
small, honest subset of S3 path-style HTTP: ListObjectsV2
(``GET /bucket/?list-type=2&prefix=``), whole-object GET with an MD5 ETag,
and ranged GET (``Range: bytes=a-b`` / ``bytes=-n``).  This module is a
local implementation of exactly that subset, so the whole tier — catalog
header probes, read-ahead, retry/budget recovery, cache verification — is
provable (tests) and measurable (tools/bench_segments.py) without real S3:

    python -m kafka_topic_analyzer_tpu.tools.objstore_serve \
        --root ./segments --port 9000 --latency-ms 25
    kafka-topic-analyzer -t orders --source segfile \
        --segment-dir http://127.0.0.1:9000/segments

``latency_ms`` injects a per-request service delay (the wire-RTT stand-in
the read-ahead pool exists to hide); ``fault_hook`` lets a harness script
failures per request — drop the connection, stall past the client timeout,
return 5xx, or corrupt response bytes in flight (see
tests/fake_objstore.py for the scripted wrapper).
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import re
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional, Tuple, Union
from urllib.parse import parse_qs, unquote, urlparse
from xml.sax.saxutils import escape

#: fault_hook(key, rng, index) -> one of:
#:   None                   serve normally
#:   ("status", code)       respond with that HTTP status, empty body
#:   "drop"                 close the socket without responding
#:   ("stall", seconds)     sleep that long BEFORE responding (client
#:                          timeouts see a hung server)
#:   ("flip", byte_index)   serve the body with one bit flipped there
#:   ("truncate", nbytes)   serve only the first nbytes of the body
#:                          (Content-Length still claims the full size —
#:                          a mid-GET connection drop)
FaultHook = Callable[[str, Optional[Tuple[Optional[int], int]], int], object]

#: put_fault_hook(key, body, index) -> one of:
#:   None                   apply the PUT normally
#:   ("status", code)       respond with that status; write NOT applied
#:   "drop"                 close the socket, write NOT applied (a plain
#:                          transport failure: the retry is safe)
#:   "lost"                 APPLY the write, then close the socket with
#:                          no response — the ambiguous-PUT case: the
#:                          client cannot know it succeeded, and its
#:                          conditional retry will 412 against its OWN
#:                          write (fleet/lease.py resolves by read-back)
#:   ("race", body2)        install ``body2`` under the key FIRST, then
#:                          evaluate the request's conditions against it
#:                          — a competing writer winning the CAS race
#:                          (the stale-ETag 412 path)
#:   ("skew", seconds)      apply the write with the lease JSON body's
#:                          ``expires_at`` shifted by that many seconds —
#:                          a writer whose clock disagrees with ours
PutFaultHook = Callable[[str, bytes, int], object]


class ObjectStoreHttpServer:
    """A threading HTTP server exposing ``root`` (a directory path, or a
    mutable ``{name: bytes}`` dict) as one S3-shaped bucket."""

    def __init__(
        self,
        root: "Union[str, Dict[str, bytes]]",
        bucket: str = "segments",
        latency_ms: float = 0.0,
        fault_hook: "Optional[FaultHook]" = None,
        put_fault_hook: "Optional[PutFaultHook]" = None,
        send_etag: bool = True,
        max_keys: int = 1000,
        sse: "Optional[str]" = None,
        etag_salt: bytes = b"",
        ignore_range: bool = False,
        host: str = "127.0.0.1",
        port: int = 0,
    ):
        self.root = root
        self.bucket = bucket
        self.latency_ms = latency_ms
        self.fault_hook = fault_hook
        self.put_fault_hook = put_fault_hook
        self.send_etag = send_etag
        #: LIST page cap (S3 caps at 1000): pages beyond it return
        #: IsTruncated=true + NextContinuationToken, so clients that fail
        #: to paginate see exactly what real S3 would show them.
        self.max_keys = max_keys
        #: When set, object responses carry x-amz-server-side-encryption
        #: (e.g. "aws:kms") — real KMS-encrypted objects have 32-hex
        #: ETags that are NOT the content MD5.
        self.sse = sse
        #: Salts the ETag hash: a 32-hex ETag that never matches the body
        #: MD5 (the SSE-KMS/SSE-C shape, minus the header when sse=None).
        self.etag_salt = etag_salt
        #: Serve every ranged GET as a 200 full-object response (servers
        #: that don't implement Range exist; clients must not burn their
        #: retry budget calling the full body 'truncated').
        self.ignore_range = ignore_range
        self.requests_served = 0
        self._request_index = 0
        self._lock = threading.Lock()
        #: key -> (stat signature, md5) so ETags (whole-object by S3
        #: semantics) are computed once per object version, not per
        #: request — a 32-byte header probe must not cost a full-file
        #: read + hash, or the server's own overhead drowns the injected
        #: latency the benchmarks measure.
        self._etags: "Dict[str, Tuple[object, str]]" = {}
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # noqa: D102 — quiet server
                pass

            def do_GET(self):  # noqa: N802 — http.server contract
                outer._handle(self)

            def do_PUT(self):  # noqa: N802 — http.server contract
                outer._handle(self)

        class Server(ThreadingHTTPServer):
            # Many concurrent clients (read-ahead pools x ingest workers)
            # connect in one burst; the http.server default backlog of 5
            # drops SYNs and the kernel's ~1s retransmit would masquerade
            # as store latency.  A real endpoint accepts deeper.
            request_queue_size = 128
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="kta-objstore-serve",
        )

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ObjectStoreHttpServer":
        self._thread.start()
        return self

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5)

    def __enter__(self) -> "ObjectStoreHttpServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}/{self.bucket}"

    # -- object access -------------------------------------------------------

    def _keys(self) -> "list[str]":
        if isinstance(self.root, dict):
            return sorted(self.root)
        return sorted(
            f for f in os.listdir(self.root)
            if os.path.isfile(os.path.join(self.root, f))
        )

    def _size(self, key: str) -> "Optional[int]":
        if isinstance(self.root, dict):
            data = self.root.get(key)
            return None if data is None else len(data)
        path = os.path.join(self.root, key)
        try:
            return os.path.getsize(path)
        except OSError:
            return None

    def _read_range(
        self, key: str, rng: "Optional[Tuple[Optional[int], int]]"
    ) -> "Tuple[Optional[bytes], int]":
        """(bytes of the requested range — or the whole object — and the
        full object size).  File roots read ONLY the range: a ranged
        header probe costs a seek + a few bytes, not the chunk."""
        if isinstance(self.root, dict):
            data = self.root.get(key)
            if data is None:
                return None, 0
            full = len(data)
            if rng is None:
                return data, full
            lo, hi = rng
            return (data[-hi:] if hi else b"") if lo is None else (
                data[lo : hi + 1]
            ), full
        path = os.path.join(self.root, key)
        try:
            size = os.path.getsize(path)
            with open(path, "rb") as f:
                if rng is None:
                    return f.read(), size
                lo, hi = rng
                if lo is None:
                    f.seek(max(0, size - hi))
                    return (f.read() if hi else b""), size
                f.seek(lo)
                return f.read(max(0, hi - lo + 1)), size
        except OSError:
            return None, 0

    def _etag(self, key: str) -> "Optional[str]":
        """Whole-object MD5 (S3 ETag semantics).  Dict roots hash the
        bytes directly (cheap test data; caching under ``id(data)`` can
        serve a STALE ETag after CPython reuses a freed address for a
        replacement object of the same length).  File roots cache per
        object version, keyed on (size, mtime), so a 32-byte header
        probe never costs a full-file read + hash."""
        if isinstance(self.root, dict):
            data = self.root.get(key)
            if data is None:
                return None
            return hashlib.md5(data + self.etag_salt).hexdigest()
        try:
            st = os.stat(os.path.join(self.root, key))
        except OSError:
            return None
        sig = (st.st_size, st.st_mtime_ns)
        cached = self._etags.get(key)
        if cached is not None and cached[0] == sig:
            return cached[1]
        data, _ = self._read_range(key, None)
        if data is None:
            return None
        etag = hashlib.md5(data + self.etag_salt).hexdigest()
        self._etags[key] = (sig, etag)
        return etag

    # -- request handling ----------------------------------------------------

    @staticmethod
    def _parse_range(header: str) -> "Optional[Tuple[Optional[int], int]]":
        m = re.fullmatch(r"bytes=(\d*)-(\d*)", header or "")
        if not m or (not m.group(1) and not m.group(2)):
            return None
        if not m.group(1):  # suffix range: bytes=-n
            return None, int(m.group(2))
        return int(m.group(1)), int(m.group(2) or (1 << 62))

    def _handle(self, req: BaseHTTPRequestHandler) -> None:
        with self._lock:
            index = self._request_index
            self._request_index += 1
        parsed = urlparse(req.path)
        parts = [p for p in unquote(parsed.path).split("/") if p]
        if not parts or parts[0] != self.bucket:
            self._respond(req, 404, b"no such bucket")
            return
        if self.latency_ms > 0:
            time.sleep(self.latency_ms / 1000.0)
        query = parse_qs(parsed.query)
        if req.command == "PUT":
            if len(parts) < 2:
                self._respond(req, 400, b"missing key")
                return
            self._handle_put(req, "/".join(parts[1:]), index)
            return
        if len(parts) == 1 and "list-type" in query:
            self._handle_list(req, query)
            return
        if len(parts) < 2:
            self._respond(req, 400, b"missing key")
            return
        self._handle_object(req, "/".join(parts[1:]), index)

    def _handle_list(
        self, req: BaseHTTPRequestHandler, query: "Dict[str, list]"
    ) -> None:
        prefix = query.get("prefix", [""])[0]
        token = query.get("continuation-token", [""])[0]
        try:
            max_keys = int(query.get("max-keys", [str(self.max_keys)])[0])
        except ValueError:
            max_keys = -1
        if max_keys < 1:
            # 0 would paginate forever without progress (page[-1] of an
            # empty page); fail it deterministically.
            self._respond(req, 400, b"bad max-keys")
            return
        # ListObjectsV2 pagination: the continuation token is the last key
        # of the previous page (keys enumerate in lexicographic order, so
        # strictly-greater resumes exactly after it).
        matched = [
            key
            for key in self._keys()
            if key.startswith(prefix) and (not token or key > token)
        ]
        page, truncated = matched[:max_keys], len(matched) > max_keys
        rows = []
        for key in page:
            size = self._size(key)
            if size is None:
                continue
            etag = (self._etag(key) or "") if self.send_etag else ""
            rows.append(
                "<Contents>"
                f"<Key>{escape(key)}</Key><Size>{size}</Size>"
                + (f"<ETag>&quot;{etag}&quot;</ETag>" if etag else "")
                + "</Contents>"
            )
        body = (
            '<?xml version="1.0" encoding="UTF-8"?>'
            "<ListBucketResult>"
            f"<Name>{escape(self.bucket)}</Name>"
            f"<IsTruncated>{'true' if truncated else 'false'}</IsTruncated>"
            + (
                f"<NextContinuationToken>{escape(page[-1])}"
                "</NextContinuationToken>"
                if truncated
                else ""
            )
            + f"{''.join(rows)}"
            "</ListBucketResult>"
        ).encode()
        self._respond(req, 200, body, content_type="application/xml")

    def _handle_object(
        self, req: BaseHTTPRequestHandler, key: str, index: int
    ) -> None:
        rng = self._parse_range(req.headers.get("Range", ""))
        action = (
            self.fault_hook(key, rng, index)
            if self.fault_hook is not None
            else None
        )
        if isinstance(action, tuple) and action[0] == "stall":
            time.sleep(action[1])
            action = None
        if action == "drop":
            # Kill the socket without an HTTP response: the client sees a
            # reset/short read mid-GET.
            try:
                req.connection.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            req.close_connection = True
            return
        if isinstance(action, tuple) and action[0] == "status":
            self._respond(req, int(action[1]), b"injected fault")
            return
        # ignore_range mode answers a ranged GET with the full object and
        # a 200 (the fault hook still sees the range the client asked for).
        serve_rng = None if self.ignore_range else rng
        data, _full_size = self._read_range(key, serve_rng)
        if data is None:
            self._respond(req, 404, b"no such key")
            return
        status = 200 if serve_rng is None else 206
        claimed_len = len(data)
        if isinstance(action, tuple) and action[0] == "flip":
            flipped = bytearray(data)
            flipped[action[1] % max(1, len(flipped))] ^= 0x01
            data = bytes(flipped)
        elif isinstance(action, tuple) and action[0] == "truncate":
            data = data[: action[1]]
        headers = {}
        if self.sse:
            headers["x-amz-server-side-encryption"] = self.sse
        if self.send_etag:
            # S3 semantics: the ETag always describes the WHOLE object
            # (the TRUE object — an injected in-flight flip must not
            # change it, exactly like real wire damage would not).
            etag = self._etag(key)
            if etag:
                headers["ETag"] = f'"{etag}"'
        self._respond(
            req, status, data, claimed_len=claimed_len, headers=headers
        )
        with self._lock:
            self.requests_served += 1

    # -- conditional writes (the lease transport, DESIGN.md §23) -------------

    def _write_key(self, key: str, body: bytes) -> str:
        """Install ``body`` under ``key`` and return its new ETag.  File
        roots write tmp-then-replace so a concurrent GET never reads a
        torn object (the same discipline the clients themselves use)."""
        with self._lock:
            if isinstance(self.root, dict):
                self.root[key] = body
            else:
                path = os.path.join(self.root, key)
                tmp = f"{path}.put-tmp"
                with open(tmp, "wb") as f:
                    f.write(body)
                os.replace(tmp, path)
                self._etags.pop(key, None)
        return hashlib.md5(body + self.etag_salt).hexdigest()

    @staticmethod
    def _skew_body(body: bytes, seconds: float) -> bytes:
        """Shift ``expires_at`` in a lease JSON body (the clock-skewed
        writer fault); non-lease bodies pass through untouched."""
        try:
            doc = json.loads(body.decode("utf-8"))
            doc["expires_at"] = float(doc["expires_at"]) + seconds
            return json.dumps(doc, sort_keys=True).encode("utf-8")
        except (ValueError, KeyError, TypeError, UnicodeDecodeError):
            return body

    def _handle_put(
        self, req: BaseHTTPRequestHandler, key: str, index: int
    ) -> None:
        try:
            length = int(req.headers.get("Content-Length", "0") or "0")
        except ValueError:
            self._respond(req, 400, b"bad content-length")
            return
        body = req.rfile.read(length) if length > 0 else b""
        action = (
            self.put_fault_hook(key, body, index)
            if self.put_fault_hook is not None
            else None
        )
        if action == "drop":
            # Plain transport failure: the write was NOT applied, so the
            # client's retry (same condition) is safe.
            try:
                req.connection.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            req.close_connection = True
            return
        if isinstance(action, tuple) and action[0] == "status":
            self._respond(req, int(action[1]), b"injected fault")
            return
        if isinstance(action, tuple) and action[0] == "race":
            # A competing writer lands FIRST; this request's condition is
            # then evaluated against the competitor's object (genuine
            # stale-ETag 412, not an injected status).
            self._write_key(key, bytes(action[1]))
        if isinstance(action, tuple) and action[0] == "skew":
            body = self._skew_body(body, float(action[1]))
        if_match = req.headers.get("If-Match")
        if_none_match = req.headers.get("If-None-Match")
        current = self._etag(key)
        if if_match is not None:
            # If-Match against a missing object fails too: you cannot
            # fence on a version that no longer exists.
            if current is None or if_match.strip('"') != current:
                self._respond(req, 412, b"precondition failed")
                return
        elif if_none_match is not None:
            if current is not None:
                self._respond(req, 412, b"precondition failed")
                return
        etag = self._write_key(key, body)
        if action == "lost":
            # The ambiguous PUT: applied server-side, but the response
            # never reaches the client — its conditional retry will 412
            # against its OWN write (resolved by read-back upstream).
            try:
                req.connection.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            req.close_connection = True
            return
        headers = {"ETag": f'"{etag}"'} if self.send_etag else {}
        self._respond(req, 200, b"", headers=headers)
        with self._lock:
            self.requests_served += 1

    def _respond(
        self,
        req: BaseHTTPRequestHandler,
        status: int,
        body: bytes,
        content_type: str = "application/octet-stream",
        claimed_len: "Optional[int]" = None,
        headers: "Optional[Dict[str, str]]" = None,
    ) -> None:
        try:
            req.send_response(status)
            req.send_header("Content-Type", content_type)
            req.send_header(
                "Content-Length",
                str(len(body) if claimed_len is None else claimed_len),
            )
            for k, v in (headers or {}).items():
                req.send_header(k, v)
            req.end_headers()
            req.wfile.write(body)
            if claimed_len is not None and claimed_len != len(body):
                # Truncation fault: the headers promised more than was
                # written — drop the connection so the client's read fails.
                req.connection.shutdown(socket.SHUT_RDWR)
                req.close_connection = True
        except OSError:
            req.close_connection = True


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", required=True,
                    help="directory of .ktaseg chunks to serve")
    ap.add_argument("--bucket", default="segments",
                    help="bucket name (the URL path prefix)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="0 binds an ephemeral port (announced on stdout)")
    ap.add_argument("--latency-ms", type=float, default=0.0,
                    help="injected per-request service delay")
    args = ap.parse_args(argv)
    if not os.path.isdir(args.root):
        ap.error(f"--root {args.root!r} is not a directory")
    server = ObjectStoreHttpServer(
        args.root, bucket=args.bucket, latency_ms=args.latency_ms,
        host=args.host, port=args.port,
    ).start()
    print(f"objstore_serve: {server.url} (root {args.root})", flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        server.close()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
