"""Run every BASELINE benchmark config and emit one JSON report.

Usage:
    python -m kafka_topic_analyzer_tpu.tools.bench_all [--batch-size N]
        [--steps N] [--out report.json]

Each config runs through bench.py in a subprocess (fresh jit caches, honest
per-config timing); the report maps config id → bench JSON line.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=None,
                    help="records per device step; default lets bench.py "
                         "pick per platform (2^20, or the proven-good 2^16 "
                         "on the axon tunnel)")
    ap.add_argument("--batches", type=int, default=8)
    ap.add_argument("--steps", type=int, default=32)
    ap.add_argument("--out", default="-")
    ap.add_argument("--configs", default="1,2,3,4,5")
    args = ap.parse_args(argv)

    repo = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    # Probe the accelerator ONCE and pass the verdict to every child: a
    # wedged tunnel would otherwise cost each config the full probe timeout.
    from kafka_topic_analyzer_tpu.jax_support import ensure_responsive_accelerator

    child_env = dict(os.environ)
    report = {}
    verdict = ensure_responsive_accelerator()
    if verdict:
        # Pass the probed platform itself when we have it ("cpu" makes the
        # children drop the tunnel factory instead of racing a wedge-prone
        # client init; see jax_support.ensure_responsive_accelerator).
        child_env.setdefault(
            "KTA_ACCEL_OK", verdict if isinstance(verdict, str) else "1"
        )
    else:
        child_env["KTA_JAX_PLATFORMS"] = "cpu"
        # Children must self-describe too: an explicit platform override
        # alone reads as a deliberate CPU run, but this one is a fallback.
        child_env["KTA_DEGRADED"] = "1"
        report["degraded_cpu_fallback"] = True
    for cfg in [int(c) for c in args.configs.split(",") if c]:
        cmd = [
            sys.executable, os.path.join(repo, "bench.py"),
            "--config", str(cfg),
            "--batches", str(args.batches),
            "--steps", str(args.steps),
            "--accuracy",  # the BASELINE metric includes sketch error
        ]
        if args.batch_size:
            cmd += ["--batch-size", str(args.batch_size)]
        print(f"bench_all: running config {cfg}...", file=sys.stderr)
        proc = subprocess.run(cmd, capture_output=True, text=True, env=child_env)
        if proc.returncode != 0:
            report[str(cfg)] = {"error": proc.stderr.strip()[-500:]}
            continue
        last = proc.stdout.strip().splitlines()[-1]
        report[str(cfg)] = json.loads(last)
        print(f"bench_all: config {cfg}: {last}", file=sys.stderr)

    # End-to-end pipeline figure (broker → wire client → decode → pack →
    # device) next to the device-path numbers — the apples-to-apples
    # comparison to the reference's published 590,221 msgs/s
    # (demo_output.png, src/main.rs:130).
    cmd = [
        sys.executable, "-m", "kafka_topic_analyzer_tpu.tools.bench_e2e",
        "--backend", "tpu", "--quiet",
    ]
    print("bench_all: running e2e pipeline...", file=sys.stderr)
    try:
        # Same hang discipline as bench.py's supervisor: a wedged device
        # step must not block the report the driver is waiting for.
        proc = subprocess.run(
            cmd, capture_output=True, text=True, env=child_env, cwd=repo,
            timeout=float(os.environ.get("KTA_BENCH_DEADLINE") or 900),
        )
    except subprocess.TimeoutExpired:
        proc = None
    if proc is None:
        report["e2e"] = {"error": "timed out (accelerator hang?)"}
    elif proc.returncode != 0:
        report["e2e"] = {"error": proc.stderr.strip()[-500:]}
    else:
        last = proc.stdout.strip().splitlines()[-1]
        report["e2e"] = json.loads(last)
        print(f"bench_all: e2e: {last}", file=sys.stderr)

    out = json.dumps(report, indent=2)
    if args.out == "-":
        print(out)
    else:
        with open(args.out, "w") as f:
            f.write(out + "\n")
        print(f"bench_all: wrote {args.out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
