"""Cold-path capacity benchmark: broker-bypass segment scanning rec/s vs
worker count (BENCH round 8), plus the remote-tier latency-hiding referee
(BENCH round 14).

Measures the `--source segfile` ingest pipeline — .ktaseg chunks →
zero-copy column views → wire pack — through the same partition-sharded
fan-in the engine runs (`parallel/ingest.py`), minus the device backend,
so the number is the cold scan's host ingest ceiling.  With ``--store
serve`` the same chunks are served through the in-process S3-shaped
object store (tools/objstore_serve.py) with ``--inject-latency-ms`` of
per-GET service delay, and the sweep crosses worker counts with
``--readahead`` depths — the referee for DESIGN.md §21's claim that
read-ahead hides wire latency behind the decode→pack pass.  ``--cache``
adds the warm-vs-cold re-audit split (pass 1 fills the segment cache,
later passes hit it).

One JSON line, bench_ingest-style: per-cell wall rates (best-of with the
full run list), records/client-CPU-second, and the catalog digest.

Usage:
    python -m kafka_topic_analyzer_tpu.tools.bench_segments \
        --records 8000000 --partitions 16 --workers 1,2,4,8
    python -m kafka_topic_analyzer_tpu.tools.bench_segments \
        --records 2000000 --partitions 16 --workers 4 \
        --store serve --inject-latency-ms 50 --readahead 0,4
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

from kafka_topic_analyzer_tpu.config import AnalyzerConfig, SegmentFetchConfig


def _build_segments(args, directory: str) -> None:
    """Synthesize the workload as .ktaseg chunks (tools/make_segments with
    the native generator when available)."""
    from kafka_topic_analyzer_tpu.tools.make_segments import main as ms_main

    per_part = max(args.records // args.partitions, 1)
    spec = (
        f"partitions={args.partitions},messages={per_part},"
        f"keys={args.keys},tombstones=100"
    )
    rc = ms_main([
        "--out", directory, "--topic", args.topic, "--synthetic", spec,
        "--batch-size", str(max(args.batch_size, 1 << 18)),
        "--chunk-records", str(args.chunk_records),
        "--native", args.native,
    ])
    if rc != 0:
        raise SystemExit("segment generation failed")


def _measure(source, batch_size: int, workers: int, stage) -> dict:
    """One timed drain: N=1 is the sequential referee (plain batches()
    loop + inline stage — the engine's prefetch path minus the thread),
    N>1 the deterministic fan-in with per-worker staging, exactly what
    `--ingest-workers N` runs inside the engine."""
    from kafka_topic_analyzer_tpu.parallel.ingest import (
        ParallelIngest,
        shard_partitions,
    )

    got = 0
    c0 = os.times()
    t0 = time.perf_counter()
    if workers == 1:
        for batch in source.batches(batch_size):
            if stage is not None:
                stage(batch)
            got += len(batch)
    else:
        groups = shard_partitions(
            source.partitions(), workers,
            weights=source.partition_record_counts(),
        )
        pool = ParallelIngest(source, batch_size, groups, stage=stage, depth=2)
        try:
            for batch, _staged in pool:
                got += len(batch)
        finally:
            pool.close()
    wall = time.perf_counter() - t0
    c1 = os.times()
    return {
        "records": got,
        "wall": wall,
        "cpu": (c1.user - c0.user) + (c1.system - c0.system),
    }


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--segment-dir",
                    help="existing .ktaseg directory to scan; default: "
                         "synthesize one from the workload flags below "
                         "into a temp dir")
    ap.add_argument("--topic", default="bench-seg")
    ap.add_argument("--records", type=int, default=8_000_000)
    ap.add_argument("--partitions", type=int, default=16)
    ap.add_argument("--keys", type=int, default=5000)
    ap.add_argument("--batch-size", type=int, default=1 << 16)
    ap.add_argument("--chunk-records", type=int, default=1 << 16,
                    help="rolled chunk size for synthesized segments "
                         "(many chunks per partition = the shape "
                         "read-ahead works against)")
    ap.add_argument("--workers", default="1,2,4,8",
                    help="comma-separated worker counts to sweep")
    ap.add_argument("--store", default="dir", metavar="dir|serve|URL",
                    help="'dir' scans the local directory (the round-8 "
                         "referee); 'serve' serves the same chunks "
                         "through the in-process S3-shaped store "
                         "(tools/objstore_serve.py) and scans remotely; "
                         "an http(s):// URL scans that store as-is")
    ap.add_argument("--inject-latency-ms", type=float, default=0.0,
                    help="per-GET service delay for --store serve — the "
                         "wire-RTT stand-in the read-ahead referee "
                         "measures against")
    ap.add_argument("--readahead", default="auto",
                    help="comma-separated --segment-readahead depths to "
                         "sweep for remote stores (e.g. '0,4'); 'auto' "
                         "uses the resolved default")
    ap.add_argument("--fetch-concurrency", default="auto",
                    help="comma-separated --fetch-concurrency sizes to "
                         "sweep for remote stores (e.g. '2,4,8'), 'auto' "
                         "for the resolved default only, or 'sweep' for "
                         "the canonical 2,4,8,auto ladder — the BENCH "
                         "round 16 referee for the shared-scheduler "
                         "admission layer")
    ap.add_argument("--cache", metavar="DIR",
                    help="run remote cells through a --segment-cache at "
                         "DIR: the first pass per cell is recorded as "
                         "COLD (cache cleared), later passes as WARM")
    ap.add_argument("--timeout-s", type=float, default=30.0,
                    help="remote fetch timeout per request")
    ap.add_argument("--repeat", type=int, default=3,
                    help="passes per cell; best is the headline "
                         "(capacity is a max on a shared box), with the "
                         "full run list alongside")
    ap.add_argument("--no-pack", action="store_true",
                    help="skip the wire pack stage (isolates the "
                         "read cost; default stages pack on the "
                         "workers exactly like the tpu cold scan)")
    ap.add_argument("--features", default="counters",
                    help="comma list for the pack config: counters[,alive]"
                         "[,hll][,quantiles]")
    ap.add_argument("--native", choices=["auto", "on", "off"], default="auto")
    args = ap.parse_args(argv)
    sweep = [int(w) for w in args.workers.split(",") if w]
    if any(w < 1 for w in sweep):
        ap.error("--workers entries must be >= 1")
    if args.cache and args.store == "dir":
        ap.error("--cache only applies to remote stores (--store serve/URL)")
    if args.cache and args.repeat < 2:
        ap.error(
            "--cache needs --repeat >= 2: pass 1 is the COLD fill; "
            "reporting it as the warm headline would compare cold to cold"
        )
    ra_sweep: "list[int | str]" = [
        ("auto" if r.strip().lower() == "auto" else int(r))
        for r in args.readahead.split(",")
        if r.strip()
    ]
    fc_text = args.fetch_concurrency.strip().lower()
    if fc_text == "sweep":
        fc_text = "2,4,8,auto"
    fc_sweep: "list[int | str]" = [
        ("auto" if c.strip().lower() == "auto" else int(c))
        for c in fc_text.split(",")
        if c.strip()
    ]
    if any(isinstance(c, int) and c < 1 for c in fc_sweep):
        ap.error("--fetch-concurrency entries must be >= 1 or 'auto'")

    from kafka_topic_analyzer_tpu.io.segfile import SegmentFileSource
    from kafka_topic_analyzer_tpu.packing import pack_batch

    tmp = None
    seg_dir = args.segment_dir
    if seg_dir is None:
        tmp = tempfile.mkdtemp(prefix="kta-bench-seg-")
        seg_dir = tmp
        print(f"bench_segments: building segments in {seg_dir}",
              file=sys.stderr)
        _build_segments(args, seg_dir)
    server = None
    store_spec = None
    if args.store == "serve":
        from kafka_topic_analyzer_tpu.tools.objstore_serve import (
            ObjectStoreHttpServer,
        )

        server = ObjectStoreHttpServer(
            seg_dir, latency_ms=args.inject_latency_ms
        ).start()
        store_spec = server.url
        print(f"bench_segments: serving {seg_dir} at {store_spec} "
              f"(+{args.inject_latency_ms:g} ms/GET)", file=sys.stderr)
    elif args.store != "dir":
        store_spec = args.store
    remote = store_spec is not None
    if not remote:
        ra_sweep = ["auto"]  # local: readahead resolves to 0; one cell
        fc_sweep = ["auto"]  # local scans never touch the scheduler

    def make_source(ra, fc="auto") -> SegmentFileSource:
        if not remote:
            return SegmentFileSource(seg_dir, args.topic)
        fetch = SegmentFetchConfig(
            readahead=ra,
            cache_dir=args.cache,
            timeout_s=args.timeout_s,
            fetch_concurrency=fc,
        )
        return SegmentFileSource(store_spec, args.topic, fetch=fetch)

    def reset_scheduler() -> None:
        """Fresh scheduler per fetch-concurrency cell: the pool is a
        process singleton and an explicit size latches, so sweeping
        sizes inside one bench process needs a clean teardown between
        cells (threads joined, configuration forgotten)."""
        if remote:
            from kafka_topic_analyzer_tpu.io import fetchsched

            fetchsched._reset_for_tests()

    try:
        probe = make_source(0 if remote else "auto")
        feats = {f.strip() for f in args.features.split(",") if f.strip()}
        config = AnalyzerConfig(
            num_partitions=len(probe.partitions()),
            batch_size=args.batch_size,
            count_alive_keys="alive" in feats,
            enable_hll="hll" in feats,
            enable_quantiles="quantiles" in feats,
        )
        use_native = args.native in ("auto", "on")
        stage = None
        if not args.no_pack:
            # Mirror the engine's worker staging: dense ids + wire pack
            # (native, GIL-released) on the worker thread.  Synthetic dumps
            # are dense already; a user-supplied catalog may not be.
            from kafka_topic_analyzer_tpu.engine import PartitionIndex

            pindex = PartitionIndex(probe.partitions())

            def stage(b):  # noqa: F811 — the staging callable
                return pack_batch(
                    pindex.remap_batch(b), config, use_native=use_native
                )

        doc: "dict[str, object]" = {
            "metric": "segments",
            "nproc": os.cpu_count(),
            "topic": args.topic,
            "batch_size": args.batch_size,
            "pack": not args.no_pack,
            "features": sorted(feats),
            "store": args.store,
            "inject_latency_ms": args.inject_latency_ms,
            "cache": bool(args.cache),
            "fetch_concurrency": [str(c) for c in fc_sweep],
            "catalog": {
                "files": probe.catalog.num_files,
                "bytes": probe.catalog.total_bytes,
                "records": sum(probe.catalog.record_counts().values()),
                "partitions": len(probe.partitions()),
            },
        }
        rates: "dict[str, int]" = {}
        runs: "dict[str, list[int]]" = {}
        cpu_rates: "dict[str, int]" = {}
        cold_rates: "dict[str, int]" = {}
        for n in sweep:
            for ra in ra_sweep:
              for fc in fc_sweep:
                if not remote:
                    key = str(n)
                elif len(fc_sweep) > 1:
                    key = f"w{n}.ra{ra}.fc{fc}"
                else:
                    # Round-14-compatible keys when concurrency isn't
                    # being swept, so old/new ledgers diff cell-by-cell.
                    key = f"w{n}.ra{ra}"
                reset_scheduler()
                if args.cache:
                    # Cold half of the warm-vs-cold referee: an empty
                    # cache, so pass 1 pays every fetch.
                    shutil.rmtree(args.cache, ignore_errors=True)
                best = None
                n_runs = []
                for rep in range(max(args.repeat, 1)):
                    # A fresh source per pass: per-file constant caches and
                    # OS page cache persist (deliberately — cold *IO* is
                    # the disk's story; this measures the pipeline), but
                    # reader state does not leak across cells.  The warm
                    # passes also restart the verify latch trust (new
                    # scheduler/config process state persists within a
                    # bench process — the latch is per-process, so pass 2+
                    # measure the LATCHED warm path).
                    src = make_source(ra, fc)
                    r = _measure(src, args.batch_size, n, stage)
                    rate = round(r["records"] / r["wall"])
                    n_runs.append(rate)
                    if args.cache and rep == 0:
                        cold_rates[key] = rate
                    if best is None or r["records"] / r["wall"] > (
                        best["records"] / best["wall"]
                    ):
                        best = r
                warm_runs = n_runs[1:] if args.cache and len(n_runs) > 1 \
                    else n_runs
                rates[key] = max(warm_runs)
                runs[key] = n_runs
                cpu_rates[key] = (
                    round(best["records"] / best["cpu"]) if best["cpu"] else 0
                )
                print(
                    f"bench_segments: {key}: {best['records']} records, "
                    f"best of {len(n_runs)}: {rates[key]:,}/s "
                    f"(wall={best['wall']:.2f}s cpu={best['cpu']:.2f}s)"
                    + (
                        f" cold={cold_rates[key]:,}/s"
                        if key in cold_rates else ""
                    ),
                    file=sys.stderr,
                )
        doc["seg_msgs_per_sec"] = rates
        doc["seg_runs"] = runs
        doc["seg_cpu_msgs_per_sec"] = cpu_rates
        if cold_rates:
            doc["seg_cold_msgs_per_sec"] = cold_rates
        if "1" in rates:
            doc["speedup_vs_1"] = {
                n: round(v / rates["1"], 2) for n, v in rates.items()
            }
        print(json.dumps(doc))
        return 0
    finally:
        if server is not None:
            server.close()
        if tmp is not None:
            shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
