"""Cold-path capacity benchmark: broker-bypass segment scanning rec/s vs
worker count (BENCH round 8).

Measures the `--source segfile` ingest pipeline — memory-mapped .ktaseg
chunks → zero-copy column views → wire-v4 pack — through the same
partition-sharded fan-in the engine runs (`parallel/ingest.py`), minus the
device backend, so the number is the cold scan's host ingest ceiling.  The
referee for the worker sweep is the round-3 socket-free pipeline
measurement (12-13M rec/s/core on this class of box): the segment path
deletes the kernel receive cost entirely, so N workers should aggregate
toward N x the per-core pipeline rate until memory bandwidth binds.

One JSON line, bench_ingest-style: per-N wall rates (best-of with the
full run list), records/client-CPU-second, and the catalog digest.

Usage:
    python -m kafka_topic_analyzer_tpu.tools.bench_segments \
        --records 8000000 --partitions 16 --workers 1,2,4,8
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

from kafka_topic_analyzer_tpu.config import AnalyzerConfig


def _build_segments(args, directory: str) -> None:
    """Synthesize the workload as .ktaseg chunks (tools/make_segments with
    the native generator when available)."""
    from kafka_topic_analyzer_tpu.tools.make_segments import main as ms_main

    per_part = max(args.records // args.partitions, 1)
    spec = (
        f"partitions={args.partitions},messages={per_part},"
        f"keys={args.keys},tombstones=100"
    )
    rc = ms_main([
        "--out", directory, "--topic", args.topic, "--synthetic", spec,
        "--batch-size", str(max(args.batch_size, 1 << 18)),
        "--native", args.native,
    ])
    if rc != 0:
        raise SystemExit("segment generation failed")


def _measure(source, batch_size: int, workers: int, stage) -> dict:
    """One timed drain: N=1 is the sequential referee (plain batches()
    loop + inline stage — the engine's prefetch path minus the thread),
    N>1 the deterministic fan-in with per-worker staging, exactly what
    `--ingest-workers N` runs inside the engine."""
    from kafka_topic_analyzer_tpu.parallel.ingest import (
        ParallelIngest,
        shard_partitions,
    )

    got = 0
    c0 = os.times()
    t0 = time.perf_counter()
    if workers == 1:
        for batch in source.batches(batch_size):
            if stage is not None:
                stage(batch)
            got += len(batch)
    else:
        groups = shard_partitions(
            source.partitions(), workers,
            weights=source.partition_record_counts(),
        )
        pool = ParallelIngest(source, batch_size, groups, stage=stage, depth=2)
        try:
            for batch, _staged in pool:
                got += len(batch)
        finally:
            pool.close()
    wall = time.perf_counter() - t0
    c1 = os.times()
    return {
        "records": got,
        "wall": wall,
        "cpu": (c1.user - c0.user) + (c1.system - c0.system),
    }


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--segment-dir",
                    help="existing .ktaseg directory to scan; default: "
                         "synthesize one from the workload flags below "
                         "into a temp dir")
    ap.add_argument("--topic", default="bench-seg")
    ap.add_argument("--records", type=int, default=8_000_000)
    ap.add_argument("--partitions", type=int, default=16)
    ap.add_argument("--keys", type=int, default=5000)
    ap.add_argument("--batch-size", type=int, default=1 << 16)
    ap.add_argument("--workers", default="1,2,4,8",
                    help="comma-separated worker counts to sweep")
    ap.add_argument("--repeat", type=int, default=3,
                    help="passes per worker count; best is the headline "
                         "(capacity is a max on a shared box), with the "
                         "full run list alongside")
    ap.add_argument("--no-pack", action="store_true",
                    help="skip the wire-v4 pack stage (isolates the "
                         "mmap-read cost; default stages pack on the "
                         "workers exactly like the tpu cold scan)")
    ap.add_argument("--features", default="counters",
                    help="comma list for the pack config: counters[,alive]"
                         "[,hll][,quantiles]")
    ap.add_argument("--native", choices=["auto", "on", "off"], default="auto")
    args = ap.parse_args(argv)
    sweep = [int(w) for w in args.workers.split(",") if w]
    if any(w < 1 for w in sweep):
        ap.error("--workers entries must be >= 1")

    from kafka_topic_analyzer_tpu.io.segfile import SegmentFileSource
    from kafka_topic_analyzer_tpu.packing import pack_batch

    tmp = None
    seg_dir = args.segment_dir
    if seg_dir is None:
        tmp = tempfile.mkdtemp(prefix="kta-bench-seg-")
        seg_dir = tmp
        print(f"bench_segments: building segments in {seg_dir}",
              file=sys.stderr)
        _build_segments(args, seg_dir)
    try:
        probe = SegmentFileSource(seg_dir, args.topic)
        feats = {f.strip() for f in args.features.split(",") if f.strip()}
        config = AnalyzerConfig(
            num_partitions=len(probe.partitions()),
            batch_size=args.batch_size,
            count_alive_keys="alive" in feats,
            enable_hll="hll" in feats,
            enable_quantiles="quantiles" in feats,
        )
        use_native = args.native in ("auto", "on")
        stage = None
        if not args.no_pack:
            # Mirror the engine's worker staging: dense ids + wire-v4 pack
            # (native, GIL-released) on the worker thread.  Synthetic dumps
            # are dense already; a user-supplied catalog may not be.
            from kafka_topic_analyzer_tpu.engine import PartitionIndex

            pindex = PartitionIndex(probe.partitions())

            def stage(b):  # noqa: F811 — the staging callable
                return pack_batch(
                    pindex.remap_batch(b), config, use_native=use_native
                )

        doc: "dict[str, object]" = {
            "metric": "segments",
            "nproc": os.cpu_count(),
            "topic": args.topic,
            "batch_size": args.batch_size,
            "pack": not args.no_pack,
            "features": sorted(feats),
            "catalog": {
                "files": probe.catalog.num_files,
                "bytes": probe.catalog.total_bytes,
                "records": sum(probe.catalog.record_counts().values()),
                "partitions": len(probe.partitions()),
            },
        }
        rates: "dict[str, int]" = {}
        runs: "dict[str, list[int]]" = {}
        cpu_rates: "dict[str, int]" = {}
        for n in sweep:
            best = None
            n_runs = []
            for _ in range(max(args.repeat, 1)):
                # A fresh source per pass: per-file constant caches and OS
                # page cache persist (deliberately — cold *IO* is the disk's
                # story; this measures the pipeline), but reader state does
                # not leak across worker counts.
                src = SegmentFileSource(seg_dir, args.topic)
                r = _measure(src, args.batch_size, n, stage)
                n_runs.append(round(r["records"] / r["wall"]))
                if best is None or r["records"] / r["wall"] > (
                    best["records"] / best["wall"]
                ):
                    best = r
            rates[str(n)] = max(n_runs)
            runs[str(n)] = n_runs
            cpu_rates[str(n)] = (
                round(best["records"] / best["cpu"]) if best["cpu"] else 0
            )
            print(
                f"bench_segments: {n} worker(s) {best['records']} records, "
                f"best of {len(n_runs)}: {max(n_runs):,}/s "
                f"(wall={best['wall']:.2f}s cpu={best['cpu']:.2f}s)",
                file=sys.stderr,
            )
        doc["seg_msgs_per_sec"] = rates
        doc["seg_runs"] = runs
        doc["seg_cpu_msgs_per_sec"] = cpu_rates
        if "1" in rates:
            doc["speedup_vs_1"] = {
                n: round(v / rates["1"], 2) for n, v in rates.items()
            }
        print(json.dumps(doc))
        return 0
    finally:
        if tmp is not None:
            shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
