"""End-to-end pipeline benchmark: ONE measured msgs/s figure covering
broker → wire client → record decode → pack → device → reduce.

This is the apples-to-apples comparison to the reference's published
590,221 msgs/s (demo_output.png; formula src/main.rs:130 =
overall_count / max(secs, 1)): the reference's number times the whole
consume pipeline, whereas ``bench.py`` times the device path with
pre-materialized batches.  Here the records cross a real loopback TCP
socket as Kafka Fetch v4 responses and the scan runs through the real
engine (`engine.run_scan`) — the same code path as ``kta --source kafka``.

The serving side must be far faster than the client under test, so the
broker never encodes per record at fetch time.  It pre-encodes a small
set of **template RecordBatches** (base_offset 0) and serves every offset
window as a template copy with the base_offset header patched in place.
That is valid Kafka wire data: the v2 batch CRC32-C covers attributes
onward and explicitly EXCLUDES base_offset/batch_length/leader_epoch/
magic/crc (io/kafka_codec.py:encode_record_batch), and record offset
deltas are relative to base_offset — so an 8-byte patch retargets a batch
to any window at memcpy speed.  Distinct templates carry distinct key
sets, so HLL/alive-key paths still see `templates × records_per_batch`
unique keys cycling through the topic.
"""

from __future__ import annotations

import argparse
import json
import socket
import struct
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from kafka_topic_analyzer_tpu.io import kafka_codec as kc

BASELINE_MSGS_PER_SEC = 590_221.0


def build_templates(
    records_per_batch: int,
    n_templates: int,
    vmin: int,
    vmax: int,
    seed: int = 7,
    compression: int = kc.COMPRESSION_NONE,
    tombstone_every: int = 0,
) -> List[bytes]:
    """Encode ``n_templates`` RecordBatches (base_offset 0) with disjoint
    key sets and seeded value sizes in [vmin, vmax]."""
    rng = np.random.default_rng(seed)
    base_ts = 1_767_225_600_000  # 2026-01-01T00:00:00Z, ms
    out = []
    for t in range(n_templates):
        sizes = rng.integers(vmin, vmax + 1, size=records_per_batch)
        recs: List[kc.OffsetRecord] = []
        for i in range(records_per_batch):
            key = b"k%04d-%08d" % (t, i)
            if tombstone_every and i % tombstone_every == (t % tombstone_every):
                value = None
            else:
                value = bytes(int(sizes[i]))
            recs.append((i, base_ts + i, key, value))
        out.append(kc.encode_record_batch(recs, compression=compression))
    return out


def _sendmsg_all(conn: socket.socket, bufs: "list") -> None:
    """sendall semantics for a scatter-gather buffer list (sendmsg may
    send partially; resume from the exact byte)."""
    views = [memoryview(b) for b in bufs]
    i = 0
    while i < len(views):
        sent = conn.sendmsg(views[i : i + 512])
        while i < len(views) and sent >= len(views[i]):
            sent -= len(views[i])
            i += 1
        if sent:
            views[i] = views[i][sent:]


class TemplateBroker:
    """Loopback Kafka broker serving base_offset-patched template batches.

    Speaks exactly the APIs the wire client negotiates (ApiVersions v0,
    Metadata v1–v5, ListOffsets v1, Fetch v4) and honors both byte budgets
    of a Fetch request — partition_max_bytes per partition and the KIP-74
    request-level max_bytes (first batch always served whole).
    """

    def __init__(
        self,
        topic: str,
        partitions: int,
        windows_per_partition: int,
        templates: List[bytes],
        records_per_batch: int,
        brokers: int = 1,
    ):
        self.topic = topic
        self.partitions = list(range(partitions))
        self.partition_set = set(self.partitions)
        #: Template split for scatter-gather serving: the first 8 bytes of
        #: a v2 frame are base_offset (not CRC-covered), so a response is
        #: [8-byte patched header][shared template tail] pairs — the tails
        #: are served zero-copy straight from these views by sendmsg.
        self.tmpl_tails = [memoryview(t)[8:] for t in templates]
        self.windows = windows_per_partition
        self.templates = templates
        self.R = records_per_batch
        self.end_offset = windows_per_partition * records_per_batch
        #: N listener sockets = N advertised broker nodes (partition p is
        #: led by node p % N) — exercises the wire client's
        #: leader-parallel fetch the way a real multi-broker cluster does.
        self._socks: List[socket.socket] = []
        self.ports: List[int] = []
        for _ in range(max(1, brokers)):
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind(("127.0.0.1", 0))
            s.listen(16)
            self._socks.append(s)
            self.ports.append(s.getsockname()[1])
        self.port = self.ports[0]
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "TemplateBroker":
        for s in self._socks:
            t = threading.Thread(
                target=self._accept_loop, args=(s,), daemon=True
            )
            t.start()
            self._threads.append(t)
        return self

    def stop(self) -> None:
        self._stop.set()
        for s in self._socks:
            try:
                s.close()
            except OSError:
                pass

    def __enter__(self) -> "TemplateBroker":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _accept_loop(self, sock: socket.socket) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = sock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = threading.Thread(target=self._serve, args=(conn,), daemon=True)
            t.start()
            self._threads.append(t)

    @staticmethod
    def _recv_exact(conn: socket.socket, n: int) -> Optional[bytes]:
        buf = bytearray()
        while len(buf) < n:
            try:
                chunk = conn.recv(n - len(buf))
            except OSError:
                return None
            if not chunk:
                return None
            buf += chunk
        return bytes(buf)

    # -- serving -------------------------------------------------------------

    def _serve(self, conn: socket.socket) -> None:
        with conn:
            while not self._stop.is_set():
                head = self._recv_exact(conn, 4)
                if head is None:
                    return
                (length,) = struct.unpack(">i", head)
                payload = self._recv_exact(conn, length)
                if payload is None:
                    return
                api_key, api_version, corr, _client, r = (
                    kc.decode_request_header(payload)
                )
                body = self._dispatch(api_key, api_version, r)
                # Fetch responses are iovec lists served scatter-gather:
                # sendmsg lets the kernel read the shared template tails
                # directly — zero Python-side assembly of the ~64 MB body.
                if isinstance(body, list):
                    total = sum(len(b) for b in body)
                    _sendmsg_all(
                        conn,
                        [struct.pack(">ii", 4 + total, corr)] + body,
                    )
                else:
                    conn.sendall(struct.pack(">ii", 4 + len(body), corr))
                    conn.sendall(body)

    def _fetch_response(self, parts, max_bytes: int) -> "list":
        """Build the Fetch v4 response as an iovec list: small packed
        header chunks interleaved with the SHARED template tails (the
        first 8 bytes of each frame — base_offset, not CRC-covered — are
        per-window header chunks).  sendmsg serves the tails zero-copy, so
        the serving side never assembles the multi-MB body at all and
        stays far faster than the client under test.

        Budgets mirror a real broker: per-partition ``partition_max_bytes``
        and the KIP-74 request-level ``max_bytes``, with the first
        non-empty partition always granted one whole batch (minOneMessage),
        which the wire client's starvation logic relies on."""
        K = len(self.templates)
        plan = []  # (pid, err, first_window, n_windows, rs_bytes)
        budget = max_bytes
        served_any = False
        for pid, fetch_offset, pmax, _epoch in parts:
            if pid not in self.partition_set:
                plan.append((pid, kc.ERR_UNKNOWN_TOPIC_OR_PARTITION, 0, 0, 0))
                continue
            w0 = fetch_offset // self.R  # align down; clients skip below
            lim = min(pmax, budget)
            n = 0
            size = 0
            while w0 + n < self.windows and (
                size < lim or (n == 0 and not served_any)
            ):
                size += len(self.templates[(w0 + n) % K])
                n += 1
            if n:
                served_any = True
            budget = max(0, budget - size)
            plan.append((pid, 0, w0, n, size))

        topic_b = self.topic.encode()
        head = struct.pack(
            ">iiH", 0, 1, len(topic_b)
        ) + topic_b + struct.pack(">i", len(plan))
        iov = [head]
        for pid, err, w0, n, size in plan:
            iov.append(
                struct.pack(
                    ">ihqqii", pid, err, self.end_offset,
                    self.end_offset, 0, size,
                )
            )
            for i in range(n):
                w = w0 + i
                iov.append(struct.pack(">q", w * self.R))
                iov.append(self.tmpl_tails[w % K])
        return iov

    def _dispatch(self, api_key: int, api_version: int, r: kc.ByteReader) -> bytes:
        if api_key == kc.API_VERSIONS:
            return kc.encode_api_versions_response(
                [
                    (kc.API_FETCH, 0, 4),
                    (kc.API_LIST_OFFSETS, 0, 1),
                    (kc.API_METADATA, 0, 5),
                ]
            )
        if api_key == kc.API_METADATA:
            requested = []
            n = r.i32()
            for _ in range(max(n, 0)):
                requested.append(r.string())
            nb = len(self.ports)
            topics = [
                kc.TopicMetadata(
                    0,
                    self.topic,
                    [
                        kc.PartitionMetadata(0, p, p % nb)
                        for p in self.partitions
                    ],
                )
                if name == self.topic
                else kc.TopicMetadata(
                    kc.ERR_UNKNOWN_TOPIC_OR_PARTITION, name or "", []
                )
                for name in (requested if requested else [self.topic])
            ]
            return kc.encode_metadata_response(
                kc.MetadataResponse(
                    {i: ("127.0.0.1", port) for i, port in enumerate(self.ports)},
                    0,
                    topics,
                ),
                version=api_version,
            )
        if api_key == kc.API_LIST_OFFSETS:
            _topic, parts = kc.decode_list_offsets_request(r)
            results = []
            for pid, ts in parts:
                if pid not in self.partitions:
                    results.append(
                        (pid, kc.ERR_UNKNOWN_TOPIC_OR_PARTITION, -1, -1)
                    )
                elif ts == kc.EARLIEST_TIMESTAMP:
                    results.append((pid, 0, -1, 0))
                elif ts == kc.LATEST_TIMESTAMP:
                    results.append((pid, 0, -1, self.end_offset))
                else:
                    results.append((pid, 0, ts, 0))
            return kc.encode_list_offsets_response(self.topic, results)
        if api_key == kc.API_FETCH:
            _topic, parts, _mw, _mb, max_bytes = kc.decode_fetch_request(r)
            return self._fetch_response(parts, max_bytes)
        raise AssertionError(f"bench broker: unsupported api {api_key}")


def _broker_child(pipe, topic, partitions, windows, R, n_templates,
                  vmin, vmax, compression, tombstone_every,
                  brokers) -> None:
    """Subprocess entry: build templates, serve, report the port, block.

    The broker must live in its own process — in-process serving steals
    GIL time from the client under test and the measurement stops being
    a client-side number."""
    templates = build_templates(
        R, n_templates, vmin, vmax,
        compression=compression, tombstone_every=tombstone_every,
    )
    broker = TemplateBroker(
        topic, partitions, windows, templates, R, brokers=brokers
    )
    broker.start()
    pipe.send(broker.port)
    pipe.recv()  # parent says stop (or EOFError on parent death)


class BrokerProcess:
    """TemplateBroker in a child process; context manager yields the port."""

    def __init__(self, **kw):
        self._kw = kw

    def __enter__(self) -> int:
        import multiprocessing as mp

        ctx = mp.get_context("spawn")
        self._parent, child = ctx.Pipe()
        self._proc = ctx.Process(
            target=_broker_child,
            args=(
                child,
                self._kw["topic"], self._kw["partitions"], self._kw["windows"],
                self._kw["R"], self._kw["n_templates"], self._kw["vmin"],
                self._kw["vmax"], self._kw["compression"],
                self._kw.get("tombstone_every", 0),
                self._kw.get("brokers", 1),
            ),
            daemon=True,
        )
        self._proc.start()
        if not self._parent.poll(120):
            self._proc.terminate()
            raise RuntimeError("bench broker failed to start within 120s")
        return self._parent.recv()

    def __exit__(self, *exc) -> None:
        try:
            self._parent.send("stop")
        except OSError:
            pass
        self._proc.join(5)
        if self._proc.is_alive():
            self._proc.terminate()


def run(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--partitions", type=int, default=16)
    ap.add_argument("--records", type=int, default=50_000_000,
                    help="total logical records served across partitions")
    ap.add_argument("--batch-size", type=int, default=1 << 20)
    ap.add_argument("--records-per-batch", type=int, default=4096,
                    help="records per wire RecordBatch (template size)")
    ap.add_argument("--templates", type=int, default=16,
                    help="distinct templates (keys = templates x "
                         "records-per-batch)")
    ap.add_argument("--features", default="counters",
                    help="comma set: counters,alive,hll,quantiles "
                         "(default matches the reference's headline scan)")
    ap.add_argument("--backend", default="tpu", choices=["cpu", "tpu"])
    ap.add_argument("--vmin", type=int, default=100)
    ap.add_argument("--vmax", type=int, default=420)
    ap.add_argument("--compression", default="none",
                    choices=["none", "gzip", "snappy", "lz4", "zstd"])
    ap.add_argument("--tombstone-every", type=int, default=0,
                    help="make every Nth template record a tombstone "
                         "(0 = none)")
    ap.add_argument("--brokers", type=int, default=1,
                    help="advertised broker nodes (partition p led by "
                         "p %% N) — exercises leader-parallel fetching")
    ap.add_argument("--alive-bits", type=int, default=26)
    ap.add_argument("--wire-format", choices=["v4", "v5"], default="v5",
                    help="Packed wire format referee (BENCH round 11): v5 "
                         "combiner rows vs v4 per-record columns")
    ap.add_argument("--alive-compaction", choices=["auto", "off"],
                    default="auto",
                    help="alive-pair compaction referee (BENCH round 13): "
                         "'auto' = one bounded per-dispatch pair table, "
                         "'off' = per-row pair sections + in-scan scatter")
    ap.add_argument("--superbatch", default="1", metavar="K|auto",
                    help="stack K packed batches per jitted scan dispatch "
                         "(tpu backend; 'auto' targets 2^20 records per "
                         "dispatch)")
    ap.add_argument("--dispatch-depth", type=int, default=2,
                    help="superbatches allowed in flight while the device "
                         "folds (default 2)")
    ap.add_argument("--ingest-workers", default="1", metavar="N|auto",
                    help="partition-sharded parallel ingest workers for "
                         "the scan (engine --ingest-workers; composes "
                         "with --mesh: per-controller fan-in per data row)")
    ap.add_argument("--mesh", default="1", metavar="DATA[,SPACE]",
                    help="device mesh for the sharded backend (tpu only). "
                         "On a CPU-platform bench this forces the needed "
                         "virtual device count when jax is not yet "
                         "imported — the mesh x workers sweep referee")
    ap.add_argument("--flight-record", action="store_true",
                    help="run the pipeline flight recorder during the "
                         "scan and print the doctor's BOTTLENECK verdict "
                         "— the shipped replacement for the manual "
                         "BENCH_NOTES ledger procedure. Also the overhead "
                         "referee: an A/B against a run without this flag "
                         "must stay within 2%% (DESIGN.md §17)")
    ap.add_argument("--service-obs", metavar="DIR",
                    help="run the FULL service-observability stack during "
                         "the scan: flight recorder + the disk-backed "
                         "telemetry history persisted under DIR + "
                         "alert-engine evaluation at heartbeat cadence "
                         "(DESIGN.md §22). The BENCH round 15 overhead "
                         "referee: an A/B against a plain run must stay "
                         "within the same 2%% bar as --flight-record "
                         "alone")
    ap.add_argument("--quiet", action="store_true")
    args = ap.parse_args(argv)

    from kafka_topic_analyzer_tpu.cli import parse_mesh

    mesh_shape = parse_mesh(args.mesh)
    if mesh_shape != (1, 1):
        if args.backend != "tpu":
            ap.error("--mesh requires --backend tpu")
        # Virtual-device bring-up must precede the first jax import; when
        # the bench runner already imported jax this is a no-op and the
        # mesh constructor will reject a too-small device count itself.
        import os as _os

        need = mesh_shape[0] * mesh_shape[1]
        flags = _os.environ.get("XLA_FLAGS", "")
        if (
            "jax" not in sys.modules
            and "xla_force_host_platform_device_count" not in flags
            and _os.environ.get("JAX_PLATFORMS", "") == "cpu"
        ):
            _os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={need}"
            ).strip()

    feats = {f.strip() for f in args.features.split(",") if f.strip()}
    R = args.records_per_batch
    windows = max(1, args.records // (args.partitions * R))
    total = windows * R * args.partitions

    comp = {
        "none": kc.COMPRESSION_NONE,
        "gzip": kc.COMPRESSION_GZIP,
        "snappy": kc.COMPRESSION_SNAPPY,
        "lz4": kc.COMPRESSION_LZ4,
        "zstd": kc.COMPRESSION_ZSTD,
    }[args.compression]

    from kafka_topic_analyzer_tpu.backends.base import make_backend
    from kafka_topic_analyzer_tpu.config import AnalyzerConfig
    from kafka_topic_analyzer_tpu.engine import run_scan
    from kafka_topic_analyzer_tpu.io.kafka_wire import KafkaWireSource
    from kafka_topic_analyzer_tpu.utils.progress import Spinner

    config = AnalyzerConfig(
        num_partitions=args.partitions,
        batch_size=args.batch_size,
        count_alive_keys="alive" in feats,
        alive_bitmap_bits=args.alive_bits,
        enable_hll="hll" in feats,
        enable_quantiles="quantiles" in feats,
        mesh_shape=mesh_shape,
        wire_format={"v4": 4, "v5": 5}[args.wire_format],
        alive_compaction=args.alive_compaction,
    )
    degraded = False
    if args.backend == "tpu":
        from kafka_topic_analyzer_tpu.jax_support import (
            detect_cpu_fallback,
            ensure_responsive_accelerator,
        )

        degraded = not ensure_responsive_accelerator() or detect_cpu_fallback()
    # Same validation as the CLI (cli.resolve_dispatch): an explicit
    # --superbatch K>1 on the cpu backend is rejected, never silently
    # dropped — a published bench number must not claim a dispatch
    # configuration that never ran.
    from kafka_topic_analyzer_tpu.cli import (
        resolve_dispatch,
        resolve_ingest_workers,
    )

    try:
        dispatch = resolve_dispatch(args)
        ingest_workers = resolve_ingest_workers(
            args, mesh_shape, args.partitions
        )
    except ValueError as e:
        ap.error(str(e))
    if mesh_shape != (1, 1):
        from kafka_topic_analyzer_tpu.parallel.sharded import (
            ShardedTpuBackend,
        )

        backend = ShardedTpuBackend(config, dispatch=dispatch)
    else:
        backend = make_backend(args.backend, config, dispatch=dispatch)

    with BrokerProcess(
        topic="bench-e2e", partitions=args.partitions, windows=windows,
        R=R, n_templates=args.templates, vmin=args.vmin, vmax=args.vmax,
        compression=comp, tombstone_every=args.tombstone_every,
        brokers=args.brokers,
    ) as port:
        source = KafkaWireSource(f"127.0.0.1:{port}", "bench-e2e")
        recorder = None
        store = None
        if args.flight_record or args.service_obs:
            from kafka_topic_analyzer_tpu.obs import flight as obs_flight

            recorder = obs_flight.FlightRecorder()
            if args.service_obs:
                from kafka_topic_analyzer_tpu.obs import (
                    health as obs_health,
                    history as obs_history,
                )

                store = obs_history.HistoryStore(args.service_obs)
                recorder.attach_history(store)
                obs_history.set_active(store)
                obs_health.set_active(obs_health.HealthEngine())
            obs_flight.set_active(recorder)
            recorder.start()
        try:
            t0 = time.perf_counter()
            result = run_scan(
                "bench-e2e",
                source,
                backend,
                batch_size=args.batch_size,
                spinner=Spinner(enabled=False),
                ingest_workers=ingest_workers,
            )
            if hasattr(backend, "block_until_ready"):
                backend.block_until_ready()
            elapsed = time.perf_counter() - t0
        finally:
            # A failing scan (or the count-mismatch early return below)
            # must not leak a live sampler thread as the process-wide
            # active recorder; the stopped series stays readable.
            if recorder is not None:
                recorder.stop()
                obs_flight.set_active(None)
            if store is not None:
                from kafka_topic_analyzer_tpu.obs import (
                    health as obs_health,
                    history as obs_history,
                )

                store.close()
                obs_history.set_active(None)
                obs_health.set_active(None)
        source.close()

    got = int(result.metrics.overall_count)
    if got != total:
        print(
            f"bench-e2e: scanned {got} records, expected {total}",
            file=sys.stderr,
        )
        return 1
    value = total / elapsed
    diagnosis = None
    if recorder is not None:
        from kafka_topic_analyzer_tpu.obs import doctor

        diagnosis = doctor.diagnose(
            result.telemetry,
            controllers=max(1, len(result.ingest_workers_per_controller)),
            dispatch_depth=result.dispatch_depth,
            flight=recorder.series(),
        )
    if not args.quiet:
        print(
            f"# e2e: {total} records, {args.partitions} partitions, "
            f"{elapsed:.2f}s, backend={args.backend}, "
            f"features={sorted(feats)}, compression={args.compression}",
            file=sys.stderr,
        )
        print(result.profile.summary(), file=sys.stderr)
        if diagnosis is not None:
            from kafka_topic_analyzer_tpu.report import render_bottleneck

            sys.stderr.write(render_bottleneck(diagnosis))
    doc = {
        "metric": "e2e_msgs_per_sec",
        "value": round(value),
        "unit": "msgs/s",
        "vs_baseline": round(value / BASELINE_MSGS_PER_SEC, 2),
        "superbatch_k": result.superbatch_k,
        "dispatch_depth": result.dispatch_depth,
        "ingest_workers": result.ingest_workers,
        "ingest_workers_per_controller": result.ingest_workers_per_controller,
        "mesh": list(mesh_shape),
        "batch_size": args.batch_size,
    }
    if diagnosis is not None:
        doc["flight"] = {
            "verdict": diagnosis.verdict,
            "stages": {
                k: round(v, 4) for k, v in diagnosis.stages.items()
            },
            "window_share": {
                k: round(v, 4)
                for k, v in diagnosis.window_share.items()
            },
        }
    if degraded:
        # Same honesty rule as bench.py; --backend cpu runs are deliberate
        # host pipeline measurements and keep their ratio.
        from kafka_topic_analyzer_tpu.jax_support import mark_degraded

        mark_degraded(doc)
    print(json.dumps(doc))
    return 0


if __name__ == "__main__":
    sys.exit(run())
