"""Shared hardware measurement protocol.

One implementation of the two measurements both bench.py (the driver's
headline JSON line) and tools/bench_hw.py (the staged campaign) report, so
the protocols cannot drift: host->device transfer bandwidth, and the
analyzer-step rate with donated state (streamed or device-resident).
"""

from __future__ import annotations

import time
from typing import Optional, Sequence


def measure_transfer_gbps(dev=None, mib_sizes: Sequence[int] = (8,)) -> dict:
    """Time one synchronous device_put per size; returns {MiB: GB/s}."""
    import jax
    import numpy as np

    # Untimed warmup put: the process's first transfer pays one-time
    # allocator/stream setup, which would otherwise deflate the first
    # size's figure.
    jax.device_put(np.ones((1 << 16,), np.uint8), dev).block_until_ready()
    out = {}
    for mib in mib_sizes:
        host = np.ones((mib << 20,), np.uint8)
        t0 = time.perf_counter()
        d = jax.device_put(host, dev)
        d.block_until_ready()
        out[mib] = round(mib / 1024 / (time.perf_counter() - t0), 4)
        del d
    return out


#: The size every reporter uses for its comparable `transfer_gbps` figure.
HEADLINE_TRANSFER_MIB = 8


def headline_transfer_gbps(dev=None) -> float:
    """The single-put bandwidth figure reported as `transfer_gbps` by both
    bench.py and tools/bench_hw.py — one policy, one key, comparable
    across reports."""
    return measure_transfer_gbps(dev, (HEADLINE_TRANSFER_MIB,))[
        HEADLINE_TRANSFER_MIB
    ]


def timed_step_loop(
    config,
    feed,
    *,
    steps: int,
    device_resident: bool,
    dev=None,
    state=None,
) -> dict:
    """Warmup-compile the packed analyzer step, then time `steps` steps
    with donated state, cycling `feed` (packed uint8 buffers — device
    arrays when ``device_resident`` else host arrays put each step).

    Returns {"msgs_per_sec", "compile_s", "state"} — rate uses
    config.batch_size records per step.
    """
    import jax

    from kafka_topic_analyzer_tpu.backends.tpu import make_packed_step
    from kafka_topic_analyzer_tpu.models.state import AnalyzerState

    if state is None:
        state = AnalyzerState.init(config)
    step = jax.jit(make_packed_step(config), donate_argnums=(0,))

    def put(buf):
        return buf if device_resident else jax.device_put(buf, dev)

    # Compacted alive configs take a pair-table buffer per step.  The
    # feed is already-packed rows (no decoded batch to dedupe), so the
    # loop ships identity (empty) tables — the device cost is shape-
    # static under jit, so the timed rate still includes the full
    # per-dispatch pair-apply work.
    pair_feed = None
    if getattr(config, "compact_alive", False):
        from kafka_topic_analyzer_tpu.packing import (
            pack_pair_table,
            pair_table_capacity,
        )

        cap = pair_table_capacity(config, config.batch_size, 1)
        # ONE shared buffer: the step never donates it, and a mask-form
        # table can be tens of MB — duplicating it per feed entry would
        # just pin device memory for identical bytes.
        pair_feed = jax.device_put(pack_pair_table([], config, cap)[0], dev)

    def run(i, st):
        buf = put(feed[i % len(feed)])
        if pair_feed is not None:
            return step(st, buf, pair_feed)
        return step(st, buf)

    t0 = time.perf_counter()
    state = run(0, state)
    jax.block_until_ready(state)
    compile_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    for i in range(steps):
        state = run(i, state)
    jax.block_until_ready(state)
    dt = time.perf_counter() - t0
    return {
        "msgs_per_sec": round(steps * config.batch_size / dt, 1),
        "compile_s": round(compile_s, 2),
        "state": state,
    }
