"""Serving-plane referee (ISSUE 20; DESIGN §26): what does a fleet of
dashboards cost, and does the scan notice them?

Four arms, one JSON:

1. ``poll_naive`` — N pollers at ``--hz`` for ``--seconds``, no
   ``If-None-Match``, no ``Accept-Encoding``: every poll pays the full
   identity body.  This is what round 13's read path charged every
   poller, every second.
2. ``poll_conditional`` — the SAME poller fleet using the round-17
   contract (ETag revalidation + gzip): a poll costs zero body bytes
   until the report actually changes, then one gzip body.  The
   bytes-on-wire ratio between the two arms is the tentpole's headline.
3. ``scan_bare`` — a follow scan over a loopback FakeBroker with NO
   serving plane: the interference referee's denominator.
4. ``scan_loaded`` — the same scan with the WHOLE plane on (exporter +
   SSE publisher + conditional poller fleet + SSE subscribers), p50/p99
   of ``/report.json`` measured WHILE the scan folds.

Bars (recorded met-or-missed in the JSON, never silently):
  - conditional+gzip cuts bytes-on-wire >= 10x vs naive polling;
  - p99 /report.json <= 50 ms under the loaded scan;
  - scan wall-clock interference <= 5%.

Box caveat: on a 1-core container the poller fleet, the HTTP server
threads, the broker child, and the fold all share the core — poller
throughput UNDERSTATES a real host and interference OVERSTATES it.  The
JSON records achieved rates so the window is honest about what it ran.
"""

from __future__ import annotations

import argparse
import gzip as _gzip
import http.client
import json
import os
import sys
import threading
import time


def _import_fake_broker():
    """tests/fake_broker.py is the referee's loopback cluster (same one
    the tier-1 identity tests use); it ships in the repo, not the
    package."""
    tests_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "tests",
    )
    if os.path.isdir(tests_dir) and tests_dir not in sys.path:
        sys.path.insert(0, tests_dir)
    from fake_broker import FakeBroker  # type: ignore
    return FakeBroker


# ---------------------------------------------------------------------------
# a realistic report body


def make_report_doc(topics: int = 24) -> dict:
    """A fleet-rollup-shaped document: per-topic metric blocks at the
    field fan-out a real scan publishes (sizes match a ~24-topic fleet;
    the JSON records the exact byte count actually served)."""
    def topic_block(i: int) -> dict:
        return {
            "topic": f"fleet.topic.{i:03d}",
            "status": "ok",
            "passes": 3 + i % 5,
            "metrics": {
                "count": 1_000_000 + i * 7919,
                "tombstones": 12_345 + i,
                "alive_keys": 404_040 + i * 31,
                "key_cardinality_hll": 398_872 + i * 29,
                "largest_message": 1_048_576 - i,
                "earliest_ts": 1_600_000_000_000 + i,
                "latest_ts": 1_700_000_000_000 + i,
                "key_size": {"p50": 18, "p90": 42, "p99": 64, "sum": 18_000_000 + i},
                "value_size": {"p50": 256, "p90": 1024, "p99": 4096, "sum": 256_000_000 + i},
                "partitions": {
                    str(p): {
                        "count": 62_500 + p * 13 + i,
                        "start_offset": 0,
                        "end_offset": 62_500 + p * 13 + i,
                        "tombstones": 771 + p,
                        "alive_keys": 25_252 + p,
                    }
                    for p in range(16)
                },
            },
        }
    return {
        "mode": "fleet-rollup",
        "instance": "bench",
        "topics": {b["topic"]: b for b in map(topic_block, range(topics))},
        "degraded": [],
        "corrupt": [],
    }


# ---------------------------------------------------------------------------
# the poller fleet


class Poller(threading.Thread):
    """One dashboard: a persistent keep-alive connection polling
    /report.json at ``hz``, optionally with the conditional+gzip
    contract.  Falls behind rather than bursting — missed ticks are
    counted, not replayed (a real 1 Hz dashboard drops frames too)."""

    def __init__(self, port: int, hz: float, t_end: float,
                 conditional: bool, phase: float):
        super().__init__(daemon=True)
        self.port = port
        self.hz = hz
        self.t_end = t_end
        self.conditional = conditional
        self.phase = phase
        self.lat_ms: "list[float]" = []
        self.body_bytes = 0
        self.polls = 0
        self.not_modified = 0
        self.gzip_bodies = 0
        self.errors = 0
        self.missed_ticks = 0

    def run(self) -> None:
        conn = http.client.HTTPConnection("127.0.0.1", self.port, timeout=15)
        etag = None
        period = 1.0 / self.hz
        next_tick = time.monotonic() + self.phase
        while True:
            now = time.monotonic()
            if now >= self.t_end:
                break
            if now < next_tick:
                time.sleep(min(next_tick - now, self.t_end - now))
                continue
            behind = int((now - next_tick) / period)
            if behind > 0:
                self.missed_ticks += behind
            next_tick += period * (behind + 1)
            hdrs = {}
            if self.conditional:
                hdrs["Accept-Encoding"] = "gzip"
                if etag:
                    hdrs["If-None-Match"] = etag
            t0 = time.perf_counter()
            try:
                conn.request("GET", "/report.json", headers=hdrs)
                resp = conn.getresponse()
                body = resp.read()
            except (OSError, http.client.HTTPException):
                self.errors += 1
                conn.close()
                conn = http.client.HTTPConnection(
                    "127.0.0.1", self.port, timeout=15)
                continue
            self.lat_ms.append((time.perf_counter() - t0) * 1e3)
            self.polls += 1
            self.body_bytes += len(body)
            if resp.status == 200:
                etag = resp.headers.get("ETag")
                if resp.headers.get("Content-Encoding") == "gzip":
                    self.gzip_bodies += 1
            elif resp.status == 304:
                self.not_modified += 1
            elif resp.status not in (404, 503):
                self.errors += 1
        conn.close()


class SseListener(threading.Thread):
    """One push client: counts frames until the deadline."""

    def __init__(self, port: int, t_end: float):
        super().__init__(daemon=True)
        self.port = port
        self.t_end = t_end
        self.frames = 0

    def run(self) -> None:
        conn = http.client.HTTPConnection("127.0.0.1", self.port, timeout=20)
        try:
            conn.request("GET", "/events")
            resp = conn.getresponse()
            while time.monotonic() < self.t_end:
                line = resp.fp.readline()
                if not line:
                    break
                if line.startswith(b"event:"):
                    self.frames += 1
        except (OSError, http.client.HTTPException):
            pass
        finally:
            conn.close()


def _pct(sorted_ms: "list[float]", q: float) -> float:
    if not sorted_ms:
        return 0.0
    return sorted_ms[min(len(sorted_ms) - 1, int(len(sorted_ms) * q))]


def run_fleet(port: int, n: int, hz: float, seconds: float,
              conditional: bool, sse: int = 0) -> dict:
    t_end = time.monotonic() + seconds
    pollers = [
        Poller(port, hz, t_end, conditional, phase=(i / n) / hz)
        for i in range(n)
    ]
    listeners = [SseListener(port, t_end) for _ in range(sse)]
    for t in pollers + listeners:
        t.start()
    for t in pollers:
        t.join(seconds + 30)
    lat = sorted(x for p in pollers for x in p.lat_ms)
    polls = sum(p.polls for p in pollers)
    out = {
        "pollers": n,
        "hz": hz,
        "seconds": seconds,
        "conditional_gzip": conditional,
        "polls": polls,
        "achieved_hz_per_poller": round(polls / max(seconds, 1e-9) / n, 3),
        "missed_ticks": sum(p.missed_ticks for p in pollers),
        "errors": sum(p.errors for p in pollers),
        "not_modified": sum(p.not_modified for p in pollers),
        "gzip_bodies": sum(p.gzip_bodies for p in pollers),
        "body_bytes_total": sum(p.body_bytes for p in pollers),
        "bytes_per_poll": round(
            sum(p.body_bytes for p in pollers) / max(polls, 1), 1),
        "lat_p50_ms": round(_pct(lat, 0.50), 2),
        "lat_p99_ms": round(_pct(lat, 0.99), 2),
        "lat_max_ms": round(_pct(lat, 1.0), 2),
    }
    if sse:
        out["sse_listeners"] = sse
        out["sse_frames"] = sum(ls.frames for ls in listeners)
    return out


# ---------------------------------------------------------------------------
# arm 1+2: the byte-cut referee (static publisher, republish cadence)


def bench_poll(n: int, hz: float, seconds: float, republish_s: float) -> dict:
    from kafka_topic_analyzer_tpu.obs.exporters import PrometheusExporter
    from kafka_topic_analyzer_tpu.obs.registry import default_registry
    from kafka_topic_analyzer_tpu.serve import push as serve_push
    from kafka_topic_analyzer_tpu.serve import state as serve_state
    from kafka_topic_analyzer_tpu.serve.push import SsePublisher
    from kafka_topic_analyzer_tpu.serve.state import ServiceState

    doc = make_report_doc()
    raw = json.dumps(doc).encode()
    arms = {}
    for conditional in (False, True):
        default_registry().reset()
        svc = ServiceState()
        serve_state.set_active(svc)
        pub = SsePublisher().start()
        serve_push.set_active(pub)
        svc.publish(dict(doc), summary={"records": 1})
        exporter = PrometheusExporter(0)
        stop = threading.Event()

        def republisher():
            i = 2
            while not stop.wait(republish_s):
                d = dict(doc)
                d["pass"] = i  # content actually changes each publish
                svc.publish(d, summary={"records": i})
                i += 1

        rt = threading.Thread(target=republisher, daemon=True)
        rt.start()
        try:
            arms["conditional" if conditional else "naive"] = run_fleet(
                exporter.port, n, hz, seconds, conditional)
        finally:
            stop.set()
            rt.join(5)
            pub.stop()
            exporter.close()
            serve_push.set_active(None)
            serve_state.set_active(None)
    naive, cond = arms["naive"], arms["conditional"]
    ratio = (
        naive["bytes_per_poll"] / cond["bytes_per_poll"]
        if cond["bytes_per_poll"] else float("inf")
    )
    return {
        "report_identity_bytes": len(raw),
        "report_gzip_bytes": len(_gzip.compress(raw, 6)),
        "republish_every_s": republish_s,
        "naive": naive,
        "conditional": cond,
        "bytes_per_poll_cut": round(ratio, 1),
    }


# ---------------------------------------------------------------------------
# arm 3+4: the interference referee (real follow scan on FakeBroker)


def _mk_records(partition: int, n: int):
    return [
        (
            i,
            1_600_000_000_000 + i * 1000,
            f"k{partition}-{i % 997}".encode() if i % 5 else None,
            bytes(64 + (i % 129)) if i % 7 else None,
        )
        for i in range(n)
    ]


def _scan_once(records, serving: "dict | None") -> dict:
    """One follow scan to drain + idle-exit; returns wall seconds and
    (when serving) the fleet's client-side view measured DURING it."""
    from kafka_topic_analyzer_tpu.backends.tpu import TpuBackend
    from kafka_topic_analyzer_tpu.config import AnalyzerConfig, FollowConfig
    from kafka_topic_analyzer_tpu.io.kafka_wire import KafkaWireSource
    from kafka_topic_analyzer_tpu.obs.registry import default_registry
    from kafka_topic_analyzer_tpu.serve.follow import FollowService

    FakeBroker = _import_fake_broker()
    default_registry().reset()
    n_parts = len(records)
    cfg = AnalyzerConfig(
        num_partitions=n_parts, batch_size=256,
        count_alive_keys=True, alive_bitmap_bits=18,
        enable_hll=True, hll_p=12,
    )
    follow = FollowConfig(
        poll_interval_s=0.02, idle_backoff_max_s=0.05, idle_exit_s=0.5,
    )
    fleet_stats = None
    with FakeBroker("bench.serve", records, max_records_per_fetch=512) as b:
        src = KafkaWireSource(
            f"127.0.0.1:{b.port}", "bench.serve",
            overrides={"retry.backoff.ms": "5"},
        )
        svc = FollowService(
            "bench.serve", src,
            TpuBackend(cfg, init_now_s=10**10), 256, follow,
        )
        t0 = time.perf_counter()
        if serving is None:
            result = svc.run()
            wall = time.perf_counter() - t0
        else:
            fleet_box = {}

            def fleet():
                fleet_box["stats"] = run_fleet(
                    serving["port"], serving["pollers"], serving["hz"],
                    serving["seconds"], conditional=True,
                    sse=serving["sse"],
                )

            ft = threading.Thread(target=fleet, daemon=True)
            ft.start()
            result = svc.run()
            wall = time.perf_counter() - t0
            ft.join(serving["seconds"] + 60)
            fleet_stats = fleet_box.get("stats")
        src.close()
    count = result.metrics.to_dict(
        result.start_offsets, result.end_offsets
    )["overall"]["count"]
    out = {"wall_s": round(wall, 3), "records_folded": int(count)}
    if fleet_stats is not None:
        out["fleet"] = fleet_stats
    return out


def bench_scan(n_pollers: int, hz: float) -> dict:
    from kafka_topic_analyzer_tpu.obs.exporters import PrometheusExporter
    from kafka_topic_analyzer_tpu.serve import push as serve_push
    from kafka_topic_analyzer_tpu.serve.push import SsePublisher

    records = {p: _mk_records(p, 12000) for p in range(4)}
    # Best-of-3 bare: the interference denominator must not be a noisy
    # single sample on a shared core.
    bare_runs = [_scan_once(records, serving=None) for _ in range(3)]
    bare = min(bare_runs, key=lambda r: r["wall_s"])
    bare["wall_s_runs"] = [r["wall_s"] for r in bare_runs]
    # Size the poller window to the bare wall so the fleet hammers the
    # scan for its WHOLE duration (plus the drain tail).
    window = max(6.0, bare["wall_s"] * 1.5)

    pub = SsePublisher().start()
    serve_push.set_active(pub)
    exporter = PrometheusExporter(0)
    try:
        loaded = _scan_once(records, serving={
            "port": exporter.port, "pollers": n_pollers, "hz": hz,
            "seconds": window, "sse": 8,
        })
    finally:
        pub.stop()
        exporter.close()
        serve_push.set_active(None)
    assert loaded["records_folded"] == bare["records_folded"]
    interference = loaded["wall_s"] / bare["wall_s"] - 1.0
    return {
        "records": sum(len(r) for r in records.values()),
        "partitions": len(records),
        "bare": bare,
        "loaded": loaded,
        "interference_pct": round(interference * 100.0, 1),
    }


# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--pollers", type=int, default=1000,
                    help="concurrent dashboard connections (default 1000)")
    ap.add_argument("--hz", type=float, default=1.0,
                    help="poll rate per dashboard (default 1 Hz)")
    ap.add_argument("--seconds", type=float, default=12.0,
                    help="duration of each static poll arm")
    ap.add_argument("--republish", type=float, default=2.0,
                    help="report republish cadence in the poll arms")
    ap.add_argument("--scan-pollers", type=int, default=None,
                    help="poller count during the scan arms "
                         "(default: same as --pollers)")
    ap.add_argument("--out", default="BENCH_r17.json")
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    t0 = time.time()
    print(f"== poll arms: {args.pollers} pollers @ {args.hz} Hz, "
          f"{args.seconds}s each, republish every {args.republish}s",
          flush=True)
    poll = bench_poll(args.pollers, args.hz, args.seconds, args.republish)
    print(json.dumps({k: poll[k] for k in
                      ("report_identity_bytes", "bytes_per_poll_cut")}),
          flush=True)
    scan_pollers = args.scan_pollers or args.pollers
    print(f"== scan arms: follow scan bare vs {scan_pollers} "
          f"conditional pollers + 8 SSE listeners", flush=True)
    scan = bench_scan(scan_pollers, args.hz)
    print(json.dumps({"interference_pct": scan["interference_pct"],
                      "p99_ms": scan["loaded"]["fleet"]["lat_p99_ms"]
                      if scan["loaded"].get("fleet") else None}),
          flush=True)
    scan_moderate = None
    if scan_pollers > 100:
        # Attribution arm: the same referee at a fleet a shared core can
        # actually schedule — shows whether a miss above is the design
        # or the box.
        print("== scan arms (attribution): 100-poller fleet", flush=True)
        scan_moderate = bench_scan(100, args.hz)
        print(json.dumps(
            {"interference_pct": scan_moderate["interference_pct"],
             "p99_ms": scan_moderate["loaded"]["fleet"]["lat_p99_ms"]}),
            flush=True)

    bars = {
        "bytes_cut_10x": {
            "bar": ">= 10x bytes-per-poll cut, conditional+gzip vs naive",
            "measured": poll["bytes_per_poll_cut"],
            "met": poll["bytes_per_poll_cut"] >= 10.0,
        },
        "p99_under_scan_50ms": {
            "bar": "p99 /report.json <= 50 ms while the scan folds",
            "measured": (scan["loaded"].get("fleet") or {}).get("lat_p99_ms"),
            "met": bool(scan["loaded"].get("fleet"))
            and scan["loaded"]["fleet"]["lat_p99_ms"] <= 50.0,
        },
        "interference_5pct": {
            "bar": "scan wall-clock interference <= 5% with the plane on",
            "measured": scan["interference_pct"],
            "met": scan["interference_pct"] <= 5.0,
        },
    }
    doc = {
        "bench": "serve",
        "round": 17,
        "host": {"nproc": os.cpu_count(),
                 "note": "poller fleet, server threads, broker child and "
                         "fold share these cores; 1-core containers "
                         "understate throughput and overstate "
                         "interference"},
        "wall_s": round(time.time() - t0, 1),
        "poll": poll,
        "scan": scan,
        "scan_100_pollers": scan_moderate,
        "bars": bars,
    }
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=2)
        f.write("\n")
    print(f"wrote {args.out}")
    for name, b in bars.items():
        print(f"  {'MET ' if b['met'] else 'MISS'} {name}: "
              f"{b['measured']} ({b['bar']})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
