"""Terminal report renderer — byte-compatible with the reference.

Reproduces the report block of ``src/main.rs:123-179``: the global stats
lines, the optional alive-keys block, the legend, and the 15-column
per-partition prettytable.  New-capability lines (HLL distinct keys, size
quantiles) are appended *after* the reference-compatible block so the
reference surface stays byte-identical.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from kafka_topic_analyzer_tpu.results import TopicMetrics
from kafka_topic_analyzer_tpu.utils.table import render_table
from kafka_topic_analyzer_tpu.utils.timefmt import format_utc_seconds

#: Header row of the per-partition table (src/main.rs:150).
TABLE_HEADER = [
    "P", "< OS", "> OS", "Total", "Alive", "Tmb", "DR", "K Null", "K !Null",
    "P-Bytes", "K-Bytes", "V-Bytes", "A K-Sz", "A V-Sz", "A M-Sz",
]

LEGEND = (
    "| K = Key, V = Value, P = Partition, Tmb = Tombstone(s), Sz = Size\n"
    "| DR = Dirty Ratio, A = Average, Lst = last, < OS = start offset, > OS = end offset\n"
)


def render_report(
    topic: str,
    metrics: TopicMetrics,
    start_offsets: Dict[int, int],
    end_offsets: Dict[int, int],
    duration_secs: int,
    show_alive_keys: bool = False,
    show_extensions: bool = True,
) -> str:
    """Render the full post-scan report (src/main.rs:123-179)."""
    eq = "=" * 120
    dash = "-" * 120
    out: List[str] = []
    out.append("")
    out.append(eq)
    out.append("Calculating statistics...")
    out.append(f"Topic {topic}")
    out.append(f"Scanning took: {duration_secs} seconds")
    # Integer division, denominator clamped to >= 1 (src/main.rs:130).
    out.append(f"Estimated Msg/s: {metrics.overall_count // max(duration_secs, 1)}")
    out.append(dash)
    out.append(f"Earliest Message: {format_utc_seconds(metrics.earliest_ts_s)}")
    out.append(f"Latest Message: {format_utc_seconds(metrics.latest_ts_s)}")
    out.append(dash)
    out.append(f"Largest Message: {metrics.largest_message} bytes")
    out.append(f"Smallest Message: {metrics.smallest_message_reported()} bytes")
    out.append(f"Topic Size: {metrics.overall_size} bytes")
    if show_alive_keys and metrics.alive_keys is not None:
        out.append(dash)
        out.append(f"Alive keys: {metrics.alive_keys}")
        out.append(dash)
    out.append(eq)

    rows: List[List[str]] = [TABLE_HEADER]
    for p in metrics.partitions:
        rows.append([
            f"{p}",
            f"{start_offsets[p]}",
            f"{end_offsets[p]}",
            f"{metrics.total(p)}",
            f"{metrics.alive(p)}",
            f"{metrics.tombstones(p)}",
            f"{metrics.dirty_ratio(p):.4f}",
            f"{metrics.key_null(p)}",
            f"{metrics.key_non_null(p)}",
            f"{metrics.key_size_sum(p) + metrics.value_size_sum(p)}",
            f"{metrics.key_size_sum(p)}",
            f"{metrics.value_size_sum(p)}",
            f"{metrics.key_size_avg(p)}",
            f"{metrics.value_size_avg(p)}",
            f"{metrics.message_size_avg(p)}",
        ])

    body = "\n".join(out) + "\n"
    # Legend is printed *before* the table in the reference (src/main.rs:174-176).
    body += LEGEND
    body += render_table(rows)
    body += "\n" + eq + "\n"
    body += _render_extensions(metrics) if show_extensions else ""
    return body


def render_degraded_block(degraded: "Dict[int, str]") -> str:
    """Post-table warning block for partitions dropped mid-scan after
    exhausting their transport retry budget.  Rendered OUTSIDE the
    reference-compatible report (which stays byte-identical for clean
    scans): their table rows undercount, so the reader must see why."""
    if not degraded:
        return ""
    bang = "!" * 120
    lines = [bang, f"WARNING: {len(degraded)} partition(s) DEGRADED — "
                   "metrics below undercount their unscanned tails"]
    for p in sorted(degraded):
        lines.append(f"  partition {p}: {degraded[p]}")
    lines.append(
        "Rerun with --resume (snapshot written) once the cluster recovers."
    )
    lines.append(bang)
    return "\n".join(lines) + "\n"


def render_corrupt_block(corrupt: "Dict[int, dict]") -> str:
    """Post-table block for poisoned frames skipped/quarantined under
    --on-corruption.  Like the degraded block, rendered OUTSIDE the
    reference-compatible report: the scan COMPLETED, but the metrics
    exclude exactly the unreadable frames' records, and the reader must
    see what was lost and where the evidence went."""
    if not corrupt:
        return ""
    bar = "%" * 120
    frames = sum(d.get("frames", 0) for d in corrupt.values())
    quarantined = sum(d.get("quarantined", 0) for d in corrupt.values())
    lines = [
        bar,
        f"CORRUPT: {frames} unreadable frame(s) across "
        f"{len(corrupt)} partition(s) — skipped; metrics exclude exactly "
        "their records",
    ]
    for p in sorted(corrupt):
        d = corrupt[p]
        kinds = ", ".join(
            f"{k} x{n}" for k, n in sorted(d.get("kinds", {}).items())
        )
        where = f"partition {p}" if p >= 0 else "another process"
        lines.append(
            f"  {where}: {d.get('frames', 0)} frame(s), "
            f"{d.get('records', 0)} record(s), {d.get('bytes', 0)} bytes"
            + (f" [{kinds}]" if kinds else "")
            + (" — quarantined" if d.get("quarantined") else "")
        )
    if quarantined:
        lines.append(
            "Raw frames + JSON sidecars are spooled in --quarantine-dir."
        )
    else:
        lines.append(
            "Rerun with --on-corruption=quarantine --quarantine-dir to "
            "preserve the raw frames."
        )
    lines.append(bar)
    return "\n".join(lines) + "\n"


def render_data_loss_block(lost: "Dict[int, dict]") -> str:
    """Post-table block for offset ranges the log mutated out from under
    the scan (retention races, truncation after unclean election,
    resume-below-log-start).  Like the corrupt block, rendered OUTSIDE
    the reference-compatible report: the metrics describe exactly the
    surviving records, and the reader must see what the log took back —
    a truncation additionally marks the partition's fold
    non-authoritative (records already folded were replaced under the
    scan)."""
    if not lost:
        return ""
    bar = "#" * 120
    records = sum(d.get("records", 0) for d in lost.values())
    lines = [
        bar,
        f"DATA-LOSS: {records} record(s) across {len(lost)} partition(s) "
        "mutated out from under the scan — metrics cover exactly the "
        "surviving records",
    ]
    for p in sorted(lost):
        d = lost[p]
        reasons = ", ".join(
            f"{k} x{n}" for k, n in sorted(d.get("reasons", {}).items())
        )
        where = f"partition {p}" if p >= 0 else "another process"
        spans = ", ".join(
            f"[{s['start']}, {s['end']})" for s in d.get("spans", [])
        )
        lines.append(
            f"  {where}: {d.get('records', 0)} record(s) in "
            f"{d.get('ranges', 0)} range(s)"
            + (f" [{reasons}]" if reasons else "")
            + (f" at {spans}" if spans else "")
            + (
                ""
                if d.get("authoritative", True)
                else " — FOLD NON-AUTHORITATIVE (truncation replaced "
                     "already-counted records)"
            )
        )
    lines.append(bar)
    return "\n".join(lines) + "\n"


def _metric_total(snapshot: Dict, name: str) -> float:
    """Sum of a metric's sample values across label sets (0 if absent)."""
    metric = snapshot.get(name)
    if metric is None:
        return 0.0
    return sum(s.get("value", 0.0) for s in metric["samples"])


def _worker_sort_key(label: str):
    """Natural sort for worker labels: plain ints on single-controller
    scans ('0', '1', ...), controller-prefixed under multi-controller
    ('c0.0', 'c1.2', ...) — numeric runs compare numerically either way."""
    import re

    return [
        int(tok) if tok.isdigit() else tok
        for tok in re.split(r"(\d+)", label)
    ]


def render_stage_stats(snapshot: Optional[Dict]) -> str:
    """``--stats`` per-stage digest from the registry snapshot — the SAME
    source the scan doctor attributes from (results.StageDigest), so the
    stage timings a human reads and the verdict's inputs can never drift.
    Replaces the old in-process ``ScanProfile.summary()`` print: under
    multi-controller these are fleet totals from the gathered merge."""
    from kafka_topic_analyzer_tpu.results import StageDigest

    digest = StageDigest.from_telemetry(snapshot)
    if not digest.stages:
        return ""
    lines = ["scan stages:"]
    for name, (secs, items, nbytes) in digest.stages.items():
        line = f"  {name}: {secs:.3f}s, {items} records"
        if items and secs > 0:
            line += f" ({items / secs:,.0f}/s)"
        if nbytes:
            line += f", {nbytes / 1e6:,.1f} MB"
            if secs > 0:
                line += f" ({nbytes / secs / 1e6:,.1f} MB/s)"
        lines.append(line)
    return "\n".join(lines) + "\n"


def render_bottleneck(diagnosis) -> str:
    """``--stats`` BOTTLENECK digest from an obs.doctor.Diagnosis: the
    ranked verdict, the per-stage occupancy it was computed from, the
    queue-theory evidence, and (when a flight recorder ran) the windowed
    verdict timeline — the shipped replacement for the hand-built
    BENCH_NOTES ledger procedure."""
    if diagnosis is None:
        return ""
    pct = lambda v: f"{v * 100.0:.0f}%"  # noqa: E731
    lines = [f"BOTTLENECK: {diagnosis.verdict} — {diagnosis.rationale}"]
    if diagnosis.stages:
        lines.append(
            "  occupancy: "
            + " | ".join(
                f"{s} {pct(v)}" for s, v in diagnosis.stages.items()
            )
        )
    if diagnosis.evidence:
        lines.append(
            "  evidence: "
            + " | ".join(
                f"{k.replace('_', '-')} {pct(v)}"
                for k, v in sorted(diagnosis.evidence.items())
            )
        )
    if diagnosis.window_share:
        lines.append(
            "  windows: "
            + " | ".join(
                f"{v} {pct(share)}"
                for v, share in sorted(
                    diagnosis.window_share.items(),
                    key=lambda kv: -kv[1],
                )
            )
        )
    return "\n".join(lines) + "\n"


def render_health(doc: "Optional[Dict]") -> str:
    """``--stats`` HEALTH digest from an alert-engine document
    (obs/health.HealthEngine.doc()): the verdict line plus one line per
    ACTIVE alert with its evidence — the same document /healthz serves,
    rendered once, so the operator's terminal and the liveness probe can
    never disagree."""
    if not doc:
        return ""
    firing = doc.get("firing") or []
    if not firing:
        return (
            f"HEALTH: ok ({doc.get('evaluations', 0)} evaluations, "
            "no active alerts)\n"
        )
    lines = [
        f"HEALTH: {len(firing)} active alert(s) "
        f"({doc.get('evaluations', 0)} evaluations)"
    ]
    for r in firing:
        where = f" [{r['topic']}]" if r.get("topic") else ""
        ev = r.get("evidence") or {}
        ev_text = ", ".join(f"{k}={v}" for k, v in sorted(ev.items()))
        lines.append(
            f"  {r['rule']}{where}: {r['state']} "
            f"{r.get('firing_s', 0) or 0:.0f}s — {r['summary']}"
            + (f" ({ev_text})" if ev_text else "")
        )
    return "\n".join(lines) + "\n"


def render_trends(findings: "Optional[List[dict]]") -> str:
    """``--stats`` TRENDS digest from the trend doctor's findings
    (obs/doctor.diagnose_trends over a history window) — empty string
    when the window is healthy or too short to judge."""
    if not findings:
        return ""
    lines = ["TRENDS:"]
    for f in findings:
        lines.append(f"  {f['summary']}")
    return "\n".join(lines) + "\n"


def render_telemetry_stats(
    snapshot: Optional[Dict],
    ingest_workers: int = 1,
    ingest_workers_per_controller: "Optional[List[int]]" = None,
    superbatch_k: int = 1,
    dispatch_depth: int = 1,
    wire=None,
) -> str:
    """``--stats`` telemetry section from a registry snapshot (cluster-wide
    under multi-controller: the engine merges every process's registry
    before this renders).  Counter-only digest — the full instrument set,
    including histograms and per-partition gauges, is what ``--metrics-port``
    serves and ``--json``'s ``telemetry`` block embeds."""
    if not snapshot:
        return ""
    t = lambda name: _metric_total(snapshot, name)  # noqa: E731
    lines = [
        "telemetry:",
        (
            f"  scan: {t('kta_scan_records_total'):,.0f} records, "
            f"{t('kta_scan_batches_total'):,.0f} batches, "
            f"{t('kta_scan_bytes_total') / 1e6:,.1f} MB"
        ),
        (
            f"  wire: {t('kta_fetch_requests_total'):,.0f} fetches "
            f"({t('kta_fetch_bytes_total') / 1e6:,.1f} MB), "
            f"{t('kta_fetch_errors_total'):,.0f} fetch errors, "
            f"{t('kta_metadata_reloads_total'):,.0f} metadata reloads"
        ),
        (
            f"  faults: {t('kta_transport_failures_total'):,.0f} transport "
            f"failures, {t('kta_connection_evictions_total'):,.0f} "
            f"evictions, {t('kta_backoff_sleeps_total'):,.0f} backoff "
            f"sleeps ({t('kta_backoff_sleep_seconds_total'):.2f}s), "
            f"{t('kta_retry_budget_exhaustions_total'):,.0f} budget "
            f"exhaustions"
        ),
        (
            f"  corruption: {t('kta_corrupt_frames_total'):,.0f} corrupt "
            f"frames ({t('kta_corrupt_records_total'):,.0f} records, "
            f"{t('kta_corrupt_bytes_total'):,.0f} B), "
            f"{t('kta_corrupt_quarantined_total'):,.0f} quarantined, "
            f"{t('kta_corrupt_refetches_total'):,.0f} disambiguation "
            f"re-fetches"
        ),
        (
            f"  state: {t('kta_snapshots_saved_total'):,.0f} snapshots "
            f"saved, {t('kta_scan_degraded_partitions'):,.0f} degraded "
            f"partitions"
        ),
    ]
    # Log-mutation digest: only rendered when the log actually moved (or
    # the fencing machinery fired) — stable-log scans keep the classic
    # digest byte-identical.
    from kafka_topic_analyzer_tpu.results import LossStats

    loss = LossStats.from_telemetry(snapshot)
    if loss.ranges or loss.fences or loss.divergence_checks \
            or loss.watermark_regressions:
        reasons = ", ".join(
            f"{k}={v:,}" for k, v in sorted(loss.by_reason.items())
        )
        lines.append(
            f"  log-mutation: {loss.records:,} records lost in "
            f"{loss.ranges:,} range(s)"
            + (f" ({reasons})" if reasons else "")
            + f", {loss.fences:,} epoch fences, "
            f"{loss.divergence_checks:,} divergence checks, "
            f"{loss.watermark_regressions:,} watermark regressions"
        )
    # Cold-path digest: what the segment catalog opened/mapped and how many
    # records came off the mapped chunks.  Only rendered when the scan
    # actually read segments (broker scans never touch these instruments).
    from kafka_topic_analyzer_tpu.results import SegmentStats

    seg = SegmentStats.from_telemetry(snapshot)
    if seg.files:
        lines.append(
            f"  segments: {seg.files:,} chunk(s) "
            f"({seg.bytes_mapped / 1e6:,.1f} MB mapped), "
            f"{seg.records:,.0f} records in {seg.batches:,.0f} batches"
        )
    # Remote-tier digest (io/objstore.py): what the object-store client
    # actually fetched, retried, and served from the local cache.  Only
    # rendered when the scan spoke to a remote store.
    if seg.gets:
        line = (
            f"  segstore: {seg.gets:,} GETs "
            f"({seg.bytes_fetched / 1e6:,.1f} MB fetched), "
            f"{seg.retries:,} retries"
        )
        if seg.cache_hits or seg.cache_misses or seg.cache_evictions:
            line += (
                f", cache {seg.cache_hits:,} hit(s) / "
                f"{seg.cache_misses:,} miss(es) / "
                f"{seg.cache_evictions:,} eviction(s)"
            )
        lines.append(line)
    # Packed wire-format digest (results.WireStats, engine-built): which
    # format the scan's device buffers used, the actual bytes/record, and
    # the fold-table vs per-record split — the v4→v5 combiner saving as a
    # measured number, not a layout inference.
    if wire is not None:
        lines.append(
            f"  wire-format: v{wire.format}, "
            f"{wire.bytes_total / 1e6:,.1f} MB packed "
            f"({wire.bytes_per_record:,.1f} B/record), buffer split "
            f"{wire.per_record_bytes:,} B per-record + "
            f"{wire.table_bytes:,} B fold-table per {wire.batch_size:,}"
            f"-record buffer"
        )
        # Alive-pair compaction line (DESIGN §19): the measured
        # raw→emitted dedupe of the per-dispatch pair tables, or — never
        # silently — why an alive-key scan ran uncompacted.
        if wire.alive_compaction == "on":
            lines.append(
                f"  alive-compaction: on — {wire.pairs_raw:,} raw pairs "
                f"→ {wire.pairs_emitted:,} emitted "
                f"(ratio {wire.compaction_ratio:.3f})"
            )
        elif wire.alive_compaction != "n/a":
            lines.append(f"  alive-compaction: {wire.alive_compaction}")
    # Fused ingest digest: rows/records through the one-pass native
    # decode→pack, and — never silently — everything that bypassed it,
    # by reason (compressed/legacy frames, salvage, missing shim).
    from kafka_topic_analyzer_tpu.results import FusedStats

    fused = FusedStats.from_telemetry(snapshot)
    if fused.rows or fused.fallbacks:
        line = (
            f"  fused: {fused.records:,.0f} records in {fused.rows:,} "
            f"row(s) via native decode→pack"
        )
        if fused.fallbacks:
            per = ", ".join(
                f"{r} {int(n):,}"
                for r, n in sorted(fused.fallbacks.items())
            )
            line += f" — fallbacks: {per}"
        lines.append(line)
    # Parallelism context for every throughput number above: worker count
    # always, the per-worker split when the scan actually ran parallel
    # (sequential scans never touch the per-worker instruments).
    from kafka_topic_analyzer_tpu.results import IngestStats

    ingest = IngestStats.from_telemetry(snapshot)
    per_ctrl = ingest_workers_per_controller or []
    if len(per_ctrl) > 1:
        # Multi-controller: the fleet total plus each controller's
        # resolved count (they differ when shard partition counts or
        # host core counts differ).
        line = (
            f"  ingest: {sum(per_ctrl)} worker(s) across "
            f"{len(per_ctrl)} controller(s) "
            f"({'+'.join(str(v) for v in per_ctrl)})"
        )
    else:
        line = f"  ingest: {ingest_workers} worker(s)"
    if ingest.workers:
        per = ", ".join(
            # Plain integer labels read better with a 'w' prefix;
            # controller-prefixed labels ("c0.3") already carry one.
            (f"w{w}" if w.isdigit() else w) + f" {n:,}" + (
                f" (stalled {ingest.stalls[w]:.1f}s)"
                if ingest.stalls.get(w, 0) >= 0.05 else ""
            )
            for w, n in sorted(
                ingest.workers.items(),
                key=lambda kv: _worker_sort_key(kv[0]),
            )
        )
        line += f" — records {per}"
    lines.append(line)
    # Dispatch amortization context (the superbatch layer): device
    # dispatches, batches per dispatch, and mean per-dispatch latency.
    # Only rendered when the scan actually ran superbatched — the
    # per-batch path never touches the dispatch instruments.
    from kafka_topic_analyzer_tpu.results import DispatchStats

    dispatch = DispatchStats.from_telemetry(snapshot)
    if dispatch.dispatches:
        lines.append(
            f"  dispatch: {dispatch.dispatches:,} superbatch dispatches "
            f"(K={superbatch_k}, depth={dispatch_depth}), "
            f"{dispatch.batches:,} batches folded, "
            f"{dispatch.mean_latency_ms:.1f} ms mean dispatch latency"
        )
    # Follow-service digest: polls/passes at the head plus the two
    # never-silent failure counters.  Only rendered for --follow runs —
    # batch scans never touch the follow instruments.
    from kafka_topic_analyzer_tpu.results import FollowStats

    follow = FollowStats.from_telemetry(snapshot)
    if follow.polls or follow.passes:
        lines.append(
            f"  follow: {follow.polls:,} watermark polls, "
            f"{follow.passes:,} fold passes, "
            f"{follow.report_snapshots:,} report snapshots published, "
            f"{follow.refresh_failures:,} refresh give-ups"
        )
    return "\n".join(lines) + "\n"


def attach_scan_digests(doc: dict, result, diagnosis=None) -> None:
    """The digest blocks every ``--json`` document carries (single-topic,
    multi-topic fan-in, and /report.json alike): ``segments`` when the
    scan read a segment store, ``wire`` for packed backends, ``flight``
    when a diagnosis was computed.  ONE implementation so the surfaces
    cannot drift field-by-field."""
    from kafka_topic_analyzer_tpu.results import SegmentStats

    seg = SegmentStats.from_telemetry(result.telemetry)
    if seg.files:
        doc["segments"] = seg.as_dict()
    if getattr(result, "wire", None) is not None:
        doc["wire"] = result.wire.as_dict()
    if diagnosis is not None:
        doc["flight"] = diagnosis.as_dict()


def attach_issue_blocks(doc: dict, result) -> None:
    """The str-keyed ``corrupt_partitions``/``degraded_partitions``/
    ``data_loss`` maps (shared by every --json surface and
    cli._scan_issue_exit)."""
    corrupt = getattr(result, "corrupt_partitions", None) or {}
    if corrupt:
        doc["corrupt_partitions"] = {str(p): d for p, d in corrupt.items()}
    if result.degraded_partitions:
        doc["degraded_partitions"] = {
            str(p): r for p, r in result.degraded_partitions.items()
        }
    lost = getattr(result, "lost_partitions", None) or {}
    if lost:
        doc["data_loss"] = {str(p): d for p, d in lost.items()}


def build_json_doc(
    topic: str,
    result,
    diagnosis=None,
    follow: "Optional[dict]" = None,
    windows: "Optional[dict]" = None,
    fleet: "Optional[dict]" = None,
    health: "Optional[dict]" = None,
) -> dict:
    """The machine-readable report document — ONE builder for every
    surface that emits it: the CLI's ``--json`` stdout, the follow
    service's poll-boundary publishes, the fleet service's per-topic
    publishes, and therefore the ``/report.json`` endpoint
    (serve/state.py) with and without ``?topic=``, which by construction
    can never drift from the CLI schema.  ``result`` is an
    `engine.ScanResult`; ``diagnosis`` the scan doctor's verdict
    (obs/doctor.diagnose_scan); ``follow``/``windows``/``fleet`` the
    service-layer blocks (absent for batch scans)."""
    doc = result.metrics.to_dict(result.start_offsets, result.end_offsets)
    doc["topic"] = topic
    doc["duration_secs"] = result.duration_secs
    doc["ingest_workers"] = result.ingest_workers
    doc["ingest_workers_per_controller"] = (
        result.ingest_workers_per_controller
    )
    doc["superbatch_k"] = result.superbatch_k
    doc["dispatch_depth"] = result.dispatch_depth
    doc["telemetry"] = result.telemetry
    attach_scan_digests(doc, result, diagnosis)
    if follow is not None:
        doc["follow"] = follow
    if windows is not None:
        doc["windows"] = windows
    if fleet is not None:
        doc["fleet"] = fleet
    if health is not None:
        doc["health"] = health
    attach_issue_blocks(doc, result)
    return doc


def render_fleet_status(rollup: dict) -> str:
    """The fleet status table + totals block from a rollup document
    (fleet/report.build_fleet_rollup) — what ``--fleet`` prints after the
    per-topic reports and what ``--stats`` sends to stderr.  One renderer
    over the same document /report.json serves, so the table an operator
    reads and the JSON a dashboard reads cannot disagree."""
    fleet = rollup.get("fleet") or {}
    statuses: Dict[str, dict] = fleet.get("statuses") or {}
    eq = "=" * 120
    lines: List[str] = [eq]
    totals = fleet.get("totals") or {}
    lines.append(
        f"FLEET: {fleet.get('topics', 0)} topic(s) "
        f"(of {fleet.get('topics_discovered', 0)} discovered) — "
        f"{totals.get('records', 0)} records, "
        f"{totals.get('bytes', 0)} bytes, "
        f"lag {totals.get('lag', 0)}, "
        f"{totals.get('passes', 0)} pass(es)"
    )
    rows: List[List[str]] = [
        ["Topic", "Status", "P", "Records", "Bytes", "Lag", "W", "Passes",
         "Verdict"],
    ]
    for t in sorted(statuses):
        s = statuses[t]
        rows.append([
            t,
            s.get("status", "?"),
            f"{s.get('partitions', 0)}",
            f"{s.get('records', 0)}",
            f"{s.get('bytes', 0)}",
            f"{s.get('lag', 0)}",
            f"{s.get('workers', 0)}",
            f"{s.get('passes', 0)}",
            s.get("verdict", "") or "-",
        ])
    body = "\n".join(lines) + "\n" + render_table(rows)
    issues = [
        (t, statuses[t].get("error"))
        for t in sorted(statuses)
        if statuses[t].get("status") == "failed"
    ]
    if issues:
        bang = "!" * 120
        body += bang + "\n"
        body += (
            f"WARNING: {len(issues)} topic(s) FAILED — their rows above "
            "are partial; every other topic's results are unaffected\n"
        )
        for t, err in issues:
            body += f"  topic {t}: {err}\n"
        body += bang + "\n"
    return body + eq + "\n"


def render_extremes_table(metrics: TopicMetrics) -> str:
    """Optional per-partition extremes table (new capability; the reference
    only has global lines).  Columns: first/last timestamp, min/max sized
    message bytes; sentinel rows (no records / no sized records) show n/a."""
    if metrics.per_partition_extremes is None:
        return ""
    rows: List[List[str]] = [["P", "First Ts", "Last Ts", "Min-Sz", "Max-Sz"]]
    for p, e, l, s, g in metrics.extremes_decoded():
        rows.append([
            f"{p}",
            format_utc_seconds(e) if e is not None else "n/a",
            format_utc_seconds(l) if l is not None else "n/a",
            f"{s}" if s is not None else "n/a",
            f"{g}" if g is not None else "n/a",
        ])
    return "Per-partition extremes:\n" + render_table(rows)


def _render_extensions(metrics: TopicMetrics) -> str:
    """New-capability lines, outside the reference-compatible block."""
    lines: List[str] = []
    if metrics.distinct_keys_hll is not None:
        lines.append(f"Distinct keys (HLL est.): {round(metrics.distinct_keys_hll)}")
    if metrics.distinct_keys_exact is not None:
        lines.append(f"Distinct keys (exact): {metrics.distinct_keys_exact}")
    if metrics.distinct_keys_hll_per_partition is not None:
        for p, est in zip(metrics.partitions, metrics.distinct_keys_hll_per_partition):
            lines.append(f"  partition {p} distinct keys (HLL est.): {round(est)}")
    if metrics.distinct_keys_exact_per_partition is not None:
        for p, n in zip(metrics.partitions, metrics.distinct_keys_exact_per_partition):
            lines.append(f"  partition {p} distinct keys (exact): {n}")
    if metrics.quantiles is not None:
        qs = " ".join(
            f"p{int(p * 100)}={v:.0f}B" for p, v in zip(metrics.quantiles.probs, metrics.quantiles.values)
        )
        lines.append(f"Message size quantiles: {qs}")
    if metrics.quantiles_per_partition is not None:
        import math

        for p, summary in zip(metrics.partitions, metrics.quantiles_per_partition):
            if any(math.isnan(v) for v in summary.values):
                # No sized (non-tombstone) messages in this partition.
                lines.append(f"  partition {p} size quantiles: n/a")
                continue
            qs = " ".join(
                f"p{int(q * 100)}={v:.0f}B"
                for q, v in zip(summary.probs, summary.values)
            )
            lines.append(f"  partition {p} size quantiles: {qs}")
    return ("\n".join(lines) + "\n") if lines else ""
