"""ASCII table renderer byte-compatible with prettytable-rs 0.8 defaults.

The reference renders its per-partition table with ``prettytable-rs``'s
default format (``src/main.rs:148-176``): ``+``/``-`` junction rows around
and *between every* row, ``|`` column separators, one space of padding, and
left-aligned cells.  We hand-roll the same format instead of pulling a Python
table dependency so the output is under our control and locked by golden
tests.
"""

from __future__ import annotations

from typing import List, Sequence


def render_table(rows: Sequence[Sequence[str]]) -> str:
    """Render rows (first row = header) in prettytable-rs default style.

    Returns the table as a string terminated by a newline, e.g.::

        +---+-----+
        | P | Tot |
        +---+-----+
        | 0 | 12  |
        +---+-----+
    """
    if not rows:
        return ""
    ncols = max(len(r) for r in rows)
    widths = [0] * ncols
    norm: List[List[str]] = []
    for row in rows:
        cells = [str(c) for c in row] + [""] * (ncols - len(row))
        norm.append(cells)
        for i, c in enumerate(cells):
            widths[i] = max(widths[i], len(c))

    sep = "+" + "+".join("-" * (w + 2) for w in widths) + "+"
    lines = [sep]
    for cells in norm:
        line = "| " + " | ".join(c.ljust(widths[i]) for i, c in enumerate(cells)) + " |"
        lines.append(line)
        lines.append(sep)
    return "\n".join(lines) + "\n"
