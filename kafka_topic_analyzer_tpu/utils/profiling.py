"""Per-stage throughput counters + optional JAX profiler traces.

The reference's only observability is wall-clock + a derived msgs/s
(src/main.rs:129-130, SURVEY.md §5.1).  Since msgs/s *is* the north-star
metric here, the engine keeps per-stage (ingest / dispatch / finalize)
wall-time and record counters, and can wrap the scan in a JAX profiler trace
(``--profile-dir``) for XLA-level analysis on TPU.
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from typing import Dict, Iterator


@dataclasses.dataclass
class StageStats:
    seconds: float = 0.0
    items: int = 0
    bytes: int = 0

    @property
    def items_per_sec(self) -> float:
        return self.items / self.seconds if self.seconds > 0 else 0.0


class ScanProfile:
    def __init__(self) -> None:
        self.stages: Dict[str, StageStats] = {}
        self.wall_start = time.monotonic()

    @contextlib.contextmanager
    def stage(self, name: str, items: int = 0, nbytes: int = 0) -> Iterator[None]:
        st = self.stages.setdefault(name, StageStats())
        t0 = time.perf_counter()
        try:
            yield
        finally:
            st.seconds += time.perf_counter() - t0
            st.items += items
            st.bytes += nbytes

    @property
    def wall_seconds(self) -> float:
        return time.monotonic() - self.wall_start

    def summary(self) -> str:
        lines = []
        for name, st in self.stages.items():
            lines.append(
                f"  {name}: {st.seconds:.3f}s, {st.items} records"
                + (f" ({st.items_per_sec:,.0f}/s)" if st.items else "")
            )
        return "\n".join(lines)


@contextlib.contextmanager
def maybe_jax_trace(profile_dir: "str | None") -> Iterator[None]:
    if not profile_dir:
        yield
        return
    import jax

    with jax.profiler.trace(profile_dir):
        yield
