"""Per-stage throughput counters + optional JAX profiler traces.

The reference's only observability is wall-clock + a derived msgs/s
(src/main.rs:129-130, SURVEY.md §5.1).  Since msgs/s *is* the north-star
metric here, the engine keeps per-stage (ingest / dispatch / finalize)
wall-time and record counters, and can wrap the scan in a JAX profiler trace
(``--profile-dir``) for XLA-level analysis on TPU.

With a span tracer attached (``--trace-json``, obs/trace.py) every stage
window is also mirrored into the Chrome trace with the *same* measured
duration, so the host trace's per-stage totals agree with ``--stats``
exactly.
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from typing import Dict, Iterator, Optional

from kafka_topic_analyzer_tpu.obs import metrics as obs_metrics

#: Canonical drive-loop stage order: pipeline position, not insertion
#: order (insertion order varies with which stage fires first — e.g. a
#: resumed scan snapshots before its first dispatch).  THE one list —
#: the --stats stage digest (results.StageDigest), the flight recorder's
#: stage tracks (obs/flight.py), and the scan doctor's occupancy model
#: (obs/doctor.py) all import it, so adding a stage here propagates to
#: every surface instead of silently dropping out of one.
STAGE_ORDER = ("ingest", "dispatch", "snapshot", "finalize")


@dataclasses.dataclass
class StageStats:
    seconds: float = 0.0
    items: int = 0
    bytes: int = 0

    @property
    def items_per_sec(self) -> float:
        return self.items / self.seconds if self.seconds > 0 else 0.0

    @property
    def mb_per_sec(self) -> float:
        return (
            self.bytes / self.seconds / 1e6 if self.seconds > 0 else 0.0
        )


class ScanProfile:
    def __init__(self, tracer=None) -> None:
        self.stages: Dict[str, StageStats] = {}
        self.wall_start = time.monotonic()
        #: Optional obs.trace.SpanTracer — stage windows mirror into it.
        self.tracer = tracer

    @contextlib.contextmanager
    def stage(self, name: str, items: int = 0, nbytes: int = 0) -> Iterator[None]:
        st = self.stages.setdefault(name, StageStats())
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            st.seconds += dt
            st.items += items
            st.bytes += nbytes
            if self.tracer is not None:
                # Same t0/dt as the stat above: the trace and --stats can
                # never drift apart.
                self.tracer.add_complete(name, t0, dt, cat="stage")
            # Book the SAME measurement into the live stage counters at
            # every window exit (not once at scan end): the flight
            # recorder samples these mid-scan for per-stage occupancy,
            # and the --stats stage digest renders from the registry
            # snapshot — one measurement, every surface (DESIGN.md §17).
            obs_metrics.STAGE_SECONDS.labels(stage=name).inc(dt)
            if items:
                obs_metrics.STAGE_RECORDS.labels(stage=name).inc(items)
            if nbytes:
                obs_metrics.STAGE_BYTES.labels(stage=name).inc(nbytes)

    @property
    def wall_seconds(self) -> float:
        return time.monotonic() - self.wall_start

    def ordered_stages(self) -> "list[tuple[str, StageStats]]":
        """Stages in canonical pipeline order, then alphabetical for any
        stage outside the canon — deterministic across runs."""
        rank = {name: i for i, name in enumerate(STAGE_ORDER)}
        return sorted(
            self.stages.items(),
            key=lambda kv: (rank.get(kv[0], len(STAGE_ORDER)), kv[0]),
        )

    def summary(self) -> str:
        lines = []
        for name, st in self.ordered_stages():
            line = f"  {name}: {st.seconds:.3f}s, {st.items} records"
            if st.items:
                line += f" ({st.items_per_sec:,.0f}/s)"
            if st.bytes:
                line += (
                    f", {st.bytes / 1e6:,.1f} MB ({st.mb_per_sec:,.1f} MB/s)"
                )
            lines.append(line)
        return "\n".join(lines)


@contextlib.contextmanager
def maybe_jax_trace(profile_dir: "Optional[str]") -> Iterator[None]:
    if not profile_dir:
        yield
        return
    import jax

    with jax.profiler.trace(profile_dir):
        yield
