"""Background batch prefetching — overlap host ingest with device compute.

The reference's loop strictly alternates poll → process (src/kafka.rs:92-135,
single thread).  Here device dispatch is already asynchronous, so the
remaining serialization is host-side batch production (fetch/decode/pack);
a small bounded queue filled by a worker thread overlaps it with the device
step (SURVEY.md §7 M5 'double-buffered host→device pipeline').  The native
generator and socket IO release the GIL, so the overlap is real.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator, TypeVar

T = TypeVar("T")

_SENTINEL = object()


class _Error:
    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


class PrefetchIterator:
    """Wraps an iterator, producing items from a worker thread.

    Exceptions raised by the source are re-raised at the consuming side, at
    the position they occurred; the worker stops on first error.
    """

    def __init__(self, it: Iterator[T], depth: int = 2):
        if depth < 1:
            raise ValueError("depth must be >= 1")
        self._q: "queue.Queue[object]" = queue.Queue(maxsize=depth)
        self._it = it
        self._cancel = threading.Event()
        self._source_closed = False
        self._thread = threading.Thread(target=self._fill, daemon=True)
        self._thread.start()

    def _put(self, item: object) -> bool:
        """Bounded put that gives up when the consumer cancelled."""
        while not self._cancel.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _close_source(self) -> None:
        """Throw GeneratorExit into the wrapped iterator (idempotent) so
        its finally blocks run — sources hold real resources (the wire
        client's per-stream broker connections).  Only called while no
        thread is executing the generator: from the worker after its loop
        exits, or from ``close()`` after the worker thread is gone."""
        if self._source_closed:
            return
        self._source_closed = True
        if hasattr(self._it, "close"):
            try:
                self._it.close()
            except Exception:
                pass  # a dying source must not mask the scan's real error

    def _fill(self) -> None:
        try:
            for item in self._it:
                if not self._put(item):
                    break
        except BaseException as e:  # propagate to the consumer
            self._put(_Error(e))
            return
        finally:
            if self._cancel.is_set():
                self._close_source()  # close the abandoned generator
        self._put(_SENTINEL)

    def close(self) -> None:
        """Stop the worker and release the wrapped iterator.  Safe to call
        multiple times; the engine calls it from a finally so early exits
        (errors, interrupts) never leak the thread, the underlying
        generator, or its connections."""
        self._cancel.set()
        # Drain so a blocked worker can observe the cancel promptly.
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5.0)
        if not self._thread.is_alive():
            # The worker can exit without taking its cancel-path close: it
            # already finished (exhaustion, error) before close() was
            # called, or it lost the cancel race right after its loop.
            # Either way the generator is quiescent now — close it HERE so
            # an early consumer exit always unwinds the source's finally
            # blocks, not just the worker thread.
            self._close_source()

    def __iter__(self) -> "PrefetchIterator":
        return self

    def __next__(self) -> T:
        item = self._q.get()
        if item is _SENTINEL:
            raise StopIteration
        if isinstance(item, _Error):
            raise item.exc
        return item


def prefetch(it: Iterator[T], depth: int = 2) -> Iterator[T]:
    """0/negative depth disables prefetching (pass-through)."""
    if depth <= 0:
        return it
    return PrefetchIterator(it, depth)
