"""chrono-compatible UTC timestamp formatting.

The reference prints ``DateTime<Utc>`` values with chrono's ``Display``
impl — ``YYYY-MM-DD HH:MM:SS UTC`` (seen in demo_output.png; values built at
second granularity, src/metric.rs:209-211).  The report must byte-match.
"""

from __future__ import annotations

import datetime


def format_utc_seconds(ts_s: int) -> str:
    """Render an epoch-seconds timestamp exactly like chrono's
    ``DateTime<Utc>`` Display: ``1970-01-01 00:00:00 UTC``."""
    dt = datetime.datetime.fromtimestamp(int(ts_s), tz=datetime.timezone.utc)
    return dt.strftime("%Y-%m-%d %H:%M:%S UTC")


def utc_now_seconds() -> int:
    return int(datetime.datetime.now(tz=datetime.timezone.utc).timestamp())
