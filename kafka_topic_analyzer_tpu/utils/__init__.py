"""Small shared utilities: report table rendering, chrono-compatible time
formatting, progress display, logging setup, profiling counters."""
