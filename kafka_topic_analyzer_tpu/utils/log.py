"""Logging setup — the reference's env_logger convention.

The reference initializes env_logger at startup (src/main.rs:30) and is
driven by ``RUST_LOG``.  We honor the same variable (plus ``KTA_LOG``) so a
user switching tools keeps their habits — including env_logger's
``target=level`` segments: ``KTA_LOG=kta.io=debug,error`` floods the wire
client's logger while everything else stays at ERROR.  Targets are logger
names; the ``kta`` prefix aliases the package root, so ``kta.io`` means
``kafka_topic_analyzer_tpu.io`` (and every module logger under it).
"""

from __future__ import annotations

import logging
import os
from typing import Dict, Tuple

_LEVELS = {
    "trace": logging.DEBUG,
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "off": logging.CRITICAL,
}

#: env_logger-style short prefix for the package's logger tree.
_ALIAS = "kta"
_PACKAGE = "kafka_topic_analyzer_tpu"


def parse_spec(spec: str) -> "Tuple[int, Dict[str, int]]":
    """env_logger spec → (default level, {target: level}).

    ``"level"`` segments set the default (first one wins, like
    env_logger's last-wins is for *conflicting* targets — bare repeats are
    junk); ``target=level`` segments configure that target's logger.
    Junk segments — unknown levels, empty targets — are ignored, and a
    spec with no usable default falls back to ERROR."""
    default: "int | None" = None
    targets: Dict[str, int] = {}
    for seg in spec.split(","):
        seg = seg.strip()
        if not seg:
            continue
        if "=" in seg:
            target, _, level = seg.partition("=")
            target = target.strip()
            level = level.strip().lower()
            if target and level in _LEVELS:
                targets[target] = _LEVELS[level]
            continue
        if default is None and seg.lower() in _LEVELS:
            default = _LEVELS[seg.lower()]
    return (logging.ERROR if default is None else default), targets


def parse_level(spec: str) -> int:
    """Default (root) level of an env_logger spec — see parse_spec."""
    return parse_spec(spec)[0]


def resolve_target(target: str) -> str:
    """Map an env_logger target onto a logger name (``kta`` → package)."""
    if target == _ALIAS:
        return _PACKAGE
    if target.startswith(_ALIAS + "."):
        return _PACKAGE + target[len(_ALIAS):]
    return target


def init_logging() -> None:
    spec = os.environ.get("KTA_LOG") or os.environ.get("RUST_LOG") or "error"
    default, targets = parse_spec(spec)
    logging.basicConfig(
        level=default,
        format="[%(asctime)s %(levelname)s %(name)s] %(message)s",
    )
    # Per-target levels ride on logger-name hierarchy: setting
    # kafka_topic_analyzer_tpu.io covers every module logger beneath it,
    # and the root handler (level NOTSET) passes whatever they emit.
    for target, level in targets.items():
        logging.getLogger(resolve_target(target)).setLevel(level)
