"""Logging setup — the reference's env_logger convention.

The reference initializes env_logger at startup (src/main.rs:30) and is
driven by ``RUST_LOG``.  We honor the same variable (plus ``KTA_LOG``) so a
user switching tools keeps their habits: ``RUST_LOG=warn kta ...``.
"""

from __future__ import annotations

import logging
import os

_LEVELS = {
    "trace": logging.DEBUG,
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "off": logging.CRITICAL,
}


def parse_level(spec: str) -> int:
    """env_logger accepts "level" or "target=level,..." — take the first
    bare level segment; unknown specs fall back to ERROR."""
    for seg in spec.split(","):
        if "=" not in seg and seg.strip().lower() in _LEVELS:
            return _LEVELS[seg.strip().lower()]
    return logging.ERROR


def init_logging() -> None:
    spec = os.environ.get("KTA_LOG") or os.environ.get("RUST_LOG") or "error"
    logging.basicConfig(
        level=parse_level(spec),
        format="[%(asctime)s %(levelname)s %(name)s] %(message)s",
    )
