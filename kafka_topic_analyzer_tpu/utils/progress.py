"""indicatif-style progress spinner.

The reference updates an indicatif spinner with template
``"{spinner:.green} [{elapsed_precise}] {msg}"`` once *per message*
(src/kafka.rs:85-86, :111-113) — a measured hot-loop cost (SURVEY.md §3.3).
Here the spinner updates once per batch, rate-limited, and writes to stderr
so report output stays clean.  A rate-limited message is kept as *pending*
rather than dropped, so the final pre-finish update (the last Sq/offset
frame of the scan) always lands; and ``finish_with_message`` stays silent
when no frame was ever drawn (nothing to finish — e.g. a sub-interval scan
whose every update was elided would otherwise emit a lone "done" line).
"""

from __future__ import annotations

import sys
import time
from typing import Callable, Optional

_FRAMES = "⠁⠂⠄⡀⢀⠠⠐⠈"


class Spinner:
    def __init__(
        self,
        enabled: "bool | None" = None,
        min_interval_s: float = 0.1,
        clock: Callable[[], float] = time.monotonic,
    ):
        if enabled is None:
            enabled = sys.stderr.isatty()
        self.enabled = enabled
        self.min_interval_s = min_interval_s
        self._clock = clock
        self.start = clock()
        self._last = 0.0
        self._frame = 0
        self._dirty = False
        self._pending: Optional[str] = None

    def _elapsed_precise(self) -> str:
        e = int(self._clock() - self.start)
        return f"{e // 3600:02d}:{(e % 3600) // 60:02d}:{e % 60:02d}"

    def _draw(self, msg: str) -> None:
        self._last = self._clock()
        frame = _FRAMES[self._frame % len(_FRAMES)]
        self._frame += 1
        sys.stderr.write(f"\r{frame} [{self._elapsed_precise()}] {msg}\x1b[K")
        sys.stderr.flush()
        self._dirty = True
        self._pending = None

    def set_message(self, msg: str) -> None:
        if not self.enabled:
            return
        if self._clock() - self._last < self.min_interval_s:
            self._pending = msg  # held, not dropped — flushed by finish
            return
        self._draw(msg)

    def finish_with_message(self, msg: str) -> None:
        if not self.enabled:
            return
        if self._pending is not None:
            # The last rate-limited update still reaches the terminal
            # before the finish line replaces it.
            self._draw(self._pending)
        if not self._dirty:
            return  # no frame was ever drawn; nothing to finish
        sys.stderr.write(f"\r  [{self._elapsed_precise()}] {msg}\x1b[K\n")
        sys.stderr.flush()
        self._dirty = False
