"""indicatif-style progress spinner.

The reference updates an indicatif spinner with template
``"{spinner:.green} [{elapsed_precise}] {msg}"`` once *per message*
(src/kafka.rs:85-86, :111-113) — a measured hot-loop cost (SURVEY.md §3.3).
Here the spinner updates once per batch, rate-limited, and writes to stderr
so report output stays clean.
"""

from __future__ import annotations

import sys
import time

_FRAMES = "⠁⠂⠄⡀⢀⠠⠐⠈"


class Spinner:
    def __init__(self, enabled: "bool | None" = None, min_interval_s: float = 0.1):
        if enabled is None:
            enabled = sys.stderr.isatty()
        self.enabled = enabled
        self.min_interval_s = min_interval_s
        self.start = time.monotonic()
        self._last = 0.0
        self._frame = 0
        self._dirty = False

    def _elapsed_precise(self) -> str:
        e = int(time.monotonic() - self.start)
        return f"{e // 3600:02d}:{(e % 3600) // 60:02d}:{e % 60:02d}"

    def set_message(self, msg: str) -> None:
        if not self.enabled:
            return
        now = time.monotonic()
        if now - self._last < self.min_interval_s:
            return
        self._last = now
        frame = _FRAMES[self._frame % len(_FRAMES)]
        self._frame += 1
        sys.stderr.write(f"\r{frame} [{self._elapsed_precise()}] {msg}\x1b[K")
        sys.stderr.flush()
        self._dirty = True

    def finish_with_message(self, msg: str) -> None:
        if not self.enabled:
            return
        sys.stderr.write(f"\r  [{self._elapsed_precise()}] {msg}\x1b[K\n")
        sys.stderr.flush()
        self._dirty = False
