"""Scan snapshots: resumable long scans (SURVEY.md §5.4).

The reference deliberately has no resume: offsets are stored but never
committed, and every run rescans from earliest (src/kafka.rs:28-34,
src/main.rs:63-65's stale help text notwithstanding).  For 1B-message scans
that is wasteful, so the TPU build adds periodic snapshots: the analyzer
state is a small, associatively-merged pytree, so a snapshot is just

    (config fingerprint, per-partition next offsets, state arrays)

written atomically.  Resuming replays nothing: the saved state already
folds every record below the saved offsets, and the source continues from
them.  Works because updates are deterministic folds and batches respect
per-partition offset order (records.py contract).

Format: one ``.npz`` per snapshot (atomic rename), holding the state leaves
flattened by pytree path plus offset/config metadata as JSON strings.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import tempfile
from typing import Dict, Optional, Tuple

import jax
import numpy as np

from kafka_topic_analyzer_tpu.config import AnalyzerConfig
from kafka_topic_analyzer_tpu.models.state import AnalyzerState

SNAPSHOT_NAME = "scan_snapshot.npz"


class StaleLeaseEpochError(ValueError):
    """A snapshot save or load was FENCED: the caller's lease epoch is
    older than the epoch already stamped on disk (DESIGN.md §23).

    This is the zombie-writer guard of the lease layer (fleet/lease.py):
    an instance that lost its topic lease while paused mid-pass must
    never land a checkpoint over its successor's, and must never resume
    FROM a successor's checkpoint as if it still owned the topic.  Named
    (rather than a bare ValueError) the same way the mesh-pinned
    fingerprint rejection is: the operator-facing message says who
    fenced whom and what to do about it."""


#: Config fields that change neither state shapes nor fold semantics —
#: pure execution strategy, safe to flip across a resume (the pallas and
#: lax counter paths are bit-identical, tests/test_pallas_counters.py;
#: wire v4 and v5 fold to byte-identical state, tests/test_wire_v5.py —
#: a v4 snapshot resumes under v5 and vice versa; compacted and
#: uncompacted alive-pair folds are byte-identical,
#: tests/test_alive_compaction.py).  Excluding wire_format (and
#: alive_compaction) also keeps pre-v5 snapshots' fingerprints valid
#: unchanged.
_EXECUTION_ONLY_FIELDS = (
    "use_pallas_counters",
    "wire_format",
    "alive_compaction",
)


def _fingerprint_at(
    config: AnalyzerConfig, topic: str, version: int, mesh_free: bool = False
) -> str:
    fields = dataclasses.asdict(config)
    for k in _EXECUTION_ONLY_FIELDS:
        fields.pop(k, None)
    if mesh_free:
        # Mesh-free snapshots store the CANONICAL (single-device-layout)
        # state, which every mesh can adopt — so the mesh shape is pure
        # execution strategy for them and must not pin the fingerprint.
        fields.pop("mesh_shape", None)
    if config.enable_quantiles:
        # PR 9 changed the DDSketch bucket rule (float32 log → the shared
        # integer edge table, ops/ddsketch.ddsketch_edges): borderline
        # sizes can land one bucket over vs the old rule, so a pre-change
        # quantile snapshot's accumulated buckets must NOT merge with
        # new-rule buckets — stamp the rule so those snapshots are
        # cleanly rejected instead.  Quantile-free configs keep their
        # pre-change fingerprints (no bucket state to skew).
        fields["ddsketch_bucket_rule"] = "edges-v1"
    payload = json.dumps(
        {"topic": topic, "state_version": version, **fields},
        sort_keys=True,
    )
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


def mesh_free_snapshots(config: AnalyzerConfig) -> bool:
    """True when this config's snapshots canonicalize to the mesh-free
    single-device layout and may resume under ANY mesh shape (and any
    --ingest-workers / --superbatch / --dispatch-depth — those never
    entered the fingerprint).

    Every analyzer fold except one is associative AND commutative across
    device rows (counters and DDSketch rows add, extremes and HLL
    registers merge by min/max), so a stacked state folds down to one
    canonical row at save time and redistributes as (canonical, identity,
    identity, ...) at load time — the mesh's finalize reduction then
    reproduces exactly the canonical values (DESIGN.md §14).  The
    exception is the alive-key bitmap: last-writer-wins bit CLEARS only
    resolve correctly against the same row that set the bit, and the
    partition→row assignment changes with the mesh — so alive-key scans
    keep the mesh-pinned fingerprint (resuming them under a different
    mesh is a clean error, not a silent miscount)."""
    return not config.count_alive_keys


def config_fingerprint(config: AnalyzerConfig, topic: str) -> str:
    """Snapshot compatibility key (the one new snapshots are stamped with):
    anything that changes state shapes or fold semantics participates.

    state_version: bump whenever the AnalyzerState layout changes so stale
    snapshots are rejected instead of shape-erroring.  v3: space_shards>1
    meshes changed record-parallel leaves from D to D*S leading rows
    (parallel/sharded.py, r2 commit 9409a31).  S=1 layouts were untouched
    by that change, so they stamp version 2 — and loaders additionally
    accept the v3-stamped fingerprint for S=1 configs
    (`acceptable_fingerprints`), keeping both pre-r2 AND r2/r3-era
    single-space-shard snapshots resumable (the r2/r3 code stamped every
    config v3).  v4 (r7): configs without the alive bitmap store the
    CANONICAL mesh-free layout (see `mesh_free_snapshots`) and drop
    mesh_shape from the fingerprint — any-mesh↔any-mesh resume."""
    if mesh_free_snapshots(config):
        return _fingerprint_at(config, topic, 4, mesh_free=True)
    version = 2 if config.space_shards == 1 else 3
    return _fingerprint_at(config, topic, version)


def acceptable_fingerprints(config: AnalyzerConfig, topic: str) -> "set[str]":
    """All fingerprints a loader should accept for this config: the
    canonical one, plus compatible legacy stamps — the v3 variant for S=1
    configs whose state layout is identical under both version labels,
    and (for mesh-free configs) the pre-v4 mesh-pinned stamps of the SAME
    mesh, whose stacked leaves still match the current backend's template
    exactly (see config_fingerprint)."""
    out = {config_fingerprint(config, topic)}
    if mesh_free_snapshots(config):
        # Legacy (pre-r7) snapshots of this exact mesh: stacked layout,
        # mesh-pinned stamp.  Shapes match the current template, so they
        # load directly.
        out.add(_fingerprint_at(config, topic, 2 if config.space_shards == 1 else 3))
    if config.space_shards == 1:
        out.add(_fingerprint_at(config, topic, 3))
    return out


def _canonicalize(state: AnalyzerState) -> AnalyzerState:
    """Fold a stacked state's leading device axis down to the canonical
    single-device layout via the state's OWN associative merge
    (`AnalyzerState.merge` — the single source of the per-leaf law: sums
    add, extremes min/max, HLL registers max, DDSketch buckets add).
    Already-canonical states pass through untouched.  Never called with an
    alive bitmap (mesh_free_snapshots gates it out: bit clears are only
    exact against the row that set the bit)."""
    assert state.alive is None, "alive-bitmap states are mesh-pinned"
    probe = np.asarray(state.metrics.per_partition)
    if probe.ndim == 2:
        return state  # single-device layout already
    acc = None
    for i in range(probe.shape[0]):
        row = jax.tree.map(lambda x: np.asarray(x)[i], state)
        acc = row if acc is None else acc.merge(row)
    return jax.tree.map(np.asarray, acc)


def _distribute(
    canonical: AnalyzerState, template: AnalyzerState, identity: AnalyzerState
) -> AnalyzerState:
    """Inverse placement for resuming a canonical snapshot on a stacked
    (device-row-stacked) template: device row 0 carries the canonical
    fold, every other row its leaf's merge IDENTITY — ``identity`` is a
    fresh `AnalyzerState.init` (a fresh state IS the merge identity;
    that is what makes merging one in a no-op), broadcast to the
    template's stacked shape.  The backend's finalize reduction then
    reproduces exactly the canonical values, and records folded after
    the resume land in whichever row their partition now maps to —
    byte-identical either way, because every one of these folds is
    associative and commutative across rows."""

    def place(ident, tmpl, canon) -> np.ndarray:
        out = np.broadcast_to(
            np.asarray(ident), np.asarray(tmpl).shape
        ).copy()
        out[0] = np.asarray(canon)
        return out

    return jax.tree.map(place, identity, template, canonical)


def _flatten(state: AnalyzerState) -> Dict[str, np.ndarray]:
    flat = {}
    leaves = jax.tree_util.tree_flatten_with_path(state)[0]
    for path, leaf in leaves:
        key = "state" + "".join(str(p) for p in path)
        flat[key] = np.asarray(leaf)
    return flat


def _snapshot_path(directory: str, scope) -> str:
    """Single-controller snapshots are one file; multi-controller scans
    write one file PER PROCESS (its own data rows + its own partitions'
    offsets).  Data shards fold independently, so each process may resume
    from its file with no cross-process coordination — a process without
    a file simply rescans its shards from zero, which is still exact."""
    if scope is None:
        return os.path.join(directory, SNAPSHOT_NAME)
    pid, nproc, _rows = scope
    return os.path.join(directory, f"scan_snapshot.p{pid}of{nproc}.npz")


def save_snapshot(
    directory: str,
    topic: str,
    config: AnalyzerConfig,
    state: AnalyzerState,
    next_offsets: Dict[int, int],
    records_seen: int,
    init_now_s: int,
    scope=None,
    degraded: "Optional[Dict[int, str]]" = None,
    corrupt: "Optional[list]" = None,
    lease_epoch: "Optional[int]" = None,
    lost: "Optional[list]" = None,
    partition_meta: "Optional[Dict[int, dict]]" = None,
) -> str:
    """Atomically write the snapshot; returns its path.

    ``scope``: None, or ``(process_index, process_count, local_rows)`` for
    multi-controller runs — ``state`` is then the PROCESS-LOCAL rows
    (ShardedTpuBackend.get_state_local).

    ``degraded``: partition -> reason for partitions the scan dropped
    (transport-fault degradation).  Informational only — resume reads
    ``next_offsets``, which already stop at each degraded partition's last
    folded record — but it lets an operator see from the snapshot alone
    why a rerun is needed.

    ``corrupt``: the span list of poisoned frames the scan skipped or
    quarantined (KafkaWireSource.corruption_spans format).  NOT merely
    informational: a --resume seeds the source with it
    (`load_corrupt_spans`) so re-walking an already-skipped span — the
    offset tracker cannot advance past a span that yielded no records —
    neither re-counts nor double-quarantines it.

    ``lost``: the span list of offset ranges the log mutated away from the
    scan (KafkaWireSource.lost_spans format — retention races, truncation,
    resume-below-log-start).  Like ``corrupt``, NOT merely informational: a
    --resume seeds the source with it (`load_lost_spans`) so the logical
    scan's final report still names the loss, without re-booking it.

    ``partition_meta``: per-partition durable-fencing facts
    ({partition: {leader_epoch, log_start_offset}},
    KafkaWireSource.partition_meta format).  Resume validates the saved
    cursor against the live log with these (`load_partition_meta` →
    validate_resume): a cursor below the live log start is a named
    retention loss BEFORE the first fetch, and an epoch that moved since
    the save triggers the OffsetForLeaderEpoch divergence check.

    ``lease_epoch``: the writer's topic-ownership lease epoch under a
    multi-instance fleet (fleet/lease.py).  The save is FENCED at write
    time: if the on-disk snapshot already carries a NEWER epoch, a
    successor instance owns this topic and the write raises
    `StaleLeaseEpochError` instead of clobbering its state.  None (solo
    scans, lease-less fleets) skips the check and stamps nothing.

    The fence is check-then-act (read the stamp, then rename), so it
    closes only once the successor's FIRST save lands: a zombie at
    epoch N racing a successor (epoch N+1) that has acquired but not
    yet saved still reads stamp <= N and lands one stale checkpoint.
    The successor's save then overwrites it, bounding the damage to at
    most one stale pass — but a crash inside that window resumes from
    the zombie's state, and anything the zombie published during that
    pass was double-scanned (DESIGN.md §23 failure matrix)."""
    os.makedirs(directory, exist_ok=True)
    if lease_epoch is not None:
        try:
            prev = snapshot_info(directory, scope)
        except Exception:
            prev = None  # unreadable/truncated snapshot cannot outrank us
        prev_epoch = int((prev or {}).get("lease_epoch", 0))
        if prev_epoch > int(lease_epoch):
            raise StaleLeaseEpochError(
                f"STALE-LEASE-EPOCH: refusing to save snapshot for topic "
                f"{topic!r}: the on-disk snapshot carries lease epoch "
                f"{prev_epoch}, this writer holds epoch {int(lease_epoch)} "
                "— this instance was fenced (its topic lease expired and "
                "a successor took over; DESIGN.md §23).  Do not retry: "
                "the successor's checkpoint is the live one"
            )
    host_state = jax.tree.map(np.asarray, jax.device_get(state))
    if mesh_free_snapshots(config):
        # Store the canonical mesh-free layout (v4 stamp): a stacked
        # state folds its leading device axis down host-side, so ANY mesh
        # (or the single device) can adopt the snapshot on resume.
        host_state = _canonicalize(host_state)
    flat = _flatten(host_state)
    meta = {
        "fingerprint": config_fingerprint(config, topic),
        "topic": topic,
        "next_offsets": {str(k): int(v) for k, v in next_offsets.items()},
        "records_seen": int(records_seen),
        "init_now_s": int(init_now_s),
    }
    if degraded:
        meta["degraded"] = {str(k): str(v) for k, v in degraded.items()}
    if corrupt:
        meta["corrupt_spans"] = list(corrupt)
    if lost:
        meta["lost_spans"] = list(lost)
    if partition_meta:
        meta["partition_meta"] = {
            str(k): {
                "leader_epoch": int(v.get("leader_epoch", -1)),
                "log_start_offset": int(v.get("log_start_offset", -1)),
            }
            for k, v in partition_meta.items()
        }
    if lease_epoch is not None:
        meta["lease_epoch"] = int(lease_epoch)
    if scope is not None:
        meta["process"] = [int(scope[0]), int(scope[1])]
        meta["local_rows"] = [int(r) for r in scope[2]]
    path = _snapshot_path(directory, scope)
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".npz.tmp")
    os.close(fd)
    try:
        with open(tmp, "wb") as f:
            np.savez(f, __meta__=json.dumps(meta), **flat)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return path


def _fingerprint_mismatch_message(
    path: str, meta: dict, config: AnalyzerConfig, topic: str
) -> str:
    """A rejection message that NAMES the cause when it can.

    Alive-key scans are mesh-pinned (`mesh_free_snapshots` — LWW bit
    clears only resolve against the row that set the bit), and "I resumed
    an alive scan on a different mesh" is by far the most common way to
    hit this error — so instead of a bare "fingerprint mismatch", probe
    whether the snapshot's stamp matches THIS config under some other
    mesh shape and, when it does, say which mesh wrote it and what a
    resume is allowed to change."""
    base = (
        f"snapshot at {path} was taken with a different topic/config "
        "(fingerprint mismatch)"
    )
    if not config.count_alive_keys:
        return base + " — delete it or match the original flags"
    # Probe plausible writer meshes: same config, different (data, space)
    # shape.  Bounded sweep — meshes are small integer grids.
    stamp = meta.get("fingerprint")
    for d in range(1, 65):
        for s in (1, 2, 4, 8):
            shape = (d, s)
            if shape == tuple(config.mesh_shape):
                continue
            try:
                probe = dataclasses.replace(config, mesh_shape=shape)
            except ValueError:
                continue
            # s==1 writers stamp version 2 today, but r2/r3-era builds
            # stamped every config v3 (see acceptable_fingerprints) —
            # probe both so legacy snapshots get the same diagnosis.
            versions = (2, 3) if s == 1 else (3,)
            if any(
                _fingerprint_at(probe, topic, v) == stamp for v in versions
            ):
                return (
                    f"snapshot at {path} is MESH-PINNED and was written by "
                    f"a mesh {shape[0]}x{shape[1]} scan: this scan counts "
                    "alive keys (-c/--count-alive-keys), and alive-key "
                    "snapshots only resume under the ORIGINAL mesh shape "
                    "(last-writer-wins bit clears must land on the data "
                    "row that set the bit — DESIGN.md §14).  Resume with "
                    f"--mesh {shape[0]},{shape[1]} (ingest workers, "
                    "superbatch, dispatch depth, wire format and "
                    "alive-compaction may all change freely), or delete "
                    "the snapshot to rescan under "
                    f"--mesh {config.mesh_shape[0]},{config.mesh_shape[1]}"
                )
    return (
        base
        + " — this scan counts alive keys (-c/--count-alive-keys), whose "
        "snapshots additionally pin the mesh shape; delete the snapshot "
        "or match the original flags"
    )


def load_snapshot(
    directory: str,
    topic: str,
    config: AnalyzerConfig,
    template: Optional[AnalyzerState] = None,
    scope=None,
    lease_epoch: "Optional[int]" = None,
) -> Optional[Tuple[AnalyzerState, Dict[int, int], int, int]]:
    """Load (state, next_offsets, records_seen, init_now_s), or None if no
    compatible snapshot exists.  An incompatible snapshot (different config/
    topic) raises — silently restarting would double-count.

    ``lease_epoch``: the loader's topic-ownership lease epoch
    (fleet/lease.py).  A snapshot stamped with a NEWER epoch was written
    by a successor instance — the loader was fenced, and resuming from
    (then overwriting) the successor's state would double-count: raises
    `StaleLeaseEpochError`.  A snapshot with an older or absent epoch
    loads normally — that is exactly the failover path, where epoch E+1
    resumes its predecessor's epoch-E checkpoint.

    ``template`` supplies the expected state shapes; it defaults to the
    single-device layout.  Sharded backends pass their freshly-initialized
    (data-stacked) state — the engine uses ``backend.get_state()`` — since
    their leaves carry a leading data-shard axis.  With ``scope`` set (see
    save_snapshot) the template and returned state are process-local.
    """
    path = _snapshot_path(directory, scope)
    if not os.path.exists(path):
        return None
    with np.load(path, allow_pickle=False) as z:
        meta = json.loads(str(z["__meta__"]))
        if meta["fingerprint"] not in acceptable_fingerprints(config, topic):
            raise ValueError(
                _fingerprint_mismatch_message(path, meta, config, topic)
            )
        if (
            lease_epoch is not None
            and int(meta.get("lease_epoch", 0)) > int(lease_epoch)
        ):
            raise StaleLeaseEpochError(
                f"STALE-LEASE-EPOCH: refusing to resume topic {topic!r} "
                f"from {path}: the snapshot was written under lease epoch "
                f"{int(meta['lease_epoch'])}, this loader holds epoch "
                f"{int(lease_epoch)} — this instance was fenced (a "
                "successor owns the topic; DESIGN.md §23).  Re-acquire "
                "the lease to get a current epoch before resuming"
            )
        if scope is not None:
            pid, nproc, rows = scope
            if meta.get("process") != [pid, nproc] or meta.get(
                "local_rows"
            ) != [int(r) for r in rows]:
                raise ValueError(
                    f"snapshot at {path} belongs to a different process "
                    "layout (process/data-row mismatch) — delete it or "
                    "rerun with the original mesh and process count"
                )
        if template is None:
            template = AnalyzerState.init(config)
        template = jax.tree.map(np.asarray, jax.device_get(template))
        flat = _flatten(template)
        loaded = {k: z[k] for k in flat}
    leaves_p, treedef = jax.tree_util.tree_flatten_with_path(template)
    direct = all(
        loaded["state" + "".join(str(p) for p in path_key)].shape
        == np.asarray(leaf).shape
        for path_key, leaf in leaves_p
    )
    canon_identity = None
    if not direct and mesh_free_snapshots(config):
        # Cross-mesh resume: the stored leaves are the canonical
        # single-device layout (v4 snapshots always are), the template is
        # this backend's stacked layout.  Validate against the canonical
        # shapes instead, then redistribute below: row 0 = canonical,
        # other rows = identity (see _distribute — the fresh init state
        # doubles as both the shape template and the identity values).
        canon_identity = jax.tree.map(
            np.asarray, jax.device_get(AnalyzerState.init(config))
        )
        leaves_p, treedef = jax.tree_util.tree_flatten_with_path(
            canon_identity
        )
    new_leaves = []
    for path_key, leaf in leaves_p:
        key = "state" + "".join(str(p) for p in path_key)
        arr = loaded[key]
        if arr.shape != leaf.shape or arr.dtype != np.asarray(leaf).dtype:
            raise ValueError(f"snapshot leaf {key} has shape {arr.shape}")
        new_leaves.append(arr)
    state = jax.tree_util.tree_unflatten(treedef, new_leaves)
    if canon_identity is not None:
        state = _distribute(state, template, canon_identity)
    offsets = {int(k): int(v) for k, v in meta["next_offsets"].items()}
    return state, offsets, int(meta["records_seen"]), int(meta["init_now_s"])


def snapshot_info(directory: str, scope=None) -> "Optional[dict]":
    """Snapshot METADATA (fingerprint, topic, per-partition next offsets,
    records_seen, degraded/corrupt annotations) without loading the state
    arrays — or None when no snapshot exists.  The follow service's
    startup banner reads this to report where a ``--resume`` will pick up
    (serve/follow.py), and operator tooling can answer "how far did the
    dead service get" from the file alone, before paying the .npz load.
    Works on any snapshot — batch- or follow-written; the format never
    learned the difference."""
    path = _snapshot_path(directory, scope)
    if not os.path.exists(path):
        return None
    with np.load(path, allow_pickle=False) as z:
        return json.loads(str(z["__meta__"]))


HISTORY_DIR_NAME = "_kta_history"


def history_dir(directory: str) -> str:
    """Where the disk-backed telemetry history (obs/history.py,
    ``--history-bytes``) lives: a reserved subdirectory of the
    ``--snapshot-dir``, so a SIGTERM→restart that resumes the state from
    its checkpoint resumes the telemetry series from the same place —
    one directory to move, back up, or delete.  The underscore-prefixed
    reserved name keeps it out of the fleet's per-topic snapshot
    inventory (`list_topic_snapshots` skips directories without a
    snapshot file; a real Kafka topic named exactly ``_kta_history``
    would collide — don't).  Process-wide: fleet runs share one history
    (the recorder's tracks are process totals; per-topic lag lives in
    the labeled gauges)."""
    return os.path.join(directory, HISTORY_DIR_NAME)


def topic_snapshot_dir(directory: str, topic: str) -> str:
    """Fleet-mode checkpoint namespacing: each topic's snapshots live in
    their own subdirectory of the fleet ``--snapshot-dir`` (Kafka topic
    names are ``[a-zA-Z0-9._-]``, so the name IS a safe path segment).  A
    solo scan of one topic pointed at the same subdirectory resumes the
    fleet's checkpoint and vice versa — the snapshot format never learns
    it was written by a fleet."""
    return os.path.join(directory, topic)


def list_topic_snapshots(directory: str) -> "dict[str, dict]":
    """topic -> snapshot metadata for every per-topic snapshot under a
    fleet snapshot directory (`snapshot_info` over each subdirectory) —
    the fleet resume banner: "which topics will pick up where" from the
    files alone, before any broker handshake or state load."""
    out: "dict[str, dict]" = {}
    if not os.path.isdir(directory):
        return out
    for name in sorted(os.listdir(directory)):
        sub = os.path.join(directory, name)
        if not os.path.isdir(sub):
            continue
        try:
            info = snapshot_info(sub)
        except Exception:
            # One topic's corrupt/truncated snapshot (a fleet killed
            # mid-write) must not break the inventory — the fleet's
            # isolation contract starts at the banner.  That topic's own
            # resume will surface the real error in its status row.
            import logging

            logging.getLogger(__name__).warning(
                "snapshot inventory: unreadable snapshot under %r "
                "(skipped)", sub, exc_info=True,
            )
            continue
        if info is not None:
            out[name] = info
    return out


def load_corrupt_spans(directory: str, scope=None) -> list:
    """The ``corrupt_spans`` metadata of a snapshot, or [] when the
    snapshot (or the list) is absent.  Split from `load_snapshot` so the
    engine can seed the source without changing that function's
    long-standing 4-tuple contract."""
    path = _snapshot_path(directory, scope)
    if not os.path.exists(path):
        return []
    with np.load(path, allow_pickle=False) as z:
        meta = json.loads(str(z["__meta__"]))
    return list(meta.get("corrupt_spans", []))


def load_lost_spans(directory: str, scope=None) -> list:
    """The ``lost_spans`` metadata of a snapshot, or [] when the snapshot
    (or the list) is absent — same split-from-`load_snapshot` rationale as
    `load_corrupt_spans`."""
    path = _snapshot_path(directory, scope)
    if not os.path.exists(path):
        return []
    with np.load(path, allow_pickle=False) as z:
        meta = json.loads(str(z["__meta__"]))
    return list(meta.get("lost_spans", []))


def load_partition_meta(directory: str, scope=None) -> "Dict[int, dict]":
    """The ``partition_meta`` durable-fencing map of a snapshot
    ({partition: {leader_epoch, log_start_offset}}), or {} when the
    snapshot (or the map) is absent."""
    path = _snapshot_path(directory, scope)
    if not os.path.exists(path):
        return {}
    with np.load(path, allow_pickle=False) as z:
        meta = json.loads(str(z["__meta__"]))
    return {
        int(k): {
            "leader_epoch": int(v.get("leader_epoch", -1)),
            "log_start_offset": int(v.get("log_start_offset", -1)),
        }
        for k, v in meta.get("partition_meta", {}).items()
    }
