"""Finalized analysis results + the reference's derived-metric semantics.

`TopicMetrics` is the backend-agnostic result every backend (cpu, tpu,
sharded-tpu) finalizes into; the report renderer consumes only this.  The
derived metrics reproduce ``src/metric.rs`` exactly, including its quirks
(documented per method) — bug-compatibility decisions per SURVEY.md §3.4.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import numpy as np

#: Column order of the per-partition counter matrix ``per_partition[P, 7]``.
COUNTER_CHANNELS = (
    "total",
    "tombstones",
    "alive",
    "key_null",
    "key_non_null",
    "key_size_sum",
    "value_size_sum",
)
CH = {name: i for i, name in enumerate(COUNTER_CHANNELS)}

#: Sentinel matching Rust's ``u64::MAX`` initialisation of smallest_message
#: (src/metric.rs:42) — reported as 0 when never set (src/metric.rs:177-183).
U64_MAX = (1 << 64) - 1


#: The quantiles every backend reports (single source: a cpu/tpu mismatch
#: here would silently break parity comparisons).
QUANTILE_PROBS = (0.5, 0.9, 0.99)


def _avg(size_sum: int, alive: int) -> int:
    """Floor(sum/alive) — the reference divides by *alive*, not total or
    key_non_null (src/metric.rs:132-139), and guards on ``sum > 0``.  A
    partition whose retained records are all keyed tombstones has sum > 0
    with alive == 0; the reference panics there (divide-by-zero,
    src/metric.rs:134-138).  Deliberate divergence: report 0 instead of
    crashing after a completed scan."""
    return size_sum // alive if size_sum > 0 and alive > 0 else 0


@dataclasses.dataclass
class QuantileSummary:
    """Message-size quantiles (new capability; not in the reference)."""

    probs: "list[float]"
    values: "list[float]"

    def as_dict(self) -> Dict[float, float]:
        return dict(zip(self.probs, self.values))


@dataclasses.dataclass
class IngestStats:
    """Parallel-ingest accounting extracted from a telemetry snapshot
    (`ScanResult.telemetry`): per-worker record counts and backpressure
    stalls.  Consumed by the ``--stats`` digest (report.py) and the
    round-6 ingest benchmark; empty (``workers == {}``) for sequential
    scans, which never touch the per-worker instruments."""

    #: worker label -> valid records that worker produced.  Labels are
    #: plain worker indices on single-controller scans ("0", "1", ...);
    #: sharded multi-controller scans prefix the controller id ("c1.3")
    #: so the gather_telemetry merge unions per-controller samples
    #: instead of summing unrelated workers (parallel/ingest.py).
    workers: "Dict[str, int]"
    #: worker label -> seconds blocked on a full fan-in queue.
    stalls: "Dict[str, float]"

    @classmethod
    def from_telemetry(cls, snapshot: "Optional[dict]") -> "IngestStats":
        def by_worker(name: str) -> "Dict[str, float]":
            metric = (snapshot or {}).get(name)
            if not metric:
                return {}
            return {
                s["labels"]["worker"]: s["value"]
                for s in metric["samples"]
                if "worker" in s.get("labels", {})
            }

        return cls(
            workers={
                w: int(v)
                for w, v in by_worker(
                    "kta_ingest_worker_records_total"
                ).items()
            },
            stalls=by_worker("kta_ingest_worker_stall_seconds_total"),
        )


@dataclasses.dataclass
class FusedStats:
    """Fused decode→pack accounting from a telemetry snapshot: rows and
    records that took the one-pass native path, plus per-reason records
    that fell back to the python chain (compressed/legacy frames, salvage,
    missing native shim...).  The ``--stats`` digest renders it so a
    bypassed fused path is never silent; empty for chained scans."""

    rows: int
    records: int
    #: fallback reason label -> records (or stream-level bypass events).
    fallbacks: "Dict[str, int]"

    @classmethod
    def from_telemetry(cls, snapshot: "Optional[dict]") -> "FusedStats":
        snap = snapshot or {}

        def total(name: str) -> int:
            metric = snap.get(name)
            if not metric:
                return 0
            return int(sum(s["value"] for s in metric["samples"]))

        fb = snap.get("kta_fused_fallback_total")
        return cls(
            rows=total("kta_fused_batches_total"),
            records=total("kta_fused_records_total"),
            fallbacks={
                s["labels"].get("reason", "?"): int(s["value"])
                for s in (fb["samples"] if fb else [])
            },
        )


@dataclasses.dataclass
class WireStats:
    """Packed host→device wire accounting for one scan (the ``--stats``
    wire line and the ``--json`` ``wire`` block).  Built by the engine
    from the backend's config (``packing.section_byte_split`` — the byte
    split derives from the one layout source, lint rule 7) plus the scan's
    ``kta_wire_bytes_total`` delta, so the v4→v5 saving is observable per
    scan, not inferred from the layout."""

    #: Wire format the scan's packed buffers used (4 or 5).
    format: int
    #: Records per packed buffer (batch or chunk size).
    batch_size: int
    #: Bytes of one packed buffer in per-record sections (scale with B).
    per_record_bytes: int
    #: Bytes of one packed buffer in fold-table sections + header
    #: (constant per buffer — the combiner share).
    table_bytes: int
    #: Actual packed bytes this scan dispatched (this process).
    bytes_total: int = 0
    #: Valid records the scan folded (denominator for bytes/record).
    records: int = 0
    #: Alive-pair compaction state (DESIGN §19): ``on``, ``off`` (with the
    #: resolved reason — explicit / env-kill-switch / wire-v4), or ``n/a``
    #: for scans without the alive bitmap.
    alive_compaction: str = "n/a"
    #: Per-batch LWW pairs entering the dispatch-level compaction merge
    #: (``kta_alive_pairs_raw_total`` delta for this scan).
    pairs_raw: int = 0
    #: Merged pairs shipped in per-dispatch tables
    #: (``kta_alive_pairs_emitted_total`` delta) — emitted/raw is the
    #: measured compaction ratio (1.0 = all-unique worst case).
    pairs_emitted: int = 0

    @property
    def packed_nbytes(self) -> int:
        return self.per_record_bytes + self.table_bytes

    @property
    def bytes_per_record(self) -> float:
        if not self.records:
            return 0.0
        return self.bytes_total / self.records

    @property
    def compaction_ratio(self) -> float:
        """emitted/raw pairs — the measured dispatch-level dedupe win
        (0.0 when the compacted path saw no pairs)."""
        if not self.pairs_raw:
            return 0.0
        return self.pairs_emitted / self.pairs_raw

    def as_dict(self) -> dict:
        doc = {
            "format": self.format,
            "batch_size": self.batch_size,
            "per_record_bytes": self.per_record_bytes,
            "table_bytes": self.table_bytes,
            "packed_nbytes": self.packed_nbytes,
            "bytes_total": self.bytes_total,
            "bytes_per_record": round(self.bytes_per_record, 2),
            "alive_compaction": self.alive_compaction,
        }
        if self.alive_compaction == "on":
            doc["alive_pairs_raw"] = self.pairs_raw
            doc["alive_pairs_emitted"] = self.pairs_emitted
            doc["alive_compaction_ratio"] = round(self.compaction_ratio, 4)
        return doc


@dataclasses.dataclass
class SegmentStats:
    """Cold-path accounting extracted from a telemetry snapshot
    (`ScanResult.telemetry`): segment chunks the catalog opened, bytes it
    mapped, and the records/batches read from them.  Consumed by the
    ``--stats`` digest (report.py) and the ``--json`` ``segments`` block
    (cli.py); empty (``files == 0``) for scans that never touched a
    segment store."""

    #: .ktaseg chunks opened by the catalog.
    files: int
    #: Bytes of chunk data memory-mapped (local tier) or catalogued
    #: (remote tier lists the same sizes).
    bytes_mapped: int
    #: Records read out of the mapped chunks.
    records: int
    #: Batches cut from them.
    batches: int
    #: Remote-tier accounting (io/objstore.py; all zero for local scans):
    #: object-store GETs completed (list + header probes + bodies +
    #: disambiguation re-fetches), bytes fetched, transient retries, and
    #: the local segment cache's hit/miss/eviction counts.
    gets: int = 0
    bytes_fetched: int = 0
    retries: int = 0
    cache_hits: int = 0
    cache_misses: int = 0
    cache_evictions: int = 0

    @classmethod
    def from_telemetry(cls, snapshot: "Optional[dict]") -> "SegmentStats":
        def total(name: str) -> float:
            metric = (snapshot or {}).get(name)
            if not metric:
                return 0.0
            return sum(s.get("value", 0.0) for s in metric["samples"])

        return cls(
            files=int(total("kta_segment_files_opened_total")),
            bytes_mapped=int(total("kta_segment_bytes_mapped_total")),
            records=int(total("kta_segment_records_total")),
            batches=int(total("kta_segment_batches_total")),
            gets=int(total("kta_segstore_gets_total")),
            bytes_fetched=int(total("kta_segstore_bytes_fetched_total")),
            retries=int(total("kta_segstore_retries_total")),
            cache_hits=int(total("kta_segstore_cache_hits_total")),
            cache_misses=int(total("kta_segstore_cache_misses_total")),
            cache_evictions=int(total("kta_segstore_cache_evictions_total")),
        )

    def as_dict(self) -> dict:
        doc = {
            "files": self.files,
            "bytes_mapped": self.bytes_mapped,
            "records": self.records,
            "batches": self.batches,
        }
        if self.gets:
            # Remote-tier block only when the scan actually spoke to an
            # object store — local cold scans keep the historical shape.
            doc["store_gets"] = self.gets
            doc["store_bytes_fetched"] = self.bytes_fetched
            doc["store_retries"] = self.retries
            doc["cache_hits"] = self.cache_hits
            doc["cache_misses"] = self.cache_misses
            doc["cache_evictions"] = self.cache_evictions
        return doc


@dataclasses.dataclass
class FollowStats:
    """Follow-service accounting extracted from a telemetry snapshot
    (`ScanResult.telemetry`): watermark polls, fold passes, refresh
    give-ups, and published report snapshots.  Consumed by the ``--stats``
    digest (report.py) and the report document's ``follow`` block
    (serve/follow.py layers the live cursor on top); empty
    (``polls == 0``) for batch scans, which never touch the follow
    instruments."""

    #: Watermark re-polls the service took at the head.
    polls: int
    #: Fold passes (initial catch-up + one per productive poll + final).
    passes: int
    #: Re-polls that exhausted the retry budget and kept the old snapshot.
    refresh_failures: int
    #: Report documents published for /report.json.
    report_snapshots: int

    @classmethod
    def from_telemetry(cls, snapshot: "Optional[dict]") -> "FollowStats":
        def total(name: str) -> int:
            metric = (snapshot or {}).get(name)
            if not metric:
                return 0
            return int(sum(s.get("value", 0.0) for s in metric["samples"]))

        return cls(
            polls=total("kta_follow_polls_total"),
            passes=total("kta_follow_passes_total"),
            refresh_failures=total("kta_watermark_refresh_failures_total"),
            report_snapshots=total("kta_report_snapshots_total"),
        )

    def as_dict(self) -> dict:
        return {
            "polls": self.polls,
            "passes": self.passes,
            "watermark_refresh_failures": self.refresh_failures,
            "report_snapshots": self.report_snapshots,
        }


@dataclasses.dataclass
class LossStats:
    """Log-mutation accounting extracted from a telemetry snapshot
    (`ScanResult.telemetry`): records/ranges the log mutated out from
    under the scan, split by reason (retention, truncation,
    resume-below-log-start, re-anchor-regressed), plus the epoch-fencing
    machinery's activity.  Consumed by the ``--stats`` digest (report.py);
    empty (``ranges == 0 and fences == 0``) for scans of a stable log."""

    #: Records lost, total across reasons.
    records: int
    #: Lost ranges booked, total across reasons (includes zero-record
    #: re-anchor-regressed bookings).
    ranges: int
    #: reason -> records lost to it.
    by_reason: "Dict[str, int]"
    #: FENCED/UNKNOWN_LEADER_EPOCH fetch answers (KIP-320 fences).
    fences: int
    #: OffsetForLeaderEpoch divergence probes run.
    divergence_checks: int
    #: Follow-mode end-watermark regressions held (stale replica heads).
    watermark_regressions: int

    @classmethod
    def from_telemetry(cls, snapshot: "Optional[dict]") -> "LossStats":
        def total(name: str) -> int:
            metric = (snapshot or {}).get(name)
            if not metric:
                return 0
            return int(sum(s.get("value", 0.0) for s in metric["samples"]))

        def by_label(name: str, label: str) -> "Dict[str, int]":
            metric = (snapshot or {}).get(name)
            out: "Dict[str, int]" = {}
            for s in (metric or {}).get("samples", []):
                key = s.get("labels", {}).get(label)
                if key is not None:
                    out[key] = out.get(key, 0) + int(s.get("value", 0.0))
            return out

        return cls(
            records=total("kta_log_lost_records_total"),
            ranges=total("kta_log_lost_ranges_total"),
            by_reason=by_label("kta_log_lost_records_total", "reason"),
            fences=total("kta_log_epoch_fences_total"),
            divergence_checks=total("kta_log_divergence_checks_total"),
            watermark_regressions=total(
                "kta_log_watermark_regressions_total"
            ),
        )

    def as_dict(self) -> dict:
        return {
            "records": self.records,
            "ranges": self.ranges,
            "by_reason": dict(self.by_reason),
            "epoch_fences": self.fences,
            "divergence_checks": self.divergence_checks,
            "watermark_regressions": self.watermark_regressions,
        }


@dataclasses.dataclass
class DispatchStats:
    """Superbatch-dispatch accounting extracted from a telemetry snapshot
    (`ScanResult.telemetry`): device dispatches, batches folded through
    them, and per-dispatch latency totals.  Consumed by the ``--stats``
    digest (report.py); empty (``dispatches == 0``) for per-batch scans,
    which never touch the dispatch instruments."""

    #: Superbatch dispatches launched (kta_superbatch_size sample count).
    dispatches: int
    #: Packed batches folded through them (kta_superbatch_size sum).
    batches: int
    #: (count, seconds) of the per-dispatch latency histogram.
    latency_count: int
    latency_seconds: float

    @property
    def mean_latency_ms(self) -> float:
        if not self.latency_count:
            return 0.0
        return (self.latency_seconds / self.latency_count) * 1e3

    @classmethod
    def from_telemetry(cls, snapshot: "Optional[dict]") -> "DispatchStats":
        def agg(name: str) -> "tuple[float, float]":
            metric = (snapshot or {}).get(name)
            if not metric:
                return 0.0, 0.0
            return (
                sum(s.get("count", 0.0) for s in metric["samples"]),
                sum(s.get("sum", 0.0) for s in metric["samples"]),
            )

        n_dispatch, n_batches = agg("kta_superbatch_size")
        lat_n, lat_s = agg("kta_dispatch_seconds")
        return cls(
            dispatches=int(n_dispatch),
            batches=int(n_batches),
            latency_count=int(lat_n),
            latency_seconds=lat_s,
        )


@dataclasses.dataclass
class StageDigest:
    """Per-stage drive-loop accounting extracted from a telemetry
    snapshot: the live-booked ``kta_stage_{seconds,records,bytes}_total``
    counters (utils/profiling.ScanProfile books them at every stage
    window exit).  This is the ONE stage-timings source for the
    ``--stats`` digest AND the scan doctor (obs/doctor.py) — under
    multi-controller it renders fleet totals, which the old in-process
    ``ScanProfile.summary()`` line never could."""

    #: stage -> (seconds, records, bytes), canonical pipeline order.
    stages: "Dict[str, tuple]"

    @classmethod
    def from_telemetry(cls, snapshot: "Optional[dict]") -> "StageDigest":
        from kafka_topic_analyzer_tpu.utils.profiling import STAGE_ORDER
        def by_stage(name: str) -> "Dict[str, float]":
            metric = (snapshot or {}).get(name)
            if not metric:
                return {}
            return {
                s["labels"]["stage"]: s["value"]
                for s in metric["samples"]
                if "stage" in s.get("labels", {})
            }

        secs = by_stage("kta_stage_seconds_total")
        recs = by_stage("kta_stage_records_total")
        byts = by_stage("kta_stage_bytes_total")
        rank = {name: i for i, name in enumerate(STAGE_ORDER)}
        ordered = sorted(
            secs, key=lambda s: (rank.get(s, len(STAGE_ORDER)), s)
        )
        return cls(
            stages={
                s: (secs[s], int(recs.get(s, 0)), int(byts.get(s, 0)))
                for s in ordered
                # The flight recorder creates zero-valued stage children
                # eagerly; an all-zero stage never ran — don't render it.
                if secs[s] or recs.get(s) or byts.get(s)
            }
        )


@dataclasses.dataclass
class TopicMetrics:
    """Finalized topic metrics.

    ``per_partition`` rows follow the partition-id order of ``partitions``;
    channels follow `COUNTER_CHANNELS`.  Scalars mirror the globals of
    ``MessageMetrics`` (src/metric.rs:20-26).
    """

    partitions: "list[int]"
    per_partition: np.ndarray  # int64[P, 7]
    earliest_ts_s: int
    latest_ts_s: int
    smallest_message: int  # U64_MAX when no sized message was seen
    largest_message: int
    overall_size: int
    overall_count: int
    #: Alive-key count from the reference-compatible fnv32 bitmap (``-c``).
    alive_keys: Optional[int] = None
    #: HLL estimate of distinct keys ever seen (new capability).
    distinct_keys_hll: Optional[float] = None
    #: Per-partition HLL estimates, one per `partitions` row.
    distinct_keys_hll_per_partition: "Optional[list[float]]" = None
    #: Per-partition exact distinct counts (CPU oracle referee).
    distinct_keys_exact_per_partition: "Optional[list[int]]" = None
    #: Exact distinct keys (CPU oracle only; referee for the HLL claim).
    distinct_keys_exact: Optional[int] = None
    #: Message-size quantiles (new capability).
    quantiles: Optional[QuantileSummary] = None
    #: Per-partition size quantiles, one entry per `partitions` row
    #: (BASELINE.json config 2).
    quantiles_per_partition: "Optional[list[QuantileSummary]]" = None
    #: Per-partition extremes (new capability; also enables exact row
    #: slicing for multi-topic fan-in): int64[P, 4] columns
    #: (earliest_ts, latest_ts, smallest, largest) with raw sentinels
    #: (I64_MAX/I64_MIN) where a partition never saw a record.
    per_partition_extremes: Optional[np.ndarray] = None
    #: Scan-start time used for the reference's earliest-message fallback
    #: (src/metric.rs:40); kept so row slices can re-derive global lines.
    init_now_s: Optional[int] = None

    # -- per-partition getters (reference getter semantics) ------------------

    def _row(self, partition: int) -> np.ndarray:
        return self.per_partition[self.partitions.index(partition)]

    def total(self, p: int) -> int:
        return int(self._row(p)[CH["total"]])

    def tombstones(self, p: int) -> int:
        return int(self._row(p)[CH["tombstones"]])

    def alive(self, p: int) -> int:
        return int(self._row(p)[CH["alive"]])

    def key_null(self, p: int) -> int:
        return int(self._row(p)[CH["key_null"]])

    def key_non_null(self, p: int) -> int:
        return int(self._row(p)[CH["key_non_null"]])

    def key_size_sum(self, p: int) -> int:
        return int(self._row(p)[CH["key_size_sum"]])

    def value_size_sum(self, p: int) -> int:
        return int(self._row(p)[CH["value_size_sum"]])

    def key_size_avg(self, p: int) -> int:
        return _avg(self.key_size_sum(p), self.alive(p))

    def value_size_avg(self, p: int) -> int:
        return _avg(self.value_size_sum(p), self.alive(p))

    def message_size_avg(self, p: int) -> int:
        return _avg(self.key_size_sum(p) + self.value_size_sum(p), self.alive(p))

    def dirty_ratio(self, p: int) -> float:
        """Percentage of tombstones, computed in float32 exactly like
        ``tombstones as f32 / (total as f32 / 100.0)`` (src/metric.rs:159-167)."""
        total = self.total(p)
        tomb = self.tombstones(p)
        if total > 0 and tomb > 0:
            return float(np.float32(tomb) / (np.float32(total) / np.float32(100.0)))
        return 0.0

    # -- global getters ------------------------------------------------------

    def smallest_message_reported(self) -> int:
        """0 when never set (src/metric.rs:177-183)."""
        return 0 if self.smallest_message == U64_MAX else self.smallest_message

    def extremes_decoded(self):
        """Per-partition extremes with sentinels decoded to None — the one
        place that knows the encoding: earliest/smallest sentinel is
        I64_MAX, latest is I64_MIN, and largest's 0 means "never set"
        exactly when smallest is the sentinel (tombstone-only partitions).
        Yields (partition, earliest|None, latest|None, smallest|None,
        largest|None)."""
        if self.per_partition_extremes is None:
            return
        for p, (e, l, s, g) in zip(
            self.partitions, self.per_partition_extremes.tolist()
        ):
            no_sized = s == I64_MAX_NP
            yield (
                p,
                None if e == I64_MAX_NP else e,
                None if l == I64_MIN_NP else l,
                None if no_sized else s,
                None if no_sized else g,
            )

    def to_dict(
        self,
        start_offsets: "Optional[Dict[int, int]]" = None,
        end_offsets: "Optional[Dict[int, int]]" = None,
    ) -> dict:
        """Machine-readable report (``--json``): the same numbers as the
        terminal report, keyed by name."""
        out: dict = {
            "overall": {
                "count": self.overall_count,
                "size_bytes": self.overall_size,
                "earliest_ts": self.earliest_ts_s,
                "latest_ts": self.latest_ts_s,
                "largest_message": self.largest_message,
                "smallest_message": self.smallest_message_reported(),
            },
            "partitions": {},
        }
        for p in self.partitions:
            row = {
                name: int(self._row(p)[i])
                for name, i in CH.items()
            }
            row["dirty_ratio"] = self.dirty_ratio(p)
            row["key_size_avg"] = self.key_size_avg(p)
            row["value_size_avg"] = self.value_size_avg(p)
            row["message_size_avg"] = self.message_size_avg(p)
            if start_offsets is not None:
                row["start_offset"] = start_offsets[p]
            if end_offsets is not None:
                row["end_offset"] = end_offsets[p]
            out["partitions"][str(p)] = row
        if self.alive_keys is not None:
            out["alive_keys"] = self.alive_keys
        if self.distinct_keys_hll is not None:
            out["distinct_keys_hll"] = self.distinct_keys_hll
        if self.distinct_keys_exact is not None:
            out["distinct_keys_exact"] = self.distinct_keys_exact
        if self.distinct_keys_hll_per_partition is not None:
            out["distinct_keys_hll_per_partition"] = {
                str(p): est
                for p, est in zip(self.partitions, self.distinct_keys_hll_per_partition)
            }
        if self.distinct_keys_exact_per_partition is not None:
            out["distinct_keys_exact_per_partition"] = {
                str(p): n
                for p, n in zip(
                    self.partitions, self.distinct_keys_exact_per_partition
                )
            }
        if self.quantiles is not None:
            out["size_quantiles"] = self.quantiles.as_dict()
        if self.quantiles_per_partition is not None:
            out["size_quantiles_per_partition"] = {
                str(p): q.as_dict()
                for p, q in zip(self.partitions, self.quantiles_per_partition)
            }
        if self.per_partition_extremes is not None:
            out["extremes_per_partition"] = {
                str(p): {
                    "earliest_ts": e,
                    "latest_ts": l,
                    "smallest": s,
                    "largest": g,
                }
                for p, e, l, s, g in self.extremes_decoded()
            }
        return out


I64_MAX_NP = np.iinfo(np.int64).max
I64_MIN_NP = np.iinfo(np.int64).min


def finalize_extremes(
    earliest_raw: int, latest_raw: int, smallest_raw: int, init_now_s: int
) -> "tuple[int, int, int]":
    """Map sentinel-initialized extremes to the reference's reporting values
    (single source of truth — backends and row slices all call this).

    The reference initializes ``earliest_message`` to *scan start time* and
    ``latest_message`` to epoch 0 (src/metric.rs:40-41), so the reported
    earliest is ``min(now, min_ts)`` and latest ``max(0, max_ts)``;
    ``smallest_message`` keeps u64::MAX until set (src/metric.rs:42).
    """
    earliest = (
        min(init_now_s, earliest_raw) if earliest_raw != I64_MAX_NP else init_now_s
    )
    latest = max(0, latest_raw) if latest_raw != I64_MIN_NP else 0
    smallest = U64_MAX if smallest_raw == I64_MAX_NP else smallest_raw
    return earliest, latest, smallest


def slice_rows(
    metrics: TopicMetrics,
    rows: "list[int]",
    partition_ids: "list[int]",
) -> TopicMetrics:
    """Project a multi-topic (fan-in) result onto one topic's rows.

    Exact for everything derived from per-row state (counters, extremes,
    overall sums); cross-topic merged sketches (alive bitmap, HLL,
    quantiles) cannot be un-merged and are dropped from the slice — they
    live in the fan-in union report.
    """
    if metrics.per_partition_extremes is None:
        raise ValueError("slice_rows needs per-partition extremes")
    per = metrics.per_partition[rows]
    ext = metrics.per_partition_extremes[rows]
    earliest_raw = int(ext[:, 0].min()) if len(rows) else I64_MAX_NP
    latest_raw = int(ext[:, 1].max()) if len(rows) else I64_MIN_NP
    smallest_raw = int(ext[:, 2].min()) if len(rows) else I64_MAX_NP
    largest = int(ext[:, 3].max()) if len(rows) else 0
    now = metrics.init_now_s if metrics.init_now_s is not None else 0
    earliest, latest, smallest = finalize_extremes(
        earliest_raw, latest_raw, smallest_raw, now
    )
    overall_size = int(
        per[:, CH["key_size_sum"]].sum() + per[:, CH["value_size_sum"]].sum()
    )
    overall_count = int(per[:, CH["total"]].sum())
    qpp = None
    if metrics.quantiles_per_partition is not None:
        # Per-partition sketches are per-row state — sliceable like extremes.
        qpp = [metrics.quantiles_per_partition[r] for r in rows]
    hpp = None
    if metrics.distinct_keys_hll_per_partition is not None:
        hpp = [metrics.distinct_keys_hll_per_partition[r] for r in rows]
    epp = None
    if metrics.distinct_keys_exact_per_partition is not None:
        epp = [metrics.distinct_keys_exact_per_partition[r] for r in rows]
    return TopicMetrics(
        partitions=list(partition_ids),
        per_partition=per,
        earliest_ts_s=earliest,
        latest_ts_s=latest,
        smallest_message=smallest,
        largest_message=largest,
        overall_size=overall_size,
        overall_count=overall_count,
        quantiles_per_partition=qpp,
        distinct_keys_hll_per_partition=hpp,
        distinct_keys_exact_per_partition=epp,
        per_partition_extremes=ext,
        init_now_s=metrics.init_now_s,
    )
