"""Partition-sharded parallel ingest for a SINGLE scan.

The reference consumes a topic strictly sequentially (src/kafka.rs:74-137),
and the single-device scan path used to as well: one ``batches()`` stream
feeding the device through a depth-2 prefetch thread, which caps a scan at
the one-core host-ingest ceiling (BENCH_NOTES.md round 5: ~3.2-3.7M rec/s).
The same round's multi-stream measurement showed the GIL share stays flat
as streams are added (the native fetch/decode/pack path releases the GIL),
so the way past the ceiling is more ingest *threads*, not faster ones.

This module runs N of them inside one scan:

- the partition set is sharded into N disjoint groups
  (``shard_partitions`` — same round-robin rule as the mesh's data-shard
  assignment, so skew balancing matches parallel/mesh.py; cold sources
  whose catalogs know exact per-partition record counts pass ``weights``
  and get a deterministic greedy-LPT balance instead);
- on a sharded mesh the same machinery runs PER CONTROLLER: each data
  row this process feeds gets its own fan-in over that row's partitions
  (``allocate_row_workers`` splits the controller's worker budget across
  its rows), so host-parallel ingest multiplies with device-parallel
  folding instead of replacing it (DESIGN.md §14).  ``wid_base``/
  ``label_prefix`` keep worker telemetry labels disjoint across a
  controller's pools and across controllers;
- each group gets a private ``source.batches()`` stream on its own worker
  thread (the wire layer guarantees per-stream connection privacy, so
  workers never share a socket), which also stages decode→remap→pack so
  the native GIL-releasing work parallelizes;
- workers push (batch, staged) pairs into bounded per-worker queues
  (backpressure = queue depth, the prefetch contract's ``prefetch_depth``);
- the consuming thread merges the queues in a DETERMINISTIC round-robin
  order (worker 0, 1, ..., N-1, 0, ... — exhausted workers drop out of the
  rotation).

Why the merge can be any fixed order at all: every fold the backend runs is
associative and commutative ACROSS partitions (counters add, min/max and
HLL registers merge by max, DDSketch rows add), and the only
order-sensitive fold — last-writer-wins alive-key tracking — is
order-sensitive strictly WITHIN a partition, whose records all travel in
one worker's stream in offset order.  So the N-worker scan's ``ScanResult``
is byte-identical to the 1-worker scan's (DESIGN.md §11), checkpoints stay
fold-consistent per partition (each partition lives in exactly one worker,
``next_offset`` semantics unchanged), and the chaos/corruption policies of
PRs 1-3 compose per worker: degraded/corrupt partitions aggregate on the
shared source exactly as they do for sharded multi-stream scans.

Thread-safety rule for this module (enforced by tools/lint.sh): worker
code paths (anything that runs on an ``_IngestWorker`` thread) never
mutate scan-shared dict/list/set state without holding a lock — shared
mutable state is either confined to the consumer thread (the merge loop)
or crosses threads only through the per-worker ``queue.Queue``.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Dict, Iterator, List, Optional, Tuple

from kafka_topic_analyzer_tpu.io.source import RecordSource
from kafka_topic_analyzer_tpu.obs import metrics as obs_metrics
from kafka_topic_analyzer_tpu.packing import PackedRow
from kafka_topic_analyzer_tpu.records import RecordBatch

_SENTINEL = object()


class _Error:
    """Exception envelope (mirrors utils/prefetch.py): raised on the
    consumer side at the failed worker's position in the rotation."""

    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


def shard_partitions(
    partitions: List[int],
    workers: int,
    weights: "Optional[Dict[int, int]]" = None,
) -> List[List[int]]:
    """Disjoint partition groups, one per worker.

    Without ``weights``: round-robin — LITERALLY the mesh data axis'
    assignment rule (delegated, so a future skew-aware change there cannot
    desynchronize worker sharding from mesh sharding).

    With ``weights`` (partition -> expected records; the cold segment
    path's catalog knows these exactly — SegmentFileSource.
    partition_record_counts): deterministic greedy LPT — partitions
    descend by weight (ties by id) onto the least-loaded group (ties by
    group index), so a skewed catalog doesn't leave workers idle behind
    one hot partition.  The grouping stays a pure function of the inputs,
    and ANY disjoint grouping folds byte-identically (DESIGN.md §11 — a
    partition's records still travel one worker's stream in offset order).

    Empty groups are dropped (callers clamp ``workers`` to the partition
    count first, but a caller that does not must still get only live
    workers).

    The fleet scheduler reuses this exact rule one level up
    (fleet/scheduler.py::plan_waves): topics descend by lag/partition
    weight onto the least-loaded admission wave — the grouping algebra is
    the same whether the items are partitions or whole topics."""
    if workers < 1:
        raise ValueError("workers must be >= 1")
    if weights:
        loads = [0] * workers
        groups: "List[List[int]]" = [[] for _ in range(workers)]
        for p in sorted(partitions, key=lambda p: (-weights.get(p, 0), p)):
            w = min(range(workers), key=lambda i: (loads[i], i))
            groups[w].append(p)
            loads[w] += weights.get(p, 0)
        # Offset order within a worker's stream is per partition either
        # way; ascending ids keep the group layout readable in --stats.
        return [sorted(g) for g in groups if g]
    from kafka_topic_analyzer_tpu.parallel.mesh import assign_partitions

    return [g for g in assign_partitions(partitions, workers) if g]


def allocate_row_workers(
    budget: int, row_counts: "Dict[int, int]"
) -> "Dict[int, int]":
    """Split one controller's ingest-worker budget across its data rows.

    ``row_counts`` maps data row -> partition count for the rows THIS
    controller feeds.  Every non-empty row needs at least one stream (the
    collective round loop pulls one batch per row per round), so each
    gets 1 even when ``budget`` is smaller; the remaining budget goes one
    worker at a time to the row with the most partitions per worker (ties
    by row index), clamped at the row's partition count — a worker beyond
    it would own an empty group.  Pure function of the inputs, so every
    controller (and every rerun) allocates identically.  (The fleet
    scheduler reuses this rule to split the global worker budget across
    an admitted wave of topic scans — fleet/scheduler.py.)"""
    if budget < 1:
        raise ValueError("worker budget must be >= 1")
    alloc = {r: (1 if n > 0 else 0) for r, n in row_counts.items()}
    spent = sum(alloc.values())
    while spent < budget:
        best = None
        for r in sorted(row_counts):
            n, w = row_counts[r], alloc[r]
            if w == 0 or w >= n:
                continue
            ratio = n / w
            if best is None or ratio > best[0]:
                best = (ratio, r)
        if best is None:
            break  # every row saturated at its partition count
        alloc[best[1]] += 1
        spent += 1
    return alloc


class _IngestWorker(threading.Thread):
    """One worker: a private ``source.batches()`` stream for one partition
    group, staged (pack + host→device transfer start) on this thread, fed
    into a bounded queue.  Mirrors the prefetch contract: errors travel to
    the consumer as `_Error`, exhaustion as a sentinel, and close-on-exit
    drains the thread AND closes the underlying generator."""

    def __init__(
        self,
        wid: "int | str",
        source: RecordSource,
        batch_size: int,
        group: List[int],
        start_at: "Optional[Dict[int, int]]",
        stage: "Optional[Callable[[RecordBatch], object]]",
        depth: int,
        cancel: threading.Event,
        sink=None,
    ):
        super().__init__(daemon=True, name=f"kta-ingest-{wid}")
        self.wid = wid
        self.group = list(group)
        self.queue: "queue.Queue[object]" = queue.Queue(maxsize=max(depth, 1))
        self._stage = stage
        self._cancel = cancel
        # The generator object is created here (cheap — the body only runs
        # on first next()) so close() can reach it even if the thread never
        # gets scheduled; only this thread ever *advances* it.
        # A fused sink (private to this worker — sinks are single-threaded
        # state) makes the stream yield pre-packed, pre-staged PackedRow
        # items; `stage` then never runs for them.
        self._it = source.batches(
            batch_size, partitions=self.group, start_at=start_at,
            **({"sink": sink} if sink is not None else {}),
        )
        self._source_closed = False
        self._stall = obs_metrics.INGEST_WORKER_STALL_SECONDS.labels(
            worker=wid
        )
        self._active = obs_metrics.INGEST_WORKER_ACTIVE_SECONDS.labels(
            worker=wid
        )

    def _put(self, item: object) -> bool:
        """Bounded put; gives up when the consumer cancelled.  Time spent
        blocked on a full queue is the worker's backpressure stall — booked
        per worker so ``--stats``/Prometheus show which shard outruns the
        device."""
        if self._cancel.is_set():
            # Checked BEFORE the fast path (mirroring prefetch._put): an
            # aborting close() drains the queues, and a cancelled worker
            # must not slip items into the fresh space and keep fetching/
            # staging dead work for up to `depth` more rounds.
            return False
        try:
            self.queue.put_nowait(item)
            return True
        except queue.Full:
            pass
        t0 = time.perf_counter()
        try:
            while not self._cancel.is_set():
                try:
                    self.queue.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False
        finally:
            self._stall.inc(time.perf_counter() - t0)

    def run(self) -> None:
        # Lifetime booking brackets the whole stream: busy fraction =
        # (active - stall) / active stays honest for workers whose
        # partitions drain early (obs/doctor.py reads both counters).
        t_run = time.perf_counter()
        try:
            for batch in self._it:
                if isinstance(batch, PackedRow):
                    staged = batch.staged  # fused: staged by the sink
                else:
                    staged = (
                        self._stage(batch) if self._stage is not None else None
                    )
                if not self._put((batch, staged)):
                    return  # cancelled; finally closes the source stream
        except BaseException as e:
            self._put(_Error(e))
            return
        finally:
            self._active.inc(time.perf_counter() - t_run)
            if self._cancel.is_set():
                self.close_source()
        self._put(_SENTINEL)

    def close_source(self) -> None:
        """Close the underlying batches() generator (GeneratorExit unwinds
        its finally blocks, releasing the stream's private connections).
        Called from the owning thread on cancel, or from ``close()`` after
        the thread has exited (a generator can only be closed while no
        thread is executing it)."""
        if self._source_closed:
            return
        self._source_closed = True
        close = getattr(self._it, "close", None)
        if close is not None:
            try:
                close()
            except Exception:  # a dying stream must not mask the real error
                pass


class ParallelIngest:
    """Fan-in over N ingest workers with a deterministic round-robin merge.

    Iterating yields ``(batch, staged)`` exactly like the staged prefetch
    stream the single-worker path consumes, so the engine's bookkeeping
    loop is identical for N=1 and N>1.  ``close()`` mirrors the prefetch
    close-on-exit contract: cancel, drain, join, and release every
    worker's underlying stream — the engine calls it from its ``finally``
    so errors and interrupts never leak threads or connections.
    """

    def __init__(
        self,
        source: RecordSource,
        batch_size: int,
        groups: List[List[int]],
        start_at: "Optional[Dict[int, int]]" = None,
        stage: "Optional[Callable[[RecordBatch], object]]" = None,
        depth: int = 2,
        wid_base: int = 0,
        label_prefix: str = "",
        sink_factory: "Optional[Callable[[], object]]" = None,
    ):
        """``wid_base``/``label_prefix`` exist for multi-pool scans: a
        sharded-mesh controller runs ONE fan-in per data row it feeds
        (engine.py), and worker telemetry labels must stay disjoint —
        across that controller's pools (``wid_base`` continues the worker
        numbering from the previous row's pool) and across controllers
        (``label_prefix`` carries the controller index, e.g. ``"c1."``,
        so the gather_telemetry merge unions instead of summing unrelated
        workers into one sample)."""
        if not groups:
            raise ValueError("parallel ingest needs at least one group")
        self._cancel = threading.Event()
        self.workers = [
            _IngestWorker(
                f"{label_prefix}{wid_base + w}", source, batch_size, g,
                start_at, stage, depth, self._cancel,
                sink=sink_factory() if sink_factory is not None else None,
            )
            for w, g in enumerate(groups)
        ]
        self._depth_gauge = obs_metrics.INGEST_QUEUE_DEPTH.labels(
            pool=f"{label_prefix}{wid_base}"
        )
        #: Rotation position and per-worker liveness for the merge.
        self._rr = 0
        self._alive = [True] * len(self.workers)
        self._alive_count = len(self.workers)
        self._closed = False
        for w in self.workers:
            w.start()

    def __iter__(self) -> "ParallelIngest":
        return self

    def __next__(self) -> "Tuple[RecordBatch, object]":
        # Deterministic rotation: always poll workers in index order,
        # blocking on each worker's own queue until it produces or
        # finishes.  Given deterministic per-worker streams this makes the
        # merged fold order a pure function of the inputs — N-worker runs
        # reproduce each other exactly, not just statistically.
        while self._alive_count:
            w = self.workers[self._rr]
            if not self._alive[self._rr]:
                self._rr = (self._rr + 1) % len(self.workers)
                continue
            item = w.queue.get()
            if item is _SENTINEL:
                self._alive[self._rr] = False
                self._alive_count -= 1
                self._rr = (self._rr + 1) % len(self.workers)
                continue
            if isinstance(item, _Error):
                # One worker died: the scan aborts (the engine's failure
                # path snapshots committed progress and its finally calls
                # close(), cancelling the surviving workers).
                self._alive[self._rr] = False
                self._alive_count -= 1
                raise item.exc
            self._rr = (self._rr + 1) % len(self.workers)
            batch, staged = item
            obs_metrics.INGEST_WORKER_RECORDS.labels(worker=w.wid).inc(
                batch.num_valid
            )
            self._depth_gauge.set(self.queue_depth())
            return batch, staged
        raise StopIteration

    def queue_depth(self) -> int:
        """Total staged batches waiting in the fan-in (all workers)."""
        return sum(w.queue.qsize() for w in self.workers)

    def close(self) -> None:
        """Stop every worker and release their streams.  Idempotent."""
        if self._closed:
            return
        self._closed = True
        self._cancel.set()
        # Drain so blocked workers observe the cancel promptly (their puts
        # poll the event between bounded-put timeouts).
        for w in self.workers:
            try:
                while True:
                    w.queue.get_nowait()
            except queue.Empty:
                pass
        # One SHARED deadline across all joins: N workers blocked in
        # broker I/O must cost ~5s of shutdown latency total, not N x 5s.
        deadline = time.monotonic() + 5.0
        for w in self.workers:
            w.join(timeout=max(0.0, deadline - time.monotonic()))
        for w in self.workers:
            if not w.is_alive():
                # The thread exited without running its cancel-path close
                # (error, exhaustion, or cancel won the race after the
                # loop): close the generator from here — safe now that no
                # thread is executing it.
                w.close_source()
        self._depth_gauge.set(0)


def iter_staged(
    it: "Iterator[RecordBatch]",
    stage: "Optional[Callable[[RecordBatch], object]]",
) -> "Iterator[Tuple[RecordBatch, object]]":
    """Single-worker staging adapter: the same (batch, staged) item shape
    ParallelIngest yields, for the N=1 path's prefetch worker.  Fused
    PackedRow items arrive pre-staged by their sink; `stage` never runs
    for them."""
    for b in it:
        if isinstance(b, PackedRow):
            yield b, b.staged
        else:
            yield b, (stage(b) if stage is not None else None)
