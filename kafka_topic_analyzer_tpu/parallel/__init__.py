"""Data/space parallel scale-out over a jax.sharding.Mesh.

The reference is single-threaded end to end (SURVEY.md §2.4) — its only
concurrency lives inside librdkafka's broker threads.  Scale-out here is the
genuinely new design:

- **'data' axis** — Kafka partitions are the natural data-parallel axis.
  Each data shard owns a disjoint set of partitions and folds its own batches
  into a device-local `AnalyzerState` with *no per-step collectives*; states
  merge once at finalize with XLA collectives over ICI (``psum`` for sums,
  ``pmin``/``pmax`` for extremes, all-gather+OR for the alive bitmap).
  This works because every accumulator is associative and commutative, and
  because a Kafka key lives in exactly one partition (records.py contract).
- **'space' axis** — the alive-key bitmap's slot space (up to 512 MiB packed
  bits) is model-parallel sharded: each space shard masks updates to its slot
  range, again collective-free per step.

Multi-host runs extend the same mesh over DCN via ``jax.distributed`` — the
mesh shape is the only thing that changes (SURVEY.md §5.8).
"""

from kafka_topic_analyzer_tpu.parallel.mesh import make_mesh  # noqa: F401
from kafka_topic_analyzer_tpu.parallel.sharded import ShardedTpuBackend  # noqa: F401
