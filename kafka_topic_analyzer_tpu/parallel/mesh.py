"""Mesh construction and partition→shard assignment."""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
from jax.sharding import Mesh

DATA_AXIS = "data"
SPACE_AXIS = "space"


def initialize_distributed(spec: str) -> None:
    """Multi-host bring-up: ``"coordinator:port,process_id,num_processes"``.

    After this, `jax.devices()` spans every host's chips and the same
    (data, space) mesh extends over DCN — the collective merges in
    sharded.py are unchanged, XLA routes them across hosts
    (SURVEY.md §5.8).  Each host's engine should feed only its own data
    shards' partitions (`assign_partitions` over the global shard count).
    """
    parts = spec.split(",")
    if len(parts) != 3:
        raise ValueError(
            f"bad --distributed {spec!r}: expected coordinator:port,pid,nprocs"
        )
    coordinator, pid, nprocs = parts[0], int(parts[1]), int(parts[2])
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=nprocs,
        process_id=pid,
    )


def make_mesh(
    data: int, space: int = 1, devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    """Build a (data, space) mesh from the first data*space local devices.

    On a v5e-8 slice ``make_mesh(8)`` data-shards all cores over ICI;
    ``make_mesh(4, 2)`` additionally splits the bitmap slot space.
    """
    devs = list(devices) if devices is not None else jax.devices()
    need = data * space
    if len(devs) < need:
        raise ValueError(f"mesh {data}x{space} needs {need} devices, have {len(devs)}")
    import numpy as np

    grid = np.array(devs[:need]).reshape(data, space)
    return Mesh(grid, (DATA_AXIS, SPACE_AXIS))


def local_data_rows(mesh: Mesh) -> List[int]:
    """Data-axis rows of ``mesh`` owned entirely by THIS process.

    The turnkey multi-host contract (SURVEY.md §5.8): every host runs the
    same CLI command; each host's engine feeds exactly the data shards
    whose devices it hosts, so no manual per-host partition wiring is
    needed.  A data row that straddles processes has no single feeding
    host — reject it with the fix (data_shards divisible by process
    count) rather than silently dropping records.
    """
    me = jax.process_index()
    grid = mesh.devices
    rows = []
    for d in range(grid.shape[0]):
        owners = {dev.process_index for dev in grid[d].flat}
        if owners == {me}:
            rows.append(d)
        elif me in owners:
            raise ValueError(
                f"mesh data row {d} spans processes {sorted(owners)}; "
                "choose data_shards divisible by the process count so "
                "every data shard has one feeding host"
            )
    return rows


def assign_partitions(partitions: List[int], data_shards: int) -> List[List[int]]:
    """Round-robin partitions over data shards (shard d gets partitions[d::D]).

    Any partition→shard assignment is correct (states merge associatively);
    round-robin balances retained-message skew reasonably without needing
    per-partition sizes up front.
    """
    parts = sorted(partitions)
    return [parts[d::data_shards] for d in range(data_shards)]
