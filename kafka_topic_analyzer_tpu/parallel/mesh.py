"""Mesh construction and partition→shard assignment."""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
from jax.sharding import Mesh

DATA_AXIS = "data"
SPACE_AXIS = "space"


def make_mesh(
    data: int, space: int = 1, devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    """Build a (data, space) mesh from the first data*space local devices.

    On a v5e-8 slice ``make_mesh(8)`` data-shards all cores over ICI;
    ``make_mesh(4, 2)`` additionally splits the bitmap slot space.
    """
    devs = list(devices) if devices is not None else jax.devices()
    need = data * space
    if len(devs) < need:
        raise ValueError(f"mesh {data}x{space} needs {need} devices, have {len(devs)}")
    import numpy as np

    grid = np.array(devs[:need]).reshape(data, space)
    return Mesh(grid, (DATA_AXIS, SPACE_AXIS))


def assign_partitions(partitions: List[int], data_shards: int) -> List[List[int]]:
    """Round-robin partitions over data shards (shard d gets partitions[d::D]).

    Any partition→shard assignment is correct (states merge associatively);
    round-robin balances retained-message skew reasonably without needing
    per-partition sizes up front.
    """
    parts = sorted(partitions)
    return [parts[d::data_shards] for d in range(data_shards)]
