"""Sharded TPU backend: shard_map update steps + collective finalize.

The record stream is sharded over BOTH mesh axes: partitions are assigned
to data rows (parallel/mesh.py::assign_partitions), and each data row's
batch is split into `space_shards` contiguous chunks — one per space
shard, `batch_size / space_shards` records each.  Host→device transfer
and per-device reduction work therefore scale down with the full device
count, not just the data axis (with the batch replicated over 'space',
the old layout, every added space shard re-transferred and re-reduced
the whole batch).

Per-step communication is a single small ICI collective: the alive
bitmap's host-deduped (slot, aliveness) pairs are all_gathered over
'space' and applied in source-chunk order, because a slot's updates may
straddle chunk boundaries and last-writer-wins is order-sensitive
(backends/step.py).  Under alive-pair COMPACTION (the wire-v5 default —
``AnalyzerConfig.compact_alive``, DESIGN §19) even that disappears: the
host LWW-merges each data row's pairs per DISPATCH into one bounded
table whose ``P(data, None)`` spec replicates it over 'space' at
transfer time, and each space shard applies its slot range once after
the (scanned) step — no per-step collective remains.  Everything else
is chunk-local per step; the remaining axes reduce once, in finalize:

- counters / byte sums / counts : ``psum``   over ('data', 'space')
- timestamp & size extremes     : ``pmin`` / ``pmax`` over ('data', 'space')
- HLL registers                 : ``pmax``  over ('data', 'space')
- DDSketch bucket counts        : ``psum``  over ('data', 'space')
- alive bitmap                  : ``all_gather`` over 'data' + OR-reduce
                                  (bit-OR has no wired-in collective; the
                                  gather is one-shot), popcount, then
                                  ``psum`` over 'space'

State layout: metrics / HLL / DDSketch leaves gain a leading device axis
of size D·S sharded over ('data', 'space') jointly; the bitmap keeps a
leading 'data' axis with its word axis sharded over 'space' (slot-range
ownership).  The update step is jitted with the state donated, exactly
like the single-device path.
"""

from __future__ import annotations

import functools
from typing import List, Optional

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from kafka_topic_analyzer_tpu.backends.base import (
    DispatchQueue,
    MetricBackend,
    instrument_steps,
)
from kafka_topic_analyzer_tpu.backends.finalize import metrics_from_state
from kafka_topic_analyzer_tpu.backends.step import (
    analyzer_step,
    apply_pair_table,
    superbatch_fold,
)
from kafka_topic_analyzer_tpu.config import AnalyzerConfig, DispatchConfig
from kafka_topic_analyzer_tpu.packing import (
    batch_alive_pairs,
    pack_chunks,
    pack_pair_table,
    pair_table_capacity,
    unpack_device,
    unpack_pair_table_device,
)
from kafka_topic_analyzer_tpu.jax_support import jnp, lax, shard_map
from kafka_topic_analyzer_tpu.models.compaction import AliveBitmapState
from kafka_topic_analyzer_tpu.models.message_metrics import MessageMetricsState
from kafka_topic_analyzer_tpu.models.state import AnalyzerState
from kafka_topic_analyzer_tpu.obs import metrics as obs_metrics
from kafka_topic_analyzer_tpu.ops.bitmap import bitmap_num_words
from kafka_topic_analyzer_tpu.parallel.mesh import DATA_AXIS, SPACE_AXIS, make_mesh
from kafka_topic_analyzer_tpu.records import RecordBatch
from kafka_topic_analyzer_tpu.results import TopicMetrics
from kafka_topic_analyzer_tpu.utils.timefmt import utc_now_seconds


#: Leading device axis of the record-parallel state leaves: sharded over
#: data AND space jointly (D·S rows), since each (data, space) device folds
#: its own record chunk.
_DEV = (DATA_AXIS, SPACE_AXIS)


def _state_specs(config: AnalyzerConfig) -> AnalyzerState:
    """PartitionSpec pytree matching the stacked AnalyzerState."""
    metrics = MessageMetricsState(
        per_partition=P(_DEV),
        earliest_s=P(_DEV),
        latest_s=P(_DEV),
        smallest=P(_DEV),
        largest=P(_DEV),
        overall_size=P(_DEV),
        overall_count=P(_DEV),
    )
    alive = (
        AliveBitmapState(words=P(DATA_AXIS, SPACE_AXIS))
        if config.count_alive_keys
        else None
    )
    from kafka_topic_analyzer_tpu.models.compaction import HLLState
    from kafka_topic_analyzer_tpu.models.quantiles import DDSketchState

    hll = HLLState(regs=P(_DEV)) if config.enable_hll else None
    quantiles = DDSketchState(counts=P(_DEV)) if config.enable_quantiles else None
    return AnalyzerState(metrics=metrics, alive=alive, hll=hll, quantiles=quantiles)


def _global_put(x: np.ndarray, mesh, spec) -> jax.Array:
    """Place a host-replicated numpy value as a global sharded array.

    `jax.device_put` only accepts shardings whose devices are all
    addressable; under multi-controller (`jax.distributed`) each process
    holds the same host value, so materializing per-shard via callback
    builds the same global array on every process."""
    sharding = NamedSharding(mesh, spec)
    return jax.make_array_from_callback(x.shape, sharding, lambda idx: x[idx])


def _stacked_init(config: AnalyzerConfig, mesh) -> AnalyzerState:
    """Host-built stacked state (leading device axis), placed with shardings."""
    d = config.data_shards
    dev = d * config.space_shards  # record-parallel leaves: one row per device
    p = config.num_partitions
    i64max = np.iinfo(np.int64).max
    i64min = np.iinfo(np.int64).min
    metrics = MessageMetricsState(
        per_partition=np.zeros((dev, p, 7), np.int64),
        earliest_s=np.full((dev, p), i64max, np.int64),
        latest_s=np.full((dev, p), i64min, np.int64),
        smallest=np.full((dev, p), i64max, np.int64),
        largest=np.zeros((dev, p), np.int64),
        overall_size=np.zeros((dev,), np.int64),
        overall_count=np.zeros((dev,), np.int64),
    )
    alive = None
    if config.count_alive_keys:
        w_local = bitmap_num_words(config.alive_bitmap_bits, config.space_shards)
        alive = AliveBitmapState(
            words=np.zeros((d, w_local * config.space_shards), np.uint32)
        )
    hll = None
    if config.enable_hll:
        from kafka_topic_analyzer_tpu.models.compaction import HLLState

        rows = config.num_partitions if config.distinct_keys_per_partition else 1
        hll = HLLState(regs=np.zeros((dev, rows, config.hll_m), np.int32))
    quantiles = None
    if config.enable_quantiles:
        from kafka_topic_analyzer_tpu.models.quantiles import DDSketchState
        from kafka_topic_analyzer_tpu.ops.ddsketch import ddsketch_num_buckets

        rows = config.num_partitions if config.quantiles_per_partition else 1
        quantiles = DDSketchState(
            counts=np.zeros(
                (dev, rows, ddsketch_num_buckets(config.quantile_buckets)), np.int64
            )
        )
    state = AnalyzerState(metrics=metrics, alive=alive, hll=hll, quantiles=quantiles)
    specs = _state_specs(config)
    return jax.tree.map(lambda x, s: _global_put(x, mesh, s), state, specs)


class PackedShard:
    """One data row's batch already packed into its space chunks
    ``[S, chunk_nbytes]`` by ``ShardedTpuBackend.prepare_shard`` — the
    sharded counterpart of ``backends.tpu.StagedBatch``.  Just a typed
    array: all bookkeeping stays with the decoded batch the engine holds.

    ``pairs`` rides the compacted alive path: the row batch's LWW
    ``(slot u32[n], flag u8[n])`` host arrays in stream order (per-chunk
    deduped on the fused path — the dispatch merge resolves cross-chunk
    duplicates), None when compaction is off."""

    __slots__ = ("chunks", "pairs")

    def __init__(self, chunks: np.ndarray, pairs=None):
        self.chunks = chunks
        self.pairs = pairs


@instrument_steps
class ShardedTpuBackend(MetricBackend):
    """Multi-device backend over a (data, space) mesh.

    Feed it via `update_shards` with one batch per data shard (the engine
    routes each partition to a fixed shard — records.py ordering contract).
    `update` also works for convenience and splits a mixed batch by the
    partition→shard assignment.
    """

    def __init__(
        self,
        config: AnalyzerConfig,
        mesh=None,
        init_now_s: "int | None" = None,
        use_native: bool = True,
        dispatch: "DispatchConfig | None" = None,
    ):
        super().__init__(config)
        self.init_now_s = utc_now_seconds() if init_now_s is None else init_now_s
        self.mesh = mesh if mesh is not None else make_mesh(*config.mesh_shape)
        if dict(zip(self.mesh.axis_names, self.mesh.devices.shape)) != {
            DATA_AXIS: config.data_shards,
            SPACE_AXIS: config.space_shards,
        }:
            raise ValueError("mesh shape does not match config.mesh_shape")
        self.state = _stacked_init(config, self.mesh)
        self._specs = _state_specs(config)
        # Packed buffers: one CHUNK (batch_size / space_shards records) per
        # (data, space) device — shape (D, S, chunk_nbytes).
        self._buf_sharding = NamedSharding(self.mesh, P(DATA_AXIS, SPACE_AXIS))
        self._row_sharding = NamedSharding(self.mesh, P(DATA_AXIS))
        import dataclasses as _dc

        if config.batch_size % config.space_shards:
            raise ValueError(
                "batch_size must divide evenly into space_shards chunks"
            )
        if (
            config.use_pallas_counters
            and config.wire_format == 4
            and config.chunk_size % 1024
        ):
            # v4 MXU-kernel block constraint only; the v5 table merge
            # (pallas_counters_merge) pads any shape internally.
            raise ValueError(
                "use_pallas_counters requires a per-space-shard chunk "
                "(batch_size / space_shards) that is a multiple of 1024"
            )
        self._chunk_config = (
            _dc.replace(config, batch_size=config.chunk_size)
            if config.space_shards > 1
            else config
        )
        self.use_native = use_native
        # Multi-controller support: the data rows THIS process feeds, and
        # whether device transfers must go through the process-local API.
        from kafka_topic_analyzer_tpu.parallel.mesh import local_data_rows

        self.local_rows = local_data_rows(self.mesh)
        self._multiprocess = jax.process_count() > 1
        rows = self.local_rows
        self._rows_contiguous = rows == list(
            range(rows[0], rows[0] + len(rows))
        ) if rows else True
        #: Per-process snapshots assemble contiguous local row blocks; a
        #: mesh that interleaves process ownership along the data axis
        #: can't snapshot — the engine degrades with a warning instead of
        #: crashing at the first snapshot interval.
        self.snapshot_capable = not self._multiprocess or self._rows_contiguous

        chunk_config = self._chunk_config
        # Compacted alive path (DESIGN.md §19): each data row ships ONE
        # LWW-merged pair table per dispatch, replicated over the space
        # axis by its P(data, None) spec — each space shard applies its
        # slot range AFTER the scan, so the per-step pair all_gather over
        # 'space' disappears from the compacted step entirely.
        self._compact = config.compact_alive
        self._pair_cap1 = (
            pair_table_capacity(config, config.batch_size, 1)
            if self._compact
            else 0
        )
        self._pair_sharding = NamedSharding(self.mesh, P(DATA_AXIS, None))

        def _step_body(state, bufs, ptabs=None):
            local = jax.tree.map(lambda x: x[0], state)
            arrays = unpack_device(bufs[0, 0], chunk_config)
            space_idx = lax.axis_index(SPACE_AXIS)
            new = analyzer_step(
                local,
                arrays,
                chunk_config,
                space_index=space_idx,
                space_axis=SPACE_AXIS,
            )
            if ptabs is not None:
                new = apply_pair_table(
                    new,
                    unpack_pair_table_device(
                        ptabs[0], config, self._pair_cap1
                    ),
                    config,
                    space_index=space_idx,
                )
            return jax.tree.map(lambda x: x[None], new)

        # The Pallas counter kernel declares its varying axes (vma) so the
        # static checker stays on for compiled TPU runs; jax's pallas
        # INTERPRETER (the CPU fallback the tests/virtual meshes use)
        # internally builds replicated constants that trip the checker —
        # a jax-side limitation its own error message acknowledges — so
        # only that combination relaxes it.
        relax_vma = (
            config.use_pallas_counters and jax.default_backend() == "cpu"
        )
        step = shard_map(
            _step_body,
            mesh=self.mesh,
            in_specs=(
                (self._specs, P(DATA_AXIS, SPACE_AXIS), P(DATA_AXIS, None))
                if self._compact
                else (self._specs, P(DATA_AXIS, SPACE_AXIS))
            ),
            out_specs=self._specs,
            check_vma=not relax_vma,
        )
        self._step = jax.jit(step, donate_argnums=(0,))
        self._merge = jax.jit(self._build_merge())

        # Superbatch dispatch layer: K rounds of per-row chunk stacks
        # folded by ONE scanned collective dispatch (state donated once
        # per superbatch).  The scanned axis is the ROUND axis: scan step
        # k replays exactly what the per-round collective step would have
        # done at round k — including the alive-pair all_gather over
        # 'space', which runs once per scan step in step order, so
        # last-writer-wins application order is preserved across the
        # scanned axis and results stay byte-identical.
        self.dispatch_config = dispatch if dispatch is not None else DispatchConfig()
        self.superbatch_k = self.dispatch_config.resolve(config.batch_size)
        self.dispatch_depth = self.dispatch_config.depth
        if self.superbatch_k > 1:
            self._pair_cap_k = (
                pair_table_capacity(
                    config, config.batch_size, self.superbatch_k
                )
                if self._compact
                else 0
            )

            def _superstep_body(state, bufs, ptabs=None):
                # bufs block: [K, 1, 1, chunk_nbytes] per (data, space)
                # device (in_spec puts the round axis on no mesh axis).
                local = jax.tree.map(lambda x: x[0], state)
                space_idx = lax.axis_index(SPACE_AXIS)
                local, n_valid = superbatch_fold(
                    local,
                    bufs,
                    lambda buf: unpack_device(buf[0, 0], chunk_config),
                    chunk_config,
                    space_index=space_idx,
                    space_axis=SPACE_AXIS,
                    # Compacted path: the row's K rounds merged into one
                    # table, applied once after the scanned rounds — the
                    # per-scan-step pair all_gather is gone.
                    pairs=(
                        unpack_pair_table_device(
                            ptabs[0], config, self._pair_cap_k
                        )
                        if ptabs is not None
                        else None
                    ),
                )
                # Completion token: per-device [1, 1] block → global
                # [D, S] (no extra collective; any leaf syncs the step).
                token = jnp.sum(n_valid).astype(jnp.int32).reshape(1, 1)
                return jax.tree.map(lambda x: x[None], local), token

            superstep = shard_map(
                _superstep_body,
                mesh=self.mesh,
                in_specs=(
                    (
                        self._specs,
                        P(None, DATA_AXIS, SPACE_AXIS),
                        P(DATA_AXIS, None),
                    )
                    if self._compact
                    else (self._specs, P(None, DATA_AXIS, SPACE_AXIS))
                ),
                out_specs=(self._specs, P(DATA_AXIS, SPACE_AXIS)),
                check_vma=not relax_vma,
            )
            self._superstep = jax.jit(superstep, donate_argnums=(0,))
            self._superbuf_sharding = NamedSharding(
                self.mesh, P(None, DATA_AXIS, SPACE_AXIS)
            )
            self._queue = DispatchQueue(self.dispatch_depth)
            from kafka_topic_analyzer_tpu.packing import (
                SuperbatchStager,
                packed_nbytes,
            )

            # One collective round stages as [local_rows, S, chunk_nbytes];
            # the ring assembles K of them in a single pass (no
            # stack-then-restack copy) into transfer-quiescent memory.
            self._stager = SuperbatchStager(
                (
                    len(self.local_rows),
                    config.space_shards,
                    packed_nbytes(self._chunk_config, config.chunk_size),
                ),
                self.superbatch_k,
                self.dispatch_depth,
            )
            self._empty_chunks: "Optional[np.ndarray]" = None

    # -- merge ---------------------------------------------------------------

    def _build_merge(self):
        config = self.config
        specs = self._specs

        def merge_body(state):
            local = jax.tree.map(lambda x: x[0], state)
            m = local.metrics
            # Record-parallel leaves fold per (data, space) device, so their
            # reductions span both mesh axes.
            dev_axes = (DATA_AXIS, SPACE_AXIS)
            merged = MessageMetricsState(
                per_partition=lax.psum(m.per_partition, dev_axes),
                earliest_s=lax.pmin(m.earliest_s, dev_axes),
                latest_s=lax.pmax(m.latest_s, dev_axes),
                smallest=lax.pmin(m.smallest, dev_axes),
                largest=lax.pmax(m.largest, dev_axes),
                overall_size=lax.psum(m.overall_size, dev_axes),
                overall_count=lax.psum(m.overall_count, dev_axes),
            )
            alive_count = jnp.int64(-1)
            if local.alive is not None:
                gathered = lax.all_gather(local.alive.words, DATA_AXIS)  # [D, W]
                words = lax.reduce(
                    gathered, np.uint32(0), lambda a, b: a | b, (0,)
                )
                pops = jnp.sum(lax.population_count(words).astype(jnp.int64))
                # The OR-reduced words are equal on every data shard but vma
                # still marks them varying over 'data'; a scalar pmax makes
                # the replication explicit (and is a no-op numerically).
                alive_count = lax.pmax(lax.psum(pops, SPACE_AXIS), DATA_AXIS)
            hll_regs = (
                lax.pmax(local.hll.regs, dev_axes) if local.hll is not None else None
            )
            dd_counts = (
                lax.psum(local.quantiles.counts, dev_axes)
                if local.quantiles is not None
                else None
            )
            return merged, alive_count, hll_regs, dd_counts

        out_specs = (
            jax.tree.map(lambda _: P(), _state_specs(self.config).metrics),
            P(),
            P() if config.enable_hll else None,
            P() if config.enable_quantiles else None,
        )
        return shard_map(
            merge_body,
            mesh=self.mesh,
            in_specs=(specs,),
            out_specs=out_specs,
        )

    # -- update --------------------------------------------------------------

    def _pack_chunks(
        self,
        batch: "Optional[RecordBatch]",
        out: "Optional[np.ndarray]" = None,
    ) -> np.ndarray:
        """Contiguous 1/S record chunks of one data row's batch, packed
        into ``[S, chunk_nbytes]`` (packing.pack_chunks — the single
        chunking rule).  ``out`` packs straight into a caller buffer (the
        superbatch stager's ring rows) instead of allocating."""
        if batch is None:
            batch = RecordBatch.empty(0)
        return pack_chunks(
            batch,
            self._chunk_config,
            self.config.space_shards,
            use_native=self.use_native,
            out=out,
        )

    def _row_pairs(self, batch: "Optional[RecordBatch]"):
        """One data row's LWW pairs for the compacted path (None rows —
        another process's, or identity pads — contribute none)."""
        if batch is None or len(batch) == 0:
            return (np.empty(0, np.uint32), np.empty(0, np.uint8))
        return batch_alive_pairs(batch, self.config, self.use_native)

    def _pack_row_pair_tables(self, pair_lists_per_row, cap) -> np.ndarray:
        """``[local_rows, pair_table_nbytes]`` — one merged table per fed
        data row, raw→emitted compaction split booked (never silent)."""
        bufs = []
        for pair_lists in pair_lists_per_row:
            buf, raw, emitted = pack_pair_table(
                pair_lists, self.config, cap, use_native=self.use_native
            )
            obs_metrics.ALIVE_PAIRS_RAW.inc(raw)
            obs_metrics.ALIVE_PAIRS_EMITTED.inc(emitted)
            bufs.append(buf)
        return np.stack(bufs)

    def _put_pair_tables(self, tables: np.ndarray):
        obs_metrics.WIRE_BYTES.inc(int(tables.nbytes))
        if self._multiprocess:
            return jax.make_array_from_process_local_data(
                self._pair_sharding,
                tables,
                global_shape=(self.config.data_shards,) + tables.shape[1:],
            )
        return jax.device_put(tables, self._pair_sharding)

    def prepare_shard(self, batch: RecordBatch) -> "PackedShard":
        """Pack one data row's batch ahead of its collective step — safe on
        a prefetch worker thread (pure numpy/C++), so the S-way chunk
        packing of every row overlaps the device's current step instead of
        serializing in front of update_shards (engine staging).  Compacted
        alive configs dedupe the row's pairs here too (the dispatch merges
        them per row)."""
        if self._compact:
            return PackedShard(
                self._pack_chunks(batch), self._row_pairs(batch)
            )
        return PackedShard(self._pack_chunks(batch))

    def make_fused_sink(self, dense_of):
        """A packing.FusedPackSink whose rows are this backend's
        ``[S, chunk_nbytes]`` chunk stacks — records fill chunk 0..S-1 at
        chunk_size each, the exact ``pack_chunks`` rule, so a fused row
        is byte-for-byte what ``prepare_shard`` would have staged.  One
        sink per fed data row's ingest stream (engine.run_scan).  Under
        compaction the sink hands the row's harvested pairs to the staged
        form (PackedShard.pairs)."""
        from kafka_topic_analyzer_tpu.packing import FusedPackSink

        return FusedPackSink(
            self._chunk_config,
            self.config.chunk_size,
            dense_of,
            stage=PackedShard,
            space_shards=self.config.space_shards,
            chunk_rows=True,
        )

    def update_shards(
        self, batches: "List[RecordBatch | PackedShard | None]"
    ) -> None:
        """One collective step; ``batches[d]`` feeds data row ``d`` — a
        decoded batch, or a ``PackedShard`` staged via ``prepare_shard``.

        Under multi-controller, entries for rows another process hosts are
        ignored here (that process supplies them in ITS call) — the engine
        passes None for them.  Every process must call this in lockstep:
        the compiled step is a global program."""
        d = self.config.data_shards
        if len(batches) != d:
            raise ValueError(f"expected {d} shard batches, got {len(batches)}")

        local = [batches[r] for r in self.local_rows]
        per_shard = np.stack([
            b.chunks if isinstance(b, PackedShard) else self._pack_chunks(b)
            for b in local
        ])  # [local_rows, S, chunk_nbytes]
        obs_metrics.WIRE_BYTES.inc(int(per_shard.nbytes))  # this process's rows
        if self._multiprocess:
            bufs = jax.make_array_from_process_local_data(
                self._buf_sharding,
                per_shard,
                global_shape=(d,) + per_shard.shape[1:],
            )
        else:
            bufs = jax.device_put(per_shard, self._buf_sharding)
        if self._compact:
            tables = self._pack_row_pair_tables(
                [
                    [
                        b.pairs
                        if isinstance(b, PackedShard) and b.pairs is not None
                        else self._row_pairs(
                            None if isinstance(b, PackedShard) else b
                        )
                    ]
                    for b in local
                ],
                self._pair_cap1,
            )
            self.state = self._step(
                self.state, bufs, self._put_pair_tables(tables)
            )
            return
        self.state = self._step(self.state, bufs)

    def update_shards_superbatch(
        self, rounds: "List[List[RecordBatch | PackedShard | None]]"
    ) -> None:
        """Fold up to K rounds of shard batches in ONE scanned collective
        dispatch — byte-identical to K sequential ``update_shards`` calls
        (the scan replays them in order).  A partial tail is padded to K
        with empty rounds (identity folds) so the compiled program count
        stays one.  Collective: under multi-controller every process must
        call this in lockstep with the same round count — the engine's
        per-round ``global_any`` agreement guarantees all processes
        accumulate and flush at the same rounds."""
        k = self.superbatch_k
        if not rounds or len(rounds) > k:
            raise ValueError(f"superbatch of {len(rounds)} rounds (K={k})")
        d = self.config.data_shards
        for batches in rounds:
            if len(batches) != d:
                raise ValueError(
                    f"expected {d} shard batches per round, got {len(batches)}"
                )
        self._queue.throttle()  # before staging: bounds host stacks too
        stacked = self._stager.next_slot()  # [K, local_rows, S, chunk_nbytes]
        for i, batches in enumerate(rounds):
            for j, r in enumerate(self.local_rows):
                b = batches[r]
                if isinstance(b, PackedShard):
                    # Worker-staged upstream (parallel ingest packs before
                    # the fan-in order — and hence the ring row — is
                    # known): one copy into the ring.
                    np.copyto(stacked[i, j], b.chunks)
                else:
                    # Unstaged: pack straight into the ring row, no
                    # intermediate [S, nbytes] stack.
                    self._pack_chunks(b, out=stacked[i, j])
        if len(rounds) < k:
            if self._empty_chunks is None:
                self._empty_chunks = np.stack(
                    [self._pack_chunks(None) for _ in self.local_rows]
                )
            for i in range(len(rounds), k):
                np.copyto(stacked[i], self._empty_chunks)
        obs_metrics.WIRE_BYTES.inc(int(stacked.nbytes))  # this process's rows
        if self._multiprocess:
            bufs = jax.make_array_from_process_local_data(
                self._superbuf_sharding,
                stacked,
                global_shape=(k, d) + stacked.shape[2:],
            )
        else:
            bufs = jax.device_put(stacked, self._superbuf_sharding)
        if self._compact:
            # Per-row LWW merge across the superbatch's K rounds, in round
            # order — the scanned steps then fold pair-free and each row's
            # table applies once after the scan (identity-pad rounds
            # contribute no pairs).
            per_row_lists = []
            for r in self.local_rows:
                lists = []
                for batches in rounds:
                    b = batches[r]
                    if isinstance(b, PackedShard):
                        if b.pairs is not None:
                            lists.append(b.pairs)
                    else:
                        lists.append(self._row_pairs(b))
                per_row_lists.append(lists)
            tables = self._pack_row_pair_tables(
                per_row_lists, self._pair_cap_k
            )
            self.state, token = self._superstep(
                self.state, bufs, self._put_pair_tables(tables)
            )
        else:
            self.state, token = self._superstep(self.state, bufs)
        self._queue.launched(token, len(rounds))

    def global_any(self, flag: bool) -> bool:
        """All-process OR of a host flag, via a psum over the data axis.

        The multi-host scan loop's agreement point: processes drain their
        shard streams at different times, but collective steps must stay in
        lockstep — each round every process contributes "I still have
        data", and the loop continues iff anyone does.  Same result on
        every process (it's a collective), so break decisions stay
        consistent and deadlock-free."""
        if not hasattr(self, "_any_fn"):
            def body(x):
                return lax.psum(x, DATA_AXIS)

            self._any_fn = jax.jit(
                shard_map(
                    body,
                    mesh=self.mesh,
                    in_specs=P(DATA_AXIS),
                    out_specs=P(),
                )
            )
        local = np.full((len(self.local_rows),), int(flag), np.int32)
        if self._multiprocess:
            arr = jax.make_array_from_process_local_data(
                self._row_sharding,
                local,
                global_shape=(self.config.data_shards,),
            )
        else:
            arr = jax.device_put(local, self._row_sharding)
        return bool(np.asarray(self._any_fn(arr)).sum() > 0)

    def gather_telemetry(self) -> "List[dict]":
        """Per-process registry snapshots, one per controller.

        Multi-controller aggregation over the same lockstep collective
        machinery as ``global_any``: each process JSON-encodes its local
        obs registry snapshot, the fleet agrees on the max payload size
        (pmax over the data axis), and the length-prefixed padded byte
        rows are all_gathered so every process can decode every
        snapshot.  Rows are deduped by process id (a process hosting R
        data rows contributes R identical copies).  Collective — every
        process must call it at the same point (the engine does, in
        ``run_scan``'s tail); the report process then folds the list with
        ``obs.registry.merge_snapshots`` into the cluster-wide view.

        This same merge is what gives mesh scans a FLEET-WIDE bottleneck
        verdict: every occupancy signal the scan doctor attributes from
        (live stage seconds, throttle waits, worker stall/active seconds,
        fetch/decode seconds — obs/doctor.py) is a counter, and counters
        sum across this gather, so process 0's digest attributes the
        whole fleet without shipping any flight-recorder series."""
        import json

        from kafka_topic_analyzer_tpu.obs.registry import default_registry

        snap = default_registry().snapshot()
        if not self._multiprocess:
            return [snap]
        payload = json.dumps(
            {"pid": jax.process_index(), "telemetry": snap}
        ).encode()

        def _row_array(local: np.ndarray, sharding, global_rows: int):
            return jax.make_array_from_process_local_data(
                sharding, local, global_shape=(global_rows,) + local.shape[1:]
            )

        d = self.config.data_shards
        n_local = len(self.local_rows)
        # Round 1: agree on the widest payload (pmax over 'data').
        if not hasattr(self, "_pmax_fn"):
            self._pmax_fn = jax.jit(
                shard_map(
                    lambda x: lax.pmax(x, DATA_AXIS),
                    mesh=self.mesh,
                    in_specs=P(DATA_AXIS),
                    out_specs=P(),
                )
            )
        lens = np.full((n_local,), len(payload), np.int32)
        width = int(np.asarray(
            self._pmax_fn(_row_array(lens, self._row_sharding, d))
        ).max())
        # Round 2: all_gather the length-prefixed, zero-padded rows.  Not
        # cached/jitted: the width varies per call and this runs once per
        # scan.
        gather = shard_map(
            lambda x: lax.all_gather(x, DATA_AXIS, tiled=True),
            mesh=self.mesh,
            in_specs=P(DATA_AXIS, None),
            out_specs=P(None, None),
        )
        rows = np.zeros((n_local, 4 + width), np.uint8)
        prefix = np.frombuffer(
            len(payload).to_bytes(4, "big"), np.uint8
        )
        for r in range(n_local):
            rows[r, :4] = prefix
            rows[r, 4:4 + len(payload)] = np.frombuffer(payload, np.uint8)
        sharding = NamedSharding(self.mesh, P(DATA_AXIS, None))
        gathered = np.asarray(
            jax.jit(gather)(_row_array(rows, sharding, d))
        )
        out: "dict[int, dict]" = {}
        for r in range(d):
            n = int.from_bytes(gathered[r, :4].tobytes(), "big")
            doc = json.loads(gathered[r, 4:4 + n].tobytes().decode())
            out.setdefault(doc["pid"], doc["telemetry"])
        return [out[pid] for pid in sorted(out)]

    def update(self, batch: RecordBatch) -> None:
        """Split a mixed batch by partition→shard (partition % D)."""
        d = self.config.data_shards
        shard_of = np.asarray(batch.partition) % d
        self.update_shards(
            [batch.take(np.nonzero(shard_of == s)[0]) for s in range(d)]
        )

    def drain_dispatch(self) -> None:
        """Retire every in-flight superbatch dispatch WITHOUT launching a
        new collective — the engine's failure path calls this before its
        final snapshot (DESIGN.md §14 lockstep flush protocol).

        Lockstep-safe even when only THIS controller is stopping: the
        queued completion tokens belong to scanned steps that every
        controller already launched at a lockstep-agreed round (the
        engine accumulates and flushes superbatches only after the
        per-round ``global_any`` agreement), so blocking on them is a
        local wait on collective programs that are already running
        fleet-wide — never a one-sided collective that could deadlock a
        peer.  Contrast with the partial-tail flush, which WOULD launch a
        new collective and is therefore skipped on multi-controller fault
        paths (engine.py ``fault_flush``)."""
        if self.superbatch_k > 1:
            self._queue.drain()

    def block_until_ready(self) -> None:
        self.drain_dispatch()
        jax.block_until_ready(self.state)

    # -- snapshot/resume (checkpoint.py) -------------------------------------

    def get_state(self) -> AnalyzerState:
        return self.state

    def set_state(self, host_state: AnalyzerState) -> None:
        self.state = jax.tree.map(
            lambda x, s: jax.device_put(
                np.asarray(x), NamedSharding(self.mesh, s)
            ),
            host_state,
            self._specs,
        )

    @property
    def controller_index(self) -> int:
        """This process's index in the fleet (0 single-controller) — the
        engine prefixes per-worker ingest telemetry labels with it so the
        cross-controller merge unions worker samples instead of summing
        unrelated workers that happen to share an id."""
        return jax.process_index() if self._multiprocess else 0

    @property
    def snapshot_scope(self):
        """None single-controller; (pid, nproc, local_rows) under
        jax.distributed — the engine then snapshots per process via
        get_state_local/set_state_local (data shards fold independently,
        so per-process files need no coordination)."""
        if not self._multiprocess:
            return None
        return (jax.process_index(), jax.process_count(), self.local_rows)

    def get_state_local(self) -> AnalyzerState:
        """Host copy of THIS process's data rows of every state leaf."""
        rows = self.local_rows
        row0 = rows[0]
        if rows != list(range(row0, row0 + len(rows))):
            raise NotImplementedError(
                "snapshots need contiguous local data rows"
            )

        d = self.config.data_shards

        def to_local(arr):
            # Record-parallel leaves carry D·S leading rows (one per
            # device), the bitmap D; either way each data row owns a
            # contiguous `scale`-row block of the leading axis.
            scale = arr.shape[0] // d
            base = row0 * scale
            local_shape = (len(rows) * scale,) + arr.shape[1:]
            buf = np.empty(local_shape, dtype=arr.dtype)
            for sh in arr.addressable_shards:
                idx = sh.index
                r = idx[0]
                lo = (r.start or 0) - base
                hi = (r.stop if r.stop is not None else arr.shape[0]) - base
                buf[(slice(lo, hi),) + tuple(idx[1:])] = np.asarray(sh.data)
            return buf

        return jax.tree.map(to_local, self.state)

    def set_state_local(self, local_state: AnalyzerState) -> None:
        """Rebuild the global state from THIS process's rows (the other
        processes supply theirs in their own call)."""
        d = self.config.data_shards
        n_local = len(self.local_rows)

        def put(x, s):
            x = np.asarray(x)
            scale = x.shape[0] // n_local
            return jax.make_array_from_process_local_data(
                NamedSharding(self.mesh, s),
                x,
                global_shape=(d * scale,) + x.shape[1:],
            )

        self.state = jax.tree.map(put, local_state, self._specs)

    # -- finalize ------------------------------------------------------------

    def finalize(self) -> TopicMetrics:
        # Complete the dispatch-latency histogram before the merge
        # collective syncs the state anyway.
        self.drain_dispatch()
        merged, alive_count, hll_regs, dd_counts = self._merge(self.state)
        merged = jax.tree.map(np.asarray, jax.device_get(merged))
        alive_count = int(alive_count)

        from kafka_topic_analyzer_tpu.models.compaction import HLLState
        from kafka_topic_analyzer_tpu.models.quantiles import DDSketchState

        host_state = AnalyzerState(
            metrics=merged,
            alive=None,
            hll=HLLState(regs=np.asarray(hll_regs)) if hll_regs is not None else None,
            quantiles=(
                DDSketchState(counts=np.asarray(dd_counts))
                if dd_counts is not None
                else None
            ),
        )
        metrics = metrics_from_state(host_state, self.config, self.init_now_s)
        if self.config.count_alive_keys:
            metrics.alive_keys = alive_count
        return metrics
