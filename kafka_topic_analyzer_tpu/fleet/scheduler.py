"""The fleet admission scheduler: N topic scans, one budget (DESIGN.md §20).

A fleet scan multiplies the per-topic pipeline across the cluster, but
the host resources it multiplies over — ingest worker threads and
superbatch dispatch depth — are global.  This module owns the admission
algebra that shares them:

- **Admission**: a topic with work (watermark lag, or its initial
  catch-up) asks for a grant; the scheduler admits up to
  ``max_concurrent`` topics at once, each holding at least one ingest
  worker and one dispatch-depth token, and defers the rest until budget
  returns.  Wave *planning* for batch fleets reuses the greedy-LPT rule
  from ``parallel/ingest.shard_partitions(weights=)`` — topics descend by
  weight onto the least-loaded wave, so one giant topic does not serialize
  the whole cluster behind it — and worker *splitting* within an admitted
  set reuses ``allocate_row_workers`` (every admitted topic gets >= 1
  worker, the remainder goes where partitions-per-worker is worst).
- **Rebalance** (between follow polls): the scan doctor's per-topic
  verdicts (obs/doctor.diagnose_scan) drive budget moves — a
  *dispatch-bound* scan's workers are stalled on the device, so it sheds
  one to the pool; an *ingest-bound* scan is starved on fetch→decode, so
  it takes a worker from the pool and sheds dispatch share it cannot use.
  Grants change only between passes (a pass runs with the workers it was
  granted), so rebalancing never perturbs in-flight fold order.

Invariants (property-tested in tests/test_fleet.py): at every point in
any admit/release/rebalance sequence, the sum of granted workers never
exceeds the worker budget, the sum of granted dispatch tokens never
exceeds the dispatch budget, every active grant keeps >= 1 of each, and
a topic's workers never exceed its partition count (a worker beyond it
would own an empty partition group).

Every admission decision books exactly one ``kta_fleet_admissions_total``
reason (tools/lint.sh rule 10) — the admission trace is reconstructible
from the counter alone.  The scheduler itself is pure bookkeeping: it
never touches sources, backends, or the drive loop (also rule 10), which
is what keeps it unit-testable without a broker.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Sequence

from kafka_topic_analyzer_tpu.obs import metrics as obs_metrics


@dataclasses.dataclass
class Grant:
    """One admitted topic's slice of the global budgets."""

    workers: int
    dispatch_depth: int


@dataclasses.dataclass(frozen=True)
class TopicSeed:
    """Admission input for one topic: identity plus the two weights the
    scheduler balances on (partition count bounds the useful worker
    grant; lag orders who goes first)."""

    name: str
    partitions: int
    #: Records behind the head (watermark lag); batch seeding uses the
    #: full retained count.  0 = nothing to do.
    lag: int = 0

    @property
    def weight(self) -> int:
        """LPT weight: lag when known, else partition count — a topic we
        know nothing about yet is assumed proportional to its width."""
        return self.lag if self.lag > 0 else self.partitions


class FleetScheduler:
    def __init__(
        self,
        worker_budget: int,
        dispatch_budget: int,
        max_concurrent: int,
        instance: str = "solo",
    ):
        if worker_budget < 1:
            raise ValueError("fleet worker budget must be >= 1")
        if dispatch_budget < 1:
            raise ValueError("fleet dispatch budget must be >= 1")
        if max_concurrent < 1:
            raise ValueError("fleet concurrency must be >= 1")
        self.worker_budget = worker_budget
        self.dispatch_budget = dispatch_budget
        self.max_concurrent = max_concurrent
        #: Analyzer instance id carried on every booked decision ("solo"
        #: outside a multi-instance fleet) — federation per DESIGN §23.
        self.instance = instance
        #: topic -> live Grant (the budget ledger).
        self._grants: "Dict[str, Grant]" = {}
        #: topic -> partition count (the per-topic worker clamp).
        self._partitions: "Dict[str, int]" = {}

    # -- ledger views ---------------------------------------------------------

    def grants(self) -> "Dict[str, Grant]":
        return {t: dataclasses.replace(g) for t, g in self._grants.items()}

    def grant_for(self, topic: str) -> "Grant | None":
        g = self._grants.get(topic)
        return dataclasses.replace(g) if g is not None else None

    @property
    def workers_granted(self) -> int:
        return sum(g.workers for g in self._grants.values())

    @property
    def dispatch_granted(self) -> int:
        return sum(g.dispatch_depth for g in self._grants.values())

    @property
    def active(self) -> int:
        return len(self._grants)

    # -- wave planning (batch fleets) -----------------------------------------

    def plan_waves(self, seeds: "Sequence[TopicSeed]") -> "List[List[str]]":
        """Group the topic set into admission waves of at most
        ``max_concurrent`` topics, balanced by weight via the greedy-LPT
        grouping rule (parallel/ingest.shard_partitions(weights=) — the
        same deterministic descend-by-weight-onto-least-loaded placement
        that shards partitions across ingest workers).  Waves run in
        index order; within a wave, scans run concurrently."""
        from kafka_topic_analyzer_tpu.parallel.ingest import shard_partitions

        if not seeds:
            return []
        n_waves = max(1, -(-len(seeds) // self.max_concurrent))
        idx_groups = shard_partitions(
            list(range(len(seeds))),
            n_waves,
            weights={i: s.weight for i, s in enumerate(seeds)},
        )
        # LPT balances weight, not cardinality: spill overfull groups'
        # lightest members into the emptiest groups so no wave exceeds
        # the concurrency bound (budget would be over-subscribed).
        groups = [list(g) for g in idx_groups]
        while True:
            over = next(
                (g for g in groups if len(g) > self.max_concurrent), None
            )
            if over is None:
                break
            under = min(groups, key=len)
            if len(under) >= self.max_concurrent:
                groups.append([])
                under = groups[-1]
            lightest = min(over, key=lambda i: (seeds[i].weight, i))
            over.remove(lightest)
            under.append(lightest)
        return [
            [seeds[i].name for i in sorted(g)] for g in groups if g
        ]

    # -- admission ------------------------------------------------------------

    def admit(
        self,
        ready: "Sequence[TopicSeed]",
        reason: str = "admitted-poll",
    ) -> "Dict[str, Grant]":
        """Grant budget to as many of ``ready`` as fit (heaviest first).

        Already-admitted topics are left untouched (their grants persist
        across polls until ``release``).  Newly admitted topics split the
        UNGRANTED worker budget via ``allocate_row_workers`` (>= 1 each,
        clamped at partition count) and the ungranted dispatch budget
        evenly (>= 1 each).  Topics that fit no budget slot are deferred
        — booked, not forgotten: the next poll re-offers them.  Returns
        the grants for exactly the topics admitted THIS call."""
        new = [
            s for s in sorted(ready, key=lambda s: (-s.weight, s.name))
            if s.name not in self._grants
        ]
        admitted: "Dict[str, Grant]" = {}
        if not new:
            return admitted
        free_slots = self.max_concurrent - self.active
        free_workers = self.worker_budget - self.workers_granted
        free_dispatch = self.dispatch_budget - self.dispatch_granted
        n = min(len(new), free_slots, free_workers, free_dispatch)
        if n > 0:
            from kafka_topic_analyzer_tpu.parallel.ingest import (
                allocate_row_workers,
            )

            take = new[:n]
            split = allocate_row_workers(
                free_workers,
                {i: max(1, s.partitions) for i, s in enumerate(take)},
            )
            depth_each = max(1, free_dispatch // n)
            spent_dispatch = 0
            for i, s in enumerate(take):
                depth = min(depth_each, free_dispatch - spent_dispatch - (n - i - 1))
                depth = max(1, depth)
                spent_dispatch += depth
                g = Grant(workers=max(1, split.get(i, 1)), dispatch_depth=depth)
                self._grants[s.name] = g
                self._partitions[s.name] = max(1, s.partitions)
                admitted[s.name] = dataclasses.replace(g)
                obs_metrics.FLEET_ADMISSIONS.labels(
                    reason=reason, instance=self.instance
                ).inc()
        for s in new[n:]:
            obs_metrics.FLEET_ADMISSIONS.labels(
                reason="deferred-budget", instance=self.instance
            ).inc()
        obs_metrics.FLEET_TOPICS_ACTIVE.labels(
            instance=self.instance
        ).set(self.active)
        return admitted

    def skip_idle(self, count: int) -> None:
        """Book topics that polled at the head with nothing to do — an
        admission DECISION (the answer was "no work"), so it is traced
        like every other one."""
        for _ in range(max(0, int(count))):
            obs_metrics.FLEET_ADMISSIONS.labels(
                reason="skipped-empty", instance=self.instance
            ).inc()

    def release(self, topic: str) -> None:
        """Return a finished (or caught-up, or failed) topic's budget."""
        if self._grants.pop(topic, None) is not None:
            obs_metrics.FLEET_ADMISSIONS.labels(
                reason="released", instance=self.instance
            ).inc()
        obs_metrics.FLEET_TOPICS_ACTIVE.labels(
            instance=self.instance
        ).set(self.active)

    # -- the rebalance rule (between polls) -----------------------------------

    def rebalance(self, verdicts: "Dict[str, str]") -> int:
        """Move budget between live grants on doctor verdicts; returns the
        number of moves applied (booked on kta_fleet_rebalances_total).

        The rule (DESIGN.md §20): dispatch-bound scans shed one worker
        each into the pool (their workers are stalled on the device
        anyway) and keep their dispatch share; ingest-bound scans shed
        dispatch share down to 1 (their device is idle) and then draw
        workers from the pool — heaviest-clamped-first, one at a time,
        until the pool is dry or every ingest-bound scan is at its
        partition clamp.  Balanced/no-signal scans hold still.  All
        invariants (budget sums, >= 1 floors, partition clamps) are
        preserved by construction."""
        moves = 0
        # Shed: dispatch-bound workers → pool; ingest-bound dispatch → pool.
        for t in sorted(verdicts):
            g = self._grants.get(t)
            if g is None:
                continue
            v = verdicts[t]
            if v == "dispatch-bound" and g.workers > 1:
                g.workers -= 1
                moves += 1
            elif v == "ingest-bound" and g.dispatch_depth > 1:
                g.dispatch_depth = 1
                moves += 1
        # Draw: pool workers → ingest-bound scans, most-starved first
        # (fewest workers per partition), clamped at partition count.
        pool = self.worker_budget - self.workers_granted
        starved = [
            t for t in sorted(verdicts)
            if verdicts[t] == "ingest-bound" and t in self._grants
        ]
        while pool > 0 and starved:
            best = None
            for t in starved:
                g = self._grants[t]
                clamp = self._partitions.get(t, g.workers)
                if g.workers >= clamp:
                    continue
                ratio = clamp / g.workers
                if best is None or ratio > best[0]:
                    best = (ratio, t)
            if best is None:
                break
            self._grants[best[1]].workers += 1
            pool -= 1
            moves += 1
        if moves:
            obs_metrics.FLEET_REBALANCES.labels(
                instance=self.instance
            ).inc(moves)
        return moves
