"""The fleet drive layer: admitted per-topic scans on shared budgets.

Each admitted topic runs the SAME pass chain a solo scan of that topic
would — ``engine.run_scan`` over ``[cursor, head)`` windows on the
topic's own backend — so per-topic metrics are byte-identical to a solo
scan stopped at the same offsets (the follow service's associativity
argument, DESIGN.md §18, applied per topic; tests/test_fleet.py sweeps
it across workers × superbatch).  What the fleet layer adds is strictly
*around* the passes:

- **admission**: the `fleet.scheduler.FleetScheduler` decides which
  topics hold ingest-worker/dispatch budget at any moment; passes run
  with the granted worker count (grants change only between passes);
- **failure isolation**: one topic's scan raising — deterministic
  corruption under the ``fail`` policy, an exhausted transport budget, a
  source that cannot even connect — marks THAT topic ``failed`` in the
  status table and releases its budget; every other topic's scan is
  untouched (the exception never crosses the topic boundary);
- **namespacing**: each topic's checkpoints live in their own
  subdirectory (``checkpoint.topic_snapshot_dir``) and each topic's
  report document is published to its own ``/report.json?topic=`` slot,
  both via the same one-builder/one-format machinery solo scans use;
- **the rollup**: after every wave/poll the service publishes a cluster
  rollup (totals, top topics, per-topic status rows — fleet/report.py)
  to the main ``/report.json`` slot.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, List, Optional

from kafka_topic_analyzer_tpu.checkpoint import StaleLeaseEpochError
from kafka_topic_analyzer_tpu.config import FollowConfig, TransportRetryConfig
from kafka_topic_analyzer_tpu.engine import ScanResult, run_scan
from kafka_topic_analyzer_tpu.fleet.lease import LeaseManager
from kafka_topic_analyzer_tpu.fleet.report import build_fleet_rollup
from kafka_topic_analyzer_tpu.fleet.scheduler import (
    FleetScheduler,
    Grant,
    TopicSeed,
)
from kafka_topic_analyzer_tpu.io.retry import Backoff
from kafka_topic_analyzer_tpu.obs import events as obs_events
from kafka_topic_analyzer_tpu.obs import health as obs_health
from kafka_topic_analyzer_tpu.obs import metrics as obs_metrics
from kafka_topic_analyzer_tpu.serve import state as serve_state
from kafka_topic_analyzer_tpu.utils.progress import Spinner

log = logging.getLogger(__name__)


@dataclasses.dataclass
class TopicStatus:
    """One row of the fleet status table."""

    topic: str
    partitions: int = 0
    #: pending | scanning | ok | empty | degraded | corrupt | data-loss
    #: | failed | fenced (lease lost to a successor — not a topic
    #: failure; the topic scans on, under another instance's ownership).
    #: data-loss is set ONLY by an --on-data-loss=fail abort: a scan
    #: that completes under the report policy keeps its ordinary status
    #: (loss never changes the exit code outside the fail policy) and
    #: carries the booked loss in `lost_records` instead.
    status: str = "pending"
    records: int = 0
    bytes: int = 0
    lost_records: int = 0
    lag: int = 0
    verdict: str = ""
    workers: int = 0
    passes: int = 0
    error: "Optional[str]" = None

    def as_dict(self) -> dict:
        out = {
            "status": self.status,
            "partitions": self.partitions,
            "records": self.records,
            "bytes": self.bytes,
            "lost_records": self.lost_records,
            "lag": self.lag,
            "verdict": self.verdict,
            "workers": self.workers,
            "passes": self.passes,
        }
        if self.error:
            out["error"] = self.error
        return out


@dataclasses.dataclass
class FleetResult:
    """What a fleet run returns to the CLI: the status table, each
    scanned topic's full `ScanResult`, and the published rollup doc."""

    statuses: "Dict[str, TopicStatus]"
    results: "Dict[str, ScanResult]"
    rollup: dict
    duration_secs: int = 0

    @property
    def any_failed(self) -> bool:
        return any(s.status == "failed" for s in self.statuses.values())

    @property
    def any_degraded(self) -> bool:
        return any(s.status == "degraded" for s in self.statuses.values())

    @property
    def any_corrupt(self) -> bool:
        return any(s.status == "corrupt" for s in self.statuses.values())

    @property
    def any_data_loss(self) -> bool:
        return any(s.status == "data-loss" for s in self.statuses.values())


class _TopicScan:
    """Per-topic mutable scan state the fleet loop drives."""

    def __init__(self, seed: TopicSeed):
        self.seed = seed
        self.source = None
        self.backend = None
        self.cursor: "Dict[int, int]" = {}
        self.seq = 0
        self.first = True
        self.status = TopicStatus(topic=seed.name, partitions=seed.partitions)
        self.result: "Optional[ScanResult]" = None
        #: Last pass's doctor attribution (obs/doctor.Diagnosis) — set by
        #: every completed pass, so the rollup's verdict column fills in
        #: whether or not per-topic documents are being published.
        self.diagnosis = None
        self.lag = 0
        #: Last grant a productive pass ran under — the shutdown pass
        #: (whose budget was already released) reuses it so the final
        #: report does not overwrite the topic's real parallelism with
        #: the fallback's.
        self.last_grant: "Optional[Grant]" = None


class FleetService:
    """Own the whole-cluster scan: admission, passes, rollup, shutdown.

    ``source_factory(topic)`` builds a topic's record source (the CLI
    closes over its flag set); ``backend_factory(topic, partitions,
    grant)`` builds its backend, sized by the grant's dispatch share.
    Both are called lazily — a topic that is never admitted costs no
    broker handshake and no device state.  ``follow=None`` runs the batch
    fleet (every topic scanned once, in scheduler-planned waves);
    a `FollowConfig` turns on fleet follow: the poll loop re-polls every
    topic's watermarks, admits lagging topics, and re-enters their pass
    chains until stopped.  ``rediscover`` (follow mode) is an optional
    zero-arg callable returning fresh `TopicSeed`s — topics created after
    startup join the fleet at the next re-discovery poll.
    """

    def __init__(
        self,
        seeds: "List[TopicSeed]",
        source_factory: "Callable[[str], object]",
        backend_factory: "Callable[[str, int, Grant], object]",
        batch_size: int,
        scheduler: FleetScheduler,
        *,
        follow: "Optional[FollowConfig]" = None,
        snapshot_dir: "Optional[str]" = None,
        resume: bool = False,
        publish_reports: bool = True,
        spinner: "Optional[Spinner]" = None,
        rediscover: "Optional[Callable[[], List[TopicSeed]]]" = None,
        rediscover_every: int = 16,
        heartbeat_every_s: float = 10.0,
        health: "Optional[obs_health.HealthEngine]" = None,
        clock: Callable[[], float] = time.monotonic,
        leases: "Optional[LeaseManager]" = None,
        instance: str = "solo",
        serve_gzip: bool = True,
    ):
        self.scans: "Dict[str, _TopicScan]" = {
            s.name: _TopicScan(s) for s in seeds
        }
        self.discovered = len(seeds)
        self.source_factory = source_factory
        self.backend_factory = backend_factory
        self.batch_size = batch_size
        self.scheduler = scheduler
        self.follow = follow
        self.snapshot_dir = snapshot_dir
        self.resume = resume
        self.publish_reports = publish_reports
        self.spinner = spinner or Spinner(enabled=False)
        self.rediscover = rediscover
        self.rediscover_every = max(1, int(rediscover_every))
        self._clock = clock
        self._heartbeat = obs_events.Heartbeat(heartbeat_every_s)
        #: Alert engine evaluated at every fleet poll/wave boundary with
        #: per-topic lag + failure context (obs/health.py): explicit
        #: wins, else the telemetry session's engine, else none.
        self.health = health if health is not None else obs_health.active()
        #: Per-topic ownership leases (fleet/lease.py) — None runs the
        #: pre-lease single-instance fleet unchanged.  With a manager,
        #: admission is acquire-before-scan, renewal rides every poll
        #: boundary, and budgets release WITH the lease (DESIGN §23).
        self.leases = leases
        self.instance = instance
        self.state = serve_state.ServiceState(
            instance=instance if leases is not None else None,
            gzip_enabled=serve_gzip,
        )
        self._stop = threading.Event()
        self._stop_reason: "Optional[str]" = None
        #: Chaos seams for the offline failover tests (satellite of
        #: ISSUE 16): ``kill()`` crashes the instance — stop NOW, no
        #: shutdown passes, no lease release, exactly what SIGKILL
        #: leaves behind; ``pause()``/``unpause()`` freeze/thaw the loop
        #: right after the renew step, the zombie window epoch fencing
        #: must cover.
        self._killed = False
        self._pause = threading.Event()
        #: True exactly while the follow loop is frozen at the
        #: post-renew pause gate — the observable the chaos tests wait
        #: on (a polls-are-static heuristic cannot tell "at the gate"
        #: from "mid-pass on a slow broker").
        self.paused = False
        self.polls = 0
        self._t0 = clock()
        self._last_ckpt = clock()
        if follow is not None:
            self._idle_backoff = Backoff(
                TransportRetryConfig(
                    backoff_ms=max(1, int(follow.poll_interval_s * 1000)),
                    backoff_max_ms=max(
                        max(1, int(follow.poll_interval_s * 1000)),
                        int(follow.idle_backoff_max_s * 1000),
                    ),
                )
            )

    # -- stopping -------------------------------------------------------------

    def request_stop(self, reason: str = "stop") -> None:
        if not self._stop.is_set():
            self._stop_reason = reason
        self._stop.set()

    def kill(self) -> None:
        """Crash semantics (the chaos twin of FakeBroker.kill): the
        loop exits at the next check with NO shutdown passes and NO
        lease release — held leases dangle until their TTL expires,
        which is precisely the failover the two-instance tests prove."""
        self._killed = True
        self.request_stop("killed")

    def pause(self) -> None:
        """Freeze the follow loop at the post-renew gate (a stalled VM,
        a long GC): leases keep their last renewal and expire while
        paused — the zombie window."""
        self._pause.set()

    def unpause(self) -> None:
        # Not `resume()`: the constructor's resume-from-checkpoint flag
        # lives at `self.resume` and would shadow a method of that name.
        self._pause.clear()

    def install_signal_handlers(self):
        from kafka_topic_analyzer_tpu.serve.signals import (
            install_stop_handlers,
        )

        return install_stop_handlers(self.request_stop)

    # -- per-topic plumbing ---------------------------------------------------

    def _topic_snapshot_dir(self, topic: str) -> "Optional[str]":
        if self.snapshot_dir is None:
            return None
        from kafka_topic_analyzer_tpu.checkpoint import topic_snapshot_dir

        return topic_snapshot_dir(self.snapshot_dir, topic)

    def _ensure_source(self, scan: _TopicScan) -> bool:
        """Build the topic's source on first need; a factory failure is a
        per-topic failure, never a fleet one."""
        if scan.source is not None:
            return True
        try:
            scan.source = self.source_factory(scan.seed.name)
            scan.status.partitions = len(scan.source.partitions())
            return True
        except BaseException as e:  # noqa: BLE001 — isolation boundary
            scan.status.status = "failed"
            scan.status.error = f"{type(e).__name__}: {e}"
            log.exception("fleet: source for topic %r failed", scan.seed.name)
            return False

    def _release_source(self, scan: _TopicScan) -> None:
        """Close and drop a stopped topic's source.  Shared-pool hygiene:
        remote segment sources hold chunk bodies and fetch-scheduler
        streams, and the scheduler pool is ONE per process — a fenced or
        failed topic must stop competing for its workers the moment it
        stops scanning, not at fleet teardown.  A later pass (re-acquire
        after fencing, batch retry) rebuilds through _ensure_source."""
        source, scan.source = scan.source, None
        if source is not None and hasattr(source, "close"):
            try:
                source.close()
            except BaseException:  # noqa: BLE001 — teardown best-effort
                log.exception(
                    "fleet: closing source for topic %r failed",
                    scan.seed.name,
                )

    def _run_pass(
        self, scan: _TopicScan, grant: Grant, final: bool = False
    ) -> bool:
        """One engine pass for one topic (the fleet twin of
        serve/follow.FollowService._run_pass).  Returns True when the
        pass completed; False marks the topic failed — the exception is
        absorbed HERE, at the topic boundary, so a poisoned topic can
        never take the fleet down."""
        topic = scan.seed.name
        scan.status.status = "scanning"
        scan.status.workers = grant.workers
        scan.last_grant = dataclasses.replace(grant)
        force_ckpt = self.snapshot_dir is not None and (
            final or self._checkpoint_due()
        )
        try:
            if scan.backend is None:
                scan.backend = self.backend_factory(
                    topic, len(scan.source.partitions()), grant
                )
            else:
                # Re-apply the CURRENT dispatch share to a live backend:
                # rebalance/re-admission may have moved tokens since
                # construction, and the ledger must stay the real bound
                # (backends clamp grows at their constructed depth).
                setter = getattr(scan.backend, "set_dispatch_depth", None)
                if setter is not None:
                    setter(grant.dispatch_depth)
            result = run_scan(
                topic,
                scan.source,
                scan.backend,
                batch_size=self.batch_size,
                spinner=self.spinner,
                snapshot_dir=self._topic_snapshot_dir(topic),
                snapshot_every_s=(
                    self.follow.checkpoint_every_s
                    if self.follow is not None else 60.0
                ),
                resume=self.resume and scan.first,
                start_at=dict(scan.cursor) if not scan.first else None,
                heartbeat=self._heartbeat,
                ingest_workers=grant.workers,
                initial_seq=scan.seq,
                emit_lifecycle=False,
                book_once=scan.first,
                final_snapshot=force_ckpt,
                lease_epoch=(
                    self.leases.epoch(topic)
                    if self.leases is not None else None
                ),
            )
        except StaleLeaseEpochError as e:
            # The zombie path: this instance's lease epoch is older than
            # what a successor already stamped on disk — the checkpoint
            # write was REFUSED, the topic is not ours anymore.  Not a
            # topic failure (the topic is healthy, under new ownership):
            # fence the lease (books kta_lease_losses_total) and step
            # aside; a later acquire can win the topic back legitimately.
            scan.status.status = "fenced"
            scan.status.error = f"{type(e).__name__}: {e}"
            if self.leases is not None:
                self.leases.fence(topic)
            log.warning("fleet: topic %r fenced: %s", topic, e)
            self._release_source(scan)
            return False
        except BaseException as e:  # noqa: BLE001 — isolation boundary
            from kafka_topic_analyzer_tpu.io.kafka_wire import DataLossError

            if isinstance(e, DataLossError):
                # --on-data-loss=fail abort: the loss is booked and the
                # checkpoint fold-consistent — a NAMED stop, not a topic
                # failure (the distinct status keeps _fleet_exit's
                # EXIT_DATA_LOSS rung separate from the hard-failure 1).
                scan.status.status = "data-loss"
                scan.status.error = f"{type(e).__name__}: {e}"
                log.warning(
                    "fleet: scan of topic %r stopped on data loss: %s",
                    topic, e,
                )
                self._release_source(scan)
                return False
            scan.status.status = "failed"
            scan.status.error = f"{type(e).__name__}: {e}"
            log.exception("fleet: scan of topic %r failed", topic)
            self._release_source(scan)
            return False
        scan.first = False
        scan.result = result
        scan.cursor = dict(result.next_offsets)
        scan.seq = result.metrics.overall_count
        scan.status.passes += 1
        scan.status.records = result.metrics.overall_count
        scan.status.bytes = result.metrics.overall_size
        scan.status.lost_records = sum(
            d.get("records", 0)
            for p, d in result.lost_partitions.items()
            if p >= 0
        )
        if result.degraded_partitions:
            scan.status.status = "degraded"
        elif result.corrupt_partitions:
            scan.status.status = "corrupt"
        else:
            scan.status.status = "ok"
        # The doctor attributes EVERY completed pass — the rollup's
        # verdict column (and the scheduler's rebalance input) must not
        # depend on whether /report.json documents are being published.
        from kafka_topic_analyzer_tpu.obs.doctor import diagnose_scan

        scan.diagnosis = diagnose_scan(result)
        scan.status.verdict = scan.diagnosis.verdict
        self._publish_topic(scan)
        return True

    def _publish_topic(self, scan: _TopicScan) -> None:
        if not self.publish_reports or scan.result is None:
            return
        from kafka_topic_analyzer_tpu.report import build_json_doc

        doc = build_json_doc(
            scan.seed.name,
            scan.result,
            diagnosis=scan.diagnosis,
            fleet=scan.status.as_dict(),
            health=(
                self.health.alerts_block(topic=scan.seed.name)
                if self.health is not None
                else None
            ),
        )
        self.state.publish(
            doc,
            topic=scan.seed.name,
            summary={
                "status": scan.status.status,
                "verdict": scan.status.verdict,
                "passes": scan.status.passes,
            },
        )

    def _publish_rollup(self) -> dict:
        rollup = build_fleet_rollup(
            {t: s.status for t, s in self.scans.items()},
            discovered=self.discovered,
            duration_secs=int(self._clock() - self._t0),
            health=(
                self.health.alerts_block()
                if self.health is not None
                else None
            ),
            instance=(
                self.instance if self.leases is not None else None
            ),
            instances=(
                self.leases.known_instances()
                if self.leases is not None else None
            ),
        )
        if self.publish_reports:
            self.state.publish(
                rollup,
                summary={
                    "discovered": self.discovered,
                    "polls": self.polls,
                },
            )
        return rollup

    def _evaluate_health(self) -> None:
        """One alert-engine pass at a fleet poll/wave boundary, with the
        per-topic lag map (per-topic lag-growth scopes) and the failed
        set (the fleet-topic-failure rule) as context."""
        if self.health is None:
            return
        self.health.evaluate(
            extras={
                "topics": {
                    t: s.lag for t, s in self.scans.items()
                },
                "failed_topics": [
                    t
                    for t, s in self.scans.items()
                    if s.status.status == "failed"
                ],
                # Cumulative per-topic lost records (the lost-range
                # rule's per-topic scopes): summed from each scan's
                # result so one topic's retention race never fires the
                # alert against its fleet-mates.
                "topic_loss": {
                    t: sum(
                        d.get("records", 0)
                        for p, d in s.result.lost_partitions.items()
                        if p >= 0
                    )
                    for t, s in self.scans.items()
                    if s.result is not None
                },
            }
        )

    def _checkpoint_due(self) -> bool:
        if self.snapshot_dir is None or self.follow is None:
            return False
        if self._clock() - self._last_ckpt >= self.follow.checkpoint_every_s:
            self._last_ckpt = self._clock()
            return True
        return False

    def _finish(self) -> FleetResult:
        rollup = self._publish_rollup()
        duration = int(self._clock() - self._t0)
        obs_events.emit(
            "scan_end",
            topic="<fleet>",
            records=sum(s.status.records for s in self.scans.values()),
            duration_secs=duration,
            degraded=sum(
                1 for s in self.scans.values() if s.status.status == "degraded"
            ),
            corrupt_frames=sum(
                d.get("frames", 0)
                for s in self.scans.values()
                if s.result is not None
                for p, d in s.result.corrupt_partitions.items()
                if p >= 0
            ),
        )
        self.spinner.finish_with_message("done")
        for scan in self.scans.values():
            if scan.source is not None and hasattr(scan.source, "close"):
                try:
                    scan.source.close()
                except Exception:
                    log.exception(
                        "fleet: closing source for %r failed", scan.seed.name
                    )
        return FleetResult(
            statuses={t: s.status for t, s in self.scans.items()},
            results={
                t: s.result
                for t, s in self.scans.items()
                if s.result is not None
            },
            rollup=rollup,
            duration_secs=duration,
        )

    def _start_banner(self) -> None:
        serve_state.set_active(self.state)
        if self.health is not None:
            obs_health.set_active(self.health)
        self._t0 = self._clock()
        if self.resume and self.snapshot_dir is not None:
            from kafka_topic_analyzer_tpu.checkpoint import (
                list_topic_snapshots,
            )

            for topic, info in list_topic_snapshots(self.snapshot_dir).items():
                log.info(
                    "fleet: topic %r will resume from a snapshot at "
                    "records_seen=%s", topic, info.get("records_seen"),
                )
        obs_events.emit(
            "scan_start",
            topic="<fleet>",
            partitions=sum(s.seed.partitions for s in self.scans.values()),
            batch_size=self.batch_size,
            fleet=True,
            topics=len(self.scans),
            follow=self.follow is not None,
        )

    # -- batch fleet ----------------------------------------------------------

    def run_batch(self) -> FleetResult:
        """Scan every topic once, in scheduler-planned waves of at most
        ``max_concurrent`` concurrent scans, sharing the worker budget
        within each wave."""
        self._start_banner()
        waves = self.scheduler.plan_waves(
            [s.seed for s in self.scans.values()]
        )
        for wave in waves:
            if self._stop.is_set():
                break
            ready = []
            for topic in wave:
                scan = self.scans[topic]
                if not self._ensure_source(scan):
                    continue
                if scan.source.is_empty():
                    # A fleet audit reports the empty topic as a status
                    # row — the solo scan's exit(-2) contract stays solo.
                    scan.status.status = "empty"
                    continue
                ready.append(
                    TopicSeed(
                        name=topic,
                        partitions=len(scan.source.partitions()),
                        lag=scan.source.total_records(),
                    )
                )
            self.scheduler.skip_idle(
                sum(1 for t in wave if self.scans[t].status.status == "empty")
            )
            # Acquire-before-scan (batch form): topics another instance
            # owns drop out of the wave — their refusals are booked by
            # the manager, and a concurrent batch audit splits the
            # cluster between instances instead of double-scanning it.
            if self.leases is not None:
                ready = [
                    s for s in ready
                    if self.leases.is_held(s.name)
                    or self.leases.acquire(s.name) is not None
                ]
            # Admission can defer part of the wave (the dispatch-token
            # budget caps concurrent device scans below the wave size);
            # re-offer the deferred remainder until the wave drains — a
            # batch fleet must scan EVERY topic, deferral only sequences.
            pending = ready
            while pending and not self._stop.is_set():
                grants = self.scheduler.admit(pending, reason="admitted-seed")
                if not grants:
                    break  # budget gone for good (cannot happen while
                    # grants release below, but never spin on it)
                self.spinner.set_message(
                    f"[fleet | wave: {', '.join(sorted(grants))}]"
                )
                with ThreadPoolExecutor(max_workers=len(grants)) as pool:
                    futures = {
                        t: pool.submit(
                            self._run_pass, self.scans[t], g, True
                        )
                        for t, g in grants.items()
                    }
                    for t, fut in futures.items():
                        fut.result()  # _run_pass never raises
                        self.scheduler.release(t)
                        if self.leases is not None:
                            self.leases.release(t)
                pending = [s for s in pending if s.name not in grants]
            self._evaluate_health()
            self._publish_rollup()
        return self._finish()

    # -- fleet follow ---------------------------------------------------------

    def _poll_topic(self, scan: _TopicScan) -> int:
        """Refresh one topic's watermarks through its retry budget and
        return its lag behind the head (0 on a failed/unbuildable
        source)."""
        if scan.status.status == "failed" or not self._ensure_source(scan):
            return 0
        try:
            start_w, end_w = scan.source.refresh_watermarks()
        except BaseException as e:  # noqa: BLE001 — isolation boundary
            scan.status.status = "failed"
            scan.status.error = f"{type(e).__name__}: {e}"
            log.exception("fleet: poll of topic %r failed", scan.seed.name)
            # A topic can fail while HOLDING a grant (admitted last poll,
            # broker died before this one): return its budget, or the
            # pool shrinks permanently with every such failure.
            self.scheduler.release(scan.seed.name)
            return 0
        lag = 0
        for p, end in end_w.items():
            lag += max(0, end - scan.cursor.get(p, start_w.get(p, 0)))
        scan.lag = lag
        scan.status.lag = lag
        # EVERY instance polls EVERY topic (polling is how lag is
        # discovered before acquiring), but the lag gauge merges by sum
        # across the fleet — so only the lease holder reports a topic's
        # lag; everyone else pins 0, or a federated scrape over-counts
        # cluster lag ~N-fold.  The returned lag stays real either way:
        # admission needs it to decide WHETHER to acquire.
        held = self.leases is None or self.leases.is_held(scan.seed.name)
        obs_metrics.FLEET_TOPIC_LAG.labels(
            topic=scan.seed.name, instance=self.instance
        ).set(lag if held else 0)
        return lag

    def run_follow(self) -> FleetResult:
        """The multi-topic tail loop — ROADMAP item 2's second tenant of
        the follow service: per poll, every topic's watermarks refresh,
        lagging topics are admitted (or keep their grants), admitted
        topics run one pass each (concurrently, bounded by the
        scheduler's concurrency), and the doctor's per-topic verdicts
        rebalance the budgets before the next poll."""
        assert self.follow is not None, "run_follow needs a FollowConfig"
        self._start_banner()
        idle_streak = 0
        idle_since: "Optional[float]" = None
        while True:
            self.polls += 1
            if (
                self.rediscover is not None
                and self.polls > 1
                and (self.polls - 1) % self.rediscover_every == 0
            ):
                try:
                    for seed in self.rediscover():
                        if seed.name not in self.scans:
                            self.scans[seed.name] = _TopicScan(seed)
                            self.discovered += 1
                            log.info(
                                "fleet: discovered new topic %r", seed.name
                            )
                except BaseException:  # noqa: BLE001 — isolation boundary
                    log.exception("fleet: re-discovery failed; keeping list")
            lags = {
                t: self._poll_topic(s) for t, s in list(self.scans.items())
            }
            lag_total = sum(lags.values())
            # Poll-boundary renewal (DESIGN §23): every held lease's
            # expiry extends here, once per poll — a store blip books
            # "deferred" inside the manager and the loop keeps going.
            if self.leases is not None:
                self.leases.renew_all()
            # The pause seam sits EXACTLY after the renew: a paused
            # instance's leases are as fresh as they will ever be, and
            # everything after resume runs on epochs that may have been
            # fenced meanwhile — the window the checkpoint-epoch check
            # must cover (tests/test_lease.py's zombie proof).
            while self._pause.is_set() and not self._stop.is_set():
                self.paused = True
                time.sleep(0.005)
            self.paused = False
            if self._killed:
                # Crash semantics: not one more admission, pass, or lease
                # decision after kill() — leases dangle exactly as a
                # SIGKILL would leave them.
                break
            # Poll-boundary health: the lag map just refreshed, so a
            # diverging topic flips /healthz within one poll.
            self._evaluate_health()
            ready = [
                TopicSeed(
                    name=t,
                    partitions=max(1, self.scans[t].status.partitions),
                    lag=lag,
                )
                for t, lag in sorted(lags.items())
                if lag > 0 or (
                    self.scans[t].first
                    and self.scans[t].status.status not in ("failed", "empty")
                    and self.scans[t].source is not None
                    and not self.scans[t].source.is_empty()
                )
            ]
            # Acquire-before-scan: a topic enters admission only under
            # a held (or just-acquired) lease.  Refusals are already
            # booked by the manager (held-elsewhere / lost-race /
            # store-error on kta_lease_acquisitions_total), so they are
            # excluded from the skipped-empty count below — they had
            # work, it just belongs to another instance.
            not_ours: "set" = set()
            if self.leases is not None:
                gated = []
                for s in ready:
                    if self.leases.is_held(s.name) or (
                        self.leases.acquire(s.name) is not None
                    ):
                        gated.append(s)
                    else:
                        not_ours.add(s.name)
                ready = gated
            ready_names = {s.name for s in ready}
            self.scheduler.admit(ready)
            # "Skipped because empty" means exactly that: topics that
            # polled at the head with no work.  Failed topics are not
            # admission decisions (their trace ended at the failure), so
            # booking them here would corrupt the reconstructible trace.
            self.scheduler.skip_idle(
                sum(
                    1
                    for t in lags
                    if t not in ready_names
                    and t not in not_ours
                    and self.scans[t].status.status != "failed"
                )
            )
            admitted = {
                t: g
                for t, g in self.scheduler.grants().items()
                if t in self.scans and t in ready_names
            }
            if admitted:
                idle_streak = 0
                idle_since = None
                with ThreadPoolExecutor(max_workers=len(admitted)) as pool:
                    futures = {
                        t: pool.submit(self._run_pass, self.scans[t], g)
                        for t, g in admitted.items()
                    }
                    for t, fut in futures.items():
                        fut.result()  # _run_pass never raises
                if self._killed:
                    # kill() landed while passes ran: no post-pass
                    # bookkeeping, no caught-up lease release — the
                    # failover tests need exactly what a crash leaves.
                    break
                # Post-pass bookkeeping: verdicts drive the rebalance;
                # caught-up (or failed) topics return their budget.
                verdicts = {}
                for t in admitted:
                    scan = self.scans[t]
                    if scan.status.status == "failed":
                        self.scheduler.release(t)
                        # Let another instance try the topic — this
                        # one's source/backend is poisoned.
                        if self.leases is not None:
                            self.leases.release(t)
                        continue
                    if scan.status.status == "fenced":
                        # The lease itself was already fenced inside
                        # _run_pass; only the budget comes back here.
                        self.scheduler.release(t)
                        continue
                    caught_up = all(
                        scan.cursor.get(p, 0) >= end
                        for p, end in scan.source.watermarks()[1].items()
                    )
                    scan.lag = 0 if caught_up else scan.lag
                    scan.status.lag = scan.lag
                    if caught_up:
                        self.scheduler.release(t)
                        # Release-on-caught-up: a topic at the head is
                        # up for grabs again — ownership follows work.
                        if self.leases is not None:
                            self.leases.release(t)
                    elif scan.status.verdict:
                        verdicts[t] = scan.status.verdict
                if verdicts:
                    self.scheduler.rebalance(verdicts)
                self._evaluate_health()
                self._publish_rollup()
            else:
                idle_streak += 1
                now = self._clock()
                if idle_since is None:
                    idle_since = now
                if (
                    self.follow.idle_exit_s is not None
                    and now - idle_since >= self.follow.idle_exit_s
                ):
                    self.request_stop("idle")
                self._publish_rollup()
            if self.scans and all(
                s.status.status == "failed" for s in self.scans.values()
            ):
                # Failure isolation needs survivors: when EVERY topic is
                # terminally failed (e.g. the whole cluster is
                # unreachable) there is nothing left to follow — exit
                # like the solo scan's hard error instead of polling a
                # dead cluster forever.
                self.request_stop("all-failed")
            if self._stop.is_set():
                break
            self.spinner.set_message(
                f"[fleet | topics: {len(self.scans)} | "
                f"active: {self.scheduler.active} | lag: {lag_total} | "
                f"polls: {self.polls}]"
            )
            delay = (
                self.follow.poll_interval_s
                if idle_streak == 0
                else self._idle_backoff.delay_ms(idle_streak) / 1000.0
            )
            if idle_since is not None and self.follow.idle_exit_s is not None:
                remaining = self.follow.idle_exit_s - (
                    self._clock() - idle_since
                )
                delay = max(0.0, min(delay, remaining))
            if self._stop.wait(delay):
                break
        # Shutdown boundary: one final pass per live topic commits the
        # final checkpoint (superbatch boundary by construction) and
        # settles each status row for the closing rollup — then every
        # held lease is RELEASED (not just checkpointed), so a rolling
        # restart under SIGTERM (serve/signals.py → request_stop) fails
        # over immediately instead of waiting out the TTL.  ``kill()``
        # skips all of it: a crash leaves leases dangling, and failover
        # happens the honest way, by expiry.
        if not self._killed:
            for t, scan in self.scans.items():
                if scan.backend is None or scan.status.status in (
                    "failed", "fenced",
                ):
                    continue
                if self.leases is not None and not self.leases.is_held(t):
                    # No lease, no write: a final pass on a topic we
                    # released (or never owned) would checkpoint with no
                    # epoch stamp, bypassing the fence.  Its last
                    # in-lease checkpoint stands; a successor rescans
                    # the (small) tail from there.
                    continue
                grant = (
                    self.scheduler.grant_for(t)
                    or scan.last_grant
                    or Grant(workers=1, dispatch_depth=1)
                )
                self._run_pass(scan, grant, final=True)
                self.scheduler.release(t)
            if self.leases is not None:
                self.leases.release_all()
        obs_events.emit(
            "follow_stop",
            reason=self._stop_reason or "stop",
            polls=self.polls,
            passes=sum(s.status.passes for s in self.scans.values()),
            fleet=True,
        )
        return self._finish()
