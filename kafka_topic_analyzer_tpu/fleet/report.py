"""The cluster rollup document: the fleet's answer in one page.

Per-topic documents stay the solo ``--json`` schema (one builder,
``report.build_json_doc`` — /report.json?topic= can never drift from a
solo scan's output).  This module builds the document ABOVE them: the
cluster totals, the top-N topics by records/bytes/lag, and the per-topic
status/verdict rows — what ``--fleet --json`` prints, what ``--stats``
tabulates (report.render_fleet_status), and what the bare ``/report.json``
endpoint serves while a fleet runs.
"""

from __future__ import annotations

from typing import Dict, List

#: Rows in each "top topics by X" list — a rollup is a summary, the full
#: per-topic detail lives one ``?topic=`` away.
TOP_N = 5


def _top(statuses: "Dict[str, object]", key: str) -> "List[dict]":
    ranked = sorted(
        ((t, getattr(s, key)) for t, s in statuses.items()),
        key=lambda kv: (-kv[1], kv[0]),
    )
    return [
        {"topic": t, key: v} for t, v in ranked[:TOP_N] if v > 0
    ]


def build_fleet_rollup(
    statuses: "Dict[str, object]",
    discovered: int,
    duration_secs: int,
    health: "dict | None" = None,
    instance: "str | None" = None,
    instances: "List[str] | None" = None,
) -> dict:
    """``statuses`` maps topic -> fleet.service.TopicStatus; ``health``
    is the alert engine's latest document (obs/health.py), riding the
    rollup so the bare ``/report.json`` path answers "is the fleet
    healthy" next to the totals (each topic's own alerts ride its
    ``?topic=`` document).  ``instance`` labels which analyzer built
    THIS rollup and ``instances`` lists every instance visible through
    the lease store (DESIGN §23 federation): a dashboard scraping N
    instances can attribute each document and detect a vanished peer —
    each rollup only ever covers the topics its own instance scans."""
    counts: "Dict[str, int]" = {}
    verdicts: "Dict[str, int]" = {}
    for s in statuses.values():
        counts[s.status] = counts.get(s.status, 0) + 1
        if getattr(s, "verdict", ""):
            verdicts[s.verdict] = verdicts.get(s.verdict, 0) + 1
    doc = {
        "fleet": {
            "topics_discovered": discovered,
            "topics": len(statuses),
            "status_counts": dict(sorted(counts.items())),
            # Per-topic doctor verdicts at a glance: how many topics
            # attribute ingest- vs dispatch-bound right now (the
            # per-topic label itself is in each status row below).
            "verdict_counts": dict(sorted(verdicts.items())),
            "totals": {
                "records": sum(s.records for s in statuses.values()),
                "bytes": sum(s.bytes for s in statuses.values()),
                "lag": sum(s.lag for s in statuses.values()),
                "passes": sum(s.passes for s in statuses.values()),
            },
            "top_topics": {
                "by_records": _top(statuses, "records"),
                "by_bytes": _top(statuses, "bytes"),
                "by_lag": _top(statuses, "lag"),
            },
            "statuses": {
                t: statuses[t].as_dict() for t in sorted(statuses)
            },
        },
        "duration_secs": duration_secs,
    }
    if instance is not None:
        doc["fleet"]["instance"] = instance
    if instances is not None:
        doc["fleet"]["instances"] = list(instances)
    if health is not None:
        doc["health"] = health
    return doc
