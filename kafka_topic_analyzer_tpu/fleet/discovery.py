"""Cluster topic discovery: metadata → the fleet's topic list.

One all-topics Metadata request (``io/kafka_wire.discover_cluster_topics``
— the same v5–v12 negotiation the per-topic source runs, with a null
topic array) answers "what could a fleet scan cover"; this module turns
that raw listing into the list a fleet scan *should* cover:

- **include globs** (``-t`` under ``--fleet``; comma-separated fnmatch
  patterns, default ``*``) select topics by name;
- **exclude globs** (``--fleet-exclude``) drop matches back out — applied
  after includes, so ``-t 'orders.*' --fleet-exclude '*.dlq'`` reads the
  way it is written;
- **internal topics** (``__consumer_offsets``-style) are dropped unless
  ``--fleet-internal`` asks for them: both the broker's ``is_internal``
  metadata flag and the ``__`` name prefix count, because older brokers
  (Metadata v0/v1 era) did not always flag system topics.

Errored topic metadata (a topic mid-deletion answers with an error code)
is skipped with a log line — a fleet audit reports the cluster that
exists, it does not abort on the one topic that is going away.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import logging
from typing import Iterable, List, Optional, Sequence

log = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class DiscoveredTopic:
    """One discovery hit: everything the scheduler needs to seed a scan
    without a per-topic handshake."""

    name: str
    #: Partition count from the metadata response — the admission
    #: scheduler's seed weight (watermark lag refines it once the topic's
    #: source exists).
    partitions: int
    #: Broker-flagged or ``__``-prefixed system topic.
    internal: bool = False


def parse_globs(spec: "Optional[str]") -> "List[str]":
    """Comma-separated glob list → pattern list ('' / None → no patterns)."""
    if not spec:
        return []
    return [g.strip() for g in spec.split(",") if g.strip()]


def is_internal_name(name: str) -> bool:
    """``__consumer_offsets``-style system-topic naming (the prefix
    convention predates the metadata flag)."""
    return name.startswith("__")


def filter_topics(
    topics: "Iterable[DiscoveredTopic]",
    include: "Sequence[str]" = ("*",),
    exclude: "Sequence[str]" = (),
    include_internal: bool = False,
) -> "List[DiscoveredTopic]":
    """Apply include/exclude globs + internal exclusion; sorted by name
    so every fleet run (and every re-discovery poll) sees a deterministic
    ordering."""
    include = list(include) or ["*"]
    out = []
    for t in topics:
        if t.internal and not include_internal:
            continue
        if not any(fnmatch.fnmatchcase(t.name, g) for g in include):
            continue
        if any(fnmatch.fnmatchcase(t.name, g) for g in exclude):
            continue
        out.append(t)
    return sorted(out, key=lambda t: t.name)


def discover_topics(
    bootstrap_servers: str,
    include: "Sequence[str]" = ("*",),
    exclude: "Sequence[str]" = (),
    include_internal: bool = False,
    timeout_s: float = 10.0,
) -> "List[DiscoveredTopic]":
    """All-topics metadata → filtered, sorted `DiscoveredTopic` list.

    An empty result is a valid answer (an empty cluster, or filters that
    match nothing) — the CLI decides whether that is an error."""
    from kafka_topic_analyzer_tpu.io.kafka_wire import discover_cluster_topics

    found: "List[DiscoveredTopic]" = []
    for md in discover_cluster_topics(bootstrap_servers, timeout_s=timeout_s):
        if md.error:
            log.warning(
                "discovery: skipping topic %r (metadata error %d)",
                md.name, md.error,
            )
            continue
        found.append(
            DiscoveredTopic(
                name=md.name,
                partitions=len(md.partitions),
                internal=bool(md.is_internal) or is_internal_name(md.name),
            )
        )
    return filter_topics(found, include, exclude, include_internal)
