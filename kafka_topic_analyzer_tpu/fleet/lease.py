"""Per-topic ownership leases: the fleet's arbitration layer (DESIGN §23).

PR 13's fleet service assumed ONE process owned the whole cluster —
admission in `fleet/scheduler.py` is in-memory, so two analyzer
instances pointed at the same brokers would scan every topic twice and
clobber each other's checkpoints.  This module adds the missing
agreement primitive: a per-topic *lease* persisted through a store the
system already trusts (the checkpoint directory, or the PR-14 object
store), carrying

- an **owner** (the analyzer instance id, or None once released),
- a monotonically increasing **epoch** (bumped on every ownership
  change, NEVER reset — released records keep their epoch so a zombie
  can never reacquire at epoch 1), and
- an **expiry** (owner's local clock + TTL; renewed at poll
  boundaries).

The epoch is the fencing token: `checkpoint.save_snapshot` /
`load_snapshot` stamp and check it, so an instance that lost its lease
while paused mid-pass (a *zombie*) has its late checkpoint write
refused with a named `StaleLeaseEpochError` instead of silently
clobbering its successor's state.

Two store backends, one contract (``read`` → (lease, token), ``write``
→ new token or None on a lost compare-and-swap race):

- `FileLeaseStore`: JSON records under a reserved ``_kta_leases/``
  subdirectory of the checkpoint dir (the ``_kta_history`` precedent),
  written tmp-file → ``os.replace``.  Atomic rename has no native CAS,
  so one is built: the token is the record body the caller READ, and
  the write — inside a short O_EXCL lock file (stale locks older than
  its hold bound are broken) — re-reads the current record and refuses
  unless it still matches that token (None = expect absent).  Without
  the compare, two instances that both read "absent/expired" would
  serialize through the lock and BOTH be granted the same epoch — a
  split-brain the checkpoint fence cannot catch, since it only rejects
  OLDER epochs.  A read-back verify after the replace additionally
  catches a racer that bypassed or broke the lock; either way a lost
  race reports as None, never as a silent double-grant.
- `ObjectLeaseStore`: ETag-fenced conditional writes through
  `io/objstore.RetryingHttp.put_conditional` (``If-Match`` to replace
  the exact version read, ``If-None-Match: *`` to create).  A PUT
  retried across a transport error is AMBIGUOUS — the first attempt may
  have been applied — so a 412 is resolved by reading the record back
  and comparing owner/epoch before declaring the race lost.

`LeaseManager` drives the acquire / renew / release / fence state
machine on top, clock-injectable and degrade-not-crash: a store blip
during renewal books ``kta_lease_renewals_total{outcome="deferred"}``
and keeps scanning while the lease is locally unexpired (retries ride
`io/retry.Backoff`); the manager self-fences only when it OBSERVES a
newer epoch/other owner, or when local expiry passes with no
successful renewal.  Every held-lease state change routes through the
single ``_transition`` point, which books the ``kta_lease_*``
instruments and emits the typed event (tools/lint.sh rule 13 — the
alert-engine rule-12 discipline, applied here): the ownership history
of every topic is reconstructible from the counters alone.
"""

from __future__ import annotations

import dataclasses
import errno
import json
import logging
import os
import time
from typing import Callable, Dict, List, Optional, Set, Tuple

from kafka_topic_analyzer_tpu.obs import events as obs_events
from kafka_topic_analyzer_tpu.obs import metrics as obs_metrics

log = logging.getLogger(__name__)

#: Reserved subdirectory of the checkpoint/snapshot dir holding lease
#: records — same carve-out discipline as checkpoint.HISTORY_DIR_NAME:
#: topic snapshot subdirectories and lease records share a parent, so
#: the name must never collide with a topic directory kta would create.
LEASE_DIR_NAME = "_kta_leases"


@dataclasses.dataclass(frozen=True)
class Lease:
    """One topic's ownership record as persisted in the store."""

    topic: str
    #: Analyzer instance id, or None once released (the record is KEPT —
    #: deleting it would reset the epoch and unfence every zombie).
    owner: "Optional[str]"
    #: Monotonically increasing fencing token: bumped on every ownership
    #: change, never on renewal (a renewal extends expiry, it does not
    #: change who owns the topic).
    epoch: int
    #: Owner's local clock + TTL at the last acquire/renew.
    expires_at: float
    acquired_at: float

    def to_json(self) -> bytes:
        return json.dumps(
            {
                "topic": self.topic,
                "owner": self.owner,
                "epoch": int(self.epoch),
                "expires_at": float(self.expires_at),
                "acquired_at": float(self.acquired_at),
            },
            sort_keys=True,
        ).encode("utf-8")

    @classmethod
    def from_json(cls, data: bytes) -> "Lease":
        d = json.loads(data.decode("utf-8"))
        return cls(
            topic=str(d["topic"]),
            owner=d.get("owner"),
            epoch=int(d["epoch"]),
            expires_at=float(d["expires_at"]),
            acquired_at=float(d.get("acquired_at", 0.0)),
        )


def _safe_name(topic: str) -> str:
    """Filesystem/key-safe record name for a topic (Kafka topic names
    allow dots; path separators cannot appear, but be defensive)."""
    return "".join(
        c if c.isalnum() or c in "._-" else "_" for c in topic
    )


class FileLeaseStore:
    """Lease records as JSON files under ``{directory}/_kta_leases/``.

    The write path is lock → compare → tmp → ``os.replace`` →
    read-back verify.  The token is the raw record body the caller
    read (None = expect absent): inside the O_EXCL lock the current
    record is re-read and a mismatch fails the CAS — this is what
    stops two lock-serialized writers that both read "absent/expired"
    from each being granted the same epoch.  The read-back after the
    replace catches a racer that broke or ignored the lock — either
    way a lost race reports as None, never as a silent double-grant.
    ``verify_hook`` is a test seam invoked between the replace and the
    read-back, where an injected competing write must be detected.
    """

    #: A lock older than this is a crashed writer's leavings and is
    #: broken — the write section holds it for microseconds, so seconds
    #: of age is unambiguous abandonment.
    LOCK_STALE_S = 5.0

    def __init__(
        self,
        directory: str,
        verify_hook: "Optional[Callable[[str], None]]" = None,
    ):
        self.directory = os.path.join(directory, LEASE_DIR_NAME)
        os.makedirs(self.directory, exist_ok=True)
        self.verify_hook = verify_hook

    def _path(self, topic: str) -> str:
        return os.path.join(self.directory, f"{_safe_name(topic)}.json")

    def read(self, topic: str) -> "Tuple[Optional[Lease], Optional[str]]":
        try:
            with open(self._path(topic), "rb") as f:
                data = f.read()
        except FileNotFoundError:
            return None, None
        # The token is the exact body read — surrogateescape so even a
        # non-UTF-8 corrupt record round-trips byte-faithfully into the
        # CAS comparison.
        token = data.decode("utf-8", "surrogateescape")
        try:
            return Lease.from_json(data), token
        except (ValueError, KeyError):
            # A truncated/corrupt record cannot arbitrate ownership;
            # treat it as absent, but KEEP the token: a None token means
            # "expect absent" and the CAS would refuse the overwrite
            # forever.  With the wreck's own bytes as the token the next
            # write replaces it — at epoch 1, the honest floor when
            # history is gone.
            log.warning("lease: unreadable record for %r; treating as absent",
                        topic)
            return None, token

    def write(
        self, topic: str, lease: Lease, token: "Optional[str]"
    ) -> "Optional[str]":
        """Compare-and-swap under the lock: ``token`` is the record
        body the caller read (None = expect absent).  Returns the new
        token on success, None when the CAS failed or a competing
        writer won the race."""
        path = self._path(topic)
        lock = path + ".lock"
        try:
            fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            os.close(fd)
        except OSError as e:
            if e.errno != errno.EEXIST:
                raise
            try:
                age = time.time() - os.stat(lock).st_mtime
            except OSError:
                age = 0.0
            if age < self.LOCK_STALE_S:
                return None  # a live writer holds the section: lost race
            # Crashed writer: break the lock and take the section.
            try:
                os.unlink(lock)
            except OSError:
                pass
            try:
                fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                os.close(fd)
            except OSError:
                return None
        try:
            # The compare step: the record must still be exactly what
            # the caller saw when it DECIDED on this write.  A racer
            # that wrote since — even one that politely waited its turn
            # on the lock — fails the CAS here, so two instances that
            # both read "absent/expired" can never both be granted the
            # same epoch.
            try:
                with open(path, "rb") as f:
                    current = f.read().decode("utf-8", "surrogateescape")
            except FileNotFoundError:
                current = None
            if current != token:
                return None  # the state the caller decided on is gone
            body = lease.to_json()
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(body)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            if self.verify_hook is not None:
                self.verify_hook(topic)
            with open(path, "rb") as f:
                if f.read() != body:
                    return None  # a lock-bypassing racer overwrote us
            return body.decode("utf-8")
        finally:
            try:
                os.unlink(lock)
            except OSError:
                pass

    def owners(self) -> "Set[str]":
        """Every instance id currently named on a live (non-released)
        record — the rollup's federation view."""
        out: "Set[str]" = set()
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        for name in names:
            if not name.endswith(".json"):
                continue
            try:
                with open(os.path.join(self.directory, name), "rb") as f:
                    lease = Lease.from_json(f.read())
            except (OSError, ValueError, KeyError):
                continue
            if lease.owner:
                out.add(lease.owner)
        return out


class ObjectLeaseStore:
    """Lease records as ``_kta_leases/{topic}.json`` objects behind
    `io/objstore.RetryingHttp`, fenced by conditional PUTs.

    The token is the object's ETag.  A None from ``put_conditional``
    (HTTP 412) is NOT immediately a lost race: the PUT may have been
    retried across a transport error after the server applied the first
    attempt, in which case the 412 is our own write fencing us out.
    The record is read back and the race declared lost only when the
    stored owner/epoch differ from what we tried to write.
    """

    def __init__(self, http, prefix: str = f"{LEASE_DIR_NAME}/"):
        self.http = http
        self.prefix = prefix

    def _path(self, topic: str) -> str:
        return self.http.object_path(
            f"{self.prefix}{_safe_name(topic)}.json"
        )

    def read(self, topic: str) -> "Tuple[Optional[Lease], Optional[str]]":
        got = self.http.get_small(self._path(topic))
        if got is None:
            return None, None
        body, etag = got
        try:
            return Lease.from_json(body), etag
        except (ValueError, KeyError):
            # Surface the wreck's ETag: a None token would make the next
            # write an If-None-Match create that 412s against the object
            # forever — the topic would be permanently unacquirable.
            # With the ETag the next write If-Match-overwrites it.
            log.warning("lease: unreadable record for %r; treating as absent",
                        topic)
            return None, etag

    def write(
        self, topic: str, lease: Lease, token: "Optional[str]"
    ) -> "Optional[str]":
        body = lease.to_json()
        path = self._path(topic)
        if token is None:
            etag = self.http.put_conditional(path, body, if_none_match=True)
        else:
            etag = self.http.put_conditional(path, body, if_match=token)
        if etag is not None:
            return etag or "etag"
        # 412: lost race, OR our own ambiguous earlier attempt.  Read
        # back and compare — owner+epoch identify the writer uniquely
        # (epochs are monotone, so a successor can never echo ours).
        cur, cur_token = self.read(topic)
        if (
            cur is not None
            and cur.owner == lease.owner
            and cur.epoch == lease.epoch
        ):
            return cur_token or "etag"
        return None

    def owners(self) -> "Set[str]":
        out: "Set[str]" = set()
        try:
            names = self.http.list_objects(self.prefix)
        except Exception:
            return out
        for name, _size in names:
            if not name.endswith(".json"):
                continue
            topic = name[: -len(".json")]
            try:
                lease, _tok = self.read(topic)
            except Exception:
                continue
            if lease is not None and lease.owner:
                out.add(lease.owner)
        return out


@dataclasses.dataclass
class _Held:
    """The manager's local view of one held lease.  ``state`` moves
    ONLY inside `LeaseManager._transition` (lint rule 13): held →
    released | lost, with the loss reason ("fenced" | "expired")
    recorded on the transition."""

    topic: str
    epoch: int
    expires_at: float
    token: "Optional[str]"
    state: str = "held"


class LeaseManager:
    """The acquire / renew / release / fence state machine (DESIGN §23).

    Clock-injectable (``clock`` defaults to ``time.time`` — expiry is
    WALL time, shared via the store across instances, unlike the fleet
    loop's monotonic pass clock) and store-agnostic.  Every decision
    books a ``kta_lease_*`` reason; no path is silent.
    """

    def __init__(
        self,
        store,
        instance: str,
        ttl_s: float = 30.0,
        clock: "Callable[[], float]" = time.time,
        backoff=None,
        renew_attempts: int = 3,
    ):
        if not instance:
            raise ValueError("lease manager needs a non-empty instance id")
        if ttl_s <= 0:
            raise ValueError("lease TTL must be > 0")
        self.store = store
        self.instance = instance
        self.ttl_s = float(ttl_s)
        self.clock = clock
        #: io/retry.Backoff for transient store errors during renewal
        #: (injectable sleep keeps the outage tests clockless).
        self.backoff = backoff
        self.renew_attempts = max(1, int(renew_attempts))
        self._held: "Dict[str, _Held]" = {}

    # -- the single transition point (lint rule 13) ---------------------------

    def _transition(self, rec: _Held, new_state: str, outcome: str) -> None:
        """Move one held lease to its next state and book WHY — the one
        place ``_Held.state`` changes, so the counters reconstruct the
        full ownership history (rule 13, mirroring the alert engine's
        rule 12)."""
        rec.state = new_state
        if new_state == "held":
            obs_metrics.LEASE_ACQUISITIONS.labels(
                outcome=outcome, instance=self.instance
            ).inc()
            obs_metrics.LEASE_HELD.labels(
                topic=rec.topic, instance=self.instance
            ).set(1)
            if outcome == "takeover":
                obs_metrics.FLEET_FAILOVERS.labels(
                    instance=self.instance
                ).inc()
        elif new_state == "released":
            obs_metrics.LEASE_ACQUISITIONS.labels(
                outcome="released", instance=self.instance
            ).inc()
            obs_metrics.LEASE_HELD.labels(
                topic=rec.topic, instance=self.instance
            ).set(0)
        elif new_state == "lost":
            obs_metrics.LEASE_LOSSES.labels(instance=self.instance).inc()
            obs_metrics.LEASE_HELD.labels(
                topic=rec.topic, instance=self.instance
            ).set(0)
        obs_events.emit(
            "lease_transition",
            topic=rec.topic,
            instance=self.instance,
            epoch=rec.epoch,
            state=new_state,
            outcome=outcome,
        )

    # -- local views ----------------------------------------------------------

    def is_held(self, topic: str) -> bool:
        """Locally held — deliberately NOT expiry-checked here: expiry
        is enforced by the renewal path (an expired-unrenewed lease
        transitions to lost there), and the epoch fence covers the
        window in between (a stale pass's checkpoint is refused)."""
        rec = self._held.get(topic)
        return rec is not None and rec.state == "held"

    def epoch(self, topic: str) -> "Optional[int]":
        rec = self._held.get(topic)
        return rec.epoch if rec is not None and rec.state == "held" else None

    def held_topics(self) -> "List[str]":
        return sorted(
            t for t, r in self._held.items() if r.state == "held"
        )

    def known_instances(self) -> "List[str]":
        """Every instance id visible through the lease store, plus this
        one — the rollup's federation block.  A store outage degrades to
        the local view (never raises)."""
        try:
            others = self.store.owners()
        except Exception:
            others = set()
        return sorted(others | {self.instance})

    # -- decisions (every one books a kta_lease_* reason) ---------------------

    def acquire(self, topic: str) -> "Optional[int]":
        """Try to take ownership of ``topic``; returns the held epoch or
        None.  Epoch rules: no record → 1; expired, released, or
        self-owned record → record.epoch + 1; live record owned
        elsewhere → refused ("held-elsewhere").  Taking over ANOTHER
        instance's expired/released lease is a failover and books
        ``kta_fleet_failovers_total``."""
        if self.is_held(topic):
            return self._held[topic].epoch
        now = self.clock()
        try:
            cur, token = self.store.read(topic)
        except Exception as e:
            obs_metrics.LEASE_ACQUISITIONS.labels(
                outcome="store-error", instance=self.instance
            ).inc()
            log.warning("lease: store read for %r failed: %s", topic, e)
            return None
        prev_owner: "Optional[str]" = None
        if cur is None:
            epoch = 1
        elif cur.owner is None or cur.owner == self.instance:
            prev_owner = cur.owner
            epoch = cur.epoch + 1
        elif cur.expires_at <= now:
            prev_owner = cur.owner
            epoch = cur.epoch + 1
        else:
            obs_metrics.LEASE_ACQUISITIONS.labels(
                outcome="held-elsewhere", instance=self.instance
            ).inc()
            return None
        lease = Lease(
            topic=topic,
            owner=self.instance,
            epoch=epoch,
            expires_at=now + self.ttl_s,
            acquired_at=now,
        )
        try:
            new_token = self.store.write(topic, lease, token)
        except Exception as e:
            obs_metrics.LEASE_ACQUISITIONS.labels(
                outcome="store-error", instance=self.instance
            ).inc()
            log.warning("lease: store write for %r failed: %s", topic, e)
            return None
        if new_token is None:
            obs_metrics.LEASE_ACQUISITIONS.labels(
                outcome="lost-race", instance=self.instance
            ).inc()
            return None
        rec = _Held(
            topic=topic,
            epoch=epoch,
            expires_at=lease.expires_at,
            token=new_token,
        )
        self._held[topic] = rec
        outcome = (
            "takeover"
            if prev_owner is not None and prev_owner != self.instance
            else "acquired"
        )
        self._transition(rec, "held", outcome)
        return epoch

    def renew(self, topic: str) -> bool:
        """Extend a held lease's expiry (same epoch — renewal never
        changes ownership).  Degrade-not-crash: a store outage books
        "deferred" and the lease stays held while locally unexpired;
        the manager self-fences only on an OBSERVED newer epoch/other
        owner ("fenced") or on local expiry with no successful renewal
        ("expired") — both book ``kta_lease_losses_total``."""
        rec = self._held.get(topic)
        if rec is None or rec.state != "held":
            return False
        attempt = 0
        while True:
            now = self.clock()
            if now >= rec.expires_at:
                # Locally expired with no successful renewal (a pause/GC
                # longer than the TTL).  Rename has no CAS, so a blind
                # write here could clobber a successor's record — read
                # first and extend only if the record is still ours.
                try:
                    cur, tok = self.store.read(topic)
                except Exception:
                    cur, tok = None, None
                if not (
                    cur is not None
                    and cur.owner == self.instance
                    and cur.epoch == rec.epoch
                ):
                    self._transition(
                        rec, "lost",
                        "fenced" if cur is not None else "expired",
                    )
                    del self._held[topic]
                    return False
                rec.token = tok
            lease = Lease(
                topic=topic,
                owner=self.instance,
                epoch=rec.epoch,
                expires_at=now + self.ttl_s,
                acquired_at=now,
            )
            try:
                new_token = self.store.write(topic, lease, rec.token)
            except Exception as e:
                attempt += 1
                if attempt < self.renew_attempts:
                    if self.backoff is not None:
                        self.backoff.sleep_for(attempt)
                    continue
                # Store outage: defer, do not self-fence early — the
                # lease is OURS until its expiry passes (renewal-outage
                # degradation, DESIGN §23 failure matrix).
                if self.clock() >= rec.expires_at:
                    self._transition(rec, "lost", "expired")
                    del self._held[topic]
                    return False
                obs_metrics.LEASE_RENEWALS.labels(
                    outcome="deferred", instance=self.instance
                ).inc()
                log.warning(
                    "lease: renew of %r deferred (store outage: %s); "
                    "holding until local expiry", topic, e,
                )
                return True
            if new_token is None:
                # CAS lost: somebody else's write is in the store.  See
                # whose — a newer epoch/other owner means we are FENCED.
                self._fence_observed(rec, topic)
                return False
            rec.token = new_token
            rec.expires_at = lease.expires_at
            obs_metrics.LEASE_RENEWALS.labels(
                outcome="renewed", instance=self.instance
            ).inc()
            return True

    def _fence_observed(self, rec: _Held, topic: str) -> None:
        """A renewal CAS lost: record the loss with the right reason
        (books LEASE_LOSSES via the transition)."""
        try:
            cur, _tok = self.store.read(topic)
        except Exception:
            cur = None
        if (
            cur is not None
            and cur.owner == self.instance
            and cur.epoch == rec.epoch
        ):
            # Our own record is live after all (e.g. a racer's write
            # lost); resync the token and keep holding.
            rec.token = _tok
            rec.expires_at = cur.expires_at
            obs_metrics.LEASE_RENEWALS.labels(
                outcome="renewed", instance=self.instance
            ).inc()
            return
        self._transition(rec, "lost", "fenced")
        del self._held[topic]

    def renew_all(self) -> None:
        for topic in list(self._held):
            self.renew(topic)

    def release(self, topic: str) -> None:
        """Give the topic up cleanly: the record is rewritten with
        owner=None and the SAME epoch (kept forever — epoch monotonicity
        is the fence), so a successor acquires instantly instead of
        waiting out the TTL (the rolling-restart path)."""
        rec = self._held.get(topic)
        if rec is None or rec.state != "held":
            return
        now = self.clock()
        lease = Lease(
            topic=topic,
            owner=None,
            epoch=rec.epoch,
            expires_at=now,
            acquired_at=now,
        )
        try:
            self.store.write(topic, lease, rec.token)
        except Exception as e:
            # Best-effort: an unreleasable lease just waits out its TTL.
            log.warning("lease: release of %r failed: %s", topic, e)
        self._transition(rec, "released", "released")
        del self._held[topic]

    def release_all(self) -> None:
        for topic in list(self._held):
            self.release(topic)

    def fence(self, topic: str, reason: str = "fenced") -> None:
        """Record an externally observed fencing — the service calls
        this when `checkpoint.StaleLeaseEpochError` surfaces from a
        pass (the zombie's refused write), booking the loss under THIS
        instance's label (checkpoint.py has no instance identity)."""
        rec = self._held.get(topic)
        if rec is None or rec.state != "held":
            return
        self._transition(rec, "lost", reason)
        del self._held[topic]
