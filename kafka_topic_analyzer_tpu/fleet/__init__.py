"""Fleet mode: one service audits the whole cluster (DESIGN.md §20).

The reference tool analyzes exactly one topic per invocation.  This
package generalizes the *scenario* axis the way ``parallel/`` generalized
the hardware axis: ``discovery`` turns cluster metadata into a filtered
topic list, ``scheduler`` shares the global ingest-worker and
dispatch-depth budgets across N concurrent per-topic scans (and
rebalances them between polls on the scan doctor's verdicts), and
``service`` drives the admitted scans — each one a plain
``engine.run_scan`` pass chain, byte-identical to a solo scan of that
topic — with per-topic failure isolation, per-topic checkpoint/report
namespacing, and a cluster rollup report.
"""
