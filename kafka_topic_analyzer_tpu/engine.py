"""Scan orchestration — the analog of ``main``'s wiring + read loop.

Replaces src/main.rs:69-121 (build analyzer → snapshot offsets → empty guard
→ register handlers → scan → report) with: build source → snapshot
watermarks → empty guard → build backend → batched scan → finalize → report.

Partition ids need not be dense (the reference keeps HashMaps keyed by id);
the engine remaps true ids to dense row indices before batches reach the
backend and maps them back in the result.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional

import numpy as np

from kafka_topic_analyzer_tpu.backends.base import MetricBackend
from kafka_topic_analyzer_tpu.io.source import RecordSource
from kafka_topic_analyzer_tpu.records import RecordBatch
from kafka_topic_analyzer_tpu.results import TopicMetrics
from kafka_topic_analyzer_tpu.utils.profiling import ScanProfile
from kafka_topic_analyzer_tpu.utils.progress import Spinner
from kafka_topic_analyzer_tpu.utils.timefmt import format_utc_seconds


class PartitionIndex:
    """Bidirectional map between true partition ids and dense row indices."""

    def __init__(self, partition_ids: "list[int]"):
        self.ids = sorted(partition_ids)
        self._sorted = np.array(self.ids, dtype=np.int64)

    def __len__(self) -> int:
        return len(self.ids)

    def to_dense(self, partition: np.ndarray) -> np.ndarray:
        return np.searchsorted(self._sorted, partition).astype(np.int32)

    def remap_batch(self, batch: RecordBatch) -> RecordBatch:
        if self.ids == list(range(len(self.ids))):
            return batch  # already dense
        batch.partition = self.to_dense(batch.partition)
        return batch


@dataclasses.dataclass
class ScanResult:
    metrics: TopicMetrics
    duration_secs: int
    profile: ScanProfile
    start_offsets: "dict[int, int]"
    end_offsets: "dict[int, int]"


def run_scan(
    topic: str,
    source: RecordSource,
    backend: MetricBackend,
    batch_size: int,
    spinner: Optional[Spinner] = None,
) -> ScanResult:
    """Full earliest→latest scan of the topic through the backend."""
    pindex = PartitionIndex(source.partitions())
    start_offsets, end_offsets = source.watermarks()
    profile = ScanProfile()
    spinner = spinner or Spinner(enabled=False)
    t0 = time.monotonic()
    seq = 0

    if hasattr(backend, "update_shards"):
        # Sharded scan: one batch stream per data shard, each restricted to
        # its own partitions (records.py ordering contract), zipped so every
        # device step carries one full batch per shard.
        from kafka_topic_analyzer_tpu.parallel.mesh import assign_partitions

        d = backend.config.data_shards
        shard_parts = assign_partitions(pindex.ids, d)
        iters = [
            source.batches(batch_size, partitions=parts) if parts else iter(())
            for parts in shard_parts
        ]
        alive = [True] * d
        while any(alive):
            shard_batches: "list[RecordBatch | None]" = []
            step_valid = 0
            with profile.stage("ingest"):
                for i, it in enumerate(iters):
                    b = next(it, None) if alive[i] else None
                    if b is None:
                        alive[i] = False
                    else:
                        step_valid += b.num_valid
                        b = pindex.remap_batch(b)
                    shard_batches.append(b)
            if step_valid == 0 and not any(alive):
                break
            with profile.stage("dispatch", items=step_valid):
                backend.update_shards(shard_batches)
            seq += step_valid
            spinner.set_message(f"[Sq: {seq} | T: {topic} | shards: {d}]")
    else:
        batches = source.batches(batch_size)
        while True:
            with profile.stage("ingest"):
                batch = next(batches, None)
            if batch is None:
                break
            nvalid = batch.num_valid
            last = len(batch) - 1
            last_partition = int(batch.partition[last])  # true id, pre-remap
            batch = pindex.remap_batch(batch)
            with profile.stage("dispatch", items=nvalid, nbytes=batch.nbytes):
                backend.update(batch)
            seq += nvalid
            spinner.set_message(
                f"[Sq: {seq} | T: {topic} | P: {last_partition} | "
                f"O: ~ | Ts: {format_utc_seconds(int(batch.ts_s[last]))}]"
            )

    with profile.stage("finalize"):
        metrics = backend.finalize()
    metrics.partitions = pindex.ids
    spinner.finish_with_message("done")
    duration_secs = int(time.monotonic() - t0)
    return ScanResult(
        metrics=metrics,
        duration_secs=duration_secs,
        profile=profile,
        start_offsets=start_offsets,
        end_offsets=end_offsets,
    )
