"""Scan orchestration — the analog of ``main``'s wiring + read loop.

Replaces src/main.rs:69-121 (build analyzer → snapshot offsets → empty guard
→ register handlers → scan → report) with: build source → snapshot
watermarks → empty guard → build backend → batched scan → finalize → report.

Partition ids need not be dense (the reference keeps HashMaps keyed by id);
the engine remaps true ids to dense row indices before batches reach the
backend and maps them back in the result.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional

import numpy as np

from kafka_topic_analyzer_tpu.backends.base import MetricBackend
from kafka_topic_analyzer_tpu.config import IngestConfig
from kafka_topic_analyzer_tpu.io.source import RecordSource
from kafka_topic_analyzer_tpu.obs import events as obs_events
from kafka_topic_analyzer_tpu.obs import health as obs_health
from kafka_topic_analyzer_tpu.obs import metrics as obs_metrics
from kafka_topic_analyzer_tpu.obs import trace as obs_trace
from kafka_topic_analyzer_tpu.obs.registry import (
    default_registry,
    merge_snapshots,
)
from kafka_topic_analyzer_tpu.records import RecordBatch
from kafka_topic_analyzer_tpu.results import TopicMetrics
from kafka_topic_analyzer_tpu.utils.profiling import ScanProfile
from kafka_topic_analyzer_tpu.utils.progress import Spinner
from kafka_topic_analyzer_tpu.utils.timefmt import format_utc_seconds


class PartitionIndex:
    """Bidirectional map between true partition ids and dense row indices."""

    def __init__(self, partition_ids: "list[int]"):
        self.ids = sorted(partition_ids)
        self._sorted = np.array(self.ids, dtype=np.int64)
        #: Fixed at construction: dense ids make remapping a no-op.
        self.is_dense = self.ids == list(range(len(self.ids)))

    def __len__(self) -> int:
        return len(self.ids)

    def to_dense(self, partition: np.ndarray) -> np.ndarray:
        return np.searchsorted(self._sorted, partition).astype(np.int32)

    def remap_batch(self, batch: RecordBatch) -> RecordBatch:
        if self.is_dense:
            return batch
        batch.partition = self.to_dense(batch.partition)
        return batch


@dataclasses.dataclass
class ScanResult:
    metrics: TopicMetrics
    duration_secs: int
    profile: ScanProfile
    start_offsets: "dict[int, int]"
    end_offsets: "dict[int, int]"
    #: partition -> reason, for partitions the source dropped after
    #: exhausting their retry budget (graceful degradation).  Non-empty
    #: means the metrics UNDERCOUNT those partitions' tails: the report
    #: flags them and the CLI exits non-zero (cli.EXIT_DEGRADED).
    degraded_partitions: "dict[int, str]" = dataclasses.field(
        default_factory=dict
    )
    #: partition -> {"frames", "records", "bytes", "quarantined", "kinds",
    #: "spans"} for poisoned frames the source skipped or quarantined under
    #: --on-corruption (cumulative across a --resume chain: the snapshot
    #: carries the spans and the source is re-seeded with them).  Non-empty
    #: means the metrics exclude exactly those frames' records: the report
    #: renders a CORRUPT block and the CLI exits EXIT_CORRUPT.
    corrupt_partitions: "dict[int, dict]" = dataclasses.field(
        default_factory=dict
    )
    #: partition -> {"records", "ranges", "reasons", "authoritative",
    #: "spans"} for offset ranges the log mutated out from under the scan
    #: (retention races, truncation after unclean election,
    #: resume-below-log-start; KafkaWireSource.loss_stats format,
    #: cumulative across a --resume chain like corrupt_partitions).
    #: Non-empty means the metrics describe the SURVIVING records only:
    #: the report renders a DATA-LOSS block, and ``authoritative: False``
    #: (truncation) additionally means already-folded records were
    #: replaced under the scan.
    lost_partitions: "dict[int, dict]" = dataclasses.field(
        default_factory=dict
    )
    #: Registry snapshot taken at scan end (obs.registry format) — under
    #: multi-controller, the cluster-wide merge of every process's
    #: registry, so the report process can render fleet totals
    #: (``--stats``) and ``--json`` can embed them (``telemetry`` block).
    telemetry: "Optional[dict]" = None
    #: Parallel-ingest worker threads THIS process's scan actually ran
    #: (after clamping to the partition count); 1 = the sequential path.
    #: The ``--stats`` digest and ``--json`` report surface it so a
    #: recorded throughput number always carries its parallelism.
    ingest_workers: int = 1
    #: Resolved worker counts per controller, index = process id — under
    #: multi-controller each process resolves ``--ingest-workers`` against
    #: ITS shard's partition count, so a single scalar cannot describe the
    #: fleet.  Collected over the same gather_telemetry collective as the
    #: registry merge (per-process snapshots carry the
    #: kta_ingest_resolved_workers gauge).  Single-controller scans hold
    #: one entry equal to ``ingest_workers``.
    ingest_workers_per_controller: "list[int]" = dataclasses.field(
        default_factory=list
    )
    #: Superbatch size the device backend actually ran (resolved
    #: ``--superbatch``): packed batches folded per jitted dispatch.
    #: 1 = the classic one-dispatch-per-batch path.  Reported alongside
    #: ingest_workers for the same reason — dispatch amortization is part
    #: of any recorded throughput number's configuration.
    superbatch_k: int = 1
    #: Bound on in-flight superbatch dispatches (``--dispatch-depth``).
    dispatch_depth: int = 1
    #: Packed wire-format accounting (results.WireStats): format (v4/v5),
    #: per-record vs fold-table byte split, and the scan's actual wire
    #: bytes — None for backends without a packed transfer (cpu oracle).
    wire: "Optional[object]" = None
    #: Per-partition next-unread offsets at scan end (the progress
    #: tracker's final positions).  For a clean batch scan these equal
    #: ``end_offsets``; degraded partitions stop early.  Follow mode
    #: (serve/follow.py) chains passes on this cursor: pass N+1 starts
    #: exactly where pass N's fold committed.
    next_offsets: "dict[int, int]" = dataclasses.field(default_factory=dict)


class _ProgressTracker:
    """Per-partition next-offset tracking for snapshot resume.

    Gapless sources are tracked by counting records; sources that attach
    per-record offsets (compacted Kafka topics) are tracked exactly.
    """

    def __init__(self, start_offsets: "dict[int, int]"):
        self.next_offsets = dict(start_offsets)

    def observe(self, batch: RecordBatch, true_partition: np.ndarray) -> None:
        valid = batch.valid
        if batch.offsets is not None:
            parts = true_partition[valid]
            offs = batch.offsets[valid]
            for p in np.unique(parts):
                self.next_offsets[int(p)] = max(
                    self.next_offsets.get(int(p), 0),
                    int(offs[parts == p].max()) + 1,
                )
        else:
            parts, counts = np.unique(true_partition[valid], return_counts=True)
            for p, c in zip(parts.tolist(), counts.tolist()):
                self.next_offsets[p] = self.next_offsets.get(p, 0) + int(c)

    def observe_packed(self, row) -> None:
        """Fused-path twin of ``observe``: a packing.PackedRow carries the
        per-partition bookkeeping pre-aggregated (offset-exact partitions
        in ``next_offsets``, offset-less ones as ``counts``) instead of
        per-record columns."""
        for p, o in row.next_offsets.items():
            self.next_offsets[p] = max(self.next_offsets.get(p, 0), o)
        for p, c in row.counts.items():
            self.next_offsets[p] = self.next_offsets.get(p, 0) + c


def _note_fetch_streams(source, workers: int) -> None:
    """Tell the process-wide fetch scheduler how many ingest streams this
    scan just resolved: under ``--fetch-concurrency auto`` the shared
    pool grows so every stream can keep a demand fetch plus some
    speculation in flight (an explicit size is never overridden).  Only
    remote segment sources feed the scheduler — everything else is a
    no-op."""
    if bool(getattr(getattr(source, "store", None), "is_remote", False)):
        from kafka_topic_analyzer_tpu.io import fetchsched

        fetchsched.note_streams(workers)


def run_scan(
    topic: str,
    source: RecordSource,
    backend: MetricBackend,
    batch_size: int,
    spinner: Optional[Spinner] = None,
    snapshot_dir: Optional[str] = None,
    snapshot_every_s: float = 60.0,
    resume: bool = False,
    prefetch_depth: int = 2,
    start_at: "Optional[dict[int, int]]" = None,
    tracer=None,
    heartbeat_every_s: float = 10.0,
    ingest_workers: "int | str | IngestConfig" = 1,
    initial_seq: int = 0,
    heartbeat: "Optional[obs_events.Heartbeat]" = None,
    emit_lifecycle: bool = True,
    book_once: bool = True,
    final_snapshot: bool = False,
    lease_epoch: "Optional[int]" = None,
) -> ScanResult:
    """Full earliest→latest scan of the topic through the backend.

    With ``snapshot_dir`` set, the analyzer state + per-partition progress
    are saved atomically every ``snapshot_every_s`` seconds; with ``resume``
    a compatible snapshot restarts the scan where it left off
    (checkpoint.py; requires a backend with get_state/set_state, i.e. the
    TPU backends).

    ``tracer`` (obs.trace.SpanTracer) mirrors every profile stage into a
    Chrome trace; scan metrics/events flow to the default obs registry and
    event bus unconditionally (both are no-ops until a sink/exporter
    attaches), with per-partition lag/ETA gauges refreshed at the
    ``heartbeat_every_s`` cadence.

    ``ingest_workers`` (an int, ``"auto"``, or a config.IngestConfig)
    shards the partition set over that many private fetch→decode→pack
    worker streams feeding the backend through deterministic round-robin
    fan-ins (parallel/ingest.py) — results stay byte-identical to the
    sequential scan (DESIGN.md §11).  On sharded backends the count
    resolves PER CONTROLLER against this process's shard partition count
    and splits across its data rows, composing host-parallel ingest with
    the device-parallel collective scan (DESIGN.md §14); single-device
    backends clamp to the topic's partition count as before.

    Follow-mode pass hooks (serve/follow.py — the follow service reruns
    this function per poll on the SAME backend, so state accumulates):
    ``initial_seq`` seeds the record sequence so pass N+1's spinner/
    heartbeat/snapshot counts continue pass N's; ``heartbeat`` shares one
    rate limiter across passes (a fresh limiter per pass would fire on
    every poll at the head — event flood); ``emit_lifecycle=False``
    suppresses the per-pass scan_start/scan_end events and the per-pass
    spinner finish line — the service emits ONE lifecycle pair for its
    whole run; ``book_once=False`` suppresses the once-per-scan fallback
    bookings (wire-v4 / fused reasons) on every follow pass after the
    first, so the counters record one scan, not one per poll;
    ``final_snapshot`` forces
    a snapshot after the stream drains (at a superbatch boundary, by
    construction) — the follow service's checkpoint-interval and
    clean-shutdown commits.

    ``lease_epoch`` (fleet/lease.py, DESIGN §23): the caller's topic-
    ownership epoch, stamped on every snapshot this pass saves and
    checked against every snapshot it loads — a pass running under a
    lost lease is fenced with `checkpoint.StaleLeaseEpochError` instead
    of clobbering (or resuming over) its successor's checkpoint."""
    ingest_cfg = (
        ingest_workers
        if isinstance(ingest_workers, IngestConfig)
        else IngestConfig(workers=ingest_workers)
    )
    pindex = PartitionIndex(source.partitions())
    start_offsets, end_offsets = source.watermarks()
    if tracer is None:
        # CLI wiring: telemetry_session installs the --trace-json tracer
        # as the process-wide active one instead of threading it here.
        tracer = obs_trace.active()
    profile = ScanProfile(tracer=tracer)
    spinner = spinner or Spinner(enabled=False)
    t0 = time.monotonic()
    seq = initial_seq
    if emit_lifecycle:
        obs_events.emit(
            "scan_start",
            topic=topic,
            partitions=len(pindex),
            batch_size=batch_size,
        )
    if heartbeat is None:
        heartbeat = obs_events.Heartbeat(heartbeat_every_s)
    # Partitions THIS process feeds — the sharded branch narrows this to
    # its local rows' partitions, so that under multi-controller each
    # process's lag/ETA gauges carry a disjoint label set (the merge
    # algebra's gauge-union assumption; a process must not report full
    # lag for partitions it never observes).
    fed_partitions = list(end_offsets)

    def maybe_heartbeat() -> None:
        """Rate-limited telemetry refresh: per-partition lag/ETA gauges
        from the tracker + one heartbeat event.  O(P) work at most once
        per interval — never per batch."""
        if not heartbeat.ready():
            return
        elapsed = time.monotonic() - t0
        # Rate over THIS run only: a --resume restores seq to the
        # snapshot's cumulative count, which elapsed knows nothing about.
        rate = (seq - seq_base) / elapsed if elapsed > 0 else 0.0
        lag_total = 0
        for p in fed_partitions:
            end = end_offsets[p]
            lag = max(0, end - tracker.next_offsets.get(p, start_offsets[p]))
            lag_total += lag
            obs_metrics.PARTITION_LAG.labels(partition=p).set(lag)
            obs_metrics.PARTITION_ETA_SECONDS.labels(partition=p).set(
                lag / rate if rate > 0 else -1.0
            )
        obs_events.emit(
            "heartbeat",
            seq=seq,
            records_per_sec=round(rate, 1),
            lag_total=lag_total,
        )
        # Health evaluation rides the heartbeat boundary so a plain
        # batch scan gets a live /healthz too; the engine rate-limits
        # itself (HealthConfig.eval_interval_s) and only READS registry
        # snapshots — the scan stays byte-identical with it on or off
        # (tests/test_health.py).  Follow/fleet services additionally
        # evaluate at every poll boundary.
        health = obs_health.active()
        if health is not None:
            health.maybe_evaluate()

    # Caller-provided start offsets (e.g. --from-timestamp lookup); a
    # resumed snapshot's offsets take precedence below.
    tracker = _ProgressTracker(start_offsets)
    if start_at:
        tracker.next_offsets.update(start_at)
    can_snapshot = (
        snapshot_dir is not None
        and hasattr(backend, "get_state")
        and getattr(backend, "snapshot_capable", True)
    )
    if (
        snapshot_dir is not None
        and hasattr(backend, "get_state")
        and not getattr(backend, "snapshot_capable", True)
    ):
        import logging

        logging.getLogger(__name__).warning(
            "this backend/mesh cannot snapshot (non-contiguous per-process "
            "data rows); continuing without snapshots"
        )
    # Multi-controller runs snapshot per process (checkpoint._snapshot_path):
    # the backend exposes its scope and process-local state accessors.
    snap_scope = getattr(backend, "snapshot_scope", None)
    snap_get = (
        backend.get_state_local if snap_scope is not None else
        (backend.get_state if hasattr(backend, "get_state") else None)
    )
    snap_set = (
        backend.set_state_local if snap_scope is not None else
        (backend.set_state if hasattr(backend, "set_state") else None)
    )
    if snapshot_dir is not None and not hasattr(backend, "get_state"):
        import logging

        logging.getLogger(__name__).warning(
            "backend %s does not support snapshots; continuing without",
            type(backend).__name__,
        )
    if resume and can_snapshot:
        from kafka_topic_analyzer_tpu.checkpoint import load_snapshot

        snap = load_snapshot(
            snapshot_dir,
            topic,
            backend.config,
            template=snap_get(),
            scope=snap_scope,
            lease_epoch=lease_epoch,
        )
        if snap is not None:
            state, offsets, records_seen, init_now_s = snap
            snap_set(state)
            backend.init_now_s = init_now_s
            tracker.next_offsets.update(offsets)
            start_at = offsets
            seq = records_seen
            if hasattr(source, "seed_corrupt_spans"):
                from kafka_topic_analyzer_tpu.checkpoint import (
                    load_corrupt_spans,
                )

                # Spans a previous run already skipped/quarantined: seed
                # the source so re-walking one (corruption skips leave no
                # records for the offset tracker to advance past) neither
                # re-counts nor double-quarantines it.
                spans = load_corrupt_spans(snapshot_dir, scope=snap_scope)
                if spans:
                    source.seed_corrupt_spans(spans)
            if hasattr(source, "seed_lost_spans"):
                from kafka_topic_analyzer_tpu.checkpoint import (
                    load_lost_spans,
                    load_partition_meta,
                )

                # Loss a previous run already booked: seed the source so
                # the logical scan's final report names it without
                # re-booking (metrics counted it when it happened).
                lspans = load_lost_spans(snapshot_dir, scope=snap_scope)
                if lspans:
                    source.seed_lost_spans(lspans)
                if hasattr(source, "validate_resume"):
                    # Durable fencing: check each saved cursor against
                    # the LIVE log before fetch #1 — a cursor below the
                    # log start is a named retention loss (offsets
                    # re-anchor in place), and an epoch that moved since
                    # the save runs the divergence check.
                    source.validate_resume(
                        offsets,
                        load_partition_meta(snapshot_dir, scope=snap_scope),
                    )
                    tracker.next_offsets.update(offsets)
    seq_base = seq  # resumed records predate t0; rate math excludes them
    last_snap = time.monotonic()

    # Offsets/seq as of the last COMPLETED fold.  The tracker observes a
    # batch during ingest, before backend.update folds it, so on a mid-round
    # failure `tracker.next_offsets` can be ahead of the backend state; the
    # failure-path snapshot must use these instead or a resume would skip
    # the observed-but-never-folded records.
    committed_offsets = dict(tracker.next_offsets)
    committed_seq = seq

    def maybe_snapshot(
        force: bool = False,
        offsets: "Optional[dict[int, int]]" = None,
        records_seen: Optional[int] = None,
    ) -> None:
        nonlocal last_snap
        if not can_snapshot:
            return
        now = time.monotonic()
        if not force and now - last_snap < snapshot_every_s:
            return
        from kafka_topic_analyzer_tpu.checkpoint import save_snapshot

        with profile.stage("snapshot"):
            save_snapshot(
                snapshot_dir,
                topic,
                backend.config,
                snap_get(),
                tracker.next_offsets if offsets is None else offsets,
                seq if records_seen is None else records_seen,
                backend.init_now_s,
                scope=snap_scope,
                degraded=(
                    source.degraded_partitions()
                    if hasattr(source, "degraded_partitions")
                    else None
                ),
                corrupt=(
                    source.corruption_spans()
                    if hasattr(source, "corruption_spans")
                    else None
                ),
                lease_epoch=lease_epoch,
                lost=(
                    source.lost_spans()
                    if hasattr(source, "lost_spans")
                    else None
                ),
                partition_meta=(
                    source.partition_meta()
                    if hasattr(source, "partition_meta")
                    else None
                ),
            )
        obs_metrics.SNAPSHOTS_SAVED.inc()
        obs_events.emit(
            "snapshot_saved",
            records_seen=seq if records_seen is None else records_seen,
        )
        last_snap = time.monotonic()

    # Prefetch iterators run worker threads; close them on ANY exit so an
    # error mid-scan doesn't leak threads or the source's connections.
    open_iters: "list" = []

    def _closing(it):
        open_iters.append(it)
        return it

    from kafka_topic_analyzer_tpu.utils.prefetch import prefetch

    def _dense_copy(b: RecordBatch) -> RecordBatch:
        """Dense-partition view for packing on a prefetch worker.  A COPY
        when ids are non-dense: remap_batch mutates in place, and the main
        loop must keep true partition ids for progress/snapshot keys."""
        if pindex.is_dense:
            return b  # nothing to rewrite; safe to alias
        return dataclasses.replace(b, partition=pindex.to_dense(b.partition))

    # Fused ingest (DESIGN.md §15): when the backend can stage packed rows
    # (make_fused_sink), the source can feed a FusedPackSink
    # (supports_fused_sink), and the native shim is up, each ingest stream
    # gets a PRIVATE sink and yields packing.PackedRow items — wire bytes
    # decoded→packed (and backend-staged) in one GIL-released native pass
    # on the producing thread, no decoded-column intermediate.  Any closed
    # gate falls back to the decoded-batch chain and is booked on
    # kta_fused_fallback_total — a bypass is never silent.
    from kafka_topic_analyzer_tpu.packing import (
        PackedRow,
        fused_ingest_enabled,
    )

    _make_sink = getattr(backend, "make_fused_sink", None)
    # The attribute declares intent; the signature check confirms no
    # wrapper in between (TeeSource, test shims that __getattr__-forward
    # to a fused-capable inner source) dropped the ``sink=`` parameter
    # from its own batches() override.
    import inspect

    try:
        _accepts_sink = "sink" in inspect.signature(source.batches).parameters
    except (TypeError, ValueError):
        _accepts_sink = False
    _declares_fused = getattr(source, "supports_fused_sink", False)
    _fusable_source = _declares_fused and _accepts_sink
    fused = (
        _make_sink is not None
        and _fusable_source
        and getattr(backend, "use_native", True)
        and fused_ingest_enabled()
    )
    if _make_sink is not None and _declares_fused and not fused and book_once:
        # Book every closed gate — a bypass is never silent, including a
        # wrapper that forwards the capability flag but dropped sink=.
        # (book_once: follow runs book on their FIRST pass only.)
        if not _accepts_sink:
            reason = "source-unfusable"
        elif not getattr(backend, "use_native", True):
            reason = "native-off"
        else:
            from kafka_topic_analyzer_tpu.io.native import native_status

            ok, why = native_status()
            reason = "fused-disabled" if ok else f"native-{why}"
        obs_metrics.FUSED_FALLBACK.labels(reason=reason).inc()
    _dense_map = {p: i for i, p in enumerate(pindex.ids)}

    def make_sink():
        """A fresh per-stream sink (sinks are single-threaded state)."""
        return _make_sink(_dense_map.__getitem__)

    # Wire-format accounting + the v4 fallback booking (a bypassed v5
    # combiner is never silent — same discipline as the fused gate above).
    # Only packed backends have a wire; the cpu oracle folds decoded
    # batches directly.
    wire_stats = None
    wire_bytes0 = 0.0
    if _make_sink is not None or hasattr(backend, "update_shards"):
        from kafka_topic_analyzer_tpu.packing import section_byte_split
        from kafka_topic_analyzer_tpu.results import WireStats

        wire_cfg = backend.config
        # Sharded backends pack per-chunk buffers; the split is the same
        # layout rule at that granularity (packing._sections).
        wire_b = (
            wire_cfg.chunk_size
            if hasattr(backend, "update_shards")
            else wire_cfg.batch_size
        )
        per_rec, table = section_byte_split(wire_cfg, wire_b)
        wire_stats = WireStats(
            format=wire_cfg.wire_format,
            batch_size=wire_b,
            per_record_bytes=per_rec,
            table_bytes=table,
            alive_compaction=(
                "on"
                if wire_cfg.compact_alive
                else (
                    f"off ({wire_cfg.alive_compaction_off_reason})"
                    if wire_cfg.count_alive_keys
                    else "n/a"
                )
            ),
        )
        v4_reason = wire_cfg.wire_v4_reason
        if v4_reason is not None and book_once:
            # Once per scan — and once per follow SERVICE run, not per
            # poll pass (book_once is False on passes after the first).
            obs_metrics.WIRE_V4_FALLBACK.labels(reason=v4_reason).inc()
        compaction_off = wire_cfg.alive_compaction_off_reason
        if compaction_off is not None and book_once:
            # An alive-key scan running WITHOUT pair compaction is booked
            # with its resolved reason — the bypass is never silent (same
            # discipline as the wire-v4 fallback above).
            obs_metrics.ALIVE_COMPACTION_OFF.labels(
                reason=compaction_off
            ).inc()
        wire_bytes0 = obs_metrics.WIRE_BYTES.value
        pairs_raw0 = obs_metrics.ALIVE_PAIRS_RAW.value
        pairs_emitted0 = obs_metrics.ALIVE_PAIRS_EMITTED.value

    used_workers = 1
    # Superbatch dispatch (config.DispatchConfig, resolved by the backend):
    # accumulate K staged batches and fold them in ONE scanned device
    # dispatch.  Fold-consistency rule: progress commits (and therefore
    # snapshots) happen ONLY at superbatch boundaries — between them the
    # tracker runs ahead of the device state by the pending tail, and a
    # snapshot there would skip those records on resume.  On stop/fault/
    # corruption the pending tail is flushed as a partial superbatch
    # (identity-padded to K by the backend) so PRs 1-3 semantics — every
    # observed batch folded and committed before the failure snapshot —
    # are unchanged.  `fault_flush` is that best-effort hook; it stays
    # None when flushing from a failure path would itself be a collective
    # (multi-controller sharded runs: peers may not reach the flush, and
    # a one-sided collective deadlocks — resume simply re-scans the tail).
    super_k = int(getattr(backend, "superbatch_k", 1) or 1)
    fault_flush = None

    def make_superbatch(dispatch_fn):
        """(add, flush) pair for one drive loop's superbatch accumulation.

        ONE implementation for both the sharded and single-device branches
        so the commit/snapshot semantics can never diverge between them.
        ``add`` records the tracker offsets AT APPEND TIME: the tracker
        observes a batch slightly before it is staged into the pending
        tail, so a fault landing in that window must not let ``flush``
        commit offsets for a batch it never folded — the flush commits the
        last appended batch's snapshot, not the live tracker.
        """
        pend = {"items": [], "valid": 0, "nbytes": 0,
                "offsets": None, "seq": 0}

        def add(item, nvalid: int, nbytes: int) -> None:
            nonlocal seq
            pend["items"].append(item)
            pend["valid"] += nvalid
            pend["nbytes"] += nbytes
            seq += nvalid
            pend["offsets"] = dict(tracker.next_offsets)
            pend["seq"] = seq
            # Staging fill level (0..K) — the flight recorder's stager
            # track: how far the next superbatch has accumulated.
            obs_metrics.SUPERBATCH_FILL.set(len(pend["items"]))
            if len(pend["items"]) == super_k:
                flush()

        def flush() -> None:
            """Dispatch the accumulated (possibly partial) superbatch and
            commit fold progress — the only point the superbatch path
            snapshots.  Under multi-controller the dispatch is collective:
            every process reaches each flush at the same round count (the
            accumulation length is driven by the per-round lockstep
            agreement), and the fault path never calls this there."""
            nonlocal committed_offsets, committed_seq
            if not pend["items"]:
                return
            with profile.stage(
                "dispatch", items=pend["valid"], nbytes=pend["nbytes"],
            ):
                dispatch_fn(pend["items"])
            pend["items"] = []
            pend["valid"] = 0
            pend["nbytes"] = 0
            obs_metrics.SUPERBATCH_FILL.set(0)
            committed_offsets = pend["offsets"]
            committed_seq = pend["seq"]
            maybe_snapshot(
                offsets=committed_offsets, records_seen=committed_seq
            )

        return add, flush

    try:
        if hasattr(backend, "update_shards"):
            # Sharded scan: one batch stream PIPELINE per data shard, each
            # restricted to its own partitions (records.py ordering
            # contract), zipped so every device step carries one full batch
            # per shard.  Under multi-controller (jax.distributed), this
            # process feeds only the data rows it hosts
            # (backend.local_rows) — the turnkey multi-host contract: run
            # the same CLI on every host.
            #
            # Composed parallelism (DESIGN.md §14): each fed row's pipeline
            # is either the classic single staged prefetch stream (1
            # worker — byte-for-byte the pre-composition path) or an
            # N-worker ParallelIngest fan-in over that row's partitions,
            # so host-parallel fetch→decode→pack multiplies with the
            # device-parallel collective fold and the superbatch dispatch
            # layer below.  The round structure — and with it every
            # lockstep collective — is untouched: fan-ins only change
            # where a row's next batch comes from, never when the row
            # participates in a round.
            from kafka_topic_analyzer_tpu.parallel.ingest import (
                ParallelIngest,
                allocate_row_workers,
                shard_partitions,
            )
            from kafka_topic_analyzer_tpu.parallel.mesh import assign_partitions

            d = backend.config.data_shards
            shard_parts = assign_partitions(pindex.ids, d)
            feed_rows = list(getattr(backend, "local_rows", range(d)))
            fed_partitions = [p for r in feed_rows for p in shard_parts[r]]
            # Collective steps must stay in lockstep across processes, so
            # per-round continuation is a global agreement, not a local one.
            lockstep = getattr(backend, "global_any", None)
            multiproc = lockstep is not None and len(feed_rows) < d
            # Stage the S-way chunk packing on each row's ingest worker
            # (same contract as the single-device path below: pack a dense
            # COPY, keep the decoded batch for true-id bookkeeping).
            prepare_shard = getattr(backend, "prepare_shard", None)

            def _stage_row(it):
                for b in it:
                    if isinstance(b, PackedRow):
                        yield b, b.staged  # fused: packed AND staged already
                    elif prepare_shard is None:
                        yield b, None
                    else:
                        yield b, prepare_shard(_dense_copy(b))

            stage_shard = (
                (lambda b: prepare_shard(_dense_copy(b)))
                if prepare_shard is not None
                else None
            )
            # Per-controller resolution: the worker budget comes from THIS
            # process's shard partition count (auto = min(cores-1, local
            # partitions)) and splits deterministically across its rows.
            row_workers = allocate_row_workers(
                ingest_cfg.resolve(max(1, len(fed_partitions))),
                {r: len(shard_parts[r]) for r in feed_rows},
            )
            used_workers = max(1, sum(row_workers.values()))
            # Recorded per process so the gather below can report the
            # RESOLVED per-controller counts, not just a global scalar.
            obs_metrics.INGEST_RESOLVED_WORKERS.set(used_workers)
            _note_fetch_streams(source, used_workers)
            # Cold sources (segment catalogs) know per-partition record
            # counts: balance each row's worker groups by records
            # (greedy-LPT), exactly like the single-device path below.
            # Only consulted when some row actually runs a fan-in.
            weights = None
            if any(nw > 1 for nw in row_workers.values()):
                weigher = getattr(source, "partition_record_counts", None)
                weights = weigher() if weigher is not None else None
            # Worker telemetry labels must be disjoint across this
            # controller's per-row pools AND across controllers (the
            # gather_telemetry merge unions label sets).
            label_prefix = (
                f"c{backend.controller_index}."
                if multiproc and hasattr(backend, "controller_index")
                else ""
            )
            iters = {}
            wid_base = 0
            for r in feed_rows:
                nw = row_workers.get(r, 0)
                if not shard_parts[r]:
                    iters[r] = iter(())
                elif nw > 1:
                    iters[r] = _closing(
                        ParallelIngest(
                            source,
                            batch_size,
                            shard_partitions(
                                shard_parts[r], nw, weights=weights
                            ),
                            start_at=start_at,
                            stage=stage_shard,
                            depth=max(prefetch_depth, 1),
                            wid_base=wid_base,
                            label_prefix=label_prefix,
                            sink_factory=make_sink if fused else None,
                        )
                    )
                else:
                    iters[r] = _closing(
                        prefetch(
                            _stage_row(
                                source.batches(
                                    batch_size,
                                    partitions=shard_parts[r],
                                    start_at=start_at,
                                    **({"sink": make_sink()} if fused else {}),
                                )
                            ),
                            prefetch_depth,
                        )
                    )
                wid_base += nw
            dispatch_rounds = (
                backend.update_shards_superbatch
                if super_k > 1 and hasattr(backend, "update_shards_superbatch")
                else None
            )
            if dispatch_rounds is None:
                super_k = 1  # report the EFFECTIVE superbatch size
                add_round = flush_rounds = None
            else:
                add_round, flush_rounds = make_superbatch(dispatch_rounds)
                if not multiproc:
                    fault_flush = flush_rounds
            alive = {r: True for r in feed_rows}
            while True:
                shard_batches: "list" = [None] * d
                step_valid = 0
                step_bytes = 0
                with profile.stage("ingest"):
                    for r in feed_rows:
                        item = next(iters[r], None) if alive[r] else None
                        if item is None:
                            alive[r] = False
                            continue
                        b, staged = item
                        step_valid += b.num_valid
                        step_bytes += b.nbytes
                        if isinstance(b, PackedRow):
                            tracker.observe_packed(b)
                            shard_batches[r] = staged
                        else:
                            tracker.observe(b, b.partition)
                            shard_batches[r] = (
                                staged if staged is not None
                                else pindex.remap_batch(b)
                            )
                have_data = step_valid > 0
                if multiproc:
                    have_data = lockstep(have_data)
                if not have_data:
                    break
                if add_round is not None:
                    add_round(shard_batches, step_valid, step_bytes)
                else:
                    with profile.stage(
                        "dispatch", items=step_valid, nbytes=step_bytes,
                    ):
                        backend.update_shards(shard_batches)
                    seq += step_valid
                    committed_offsets = dict(tracker.next_offsets)
                    committed_seq = seq
                    maybe_snapshot()
                obs_metrics.SCAN_RECORDS.inc(step_valid)
                obs_metrics.SCAN_BATCHES.inc()
                obs_metrics.SCAN_BYTES.inc(step_bytes)
                obs_metrics.BATCH_RECORDS.observe(step_valid)
                maybe_heartbeat()
                spinner.set_message(f"[Sq: {seq} | T: {topic} | shards: {d}]")
            if flush_rounds is not None:
                # Stream drained on every process (lockstep agreement):
                # flush the partial superbatch tail collectively.
                flush_rounds()
        else:
            # Backends with a `prepare` method (the packed single-device
            # path) stage INSIDE the prefetch worker: remap + pack (native,
            # GIL-released) + the async host→device transfer all overlap
            # the device's current step, so the main thread only does
            # bookkeeping and step dispatch.  The decoded batch travels
            # alongside for progress/snapshot bookkeeping and MUST keep its
            # true partition ids (remap_batch mutates in place; the tracker
            # keys snapshots by true id), so the worker packs a shallow
            # copy carrying the dense ids instead.  Prefetch depth bounds
            # the in-flight device buffers.
            prepare = getattr(backend, "prepare", None)
            stage = (
                (lambda b: prepare(_dense_copy(b)))
                if prepare is not None
                else None
            )
            used_workers = ingest_cfg.resolve(len(pindex))
            obs_metrics.INGEST_RESOLVED_WORKERS.set(used_workers)
            _note_fetch_streams(source, used_workers)
            if used_workers > 1:
                # Partition-sharded parallel ingest (--ingest-workers): N
                # private fetch→decode→pack streams, merged through a
                # deterministic round-robin fan-in.  Yields the same
                # (batch, staged) items as the prefetch path below, so the
                # bookkeeping loop is shared — and the fold order is a pure
                # function of the inputs, keeping results byte-identical to
                # the sequential scan (DESIGN.md §11).
                from kafka_topic_analyzer_tpu.parallel.ingest import (
                    ParallelIngest,
                    shard_partitions,
                )

                # Cold sources (segment catalogs) know per-partition record
                # counts up front: balance workers by records, not partition
                # count.  Byte-identity is grouping-independent (DESIGN §11),
                # so the wire scan's round-robin rule and this weighted rule
                # fold to the same result.
                weigher = getattr(source, "partition_record_counts", None)
                batches = _closing(
                    ParallelIngest(
                        source,
                        batch_size,
                        shard_partitions(
                            pindex.ids,
                            used_workers,
                            weights=weigher() if weigher is not None else None,
                        ),
                        start_at=start_at,
                        stage=stage,
                        depth=max(prefetch_depth, 1),
                        sink_factory=make_sink if fused else None,
                    )
                )
            else:
                from kafka_topic_analyzer_tpu.parallel.ingest import (
                    iter_staged,
                )

                batches = _closing(
                    prefetch(
                        iter_staged(
                            source.batches(
                                batch_size,
                                start_at=start_at,
                                **({"sink": make_sink()} if fused else {}),
                            ),
                            stage,
                        ),
                        prefetch_depth,
                    )
                )
            dispatch_super = (
                backend.update_superbatch
                if super_k > 1 and hasattr(backend, "update_superbatch")
                else None
            )
            if dispatch_super is None:
                super_k = 1  # report the EFFECTIVE superbatch size
                add_batch = flush_pending = None
            else:
                add_batch, flush_pending = make_superbatch(dispatch_super)
                fault_flush = flush_pending
            while True:
                with profile.stage("ingest"):
                    item = next(batches, None)
                if item is None:
                    break
                batch, staged = item
                nvalid = batch.num_valid
                if isinstance(batch, PackedRow):
                    last_partition = batch.last_partition
                    last_offset = (
                        str(batch.last_offset)
                        if batch.last_offset >= 0 else "~"
                    )
                    last_ts = batch.last_ts_s
                    tracker.observe_packed(batch)
                    if staged is None:
                        staged = batch.staged
                else:
                    last = len(batch) - 1
                    last_partition = int(batch.partition[last])  # true id, pre-remap
                    last_offset = (
                        str(int(batch.offsets[last]))
                        if batch.offsets is not None
                        else "~"  # gapless sources don't carry offsets
                    )
                    last_ts = int(batch.ts_s[last])
                    tracker.observe(batch, batch.partition)
                    if staged is None:
                        staged = pindex.remap_batch(batch)
                if add_batch is not None:
                    add_batch(staged, nvalid, batch.nbytes)
                else:
                    # nbytes is always the DECODED batch size (remap doesn't
                    # change it) so the stat stays comparable across backends.
                    with profile.stage(
                        "dispatch", items=nvalid, nbytes=batch.nbytes,
                    ):
                        backend.update(staged)
                    seq += nvalid
                    committed_offsets = dict(tracker.next_offsets)
                    committed_seq = seq
                    maybe_snapshot()
                obs_metrics.SCAN_RECORDS.inc(nvalid)
                obs_metrics.SCAN_BATCHES.inc()
                obs_metrics.SCAN_BYTES.inc(batch.nbytes)
                obs_metrics.BATCH_RECORDS.observe(nvalid)
                maybe_heartbeat()
                # indicatif-template message like src/kafka.rs:111-113.
                spinner.set_message(
                    f"[Sq: {seq} | T: {topic} | P: {last_partition} | "
                    f"O: {last_offset} | Ts: {format_utc_seconds(last_ts)}]"
                )
            if flush_pending is not None:
                flush_pending()  # partial superbatch tail at stream end
    except BaseException:
        # Irrecoverable mid-scan failure (or interrupt): flush the pending
        # superbatch tail (so every observed batch is folded — the same
        # invariant the per-batch path holds at failure time), then persist
        # the progress as a final snapshot so a rerun with --resume
        # continues where this one died instead of rescanning from
        # earliest.  Best effort — the original failure is what must
        # surface; an unflushable tail just means resume re-scans it.
        if fault_flush is not None:
            try:
                fault_flush()
            except Exception:
                import logging

                logging.getLogger(__name__).exception(
                    "pending superbatch tail could not be flushed; the "
                    "failure snapshot falls back to the last committed "
                    "superbatch boundary"
                )
        # Retire in-flight superbatch dispatches before snapshotting.
        # Lockstep-safe even on a one-sided stop: drain_dispatch blocks
        # only on collectives every controller already launched at a
        # lockstep-agreed round — it never initiates one (unlike the tail
        # flush above, which is why THAT stays None under multiproc).
        drain = getattr(backend, "drain_dispatch", None)
        if drain is not None:
            try:
                drain()
            except Exception:
                import logging

                logging.getLogger(__name__).exception(
                    "in-flight dispatches could not be drained before the "
                    "failure snapshot"
                )
        try:
            maybe_snapshot(
                force=True,
                offsets=committed_offsets,
                records_seen=committed_seq,
            )
        except Exception:
            import logging

            logging.getLogger(__name__).exception(
                "final failure snapshot could not be written"
            )
        raise
    finally:
        for it in open_iters:
            if hasattr(it, "close"):
                it.close()

    degraded = (
        dict(source.degraded_partitions())
        if hasattr(source, "degraded_partitions")
        else {}
    )
    corrupt = (
        source.corruption_stats()
        if hasattr(source, "corruption_stats")
        else {}
    )
    lost = (
        source.loss_stats()
        if hasattr(source, "loss_stats")
        else {}
    )
    # Multi-controller: each process feeds (and can only degrade or observe
    # corruption on) its own rows, but process 0 renders the report and
    # orchestrators read every process's exit code — so "did the scan hit
    # this issue" must be a global agreement, like the per-round
    # continuation above.  One lockstep call per issue, same order on every
    # process.
    lockstep = getattr(backend, "global_any", None)
    multiproc = lockstep is not None and len(
        list(getattr(backend, "local_rows", range(backend.config.data_shards)))
    ) < backend.config.data_shards

    def issue_elsewhere(local_flag: bool) -> bool:
        """True when another process saw the issue and this one did not
        (the collective still runs when local_flag is True — every process
        must participate in every lockstep call)."""
        return multiproc and lockstep(local_flag) and not local_flag

    if issue_elsewhere(bool(degraded)):
        degraded = {
            -1: "partition(s) degraded on another process (see its log)"
        }
    if issue_elsewhere(bool(corrupt)):
        corrupt = {
            -1: {
                "frames": 0, "records": 0, "bytes": 0, "quarantined": 0,
                "kinds": {}, "spans": [],
                "note": "corrupt frame(s) on another process (see its log)",
            }
        }
    if issue_elsewhere(bool(lost)):
        lost = {
            -1: {
                "records": 0, "ranges": 0, "reasons": {},
                "authoritative": True, "spans": [],
                "note": "data loss on another process (see its log)",
            }
        }
    if degraded or corrupt or lost or final_snapshot:
        # Degraded partitions carry an unscanned tail; corrupt ones carry
        # skipped spans the offset tracker never saw; lost ones carry
        # booked spans a resume must inherit.  Snapshot so a rerun
        # resumes correctly (and, for corruption/loss, re-seeds the span
        # lists).
        # ``final_snapshot`` forces the same commit for a clean drain —
        # the follow service's checkpoint/shutdown boundary.
        maybe_snapshot(force=True)

    with profile.stage("finalize"):
        metrics = backend.finalize()
    metrics.partitions = pindex.ids
    if emit_lifecycle:
        spinner.finish_with_message("done")
    duration_secs = int(time.monotonic() - t0)
    # Final telemetry: drained partitions report zero lag, the stage
    # profile folds into the registry, and the lifecycle closes.  Follow
    # passes skip the force: the service refreshes lag gauges against the
    # MOVING head every poll, and a forced heartbeat per pass would flood
    # the event log at exactly the cadence the limiter exists to bound.
    if emit_lifecycle:
        heartbeat.force()  # the closing gauge refresh always lands
    maybe_heartbeat()
    # Locally-degraded partitions only: the -1 cross-process sentinel is
    # another process's partition, and THAT process books it — counting
    # it here would double it under the gauge's merge="sum" policy.
    local_degraded = sum(1 for p in degraded if p >= 0)
    obs_metrics.DEGRADED_PARTITIONS.set(local_degraded)
    # (Stage seconds/records/bytes are already in the registry: the
    # profile books them live at every stage window exit, so the flight
    # recorder and the gather below see the same totals — no end-of-scan
    # record_profile fold.)
    if emit_lifecycle:
        obs_events.emit(
            "scan_end",
            topic=topic,
            records=seq,
            duration_secs=duration_secs,
            degraded=local_degraded,
            corrupt_frames=sum(
                d.get("frames", 0) for p, d in corrupt.items() if p >= 0
            ),
            lost_records=sum(
                d.get("records", 0) for p, d in lost.items() if p >= 0
            ),
        )
    # Close out the wire accounting before the registry gathers, so the
    # bytes/record gauge lands in every snapshot the merge sees.
    if wire_stats is not None:
        wire_stats.bytes_total = int(
            obs_metrics.WIRE_BYTES.value - wire_bytes0
        )
        wire_stats.records = seq - seq_base
        wire_stats.pairs_raw = int(
            obs_metrics.ALIVE_PAIRS_RAW.value - pairs_raw0
        )
        wire_stats.pairs_emitted = int(
            obs_metrics.ALIVE_PAIRS_EMITTED.value - pairs_emitted0
        )
        obs_metrics.WIRE_BYTES_PER_RECORD.set(
            round(wire_stats.bytes_per_record, 2)
        )
    # Cluster-wide registry view.  gather_telemetry is a lockstep
    # collective, so it runs here — a point every process reaches — never
    # from the report-only branch of the CLI.
    gather = getattr(backend, "gather_telemetry", None)
    snaps = gather() if gather is not None else [default_registry().snapshot()]
    telemetry = merge_snapshots(snaps)
    # Per-controller resolved worker counts, read from the UN-merged
    # per-process snapshots (gather returns them pid-sorted): each process
    # stamped its kta_ingest_resolved_workers gauge before the gather.
    workers_per_controller = []
    for s in snaps:
        m = s.get("kta_ingest_resolved_workers")
        v = m["samples"][0]["value"] if m and m.get("samples") else 0
        workers_per_controller.append(max(1, int(v)))
    return ScanResult(
        metrics=metrics,
        duration_secs=duration_secs,
        profile=profile,
        start_offsets=start_offsets,
        end_offsets=end_offsets,
        degraded_partitions=degraded,
        corrupt_partitions=corrupt,
        lost_partitions=lost,
        telemetry=telemetry,
        ingest_workers=used_workers,
        ingest_workers_per_controller=workers_per_controller,
        superbatch_k=super_k,
        dispatch_depth=int(getattr(backend, "dispatch_depth", 1) or 1),
        wire=wire_stats,
        next_offsets=dict(tracker.next_offsets),
    )
