"""Packed host→device batch transfer (wire format v4).

The profiled bottleneck of the streaming path is host→device bandwidth
(SURVEY.md §7 hard part (a) — on this environment's tunneled TPU it measures
~0.25 GB/s, far below PCIe).  Three levers, all here:

1. **One buffer, one transfer** — all per-record columns packed into a single
   contiguous ``uint8[N]`` section layout instead of nine separate arrays.
2. **Minimal bytes per record** — 9 B for the exact counters (vs 37 B naive;
   17 B in v1): partition i16, key_len u16, value_len u32, flags u8;
   padding is expressed as a single ``n_valid`` prefix length in the header
   instead of a bool per record.
3. **Host pre-reduction** — anything the device would only reduce anyway is
   reduced on the host: v2 replaced v1's per-record ``ts_s i64[B]`` column
   with a per-partition min/max table ``i64[2P]`` (the device only ever
   min/maxes timestamps); the alive bitmap's last-writer-wins dedupe
   happens on the host (C++ shim / numpy): the device receives at most one
   (slot, aliveness) pair per touched slot (+5 B) and applies two scatter-ORs
   instead of sorting a million int64 keys; HLL ships as ONE host-reduced
   u8[R << p] register table per batch whenever that is smaller than the
   per-record pairs (v3 — register max is commutative, so the device
   merges elementwise, no scatter), else as pre-split (bucket index u16,
   rho u8) pairs (+3 B) instead of a full 64-bit hash.

Layout (sections in order; B = static batch size, P = num_partitions):

    header   u8[16]   n_valid i32 | n_pairs i32 | reserved
    partition i16[B]
    key_len   u16[B]  (keys > 64 KiB are rejected at pack time)
    value_len u32[B]
    flags     u8[B]   bit0 = key_null, bit1 = value_null
    ts_minmax i64[2P] per-partition ts min then max, identity-filled
    sz_minmax i64[2P] per-partition message-size min then max (v4;
             tombstone-excluded, identities I64_MAX / 0)
    [alive]  slot u32[B] + alive u8[B]          iff count_alive_keys
    [hll]    regs u8[R << p] host-reduced table (R = 1 global, P per-
             partition) WHEN R·2^p ≤ 3·B, else idx u16[B] + rho u8[B]
             pairs — one size rule, ``hll_table_rows``, decides for the
             packers and (via section presence) the device step

**Wire format v5 — the combiner** (``AnalyzerConfig.wire_format == 5``,
the default; DESIGN.md §16).  Every metric is an associative per-partition
fold, so the third lever (host pre-reduction) extends to the LAST
per-record columns: the four columns above exist only so the device can
scatter-add them, and v5 replaces them with the scatter's *result* — the
MapReduce-combiner move.  Sections in order:

    header    u8[16]      n_valid i32 | n_pairs i32 | reserved
    counts    i64[7P]     per-partition counter deltas, row-major [P, 7]
                          in results.COUNTER_CHANNELS order (total,
                          tombstones, alive, key_null, key_non_null,
                          key_size_sum, value_size_sum)
    ts_minmax i64[2P]     unchanged from v4
    sz_minmax i64[2P]     unchanged from v4
    [alive]   slot u32[B] + alive u8[B]          unchanged from v4
    [hll]     regs u8[R << p] table mode unchanged; PAIR mode ships
              idx u16[B] + rho u8[B] globally, but idx32 u32[B]
              (= partition << p | bucket) + rho u8[B] when per-partition
              registers need the row — the one sub-case that cannot ride
              unchanged because the partition column is gone
    [quant]   i64[R·(nbuckets+2)]  iff enable_quantiles: per-row DDSketch
              bucket-count deltas (R = P per-partition else 1), buckets
              from the shared integer edge table (ops/ddsketch.py)

The device fold becomes an elementwise table merge — O(P·H) per dispatch
instead of an O(B) scatter — and wire bytes per record collapse when
P ≪ B (the counts table is 56 B/partition vs 9 B/record).  v4 and v5 scan
results are byte-identical: every replaced fold is an integer sum or
min/max, associative and commutative, and the DDSketch bucket rule is the
same integer edge table on host and device (no float reassociation).

Device-side unpacking is pure ``lax.bitcast_convert_type`` on reshaped slices
(both host and TPU are little-endian; the TPU backend runs a one-time
pack→unpack self-check at init — both formats — to guarantee it).
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

from kafka_topic_analyzer_tpu.config import AnalyzerConfig
from kafka_topic_analyzer_tpu.records import RecordBatch

HEADER_BYTES = 16
MAX_KEY_LEN = 0xFFFF
#: Dense partition indices ride an i16 section.
MAX_PARTITIONS = 0x7FFF
#: 16 MiB - 1: lets byte sums decompose into two 12-bit MXU-exact digits
#: (ops/pallas_counters.py); comfortably above Kafka's practical max
#: message size.
MAX_VALUE_LEN = (1 << 24) - 1


def _sections(config: AnalyzerConfig, batch_size: int,
              pair_table: bool = False):
    """(name, dtype, count) section list, in buffer order.

    ``pair_table=True`` returns the layout of ONE compacted alive-pair
    TABLE buffer instead (wire v5 + ``config.compact_alive`` — DESIGN
    §19): the per-dispatch LWW-merged pair table the device applies once
    per dispatch, with ``batch_size`` then meaning the table CAPACITY
    (``pair_table_capacity``).  Same single-source discipline: the pair
    packers (`pack_pair_table`) and unpackers (`unpack_pair_table_*`)
    derive from this list, so they cannot skew (lint rule 7).

    The layout contract lives in ONE place — the module docstring above
    (wire format v4); this builder, the packers, the unpackers, and the
    device step all derive from this list, so they cannot skew from it.

    v2 removed the 8 B/record ``ts_s`` column: the device only ever
    reduces timestamps to per-partition min/max (ops/counters.py
    ``extremes_update``), so the HOST pre-reduces each batch to a
    ``[2P]`` int64 table (mins then maxes; I64_MAX/I64_MIN where the
    batch has no record for that partition — the identity elements, so
    merging on device is exact).  That is 8 B/record off the wire — the
    dominant column at 17-25 B/record — lifting the transfer-bound
    msgs/s ceiling ~1.5-1.9x (BENCH_NOTES.md round-1 ceiling table).
    Min/max associativity keeps the sharded chunk path exact too.

    Known trade-off: sharded scans pack each of the S space chunks with
    its own [2P] table, so per-step ts bytes are S*2P*8 instead of B*8 —
    a net INCREASE only when 2*P*S > B, i.e. partition counts within ~2x
    of MAX_PARTITIONS combined with small chunked batches; every realistic
    config (P ≤ thousands, B ≥ 2^17) is a large net win.

    v5 (the combiner format — module docstring) drops the four per-record
    columns for a per-partition counter-delta table and, with quantiles
    on, a DDSketch bucket-count table: the same trade-off taken to its
    end state, O(P·H) table bytes replacing O(B) column bytes.
    """
    b = batch_size
    p = config.num_partitions
    if pair_table:
        if alive_table_mode(config, b) == 2:
            w = _alive_mask_words(config)
            return [
                ("alive_set", np.uint32, w),
                ("alive_clear", np.uint32, w),
            ]
        return [
            ("alive_slot", np.uint32, b),
            ("alive_flag", np.uint8, b),
        ]
    if config.wire_format == 5:
        sec = [
            # Pre-reduced counter deltas in results.COUNTER_CHANNELS
            # order: what counters_update's scatter-add would have
            # produced from the four dropped columns.
            ("counts", np.int64, 7 * p),
            ("ts_minmax", np.int64, 2 * p),
            ("sz_minmax", np.int64, 2 * p),
        ]
    else:
        sec = [
            ("partition", np.int16, b),
            ("key_len", np.uint16, b),
            ("value_len", np.uint32, b),
            ("flags", np.uint8, b),
            ("ts_minmax", np.int64, 2 * p),
            # v4: per-partition message-size min/max (tombstone-excluded,
            # src/metric.rs:249-251) — integer min/max is associative, so the
            # host pre-reduces it exactly like the ts table and the device
            # drops its last extremes scatter.  Sizes still ship per record
            # (the counter sums need them), so this adds 16 B/partition and
            # removes a B-record scatter-min + scatter-max from the step.
            ("sz_minmax", np.int64, 2 * p),
        ]
    if config.count_alive_keys and not getattr(config, "compact_alive", False):
        # Compacted configs (wire v5 --alive-compaction auto) ship the
        # pairs as ONE per-dispatch merged table (pair_table=True above)
        # instead of 5 B/record of per-row sections.
        sec.append(("alive_slot", np.uint32, b))
        sec.append(("alive_flag", np.uint8, b))
    mode = hll_wire_mode(config, b)
    if mode == 2:
        # Table mode (v3): register max is fully commutative, so the
        # host pre-reduces the whole batch to a u8[R, 2^p] register
        # table (R = 1 global, R = P per-partition) and the device
        # merges it ELEMENTWISE — no scatter on the hot path.
        sec.append(
            ("hll_regs", np.uint8, hll_table_rows(config, b) << config.hll_p)
        )
    elif mode == 3:
        # v5 flat pair mode: the partition column is gone, so the
        # register ROW rides inside the index — idx32 = partition <<
        # p | bucket (15 + 16 bits fit u32).  Costs 2 B/record over
        # v4's u16 pairs, only in the rare huge-P-small-B regime
        # where pair mode wins the table-size rule at all.
        sec.append(("hll_idx32", np.uint32, b))
        sec.append(("hll_rho", np.uint8, b))
    elif mode == 1:
        # Pair mode: per-record (register index, rho) — cheaper on
        # the wire than a table whenever R·2^p > 3·B.
        sec.append(("hll_idx", np.uint16, b))
        sec.append(("hll_rho", np.uint8, b))
    if config.wire_format == 5 and config.enable_quantiles:
        from kafka_topic_analyzer_tpu.ops.ddsketch import ddsketch_num_buckets

        q_rows = p if config.quantiles_per_partition else 1
        sec.append(
            ("qcounts", np.int64,
             q_rows * ddsketch_num_buckets(config.quantile_buckets))
        )
    return sec


#: Sections whose byte count scales with the batch size — the per-record
#: share of a packed buffer.  Everything else (header included) is a
#: fold-table constant per batch.  Drives ``section_byte_split`` and the
#: ``--stats`` wire line, so the v4→v5 saving is observable, not inferred.
PER_RECORD_SECTIONS = frozenset(
    {"partition", "key_len", "value_len", "flags",
     "alive_slot", "alive_flag", "hll_idx", "hll_idx32", "hll_rho"}
)


def section_byte_split(
    config: AnalyzerConfig, batch_size: int
) -> "Tuple[int, int]":
    """(per_record_bytes, fold_table_bytes) of one packed buffer — the
    fold-table share includes the header.  Derived from ``_sections`` (the
    single layout source, lint rule 7), summing to ``packed_nbytes``."""
    per_record = 0
    table = HEADER_BYTES
    for name, dtype, count in _sections(config, batch_size):
        nbytes = np.dtype(dtype).itemsize * count
        if name in PER_RECORD_SECTIONS:
            per_record += nbytes
        else:
            table += nbytes
    return per_record, table


def hll_table_rows(config: AnalyzerConfig, batch_size: int) -> int:
    """Rows of the host-reduced HLL register table, or 0 for pair mode.

    The table costs ``R << hll_p`` bytes per batch vs 3 B/record of
    pairs: ship whichever is smaller.  Pack, unpack, and the device step
    all derive the mode from this one function (the step via the
    presence of the ``hll_regs`` array), so the decision cannot skew."""
    rows = (
        config.num_partitions if config.distinct_keys_per_partition else 1
    )
    return rows if (rows << config.hll_p) <= 3 * batch_size else 0


def hll_wire_mode(config: AnalyzerConfig, batch_size: int) -> int:
    """The HLL section mode every packer and the layout derive from — ONE
    function so the numpy path, the native calls, and ``_sections`` can
    never disagree (the same discipline as ``hll_table_rows``, which
    decides the table half of this rule):

    - ``0`` — HLL off;
    - ``1`` — u16 (bucket, rho) pairs;
    - ``2`` — host-reduced register table (``hll_table_rows`` rows);
    - ``3`` — wire-v5 flat u32 pairs (``partition << p | bucket``): the
      per-partition pair form, which cannot ship a bare bucket index once
      the v5 layout drops the partition column.
    """
    if not config.enable_hll:
        return 0
    if hll_table_rows(config, batch_size):
        return 2
    if config.wire_format == 5 and config.distinct_keys_per_partition:
        return 3
    return 1


def packed_nbytes(config: AnalyzerConfig, batch_size: int) -> int:
    return HEADER_BYTES + sum(
        np.dtype(dt).itemsize * n for _, dt, n in _sections(config, batch_size)
    )


# ---------------------------------------------------------------------------
# host-side pre-reductions


def dedupe_slots_numpy(
    h32: np.ndarray, active: np.ndarray, alive: np.ndarray, bits: int
) -> Tuple[np.ndarray, np.ndarray]:
    """Last-writer-wins (slot, aliveness) pairs for one batch (numpy).

    Equivalent to replaying insert/remove in record order
    (src/metric.rs:273-280): only each slot's last record survives.
    """
    slot = (h32.astype(np.uint64) & np.uint64((1 << bits) - 1)).astype(np.uint32)
    slot = slot[active]
    alive = alive[active]
    if len(slot) == 0:
        return slot, alive.astype(np.uint8)
    uniq, first_rev = np.unique(slot[::-1], return_index=True)
    return uniq.astype(np.uint32), alive[::-1][first_rev].astype(np.uint8)


def hll_idx_rho_numpy(
    h64: np.ndarray, active: np.ndarray, p: int
) -> Tuple[np.ndarray, np.ndarray]:
    """Pre-split HLL updates: (bucket index, rho).  Inactive records get
    bucket 0 with rho 0 — a no-op under scatter-max, so indices use the
    full u16 range (p up to 16 inclusive) with no sentinel bucket."""
    from kafka_topic_analyzer_tpu.ops.fnv import splitmix64_np

    h = splitmix64_np(h64.astype(np.uint64))
    idx = (h >> np.uint64(64 - p)).astype(np.uint16)
    rest = (h << np.uint64(p)) & np.uint64((1 << 64) - 1)
    # rho = clz(rest) + 1, capped at 64 - p + 1 when rest == 0.
    # numpy >= 2.0: bit_count unavailable for clz; use float trick on the
    # top bits via log2 of rest (exact for leading-zero counting).
    rho = np.full(h.shape, 64 - p + 1, dtype=np.uint8)
    nz = rest != 0
    # floor(log2(x)) is exact for uint64 -> float64 only up to 2^53 of
    # mantissa; compute clz via hi/lo split to stay exact.
    hi = (rest >> np.uint64(32)).astype(np.uint32)
    lo = (rest & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    clz_hi = 31 - np.floor(np.log2(np.maximum(hi, 1).astype(np.float64))).astype(np.int32)
    clz_lo = 63 - np.floor(np.log2(np.maximum(lo, 1).astype(np.float64))).astype(np.int32)
    clz = np.where(hi != 0, clz_hi, np.where(lo != 0, clz_lo, 64)).astype(np.int32)
    rho[nz] = (clz[nz] + 1).astype(np.uint8)
    # Inactive records: rho 0 is a no-op under scatter-max (registers are
    # never negative), so no index sentinel is needed.
    idx = np.where(active, idx, np.uint16(0))
    rho = np.where(active, rho, np.uint8(0))
    return idx.astype(np.uint16), rho


def _dedupe_slots(h32, active, alive, bits, use_native=True):
    if use_native:
        try:
            from kafka_topic_analyzer_tpu.io.native import dedupe_slots_native, native_available

            if native_available():
                return dedupe_slots_native(h32, active, alive, bits)
        except ImportError:
            pass
    return dedupe_slots_numpy(h32, active, alive, bits)


# ---------------------------------------------------------------------------
# compacted alive-pair table (wire v5 + AnalyzerConfig.compact_alive;
# DESIGN.md §19)
#
# With compaction on, the per-row pair sections disappear and every device
# DISPATCH carries ONE pair-table buffer: the LWW merge — in stream order —
# of the per-batch deduped pairs of all K batches the dispatch folds.  LWW
# compaction is LWW-associative (compact(a,b) then merge with compact(c,d)
# in order equals the uncompacted replay), so applying the merged table once
# AFTER the superbatch scan is byte-identical to the per-batch scatter the
# scan body used to run — and the O(W) bitmap mask apply is paid once per
# dispatch instead of K times.


def pair_table_capacity(config: AnalyzerConfig, batch_size: int,
                        k: int = 1) -> int:
    """Static capacity of one dispatch's compacted pair table — the
    bounded-table growth rule: a dispatch folds at most ``k * batch_size``
    records, and distinct slots can never exceed the bitmap's slot space,
    so ``min(k·B, 2^bits)`` bounds the merge with NO overflow path (the
    compacted wire shape never needs a mid-scan fallback)."""
    return min(int(k) * int(batch_size), 1 << config.alive_bitmap_bits)


#: Mask-form cap: the set/clear word masks may grow to at most this many
#: bytes per dispatch (the other half of the bounded-table growth rule);
#: past it the compacted PAIR list is the bounded form.  64 MiB covers
#: ``alive_bitmap_bits <= 28``; the reference-exact 2^32 slot space stays
#: on pairs.
ALIVE_MASK_CAP_BYTES = 64 << 20

#: Mask-vs-pairs trade factor: masks may cost up to this many times the
#: pair list's wire bytes.  Measured rationale (BENCH round 13): the
#: device applies elementwise mask words ~80-180x cheaper per byte than
#: scatter elements (0.7 ms per 16 MB of masks vs ~21-60 ms per 2.6 MB
#: of pair scatter at B=2^16 on the host-CPU jit), so trading up to 32x
#: the bytes for the elementwise merge wins everywhere except
#: tunnel-priced transports — where ``--alive-compaction off`` (or a
#: bitmap past the caps) keeps the pair forms.
ALIVE_MASK_TRADE_FACTOR = 32


def _alive_mask_words(config: AnalyzerConfig) -> int:
    return 1 << max(config.alive_bitmap_bits - 5, 0)


def alive_table_mode(config: AnalyzerConfig, capacity: int) -> int:
    """The compacted table's form — ONE rule (like ``hll_wire_mode``) so
    the packers, the layout, and (via section names) the device apply can
    never disagree:

    - ``1`` — bounded pair list ``slot u32[T] | flag u8[T]``, applied by
      a device scatter (the only form that stays bounded for huge slot
      spaces);
    - ``2`` — set/clear word masks ``u32[W] | u32[W]``: the host resolves
      LWW straight into bitmask form and the device merges ELEMENTWISE
      (``(words & ~clear) | set``) like any other wire-v5 table — no
      scatter at all.
    """
    mask_nbytes = 2 * _alive_mask_words(config) * 4
    if mask_nbytes <= min(
        ALIVE_MASK_TRADE_FACTOR * 5 * capacity, ALIVE_MASK_CAP_BYTES
    ):
        return 2
    return 1


def pair_table_nbytes(config: AnalyzerConfig, capacity: int) -> int:
    return HEADER_BYTES + sum(
        np.dtype(dt).itemsize * n
        for _, dt, n in _sections(config, capacity, pair_table=True)
    )


def batch_alive_pairs(
    batch: RecordBatch, config: AnalyzerConfig, use_native: bool = True
) -> Tuple[np.ndarray, np.ndarray]:
    """One batch's LWW-deduped (slot, alive) pairs for the compacted path
    — exactly the pre-reduction the per-row sections used to carry, but
    returned host-side so the dispatch can merge across batches."""
    active = batch.valid & ~batch.key_null
    alive = batch.valid & ~batch.value_null
    return _dedupe_slots(
        batch.key_hash32, active, alive, config.alive_bitmap_bits, use_native
    )


def _pairs_to_masks_numpy(
    slots: np.ndarray, flags: np.ndarray, bits: int
) -> "tuple[np.ndarray, np.ndarray]":
    """Set/clear word masks from DEDUPED (unique-slot) pairs — the numpy
    half of the mask-form build (`alive_table_mode` 2).  Sorted grouping
    + ``bitwise_or.reduceat`` keeps it vectorized; uniqueness means the
    set/clear interplay is already resolved, so plain ORs suffice."""
    w_words = 1 << max(bits - 5, 0)
    set_w = np.zeros(w_words, dtype=np.uint32)
    clear_w = np.zeros(w_words, dtype=np.uint32)
    if len(slots):
        order = np.argsort(slots, kind="stable")
        s = slots[order]
        f = flags[order].astype(bool)
        for subset, mask_out in ((f, set_w), (~f, clear_w)):
            ss = s[subset]
            if not len(ss):
                continue
            w = (ss >> np.uint32(5)).astype(np.int64)
            b = np.uint32(1) << (ss & np.uint32(31))
            starts = np.flatnonzero(np.r_[True, w[1:] != w[:-1]])
            mask_out[w[starts]] = np.bitwise_or.reduceat(b, starts)
    return set_w, clear_w


def pack_pair_table(
    pair_lists,
    config: AnalyzerConfig,
    capacity: int,
    use_native: bool = True,
    out: "np.ndarray | None" = None,
) -> "tuple[np.ndarray, int, int]":
    """LWW-merge per-batch pair lists — IN STREAM ORDER — into one packed
    compacted-alive-table buffer: ``header u8[16]`` (n_pairs at the same
    header slot the rows use) + the ``pair_table`` sections of
    ``_sections``, in the form `alive_table_mode` picks — the bounded
    pair list (mode 1) or set/clear word masks (mode 2).

    Returns ``(buffer, raw_pairs, emitted_pairs)`` — the raw→emitted
    split feeds ``kta_alive_pairs_{raw,emitted}_total`` (in mask form
    "emitted" counts distinct touched slots).  The merge is the same
    per-slot last-writer rule as the per-batch dedupe (later list wins,
    later entry within a list wins); pair ORDER in a mode-1 buffer is
    implementation-defined exactly like `_dedupe_slots` — the device
    result is order-free because slots are unique."""
    parts = [
        (np.ascontiguousarray(s, dtype=np.uint32),
         np.ascontiguousarray(f, dtype=np.uint8))
        for s, f in pair_lists
        if len(s)
    ]
    if parts:
        slots = (
            parts[0][0] if len(parts) == 1
            else np.concatenate([p[0] for p in parts])
        )
        flags = (
            parts[0][1] if len(parts) == 1
            else np.concatenate([p[1] for p in parts])
        )
        raw = len(slots)
    else:
        raw = 0
        slots = np.empty(0, dtype=np.uint32)
        flags = np.empty(0, dtype=np.uint8)
    mode = alive_table_mode(config, capacity)
    nbytes = pair_table_nbytes(config, capacity)
    if out is None:
        out = np.empty(nbytes, dtype=np.uint8)
    elif out.shape != (nbytes,) or out.dtype != np.uint8:
        raise ValueError("pack_pair_table out= must be uint8[nbytes]")
    header = np.zeros(4, dtype=np.int32)
    pos = HEADER_BYTES
    secs = {}
    for name, dtype, count in _sections(config, capacity, pair_table=True):
        nb = np.dtype(dtype).itemsize * count
        secs[name] = out[pos : pos + nb].view(dtype)
        pos += nb

    if mode == 2:
        # Mask form: resolve LWW straight into bitmask monoid values —
        # one native pass over the RAW stream (no merge table at all), or
        # dedupe-then-OR on the numpy path.
        n = None
        if use_native and raw:
            try:
                from kafka_topic_analyzer_tpu.io.native import (
                    native_available,
                    pairs_to_masks_native,
                )

                if native_available():
                    n = pairs_to_masks_native(
                        slots, flags, config.alive_bitmap_bits,
                        secs["alive_set"], secs["alive_clear"],
                    )
            except ImportError:
                pass
        if n is None:
            merged_slots, merged_flags = (
                _dedupe_slots(
                    slots, np.ones(raw, dtype=bool), flags,
                    config.alive_bitmap_bits, use_native,
                )
                if raw
                else (slots, flags)
            )
            set_w, clear_w = _pairs_to_masks_numpy(
                merged_slots, merged_flags, config.alive_bitmap_bits
            )
            secs["alive_set"][:] = set_w
            secs["alive_clear"][:] = clear_w
            n = len(merged_slots)
        header[1] = n
        out[:HEADER_BYTES] = header.view(np.uint8)
        return out, raw, n

    if raw:
        merged_slots, merged_flags = _dedupe_slots(
            slots, np.ones(raw, dtype=bool), flags,
            config.alive_bitmap_bits, use_native,
        )
    else:
        merged_slots, merged_flags = slots, flags
    n = len(merged_slots)
    if n > capacity:
        # Impossible by the capacity rule (pair_table_capacity); a breach
        # means a caller merged more batches than the capacity was sized
        # for — corrupting the table silently would be worse than dying.
        raise AssertionError(
            f"pair-table overflow: {n} merged pairs > capacity {capacity}"
        )
    header[1] = n
    out[:HEADER_BYTES] = header.view(np.uint8)
    for name in ("alive_slot", "alive_flag"):
        sec = secs[name]
        src = merged_slots if name == "alive_slot" else merged_flags
        sec[:n] = src
        sec[n:] = 0
    return out, raw, n


def unpack_pair_table_numpy(
    buf: np.ndarray, config: AnalyzerConfig, capacity: int
) -> Dict[str, np.ndarray]:
    """Host-side reference unpack of a pair-table buffer (tests)."""
    out: Dict[str, np.ndarray] = {
        "n_pairs": buf[:HEADER_BYTES].view(np.int32)[1]
    }
    pos = HEADER_BYTES
    for name, dtype, count in _sections(config, capacity, pair_table=True):
        nb = np.dtype(dtype).itemsize * count
        out[name] = buf[pos : pos + nb].view(dtype)
        pos += nb
    return out


def unpack_pair_table_device(buf, config: AnalyzerConfig, capacity: int):
    """uint8[pair_table_nbytes] → typed device arrays (runs under jit) —
    the pair-table twin of `unpack_device`, same bitcast rules."""
    from kafka_topic_analyzer_tpu.jax_support import jnp, lax

    header = lax.bitcast_convert_type(
        buf[:HEADER_BYTES].reshape(4, 4), jnp.int32
    )
    out = {"n_pairs": header[1]}
    pos = HEADER_BYTES
    for name, dtype, count in _sections(config, capacity, pair_table=True):
        nb = np.dtype(dtype).itemsize * count
        sec = buf[pos : pos + nb]
        itemsize = np.dtype(dtype).itemsize
        out[name] = (
            sec
            if itemsize == 1
            else lax.bitcast_convert_type(
                sec.reshape(-1, itemsize), jnp.dtype(dtype)
            )
        )
        pos += nb
    return out


# ---------------------------------------------------------------------------
# pack (host)


I64_MAX = np.iinfo(np.int64).max
I64_MIN = np.iinfo(np.int64).min


def ts_minmax_table(partition: np.ndarray, ts_s: np.ndarray,
                    num_partitions: int) -> np.ndarray:
    """Host-side per-partition ts reduction: ``[2P]`` int64, mins then
    maxes, identity-filled for partitions absent from this batch.  Inputs
    are the VALID prefix only (callers slice by n_valid)."""
    table = np.empty(2 * num_partitions, dtype=np.int64)
    table[:num_partitions] = I64_MAX
    table[num_partitions:] = I64_MIN
    if len(partition):
        np.minimum.at(table[:num_partitions], partition, ts_s)
        np.maximum.at(table[num_partitions:], partition, ts_s)
    return table


def sz_minmax_table(batch: RecordBatch, n_valid: int,
                    num_partitions: int) -> np.ndarray:
    """Host-side per-partition message-size extremes: ``[2P]`` int64, mins
    then maxes.  Size = key bytes (when the key is non-null) + value
    bytes; tombstones are EXCLUDED entirely (src/metric.rs:249-251).
    Identities are I64_MAX / 0 — matching the reference's ``largest``
    starting at 0 (src/metric.rs:34)."""
    table = np.empty(2 * num_partitions, dtype=np.int64)
    table[:num_partitions] = I64_MAX
    table[num_partitions:] = 0
    sized = ~batch.value_null[:n_valid]
    if sized.any():
        part = batch.partition[:n_valid][sized]
        size = (
            np.where(batch.key_null[:n_valid], 0,
                     batch.key_len[:n_valid]).astype(np.int64)
            + batch.value_len[:n_valid].astype(np.int64)
        )[sized]
        np.minimum.at(table[:num_partitions], part, size)
        np.maximum.at(table[num_partitions:], part, size)
    return table


def pack_batch(
    batch: RecordBatch,
    config: AnalyzerConfig,
    use_native: bool = True,
    out: "np.ndarray | None" = None,
) -> np.ndarray:
    """RecordBatch → one contiguous uint8 buffer (wire format v4 — the
    module docstring is the layout's single source of truth).

    The batch's valid records must be a prefix (all sources produce
    prefix-valid batches; padding lives at the tail).

    ``out`` packs into a caller-provided ``uint8[packed_nbytes]`` view —
    superbatch staging (SuperbatchStager) hands out rows of its stacked
    host array so the numpy path writes the row directly instead of
    allocating a buffer that would be copied into the stack anyway.
    Every byte of ``out`` is overwritten (header + the full section list
    cover the buffer exactly), so rows need no re-zeroing between uses.
    """
    b = config.batch_size
    n = len(batch)
    if n > b:
        raise ValueError(f"batch of {n} exceeds batch_size {b}")
    n_valid = batch.num_valid
    if n_valid and not bool(batch.valid[:n_valid].all()):
        raise ValueError("packed transfer requires prefix-valid batches")
    if batch.key_len.max(initial=0) > MAX_KEY_LEN:
        raise ValueError(
            f"key length {int(batch.key_len.max())} exceeds the packed "
            f"transfer limit of {MAX_KEY_LEN} bytes"
        )
    if n and (
        batch.partition.max(initial=0) > MAX_PARTITIONS or batch.partition.min() < 0
    ):
        raise ValueError(
            f"partition index out of packed-transfer range [0, {MAX_PARTITIONS}]"
        )
    if n_valid and batch.partition[:n_valid].max() >= config.num_partitions:
        # The v2 ts table is [2P]; a stray dense index past P would scatter
        # out of bounds (the reducers would mis-bucket it anyway).
        raise ValueError(
            f"partition index {int(batch.partition[:n_valid].max())} >= "
            f"num_partitions {config.num_partitions}"
        )
    if n and (batch.value_len.min() < 0 or batch.key_len.min() < 0):
        # astype(uint) would silently wrap a negative length into gigabytes.
        raise ValueError("negative key/value length in record batch")
    if (
        config.use_pallas_counters
        and config.wire_format == 4
        and batch.value_len.max(initial=0) > MAX_VALUE_LEN
    ):
        # Only the v4 MXU kernel's 12-bit digit decomposition needs this
        # cap; the default scatter path handles full u32 lengths exactly,
        # and the v5 table merge never sees a per-record length at all.
        raise ValueError(
            f"value length {int(batch.value_len.max())} exceeds the Pallas "
            f"counter kernel's limit of {MAX_VALUE_LEN} bytes — disable "
            f"use_pallas_counters for such topics"
        )

    if use_native:
        # Fused C++ pack (columns + dedupe + HLL split in one pass),
        # writing straight into ``out`` when given (superbatch rows take
        # the packed bytes with no intermediate buffer).  A None return
        # means the shim rejected the batch; the numpy path below
        # re-derives the descriptive error.
        try:
            from kafka_topic_analyzer_tpu.io.native import (
                native_available,
                pack_batch_native,
            )

            if native_available():
                packed = pack_batch_native(batch, config, out=out)
                if packed is not None:
                    return packed
        except ImportError:
            pass

    if out is None:
        out = np.empty(packed_nbytes(config, b), dtype=np.uint8)
    elif out.shape != (packed_nbytes(config, b),) or out.dtype != np.uint8:
        raise ValueError("pack_batch out= must be uint8[packed_nbytes]")
    header = np.zeros(4, dtype=np.int32)
    header[0] = n_valid

    pos = HEADER_BYTES
    # Integer columns go in uncast: the section write below assigns through
    # a typed view, which narrows exactly like the astype it replaces —
    # minus one intermediate array per column (range checks above already
    # guarantee the narrowing is lossless).
    fields: Dict[str, np.ndarray] = {
        "ts_minmax": ts_minmax_table(
            batch.partition[:n_valid], batch.ts_s[:n_valid],
            config.num_partitions,
        ),
        "sz_minmax": sz_minmax_table(batch, n_valid, config.num_partitions),
    }
    if config.wire_format == 5:
        # The combiner reduction: fold the four per-record columns down to
        # the per-partition delta tables the device would have scattered
        # them into — the exact contrib stack of ops/counters.py (and the
        # CPU oracle), pre-added by partition on the host.
        part = batch.partition[:n_valid]
        kn = ~batch.key_null[:n_valid]
        vn = ~batch.value_null[:n_valid]
        k_bytes = np.where(kn, batch.key_len[:n_valid], 0).astype(np.int64)
        v_bytes = np.where(vn, batch.value_len[:n_valid], 0).astype(np.int64)
        counts = np.zeros((config.num_partitions, 7), dtype=np.int64)
        if n_valid:
            contrib = np.stack(
                [
                    np.ones(n_valid, dtype=np.int64),
                    (~vn).astype(np.int64),  # tombstones
                    vn.astype(np.int64),     # alive
                    (~kn).astype(np.int64),  # key_null
                    kn.astype(np.int64),     # key_non_null
                    k_bytes,
                    v_bytes,
                ],
                axis=1,
            )
            np.add.at(counts, part, contrib)
        fields["counts"] = counts.reshape(-1)
        if config.enable_quantiles:
            from kafka_topic_analyzer_tpu.ops.ddsketch import (
                ddsketch_bucket_numpy,
                ddsketch_num_buckets,
            )

            nb = ddsketch_num_buckets(config.quantile_buckets)
            q_rows = (
                config.num_partitions if config.quantiles_per_partition else 1
            )
            qtable = np.zeros(q_rows * nb, dtype=np.int64)
            if n_valid and vn.any():
                # Quantiles run over sized (non-tombstone) messages, like
                # the size extremes; buckets come from the shared integer
                # edge table so host and device can never disagree.
                sizes = (k_bytes + v_bytes)[vn]
                idx = ddsketch_bucket_numpy(
                    sizes, config.quantile_gamma, config.quantile_buckets
                )
                if q_rows > 1:
                    idx = part[vn].astype(np.int64) * nb + idx
                np.add.at(qtable, idx, 1)
            fields["qcounts"] = qtable
    else:
        fields.update(
            {
                "partition": batch.partition,
                "key_len": batch.key_len,
                "value_len": batch.value_len,
                "flags": (
                    batch.key_null.astype(np.uint8)
                    | (batch.value_null.astype(np.uint8) << 1)
                ),
            }
        )
    if config.count_alive_keys and not config.compact_alive:
        active = batch.valid & ~batch.key_null
        alive = batch.valid & ~batch.value_null
        slots, flags = _dedupe_slots(
            batch.key_hash32, active, alive, config.alive_bitmap_bits, use_native
        )
        n_pairs = len(slots)
        if n_pairs > b:
            raise AssertionError("dedupe produced more pairs than records")
        header[1] = n_pairs
        slot_arr = np.zeros(b, dtype=np.uint32)
        flag_arr = np.zeros(b, dtype=np.uint8)
        slot_arr[:n_pairs] = slots
        flag_arr[:n_pairs] = flags
        fields["alive_slot"] = slot_arr
        fields["alive_flag"] = flag_arr
    if config.enable_hll:
        active = batch.valid & ~batch.key_null
        idx, rho = hll_idx_rho_numpy(batch.key_hash64, active, config.hll_p)
        mode = hll_wire_mode(config, b)
        if mode == 2:
            rows = hll_table_rows(config, b)
            table = np.zeros(rows << config.hll_p, dtype=np.uint8)
            if n_valid:
                # rho is 0 for masked/null-key records — a no-op under max.
                flat = idx[:n_valid].astype(np.int64)
                if rows > 1:
                    flat = flat + (
                        batch.partition[:n_valid].astype(np.int64)
                        << config.hll_p
                    )
                np.maximum.at(table, flat, rho[:n_valid])
            fields["hll_regs"] = table
        elif mode == 3:
            # v5 flat pairs: the register row travels inside the index
            # (partition << p | bucket) because the partition column no
            # longer ships.  Inactive records stay (0, 0) — a no-op
            # under the flat scatter-max exactly like v4's pair rule.
            idx32 = np.where(
                active,
                (batch.partition.astype(np.int64) << config.hll_p)
                | idx.astype(np.int64),
                0,
            ).astype(np.uint32)
            fields["hll_idx32"] = idx32
            fields["hll_rho"] = rho
        else:
            fields["hll_idx"] = idx
            fields["hll_rho"] = rho

    out[:HEADER_BYTES] = header.view(np.uint8)
    for name, dtype, count in _sections(config, b):
        # Write each section directly through a typed view of the output
        # buffer — no staging array, so the bytes move source→buffer once.
        # With memmap-backed columns (SegmentFile.read_batch) that makes
        # the whole numpy pack a single file-page→wire-row copy per column.
        nbytes = np.dtype(dtype).itemsize * count
        src = fields[name]
        sec = out[pos : pos + nbytes].view(dtype)
        sec[: len(src)] = src
        sec[len(src):] = 0  # tail padding past the batch's rows
        pos += nbytes
    return out


# ---------------------------------------------------------------------------
# fused decode→pack sink (DESIGN.md §15)
#
# The seam between the wire/segment readers and the packed device backends:
# a sink accepts raw record-set bytes (fused native decode→pack, no SoA
# intermediate) or already-decoded columns (the python-chain fallback for
# compressed/legacy/salvaged frames), fills wire-v4 rows incrementally, and
# hands completed rows — staged for the backend — back to the stream.


#: Decoded bytes per record (the RecordBatch column widths) — PackedRow
#: reports the same per-record nbytes as the decoded batch it replaces so
#: throughput stats stay comparable across the fused and chained paths.
_RECORD_NBYTES = sum(np.dtype(dt).itemsize for _, dt in RecordBatch.FIELDS)


def fused_ingest_enabled() -> bool:
    """Master gate for the fused ingest path: the native shim must load
    and ``KTA_DISABLE_FUSED`` must be unset.  Callers that get False keep
    the python decode→RecordBatch→pack chain — the fused path is an
    optimization with a reachable fallback everywhere (lint rule 6)."""
    import os

    if os.environ.get("KTA_DISABLE_FUSED"):
        return False
    from kafka_topic_analyzer_tpu.io.native import native_available

    return native_available()


class PackedRow:
    """One completed wire-v4 row from the fused ingest path, plus the
    bookkeeping the engine would otherwise read off the decoded batch:
    per-partition progress (offsets or counts), the last record's
    identity for the spinner, and the decoded-equivalent byte size for
    throughput stats.  ``staged`` carries the backend-staged form
    (StagedBatch / PackedShard) when the sink was given a stage callback —
    it runs on the producing (worker) thread, exactly like
    ``backend.prepare`` does on the chained path."""

    __slots__ = (
        "buf", "staged", "n_valid", "next_offsets", "counts",
        "last_partition", "last_offset", "last_ts_s", "pairs",
    )

    def __init__(self, buf, staged, n_valid, next_offsets, counts,
                 last_partition, last_offset, last_ts_s, pairs=None):
        self.buf = buf
        self.staged = staged
        #: Compacted-path alive pairs of THIS row — (slot u32[n], flag
        #: u8[n]) host arrays in row stream order, None when the config
        #: ships per-row pair sections instead (the staged form carries
        #: the same pairs for the backends' dispatch merge).
        self.pairs = pairs
        self.n_valid = n_valid
        #: true partition id -> one past the last appended offset (sources
        #: that carry offsets); exact-resume bookkeeping.
        self.next_offsets = next_offsets
        #: true partition id -> records appended (offset-less sources).
        self.counts = counts
        self.last_partition = last_partition
        self.last_offset = last_offset
        self.last_ts_s = last_ts_s

    @property
    def num_valid(self) -> int:
        return self.n_valid

    @property
    def nbytes(self) -> int:
        return self.n_valid * _RECORD_NBYTES


class FusedPackSink:
    """Incremental wire-v4 row assembly for one ingest stream.

    Single-device form (``space_shards=1``): rows are flat
    ``uint8[packed_nbytes]`` buffers of ``chunk_records`` records — the
    same greedy ``batch_size`` boundaries the wire layer's pend/resplit
    chain produces, so a fused row is byte-identical to
    ``pack_batch`` over the corresponding chained batch.

    Sharded form (``space_shards=S`` with the backend's chunk config):
    rows are ``uint8[S, chunk_nbytes]`` — records fill chunk 0..S-1
    sequentially at ``chunk_records`` each, the exact ``pack_chunks``
    rule, so a fused row is what ``prepare_shard`` would have staged.

    NOT thread-safe; each ingest stream owns a private sink (parallel
    ingest builds one per worker, the sharded engine one per fed row).
    Appends must preserve per-partition record order — the stream
    contract all byte-identity arguments rest on (DESIGN.md §11).
    """

    def __init__(
        self,
        pack_config: AnalyzerConfig,
        chunk_records: int,
        dense_of,
        stage=None,
        space_shards: int = 1,
        chunk_rows: "bool | None" = None,
    ):
        from kafka_topic_analyzer_tpu.io import native as _native

        self._native = _native
        self.pack_config = pack_config
        self.chunk_records = int(chunk_records)
        self.space_shards = int(space_shards)
        #: Sharded backends consume ``[S, chunk_nbytes]`` rows even at
        #: S=1 (PackedShard's shape contract); single-device rows are
        #: flat.
        self._chunked = (
            self.space_shards > 1 if chunk_rows is None else chunk_rows
        )
        self.capacity = self.chunk_records * self.space_shards
        self._dense_of = dense_of
        self._stage = stage
        self._nbytes = packed_nbytes(pack_config, self.chunk_records)
        self._scratch = np.zeros(
            _native.pack_scratch_len(pack_config, self.chunk_records),
            dtype=np.int64,
        )
        self._row: "np.ndarray | None" = None
        self._chunk = 0
        self._count = 0
        self._next_offsets: "dict[int, int]" = {}
        self._counts: "dict[int, int]" = {}
        self._last = (-1, -1, 0)
        self._done: "list[PackedRow]" = []
        #: Compacted alive path (pack_config.compact_alive): the native
        #: pass diverts each chunk's LWW pairs to the scratch emission
        #: region; they are harvested — copied out — before every scratch
        #: re-init and accumulate here until the row completes.
        self._compact = getattr(pack_config, "compact_alive", False)
        self._row_pairs: "list[tuple[np.ndarray, np.ndarray]]" = []

    # -- row lifecycle -------------------------------------------------------

    def _out_chunk(self) -> np.ndarray:
        return self._row[self._chunk] if self._chunked else self._row

    def _ensure_row(self) -> None:
        if self._row is None:
            self._row = np.empty(
                (self.space_shards, self._nbytes)
                if self._chunked
                else self._nbytes,
                dtype=np.uint8,
            )
            self._chunk = 0
            self._count = 0
            self._next_offsets = {}
            self._counts = {}
            self._native.pack_row_init(
                self._out_chunk(), self._scratch, self.pack_config,
                self.chunk_records,
            )

    def _harvest_pairs(self) -> None:
        """Copy the current chunk's compacted pairs out of the scratch
        emission region — MUST run before any ``pack_row_init`` resets the
        scratch (chunk rotation, row completion, flush padding)."""
        if self._compact and int(self._scratch[1]):
            self._row_pairs.append(
                self._native.pack_take_pairs(
                    self._scratch, self.pack_config, self.chunk_records
                )
            )

    def _take_row_pairs(self) -> "tuple[np.ndarray, np.ndarray] | None":
        if not self._compact:
            return None
        pairs = self._row_pairs
        self._row_pairs = []
        if not pairs:
            return (np.empty(0, dtype=np.uint32), np.empty(0, dtype=np.uint8))
        if len(pairs) == 1:
            return pairs[0]
        return (
            np.concatenate([p[0] for p in pairs]),
            np.concatenate([p[1] for p in pairs]),
        )

    def _advance_full_chunks(self) -> None:
        """Eagerly rotate past filled chunks: completing the row when the
        last chunk fills (full rows emit as soon as they exist — the same
        moment the chained flush would yield the corresponding batch)."""
        while self._row is not None and int(self._scratch[0]) == self.chunk_records:
            self._harvest_pairs()
            self._chunk += 1
            if self._chunk >= self.space_shards:
                self._complete_row()
                return  # next append re-allocates lazily
            self._native.pack_row_init(
                self._out_chunk(), self._scratch, self.pack_config,
                self.chunk_records,
            )

    def _complete_row(self) -> None:
        row = self._row
        self._row = None
        from kafka_topic_analyzer_tpu.obs import metrics as obs_metrics

        obs_metrics.FUSED_BATCHES.inc()
        obs_metrics.FUSED_RECORDS.inc(self._count)
        pairs = self._take_row_pairs()
        if self._stage is None:
            staged = None
        elif self._compact:
            # Compacted path: the stage callback carries the row's pairs
            # into the staged form so the backend's dispatch merge sees
            # them without re-reading the (sectionless) row.
            staged = self._stage(row, pairs)
        else:
            staged = self._stage(row)
        self._done.append(
            PackedRow(
                row,
                staged,
                self._count,
                self._next_offsets,
                self._counts,
                *self._last,
                pairs=pairs,
            )
        )

    def _note(self, partition: int, count: int, last_off: "int | None",
              last_ts: int) -> None:
        if last_off is not None and last_off >= 0:
            self._next_offsets[partition] = last_off + 1
            self._last = (partition, last_off, last_ts)
        else:
            self._counts[partition] = self._counts.get(partition, 0) + count
            self._last = (partition, -1, last_ts)

    # -- appends -------------------------------------------------------------

    def append_record_set(
        self,
        data,
        min_off: int,
        max_off: int,
        partition: int,
        verify_crc: bool = False,
        prescan: "tuple[int, int, int] | None" = None,
    ) -> "tuple[int, int, int, int]":
        """Fused decode→pack of a record set's native-decodable prefix:
        records of ``partition`` with ``min_off <= offset < max_off``
        append straight into the current row (rows rotate as they fill).
        Returns ``(accepted, consumed_bytes, covered_end, last_offset)``
        — the same contract the chained whole-set decode + accept_records
        pair implements.  Raises the packer's ValueError on records the
        wire-v4 layout cannot carry; a malformed frame just ends the
        prefix (the caller's per-frame chain classifies it)."""
        buf = np.frombuffer(data, dtype=np.uint8)
        dense = self._dense_of(partition)
        # A prescan only waives CRC verification when it provably covered
        # the ENTIRE buffer (consumed == len): the walk below is not
        # bounded by the prescan, so a partial prescan (possible from a
        # future caller; the wire layer today only stores full-set scans)
        # must not let unverified frames past the checksummed prefix
        # decode — re-verifying the prefix is wasted CPU, never a hole.
        verify = verify_crc and (prescan is None or prescan[1] != len(buf))
        pos = 0
        skip = 0
        total = 0
        covered = -1
        last_off_all = -1
        while True:
            self._ensure_row()
            (appended, pos, cov, last_off, last_ts, full, skip) = (
                self._native.decode_pack_record_set_native(
                    buf, self._out_chunk(), self._scratch,
                    self.pack_config, self.chunk_records, dense,
                    min_off, max_off, verify, start_pos=pos, skip=skip,
                )
            )
            if appended:
                total += appended
                self._count += appended
                last_off_all = last_off
                self._note(partition, appended, last_off, last_ts)
            if cov > covered:
                covered = cov
            self._advance_full_chunks()
            if not full:
                break
        return total, pos, covered, last_off_all

    def append_columns(
        self,
        partition: int,
        key_len,
        value_len,
        key_null,
        value_null,
        ts,
        key_hash32,
        key_hash64,
        n: int,
        ts_mode: int = 0,
        offsets=None,
        reason: "str | None" = None,
    ) -> int:
        """Chain-fallback append: ``n`` already-decoded single-partition
        records enter the row through the same native incremental core,
        so rows mixing fused and fallback records stay byte-identical to
        the chained pack.  ``reason`` books the fallback (never silent)."""
        if n == 0:
            return 0
        if reason is not None:
            from kafka_topic_analyzer_tpu.obs import metrics as obs_metrics

            obs_metrics.FUSED_FALLBACK.labels(reason=reason).inc(n)
        dense = self._dense_of(partition)
        start = 0
        while start < n:
            self._ensure_row()
            took = self._native.pack_append_columns_native(
                self._out_chunk(), self._scratch, self.pack_config,
                self.chunk_records, dense, key_len, value_len, key_null,
                value_null, ts, key_hash32, key_hash64, start, n,
                ts_mode=ts_mode,
            )
            if took:
                self._count += took
                start += took
                last_off = (
                    int(offsets[start - 1]) if offsets is not None else None
                )
                last_ts = int(ts[start - 1])
                if ts_mode == 1:
                    last_ts //= 1000
                elif ts_mode == 2:
                    last_ts = max(last_ts, 0) // 1000
                self._note(partition, took, last_off, last_ts)
            self._advance_full_chunks()
            if not took and int(self._scratch[0]) < self.chunk_records:
                raise RuntimeError("fused append made no progress")
        return n

    def append_batch(self, batch: RecordBatch, reason: str) -> int:
        """RecordBatch form of the fallback append (salvaged frames,
        python-decoded rows).  Single-partition by the stream contract —
        every caller hands per-frame / per-partition chunks."""
        n = len(batch)
        if n == 0:
            return 0
        p = int(batch.partition[0])
        if n > 1 and not bool((batch.partition == p).all()):
            raise ValueError(
                "fused sink chunks must be single-partition"
            )
        return self.append_columns(
            p, batch.key_len, batch.value_len, batch.key_null,
            batch.value_null, batch.ts_s, batch.key_hash32,
            batch.key_hash64, n, ts_mode=0, offsets=batch.offsets,
            reason=reason,
        )

    # -- draining ------------------------------------------------------------

    def pending_records(self) -> int:
        """Records staged in the (incomplete) current row."""
        return self._count if self._row is not None else 0

    def flush(self) -> None:
        """Complete the partial row (stream end).  Chunks never reached
        stay as initialized — an initialized chunk IS a packed empty
        batch, the superbatch identity pad — so a sharded partial row is
        exactly what ``pack_chunks`` does with a short tail batch."""
        if self._row is None:
            return
        if self._count == 0:
            self._row = None  # nothing appended: emit nothing (chain parity)
            self._row_pairs = []
            return
        self._harvest_pairs()  # before the pad inits reset the scratch
        for s in range(self._chunk + 1, self.space_shards):
            self._native.pack_row_init(
                self._row[s], self._scratch, self.pack_config,
                self.chunk_records,
            )
        self._complete_row()

    def take_completed(self) -> "list[PackedRow]":
        done, self._done = self._done, []
        return done


class SuperbatchStager:
    """Reusable host staging for stacked superbatch dispatch.

    A superbatch crosses the host→device boundary as ONE contiguous
    ``uint8[K, N]`` array (one large ``device_put`` instead of K small
    ones).  This stager owns a ring of ``depth + 1`` such arrays so
    assembling superbatch ``i`` never allocates and never overwrites
    memory an in-flight transfer may still be reading: the slot being
    reused was last dispatched as superbatch ``i - depth - 1``, and the
    dispatch queue (backends/base.py::DispatchQueue) guarantees that
    dispatch retired — its device step consumed the transfer — before
    dispatch ``i`` may launch.  Safe under either PJRT host-buffer
    semantics (immediate copy or zero-copy-until-transfer-completes).

    Callers either pack straight into a row (``pack_batch(..., out=row)``
    — no intermediate buffer at all) or ``np.copyto`` a worker-staged
    buffer into it (parallel ingest packs on worker threads before the
    fan-in order — and hence the row index — is known).

    ``row_shape`` is one batch's staged shape: ``(nbytes,)`` for the
    single-device backend, ``(local_rows, S, chunk_nbytes)`` for one
    collective round of the sharded backend — the ring arrays are
    ``uint8[(k,) + row_shape]`` either way.
    """

    def __init__(self, row_shape: "tuple[int, ...]", k: int, depth: int):
        if k < 1 or depth < 1:
            raise ValueError("superbatch k and dispatch depth must be >= 1")
        self.k = k
        self.row_shape = tuple(row_shape)
        self._ring = [
            np.empty((k,) + self.row_shape, dtype=np.uint8)
            for _ in range(depth + 1)
        ]
        self._next = 0

    def next_slot(self) -> np.ndarray:
        """The ``uint8[(K,) + row_shape]`` host array to assemble the next
        superbatch into.  Rotates the ring; see the class docstring for
        why the returned memory is quiescent."""
        from kafka_topic_analyzer_tpu.obs import metrics as obs_metrics

        slot = self._ring[self._next]
        self._next = (self._next + 1) % len(self._ring)
        # Ring-activity booking for the flight recorder: slots in use at
        # any instant = kta_dispatch_inflight + 1 (this one), and the
        # slot hand-out rate is the superbatch assembly rate.
        obs_metrics.STAGER_SLOTS.inc()
        return slot


def pack_chunks(
    batch: RecordBatch,
    chunk_config: AnalyzerConfig,
    space_shards: int,
    use_native: bool = True,
    out: "np.ndarray | None" = None,
) -> np.ndarray:
    """One data row's batch packed into its ``space_shards`` contiguous
    record chunks: ``uint8[S, chunk_nbytes]``, chunk ``s`` holding records
    ``[s*C, (s+1)*C)`` of the row's batch (``C = chunk_config.batch_size``).

    Contiguity is what makes the sharded backend's device-side ordered
    application exact — source-chunk order equals record order
    (backends/step.py) — so this function is the single chunking rule for
    ``ShardedTpuBackend`` staging (prepare_shard, the superbatch ring, the
    per-round path).

    ``out`` packs each chunk straight into the caller's ``[S, nbytes]``
    rows via ``pack_batch(out=)`` — the sharded superbatch stager hands
    its ring-slot rows here, so an unstaged batch goes file/socket →
    packed ring row with no intermediate stack-then-copy."""
    c = chunk_config.batch_size
    n = len(batch)
    if n > c * space_shards:
        raise ValueError(
            f"batch of {n} exceeds batch_size {c * space_shards}"
        )
    nbytes = packed_nbytes(chunk_config, c)
    if out is None:
        out = np.empty((space_shards, nbytes), dtype=np.uint8)
    elif out.shape != (space_shards, nbytes) or out.dtype != np.uint8:
        raise ValueError(
            f"pack_chunks out buffer must be uint8[{space_shards}, "
            f"{nbytes}], got {out.dtype}{list(out.shape)}"
        )
    for s in range(space_shards):
        lo = s * c
        pack_batch(
            batch.take(np.arange(lo, min(lo + c, n))),
            chunk_config,
            use_native=use_native,
            out=out[s],
        )
    return out


def unpack_numpy(buf: np.ndarray, config: AnalyzerConfig) -> Dict[str, np.ndarray]:
    """Host-side reference unpack (tests + the device self-check oracle)."""
    b = config.batch_size
    header = buf[:HEADER_BYTES].view(np.int32)
    out: Dict[str, np.ndarray] = {
        "n_valid": header[0],
        "n_pairs": header[1],
    }
    pos = HEADER_BYTES
    for name, dtype, count in _sections(config, b):
        nbytes = np.dtype(dtype).itemsize * count
        out[name] = buf[pos : pos + nbytes].view(dtype)
        pos += nbytes
    if config.wire_format == 5:
        out["counts"] = out["counts"].reshape(config.num_partitions, 7)
        if "qcounts" in out:
            from kafka_topic_analyzer_tpu.ops.ddsketch import (
                ddsketch_num_buckets,
            )

            out["qcounts"] = out["qcounts"].reshape(
                -1, ddsketch_num_buckets(config.quantile_buckets)
            )
    else:
        flags = out.pop("flags")
        out["key_null"] = (flags & 1).astype(bool)
        out["value_null"] = (flags & 2).astype(bool)
        out["valid"] = np.arange(b, dtype=np.int32) < out["n_valid"]
        out["partition"] = out["partition"].astype(np.int32)
        out["key_len"] = out["key_len"].astype(np.int32)
        out["value_len"] = out["value_len"].astype(np.int32)
    tm = out.pop("ts_minmax")
    out["ts_min"] = tm[: config.num_partitions]
    out["ts_max"] = tm[config.num_partitions :]
    sm = out.pop("sz_minmax")
    out["sz_min"] = sm[: config.num_partitions]
    out["sz_max"] = sm[config.num_partitions :]
    return out


# ---------------------------------------------------------------------------
# unpack (device, inside jit)
#
# "hll_regs" (table mode) flows through the generic section loop in both
# unpackers untouched — it is already u8[2^p] and the step consumes it
# elementwise.


def unpack_device(buf, config: AnalyzerConfig):
    """uint8[N] → dict of typed device arrays (runs under jit)."""
    from kafka_topic_analyzer_tpu.jax_support import jnp, lax

    b = config.batch_size

    def cast(section, dtype):
        itemsize = np.dtype(dtype).itemsize
        if itemsize == 1:
            return section.astype(dtype) if dtype != jnp.uint8 else section
        return lax.bitcast_convert_type(
            section.reshape(-1, itemsize), jnp.dtype(dtype)
        )

    header = lax.bitcast_convert_type(buf[:HEADER_BYTES].reshape(4, 4), jnp.int32)
    out = {"n_valid": header[0], "n_pairs": header[1]}
    pos = HEADER_BYTES
    for name, dtype, count in _sections(config, b):
        nbytes = np.dtype(dtype).itemsize * count
        out[name] = cast(buf[pos : pos + nbytes], dtype)
        pos += nbytes

    if config.wire_format == 5:
        out["counts"] = out["counts"].reshape(config.num_partitions, 7)
        if "qcounts" in out:
            from kafka_topic_analyzer_tpu.ops.ddsketch import (
                ddsketch_num_buckets,
            )

            out["qcounts"] = out["qcounts"].reshape(
                -1, ddsketch_num_buckets(config.quantile_buckets)
            )
        tm = out.pop("ts_minmax")
        out["ts_min"] = tm[: config.num_partitions]
        out["ts_max"] = tm[config.num_partitions :]
        sm = out.pop("sz_minmax")
        out["sz_min"] = sm[: config.num_partitions]
        out["sz_max"] = sm[config.num_partitions :]
        return out

    iota = jnp.arange(b, dtype=jnp.int32)
    valid = iota < out["n_valid"]
    flags = out.pop("flags")
    out["key_null"] = (flags & 1).astype(bool)
    out["value_null"] = (flags & 2).astype(bool)
    out["valid"] = valid
    out["partition"] = out["partition"].astype(jnp.int32)
    out["key_len"] = out["key_len"].astype(jnp.int32)
    out["value_len"] = out["value_len"].astype(jnp.int32)
    tm = out.pop("ts_minmax")
    out["ts_min"] = tm[: config.num_partitions]
    out["ts_max"] = tm[config.num_partitions :]
    sm = out.pop("sz_minmax")
    out["sz_min"] = sm[: config.num_partitions]
    out["sz_max"] = sm[config.num_partitions :]
    return out
