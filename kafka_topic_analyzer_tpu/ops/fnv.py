"""FNV-1a hashing, scalar and batch-vectorized.

The reference's only hashing utility is a 32-bit FNV-1a *variant* at
``src/fnv32.rs:68-102``: it starts from the offset basis ``0x811c9dc5`` and —
deviating from standard FNV-1a — multiplies by the offset basis again instead
of the FNV prime ``0x01000193`` (``src/fnv32.rs:92-101``).  The alive-key
bitset (``src/metric.rs:256-260``) indexes by that hash, so its collision
behavior is part of the reference's observable output.  We reproduce the
variant bit-for-bit (`fnv1a32_ref`) for the bug-compatible alive-key bitmap,
and additionally provide a standard 64-bit FNV-1a (`fnv1a64`) whose output
feeds the HLL / distinct-key sketches (the reference has no 64-bit hash; this
is new capability).

Batch forms operate on a padded ``uint8[B, L]`` matrix plus a length vector —
the host-side ingest pre-extracts these so that no variable-length bytes ever
need to reach the TPU (SURVEY.md §7 hard part (b)).
"""

from __future__ import annotations

import numpy as np

FNV32_OFFSET = np.uint32(0x811C9DC5)
# The reference multiplies by the offset basis, NOT the FNV prime 0x01000193.
FNV32_MULT = np.uint32(0x811C9DC5)

FNV64_OFFSET = np.uint64(0xCBF29CE484222325)
FNV64_PRIME = np.uint64(0x100000001B3)

_U32_MASK = 0xFFFFFFFF
_U64_MASK = 0xFFFFFFFFFFFFFFFF


def fnv1a32_ref(data: bytes) -> int:
    """Scalar bug-compatible FNV-1a-32 (multiplies by offset basis)."""
    h = 0x811C9DC5
    for b in data:
        h ^= b
        h = (h * 0x811C9DC5) & _U32_MASK
    return h


def fnv1a64(data: bytes) -> int:
    """Scalar standard FNV-1a-64."""
    h = 0xCBF29CE484222325
    for b in data:
        h ^= b
        h = (h * 0x100000001B3) & _U64_MASK
    return h


def splitmix64(x: int) -> int:
    """SplitMix64 finalizer — used to turn counters into well-mixed 64-bit
    values (synthetic workload generation and sketch hashing)."""
    x = (x + 0x9E3779B97F4A7C15) & _U64_MASK
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _U64_MASK
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _U64_MASK
    return x ^ (x >> 31)


def splitmix64_np(x: np.ndarray) -> np.ndarray:
    """Vectorized SplitMix64 over a uint64 array."""
    x = x.astype(np.uint64, copy=True)
    with np.errstate(over="ignore"):
        x += np.uint64(0x9E3779B97F4A7C15)
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return x ^ (x >> np.uint64(31))


def fnv1a32_ref_batch(padded: np.ndarray, lengths: np.ndarray) -> np.ndarray:
    """Vectorized bug-compatible FNV-1a-32 over ``uint8[B, L]`` rows.

    Row ``i`` hashes ``padded[i, :lengths[i]]``.  Columns are processed in a
    short Python loop of length ``L`` (max key length), each step fully
    vectorized over the batch — the per-byte recurrence is inherently
    sequential, the batch axis is not.
    """
    padded = np.ascontiguousarray(padded, dtype=np.uint8)
    lengths = np.asarray(lengths)
    h = np.full(padded.shape[0], FNV32_OFFSET, dtype=np.uint32)
    with np.errstate(over="ignore"):
        for col in range(padded.shape[1]):
            active = lengths > col
            nh = (h ^ padded[:, col]) * FNV32_MULT
            h = np.where(active, nh, h)
    return h


def fnv1a64_batch(padded: np.ndarray, lengths: np.ndarray) -> np.ndarray:
    """Vectorized standard FNV-1a-64 over ``uint8[B, L]`` rows."""
    padded = np.ascontiguousarray(padded, dtype=np.uint8)
    lengths = np.asarray(lengths)
    h = np.full(padded.shape[0], FNV64_OFFSET, dtype=np.uint64)
    with np.errstate(over="ignore"):
        for col in range(padded.shape[1]):
            active = lengths > col
            nh = (h ^ padded[:, col].astype(np.uint64)) * FNV64_PRIME
            h = np.where(active, nh, h)
    return h
