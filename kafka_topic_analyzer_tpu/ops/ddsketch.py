"""DDSketch message-size quantiles (device update, host estimate).

New capability (BASELINE.json config 4: p50/p99 payload-size percentiles over
1B mixed-size messages).  The classic t-digest keeps a variable-length list
of centroids — hostile to XLA's static shapes — so the TPU-native choice is
DDSketch (Masson et al., VLDB'19): fixed log-γ buckets, a guaranteed relative
error α, updates that are a single bincount scatter-add, and a merge that is
plain vector addition (``psum`` over ICI).  The independent referee is the
CPU oracle's exact size histogram (backends/cpu.py), which parity tests
compare against within 2α.

Bucket layout for non-negative integer sizes:
- bucket 0: size == 0 (possible: alive record with empty value and null key);
- bucket i in [1, nbuckets]: ceil(log_gamma(size)) == i-? (see code) — sizes
  up to gamma^nbuckets;
- bucket nbuckets+1: overflow.

Quantile answers carry relative error ≤ α (= ``quantile_alpha``).
"""

from __future__ import annotations

import functools

import numpy as np

from kafka_topic_analyzer_tpu.jax_support import jnp


def ddsketch_num_buckets(nbuckets: int) -> int:
    return nbuckets + 2  # zero bucket + log buckets + overflow


@functools.lru_cache(maxsize=8)
def ddsketch_edges(gamma: float, nbuckets: int) -> np.ndarray:
    """Integer bucket boundaries: ``edges[i]`` is the largest integer size
    assigned to log bucket ``i + 1``, i.e. ``floor(gamma^i)``.

    The bucket of an integer size ``s >= 1`` is
    ``searchsorted(edges, s, side='left') + 1`` — exactly the closed-form
    ``min k such that s <= gamma^(k-1)`` (``s <= gamma^i`` iff
    ``s <= floor(gamma^i)`` for integer ``s``), saturating naturally at
    the overflow bucket ``nbuckets + 1``.  This table is the ONE bucket
    rule shared by the device update below, the numpy wire-v5 packer, and
    the native C++ packers (packing.py / native/ingest.cpp): an integer
    comparison is exact on every backend, where the previous float32
    ``ceil(log(s)/log(gamma))`` could round differently between numpy's
    libm and XLA's vectorized log — a one-ULP disagreement the v4↔v5
    byte-identity bar cannot tolerate.  Cached per (gamma, nbuckets); the
    array is frozen because the native packers hold raw pointers into it.
    """
    powers = np.power(np.float64(gamma), np.arange(nbuckets, dtype=np.float64))
    # Clip before the int cast: an operator-supplied (alpha, nbuckets) pair
    # can push gamma^i past 2^63 (float inf → undefined int64 cast).  Any
    # edge above 2^62 is unreachable anyway (sizes are <= u16 + u32 bytes).
    edges = np.floor(np.minimum(powers, 2.0**62)).astype(np.int64)
    edges.setflags(write=False)
    return edges


def ddsketch_bucket_numpy(
    sizes: np.ndarray, gamma: float, nbuckets: int
) -> np.ndarray:
    """Host-side bucket index per size (the wire-v5 packer's reduction):
    0 for size 0, the shared edge-table bucket otherwise."""
    idx = np.searchsorted(
        ddsketch_edges(gamma, nbuckets), sizes, side="left"
    ).astype(np.int64) + 1
    return np.where(sizes == 0, 0, idx)


def ddsketch_update(
    counts, sizes, active, gamma: float, nbuckets: int, partition=None
):
    """Scatter-add one batch of sizes into the bucket counts.

    ``counts`` is ``int64[R, nbuckets+2]`` — one row per partition when
    per-partition histograms are enabled (``partition`` given), else a
    single row.  Rows merge by addition, so global quantiles over any row
    subset are exact.

    Buckets come from the shared integer edge table (``ddsketch_edges``),
    not a per-record float log: integer ``searchsorted`` is bit-exact
    across numpy and every XLA backend, which is what lets wire v5
    pre-reduce this histogram on the host byte-identically.
    """
    nb = nbuckets + 2
    rows = counts.shape[0]
    edges = jnp.asarray(ddsketch_edges(gamma, nbuckets))
    idx = (
        jnp.searchsorted(edges, sizes.astype(jnp.int64), side="left")
        .astype(jnp.int32) + 1
    )
    idx = jnp.where(sizes == 0, 0, idx)
    row = partition if partition is not None else jnp.int32(0)
    flat = row * nb + idx
    flat = jnp.where(active, flat, rows * nb)  # scratch slot for masked
    scratch = jnp.zeros((rows * nb + 1,), dtype=jnp.int64)
    delta = scratch.at[flat].add(jnp.int64(1))[: rows * nb]
    return counts + delta.reshape(rows, nb)


def ddsketch_merge(a, b):
    return a + b


def ddsketch_quantiles(counts: np.ndarray, probs, gamma: float) -> "list[float]":
    """Host-side quantile extraction from final bucket counts."""
    counts = np.asarray(counts, dtype=np.int64)
    total = int(counts.sum())
    out: "list[float]" = []
    if total == 0:
        return [float("nan") for _ in probs]
    cum = np.cumsum(counts)
    nbuckets = counts.shape[0] - 2
    for q in probs:
        rank = max(0, min(total - 1, int(np.ceil(q * total)) - 1))
        b = int(np.searchsorted(cum, rank + 1))
        if b == 0:
            out.append(0.0)
        elif b > nbuckets:
            out.append(float("inf"))
        else:
            # midpoint of (gamma^(b-2), gamma^(b-1)]: 2*gamma^(b-1)/(gamma+1)
            out.append(float(2.0 * gamma ** (b - 1) / (gamma + 1.0)))
    return out
