"""DDSketch message-size quantiles (device update, host estimate).

New capability (BASELINE.json config 4: p50/p99 payload-size percentiles over
1B mixed-size messages).  The classic t-digest keeps a variable-length list
of centroids — hostile to XLA's static shapes — so the TPU-native choice is
DDSketch (Masson et al., VLDB'19): fixed log-γ buckets, a guaranteed relative
error α, updates that are a single bincount scatter-add, and a merge that is
plain vector addition (``psum`` over ICI).  The independent referee is the
CPU oracle's exact size histogram (backends/cpu.py), which parity tests
compare against within 2α.

Bucket layout for non-negative integer sizes:
- bucket 0: size == 0 (possible: alive record with empty value and null key);
- bucket i in [1, nbuckets]: ceil(log_gamma(size)) == i-? (see code) — sizes
  up to gamma^nbuckets;
- bucket nbuckets+1: overflow.

Quantile answers carry relative error ≤ α (= ``quantile_alpha``).
"""

from __future__ import annotations

import numpy as np

from kafka_topic_analyzer_tpu.jax_support import jnp


def ddsketch_num_buckets(nbuckets: int) -> int:
    return nbuckets + 2  # zero bucket + log buckets + overflow


def ddsketch_update(
    counts, sizes, active, gamma: float, nbuckets: int, partition=None
):
    """Scatter-add one batch of sizes into the bucket counts.

    ``counts`` is ``int64[R, nbuckets+2]`` — one row per partition when
    per-partition histograms are enabled (``partition`` given), else a
    single row.  Rows merge by addition, so global quantiles over any row
    subset are exact.
    """
    nb = nbuckets + 2
    rows = counts.shape[0]
    x = sizes.astype(jnp.float32)
    log_gamma = np.float32(np.log(gamma))
    idx = jnp.ceil(jnp.log(jnp.maximum(x, 1.0)) / log_gamma).astype(jnp.int32) + 1
    idx = jnp.clip(idx, 1, nbuckets + 1)
    idx = jnp.where(sizes == 0, 0, idx)
    row = partition if partition is not None else jnp.int32(0)
    flat = row * nb + idx
    flat = jnp.where(active, flat, rows * nb)  # scratch slot for masked
    scratch = jnp.zeros((rows * nb + 1,), dtype=jnp.int64)
    delta = scratch.at[flat].add(jnp.int64(1))[: rows * nb]
    return counts + delta.reshape(rows, nb)


def ddsketch_merge(a, b):
    return a + b


def ddsketch_quantiles(counts: np.ndarray, probs, gamma: float) -> "list[float]":
    """Host-side quantile extraction from final bucket counts."""
    counts = np.asarray(counts, dtype=np.int64)
    total = int(counts.sum())
    out: "list[float]" = []
    if total == 0:
        return [float("nan") for _ in probs]
    cum = np.cumsum(counts)
    nbuckets = counts.shape[0] - 2
    for q in probs:
        rank = max(0, min(total - 1, int(np.ceil(q * total)) - 1))
        b = int(np.searchsorted(cum, rank + 1))
        if b == 0:
            out.append(0.0)
        elif b > nbuckets:
            out.append(float("inf"))
        else:
            # midpoint of (gamma^(b-2), gamma^(b-1)]: 2*gamma^(b-1)/(gamma+1)
            out.append(float(2.0 * gamma ** (b - 1) / (gamma + 1.0)))
    return out
