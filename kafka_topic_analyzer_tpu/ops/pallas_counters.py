"""Pallas TPU kernel: fused per-partition counter reduction on the MXU.

The counter update (ops/counters.py) is a segment-sum of 7 channels by
partition id — XLA lowers it as a scatter-add.  This kernel instead maps it
onto the MXU as a one-hot matmul, the TPU-native formulation of a segment
sum (guide: /opt/skills/guides/pallas_guide.md):

    contrib[16, N] · one_hot[N, P] → [16, P]    (per 1024-record block)

**Exactness.**  The MXU accumulates in f32, which is exact only below 2^24.
Counts are 0/1 so they are safe, but byte lengths are not — so the two byte
channels are decomposed into 12-bit digits (lo = v & 0xFFF, hi = v >> 12):
every matmul partial is ≤ 4095·1024 < 2^24, the per-block result converts
losslessly to i32, blocks accumulate in an i32 VMEM scratch (safe for
≤ 2^18 records per call), and the digits recombine in i64 outside.  Value
lengths are capped at 2^24-1 (16 MiB, enforced by packing.py) so two digits
suffice.

Channel plane layout (rows of the [16, P] accumulator):
    0..6  COUNTER_CHANNELS lo digits (counts have no hi digit)
    7     key_size_sum   hi digit
    8     value_size_sum hi digit
    9..15 zero padding (MXU-friendly row count)

Partition counts beyond 128 tile the grid's leading dimension (one
accumulator pass per 128-partition tile), and the kernel runs under
`shard_map` meshes (parallel/sharded.py relaxes the vma check for it).
Enabled by ``AnalyzerConfig.use_pallas_counters``; the lax scatter path
remains the default until the kernel is benchmarked faster on real hardware.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from kafka_topic_analyzer_tpu.jax_support import jnp, varying_mesh_axes

#: Records per grid step: an (8, 128) int32 tile.
BLOCK = 1024
#: Max records per pallas_call: keeps i32 scratch sums < 2^31
#: (2^18 · 4095 ≈ 1.07e9).
MAX_CALL = 1 << 18
PLANES = 16
#: Partitions per 128-lane output tile; wider topics tile the grid's
#: leading dimension (one accumulator pass per tile).
PART_TILE = 128


def _kernel(part_ref, klen_ref, vlen_ref, kn_ref, vn_ref, valid_ref, out_ref, acc_ref):
    j = pl.program_id(0)  # partition tile
    i = pl.program_id(1)  # record block

    @pl.when(i == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    part = part_ref[:].reshape(-1)          # [BLOCK] i32
    klen = klen_ref[:].reshape(-1)
    vlen = vlen_ref[:].reshape(-1)
    kn = kn_ref[:].reshape(-1)              # i32 0/1: valid & key non-null
    vn = vn_ref[:].reshape(-1)              # i32 0/1: valid & value non-null
    valid = valid_ref[:].reshape(-1)        # i32 0/1

    tomb = valid - vn                       # valid & value_null
    knull = valid - kn
    k_bytes = klen * kn
    v_bytes = vlen * vn

    planes = [
        valid,                               # total
        tomb,                                # tombstones
        vn,                                  # alive
        knull,                               # key_null
        kn,                                  # key_non_null
        k_bytes & 0xFFF,                     # key_size_sum lo
        v_bytes & 0xFFF,                     # value_size_sum lo
        k_bytes >> 12,                       # key_size_sum hi
        v_bytes >> 12,                       # value_size_sum hi
    ]
    zeros = jnp.zeros_like(valid)
    planes += [zeros] * (PLANES - len(planes))
    contrib = jnp.stack(planes).astype(jnp.float32)        # [16, BLOCK]

    # One-hot over this tile's partition range [j·128, (j+1)·128); invalid
    # records carry partition 0 but all their contribution planes are 0,
    # so they add nothing.
    iota = jax.lax.broadcasted_iota(jnp.int32, (BLOCK, PART_TILE), 1)
    iota = iota + j * PART_TILE
    one_hot = (part[:, None] == iota).astype(jnp.float32)  # [BLOCK, 128]

    # precision=HIGHEST: without it the MXU may run f32 operands through
    # bf16 passes, whose 8-bit mantissa cannot represent the 12-bit digit
    # planes — preferred_element_type alone only fixes the accumulator.
    block_out = jax.lax.dot_general(
        contrib,
        one_hot,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )                                                       # [16, 128] tile j
    acc_ref[:] += block_out.astype(jnp.int32)

    @pl.when(i == pl.num_programs(1) - 1)
    def _():
        out_ref[:] = acc_ref[:]


def _call(part, klen, vlen, kn, vn, valid, p_pad: int, interpret: bool):
    n = part.shape[0]
    assert n % BLOCK == 0 and n <= MAX_CALL
    assert p_pad % PART_TILE == 0
    rows = n // 128

    def shape2d(x):
        return x.reshape(rows, 128)

    block_rows = BLOCK // 128
    # Under a check_vma shard_map the output aval must declare its
    # varying mesh axes; the reduction preserves the inputs' variance
    # (per-device records → per-device counts), so propagate their vma.
    vma = varying_mesh_axes(part) or None
    out_aval = (
        jax.ShapeDtypeStruct((PLANES, p_pad), jnp.int32, vma=vma)
        if vma
        else jax.ShapeDtypeStruct((PLANES, p_pad), jnp.int32)
    )
    # Partition tiles lead the grid so each tile streams all record
    # blocks through its own accumulator pass (i innermost).
    in_spec = pl.BlockSpec((block_rows, 128), lambda j, i: (i, 0))
    out = pl.pallas_call(
        _kernel,
        grid=(p_pad // PART_TILE, rows // block_rows),
        in_specs=[in_spec] * 6,
        out_specs=pl.BlockSpec((PLANES, PART_TILE), lambda j, i: (0, j)),
        out_shape=out_aval,
        scratch_shapes=[pltpu.VMEM((PLANES, PART_TILE), jnp.int32)],
        interpret=interpret,
    )(
        shape2d(part), shape2d(klen), shape2d(vlen),
        shape2d(kn), shape2d(vn), shape2d(valid),
    )
    return out


def pallas_counters_update(
    per_partition,  # int64[P, 7]
    partition,      # int32[B]
    key_len,
    value_len,
    key_null,
    value_null,
    valid,
    num_partitions: int,
    interpret: bool = False,
):
    """Drop-in replacement for ops.counters.counters_update via the MXU
    kernel.  Requires B % 1024 == 0 (config batch sizes are powers of two)."""
    b = partition.shape[0]
    if b % BLOCK != 0:
        raise ValueError(f"batch size {b} must be a multiple of {BLOCK}")
    # The compiled kernel targets TPU; on the CPU platform (tests, virtual
    # meshes) fall back to the interpreter automatically.
    interpret = interpret or jax.default_backend() == "cpu"
    kn = (valid & ~key_null).astype(jnp.int32)
    vn = (valid & ~value_null).astype(jnp.int32)
    v32 = valid.astype(jnp.int32)
    part = partition.astype(jnp.int32)
    klen = key_len.astype(jnp.int32)
    vlen = value_len.astype(jnp.int32)

    p_pad = -(-num_partitions // PART_TILE) * PART_TILE
    total = jnp.zeros((PLANES, p_pad), dtype=jnp.int64)
    # Under a check_vma shard_map the kernel output varies over the mesh
    # axes its inputs vary over; the zeros accumulator starts replicated
    # and must be explicitly cast to match before the add.
    axes = tuple(sorted(varying_mesh_axes(partition)))
    if axes:
        total = jax.lax.pvary(total, axes)
    for lo in range(0, b, MAX_CALL):
        hi = min(lo + MAX_CALL, b)
        sl = slice(lo, hi)
        total = total + _call(
            part[sl], klen[sl], vlen[sl], kn[sl], vn[sl], v32[sl],
            p_pad, interpret,
        ).astype(jnp.int64)

    p = num_partitions
    counts = total[:5, :p]                                # [5, P]
    k_sum = total[5, :p] + (total[7, :p] << 12)
    v_sum = total[6, :p] + (total[8, :p] << 12)
    delta = jnp.concatenate(
        [counts, k_sum[None, :], v_sum[None, :]], axis=0
    ).T                                                    # [P, 7]
    return per_partition + delta


# ---------------------------------------------------------------------------
# Wire-v5 table merge
#
# Under the v5 combiner format (packing.py) the per-partition counter fold
# arrives as a pre-reduced i64[P, 7] delta table: there is no scatter left
# for the one-hot matmul above to replace — the whole fold is an elementwise
# i64 add.  This kernel keeps the pallas path compiled against the v5 table
# inputs (still untimed on real hardware — blocked since BENCH round 2; see
# round 11): the add runs on the VPU as two u32 digit planes with an
# explicit carry, the same exactness discipline as the matmul kernel's
# 12-bit digits (TPU pallas has no native i64 lanes).

#: Rows per merge grid step: an (8, 128) u32 tile.
_MERGE_ROWS = 8


def _merge_kernel(alo_ref, ahi_ref, blo_ref, bhi_ref, lo_ref, hi_ref):
    alo = alo_ref[:]
    lo = alo + blo_ref[:]                     # u32 add wraps mod 2^32
    carry = (lo < alo).astype(jnp.int32)      # unsigned overflow detect
    lo_ref[:] = lo
    hi_ref[:] = ahi_ref[:] + bhi_ref[:] + carry


def pallas_counters_merge(per_partition, delta, interpret: bool = False):
    """Elementwise ``per_partition + delta`` for wire-v5 ``i64[P, 7]``
    counter tables via a pallas VPU kernel — exact for every i64 value
    (lo/hi u32 digits with carry).  Drop-in for the plain jnp add the
    default v5 path uses; selected by ``use_pallas_counters``."""
    interpret = interpret or jax.default_backend() == "cpu"
    shape = per_partition.shape
    n = 1
    for d in shape:
        n *= d
    pad = -n % (_MERGE_ROWS * 128)

    def planes(v):
        flat = v.reshape(-1)
        if pad:
            zeros = jnp.zeros((pad,), dtype=flat.dtype)
            axes = tuple(sorted(varying_mesh_axes(v)))
            if axes:
                # Under a check_vma shard_map the pad constant starts
                # replicated and must match the data's variance to concat.
                zeros = jax.lax.pvary(zeros, axes)
            flat = jnp.concatenate([flat, zeros])
        # Arithmetic split instead of a bitcast: truncation and arithmetic
        # shift-right are endianness-free, so lo/hi identification cannot
        # depend on platform byte order.
        lo = (flat & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32)
        hi = (flat >> jnp.int64(32)).astype(jnp.int32)
        return lo.reshape(-1, 128), hi.reshape(-1, 128)

    alo, ahi = planes(per_partition)
    blo, bhi = planes(delta.astype(jnp.int64))
    rows = alo.shape[0]
    vma = varying_mesh_axes(per_partition) | varying_mesh_axes(delta)
    vma = vma or None

    def out_aval(dtype):
        if vma:
            return jax.ShapeDtypeStruct((rows, 128), dtype, vma=vma)
        return jax.ShapeDtypeStruct((rows, 128), dtype)

    spec = pl.BlockSpec((_MERGE_ROWS, 128), lambda i: (i, 0))
    lo, hi = pl.pallas_call(
        _merge_kernel,
        grid=(rows // _MERGE_ROWS,),
        in_specs=[spec] * 4,
        out_specs=(spec, spec),
        out_shape=(out_aval(jnp.uint32), out_aval(jnp.int32)),
        interpret=interpret,
    )(alo, ahi, blo, bhi)
    merged = (hi.astype(jnp.int64).reshape(-1) << jnp.int64(32)) | lo.astype(
        jnp.int64
    ).reshape(-1)
    if pad:
        merged = merged[:n]
    return merged.reshape(shape)
