"""Compute kernels (numpy host-side + jax/pallas device-side).

The reference computes everything one message at a time behind a virtual
``MetricHandler`` dispatch (``src/kafka.rs:18-20``, ``src/metric.rs:206-253``).
Here every kernel is a batched reduction over a structure-of-arrays
`RecordBatch`, shaped so XLA can fuse it and, where it pays off, implemented as
a Pallas TPU kernel.
"""
