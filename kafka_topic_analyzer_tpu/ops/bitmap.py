"""Packed-bit alive-key bitmap with sort-based last-writer-wins updates.

TPU-native replacement for the reference's alive-key ``BitSet`` indexed by
fnv32 hashes (src/metric.rs:256-305): same observable semantics — a key is
alive iff its *latest* record (per key, in offset order) has a non-null
value; collisions conflate keys exactly as the reference's 32-bit hash does —
but updated a batch at a time on device:

1. within the batch, records are sorted by ``(slot, position)`` and only the
   last record per slot survives (last-writer-wins compaction — the batch
   analog of replaying updates in order);
2. the surviving (slot, aliveness) pairs become two word-level masks built by
   scatter-add (each surviving slot contributes a distinct bit of its word,
   so integer add == bitwise OR);
3. ``words = (words & ~clear) | set`` applies deletes-then-inserts; ordering
   between the two is already resolved per slot by step 1.

Correctness across batches relies on batches arriving in per-partition offset
order, and across devices on every partition being pinned to one data shard
(a Kafka key lives in exactly one partition, so shard-local last-writer-wins
composes into an exact OR-merge; records.py ordering contract).

The slot space can additionally be sharded over the mesh's 'space' axis: each
space shard masks updates to its slot range, so no collective is needed per
batch and the final merge over the data axis is an elementwise OR (pmax).
"""

from __future__ import annotations

from kafka_topic_analyzer_tpu.jax_support import jnp


def bitmap_num_words(bits: int, space_shards: int = 1) -> int:
    total_words = 1 << max(bits - 5, 0)
    if total_words % space_shards:
        raise ValueError(f"2^{bits} slots not divisible into {space_shards} space shards")
    return total_words // space_shards


def bitmap_update(
    words,        # uint32[W] — this shard's packed bits
    key_hash32,   # uint32[B]
    alive,        # bool[B] — value non-null
    active,       # bool[B] — valid & key non-null
    bits: int,
    space_index=0,       # scalar int — which slot-range shard this is
    space_shards: int = 1,
):
    """Apply one batch to the packed bitmap, last-writer-wins per slot."""
    B = key_hash32.shape[0]
    W = bitmap_num_words(bits, space_shards)
    num_slots = jnp.int64(1) << bits
    slot = (key_hash32.astype(jnp.int64)) & (num_slots - 1)
    shard_base = jnp.int64(W * 32) * space_index
    in_shard = active & (slot >= shard_base) & (slot < shard_base + W * 32)
    local = slot - shard_base
    # Inactive / out-of-shard records route to a sentinel past every real slot
    # so they sort to the end and land in the scratch word.
    local = jnp.where(in_shard, local, jnp.int64(W) * 32)
    # Sort by (slot, batch position): stable last-occurrence-per-slot select.
    order_key = local * B + jnp.arange(B, dtype=jnp.int64)
    perm = jnp.argsort(order_key)
    slot_sorted = local[perm]
    alive_sorted = alive[perm]
    is_last = jnp.concatenate(
        [slot_sorted[:-1] != slot_sorted[1:], jnp.ones((1,), dtype=bool)]
    )
    real = is_last & (slot_sorted < jnp.int64(W) * 32)
    word = jnp.where(real, slot_sorted >> 5, W).astype(jnp.int32)
    bit = (jnp.uint32(1) << (slot_sorted & 31).astype(jnp.uint32))
    set_mask = jnp.where(real & alive_sorted, bit, jnp.uint32(0))
    clear_mask = jnp.where(real & ~alive_sorted, bit, jnp.uint32(0))
    scatter = jnp.zeros((W + 1,), dtype=jnp.uint32)
    # Distinct surviving slots in one word own distinct bits → add == OR.
    set_words = scatter.at[word].add(set_mask)[:W]
    clear_words = scatter.at[word].add(clear_mask)[:W]
    return (words & ~clear_words) | set_words


def bitmap_apply_pairs(
    words,        # uint32[W] — this shard's packed bits
    slot,         # uint32[B] — deduped slots (prefix of n_pairs is live)
    alive_flag,   # uint8[B]  — 1 = last record for the slot had a value
    n_pairs,      # scalar i32 — live prefix length
    bits: int,
    space_index=0,
    space_shards: int = 1,
):
    """Apply host-deduped (slot, aliveness) pairs: the fast path.

    The host ingest already performed last-writer-wins per slot
    (packing.py::dedupe_slots_*), so each live slot appears exactly once —
    distinct slots in a word own distinct bits, making scatter-add equal to
    bitwise OR, and no device-side sort is needed (that 1M-element sort was
    the measured hot spot of the all-device path, ops/bitmap.py::bitmap_update).
    """
    B = slot.shape[0]
    W = bitmap_num_words(bits, space_shards)
    live = jnp.arange(B, dtype=jnp.int32) < n_pairs
    s = slot.astype(jnp.int64)
    shard_base = jnp.int64(W * 32) * space_index
    in_shard = live & (s >= shard_base) & (s < shard_base + W * 32)
    local = s - shard_base
    word = jnp.where(in_shard, local >> 5, W).astype(jnp.int32)
    bit = jnp.uint32(1) << (local & 31).astype(jnp.uint32)
    alive = alive_flag.astype(bool)
    set_mask = jnp.where(in_shard & alive, bit, jnp.uint32(0))
    clear_mask = jnp.where(in_shard & ~alive, bit, jnp.uint32(0))
    scratch = jnp.zeros((W + 1,), dtype=jnp.uint32)
    set_words = scratch.at[word].add(set_mask)[:W]
    clear_words = scratch.at[word].add(clear_mask)[:W]
    return (words & ~clear_words) | set_words


def bitmap_apply_masks(
    words,        # uint32[W] — this shard's packed bits
    set_words,    # uint32[W_total] — full-bitmap LWW set mask
    clear_words,  # uint32[W_total] — full-bitmap LWW clear mask
    bits: int,
    space_index=0,
    space_shards: int = 1,
):
    """Apply host-built LWW set/clear word masks: the compacted alive
    table's MASK form (packing.alive_table_mode == 2).

    The host already resolved last-writer-wins per slot straight into
    bitmask form (a later set clears the slot's clear bit and vice
    versa), so the whole apply is ONE elementwise pass —
    ``(words & ~clear) | set`` — with no scatter and no per-batch scratch
    allocation.  Under a space-sharded mesh each shard dynamic-slices its
    word range out of the replicated full-bitmap masks (slot-range
    ownership, same rule as the pair forms)."""
    from kafka_topic_analyzer_tpu.jax_support import lax

    W = bitmap_num_words(bits, space_shards)
    if space_shards > 1:
        base = (jnp.int32(W) * space_index).astype(jnp.int32)
        set_words = lax.dynamic_slice(set_words, (base,), (W,))
        clear_words = lax.dynamic_slice(clear_words, (base,), (W,))
    return (words & ~clear_words) | set_words


def bitmap_popcount(words):
    """Number of alive slots — ``BitSet::len()`` (src/metric.rs:282-284)."""
    from kafka_topic_analyzer_tpu.jax_support import lax

    return jnp.sum(lax.population_count(words).astype(jnp.int64))


def bitmap_merge(words_a, words_b):
    """OR-merge of key-disjoint shards (associative, commutative)."""
    return words_a | words_b
