"""Exact per-partition counter reduction (the 7 buckets + globals), in JAX.

This is the device-side replacement for ``MessageMetrics::handle_message``
(src/metric.rs:207-252): instead of 7 HashMap increments per message, one
batched masked scatter-add produces the whole ``[P, 7]`` counter delta, and
masked min/max reductions update the global timestamp/size extremes.

Semantics preserved exactly (SURVEY.md §3.4):
- "alive" = record with non-null value, counted per record;
- key bytes count only when the key is non-null; value bytes only when the
  value is non-null;
- min/max message size is key_len+value_len and *excludes tombstones*
  (src/metric.rs:249-251);
- timestamps participate at second granularity, missing timestamps as 0.
"""

from __future__ import annotations

from kafka_topic_analyzer_tpu.jax_support import jnp

#: Sentinel for "never seen" minima (mapped to the reference's u64::MAX
#: reporting rule at finalize, src/metric.rs:177-183).
I64_MAX = jnp.iinfo(jnp.int64).max
I64_MIN = jnp.iinfo(jnp.int64).min


def counters_update(
    per_partition,  # int64[P, 7]
    partition,      # int32[B]
    key_len,        # int32[B]
    value_len,      # int32[B]
    key_null,       # bool[B]
    value_null,     # bool[B]
    valid,          # bool[B]
    num_partitions: int,
):
    """Add one batch's contribution to the ``[P, 7]`` counter matrix.

    Channel order follows ``results.COUNTER_CHANNELS``:
    total, tombstones, alive, key_null, key_non_null, key_size_sum,
    value_size_sum.
    """
    kn = valid & ~key_null
    vn = valid & ~value_null
    tomb = valid & value_null
    knull = valid & key_null
    k_bytes = jnp.where(kn, key_len, 0)
    v_bytes = jnp.where(vn, value_len, 0)
    contrib = jnp.stack(
        [
            valid.astype(jnp.int32),
            tomb.astype(jnp.int32),
            vn.astype(jnp.int32),
            knull.astype(jnp.int32),
            kn.astype(jnp.int32),
            k_bytes,
            v_bytes,
        ],
        axis=1,
    ).astype(jnp.int64)
    # Route padded records to a scratch row that is sliced off: keeps the
    # scatter free of a second mask and the shapes static.
    idx = jnp.where(valid, partition, num_partitions)
    scratch = jnp.zeros((num_partitions + 1, 7), dtype=jnp.int64)
    delta = scratch.at[idx].add(contrib)[:num_partitions]
    return per_partition + delta


def extremes_update(
    earliest_s,     # int64[P], I64_MAX sentinel when unset
    latest_s,       # int64[P], I64_MIN sentinel
    smallest,       # int64[P], I64_MAX sentinel
    largest,        # int64[P]
    ts_min,         # int64[P], host-pre-reduced (packing.ts_minmax_table)
    ts_max,         # int64[P]
    sz_min,         # int64[P], host-pre-reduced (packing.sz_minmax_table)
    sz_max,         # int64[P]
):
    """Merge per-partition timestamp and message-size extremes.

    Both arrive already reduced per partition by the host (wire v2
    dropped the per-record ts column, v4 the size-extremes scatter —
    min/max is associative, so elementwise-merging batch tables is
    exact; tombstone exclusion for sizes happens at table build,
    src/metric.rs:249-251).  No per-record work remains here.
    """
    return (
        jnp.minimum(earliest_s, ts_min),
        jnp.maximum(latest_s, ts_max),
        jnp.minimum(smallest, sz_min),
        jnp.maximum(largest, sz_max),
    )
