"""HyperLogLog distinct-key sketch (device update, host estimate).

New capability replacing the reference's O(2^32)-bit alive bitset with an
O(2^p)-register sketch for distinct-key counting (BASELINE.json north star).
The register update is a masked scatter-max — associative and commutative, so
per-device registers merge with an elementwise max (``pmax`` over ICI), the
streaming analog of the reference's single-threaded ``BitSet`` (SURVEY.md
§5.7).

Estimator: classic HLL (Flajolet et al.) with linear counting below 2.5·m and
the large-range correction; with p=14 the standard error is ~0.81%, inside
the ≤1% budget of BASELINE.md.
"""

from __future__ import annotations

import numpy as np

from kafka_topic_analyzer_tpu.jax_support import jnp, lax


def _splitmix64_jnp(x):
    """Bijective SplitMix64 finalizer: FNV-1a avalanches poorly in its high
    bits on short inputs, and HLL takes its bucket index from the top p bits —
    without this mix, thousands of short keys collapse into a few buckets.
    Being a bijection it cannot change distinct-count semantics."""
    x = x.astype(jnp.uint64)
    x = x + jnp.uint64(0x9E3779B97F4A7C15)
    x = (x ^ (x >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
    return x ^ (x >> jnp.uint64(31))


def hll_split(key_hash64, active, p: int):
    """All-device (bucket index, rho) derivation from 64-bit hashes — the
    on-device twin of packing.py::hll_idx_rho_numpy, for callers that skip
    host pre-reduction.  Feed the result to `hll_apply`."""
    h = _splitmix64_jnp(key_hash64)
    idx = (h >> (64 - p)).astype(jnp.int32)
    rest = h << p
    # rho = leading-zero count of the remaining bits + 1, capped when zero.
    rho = jnp.where(
        rest == 0,
        jnp.int32(64 - p + 1),
        lax.clz(rest).astype(jnp.int32) + 1,
    )
    rho = jnp.where(active, rho, 0)  # rho 0 is a no-op under scatter-max
    return idx, rho


def hll_apply(regs, idx, rho, partition=None):
    """Apply host pre-split HLL updates (packing.py::hll_idx_rho_numpy):
    one scatter-max of rho into the register file ``int32[R, m]``.  With
    ``partition`` given, each record updates its partition's row (R = P);
    otherwise the single global row.  Masked records carry rho=0, a no-op
    under max."""
    rows, m = regs.shape
    row = partition if partition is not None else jnp.int32(0)
    flat = row * m + idx.astype(jnp.int32)
    return (
        regs.reshape(-1).at[flat].max(rho.astype(jnp.int32)).reshape(rows, m)
    )


def hll_merge(regs_a, regs_b):
    return jnp.maximum(regs_a, regs_b)


def hll_estimate(regs: np.ndarray) -> float:
    """Host-side cardinality estimate from final registers."""
    regs = np.asarray(regs)
    m = regs.shape[0]
    if m & (m - 1):
        raise ValueError("register count must be a power of two")
    if m >= 128:
        alpha = 0.7213 / (1.0 + 1.079 / m)
    elif m == 64:
        alpha = 0.709
    elif m == 32:
        alpha = 0.697
    else:
        alpha = 0.673
    est = alpha * m * m / np.sum(np.exp2(-regs.astype(np.float64)))
    if est <= 2.5 * m:
        zeros = int(np.count_nonzero(regs == 0))
        if zeros:
            return float(m * np.log(m / zeros))  # linear counting
    # No large-range correction: that branch exists to compensate 32-bit hash
    # collisions; with a 64-bit hash it would only distort (and NaN past 2^32).
    return float(est)
