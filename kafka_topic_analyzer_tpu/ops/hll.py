"""HyperLogLog distinct-key sketch (device update, host estimate).

New capability replacing the reference's O(2^32)-bit alive bitset with an
O(2^p)-register sketch for distinct-key counting (BASELINE.json north star).
The register update is a masked scatter-max — associative and commutative, so
per-device registers merge with an elementwise max (``pmax`` over ICI), the
streaming analog of the reference's single-threaded ``BitSet`` (SURVEY.md
§5.7).

Estimator: Ertl's improved raw estimator ("New cardinality estimation
algorithms for HyperLogLog sketches", Ertl 2017, §2.3) — unbiased over the
whole cardinality range from the register histogram alone, with no
linear-counting switchover and no bias valley just past it (the classic
Flajolet estimator's weak band is exactly where mid-size topics land).
Standard error ~1.04/sqrt(2^p): 0.41% at the default p=16, comfortably
inside BASELINE.md's ≤1% budget rather than riding its 2σ edge (r3's
recorded 1.6% on config 3 was a ~2σ draw at p=14).
"""

from __future__ import annotations

import numpy as np

from kafka_topic_analyzer_tpu.jax_support import jnp, lax


def _splitmix64_jnp(x):
    """Bijective SplitMix64 finalizer: FNV-1a avalanches poorly in its high
    bits on short inputs, and HLL takes its bucket index from the top p bits —
    without this mix, thousands of short keys collapse into a few buckets.
    Being a bijection it cannot change distinct-count semantics."""
    x = x.astype(jnp.uint64)
    x = x + jnp.uint64(0x9E3779B97F4A7C15)
    x = (x ^ (x >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
    return x ^ (x >> jnp.uint64(31))


def hll_split(key_hash64, active, p: int):
    """All-device (bucket index, rho) derivation from 64-bit hashes — the
    on-device twin of packing.py::hll_idx_rho_numpy, for callers that skip
    host pre-reduction.  Feed the result to `hll_apply`."""
    h = _splitmix64_jnp(key_hash64)
    idx = (h >> (64 - p)).astype(jnp.int32)
    rest = h << p
    # rho = leading-zero count of the remaining bits + 1, capped when zero.
    rho = jnp.where(
        rest == 0,
        jnp.int32(64 - p + 1),
        lax.clz(rest).astype(jnp.int32) + 1,
    )
    rho = jnp.where(active, rho, 0)  # rho 0 is a no-op under scatter-max
    return idx, rho


def hll_apply(regs, idx, rho, partition=None):
    """Apply host pre-split HLL updates (packing.py::hll_idx_rho_numpy):
    one scatter-max of rho into the register file ``int32[R, m]``.  With
    ``partition`` given, each record updates its partition's row (R = P);
    otherwise the single global row.  Masked records carry rho=0, a no-op
    under max."""
    rows, m = regs.shape
    row = partition if partition is not None else jnp.int32(0)
    flat = row * m + idx.astype(jnp.int32)
    return (
        regs.reshape(-1).at[flat].max(rho.astype(jnp.int32)).reshape(rows, m)
    )


def hll_apply_flat(regs, idx32, rho):
    """Apply wire-v5 flat HLL pairs: ``idx32`` already encodes
    ``row << p | bucket`` (packing.py's v5 flat pair mode — the partition
    column no longer ships, so the register row rides inside the index).
    One scatter-max into the flattened register file; masked records
    carry (0, 0), a no-op under max."""
    rows, m = regs.shape
    return (
        regs.reshape(-1)
        .at[idx32.astype(jnp.int64)]
        .max(rho.astype(jnp.int32))
        .reshape(rows, m)
    )


def hll_merge(regs_a, regs_b):
    return jnp.maximum(regs_a, regs_b)


def _sigma(x: float) -> float:
    """Ertl 2017 eq. (14): power series for the small-cardinality
    (register-value-0) term.  Converges in <60 iterations for float64."""
    if x == 1.0:
        return float("inf")
    y = 1.0
    z = x
    while True:
        x = x * x
        z_prev = z
        z = z + x * y
        y = 2.0 * y
        if z == z_prev:
            return z


def _tau(x: float) -> float:
    """Ertl 2017 eq. (23): power series for the saturated-register
    (register-value-q+1) term."""
    if x == 0.0 or x == 1.0:
        return 0.0
    y = 1.0
    z = 1.0 - x
    while True:
        x = np.sqrt(x)
        z_prev = z
        y = 0.5 * y
        z = z - (1.0 - x) ** 2 * y
        if z == z_prev:
            return z / 3.0


def hll_estimate(regs: np.ndarray) -> float:
    """Host-side cardinality estimate from final registers: Ertl's
    improved raw estimator (2017, algorithm 6) over the register
    histogram.  Unbiased across the full range — no linear-counting
    branch, no empirical bias tables."""
    regs = np.asarray(regs)
    m = regs.shape[0]
    if m & (m - 1):
        raise ValueError("register count must be a power of two")
    p = int(m).bit_length() - 1
    q = 64 - p  # max rho is q + 1 (hll_split caps at 64 - p + 1)
    counts = np.bincount(regs.astype(np.int64), minlength=q + 2)
    z = m * _tau(1.0 - counts[q + 1] / m)
    for k in range(q, 0, -1):
        z = 0.5 * (z + float(counts[k]))
    z = z + m * _sigma(counts[0] / m)
    alpha_inf = 0.5 / np.log(2.0)
    return float(alpha_inf * m * m / z)
